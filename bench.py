#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload: 20 reads x 2 kb ONT-like consensus (tests/data/sim2k.fa), convex-gap
global alignment, heaviest-bundling consensus — the reference's default config.
vs_baseline is speedup over the AVX2 reference binary measured on the dev host
(bench_baseline.json). Uses the TPU (jax) DP backend when a TPU is present,
falling back to the NumPy host oracle otherwise.
"""
import io
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "bench_baseline.json")) as fp:
        baseline = json.load(fp)["workloads"]["sim2k"]

    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    # probe the accelerator in a subprocess so a wedged device tunnel cannot
    # hang the bench; fall back to the native C++ host kernel (then the NumPy
    # oracle) if no accelerator is reachable
    import subprocess
    device = "numpy"
    try:
        from abpoa_tpu.native import load
        if load() is not None:
            device = "native"
    except Exception:
        pass
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); "
             "print('acc' if any(x.platform!='cpu' for x in d) else 'cpu')"],
            capture_output=True, text=True, timeout=120)
        if probe.returncode == 0 and "acc" in probe.stdout:
            device = "jax"
    except Exception:
        pass

    path = os.path.join(here, baseline["file"])
    abpt = Params()
    abpt.device = device
    abpt.finalize()

    # warmup (compile cache) then timed run
    ab = Abpoa()
    msa_from_file(ab, abpt, path, io.StringIO())
    t0 = time.time()
    ab = Abpoa()
    out = io.StringIO()
    msa_from_file(ab, abpt, path, out)
    dt = time.time() - t0

    n_reads = baseline["n_reads"]
    reads_per_sec = n_reads / dt
    base_rps = n_reads / baseline["avx2_wall_s"]
    print(json.dumps({
        "metric": f"reads/sec (2kb ONT consensus, device={device})",
        "value": round(reads_per_sec, 3),
        "unit": "reads/sec",
        "vs_baseline": round(reads_per_sec / base_rps, 4),
    }))


if __name__ == "__main__":
    main()
