#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload: 20 reads x 2 kb ONT-like consensus (tests/data/sim2k.fa), convex-gap
global alignment, heaviest-bundling consensus — the reference's default config.
vs_baseline is speedup over the AVX2 reference binary measured on the dev host
(bench_baseline.json). Uses the TPU (jax) DP backend when a TPU is present,
falling back to the NumPy host oracle otherwise.
"""
import io
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "bench_baseline.json")) as fp:
        baseline = json.load(fp)["workloads"]["sim2k"]

    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file

    # Candidate backends: the native C++ host kernel, plus the TPU path when
    # an accelerator is reachable (probed in a subprocess so a wedged device
    # tunnel cannot hang the bench). The framework's dispatch lets a user pick
    # any backend; the bench reports the fastest available one.
    import subprocess
    devices = ["numpy"]
    try:
        from abpoa_tpu.native import load
        if load() is not None:
            devices = ["native"]
    except Exception:
        pass
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); "
             "print('acc' if any(x.platform!='cpu' for x in d) else 'cpu')"],
            capture_output=True, text=True, timeout=120)
        if probe.returncode == 0 and "acc" in probe.stdout:
            devices.append("jax")
    except Exception:
        pass

    path = os.path.join(here, baseline["file"])
    n_reads = baseline["n_reads"]
    best_rps, best_device = 0.0, devices[0]
    for device in devices:
        abpt = Params()
        abpt.device = device
        abpt.finalize()
        # warmup (compile cache) then timed run
        ab = Abpoa()
        msa_from_file(ab, abpt, path, io.StringIO())
        t0 = time.time()
        ab = Abpoa()
        msa_from_file(ab, abpt, path, io.StringIO())
        rps = n_reads / (time.time() - t0)
        if rps > best_rps:
            best_rps, best_device = rps, device

    base_rps = n_reads / baseline["avx2_wall_s"]
    print(json.dumps({
        "metric": f"reads/sec (2kb ONT consensus, device={best_device})",
        "value": round(best_rps, 3),
        "unit": "reads/sec",
        "vs_baseline": round(best_rps / base_rps, 4),
    }))


if __name__ == "__main__":
    main()
