#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

Headline workload (the BASELINE.json north star): 500 reads x 10 kb ONT-like
consensus, convex-gap global alignment, heaviest-bundling consensus — the
reference's default config at scale. Also reports the 20 x 2 kb smoke
workload. vs_baseline is speedup over the AVX2 reference binary measured on
the dev host (bench_baseline.json).

Backends: the native C++ host kernel always runs; the TPU path (the fused
all-device progressive loop, abpoa_tpu/align/fused_loop.py) runs when an
accelerator is reachable (probed in a subprocess so a wedged device tunnel
cannot hang the bench). The fastest available backend is reported per
workload; per-backend numbers go to stderr for PERF.md. The pure-Python
numpy oracle is only timed on the small workload — it would take hours on
the headline one.
"""
import getpass
import io
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

HERE = os.path.dirname(os.path.abspath(__file__))


def _ensure_sim10k(path, n_reads):
    def n_records(p):
        try:
            with open(p) as fp:
                return sum(1 for line in fp if line.startswith(">"))
        except OSError:
            return 0

    if n_records(path) != n_reads:
        subprocess.run(
            [sys.executable, os.path.join(HERE, "tests", "make_sim.py"),
             "--ref-len", "10000", "--n-reads", str(n_reads), "--err", "0.1",
             "--seed", "11", "--out", path], check=True)
        if n_records(path) != n_reads:
            raise RuntimeError(f"sim10k generation produced a bad file: {path}")
    return path


def _find_avx2_bin():
    """Locate the reference AVX2 abPOA binary for in-session re-timing:
    ABPOA_REF_BIN, the BASELINE.md .refbuild tree, then PATH. None when
    absent — the checked-in bench_baseline.json walls are used instead."""
    import shutil
    cands = [os.environ.get("ABPOA_REF_BIN"),
             os.path.join(HERE, ".refbuild", "abPOA", "bin", "abpoa"),
             os.path.join(HERE, ".refbuild", "bin", "abpoa"),
             shutil.which("abpoa")]
    for p in cands:
        if p and os.path.isfile(p) and os.access(p, os.X_OK):
            return p
    return None


def _time_avx2(ref_bin, path, timeout):
    """Wall-time one reference-binary consensus run (stdout discarded)."""
    t0 = time.time()
    subprocess.run([ref_bin, path], stdout=subprocess.DEVNULL,
                   stderr=subprocess.DEVNULL, check=True, timeout=timeout)
    return time.time() - t0


def _retime_avx2(workloads, paths):
    """In-session AVX2 walls per workload (ROADMAP item 4): speedup ratios
    on a busy/slow host compare against the SAME host's reference run, not
    the round-1 idle-host number. Returns {key: wall_s} for the workloads
    that re-timed; failures fall back silently to the checked-in wall."""
    ref_bin = _find_avx2_bin()
    if ref_bin is None:
        print("[bench] no AVX2 reference binary (ABPOA_REF_BIN unset, "
              "no .refbuild, not on PATH); using checked-in avx2_wall_s",
              file=sys.stderr)
        return {}
    walls = {}
    for key, path in paths.items():
        budget = max(120, int(workloads[key]["avx2_wall_s"] * 4))
        try:
            walls[key] = round(_time_avx2(ref_bin, path, budget), 3)
        except Exception as e:
            print(f"[bench] AVX2 re-time {key} failed: {e}", file=sys.stderr)
    if walls:
        print(f"[bench] AVX2 re-timed in-session ({ref_bin}): "
              f"{json.dumps(walls)}", file=sys.stderr)
    return walls


def _accelerator_reachable():
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); "
             "print('acc' if any(x.platform!='cpu' for x in d) else 'cpu')"],
            capture_output=True, text=True, timeout=120)
        return probe.returncode == 0 and "acc" in probe.stdout
    except Exception:
        return False


def _warm_ladder_subprocess(tier="quick", timeout=1800, env=None,
                            device="jax"):
    """AOT-warm the declared bucket ladder in a child (`abpoa-tpu warm`,
    ROADMAP item 2): every timed device child afterwards loads the warmed
    rungs from the persistent compilation cache instead of paying
    first-sight XLA compiles inside its (hard-capped) timing window.
    `device` selects whose statics get baked — the pallas kernel variants
    are distinct executables from the XLA-scan ones, so the pallas bench
    row needs its own warm pass."""
    try:
        t0 = time.time()
        subprocess.run(
            [sys.executable, "-m", "abpoa_tpu.cli", "warm", "--ladder",
             tier, "--device", device, "-q"],
            capture_output=True, text=True, timeout=timeout, check=True,
            env=env)
        print(f"[bench] ladder warm ({tier}, {device}): "
              f"{time.time() - t0:.1f}s", file=sys.stderr)
    except Exception as e:
        print(f"[bench] ladder warm failed (continuing cold): {e}",
              file=sys.stderr)


_LAST_REPORT = None


def _time_run(device, path, warm=False):
    from abpoa_tpu import obs
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file
    global _LAST_REPORT
    abpt = Params()
    abpt.device = device
    abpt.finalize()
    if warm:
        msa_from_file(Abpoa(), abpt, path, io.StringIO())
    obs.start_run()  # per-phase attribution for the timed run only
    t0 = time.time()
    msa_from_file(Abpoa(), abpt, path, io.StringIO())
    wall = time.time() - t0
    _LAST_REPORT = obs.finalize_report()
    return wall


def last_report():
    """Full obs-schema report of the most recent _time_run in this process
    (chip_watcher's bench_code children read this)."""
    return _LAST_REPORT


def last_report_summary():
    from abpoa_tpu import obs
    return obs.summary(_LAST_REPORT) if _LAST_REPORT else None


# wall-clock caps for accelerator runs: a slow/hung device path must not
# stall the bench — the native number still gets reported. Worst case with a
# tunnel that answers the probe then wedges: 420 + 1500 (jax/pallas rows) +
# 900 (fused_cpu) + 1200 (lockstep) ~= 67 min of timeouts before the native
# line prints; the native rows themselves run first-in-loop and unaffected.
_JAX_TIMEOUT = {"sim2k": 420, "sim10k_500": 1500}


def _child_line(cmd, prefix, timeout):
    """Run a child, return the payload after `prefix` on stdout, or raise
    with the stderr tail — the one pattern every subprocess row shares."""
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout)
    for line in proc.stdout.splitlines():
        if line.startswith(prefix):
            return line[len(prefix):]
    raise RuntimeError(proc.stderr.strip()[-300:] or "no timing output")


def _timed_child(code, timeout, env=None):
    """Run a timing child that prints 'WALL <s>' and 'REPORT <json>';
    return (wall_s, report_summary_or_None) or raise with the stderr
    tail. Shared by every subprocess bench row."""
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=timeout, env=env)
    wall = rep = None
    for line in proc.stdout.splitlines():
        if line.startswith("WALL "):
            wall = float(line[len("WALL "):])
        elif line.startswith("REPORT "):
            try:
                rep = json.loads(line[len("REPORT "):])
            except ValueError:
                rep = None
    if wall is None:
        raise RuntimeError(proc.stderr.strip()[-300:] or "no timing output")
    return wall, rep


def _time_run_subprocess(device, path, warm, timeout):
    """Time a run in a subprocess with a hard timeout (device paths only).
    Returns (wall_s, report_summary_or_None)."""
    code = (
        "import sys, json; sys.path.insert(0, {here!r})\n"
        "import bench\n"
        "print('WALL', bench._time_run({device!r}, {path!r}, warm={warm}))\n"
        "print('REPORT ' + json.dumps(bench.last_report_summary()))\n"
    ).format(here=HERE, device=device, path=path, warm=warm)
    return _timed_child(code, timeout)


def _time_run_cpu_fused(path, timeout=900):
    """Time the fused device loop on the CPU jax backend (VERDICT r4 #7):
    the device-path code gets a committed bench row every round, even when
    no accelerator answers. Subprocess: the config-level CPU pin must land
    before any backend init, and the probe child reads JAX_PLATFORMS."""
    code = (
        "import os, sys, json; sys.path.insert(0, {here!r})\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import bench\n"
        "print('WALL', bench._time_run('jax', {path!r}, warm=True))\n"
        "print('REPORT ' + json.dumps(bench.last_report_summary()))\n"
    ).format(here=HERE, path=path)
    return _timed_child(code, timeout, env=dict(os.environ, JAX_PLATFORMS="cpu"))


def _run_workload(key, path, n_reads, devices, warm, per_backend, results,
                  phase_reports):
    for device in devices:
        try:
            if device in ("jax", "pallas"):
                wall, rep = _time_run_subprocess(device, path, warm,
                                                 _JAX_TIMEOUT.get(key, 900))
            else:
                wall = _time_run(device, path, warm=warm)
                rep = last_report_summary()
        except Exception as e:
            print(f"[bench] {device} {key} failed: {e}", file=sys.stderr)
            continue
        rps = n_reads / wall
        per_backend.setdefault(key, {})[device] = round(rps, 2)
        if rep is not None:
            phase_reports.setdefault(key, {})[device] = rep
        best = results.get(key)
        if best is None or rps > best[0]:
            results[key] = (rps, device)


def main():
    with open(os.path.join(HERE, "bench_baseline.json")) as fp:
        workloads = json.load(fp)["workloads"]

    # enable the persistent compilation cache so driver re-runs amortize
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(HERE, ".jax_cache"))

    devices = ["numpy"]
    try:
        from abpoa_tpu.native import load
        if load() is not None:
            devices = ["native"]
    except Exception:
        pass
    if _accelerator_reachable():
        devices.append("jax")
        devices.append("pallas")
        # device children share the persistent cache set above; the
        # pallas variants are distinct executables, so warm both
        _warm_ladder_subprocess("quick")
        _warm_ladder_subprocess("quick", device="pallas")
    # the fused-loop CPU row always runs: warm its (CPU-pinned) statics too
    _warm_ladder_subprocess("quick",
                            env=dict(os.environ, JAX_PLATFORMS="cpu"))

    per_backend = {}
    results = {}
    phase_reports = {}
    sim2k = workloads["sim2k"]
    _run_workload("sim2k", os.path.join(HERE, sim2k["file"]),
                  sim2k["n_reads"], devices, True, per_backend, results,
                  phase_reports)

    # fused-loop CPU row: tracks the device-path code on every platform
    # (reported in extra only — it never competes for the headline device)
    try:
        wall, rep = _time_run_cpu_fused(os.path.join(HERE, sim2k["file"]))
        per_backend.setdefault("sim2k", {})["fused_cpu"] = round(
            sim2k["n_reads"] / wall, 2)
        if rep is not None:
            phase_reports.setdefault("sim2k", {})["fused_cpu"] = rep
    except Exception as e:
        print(f"[bench] fused_cpu sim2k failed: {e}", file=sys.stderr)

    sim10k = workloads["sim10k_500"]
    p10k = _ensure_sim10k(
        os.path.join("/tmp", f"bench_sim10k_500.{getpass.getuser()}.fa"),
        sim10k["n_reads"])
    big_devices = [d for d in devices if d != "numpy"]
    _run_workload("sim10k_500", p10k, sim10k["n_reads"], big_devices, False,
                  per_backend, results, phase_reports)

    if "jax" in devices:
        # lockstep multi-set batching: the per-chip throughput lever for
        # `-l`-shaped workloads (K sets per vmapped dispatch); reported in
        # extra so the committed bench tracks the K-scaling claim whenever
        # an accelerator answers
        try:
            mb = json.loads(_child_line(
                [sys.executable, os.path.join(HERE, "tools",
                                              "microbench_tpu.py"),
                 "--task", "lockstep", "--device", "jax",
                 "--lockstep-k", "8", "--n-reads", "30"],
                "MB ", timeout=1200))
            per_backend["lockstep_k8_30x10k"] = {
                "jax": mb.get("reads_per_sec")}
        except Exception as e:
            print(f"[bench] lockstep row failed: {e}", file=sys.stderr)

    print(f"[bench] per-backend reads/s: {json.dumps(per_backend)}",
          file=sys.stderr)

    # in-session AVX2 reference walls when a binary is discoverable;
    # checked-in walls otherwise (the ratio's denominator is recorded
    # either way in extra.avx2)
    avx2_walls = _retime_avx2(
        workloads, {"sim2k": os.path.join(HERE, sim2k["file"]),
                    "sim10k_500": p10k})
    wall2k = avx2_walls.get("sim2k", sim2k["avx2_wall_s"])
    wall10k = avx2_walls.get("sim10k_500", sim10k["avx2_wall_s"])
    base10k = sim10k["n_reads"] / wall10k
    base2k = sim2k["n_reads"] / wall2k
    rps10k, dev10k = results.get("sim10k_500", (0.0, "none"))
    rps2k, dev2k = results.get("sim2k", (0.0, "none"))
    # per-phase breakdown of each workload's winning device (full
    # per-device reports land on stderr above via per_backend debugging);
    # same obs schema as the CLI's --report
    phases = {key: phase_reports.get(key, {}).get(dev)
              for key, dev in (("sim2k", dev2k), ("sim10k_500", dev10k))}
    print(json.dumps({
        "metric": f"reads/sec (500x10kb ONT consensus, device={dev10k})",
        "value": round(rps10k, 3),
        "unit": "reads/sec",
        "vs_baseline": round(rps10k / base10k, 4),
        "extra": {
            "sim2k_reads_per_sec": round(rps2k, 3),
            "sim2k_vs_baseline": round(rps2k / base2k, 4),
            "sim2k_device": dev2k,
            "per_backend": per_backend,
            "phases": phases,
            "avx2": {
                "retimed": sorted(avx2_walls),
                "sim2k_wall_s": wall2k,
                "sim10k_500_wall_s": wall10k,
            },
        },
    }))

    # one trajectory record per bench run (obs/ledger.py): the committed
    # BENCH_* files are point-in-time; the ledger is the series the drift
    # gate (`abpoa-tpu perf --gate`) medians over
    try:
        from abpoa_tpu.obs import ledger
        rep10k = phases.get("sim10k_500") or {}
        ledger.append_record(ledger.make_record(
            "bench", workload="sim10k_500", device=dev10k,
            reads_per_sec=rps10k,
            cell_updates_per_sec=rep10k.get("cell_updates_per_sec"),
            mfu=rep10k.get("mfu"),
            read_wall_ms=rep10k.get("read_wall_ms"),
            verdict=None,
            extra={"vs_baseline": round(rps10k / base10k, 4)
                   if base10k else None,
                   "sim2k_reads_per_sec": round(rps2k, 3),
                   "sim2k_device": dev2k}))
    except Exception as e:  # the ledger must never fail the bench
        print(f"[bench] ledger append failed: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
