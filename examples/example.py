"""Whole-pipeline API example (counterpart of the reference's example.c):
align a read set, call both consensus algorithms, print MSA.

Run: python examples/example.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import abpoa_tpu.pyapi as pa

seqs = [
    "CGTCAATCTATCGAAGCATACGCGGGCAGAGCCGAAGACCTCGGCAATCACA",
    "CCACGTCAATCTATCGAAGCATACGCGGCAGCCGAACTCGACCTCGGCATCAC",
    "CGTCAATCTATCGAAGCATACGCGGCAGAGCCCGGAAGACCTCGGCAATCAC",
    "CGTCAATGCTAGTCGAAGCAGCTGCGGCAGAGCCGAAGACCTCGGCAATCAC",
    "CGTCAATCTATCGAAGCATTCTACGCGGCAGAGCCGACCTCGGCAATCAC",
]

# heaviest-bundling consensus + MSA
a = pa.msa_aligner(aln_mode="g", cons_algrm="HB")
res = a.msa(seqs, out_cons=True, out_msa=True)
print("HB consensus:", res.cons_seq[0])
print("coverage:", res.cons_cov[0][:10], "...")
res.print_msa()

# majority-vote consensus
b = pa.msa_aligner(cons_algrm="MF")
res2 = b.msa(seqs, out_cons=True, out_msa=False)
print("MF consensus:", res2.cons_seq[0])
