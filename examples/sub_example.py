"""Subgraph-alignment API example (counterpart of the reference's
sub_example.c): align a fragment against a closed subgraph of the POA DAG
between two nodes, then fuse it.

Run: python examples/sub_example.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from abpoa_tpu import Params, POAGraph, align_sequence_to_subgraph
from abpoa_tpu import constants as C

abpt = Params().finalize()
g = POAGraph()

enc = abpt.char_to_code


def encode(s):
    return enc[np.frombuffer(s.encode(), dtype=np.uint8)].astype(np.uint8)


reads = [
    "ACGTGTACAGTTGTGCATTGCAGTACGTACGTACGTTTGCAT",
    "ACGTGTACCGTTGTGCATTGCAGTACGAACGTACGTTTGCAT",
]
for i, r in enumerate(reads):
    seq = encode(r)
    from abpoa_tpu.align import align_sequence_to_graph
    res = align_sequence_to_graph(g, abpt, seq)
    g.add_alignment(abpt, seq, None, None, res.cigar, i, len(reads) + 1, True)

# pick an internal window [node 5, node 20], expand to a closed subgraph
exc_beg, exc_end = g.subgraph_nodes(abpt, 5, 20)
print(f"closed subgraph boundary nodes: {exc_beg} .. {exc_end}")

frag = encode("GTACAGTTCTGCATT")
res = align_sequence_to_subgraph(g, abpt, exc_beg, exc_end, frag)
print("fragment aligned, score:", res.best_score,
      "cigar ops:", len(res.cigar))
g.add_subgraph_alignment(abpt, exc_beg, exc_end, frag, None, None,
                         res.cigar, 2, len(reads) + 1, True)
print("graph nodes after fusion:", g.node_n)
