"""Incremental-MSA example (counterpart of the reference's incre_example.c):
build a graph from a first batch, checkpoint it as GFA, restore, and align a
second batch onto it.

Run: python examples/incre_example.py
"""
import io
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import abpoa_tpu.pyapi as pa
from abpoa_tpu.cli import args_to_params, build_parser
from abpoa_tpu.pipeline import Abpoa, msa_from_file

batch1 = [
    "ACGTGTACAGTTGTGCATTGCAGTACGTACGTACGTTTGCAT",
    "ACGTGTACCGTTGTGCATTGCAGTACGAACGTACGTTTGCAT",
]
batch2 = [
    "ACGTGTACAGTTGTGCATTACAGTACGTACGAACGTTTGCAT",
]

with tempfile.TemporaryDirectory() as td:
    fa1 = os.path.join(td, "b1.fa")
    gfa = os.path.join(td, "b1.gfa")
    fa2 = os.path.join(td, "b2.fa")
    with open(fa1, "w") as f:
        for i, s in enumerate(batch1):
            f.write(f">r{i}\n{s}\n")
    with open(fa2, "w") as f:
        for i, s in enumerate(batch2):
            f.write(f">n{i}\n{s}\n")

    # checkpoint batch 1 as GFA
    ns = build_parser().parse_args([fa1, "-r3"])
    abpt = args_to_params(ns).finalize()
    with open(gfa, "w") as out:
        msa_from_file(Abpoa(), abpt, fa1, out)
    print("checkpointed GFA:", open(gfa).readline().strip())

    # restore + align batch 2 incrementally
    ns2 = build_parser().parse_args([fa2, "-i", gfa])
    abpt2 = args_to_params(ns2).finalize()
    out = io.StringIO()
    msa_from_file(Abpoa(), abpt2, fa2, out)
    print("incremental consensus:")
    print(out.getvalue())
