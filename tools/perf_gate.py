#!/usr/bin/env python
"""Perf-regression gate: the bench trajectory, machine-enforced.

Runs the quick bench tier (sim2k, 20 x 2 kb, warm, best host backend),
compares reads/s and cell-updates/s against a checked-in baseline
(tools/perf_baseline.json), and exits non-zero when either metric drops
past its noise threshold — BENCH_r01->r05 stop depending on a human
reading JSON files. Cell-updates/s is the cross-paper throughput judge
(AnySeq/GPU, arXiv:2205.07610); reads/s is the product number.

Noise thresholds (fractional drop vs baseline that FAILS the gate):

- local / dev host (same machine as the baseline): defaults,
  --rps-threshold 0.15 --cups-threshold 0.20. sim2k warm run-to-run
  noise on an idle host is ~5-8%; 15% is outside it.
- CI (.github/workflows/ci.yml `perf-gate` job): 0.60 for both. The
  baseline was measured on the dev container; hosted runners differ by
  up to ~2x in single-core throughput, so CI's job is catching
  catastrophic regressions (native engine silently disabled, an
  accidental device sync in the hot loop), not 15% drifts. Tightening
  CI to 0.15 requires a runner-measured baseline (run with
  --update-baseline on the runner and commit it).

Faster metrics never fail; `--update-baseline` re-anchors after an
intentional improvement. `--current FILE` gates a pre-measured result
without re-running the bench (tests and multi-gate CI use this);
`--inject-slowdown F` divides the measured metrics by F — the test hook
that demonstrates the exit status actually flips.
"""
from __future__ import annotations

import argparse
import json
import os
import platform
import sys

TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS)
DEFAULT_BASELINE = os.path.join(TOOLS, "perf_baseline.json")

# metric -> (baseline key, CLI threshold dest)
METRICS = ("reads_per_sec", "cell_updates_per_sec")


def run_quick_tier(repeats: int = 3) -> dict:
    """Measure the quick tier: warm sim2k on the best host backend, best
    of `repeats` timed runs (the sim2k warm wall is ~0.1 s, so a single
    sample carries scheduler noise the thresholds would then have to
    absorb). Returns the gate's `current` dict (also the baseline
    schema)."""
    sys.path.insert(0, REPO)
    import bench
    with open(os.path.join(REPO, "bench_baseline.json")) as fp:
        wl = json.load(fp)["workloads"]["sim2k"]
    path = os.path.join(REPO, wl["file"])
    device = "numpy"
    try:
        from abpoa_tpu.native import load
        if load() is not None:
            device = "native"
    except Exception:
        pass
    wall, summ = bench._time_run(device, path, warm=True), None
    summ = bench.last_report_summary()
    misses = _report_compile_misses(bench.last_report())
    for _ in range(max(0, repeats - 1)):
        w = bench._time_run(device, path, warm=False)
        misses = max(misses, _report_compile_misses(bench.last_report()))
        if w < wall:
            wall, summ = w, bench.last_report_summary()
    summ = summ or {}
    # the host backends never dispatch jit, so their misses are trivially
    # 0 — the recompile budget needs a real jit backend under it
    dev_misses = _device_compile_misses(path)
    if dev_misses is not None:
        misses = max(misses, dev_misses)
    return {
        "workload": "sim2k",
        "device": device,
        "n_reads": wl["n_reads"],
        "wall_s": round(wall, 4),
        "reads_per_sec": round(wl["n_reads"] / wall, 3),
        "cell_updates_per_sec": summ.get("cell_updates_per_sec"),
        "read_wall_ms": summ.get("read_wall_ms"),
        "compile_misses": misses,
        "host": {"machine": platform.machine(),
                 "python": platform.python_version()},
    }


def _report_compile_misses(report) -> int:
    """In-run compile misses from a full obs report (0 when the run made
    no jit dispatches at all — a host-backend run genuinely compiles
    nothing)."""
    comp = (report or {}).get("compiles") or {}
    return int(comp.get("misses") or 0)


def _device_compile_misses(path: str, timeout: int = 900):
    """Compile misses of a WARM sim2k run on the jax backend, measured in
    a CPU-pinned child (the tunnel-wedge rules from bench.py apply). The
    child runs the workload once untimed — first-sight compiles or
    persistent-cache loads land there — then once under the report: a
    warm run that still misses has an in-run recompile (cache-key
    instability, growth churn), which is exactly what the budget gates.
    Returns None when jax is unavailable or the child fails: the budget
    then rests on the host-backend count alone rather than failing the
    gate on an environment problem."""
    code = (
        "import io, json, os\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from abpoa_tpu import obs\n"
        "from abpoa_tpu.params import Params\n"
        "from abpoa_tpu.pipeline import Abpoa, msa_from_file\n"
        "def one():\n"
        "    abpt = Params(); abpt.device = 'jax'; abpt.finalize()\n"
        "    msa_from_file(Abpoa(), abpt, %r, io.StringIO())\n"
        "one()\n"
        "obs.start_run()\n"
        "one()\n"
        "rep = obs.finalize_report()\n"
        "print('MISSES', (rep.get('compiles') or {}).get('misses', 0))\n"
        % path)
    import subprocess
    env = dict(os.environ, ABPOA_TPU_SKIP_PROBE="1")
    try:
        r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                           capture_output=True, text=True, timeout=timeout,
                           env=env)
        for line in r.stdout.splitlines():
            if line.startswith("MISSES "):
                return int(line.split()[1])
    except Exception:
        pass
    return None


def compare(current: dict, baseline: dict, thresholds: dict,
            compile_misses_max=None) -> list:
    """Pure gate decision: list of failure strings (empty = pass).
    A metric only gates when both sides carry a positive number — a
    baseline recorded without the native engine must not fail a host
    that also lacks it, and vice versa.

    compile_misses_max (CLI flag, falling back to the baseline's
    `compile_misses_max` field): recompile budget — the warmed tier must
    not compile in-run. Gates only when the current measurement carries a
    `compile_misses` count (reports without a compiles block skip)."""
    failures = []
    if compile_misses_max is None:
        compile_misses_max = baseline.get("compile_misses_max")
    misses = current.get("compile_misses")
    if compile_misses_max is not None and misses is not None:
        verdict = "FAIL" if misses > compile_misses_max else "ok"
        print(f"[perf-gate] compile_misses: current={misses} "
              f"budget={compile_misses_max} {verdict}")
        if verdict == "FAIL":
            failures.append(
                f"compile_misses {misses} exceeds budget "
                f"{compile_misses_max}: the run recompiled in-flight "
                f"(warm the ladder or extend it — see abpoa-tpu warm)")
    for metric in METRICS:
        thr = thresholds[metric]
        base = baseline.get(metric)
        cur = current.get(metric)
        if not base or not cur or base <= 0:
            print(f"[perf-gate] {metric}: no comparable numbers "
                  f"(baseline={base}, current={cur}) — skipped")
            continue
        ratio = cur / base
        verdict = "FAIL" if ratio < 1.0 - thr else "ok"
        print(f"[perf-gate] {metric}: current={cur:.3g} baseline={base:.3g} "
              f"ratio={ratio:.3f} (floor {1.0 - thr:.2f}) {verdict}")
        if verdict == "FAIL":
            failures.append(
                f"{metric} regressed {100 * (1 - ratio):.1f}% "
                f"(> {100 * thr:.0f}% threshold): "
                f"{cur:.3g} vs baseline {base:.3g}")
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="quick-tier perf-regression gate (see module docstring "
                    "for the threshold contract)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="checked-in baseline JSON")
    ap.add_argument("--current", default=None, metavar="FILE",
                    help="gate this pre-measured result instead of running "
                         "the bench")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the measured current result to FILE")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the measurement as the new baseline and "
                         "exit 0 (intentional re-anchor)")
    ap.add_argument("--rps-threshold", type=float, default=0.15,
                    help="fractional reads/s drop that fails [%(default)s]")
    ap.add_argument("--cups-threshold", type=float, default=0.20,
                    help="fractional cell-updates/s drop that fails "
                         "[%(default)s]")
    ap.add_argument("--inject-slowdown", type=float, default=None,
                    metavar="F", help="divide measured metrics by F "
                    "(test hook proving the gate flips)")
    ap.add_argument("--compile-misses-max", type=int, default=None,
                    metavar="N", help="fail when the run reports more "
                    "than N in-run compile misses (default: the "
                    "baseline's compile_misses_max field, if any)")
    args = ap.parse_args(argv)

    if args.current:
        with open(args.current) as fp:
            current = json.load(fp)
    else:
        current = run_quick_tier()
    if args.inject_slowdown:
        for metric in METRICS:
            if current.get(metric):
                current[metric] = current[metric] / args.inject_slowdown
        print(f"[perf-gate] injected {args.inject_slowdown}x slowdown "
              "(test hook)")
    if args.out:
        with open(args.out, "w") as fp:
            json.dump(current, fp, indent=2)
    if args.update_baseline:
        # the recompile budget is gate policy, not a measurement: survive
        # re-anchors
        try:
            with open(args.baseline) as fp:
                old = json.load(fp)
            if "compile_misses_max" in old:
                current["compile_misses_max"] = old["compile_misses_max"]
        except Exception:
            pass
        with open(args.baseline, "w") as fp:
            json.dump(current, fp, indent=2)
            fp.write("\n")
        print(f"[perf-gate] baseline updated: {args.baseline}")
        return 0
    if not os.path.isfile(args.baseline):
        print(f"[perf-gate] no baseline at {args.baseline}; run with "
              "--update-baseline to create one", file=sys.stderr)
        return 2
    with open(args.baseline) as fp:
        baseline = json.load(fp)
    failures = compare(current, baseline,
                       {"reads_per_sec": args.rps_threshold,
                        "cell_updates_per_sec": args.cups_threshold},
                       compile_misses_max=args.compile_misses_max)
    rc = 1 if failures else 0
    _ledger_append(current, rc)
    if failures:
        for f in failures:
            print(f"[perf-gate] FAIL: {f}", file=sys.stderr)
        return 1
    print("[perf-gate] PASS")
    return 0


def _ledger_append(current: dict, rc: int) -> None:
    """One trajectory record per gate run; a ledger problem never fails
    the gate itself."""
    try:
        sys.path.insert(0, REPO)
        from abpoa_tpu.obs import ledger
        ledger.append_record(ledger.make_record(
            "perf_gate",
            workload=current.get("workload") or "sim2k",
            device=current.get("device"),
            route="serial",
            reads_per_sec=current.get("reads_per_sec"),
            cell_updates_per_sec=current.get("cell_updates_per_sec"),
            read_wall_ms=current.get("read_wall_ms"),
            compile_misses=current.get("compile_misses"),
            verdict="pass" if rc == 0 else "fail",
            extra={"wall_s": current.get("wall_s"),
                   "n_reads": current.get("n_reads")}))
    except Exception as exc:  # pragma: no cover - best-effort observability
        print(f"[perf-gate] ledger append failed: {exc}", file=sys.stderr)


if __name__ == "__main__":
    raise SystemExit(main())
