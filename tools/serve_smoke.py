#!/usr/bin/env python
"""Serve chaos soak: the measured form of ISSUE 12's acceptance criteria.

Starts `abpoa-tpu serve` (device jax pinned to CPU — no accelerator
needed) with EVERY fault injector armed and a 1 s breaker cooldown, then
drives it with `tools/loadgen.py` at ~2x the calibrated sustainable
throughput, with poisoned payloads and tiny-deadline probes mixed in.
The server must:

- never crash or OOM: rc=0 at SIGTERM, zero transport errors client-side,
  no Traceback in its stderr;
- shed overload as 429 + Retry-After, never by queueing without bound;
- answer poisoned sets with 400 and deadline expiries with 504, each with
  a fault record — while the worker pool survives;
- keep every 200 byte-identical to the numpy oracle, through compile
  failures, injected OOMs, hangs and garbage outputs (the degradation
  ladder + output guards doing their jobs);
- trip the circuit breaker on the injected fault burst AND reclose it
  through the half-open cooldown probe once the injectors exhaust
  (abpoa_breaker_opens_total >= 1 and abpoa_breaker_recloses_total >= 1);
- leave a lint-clean Prometheus exposition and an archive window on which
  `abpoa-tpu slo` passes;
- drain clean on SIGTERM: in-flight finished, metrics flushed, exit 0.

    python tools/serve_smoke.py [--keep] [--requests N] [--no-inject]
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS)
DATA = os.path.join(REPO, "tests", "data")
sys.path.insert(0, REPO)
sys.path.insert(0, TOOLS)

POISON_BODY = b"@truncated\nACGTACGT\n+\nIII\n"   # qual len != seq len -> 400


def oracle_body(payload_path: str) -> bytes:
    """The numpy-oracle response bytes for one payload — computed in THIS
    process on the reference host path; every healthy serve response must
    match one of these byte for byte."""
    import io
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa
    abpt = Params()
    abpt.device = "numpy"
    abpt.finalize()
    buf = io.StringIO()
    msa(Abpoa(), abpt, read_fastx(payload_path), buf)
    return buf.getvalue().encode()


def wait_ready(base: str, proc, timeout_s: float = 600.0) -> None:
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        if proc.poll() is not None:
            raise RuntimeError(f"server exited rc={proc.returncode} "
                               "before becoming ready")
        try:
            with urllib.request.urlopen(base + "/readyz", timeout=2) as r:
                if r.status == 200:
                    return
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.25)
    raise RuntimeError("server never became ready")


def read_port(proc, timeout_s: float = 120.0) -> int:
    """Parse the bound port from the 'listening on' stderr line."""
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        line = proc.stderr.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(f"server exited rc={proc.returncode} "
                                   "during startup")
            time.sleep(0.05)
            continue
        sys.stderr.write(f"[server] {line}")
        if "listening on http://" in line:
            return int(line.split("listening on http://")[1]
                       .split()[0].rsplit(":", 1)[1])
    raise RuntimeError("never saw the listening line")


def _drain_stderr(proc, sink: list) -> None:
    for line in proc.stderr:
        sink.append(line)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=240,
                    help="soak request count (>= 200 for the CI claim) "
                         "[%(default)s]")
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir for inspection")
    ap.add_argument("--no-inject", action="store_true",
                    help="skip the fault injectors (pure overload soak)")
    args = ap.parse_args(argv)
    tmp = tempfile.mkdtemp(prefix="abpoa_serve_smoke_")
    payload = os.path.join(DATA, "test.fa")
    payload2 = os.path.join(DATA, "seq.fa")
    oracles = {oracle_body(payload), oracle_body(payload2)}
    metrics_path = os.path.join(tmp, "metrics.prom")
    archive_dir = os.path.join(tmp, "reports")
    failures: list = []

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        ABPOA_TPU_SKIP_PROBE="1",
        ABPOA_TPU_BREAKER_THRESHOLD="2",
        # 0.5 s cooldown: the injected fault burst trips the breaker, then
        # the half-open probes burn the remaining injector shots and
        # reclose it DURING the soak — the PR-12 recovery story, measured
        ABPOA_TPU_BREAKER_COOLDOWN_S="0.5",
        ABPOA_TPU_INJECT_HANG_S="2.0",
        ABPOA_TPU_ARCHIVE="1",
        ABPOA_TPU_ARCHIVE_DIR=archive_dir,
        ABPOA_TPU_SERVE_QUEUE="8",
        # a 50 ms service-time floor makes "sustainable throughput" a
        # machine-independent ~40/s (2 workers), so 2x overload is a
        # deliverable client rate instead of a same-host TCP stress test
        ABPOA_TPU_SERVE_DELAY_S="0.05",
    )
    if not args.no_inject:
        env["ABPOA_TPU_INJECT"] = \
            "compile_fail:2,oom:2,hang:1,garbage:1,poison_set:2"
    proc = subprocess.Popen(
        [sys.executable, "-m", "abpoa_tpu.cli", "serve", "--port", "0",
         "--device", "jax", "--workers", "2", "--warm", "quick",
         "--metrics", metrics_path],
        cwd=REPO, env=env, stderr=subprocess.PIPE, text=True)
    try:
        port = read_port(proc)
        base = f"http://127.0.0.1:{port}"
        stderr_tail: list = []
        import threading
        threading.Thread(target=_drain_stderr, args=(proc, stderr_tail),
                         daemon=True).start()
        wait_ready(base, proc)

        from loadgen import LoadGen
        with open(payload, "rb") as fp:
            body = fp.read()
        with open(payload2, "rb") as fp:
            body2 = fp.read()

        # ---- calibrate sustainable throughput on the healthy server ----
        cal = LoadGen(base, [body], rate=5.0, n=12, timeout_s=120).run()
        p50_s = (cal["latency_ms"]["p50"] or 50.0) / 1e3
        sustainable = 2 / max(1e-3, p50_s)   # 2 workers
        rate = min(max(4.0, 2.0 * sustainable), 150.0)
        print(f"[serve-smoke] calibrated p50={p50_s * 1e3:.1f}ms -> "
              f"sustainable ~{sustainable:.0f}/s, soaking at {rate:.0f}/s "
              f"x {args.requests} requests", flush=True)

        # ---- the soak: 2x overload, poison mixed in ----
        # every 40th payload is malformed -> 400 (quarantine isolation)
        payloads = ([body] * 26 + [POISON_BODY] + [body2] * 13)
        gen_soak = LoadGen(base, payloads, rate=rate, n=args.requests,
                           timeout_s=120)
        soak = gen_soak.run()
        print("[serve-smoke] soak:", json.dumps(soak), flush=True)

        # ---- deadline probes: a too-tight per-request deadline is a 504,
        # never a wedged worker ----
        probes = LoadGen(base, [body], rate=5.0, n=3, timeout_s=60,
                         deadline_hdr=0.001).run()
        print("[serve-smoke] deadline probes:", json.dumps(probes),
              flush=True)

        # ---- settle, then read the server's own story ----
        # long enough for the half-open cooldown to walk through every
        # remaining injector shot (each failed probe restarts the 0.5 s
        # cooldown; the hang probe alone costs 2 s) and reclose
        gen_settle = LoadGen(base, [body], rate=5.0, n=40, timeout_s=120)
        settle = gen_settle.run()
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            expo = r.read().decode()
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            health = json.loads(r.read())
        print("[serve-smoke] health:", json.dumps(health), flush=True)

        # ---- assertions ----
        if soak["errors"] or probes["errors"] or settle["errors"]:
            failures.append(
                f"transport errors: soak={soak['errors']} "
                f"probes={probes['errors']} settle={settle['errors']} "
                "(an admission-controlled server never drops connections)")
        if args.requests >= 100 and not soak["shed"]:
            failures.append("no 429s at 2x sustainable rate: admission "
                            "control never engaged")
        if not soak["status"].get("400"):
            failures.append("no 400s: poisoned payloads were not isolated")
        if soak["status"].get("500"):
            failures.append(f"{soak['status']['500']} 500s: a worker died "
                            "on a fault shape it should absorb")
        if probes["status"].get("504", 0) < 1:
            failures.append(f"deadline probes answered "
                            f"{probes['status']}, expected 504s")
        if settle["ok"] != 40:
            failures.append(f"settle window not fully healthy: "
                            f"{settle['status']}")
        if health["status"] == "degraded":
            failures.append("still degraded after the settle window: "
                            f"{health['degraded']} (half-open recovery "
                            "never reclaimed the backend)")

        # byte-identical healthy responses, through every injector: every
        # 200 body from the overload soak AND the settle window must be
        # one of the oracle outputs
        for name, gen in (("soak", gen_soak), ("settle", gen_settle)):
            bad = sum(1 for b in gen.bodies_ok if b not in oracles)
            if bad:
                failures.append(
                    f"{bad}/{len(gen.bodies_ok)} healthy {name} responses "
                    "NOT byte-identical to the numpy oracle")

        from abpoa_tpu.obs import metrics as M
        lint = M.lint_exposition(expo)
        if lint:
            failures.append(f"exposition lint: {lint[:3]}")
        samples, _types = M.parse_exposition(expo)

        def total(fam):
            return sum(v for (n, _l), v in samples.items() if n == fam)

        if not M.sample_value(samples, "abpoa_serve_requests_total",
                              status="ok"):
            failures.append("abpoa_serve_requests_total{status=ok} missing")
        if not args.no_inject:
            if total("abpoa_breaker_opens_total") < 1:
                failures.append("breaker never opened under the injected "
                                "fault burst")
            if total("abpoa_breaker_recloses_total") < 1:
                failures.append("breaker never reclosed: the half-open "
                                "cooldown probe did not recover the "
                                "backend")
            if total("abpoa_injected_faults_total") < 5:
                failures.append("injectors fired "
                                f"{total('abpoa_injected_faults_total')} "
                                "times, expected every armed shot")

        # ---- graceful drain ----
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=90)
        if rc != 0:
            failures.append(f"SIGTERM drain exited rc={rc}, expected 0")
        stderr_text = "".join(stderr_tail)
        if "Traceback" in stderr_text:
            failures.append("server stderr carries a Traceback:\n"
                            + stderr_text[-2000:])
        if "drained clean" not in stderr_text:
            failures.append("no 'drained clean' summary in server stderr")
        if not os.path.exists(metrics_path):
            failures.append("metrics textfile never flushed")
        else:
            with open(metrics_path) as fp:
                final = fp.read()
            lint = M.lint_exposition(final)
            if lint:
                failures.append(f"final exposition lint: {lint[:3]}")

        # ---- the archive answers `abpoa-tpu slo` ----
        slo = subprocess.run(
            [sys.executable, "-m", "abpoa_tpu.cli", "slo"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
        sys.stdout.write(slo.stdout)
        if slo.returncode != 0:
            failures.append(f"`abpoa-tpu slo` rc={slo.returncode} on the "
                            f"served archive:\n{slo.stdout}\n{slo.stderr}")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        if args.keep:
            print(f"[serve-smoke] work dir kept: {tmp}")

    if failures:
        for f in failures:
            print(f"[serve-smoke] FAIL: {f}", file=sys.stderr)
        return 1
    print(f"[serve-smoke] PASS: {args.requests} soak requests at 2x "
          "overload with every injector armed — shed as 429s, poison as "
          "400s, deadlines as 504s, healthy bytes oracle-identical, "
          "breaker tripped AND reclosed, drain rc=0, slo ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
