#!/usr/bin/env python
"""Serve chaos soak: the measured form of ISSUE 12's acceptance criteria.

Starts `abpoa-tpu serve` (device jax pinned to CPU — no accelerator
needed) with EVERY fault injector armed and a 1 s breaker cooldown, then
drives it with `tools/loadgen.py` at ~2x the calibrated sustainable
throughput, with poisoned payloads and tiny-deadline probes mixed in.
The server must:

- never crash or OOM: rc=0 at SIGTERM, zero transport errors client-side,
  no Traceback in its stderr;
- shed overload as 429 + Retry-After, never by queueing without bound;
- answer poisoned sets with 400 and deadline expiries with 504, each with
  a fault record — while the worker pool survives;
- keep every 200 byte-identical to the numpy oracle, through compile
  failures, injected OOMs, hangs and garbage outputs (the degradation
  ladder + output guards doing their jobs);
- trip the circuit breaker on the injected fault burst AND reclose it
  through the half-open cooldown probe once the injectors exhaust
  (abpoa_breaker_opens_total >= 1 and abpoa_breaker_recloses_total >= 1);
- leave a lint-clean Prometheus exposition and an archive window on which
  `abpoa-tpu slo` passes;
- drain clean on SIGTERM: in-flight finished, metrics flushed, exit 0.

A second phase (ISSUE 13, skip with --no-pool-phase) starts a fresh
server with ``--pool-workers 2`` — requests executing in supervised
worker PROCESSES — and SIGKILLs a live worker mid-soak. The service must
keep answering 200s byte-identical to the numpy oracle (the killed job
requeues once on a fresh worker; the only acceptable 5xx is a designed
504), the supervisor must respawn the worker, and the restarted worker
must be WARM: zero true XLA compiles inside workers for the whole phase
(`abpoa_pool_worker_xla_compiles_total` == 0 — every worker compile is a
persistent-cache load).

    python tools/serve_smoke.py [--keep] [--requests N] [--no-inject]
                                [--no-pool-phase]
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS)
DATA = os.path.join(REPO, "tests", "data")
sys.path.insert(0, REPO)
sys.path.insert(0, TOOLS)

POISON_BODY = b"@truncated\nACGTACGT\n+\nIII\n"   # qual len != seq len -> 400


def oracle_body(payload_path: str) -> bytes:
    """The numpy-oracle response bytes for one payload — computed in THIS
    process on the reference host path; every healthy serve response must
    match one of these byte for byte."""
    import io
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa
    abpt = Params()
    abpt.device = "numpy"
    abpt.finalize()
    buf = io.StringIO()
    msa(Abpoa(), abpt, read_fastx(payload_path), buf)
    return buf.getvalue().encode()


def wait_ready(base: str, proc, timeout_s: float = 600.0) -> None:
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        if proc.poll() is not None:
            raise RuntimeError(f"server exited rc={proc.returncode} "
                               "before becoming ready")
        try:
            with urllib.request.urlopen(base + "/readyz", timeout=2) as r:
                if r.status == 200:
                    return
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.25)
    raise RuntimeError("server never became ready")


def read_port(proc, timeout_s: float = 120.0) -> int:
    """Parse the bound port from the 'listening on' stderr line."""
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        line = proc.stderr.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(f"server exited rc={proc.returncode} "
                                   "during startup")
            time.sleep(0.05)
            continue
        sys.stderr.write(f"[server] {line}")
        if "listening on http://" in line:
            return int(line.split("listening on http://")[1]
                       .split()[0].rsplit(":", 1)[1])
    raise RuntimeError("never saw the listening line")


def _drain_stderr(proc, sink: list) -> None:
    for line in proc.stderr:
        sink.append(line)


def run_pool_kill_phase(base_env: dict, payload_path: str, oracles: set,
                        tmp: str) -> list:
    """ISSUE-13 phase: serve with --pool-workers 2, SIGKILL a worker
    mid-soak, assert containment + warm restart. ISSUE-15 extends it into
    the chaos proof: with --trace-dir + sampling on, the killed request
    must yield (a) a per-request Chrome trace whose spans cross the pipe
    boundary under one request id, (b) a harvested flight-recorder dump
    attached to its archive record, and (c) an `abpoa-tpu why` verdict
    naming the kill — and every non-ok archived record must carry a
    request id. Returns failure strings."""
    import threading
    failures: list = []
    metrics_path = os.path.join(tmp, "metrics_pool.prom")
    trace_dir = os.path.join(tmp, "traces_pool")
    env = dict(base_env)
    # two kill sources at once: the worker_sigsegv injector crashes ONE
    # request's worker twice (a poison job: quarantined, answered 500,
    # supervisor lives), and an external SIGKILL lands mid-soak (the
    # killed job requeues once and still answers 200)
    env["ABPOA_TPU_INJECT"] = "worker_sigsegv:2"
    env["ABPOA_TPU_TRACE_SAMPLE"] = "1"
    env["ABPOA_TPU_FLIGHT_DIR"] = os.path.join(tmp, "flight")
    proc = subprocess.Popen(
        [sys.executable, "-m", "abpoa_tpu.cli", "serve", "--port", "0",
         "--device", "jax", "--workers", "2", "--pool-workers", "2",
         "--warm", "quick", "--metrics", metrics_path,
         "--trace-dir", trace_dir],
        cwd=REPO, env=env, stderr=subprocess.PIPE, text=True)
    try:
        port = read_port(proc)
        base = f"http://127.0.0.1:{port}"
        stderr_tail: list = []
        threading.Thread(target=_drain_stderr, args=(proc, stderr_tail),
                         daemon=True).start()
        wait_ready(base, proc)

        from loadgen import LoadGen
        with open(payload_path, "rb") as fp:
            body = fp.read()

        def read_pool():
            with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
                return json.loads(r.read()).get("pool") or {}

        pool0 = read_pool()
        if pool0.get("workers") != 2:
            failures.append(f"pool phase: {pool0.get('workers')} workers "
                            "ready, expected 2")

        # a few warmup requests so every worker has served (their
        # first-compile cache loads land BEFORE the kill window); the
        # worker_sigsegv victim usually lands here too
        gen_warm = LoadGen(base, [body], rate=5.0, n=6, timeout_s=120)
        warm = gen_warm.run()
        print("[serve-smoke] pool warmup:", json.dumps(warm), flush=True)

        def kill_one():
            try:
                pids = read_pool().get("pids") or []
                if pids:
                    os.kill(pids[0], signal.SIGKILL)
                    print(f"[serve-smoke] pool phase: SIGKILLed worker "
                          f"pid {pids[0]} mid-soak", flush=True)
            except (OSError, urllib.error.URLError) as e:
                failures.append(f"pool phase: worker kill failed: {e}")

        timer = threading.Timer(1.5, kill_one)
        timer.start()
        gen = LoadGen(base, [body], rate=10.0, n=60, timeout_s=120)
        soak = gen.run()
        timer.cancel()
        print("[serve-smoke] pool-kill soak:", json.dumps(soak), flush=True)

        if soak["errors"]:
            failures.append(f"pool phase: {soak['errors']} transport "
                            "errors through the worker kills")
        pool1 = read_pool()
        # designed 5xx only: 504s, plus exactly one 500 per quarantined
        # poison job (the worker_sigsegv:2 victim, warmup included)
        merged = dict(warm["status"])
        for c, n in soak["status"].items():
            merged[c] = merged.get(c, 0) + n
        bad_5xx = {c: n for c, n in merged.items()
                   if c.startswith("5") and c != "504"}
        n_500 = merged.get("500", 0)
        bad_5xx.pop("500", None)
        if bad_5xx:
            failures.append(f"pool phase: undesigned 5xx through the "
                            f"worker kills: {bad_5xx}")
        if n_500 != pool1.get("poison_jobs", 0):
            failures.append(f"pool phase: {n_500} 500s vs "
                            f"{pool1.get('poison_jobs')} poison jobs — "
                            "every 500 must be a quarantined poison job")
        if pool1.get("poison_jobs") != 1:
            failures.append(f"pool phase: poison_jobs = "
                            f"{pool1.get('poison_jobs')}, expected the "
                            "worker_sigsegv:2 victim quarantined exactly "
                            "once")
        if pool1.get("requeues", 0) < 1:
            failures.append("pool phase: no requeue recorded (sigsegv "
                            "retry + SIGKILLed in-flight job)")
        # every 200 body — warmup included (those hit the coldest and the
        # sigsegv-respawned workers) — must match the numpy oracle
        bodies = list(gen_warm.bodies_ok) + list(gen.bodies_ok)
        bad = sum(1 for b in bodies if b not in oracles)
        if bad:
            failures.append(f"pool phase: {bad}/{len(bodies)} healthy "
                            "responses NOT byte-identical to the numpy "
                            "oracle")
        if not pool1.get("restarts"):
            failures.append("pool phase: supervisor recorded no restart "
                            f"after the kills ({pool1})")
        if pool1.get("workers") != 2:
            failures.append(f"pool phase: {pool1.get('workers')} workers "
                            "after the kills, expected 2 (respawn)")

        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            expo = r.read().decode()
        from abpoa_tpu.obs import metrics as M
        lint = M.lint_exposition(expo)
        if lint:
            failures.append(f"pool phase: exposition lint: {lint[:3]}")
        samples, _types = M.parse_exposition(expo)
        for fam in ("abpoa_pool_workers", "abpoa_pool_restarts_total",
                    "abpoa_pool_kills_total"):
            if M.sample_value(samples, fam) is None:
                failures.append(f"pool phase: {fam} missing from "
                                "exposition")
        # the warm-restart claim: zero true XLA compiles inside workers
        # across the WHOLE phase — the respawned worker loaded every rung
        # from the persistent cache the startup warm filled. The family
        # is materialized at pool start, so absence is a broken pipeline,
        # not a vacuous pass.
        burst = M.sample_value(samples,
                               "abpoa_pool_worker_xla_compiles_total")
        if burst is None:
            failures.append("pool phase: abpoa_pool_worker_xla_compiles_"
                            "total missing — the warm-restart claim is "
                            "unverifiable")
        elif burst:
            failures.append(f"pool phase: {burst:.0f} true XLA compiles "
                            "inside workers — the restarted worker was "
                            "NOT warm from the persistent cache")

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=90)
        if rc != 0:
            failures.append(f"pool phase: SIGTERM drain exited rc={rc}")
        if "Traceback" in "".join(stderr_tail):
            failures.append("pool phase: server stderr carries a "
                            "Traceback:\n" + "".join(stderr_tail)[-2000:])

        # ---- ISSUE-15 chaos proof: traces, dumps, why, archive lint ----
        failures.extend(check_tracing_artifacts(env, trace_dir))
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    return failures


def check_tracing_artifacts(env: dict, trace_dir: str) -> list:
    """The PR-15 acceptance assertions over the pool phase's leftovers:
    per-request traces crossing the pipe boundary, harvested flight
    dumps, `why` verdicts naming the kill, and the archive-record
    request-id lint."""
    failures: list = []
    archive_path = os.path.join(env["ABPOA_TPU_ARCHIVE_DIR"],
                                "reports.jsonl")
    recs = []
    try:
        with open(archive_path) as fp:
            for ln in fp:
                try:
                    recs.append(json.loads(ln))
                except ValueError:
                    failures.append(f"unparseable archive line: {ln[:80]}")
    except OSError as e:
        return [f"tracing: archive unreadable: {e}"]
    reqs = [r for r in recs
            if r.get("kind") in ("serve_request", "pool_job")]

    # lint: every non-2xx (non-ok) archived record carries a request id
    bad = [r for r in reqs if r.get("status") != "ok"
           and not r.get("request_id")]
    if bad:
        failures.append(f"tracing: {len(bad)} non-ok archive records "
                        f"without a request_id: {bad[:2]}")

    # (b) the killed request's harvested flight dump, attached to its
    # archive record — the mid-soak SIGKILL (requeued, then ok) and the
    # worker_sigsegv poison job (error) both must have one
    dumped = [r for r in reqs if r.get("dump_file")]
    if not dumped:
        failures.append("tracing: no archive record carries a dump_file "
                        "(flight-recorder harvest never happened)")
    for rec in dumped[:1] + [r for r in dumped if r.get("status") != "ok"][:1]:
        dump_path = rec["dump_file"]
        if not os.path.exists(dump_path):
            failures.append(f"tracing: dump_file {dump_path} missing")
            continue
        # (c) the `why` verdict names the kill
        why = subprocess.run(
            [sys.executable, "-m", "abpoa_tpu.cli", "why", dump_path],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=60)
        print(f"[serve-smoke] why {os.path.basename(dump_path)}:\n"
              + why.stdout, flush=True)
        if why.returncode != 0:
            failures.append(f"tracing: `abpoa-tpu why {dump_path}` "
                            f"rc={why.returncode}: {why.stderr[-500:]}")
        elif not ("crashed" in why.stdout or "hard-killed" in why.stdout
                  or "killed" in why.stdout):
            failures.append("tracing: why verdict does not name the kill:\n"
                            + why.stdout)
        elif "verdict:" not in why.stdout:
            failures.append("tracing: why output carries no verdict line")

    # (a) a per-request Chrome trace whose spans cross the pipe boundary
    # under one request id: parent-side pool spans AND worker-side job
    # spans in one file, all tagged with the file's rid
    traced = [r for r in reqs if r.get("trace_file")
              and os.path.exists(r.get("trace_file", ""))]
    if not traced:
        failures.append("tracing: no archive record carries a readable "
                        "trace_file")
    crossed = 0
    for rec in traced:
        with open(rec["trace_file"]) as fp:
            doc = json.load(fp)
        spans = [e for e in doc.get("traceEvents", [])
                 if e.get("ph") == "X"]
        rids = {(e.get("args") or {}).get("rid") for e in spans}
        if rids != {rec["request_id"]}:
            failures.append(f"tracing: {rec['trace_file']} carries "
                            f"foreign/missing rids: {rids}")
            continue
        cats = {e.get("cat") for e in spans}
        if "pool" in cats and "job" in cats:
            crossed += 1
    if traced and not crossed:
        failures.append("tracing: no per-request trace carries BOTH "
                        "parent-side pool spans and worker-side job "
                        "spans (the pipe crossing is invisible)")
    else:
        print(f"[serve-smoke] tracing: {len(traced)} per-request traces, "
              f"{crossed} crossing the worker pipe; {len(dumped)} dumps "
              "harvested", flush=True)
    return failures


def run_overhead_phase(base_env: dict, payload_path: str, tmp: str) -> list:
    """ISSUE-15 acceptance: sampled tracing (--trace-dir, sample 1.0)
    costs <= 2% p50 on the warm serve-smoke payload (the 50 ms shim is
    part of that payload: it models the calibrated service time the
    other phases measure against). Two identical numpy-device servers —
    no warm needed, startup is instant — one traced, one not."""
    failures: list = []
    p50 = {}
    from loadgen import LoadGen
    with open(payload_path, "rb") as fp:
        body = fp.read()
    for mode in ("off", "on"):
        env = dict(base_env)
        env.pop("ABPOA_TPU_INJECT", None)
        env["ABPOA_TPU_TRACE_SAMPLE"] = "1"
        cmd = [sys.executable, "-m", "abpoa_tpu.cli", "serve", "--port",
               "0", "--device", "numpy", "--workers", "2", "--warm", "off"]
        if mode == "on":
            cmd += ["--trace-dir", os.path.join(tmp, "traces_overhead")]
        proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                                stderr=subprocess.PIPE, text=True)
        try:
            port = read_port(proc)
            base = f"http://127.0.0.1:{port}"
            import threading
            threading.Thread(target=_drain_stderr, args=(proc, []),
                             daemon=True).start()
            wait_ready(base, proc)
            LoadGen(base, [body], rate=10.0, n=10, timeout_s=60).run()
            res = LoadGen(base, [body], rate=10.0, n=80,
                          timeout_s=60).run()
            p50[mode] = res["latency_ms"]["p50"]
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
    print(f"[serve-smoke] tracing overhead: p50 {p50['off']:.2f} ms "
          f"untraced -> {p50['on']:.2f} ms traced "
          f"({100 * (p50['on'] / p50['off'] - 1):+.1f}%)", flush=True)
    # 2% of the ~55 ms shimmed payload is ~1.1 ms; the extra 1 ms floor
    # absorbs scheduler jitter on shared CI runners
    if p50["on"] > p50["off"] * 1.02 + 1.0:
        failures.append(f"tracing overhead past the 2% bound: "
                        f"p50 {p50['off']:.2f} ms -> {p50['on']:.2f} ms")
    return failures


def run_ledger_overhead_phase(base_env: dict, tmp: str) -> list:
    """ISSUE-20 acceptance: the round-timeline ring + ledger hooks cost
    <= 2% p50 on a payload whose route actually records rounds (the
    2 kb reads that clear the serial-wins crossover, so serve coalesces
    into lockstep and every round runs the record_round hook). Same
    paired-server discipline as run_overhead_phase: two identical warm
    jax servers, one with ABPOA_TPU_ROUNDS/ABPOA_TPU_LEDGER disabled."""
    failures: list = []
    p50 = {}
    from loadgen import LoadGen
    sim = os.path.join(tmp, "ledger_overhead_4x2000.fa")
    subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "make_sim.py"),
         "--ref-len", "2000", "--n-reads", "4", "--err", "0.1",
         "--seed", "2001", "--out", sim], check=True)
    with open(sim, "rb") as fp:
        body = fp.read()
    rate = None
    for mode in ("off", "on"):
        env = dict(base_env)
        env.pop("ABPOA_TPU_INJECT", None)
        env.pop("ABPOA_TPU_SERVE_DELAY_S", None)   # real service time
        env["ABPOA_TPU_LEDGER_DIR"] = os.path.join(tmp, "ledger_overhead")
        if mode == "off":
            env["ABPOA_TPU_ROUNDS"] = "0"
            env["ABPOA_TPU_LEDGER"] = "0"
        proc = subprocess.Popen(
            [sys.executable, "-m", "abpoa_tpu.cli", "serve", "--port", "0",
             "--device", "jax", "--workers", "2", "--warm", "quick"],
            cwd=REPO, env=env, stderr=subprocess.PIPE, text=True)
        try:
            port = read_port(proc)
            base = f"http://127.0.0.1:{port}"
            import threading
            threading.Thread(target=_drain_stderr, args=(proc, []),
                             daemon=True).start()
            wait_ready(base, proc)
            # warm pass (cache loads), then — on the OFF side only —
            # calibrate; the ON side reuses the identical open-loop
            # schedule, because an A/B whose two sides run different
            # rates measures queueing, not the hook
            LoadGen(base, [body], rate=2.0, n=4, timeout_s=300).run()
            if rate is None:
                cal = LoadGen(base, [body], rate=2.0, n=6,
                              timeout_s=300).run()
                solo_s = max((cal["latency_ms"]["p50"] or 500.0) / 1e3,
                             0.05)
                # half of 2-worker capacity: queueing stays out of p50
                rate = max(0.5, 0.5 * 2 / solo_s)
            res = LoadGen(base, [body], rate=rate, n=32,
                          timeout_s=300).run()
            p50[mode] = res["latency_ms"]["p50"]
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
    print(f"[serve-smoke] ledger+ring overhead: p50 {p50['off']:.2f} ms "
          f"off -> {p50['on']:.2f} ms on "
          f"({100 * (p50['on'] / p50['off'] - 1):+.1f}%)", flush=True)
    if p50["on"] > p50["off"] * 1.02 + 1.0:
        failures.append(f"ledger+ring overhead past the 2% bound: "
                        f"p50 {p50['off']:.2f} ms -> {p50['on']:.2f} ms")
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=240,
                    help="soak request count (>= 200 for the CI claim) "
                         "[%(default)s]")
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir for inspection")
    ap.add_argument("--no-inject", action="store_true",
                    help="skip the fault injectors (pure overload soak)")
    ap.add_argument("--no-pool-phase", action="store_true",
                    help="skip the --pool-workers worker-kill phase")
    args = ap.parse_args(argv)
    tmp = tempfile.mkdtemp(prefix="abpoa_serve_smoke_")
    payload = os.path.join(DATA, "test.fa")
    payload2 = os.path.join(DATA, "seq.fa")
    oracles = {oracle_body(payload), oracle_body(payload2)}
    metrics_path = os.path.join(tmp, "metrics.prom")
    archive_dir = os.path.join(tmp, "reports")
    failures: list = []

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        ABPOA_TPU_SKIP_PROBE="1",
        ABPOA_TPU_BREAKER_THRESHOLD="2",
        # 0.5 s cooldown: the injected fault burst trips the breaker, then
        # the half-open probes burn the remaining injector shots and
        # reclose it DURING the soak — the PR-12 recovery story, measured
        ABPOA_TPU_BREAKER_COOLDOWN_S="0.5",
        ABPOA_TPU_INJECT_HANG_S="2.0",
        ABPOA_TPU_ARCHIVE="1",
        ABPOA_TPU_ARCHIVE_DIR=archive_dir,
        ABPOA_TPU_SERVE_QUEUE="8",
        # a 50 ms service-time floor makes "sustainable throughput" a
        # machine-independent ~40/s (2 workers), so 2x overload is a
        # deliverable client rate instead of a same-host TCP stress test
        ABPOA_TPU_SERVE_DELAY_S="0.05",
    )
    if not args.no_inject:
        env["ABPOA_TPU_INJECT"] = \
            "compile_fail:2,oom:2,hang:1,garbage:1,poison_set:2"
    proc = subprocess.Popen(
        [sys.executable, "-m", "abpoa_tpu.cli", "serve", "--port", "0",
         "--device", "jax", "--workers", "2", "--warm", "quick",
         "--metrics", metrics_path],
        cwd=REPO, env=env, stderr=subprocess.PIPE, text=True)
    try:
        port = read_port(proc)
        base = f"http://127.0.0.1:{port}"
        stderr_tail: list = []
        import threading
        threading.Thread(target=_drain_stderr, args=(proc, stderr_tail),
                         daemon=True).start()
        wait_ready(base, proc)

        from loadgen import LoadGen
        with open(payload, "rb") as fp:
            body = fp.read()
        with open(payload2, "rb") as fp:
            body2 = fp.read()

        # ---- calibrate sustainable throughput on the healthy server ----
        cal = LoadGen(base, [body], rate=5.0, n=12, timeout_s=120).run()
        p50_s = (cal["latency_ms"]["p50"] or 50.0) / 1e3
        sustainable = 2 / max(1e-3, p50_s)   # 2 workers
        rate = min(max(4.0, 2.0 * sustainable), 150.0)
        print(f"[serve-smoke] calibrated p50={p50_s * 1e3:.1f}ms -> "
              f"sustainable ~{sustainable:.0f}/s, soaking at {rate:.0f}/s "
              f"x {args.requests} requests", flush=True)

        # ---- the soak: 2x overload, poison mixed in ----
        # every 40th payload is malformed -> 400 (quarantine isolation)
        payloads = ([body] * 26 + [POISON_BODY] + [body2] * 13)
        gen_soak = LoadGen(base, payloads, rate=rate, n=args.requests,
                           timeout_s=120)
        soak = gen_soak.run()
        print("[serve-smoke] soak:", json.dumps(soak), flush=True)

        # ---- deadline probes: a too-tight per-request deadline is a 504,
        # never a wedged worker ----
        probes = LoadGen(base, [body], rate=5.0, n=3, timeout_s=60,
                         deadline_hdr=0.001).run()
        print("[serve-smoke] deadline probes:", json.dumps(probes),
              flush=True)

        # ---- settle, then read the server's own story ----
        # long enough for the half-open cooldown to walk through every
        # remaining injector shot (each failed probe restarts the 0.5 s
        # cooldown; the hang probe alone costs 2 s) and reclose
        gen_settle = LoadGen(base, [body], rate=5.0, n=40, timeout_s=120)
        settle = gen_settle.run()
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            expo = r.read().decode()
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            health = json.loads(r.read())
        print("[serve-smoke] health:", json.dumps(health), flush=True)

        # ---- assertions ----
        if soak["errors"] or probes["errors"] or settle["errors"]:
            failures.append(
                f"transport errors: soak={soak['errors']} "
                f"probes={probes['errors']} settle={settle['errors']} "
                "(an admission-controlled server never drops connections)")
        if args.requests >= 100 and not soak["shed"]:
            failures.append("no 429s at 2x sustainable rate: admission "
                            "control never engaged")
        if not soak["status"].get("400"):
            failures.append("no 400s: poisoned payloads were not isolated")
        if soak["status"].get("500"):
            failures.append(f"{soak['status']['500']} 500s: a worker died "
                            "on a fault shape it should absorb")
        if probes["status"].get("504", 0) < 1:
            failures.append(f"deadline probes answered "
                            f"{probes['status']}, expected 504s")
        if settle["ok"] != 40:
            failures.append(f"settle window not fully healthy: "
                            f"{settle['status']}")
        if health["status"] == "degraded":
            failures.append("still degraded after the settle window: "
                            f"{health['degraded']} (half-open recovery "
                            "never reclaimed the backend)")

        # byte-identical healthy responses, through every injector: every
        # 200 body from the overload soak AND the settle window must be
        # one of the oracle outputs
        for name, gen in (("soak", gen_soak), ("settle", gen_settle)):
            bad = sum(1 for b in gen.bodies_ok if b not in oracles)
            if bad:
                failures.append(
                    f"{bad}/{len(gen.bodies_ok)} healthy {name} responses "
                    "NOT byte-identical to the numpy oracle")

        from abpoa_tpu.obs import metrics as M
        lint = M.lint_exposition(expo)
        if lint:
            failures.append(f"exposition lint: {lint[:3]}")
        samples, _types = M.parse_exposition(expo)

        def total(fam):
            return sum(v for (n, _l), v in samples.items() if n == fam)

        if not M.sample_value(samples, "abpoa_serve_requests_total",
                              status="ok"):
            failures.append("abpoa_serve_requests_total{status=ok} missing")
        if not args.no_inject:
            if total("abpoa_breaker_opens_total") < 1:
                failures.append("breaker never opened under the injected "
                                "fault burst")
            if total("abpoa_breaker_recloses_total") < 1:
                failures.append("breaker never reclosed: the half-open "
                                "cooldown probe did not recover the "
                                "backend")
            if total("abpoa_injected_faults_total") < 5:
                failures.append("injectors fired "
                                f"{total('abpoa_injected_faults_total')} "
                                "times, expected every armed shot")

        # ---- graceful drain ----
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=90)
        if rc != 0:
            failures.append(f"SIGTERM drain exited rc={rc}, expected 0")
        stderr_text = "".join(stderr_tail)
        if "Traceback" in stderr_text:
            failures.append("server stderr carries a Traceback:\n"
                            + stderr_text[-2000:])
        if "drained clean" not in stderr_text:
            failures.append("no 'drained clean' summary in server stderr")
        if not os.path.exists(metrics_path):
            failures.append("metrics textfile never flushed")
        else:
            with open(metrics_path) as fp:
                final = fp.read()
            lint = M.lint_exposition(final)
            if lint:
                failures.append(f"final exposition lint: {lint[:3]}")

        # ---- the archive answers `abpoa-tpu slo` ----
        slo = subprocess.run(
            [sys.executable, "-m", "abpoa_tpu.cli", "slo"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
        sys.stdout.write(slo.stdout)
        if slo.returncode != 0:
            failures.append(f"`abpoa-tpu slo` rc={slo.returncode} on the "
                            f"served archive:\n{slo.stdout}\n{slo.stderr}")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        if args.keep:
            print(f"[serve-smoke] work dir kept: {tmp}")

    if not args.no_pool_phase:
        failures.extend(run_pool_kill_phase(env, payload, oracles, tmp))
        failures.extend(run_overhead_phase(env, payload, tmp))
        failures.extend(run_ledger_overhead_phase(env, tmp))

    try:
        from abpoa_tpu.obs import ledger
        lm = soak.get("latency_ms") or {}
        goodput = (round(soak["ok"] / soak["wall_s"], 3)
                   if soak.get("wall_s") else None)
        failures.extend(ledger.append_and_verify(ledger.make_record(
            "serve_smoke",
            workload=f"soak_{args.requests}req",
            device="jax",
            route="lockstep",
            reads_per_sec=goodput,
            read_wall_ms={p: lm.get(p) for p in ("p50", "p95", "p99")},
            verdict="pass" if not failures else "fail",
            extra={"errors": soak.get("errors"),
                   "shed": soak.get("shed")})))
    except Exception as exc:
        failures.append(f"ledger append raised: {exc}")

    if failures:
        for f in failures:
            print(f"[serve-smoke] FAIL: {f}", file=sys.stderr)
        return 1
    print(f"[serve-smoke] PASS: {args.requests} soak requests at 2x "
          "overload with every injector armed — shed as 429s, poison as "
          "400s, deadlines as 504s, healthy bytes oracle-identical, "
          "breaker tripped AND reclosed, drain rc=0, slo ok"
          + ("" if args.no_pool_phase else
             "; pool phase: mid-soak worker SIGKILL contained, requeued, "
             "respawned warm (0 worker XLA compiles), per-request traces "
             "cross the worker pipe, flight dumps harvested, `why` names "
             "the kill"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
