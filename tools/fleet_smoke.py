#!/usr/bin/env python
"""Fleet chaos smoke: the measured form of ISSUE 16's acceptance
criteria (CI job `fleet-smoke`).

Starts `abpoa-tpu serve --replicas 3` (numpy device — no accelerator,
instant replica startup; a 50 ms service-time shim makes throughput a
deliverable number), calibrates the single-replica sustainable rate,
then soaks the ROUTER at ~2x that rate while SIGKILLing one replica
mid-soak. The fleet must:

- lose ZERO requests: loadgen reports 0 transport errors and no 5xx —
  the killed replica's in-flight requests are failed over exactly once
  to a sibling (same request id, attempt 2) and still answer 200;
- keep every 200 byte-identical to the numpy oracle, through the kill
  and the respawn;
- respawn the killed replica (supervisor backoff) and return to 3 ready;
- expose ONE merged fleet exposition (router /metrics = replica scrapes
  + router families via merge_expositions) that lints clean, with the
  --metrics textfile carrying the same roll-up;
- answer `abpoa-tpu slo --fleet` rc=0 over the merged replica archives,
  and `abpoa-tpu why <id>` for a failed-over request id, naming the
  replica hop;
- drain clean on SIGTERM: every replica SIGTERMed, router stopped, rc 0.

    python tools/fleet_smoke.py [--requests N] [--keep]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS)
DATA = os.path.join(REPO, "tests", "data")
sys.path.insert(0, REPO)
sys.path.insert(0, TOOLS)

from serve_smoke import (_drain_stderr, oracle_body, read_port,  # noqa: E402
                         wait_ready)


def get_json(base: str, path: str) -> dict:
    with urllib.request.urlopen(base + path, timeout=10) as r:
        return json.loads(r.read())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=240,
                    help="soak request count [%(default)s]")
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir for inspection")
    args = ap.parse_args(argv)
    tmp = tempfile.mkdtemp(prefix="abpoa_fleet_smoke_")
    failures: list = []
    soak: dict = {}
    payload = os.path.join(DATA, "test.fa")
    oracles = {oracle_body(payload)}
    archive_base = os.path.join(tmp, "reports")
    metrics_path = os.path.join(tmp, "fleet_metrics.prom")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               ABPOA_TPU_SKIP_PROBE="1",
               ABPOA_TPU_ARCHIVE="1",
               ABPOA_TPU_ARCHIVE_DIR=archive_base,
               # the service-time shim: deliverable-throughput floor,
               # and the window that keeps requests IN FLIGHT when the
               # SIGKILL lands
               ABPOA_TPU_SERVE_DELAY_S="0.05",
               ABPOA_TPU_FLEET_POLL_S="0.1",
               ABPOA_TPU_POOL_BACKOFF_S="0.2")
    proc = subprocess.Popen(
        [sys.executable, "-m", "abpoa_tpu.cli", "serve", "--replicas", "3",
         "--port", "0", "--device", "numpy", "--workers", "2",
         "--warm", "off", "--metrics", metrics_path],
        cwd=REPO, env=env, stderr=subprocess.PIPE, text=True)
    stderr_tail: list = []
    try:
        # the FIRST listening line is the router's (printed before any
        # replica spawns); replica lines arrive later under [rN] prefixes
        port = read_port(proc)
        base = f"http://127.0.0.1:{port}"
        threading.Thread(target=_drain_stderr, args=(proc, stderr_tail),
                         daemon=True).start()
        wait_ready(base, proc, timeout_s=120)

        # full strength before the chaos: 3 ready replicas, known pids
        pids = {}
        deadline = time.time() + 60
        while time.time() < deadline:
            doc = get_json(base, "/healthz")
            if doc.get("ready") == 3:
                pids = doc["fleet"]["pids"]
                break
            time.sleep(0.2)
        if len(pids) != 3:
            failures.append(f"fleet never reached 3 ready replicas: {doc}")
            raise RuntimeError("startup failed")
        print(f"[fleet-smoke] 3 replicas ready, pids={pids}", flush=True)

        from loadgen import LoadGen
        with open(payload, "rb") as fp:
            body = fp.read()

        # ---- calibrate the single-replica sustainable rate ----
        cal = LoadGen(base, [body], rate=5.0, n=12, timeout_s=120,
                      fleet=True).run()
        p50_s = max(1e-3, (cal["latency_ms"]["p50"] or 50.0) / 1e3)
        sustainable = 2 / p50_s            # 2 workers per replica
        rate = min(max(4.0, 2.0 * sustainable), 150.0)
        print(f"[fleet-smoke] calibrated p50={p50_s * 1e3:.1f}ms -> "
              f"single-replica sustainable ~{sustainable:.0f}/s, soaking "
              f"the 3-replica fleet at {rate:.0f}/s "
              f"({args.requests} requests)", flush=True)

        # ---- chaos soak: SIGKILL one replica with requests in flight --
        kill_at = 0.3 * args.requests / rate

        def kill_one():
            try:
                os.kill(pids["r0"], signal.SIGKILL)
                print(f"[fleet-smoke] SIGKILLed replica r0 "
                      f"(pid {pids['r0']}) mid-soak", flush=True)
            except OSError as e:
                failures.append(f"replica kill failed: {e}")

        timer = threading.Timer(kill_at, kill_one)
        timer.start()
        gen = LoadGen(base, [body], rate=rate, n=args.requests,
                      timeout_s=120, fleet=True)
        soak = gen.run()
        timer.cancel()
        print("[fleet-smoke] soak:", json.dumps(soak), flush=True)

        # zero lost requests: no transport errors, no 5xx — the kill is
        # at most an invisible retried attempt
        if soak["errors"]:
            failures.append(f"{soak['errors']} transport errors through "
                            "the replica kill")
        bad = {c: n for c, n in soak["status"].items()
               if c.startswith("5") or c == "0"}
        if bad:
            failures.append(f"5xx through the replica kill: {bad}")
        if soak["fleet"]["failovers"] < 1 \
                and soak["fleet"]["retried_ok"] < 1:
            failures.append("no failover recorded — the kill never "
                            "exercised the retry path "
                            f"({soak['fleet']})")
        if len(soak["fleet"]["by_replica"]) < 2:
            failures.append("soak traffic never spread across replicas: "
                            f"{soak['fleet']['by_replica']}")
        bad_bodies = sum(1 for b in gen.bodies_ok if b not in oracles)
        if bad_bodies:
            failures.append(f"{bad_bodies}/{len(gen.bodies_ok)} 200 "
                            "bodies NOT byte-identical to the numpy "
                            "oracle")

        # ---- the supervisor respawns: back to 3 ready ----
        deadline = time.time() + 60
        back = 0
        while time.time() < deadline:
            back = get_json(base, "/healthz").get("ready", 0)
            if back == 3:
                break
            time.sleep(0.3)
        if back != 3:
            failures.append(f"killed replica never respawned: "
                            f"{back}/3 ready")

        # ---- merged exposition lints (router endpoint + textfile) ----
        from abpoa_tpu.obs import metrics as M
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            expo = r.read().decode()
        lint = M.lint_exposition(expo)
        if lint:
            failures.append(f"merged /metrics lint: {lint[:3]}")
        samples, _types = M.parse_exposition(expo)
        served = M.sample_value(samples, "abpoa_serve_requests_total",
                                status="ok")
        routed = M.sample_value(samples, "abpoa_fleet_requests_total",
                                status="ok")
        if not served or not routed:
            failures.append("merged exposition is missing replica or "
                            f"router families (served={served}, "
                            f"routed={routed})")
        time.sleep(2.5)               # one textfile roll interval
        try:
            with open(metrics_path) as fp:
                tf = fp.read()
            if M.lint_exposition(tf):
                failures.append("metrics textfile roll-up does not lint")
        except OSError as e:
            failures.append(f"metrics textfile missing: {e}")

        # ---- slo --fleet over the merged replica archives ----
        slo = subprocess.run(
            [sys.executable, "-m", "abpoa_tpu.cli", "slo", "--fleet"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
        print("[fleet-smoke] slo --fleet:\n" + slo.stdout, flush=True)
        if slo.returncode != 0:
            failures.append(f"`slo --fleet` rc={slo.returncode}:\n"
                            + slo.stdout + slo.stderr)

        # ---- `why` explains a failed-over request across archives ----
        from abpoa_tpu.obs import archive as A
        hop = next((rec for rec in A.read_fleet_window(0, archive_base)
                    if (rec.get("attempt") or 1) > 1
                    and rec.get("request_id")), None)
        if hop is None:
            failures.append("no attempt>1 record in any replica archive "
                            "— the failover hop left no trace")
        else:
            why = subprocess.run(
                [sys.executable, "-m", "abpoa_tpu.cli", "why",
                 hop["request_id"], "--fleet"],
                cwd=REPO, env=env, capture_output=True, text=True,
                timeout=120)
            print(f"[fleet-smoke] why {hop['request_id']}:\n"
                  + why.stdout, flush=True)
            if why.returncode != 0:
                failures.append(f"`why --fleet` rc={why.returncode}: "
                                + why.stderr[-500:])
            elif "attempt" not in why.stdout \
                    or "replica" not in why.stdout:
                failures.append("why output does not name the replica "
                                "hop:\n" + why.stdout)

        # ---- fleet drain: SIGTERM -> rc 0 ----
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        if rc != 0:
            failures.append(f"SIGTERM fleet drain exited rc={rc}")
        tail = "".join(stderr_tail)
        if "drained clean" not in tail:
            failures.append("fleet never printed its drain summary")
        if "Traceback" in tail:
            failures.append("fleet stderr carries a Traceback:\n"
                            + tail[-2000:])
    except RuntimeError:
        pass
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        if args.keep:
            print(f"[fleet-smoke] kept workdir: {tmp}", flush=True)
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    try:
        from abpoa_tpu.obs import ledger
        lm = soak.get("latency_ms") or {}
        goodput = (round(soak["ok"] / soak["wall_s"], 3)
                   if soak.get("wall_s") else None)
        failures.extend(ledger.append_and_verify(ledger.make_record(
            "fleet_smoke",
            workload=f"fleet_soak_{args.requests}req",
            device="jax",
            route="pool",
            reads_per_sec=goodput,
            read_wall_ms={p: lm.get(p) for p in ("p50", "p95", "p99")},
            verdict="pass" if not failures else "fail",
            extra={"errors": soak.get("errors"),
                   "failovers": (soak.get("fleet") or {}).get("failovers")})))
    except Exception as exc:
        failures.append(f"ledger append raised: {exc}")

    if failures:
        print("\n[fleet-smoke] FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("[fleet-smoke] OK: 3-replica fleet survived a mid-soak "
          "SIGKILL with zero lost requests, byte-identical 200s, a "
          "merged lint-clean exposition, slo --fleet rc=0 and a "
          "narrated failover hop", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
