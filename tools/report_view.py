#!/usr/bin/env python
"""Render `--report` JSON run reports as one-screen tables.

Thin checkout-local wrapper over `abpoa-tpu report` (cli.report_main)
for environments without the console script installed:

    python tools/report_view.py run_report.json
    python tools/report_view.py --diff before.json after.json

`--diff` compares two reports field by field (phase walls, reads/s,
CUPS, compiles, faults) with per-field delta and percent change.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from abpoa_tpu.cli import report_main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(report_main(sys.argv[1:]))
