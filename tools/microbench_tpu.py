#!/usr/bin/env python
"""On-chip microbenchmarks that settle the fused-loop design math.

The north star (BASELINE.json): 500x10kb in <=16.4 s wall means the
~17.5M sequential DP-row steps + ~0.83M backtrack steps must average
<= ~0.9 us per step. Until a chip answers what a sequential step actually
costs, every perf lever is speculation (VERDICT r3 #1). Each task prints
one or more `MB {json}` lines for the watcher to collect:

  floor   - us per trivial `lax.while_loop` iteration (sequential dispatch
            floor for the scan path).
  pallas  - us per Pallas grid step / per row on a synthetic R-row chain
            graph (the fused kernel's steady state), at a given UNROLL_K
            and plane width.
  e2e     - reads/s for an end-to-end N x 10kb consensus run on a given
            device backend (the real fused loop incl. graph update).

Run each task in its own process: `pallas` patches UNROLL_K before the
first trace, and jit caches would otherwise pin the first value.
"""
import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)


def emit(**kw):
    print("MB " + json.dumps(kw), flush=True)


def _platform():
    import jax
    return jax.devices()[0].platform


def task_floor(iters: int) -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def run(x):
        def body(st):
            i, v = st
            return i + 1, v + jnp.max(v) * 0  # touch a vector op per step
        def cond(st):
            return st[0] < iters
        return lax.while_loop(cond, body, (jnp.int32(0), x))

    x = jnp.zeros((8, 256), jnp.int32)
    run(x)[1].block_until_ready()
    walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        run(x)[1].block_until_ready()
        walls.append(time.perf_counter() - t0)
    best = min(walls)
    emit(task="floor", platform=_platform(), iters=iters,
         wall_s=round(best, 4), us_per_iter=round(best / iters * 1e6, 3))


def _synthetic_chain(R: int, W: int, w: int, m: int = 5):
    """A chain POA graph (row i's sole predecessor is i-1): the steady-state
    shape of a converged consensus graph, which is what the headline
    workload's DP spends its time on."""
    import numpy as np
    qlen = R - 2
    base = np.random.default_rng(0).integers(0, 4, size=R).astype(np.int32)
    packed = base.copy()
    packed[1] |= 0x100  # row 1 is the src's out row
    pre_idx = np.maximum(np.arange(R, dtype=np.int32) - 1, 0)[:, None]
    pre_cnt = (np.arange(R) >= 1).astype(np.int32)
    out_idx = np.minimum(np.arange(R, dtype=np.int32) + 1, R - 1)[:, None]
    out_cnt = (np.arange(R) <= R - 2).astype(np.int32)
    remain = (R - 1 - np.arange(R)).astype(np.int32)
    inf = -(2 ** 27)
    e1, oe1, e2, oe2 = 2, 6, 1, 26
    end0 = min(qlen, w)
    scalars = np.zeros(16, np.int32)
    scalars[:10] = [qlen, w, 0, inf, e1, oe1, e2, oe2, R, end0]
    row0 = np.full((1, W), inf, np.int32)
    row0[0, :end0 + 1] = -(oe1 + e1 * np.arange(end0 + 1))
    row0[0, 0] = 0
    qp = np.random.default_rng(1).integers(-4, 3, size=(m, qlen + W))
    return scalars, packed, pre_idx, pre_cnt, out_idx, out_cnt, remain, row0, qp.astype(np.int32)


def task_pallas(R: int, W: int, unroll_k: int, plane16: bool,
                interpret: bool = False) -> None:
    import abpoa_tpu.align.pallas_fused as pf
    pf.UNROLL_K = unroll_k  # before the first trace
    import jax.numpy as jnp

    w = 110  # the adaptive-band half width for 10 kb reads (b + f*qlen)
    (scalars, packed, pre_idx, pre_cnt, out_idx, out_cnt, remain,
     row0, qp) = _synthetic_chain(R, W, w)
    dt = jnp.int16 if plane16 else jnp.int32
    row0d = jnp.asarray(row0, dt)

    def run():
        out = pf.pallas_fused_dp(
            jnp.asarray(scalars), jnp.asarray(packed), jnp.asarray(pre_idx),
            jnp.asarray(pre_cnt), jnp.asarray(out_idx), jnp.asarray(out_cnt),
            jnp.asarray(remain), row0d, row0d, row0d, jnp.asarray(qp),
            R=R, W=W, P=1, O=1, plane16=plane16, interpret=interpret)
        out[0].block_until_ready()
        return out

    out = run()
    ok = int(out[7][0])
    walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        walls.append(time.perf_counter() - t0)
    best = min(walls)
    steps = -(-R // unroll_k)
    emit(task="pallas", platform=_platform(), R=R, W=W, K=unroll_k,
         plane16=plane16, ok=ok, wall_s=round(best, 4),
         us_per_grid_step=round(best / steps * 1e6, 3),
         us_per_row=round(best / R * 1e6, 3))


def _ensure_sim(n_reads: int, ref_len: int = 10000) -> str:
    import getpass
    path = f"/tmp/mb_sim{ref_len}_{n_reads}.{getpass.getuser()}.fa"
    try:
        with open(path) as fp:
            if sum(1 for l in fp if l.startswith(">")) == n_reads:
                return path
    except OSError:
        pass
    subprocess.run(
        [sys.executable, os.path.join(HERE, "tests", "make_sim.py"),
         "--ref-len", str(ref_len), "--n-reads", str(n_reads), "--err", "0.1",
         "--seed", "11", "--out", path], check=True)
    return path


def task_e2e(device: str, n_reads: int, ref_len: int) -> None:
    import io
    from abpoa_tpu import obs
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file
    path = _ensure_sim(n_reads, ref_len)
    abpt = Params()
    abpt.device = device
    abpt.finalize()
    t0 = time.perf_counter()
    msa_from_file(Abpoa(), abpt, path, io.StringIO())
    cold = time.perf_counter() - t0
    obs.start_run()  # phase/counter/MFU attribution for the warm run
    t0 = time.perf_counter()
    msa_from_file(Abpoa(), abpt, path, io.StringIO())
    warm = time.perf_counter() - t0
    emit(task="e2e", platform=_platform(), device=device, n_reads=n_reads,
         ref_len=ref_len, cold_wall_s=round(cold, 3),
         warm_wall_s=round(warm, 3),
         reads_per_sec=round(n_reads / warm, 3),
         report=obs.summary(obs.finalize_report()))


def _ensure_sim_seeded(n_reads: int, ref_len: int, seed: int) -> str:
    import getpass
    path = (f"/tmp/mb_sim{ref_len}_{n_reads}_s{seed}."
            f"{getpass.getuser()}.fa")
    try:
        with open(path) as fp:
            if sum(1 for l in fp if l.startswith(">")) == n_reads:
                return path
    except OSError:
        pass
    subprocess.run(
        [sys.executable, os.path.join(HERE, "tests", "make_sim.py"),
         "--ref-len", str(ref_len), "--n-reads", str(n_reads), "--err", "0.1",
         "--seed", str(seed), "--out", path], check=True)
    return path


def task_lockstep(device: str, k: int, n_reads: int, ref_len: int) -> None:
    """Reads/s for K read sets run as ONE lockstep vmapped fused-loop batch
    on a single chip (parallel/runner lockstep path) vs K=1. The per-chip
    throughput lever: each sequential graph-row step carries K sets."""
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, _ingest_records
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.align.fused_loop import progressive_poa_fused_batch
    abpt = Params()
    abpt.device = device
    abpt.finalize()
    sets, wsets = [], []
    for s in range(k):
        p = _ensure_sim_seeded(n_reads, ref_len, 20 + s)
        ab = Abpoa()
        seqs, weights = _ingest_records(ab, abpt, read_fastx(p))
        sets.append(seqs)
        wsets.append(weights)
    from abpoa_tpu import obs
    t0 = time.perf_counter()
    outs = progressive_poa_fused_batch(sets, wsets, abpt)
    cold = time.perf_counter() - t0
    obs.start_run()  # warm-run lockstep counters (K / drain / no-op frac)
    with obs.phase("align_fused"):
        t0 = time.perf_counter()
        outs = progressive_poa_fused_batch(sets, wsets, abpt)
        warm = time.perf_counter() - t0
    ok = sum(o is not None for o in outs)
    emit(task="lockstep", platform=_platform(), device=device, k=k,
         n_reads=n_reads, ref_len=ref_len, sets_ok=ok,
         cold_wall_s=round(cold, 3), warm_wall_s=round(warm, 3),
         reads_per_sec=round(k * n_reads / warm, 3),
         report=obs.summary(obs.finalize_report()))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--task", required=True,
                    choices=["floor", "pallas", "e2e", "lockstep"])
    ap.add_argument("--iters", type=int, default=100_000)
    ap.add_argument("--rows", type=int, default=8192)
    ap.add_argument("--band", type=int, default=384)
    ap.add_argument("--unroll-k", type=int, default=8)
    ap.add_argument("--plane16", action="store_true")
    ap.add_argument("--device", default="pallas")
    ap.add_argument("--interpret", action="store_true",
                    help="CPU shape/semantics validation only")
    ap.add_argument("--n-reads", type=int, default=10)
    ap.add_argument("--ref-len", type=int, default=10000)
    ap.add_argument("--lockstep-k", type=int, default=8,
                    help="sets per lockstep batch (task=lockstep)")
    a = ap.parse_args()
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(HERE, ".jax_cache"))
    if a.interpret:
        # CPU validation runs: the env var loses the platform race against
        # the site hook's device plugin; the config-level pin wins
        import jax
        jax.config.update("jax_platforms", "cpu")
    if a.task == "floor":
        task_floor(a.iters)
    elif a.task == "pallas":
        task_pallas(a.rows, a.band, a.unroll_k, a.plane16, a.interpret)
    elif a.task == "lockstep":
        task_lockstep(a.device, a.lockstep_k, a.n_reads, a.ref_len)
    else:
        task_e2e(a.device, a.n_reads, a.ref_len)


if __name__ == "__main__":
    main()
