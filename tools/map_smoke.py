#!/usr/bin/env python
"""Map-mode chaos soak: the served form of PR 18's acceptance criteria.

Starts `abpoa-tpu serve --map-graph` (device jax pinned to CPU) with the
fault injectors armed, then drives `POST /map` with `tools/loadgen.py
--map` at ~2x the calibrated sustainable throughput. The server must:

- never crash: zero transport errors client-side, no Traceback in its
  stderr, SIGTERM drain rc 0;
- shed overload as 429 + Retry-After, never by queueing without bound;
- keep every 200 byte-identical to the per-read HOST oracle
  (`map_read_host`) — through injected faults, the map group falls back
  to the host route rather than drift;
- leave a lint-clean Prometheus exposition carrying the map families
  (abpoa_map_reads_total et al.) and an archive window on which
  `abpoa-tpu slo` passes — map requests are first-class archive
  citizens, so `abpoa-tpu why <rid>` works on them verbatim.

    python tools/map_smoke.py [--requests N] [--no-inject] [--keep]
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS)
sys.path.insert(0, REPO)
sys.path.insert(0, TOOLS)

REF_LEN = 2000          # the quick-tier warm anchor's shape
GRAPH_READS = 8
READS_PER_BODY = 4


def build_payloads(tmp: str):
    """ONE sim file split into graph reads (-> the GFA the server
    restores) and map-read request bodies — same reference, so the
    mappings are real alignments (make_sim derives the reference from
    the seed; separate files would be unrelated genomes)."""
    from abpoa_tpu.io.fastx import read_fastx
    sim = os.path.join(tmp, "map_smoke.fa")
    subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "make_sim.py"),
         "--ref-len", str(REF_LEN), "--n-reads", str(GRAPH_READS + 16),
         "--err", "0.1", "--seed", "1801", "--out", sim], check=True)
    recs = read_fastx(sim)
    graph_fa = os.path.join(tmp, "map_smoke_graph.fa")
    with open(graph_fa, "w") as fp:
        for r in recs[:GRAPH_READS]:
            fp.write(f">{r.name}\n{r.seq}\n")
    gfa = os.path.join(tmp, "map_smoke_graph.gfa")
    subprocess.run(
        [sys.executable, "-m", "abpoa_tpu.cli", graph_fa,
         "-r", "4", "--device", "numpy", "-o", gfa],
        cwd=REPO, check=True)
    bodies = []
    map_recs = recs[GRAPH_READS:]
    for i in range(0, len(map_recs), READS_PER_BODY):
        chunk = map_recs[i:i + READS_PER_BODY]
        bodies.append(("".join(f">{r.name}\n{r.seq}\n" for r in chunk)
                       .encode(), chunk))
    return gfa, bodies


def oracle_bodies(gfa: str, bodies) -> set:
    """The per-read host-oracle GAF response bytes, one per request body
    — every healthy /map 200 must match one of these byte for byte."""
    import numpy as np
    from abpoa_tpu.io.gaf import gaf_record
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel.map_driver import (load_static_graph,
                                               map_read_host)
    abpt = Params()
    abpt.device = "numpy"
    abpt.finalize()
    ab, static = load_static_graph(gfa, abpt)
    encode = abpt.char_to_code
    out = set()
    for _raw, chunk in bodies:
        lines = []
        for r in chunk:
            q = encode[np.frombuffer(r.seq.encode(), dtype=np.uint8)] \
                .astype(np.uint8)
            res, strand = map_read_host(ab.graph, abpt, q)
            lines.append(gaf_record(r.name, q, res, static.base_by_nid,
                                    strand=strand))
        out.add(("\n".join(lines) + "\n").encode())
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=80,
                    help="soak request count [%(default)s]")
    ap.add_argument("--no-inject", action="store_true",
                    help="skip the fault injectors (pure overload soak)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir for inspection")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("ABPOA_TPU_SKIP_PROBE", "1")
    from serve_smoke import _drain_stderr, read_port, wait_ready

    tmp = tempfile.mkdtemp(prefix="abpoa_map_smoke_")
    metrics_path = os.path.join(tmp, "metrics.prom")
    archive_dir = os.path.join(tmp, "reports")
    failures: list = []

    gfa, bodies = build_payloads(tmp)
    oracles = oracle_bodies(gfa, bodies)
    payloads = [raw for raw, _chunk in bodies]

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        ABPOA_TPU_SKIP_PROBE="1",
        ABPOA_TPU_ARCHIVE="1",
        ABPOA_TPU_ARCHIVE_DIR=archive_dir,
        ABPOA_TPU_SERVE_QUEUE="8",
    )
    if not args.no_inject:
        env["ABPOA_TPU_INJECT"] = "compile_fail:1,oom:1,garbage:1"
    proc = subprocess.Popen(
        [sys.executable, "-m", "abpoa_tpu.cli", "serve", "--port", "0",
         "--device", "jax", "--workers", "2", "--warm", "quick",
         "--map-graph", gfa, "--metrics", metrics_path],
        cwd=REPO, env=env, stderr=subprocess.PIPE, text=True)
    try:
        port = read_port(proc)
        base = f"http://127.0.0.1:{port}"
        stderr_tail: list = []
        threading.Thread(target=_drain_stderr, args=(proc, stderr_tail),
                         daemon=True).start()
        wait_ready(base, proc)

        from loadgen import LoadGen

        # ---- calibrate on the warm server ---------------------------- #
        cal = LoadGen(base, payloads, rate=2.0, n=6, timeout_s=300,
                      endpoint="/map").run()
        p50_s = (cal["latency_ms"]["p50"] or 500.0) / 1e3
        sustainable = 2 / max(1e-3, p50_s)   # 2 workers
        rate = min(max(2.0, 2.0 * sustainable), 100.0)
        print(f"[map-smoke] calibrated p50={p50_s * 1e3:.0f}ms -> "
              f"sustainable ~{sustainable:.1f}/s, soaking at "
              f"{rate:.1f}/s x {args.requests} requests", flush=True)

        # ---- the soak: 2x overload on /map --------------------------- #
        gen = LoadGen(base, payloads, rate=rate, n=args.requests,
                      timeout_s=300, deadline_hdr=60.0, endpoint="/map")
        soak = gen.run()
        print("[map-smoke] soak:", json.dumps(soak), flush=True)

        # ---- settle, then read the server's own story ---------------- #
        settle = LoadGen(base, payloads, rate=2.0, n=6, timeout_s=300,
                         endpoint="/map").run()
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            expo = r.read().decode()
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            health = json.loads(r.read())

        # ---- assertions ---------------------------------------------- #
        if soak["errors"] or settle["errors"]:
            failures.append(f"transport errors: soak={soak['errors']} "
                            f"settle={settle['errors']}")
        if soak["status"].get("500"):
            failures.append(f"{soak['status']['500']} 500s in the soak")
        if settle["ok"] != 6:
            failures.append(f"settle window not fully healthy: "
                            f"{settle['status']}")
        if not (health.get("map_graph") or {}).get("nodes"):
            failures.append(f"healthz carries no map_graph block: "
                            f"{health.get('map_graph')}")
        bad = sum(1 for b in gen.bodies_ok if b not in oracles)
        if bad:
            failures.append(f"{bad}/{len(gen.bodies_ok)} healthy /map "
                            "responses NOT byte-identical to the "
                            "per-read host oracle")

        from abpoa_tpu.obs import metrics as M
        lint = M.lint_exposition(expo)
        if lint:
            failures.append(f"exposition lint: {lint[:3]}")
        samples, _types = M.parse_exposition(expo)
        for fam in ("abpoa_map_reads_total", "abpoa_map_rounds_total",
                    "abpoa_map_lane_occupancy"):
            v = sum(v for (n, _l), v in samples.items() if n == fam)
            if not v:
                failures.append(f"{fam} missing/zero in the exposition")

        # ---- graceful drain ------------------------------------------ #
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=90)
        if rc != 0:
            failures.append(f"SIGTERM drain exited rc={rc}")
        if "Traceback" in "".join(stderr_tail):
            failures.append("server stderr carries a Traceback:\n"
                            + "".join(stderr_tail)[-2000:])

        # ---- the archive answers `abpoa-tpu slo` for /map runs ------- #
        slo = subprocess.run(
            [sys.executable, "-m", "abpoa_tpu.cli", "slo"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=120)
        sys.stdout.write(slo.stdout)
        if slo.returncode != 0:
            failures.append(f"`abpoa-tpu slo` rc={slo.returncode} on the "
                            f"/map archive:\n{slo.stdout}\n{slo.stderr}")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        if args.keep:
            print(f"[map-smoke] work dir kept: {tmp}")

    try:
        from abpoa_tpu.obs import ledger
        lm = soak.get("latency_ms") or {}
        goodput = (round(soak["ok"] / soak["wall_s"], 3)
                   if soak.get("wall_s") else None)
        failures.extend(ledger.append_and_verify(ledger.make_record(
            "map_smoke",
            workload=f"map_soak_{args.requests}req",
            device="jax",
            route="map",
            reads_per_sec=goodput,
            read_wall_ms={p: lm.get(p) for p in ("p50", "p95", "p99")},
            verdict="pass" if not failures else "fail",
            extra={"errors": soak.get("errors"),
                   "shed": soak.get("shed")})))
    except Exception as exc:
        failures.append(f"ledger append raised: {exc}")

    if failures:
        for f in failures:
            print(f"[map-smoke] FAIL: {f}", file=sys.stderr)
        return 1
    print(f"[map-smoke] PASS: {args.requests} /map requests at 2x "
          "overload — zero transport errors, healthy GAF bytes "
          "oracle-identical, map families exposed lint-clean, drain "
          "rc=0, slo ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
