#!/usr/bin/env python
"""Lockstep scheduler-invariant gate (CI: perf-gate job, smoke scale).

The round-14 dispatch rewrite is held to one invariant on EVERY host:
lockstep must never LOSE throughput against running the same sets
serially (round 8 measured the all-device vmapped lockstep at 0.73x —
the regression this gate pins down forever). At smoke scale:

- 4 sets of 20 x 2 kb reads — the bench protocol's read length (the
  crossover where batched DP rounds beat the single-dispatch fused loop
  sits near ~1.5 kb on one core: below it, per-round dispatch overhead
  dominates and the scheduler's serial route is the right call), at the
  quick warm tier's 2.2 kb fused anchor shape (reads rung 32) so a
  warmed cache serves the serial baseline too
- serial baseline: the 4 sets back-to-back through the single-set fused
  path (what a plain run does)
- lockstep: ONE scheduler-routed `--lockstep on` K=4 group (the split
  driver on CPU hosts)
- gate 1: lockstep aggregate reads/s >= 1.0x serial (warm walls)
- gate 2: the TIMED lockstep run reports ZERO compile misses — the
  in-run recompile budget (perf_gate semantics): after the warm pass,
  a run that still compiles mid-flight has cache-key instability or an
  off-ladder shape drift. (CI's preceding `warm --ladder quick` step
  covers the same rungs via the run_dp_chunk anchor at qmax=2200, so
  even the warm pass is persistent-cache loads there.)

Exits 0 on pass, 1 on an invariant violation. --inject-slowdown F (test
hook) divides the measured lockstep throughput by F to prove the flip.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ABPOA_TPU_SKIP_PROBE", "1")

K, N_READS, REF_LEN = 4, 20, 2000


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--inject-slowdown", type=float, default=None,
                    metavar="F", help="divide lockstep reads/s by F "
                    "(test hook proving the gate flips)")
    args = ap.parse_args(argv)

    import jax
    jax.config.update("jax_platforms", "cpu")
    from abpoa_tpu import obs
    from abpoa_tpu.align.fused_loop import progressive_poa_fused
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.parallel import scheduler
    from abpoa_tpu.parallel.lockstep import progressive_poa_split_batch
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, _ingest_records

    abpt = Params()
    abpt.device = "jax"
    abpt.lockstep = "on"
    abpt.finalize()

    sets, wsets = [], []
    for s in range(K):
        p = os.path.join("/tmp", f"lockstep_gate_{N_READS}x{REF_LEN}.{s}.fa")
        if not os.path.isfile(p):
            subprocess.run(
                [sys.executable, os.path.join(REPO, "tests", "make_sim.py"),
                 "--ref-len", str(REF_LEN), "--n-reads", str(N_READS),
                 "--err", "0.1", "--seed", str(800 + s), "--out", p],
                check=True)
        seqs, weights = _ingest_records(Abpoa(), abpt, read_fastx(p))
        sets.append(seqs)
        wsets.append(weights)

    def serial_once():
        for s in range(K):
            progressive_poa_fused(sets[s], wsets[s], abpt)

    def lockstep_once():
        outs = progressive_poa_split_batch(sets, wsets, abpt)
        assert all(o is not None for o in outs), "split set fell back"

    scheduler.reset()
    route = scheduler.plan_route(abpt, K)
    print(f"[lockstep-gate] route: {route.kind}/{route.impl} "
          f"k_cap={route.k_cap}", file=sys.stderr)

    # warm pass (compiles / persistent-cache loads), then timed passes
    serial_once()
    lockstep_once()
    t0 = time.perf_counter()
    serial_once()
    serial_wall = time.perf_counter() - t0
    obs.start_run()
    t0 = time.perf_counter()
    lockstep_once()
    lock_wall = time.perf_counter() - t0
    rep = obs.finalize_report()
    misses = int((rep.get("compiles") or {}).get("misses") or 0)

    reads = K * N_READS
    serial_rps = reads / serial_wall
    lock_rps = reads / lock_wall
    if args.inject_slowdown:
        lock_rps /= args.inject_slowdown
        print(f"[lockstep-gate] injected {args.inject_slowdown}x slowdown "
              "(test hook)", file=sys.stderr)
    ratio = lock_rps / serial_rps
    print(f"[lockstep-gate] serial {serial_wall:.2f}s ({serial_rps:.1f} r/s)"
          f"  lockstep K={K} {lock_wall:.2f}s ({lock_rps:.1f} r/s)"
          f"  ratio {ratio:.2f}x  compile_misses {misses}",
          file=sys.stderr)
    rc = 0
    if ratio < 1.0:
        print(f"[lockstep-gate] FAIL: lockstep K={K} {ratio:.2f}x < 1.0x "
              "serial — the scheduler invariant is violated "
              "(ROUND8_NOTES.md regression)", file=sys.stderr)
        rc = 1
    if misses > 0:
        print(f"[lockstep-gate] FAIL: warm lockstep run compiled in-flight "
              f"({misses} misses) — cache-key instability or a shape "
              "drifting off the run_dp_chunk ladder (compile/ladder.py)",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        print("[lockstep-gate] PASS", file=sys.stderr)
    try:
        from abpoa_tpu.obs import ledger
        ledger.append_record(ledger.make_record(
            "lockstep_gate",
            workload=f"lockstep_k{K}_{N_READS}x{REF_LEN}",
            device=abpt.device,
            route=f"{route.kind}/{route.impl}",
            rung={"K": K},
            reads_per_sec=round(lock_rps, 3),
            compile_misses=misses,
            verdict="pass" if rc == 0 else "fail",
            extra={"serial_reads_per_sec": round(serial_rps, 3),
                   "ratio_vs_serial": round(ratio, 4)}))
    except Exception as exc:  # pragma: no cover - best-effort observability
        print(f"[lockstep-gate] ledger append failed: {exc}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
