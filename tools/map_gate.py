#!/usr/bin/env python
"""Map-mode gate (CI: perf-gate job, beside lockstep_gate/churn_gate).

PR 18's claim is a throughput claim: against ONE static graph whose
lockstep DP tables were built ONCE, streaming reads through the vmapped
pow2-batch kernel must strictly dominate serial per-read alignment — the
same kernel dispatched one read at a time (K=1). The graph half of every
dispatch is identical, so batching amortizes dispatch + graph-plane cost
over K lanes; this gate measures that on every host:

- workload: ONE simulated read set (tests/make_sim.py), split into graph
  reads (build the POA graph via the numpy consensus path) and map reads
  — same reference, so alignments are real, not band-edge garbage
- A: batched map (`map_reads_split`, k_cap=8); B: serial per-read (same
  static tables, k_cap=1), identical read order
- gate 1: batched reads/s AND CUPS strictly exceed serial's
- gate 2: batched GAF output byte-identical to the per-read HOST oracle
  (`map_read_host`, the numpy reference path) — throughput never buys
  drift
- gate 3: zero compile misses inside either timed window (both shapes
  warmed beforehand; in CI `warm --ladder quick` makes the warm pass a
  persistent-cache load)
- gate 4: measured map-lane occupancy (per-round live/capacity, run
  mean) exceeds the consensus churn path's 0.844 — with zero fusion
  barrier every round boundary reboards, so lanes must stay fuller

Exits 0 on pass, 1 on a violation. --inject-slowdown F (test hook)
divides the batched reads/s and CUPS by F to prove the gate flips.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ABPOA_TPU_SKIP_PROBE", "1")

REF_LEN = 2000          # the quick-tier warm anchor's shape (qmax 2200)
GRAPH_READS = 8         # consensus reads that build the static graph
K_CAP = 8
CONSENSUS_OCC = 0.844   # PR 17's measured churn occupancy (PERF.md r17)


def _payload(n_map_reads: int):
    """ONE sim file, split: the graph is built from the FIRST reads and
    the map stream is the REST — same reference (make_sim derives the
    reference from the seed, so separate files would be two unrelated
    genomes and every mapping would be band-edge garbage)."""
    n_total = GRAPH_READS + n_map_reads
    sim = os.path.join("/tmp", f"map_gate_{n_total}x{REF_LEN}.fa")
    if not os.path.isfile(sim):
        subprocess.run(
            [sys.executable, os.path.join(REPO, "tests", "make_sim.py"),
             "--ref-len", str(REF_LEN), "--n-reads", str(n_total),
             "--err", "0.1", "--seed", "1800", "--out", sim], check=True)
    from abpoa_tpu.io.fastx import read_fastx
    recs = read_fastx(sim)
    assert len(recs) == n_total
    graph_fa = os.path.join("/tmp", f"map_gate_graph_{REF_LEN}.fa")
    with open(graph_fa, "w") as fp:
        for r in recs[:GRAPH_READS]:
            fp.write(f">{r.name}\n{r.seq}\n")
    gfa = os.path.join("/tmp", f"map_gate_graph_{REF_LEN}.gfa")
    if not os.path.isfile(gfa):
        subprocess.run(
            [sys.executable, "-m", "abpoa_tpu.cli", graph_fa,
             "-r", "4", "--device", "numpy", "-o", gfa],
            cwd=REPO, check=True)
    return gfa, recs[GRAPH_READS:]


def _gaf(records, queries, outcomes, base_by_nid) -> bytes:
    from abpoa_tpu.io.gaf import gaf_record
    lines = []
    for rec, q, out in zip(records, queries, outcomes):
        res, strand = out[0], out[1]
        lines.append(gaf_record(rec.name, q, res, base_by_nid,
                                strand=strand))
    return ("\n".join(lines) + "\n").encode()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--inject-slowdown", type=float, default=None,
                    metavar="F", help="divide batched reads/s and CUPS "
                    "by F (test hook proving the gate flips)")
    ap.add_argument("--n-reads", type=int, default=32,
                    help="map-stream read count (a multiple of the k_cap "
                         "keeps every round full) [%(default)s]")
    args = ap.parse_args(argv)

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from abpoa_tpu import obs
    from abpoa_tpu.compile.warm import warm_ladder
    from abpoa_tpu.parallel import scheduler
    from abpoa_tpu.parallel.map_driver import (load_static_graph,
                                               map_read_host,
                                               map_reads_split)
    from abpoa_tpu.params import Params

    t0 = time.perf_counter()
    w = warm_ladder("quick")
    print(f"[map-gate] quick-ladder warm: {w['compiled']} compiled, "
          f"{w['persistent_cache_hits']} cache loads, "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    gfa, map_recs = _payload(args.n_reads)
    abpt = Params()
    abpt.device = "jax"
    abpt.finalize()
    ab, static = load_static_graph(gfa, abpt)
    encode = abpt.char_to_code
    queries = [encode[np.frombuffer(r.seq.encode(), dtype=np.uint8)]
               .astype(np.uint8) for r in map_recs]
    cells = sum(static.n_rows * (2 * len(q) + 1) for q in queries)
    print(f"[map-gate] graph {ab.graph.node_n} nodes "
          f"({static.n_rows} DP rows), {len(queries)} map reads, "
          f"{cells / 1e6:.1f}M cells/side", file=sys.stderr)

    # the per-read HOST oracle: every GAF byte both timed sides must match
    oracle = _gaf(map_recs, queries,
                  [map_read_host(ab.graph, abpt, q) for q in queries],
                  static.base_by_nid)

    # warm BOTH timed shapes (K=8 rounds and the K=1 serial signature)
    # before anything is measured — in CI the preceding `warm --ladder
    # quick` step makes these persistent-cache loads, and gate 3 holds
    # the timed windows to zero misses
    map_reads_split(static, queries, abpt, k_cap=K_CAP)
    map_reads_split(static, queries[:1], abpt, k_cap=1)

    obs.start_run()
    scheduler.reset()

    # ---- serial per-read: same kernel, same tables, K=1 -------------- #
    t0 = time.perf_counter()
    serial_out = map_reads_split(static, queries, abpt, k_cap=1)
    wall_serial = time.perf_counter() - t0
    serial_rps = len(queries) / wall_serial
    serial_cups = cells / wall_serial

    # ---- batched: k_cap lanes, zero fusion barrier ------------------- #
    scheduler.reset()
    t0 = time.perf_counter()
    batched_out = map_reads_split(static, queries, abpt, k_cap=K_CAP)
    wall_batched = time.perf_counter() - t0
    batched_rps = len(queries) / wall_batched
    batched_cups = cells / wall_batched
    occ = scheduler.occupancy_mean()

    rep = obs.finalize_report()
    misses = (rep.get("compiles") or {}).get("misses", 0)

    if args.inject_slowdown:
        f = args.inject_slowdown
        batched_rps /= f
        batched_cups /= f
        print(f"[map-gate] injected {f}x batched slowdown (test hook)",
              file=sys.stderr)

    print(f"[map-gate] serial  (K=1): {serial_rps:8.2f} reads/s  "
          f"{serial_cups / 1e6:8.1f}M CUPS  ({wall_serial:.2f}s)",
          file=sys.stderr)
    print(f"[map-gate] batched (K={K_CAP}): {batched_rps:8.2f} reads/s  "
          f"{batched_cups / 1e6:8.1f}M CUPS  ({wall_batched:.2f}s)  "
          f"-> {batched_rps / serial_rps:.2f}x", file=sys.stderr)
    print(f"[map-gate] map-lane occupancy {occ:.3f} "
          f"(consensus churn path: {CONSENSUS_OCC}) | compile misses in "
          f"timed windows: {misses}", file=sys.stderr)

    rc = 0
    if not (batched_rps > serial_rps and batched_cups > serial_cups):
        print("[map-gate] FAIL: batched map does not strictly dominate "
              "serial per-read alignment on reads/s AND CUPS",
              file=sys.stderr)
        rc = 1
    gaf_batched = _gaf(map_recs, queries, batched_out, static.base_by_nid)
    gaf_serial = _gaf(map_recs, queries, serial_out, static.base_by_nid)
    if gaf_batched != oracle:
        print("[map-gate] FAIL: batched GAF is NOT byte-identical to the "
              "per-read host oracle", file=sys.stderr)
        rc = 1
    if gaf_serial != oracle:
        print("[map-gate] FAIL: serial (K=1) GAF is NOT byte-identical "
              "to the per-read host oracle", file=sys.stderr)
        rc = 1
    if misses:
        print(f"[map-gate] FAIL: {misses} compile misses inside the "
              "timed windows — the warm pass did not cover a shape",
              file=sys.stderr)
        rc = 1
    if not occ > CONSENSUS_OCC:
        print(f"[map-gate] FAIL: map-lane occupancy {occ:.3f} does not "
              f"exceed the consensus path's {CONSENSUS_OCC} — the "
              "zero-barrier reboard is not keeping lanes full",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        print("[map-gate] PASS", file=sys.stderr)
    try:
        from abpoa_tpu.obs import ledger
        ledger.append_record(ledger.make_record(
            "map_gate",
            workload=f"map_{args.n_reads}x{REF_LEN}",
            device="jax",
            route="map",
            rung={"K": K_CAP},
            reads_per_sec=round(batched_rps, 3),
            cell_updates_per_sec=round(batched_cups, 1),
            occupancy=round(occ, 4),
            compile_misses=int(misses or 0),
            verdict="pass" if rc == 0 else "fail",
            extra={"serial_reads_per_sec": round(serial_rps, 3),
                   "ratio_vs_serial": round(batched_rps / serial_rps, 4)}))
    except Exception as exc:  # pragma: no cover - best-effort observability
        print(f"[map-gate] ledger append failed: {exc}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
