#!/usr/bin/env python
"""Backfill the perf ledger from the repo's historical bench records.

The repo accumulated its perf history as loose files: five BENCH_rNN.json
driver snapshots, the lockstep/pool scaling benches, BENCH_shard.json,
six MULTICHIP_rNN.json dry-run records, the reference AVX2 walls in
bench_baseline.json, and the perf_gate anchor in tools/perf_baseline.json.
This importer adapts each source shape into ledger schema v1 and appends
them to PERF_LEDGER.jsonl so `abpoa-tpu perf` renders the trajectory from
round 1 and the drift gate has history on day one.

Every record carries an idempotency key derived from its source file (and
row index) and goes through `ledger.append_unique`, so re-running the
importer is a no-op — CI can run it unconditionally before the drift
gate. Timestamps are the source files' mtimes (the only timestamp those
files have). Sources that map onto a live appender's (source, workload)
group — BENCH_shard -> shard_gate, perf_baseline -> perf_gate/map_gate —
use that group's names so fresh gate runs median against the backfilled
history.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def _mtime_ts(path: str) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ",
                         time.gmtime(os.path.getmtime(path)))


def _load(path: str):
    try:
        with open(path) as fp:
            return json.load(fp)
    except (OSError, ValueError) as exc:
        print(f"[ledger-backfill] skip {os.path.basename(path)}: {exc}",
              file=sys.stderr)
        return None


def adapt_bench_rounds(ledger, repo: str) -> list:
    """BENCH_r01..r05.json: the per-round driver snapshots. The headline
    metric string names the workload and device; early rounds report
    sim2k, later ones the sim10k_500 consensus."""
    recs = []
    for i in range(1, 6):
        path = os.path.join(repo, f"BENCH_r{i:02d}.json")
        if not os.path.isfile(path):
            continue
        doc = _load(path)
        parsed = (doc or {}).get("parsed") or {}
        if not parsed.get("value"):
            continue
        metric = parsed.get("metric") or ""
        workload = "sim10k_500" if "10kb" in metric else "sim2k"
        device = ""
        if "device=" in metric:
            device = metric.split("device=")[1].rstrip(")").split(",")[0]
        extra = {"vs_baseline": parsed.get("vs_baseline"),
                 "round": doc.get("n")}
        extra.update(parsed.get("extra") or {})
        extra.pop("per_backend", None)
        recs.append(ledger.make_record(
            "bench", workload=workload, device=device, route="serial",
            reads_per_sec=parsed["value"],
            verdict="pass" if doc.get("rc") == 0 else "fail",
            ts=_mtime_ts(path), key=f"bf:BENCH_r{i:02d}", extra=extra))
    return recs


def adapt_lockstep(ledger, repo: str) -> list:
    """BENCH_lockstep_cpu.json rows: K-scaling of the split driver."""
    path = os.path.join(repo, "BENCH_lockstep_cpu.json")
    doc = _load(path) if os.path.isfile(path) else None
    recs = []
    for j, row in enumerate((doc or {}).get("rows") or []):
        route = row.get("route") or {}
        # each K is its own workload: a scaling SWEEP is not a time
        # series, so different rungs must never median together in the
        # drift gate
        recs.append(ledger.make_record(
            "lockstep_bench",
            workload=f"k{row.get('k')}_{row.get('n_reads')}"
                     f"x{row.get('ref_len')}",
            device="jax",
            route=f"{route.get('kind')}/{route.get('impl')}",
            rung={"K": row.get("k")},
            reads_per_sec=row.get("reads_per_sec"),
            ts=_mtime_ts(path), key=f"bf:BENCH_lockstep_cpu:{j}",
            extra={"warm_wall_s": row.get("warm_wall_s"),
                   "scaling_vs_k1": row.get("scaling_vs_k1")}))
    return recs


def adapt_pool(ledger, repo: str) -> list:
    """BENCH_pool_cpu.json rows: worker-pool scaling on sim2k sets (20
    reads per set — bench_baseline's sim2k definition)."""
    path = os.path.join(repo, "BENCH_pool_cpu.json")
    doc = _load(path) if os.path.isfile(path) else None
    recs = []
    for j, row in enumerate((doc or {}).get("rows") or []):
        sets_per_s = row.get("sets_per_s")
        # per-worker-count workloads, same reasoning as the lockstep sweep
        recs.append(ledger.make_record(
            "pool_bench", workload=f"sim2k_x16_w{row.get('workers')}",
            device=doc.get("device") or "", route="pool",
            rung={"workers": row.get("workers")},
            reads_per_sec=(sets_per_s * 20 if sets_per_s else None),
            verdict="pass" if row.get("passes_rule") else "fail",
            ts=_mtime_ts(path), key=f"bf:BENCH_pool_cpu:{j}",
            extra={"sets_per_s": sets_per_s,
                   "speedup_vs_serial": row.get("speedup_vs_serial")}))
    return recs


def adapt_shard(ledger, repo: str) -> list:
    """BENCH_shard.json: shard_gate --bench's snapshot — imported into
    shard_gate's own (source, workload) group so live gate runs median
    against it."""
    path = os.path.join(repo, "BENCH_shard.json")
    doc = _load(path) if os.path.isfile(path) else None
    if not doc:
        return []
    sh = doc.get("sharded") or {}
    return [ledger.make_record(
        "shard_gate", workload="shard_map_32x2000",
        device=doc.get("platform") or "", route="sharded",
        rung={"mesh": doc.get("mesh"), "K": 64},
        reads_per_sec=sh.get("reads_per_s"),
        cell_updates_per_sec=sh.get("cups"),
        occupancy=doc.get("sharded_lane_occupancy"),
        compile_misses=doc.get("compile_misses_timed"),
        ts=_mtime_ts(path), key="bf:BENCH_shard",
        extra={"ratio_vs_unsharded": doc.get("ratio"),
               "unsharded_reads_per_sec":
                   (doc.get("unsharded") or {}).get("reads_per_s")})]


def adapt_multichip(ledger, repo: str) -> list:
    """MULTICHIP_r01..r06.json: the 8-device dry-run ok/skip records —
    no throughput, but the verdict column is the multi-chip trajectory."""
    recs = []
    for i in range(1, 7):
        path = os.path.join(repo, f"MULTICHIP_r{i:02d}.json")
        if not os.path.isfile(path):
            continue
        doc = _load(path)
        if doc is None:
            continue
        skipped = bool(doc.get("skipped"))
        recs.append(ledger.make_record(
            "multichip", workload="dryrun", device="tpu", route="sharded",
            rung={"mesh": doc.get("n_devices")},
            verdict=(None if skipped
                     else "pass" if doc.get("ok") else "fail"),
            ts=_mtime_ts(path), key=f"bf:MULTICHIP_r{i:02d}",
            extra={"skipped": skipped, "round": i}))
    return recs


def adapt_ref_baseline(ledger, repo: str) -> list:
    """bench_baseline.json: the out-of-tree AVX2 abPOA reference walls —
    the floor every bench record's vs_baseline divides by."""
    path = os.path.join(repo, "bench_baseline.json")
    doc = _load(path) if os.path.isfile(path) else None
    recs = []
    for name, wl in ((doc or {}).get("workloads") or {}).items():
        wall, n = wl.get("avx2_wall_s"), wl.get("n_reads")
        if not wall or not n:
            continue
        recs.append(ledger.make_record(
            "abpoa_ref", workload=name, device="avx2", route="serial",
            reads_per_sec=round(n / wall, 3),
            ts=_mtime_ts(path), key=f"bf:bench_baseline:{name}",
            extra={"avx2_wall_s": wall, "n_reads": n}))
    return recs


def adapt_perf_baseline(ledger, repo: str) -> list:
    """tools/perf_baseline.json: the perf_gate anchor (flat gate schema)
    plus its map-mode block — imported into perf_gate's and map_gate's
    groups."""
    path = os.path.join(repo, "tools", "perf_baseline.json")
    doc = _load(path) if os.path.isfile(path) else None
    if not doc:
        return []
    recs = [ledger.make_record(
        "perf_gate", workload=doc.get("workload") or "sim2k",
        device=doc.get("device") or "", route="serial",
        reads_per_sec=doc.get("reads_per_sec"),
        cell_updates_per_sec=doc.get("cell_updates_per_sec"),
        read_wall_ms=doc.get("read_wall_ms"),
        compile_misses=doc.get("compile_misses"),
        ts=_mtime_ts(path), key="bf:perf_baseline",
        extra={"wall_s": doc.get("wall_s"),
               "n_reads": doc.get("n_reads")})]
    mp = doc.get("map") or {}
    if mp.get("batched_reads_per_sec"):
        recs.append(ledger.make_record(
            "map_gate", workload="map_32x2000", device="jax", route="map",
            rung={"K": 8},
            reads_per_sec=mp.get("batched_reads_per_sec"),
            cell_updates_per_sec=mp.get("batched_cell_updates_per_sec"),
            occupancy=mp.get("lane_occupancy"),
            compile_misses=mp.get("compile_misses"),
            ts=_mtime_ts(path), key="bf:perf_baseline:map",
            extra={"serial_reads_per_sec": mp.get("serial_reads_per_sec"),
                   "ratio_vs_serial": mp.get("batched_over_serial")}))
    return recs


ADAPTERS = (adapt_bench_rounds, adapt_lockstep, adapt_pool, adapt_shard,
            adapt_multichip, adapt_ref_baseline, adapt_perf_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repo", default=REPO,
                    help="repo root holding the BENCH_*/MULTICHIP_* files "
                         "[%(default)s]")
    ap.add_argument("--ledger-dir", default=None,
                    help="append to this ledger dir instead of "
                         "ABPOA_TPU_LEDGER_DIR / the default cache")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the adapted records, append nothing")
    args = ap.parse_args(argv)
    if args.ledger_dir:
        os.environ["ABPOA_TPU_LEDGER_DIR"] = args.ledger_dir

    from abpoa_tpu.obs import ledger
    records = []
    for adapter in ADAPTERS:
        records.extend(adapter(ledger, args.repo))
    # chronological append order so the trajectory reads oldest-first
    records.sort(key=lambda r: (r["ts"], r["key"]))

    if args.dry_run:
        for rec in records:
            print(json.dumps(rec))
        print(f"[ledger-backfill] dry run: {len(records)} records adapted",
              file=sys.stderr)
        return 0

    imported = skipped = failed = 0
    for rec in records:
        bad = ledger.lint_record(rec)
        if bad:
            print(f"[ledger-backfill] BAD record {rec.get('key')}: {bad}",
                  file=sys.stderr)
            failed += 1
            continue
        if ledger.append_unique(rec) is None:
            skipped += 1
        else:
            imported += 1
    print(f"[ledger-backfill] {imported} imported, {skipped} already "
          f"present, {failed} rejected -> {ledger.ledger_path()}",
          file=sys.stderr)
    if imported and imported + skipped < 15:
        print("[ledger-backfill] WARNING: fewer than 15 records — source "
              "files missing?", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
