#!/usr/bin/env python
"""Chaos smoke: run the quick tier with each fault injector armed in turn.

The CI job (`chaos-smoke` in .github/workflows/ci.yml) and any operator can
prove the resilient-dispatch contract end to end: with compile failure,
device OOM, dispatch hang, garbage kernel output, or a poisoned read set
injected (ABPOA_TPU_INJECT=..., abpoa_tpu/resilience/inject.py), a multi-set
`-l` run must

- exit rc=0 (healthy sets complete; the run degrades, never dies),
- emit a consensus for every healthy set,
- carry the corresponding `faults` records — plus the circuit-breaker
  `degraded` block or quarantine counters — in the --report JSON,
- leave a lint-clean Prometheus exposition (--metrics) whose
  `abpoa_breaker_open{backend="jax"}` gauge reads 1 for the scenarios
  that tripped the breaker and whose fault counters match the injector.

Each injector runs in a fresh subprocess (injection spec and breaker state
are process-global). The device backend is `jax` pinned to CPU, so this
needs no accelerator; the injectors fire before any kernel runs, so no XLA
compile is paid for the fail-shaped runs.

The ``worker_kill:1`` / ``worker_sigsegv:2`` scenarios drive the
supervised process pool (ISSUE 13): a 4-set ``--workers 4`` batch whose
injected worker deaths must leave the supervisor alive (rc=0), requeue
the killed job exactly once, quarantine a twice-crashing job as poison,
keep every healthy set byte-identical to the numpy oracle, and export
lint-clean pool metric families.

    python tools/chaos_smoke.py [--keep] [--only KIND]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS)
DATA = os.path.join(REPO, "tests", "data")
sys.path.insert(0, REPO)

# injector -> (expected fault kind, expect breaker-degraded block).
# The non-pool scenarios run --device jax, which auto-pooling excludes
# (resolve_workers), so they take the in-process path as before — but
# every assertion below ALSO holds with ABPOA_TPU_WORKERS forced >1
# (worker report deltas merge into the parent report; verified in the
# PR-13 round).
SCENARIOS = {
    "compile_fail": ("compile_fail", True),
    "oom": ("oom", True),
    "hang": ("hang", True),
    "garbage": ("garbage_output", False),
    "poison_set:1": ("poisoned_set", False),
    # process-pool supervision (ISSUE 13): the injector kills the worker
    # a job landed on; the supervisor must survive, requeue the job
    # exactly once, and quarantine a twice-crashing job as poison —
    # rc=0 with every healthy set's output byte-identical to the numpy
    # oracle
    "worker_kill:1": ("worker_crash", False),
    "worker_sigsegv:2": ("poison_job", False),
}

POOL_SCENARIOS = ("worker_kill", "worker_sigsegv")
POOL_SETS = 4


def pool_oracle_chunks(n: int) -> list:
    """Per-set numpy-oracle output chunks (batch_index changes the
    consensus header, so each set index has its own expected bytes)."""
    import io
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, msa_from_file
    chunks = []
    for i in range(n):
        abpt = Params()
        abpt.device = "numpy"
        abpt.finalize()
        abpt.batch_index = i + 1
        buf = io.StringIO()
        msa_from_file(Abpoa(), abpt, os.path.join(DATA, "test.fa"), buf)
        chunks.append(buf.getvalue())
    return chunks


def run_one(spec: str, tmp: str, verbose: bool) -> list:
    """Run the multi-set workload with `spec` armed; return failure strings."""
    name = spec.split(":")[0]
    pool = name in POOL_SCENARIOS
    n_sets = POOL_SETS if pool else 3
    lst = os.path.join(tmp, f"list_{name}.txt")
    with open(lst, "w") as fp:
        for _ in range(n_sets):
            fp.write(os.path.join(DATA, "test.fa") + "\n")
    out = os.path.join(tmp, f"out_{name}.fa")
    rpt = os.path.join(tmp, f"report_{name}.json")
    mtx = os.path.join(tmp, f"metrics_{name}.prom")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        ABPOA_TPU_SKIP_PROBE="1",
        ABPOA_TPU_INJECT=spec,
        ABPOA_TPU_BREAKER_THRESHOLD="2",
        ABPOA_TPU_ARCHIVE_DIR=os.path.join(tmp, "reports"),
    )
    if name == "hang":
        # short injected hang + tight deadline — ONLY for the hang
        # scenario: a tight deadline on the others would trip on honest
        # first-sight compiles, which is exactly what the default
        # deadline is sized to never do
        env["ABPOA_TPU_INJECT_HANG_S"] = "1.0"
        env["ABPOA_TPU_WATCHDOG_S"] = "0.5"
    argv = [sys.executable, "-m", "abpoa_tpu.cli", "-l", lst,
            "-o", out, "--report", rpt, "--metrics", mtx]
    # the device-dispatch injectors need a device backend; the worker-kill
    # scenarios kill processes, not kernels — the numpy engine keeps the
    # 4-worker spawns jax-import-free and the oracle trivially identical
    argv += (["--device", "numpy", "--workers", str(POOL_SETS)] if pool
             else ["--device", "jax"])
    proc = subprocess.run(argv, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=600)
    failures = []
    expected_kind, expect_degraded = SCENARIOS[spec]
    if proc.returncode != 0:
        return [f"{name}: rc={proc.returncode} (must complete degraded, "
                f"rc=0)\nstderr:\n{proc.stderr[-2000:]}"]
    n_expected = {"poison_set": 2, "worker_sigsegv": POOL_SETS - 1,
                  "worker_kill": POOL_SETS}.get(name, 3)
    with open(out) as fp:
        out_text = fp.read()
    n_cons = out_text.count(">Consensus_sequence")
    if n_cons != n_expected:
        failures.append(f"{name}: {n_cons} consensus sequences, "
                        f"expected {n_expected}")
    with open(rpt) as fp:
        rep = json.load(fp)
    if pool:
        failures.extend(_check_pool_scenario(name, spec, out_text, rep))
    kinds = (rep.get("faults") or {}).get("kinds") or {}
    if not kinds.get(expected_kind):
        failures.append(f"{name}: no '{expected_kind}' faults record "
                        f"(kinds: {kinds})")
    if not rep["counters"].get(f"inject.{name}"):
        failures.append(f"{name}: injector never fired")
    if expect_degraded and not rep.get("degraded"):
        failures.append(f"{name}: breaker never opened (degraded block "
                        "missing)")
    if name == "poison_set" and not rep["counters"].get("quarantine.sets"):
        failures.append(f"{name}: quarantine counter missing")
    # the fleet registry's view of the same run (ISSUE 10): the exposition
    # must lint clean, carry the injector's fault counter, and — for the
    # breaker scenarios — show the breaker-state gauge flipped to open
    from abpoa_tpu.obs import metrics as M
    with open(mtx) as fp:
        text = fp.read()
    lint = M.lint_exposition(text)
    if lint:
        failures.append(f"{name}: exposition lint: {lint[:3]}")
    samples, _types = M.parse_exposition(text)
    if not M.sample_value(samples, "abpoa_faults_total", kind=expected_kind):
        failures.append(f"{name}: abpoa_faults_total"
                        f'{{kind="{expected_kind}"}} missing from metrics')
    if expect_degraded:
        gauge = M.sample_value(samples, "abpoa_breaker_open", backend="jax")
        if gauge != 1:
            failures.append(f"{name}: abpoa_breaker_open{{backend=\"jax\"}} "
                            f"= {gauge}, expected 1 after the breaker "
                            "tripped")
    if pool:
        # the pool families must exist (materialized at 0) and lint clean
        # in the exposition — "zero kills" is a readable 0, not absence
        for fam in ("abpoa_pool_workers", "abpoa_pool_restarts_total",
                    "abpoa_pool_kills_total", "abpoa_pool_requeues_total",
                    "abpoa_pool_poison_jobs_total"):
            if M.sample_value(samples, fam) is None:
                failures.append(f"{name}: {fam} missing from the "
                                "exposition")
        v = M.sample_value(samples, "abpoa_pool_restarts_total")
        if not v:
            failures.append(f"{name}: abpoa_pool_restarts_total = {v}, "
                            "expected >= 1 after the worker death")
    if verbose:
        print(f"[chaos-smoke] {name}: rc=0, {n_cons} consensus, "
              f"faults={kinds}, degraded={sorted(rep.get('degraded') or {})}")
    return failures


def _check_pool_scenario(name: str, spec: str, out_text: str,
                         rep: dict) -> list:
    """The supervised-pool contract: supervisor rc=0 (checked by the
    caller), exactly one requeue per killed job, a twice-crashing job
    quarantined as poison, and every healthy set's output byte-identical
    to the numpy oracle."""
    failures = []
    counters = rep.get("counters") or {}
    shots = int(spec.split(":")[1])
    if counters.get(f"inject.{name}") != shots:
        failures.append(f"{name}: injector fired "
                        f"{counters.get(f'inject.{name}')} times, "
                        f"expected {shots}")
    if counters.get("pool.requeues") != 1:
        failures.append(f"{name}: pool.requeues = "
                        f"{counters.get('pool.requeues')} — the killed "
                        "job must requeue exactly once")
    if not counters.get("pool.restarts"):
        failures.append(f"{name}: no pool.restarts recorded")
    if name == "worker_kill":
        if counters.get("pool.poison_jobs"):
            failures.append(f"{name}: a once-killed job was quarantined "
                            "(the retry should have succeeded)")
    else:  # worker_sigsegv:2 — the bound job crashes twice -> poison
        if counters.get("pool.poison_jobs") != 1:
            failures.append(f"{name}: pool.poison_jobs = "
                            f"{counters.get('pool.poison_jobs')}, "
                            "expected exactly 1")
        if counters.get("quarantine.sets") != 1:
            failures.append(f"{name}: quarantine.sets = "
                            f"{counters.get('quarantine.sets')}, "
                            "expected 1 (rc stayed 0 for the healthy "
                            "sets)")
    # healthy output byte-identical to the numpy oracle: the surviving
    # per-set chunks, in file order (a poisoned set's chunk is absent)
    chunks = pool_oracle_chunks(POOL_SETS)
    candidates = ["".join(chunks)] + [
        "".join(c for j, c in enumerate(chunks) if j != i)
        for i in range(POOL_SETS)]
    if out_text not in candidates:
        failures.append(f"{name}: output is not byte-identical to the "
                        "numpy oracle for any surviving-set combination")
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--only", default=None,
                    help="run a single injector (e.g. 'hang')")
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir for inspection")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)
    specs = [s for s in SCENARIOS
             if args.only is None or s.split(":")[0] == args.only]
    if not specs:
        print(f"[chaos-smoke] unknown injector {args.only!r}",
              file=sys.stderr)
        return 2
    tmp = tempfile.mkdtemp(prefix="abpoa_chaos_")
    failures = []
    for spec in specs:
        failures.extend(run_one(spec, tmp, verbose=not args.quiet))
    if args.keep:
        print(f"[chaos-smoke] work dir kept: {tmp}")
    try:
        from abpoa_tpu.obs import ledger
        failures.extend(ledger.append_and_verify(ledger.make_record(
            "chaos_smoke",
            workload=f"injectors_{len(specs)}",
            device="jax",
            route="pool",
            verdict="pass" if not failures else "fail",
            extra={"injectors": [s.split(":")[0] for s in specs]})))
    except Exception as exc:
        failures.append(f"ledger append raised: {exc}")
    if failures:
        for f in failures:
            print(f"[chaos-smoke] FAIL: {f}", file=sys.stderr)
        return 1
    print(f"[chaos-smoke] PASS: {len(specs)} injectors, every run "
          "completed degraded with the expected fault records")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
