#!/usr/bin/env python
"""Chaos smoke: run the quick tier with each fault injector armed in turn.

The CI job (`chaos-smoke` in .github/workflows/ci.yml) and any operator can
prove the resilient-dispatch contract end to end: with compile failure,
device OOM, dispatch hang, garbage kernel output, or a poisoned read set
injected (ABPOA_TPU_INJECT=..., abpoa_tpu/resilience/inject.py), a multi-set
`-l` run must

- exit rc=0 (healthy sets complete; the run degrades, never dies),
- emit a consensus for every healthy set,
- carry the corresponding `faults` records — plus the circuit-breaker
  `degraded` block or quarantine counters — in the --report JSON,
- leave a lint-clean Prometheus exposition (--metrics) whose
  `abpoa_breaker_open{backend="jax"}` gauge reads 1 for the scenarios
  that tripped the breaker and whose fault counters match the injector.

Each injector runs in a fresh subprocess (injection spec and breaker state
are process-global). The device backend is `jax` pinned to CPU, so this
needs no accelerator; the injectors fire before any kernel runs, so no XLA
compile is paid for the fail-shaped runs.

    python tools/chaos_smoke.py [--keep] [--only KIND]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS)
DATA = os.path.join(REPO, "tests", "data")
sys.path.insert(0, REPO)

# injector -> (expected fault kind, expect breaker-degraded block)
SCENARIOS = {
    "compile_fail": ("compile_fail", True),
    "oom": ("oom", True),
    "hang": ("hang", True),
    "garbage": ("garbage_output", False),
    "poison_set:1": ("poisoned_set", False),
}


def run_one(spec: str, tmp: str, verbose: bool) -> list:
    """Run the multi-set workload with `spec` armed; return failure strings."""
    name = spec.split(":")[0]
    lst = os.path.join(tmp, f"list_{name}.txt")
    with open(lst, "w") as fp:
        for _ in range(3):
            fp.write(os.path.join(DATA, "test.fa") + "\n")
    out = os.path.join(tmp, f"out_{name}.fa")
    rpt = os.path.join(tmp, f"report_{name}.json")
    mtx = os.path.join(tmp, f"metrics_{name}.prom")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        ABPOA_TPU_SKIP_PROBE="1",
        ABPOA_TPU_INJECT=spec,
        ABPOA_TPU_BREAKER_THRESHOLD="2",
        ABPOA_TPU_ARCHIVE_DIR=os.path.join(tmp, "reports"),
    )
    if name == "hang":
        # short injected hang + tight deadline — ONLY for the hang
        # scenario: a tight deadline on the others would trip on honest
        # first-sight compiles, which is exactly what the default
        # deadline is sized to never do
        env["ABPOA_TPU_INJECT_HANG_S"] = "1.0"
        env["ABPOA_TPU_WATCHDOG_S"] = "0.5"
    proc = subprocess.run(
        [sys.executable, "-m", "abpoa_tpu.cli", "-l", lst, "--device", "jax",
         "-o", out, "--report", rpt, "--metrics", mtx],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    failures = []
    expected_kind, expect_degraded = SCENARIOS[spec]
    if proc.returncode != 0:
        return [f"{name}: rc={proc.returncode} (must complete degraded, "
                f"rc=0)\nstderr:\n{proc.stderr[-2000:]}"]
    n_expected = 2 if name == "poison_set" else 3
    with open(out) as fp:
        n_cons = fp.read().count(">Consensus_sequence")
    if n_cons != n_expected:
        failures.append(f"{name}: {n_cons} consensus sequences, "
                        f"expected {n_expected}")
    with open(rpt) as fp:
        rep = json.load(fp)
    kinds = (rep.get("faults") or {}).get("kinds") or {}
    if not kinds.get(expected_kind):
        failures.append(f"{name}: no '{expected_kind}' faults record "
                        f"(kinds: {kinds})")
    if not rep["counters"].get(f"inject.{name}"):
        failures.append(f"{name}: injector never fired")
    if expect_degraded and not rep.get("degraded"):
        failures.append(f"{name}: breaker never opened (degraded block "
                        "missing)")
    if name == "poison_set" and not rep["counters"].get("quarantine.sets"):
        failures.append(f"{name}: quarantine counter missing")
    # the fleet registry's view of the same run (ISSUE 10): the exposition
    # must lint clean, carry the injector's fault counter, and — for the
    # breaker scenarios — show the breaker-state gauge flipped to open
    from abpoa_tpu.obs import metrics as M
    with open(mtx) as fp:
        text = fp.read()
    lint = M.lint_exposition(text)
    if lint:
        failures.append(f"{name}: exposition lint: {lint[:3]}")
    samples, _types = M.parse_exposition(text)
    if not M.sample_value(samples, "abpoa_faults_total", kind=expected_kind):
        failures.append(f"{name}: abpoa_faults_total"
                        f'{{kind="{expected_kind}"}} missing from metrics')
    if expect_degraded:
        gauge = M.sample_value(samples, "abpoa_breaker_open", backend="jax")
        if gauge != 1:
            failures.append(f"{name}: abpoa_breaker_open{{backend=\"jax\"}} "
                            f"= {gauge}, expected 1 after the breaker "
                            "tripped")
    if verbose:
        print(f"[chaos-smoke] {name}: rc=0, {n_cons} consensus, "
              f"faults={kinds}, degraded={sorted(rep.get('degraded') or {})}")
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--only", default=None,
                    help="run a single injector (e.g. 'hang')")
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir for inspection")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)
    specs = [s for s in SCENARIOS
             if args.only is None or s.split(":")[0] == args.only]
    if not specs:
        print(f"[chaos-smoke] unknown injector {args.only!r}",
              file=sys.stderr)
        return 2
    tmp = tempfile.mkdtemp(prefix="abpoa_chaos_")
    failures = []
    for spec in specs:
        failures.extend(run_one(spec, tmp, verbose=not args.quiet))
    if args.keep:
        print(f"[chaos-smoke] work dir kept: {tmp}")
    if failures:
        for f in failures:
            print(f"[chaos-smoke] FAIL: {f}", file=sys.stderr)
        return 1
    print(f"[chaos-smoke] PASS: {len(specs)} injectors, every run "
          "completed degraded with the expected fault records")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
