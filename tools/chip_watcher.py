#!/usr/bin/env python
"""Standing TPU-window watcher (VERDICT r3, task 1).

Round 3 had the tunnel alive for ~70 minutes and recorded zero timings.
This process probes the tunnel continuously; the moment a non-CPU platform
answers it runs the measurement playbook, one step per subprocess with a
hard timeout, committing each artifact the instant it lands so even a
10-minute window leaves on-chip numbers in git.

Playbook order (cheap + decision-critical first):
  1. floor          - us per while_loop iteration (scan-path dispatch floor)
  2. pallas K=8     - us per Pallas grid step, int32 planes
  3. pallas K=1     - the unroll lever, measured not assumed
  4. pallas K=8 i16 - int16 HBM staging cost/benefit
  5. e2e 10x10kb    - jax + pallas reads/s (real fused loop)
  6. sim2k bench    - jax + pallas on the 20x2kb smoke workload
  7. sim10k 30      - mid-size scale check
  8. sim10k 500     - the north-star workload, best device
  9. onchip parity  - committed pytest transcript of every compiled-on-chip
                      test (runs LAST: timings before transcripts)

Artifacts: BENCH_onchip.json (JSONL, one line per measurement),
TPU_PROBE_LOG.jsonl (probe transitions), PERF.md (appended summary).
State in .chip_watcher_state.json lets a second window resume where the
first died.
"""
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable
STATE = os.path.join(HERE, ".chip_watcher_state.json")
ONCHIP = os.path.join(HERE, "BENCH_onchip.json")
PROBE_LOG = os.path.join(HERE, "TPU_PROBE_LOG.jsonl")
MICRO = os.path.join(HERE, "tools", "microbench_tpu.py")

PROBE_CODE = (
    "import jax; d = jax.devices(); "
    "print('PLATFORM', d[0].platform, d[0].device_kind if hasattr(d[0], 'device_kind') else '')"
)


def now():
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


if HERE not in sys.path:
    sys.path.insert(0, HERE)


def log_probe(status, **kw):
    # bounded append (utils/probe.py): the probe log keeps only the newest
    # ABPOA_TPU_PROBE_LOG_MAX entries instead of growing forever on a
    # long-lived host
    try:
        from abpoa_tpu.utils.probe import append_jsonl_bounded
        append_jsonl_bounded(PROBE_LOG, {"ts": now(), "tpu": status, **kw})
    except ImportError:
        with open(PROBE_LOG, "a") as fp:
            fp.write(json.dumps({"ts": now(), "tpu": status, **kw}) + "\n")


def probe():
    """(alive, platform_str). alive only for a real accelerator."""
    try:
        p = subprocess.run([PY, "-c", PROBE_CODE], capture_output=True,
                           text=True, timeout=90)
        for line in p.stdout.splitlines():
            if line.startswith("PLATFORM "):
                parts = line.split(None, 2)
                plat = parts[1]
                return plat not in ("cpu",), line[len("PLATFORM "):]
    except Exception:
        pass
    return False, "unreachable"


def load_state():
    try:
        with open(STATE) as fp:
            return json.load(fp)
    except Exception:
        return {"done": []}


def save_state(st):
    with open(STATE, "w") as fp:
        json.dump(st, fp, indent=1)


def record(step, lines, wall_s):
    with open(ONCHIP, "a") as fp:
        for obj in lines:
            fp.write(json.dumps({"ts": now(), "step": step,
                                 "wall_s": round(wall_s, 1), **obj}) + "\n")
    # `--only <paths>` commits JUST these two artifacts straight from the
    # working tree, leaving anything the user has staged untouched (ADVICE
    # r4: a bare `git commit` here would sweep unrelated staged work into
    # the automated commit). The `git add` first is required: `--only` on a
    # still-untracked pathspec fails outright (BENCH_onchip.json does not
    # exist until the first measurement), and with `--only` the add does NOT
    # leak other staged paths into this commit. .chip_watcher_state.json
    # stays out: it is host-local resume state, gitignored.
    subprocess.run(["git", "-C", HERE, "add",
                    "BENCH_onchip.json", "TPU_PROBE_LOG.jsonl"],
                   capture_output=True)
    subprocess.run(["git", "-C", HERE, "commit", "--no-verify",
                    "--only", "BENCH_onchip.json", "TPU_PROBE_LOG.jsonl",
                    "-m", f"On-chip measurement: {step}"],
                   capture_output=True)


def bench_code(device, workload):
    # the `report` field is the obs-schema summary (per-phase wall, DP-cell
    # totals, cell-updates/s, MFU): every on-chip bench line in
    # BENCH_onchip.json carries the per-phase attribution the VERDICT asks
    # for, not just a single reads/s scalar
    if workload == "sim2k":
        path = os.path.join(HERE, "tests", "data", "sim2k.fa")
        n = 20
        return (f"import sys; sys.path.insert(0, {HERE!r})\n"
                f"import bench, json\n"
                f"w = bench._time_run({device!r}, {path!r}, warm=True)\n"
                f"print('MB ' + json.dumps(dict(task='bench', workload='sim2k',"
                f" device={device!r}, wall_s=round(w,3),"
                f" reads_per_sec=round({n}/w,3),"
                f" report=bench.last_report_summary())))\n")
    n = int(workload.split("_")[1])
    return (f"import sys; sys.path.insert(0, {HERE!r})\n"
            f"import bench, json\n"
            f"p = bench._ensure_sim10k('/tmp/wtch_sim10k_{n}.fa', {n})\n"
            f"w = bench._time_run({device!r}, p, warm=False)\n"
            f"print('MB ' + json.dumps(dict(task='bench', workload={workload!r},"
            f" device={device!r}, wall_s=round(w,3),"
            f" reads_per_sec=round({n}/w,3),"
            f" report=bench.last_report_summary())))\n")


# committed on-chip test transcript (VERDICT r3 missing #7): run every
# compiled-on-chip parity test and record pass/fail + commit hash as an
# artifact, so the repo always says WHEN on-chip parity last held and at
# what commit — not just a probe-log note. An all-skipped run (chip wedged
# again between the watcher probe and pytest's own) prints no MB line and
# exits nonzero, so the step is retried instead of recording a false
# "parity did not hold" artifact.
PARITY_CODE = (
    "import subprocess, sys, json\n"
    "r = subprocess.run([sys.executable, '-m', 'pytest',\n"
    "                    'tests/test_pallas.py', 'tests/test_pallas_fused.py',\n"
    "                    '-k', 'compiled_on_chip', '-q'],\n"
    f"                   capture_output=True, text=True, cwd={HERE!r})\n"
    "tail = ((r.stdout or '').strip().splitlines() or [''])[-1]\n"
    "if 'passed' not in tail and 'failed' not in tail:\n"
    "    sys.exit(1)  # nothing actually ran (all skipped): retry later\n"
    f"commit = subprocess.run(['git', '-C', {HERE!r}, 'rev-parse',\n"
    "                         '--short', 'HEAD'],\n"
    "                        capture_output=True, text=True).stdout.strip()\n"
    "print('MB ' + json.dumps(dict(task='onchip_parity', commit=commit,\n"
    "                              rc=r.returncode,\n"
    "                              ok=(r.returncode == 0 and 'passed' in tail),\n"
    "                              summary=tail[:200])))\n"
)

STEPS = [
    ("floor", [PY, MICRO, "--task", "floor"], 420),
    ("pallas_k8_i32", [PY, MICRO, "--task", "pallas", "--unroll-k", "8"], 900),
    ("pallas_k1_i32", [PY, MICRO, "--task", "pallas", "--unroll-k", "1"], 900),
    ("pallas_k8_i16", [PY, MICRO, "--task", "pallas", "--unroll-k", "8",
                       "--plane16"], 900),
    # lockstep multi-set batching: the per-chip throughput lever (reads/s
    # should scale ~K for any per-step cost); K=1 is the baseline
    ("lockstep_k1_10x10k", [PY, MICRO, "--task", "lockstep", "--device",
                            "jax", "--lockstep-k", "1", "--n-reads", "10"],
     1800),
    ("lockstep_k4_10x10k", [PY, MICRO, "--task", "lockstep", "--device",
                            "jax", "--lockstep-k", "4", "--n-reads", "10"],
     2400),
    ("lockstep_k8_10x10k", [PY, MICRO, "--task", "lockstep", "--device",
                            "jax", "--lockstep-k", "8", "--n-reads", "10"],
     3000),
    ("e2e_jax_10x10k", [PY, MICRO, "--task", "e2e", "--device", "jax",
                        "--n-reads", "10"], 1200),
    ("e2e_pallas_10x10k", [PY, MICRO, "--task", "e2e", "--device", "pallas",
                           "--n-reads", "10"], 1200),
    ("sim2k_jax", [PY, "-c", bench_code("jax", "sim2k")], 600),
    ("sim2k_pallas", [PY, "-c", bench_code("pallas", "sim2k")], 600),
    ("sim10k30_jax", [PY, "-c", bench_code("jax", "sim10k_30")], 1200),
    ("sim10k30_pallas", [PY, "-c", bench_code("pallas", "sim10k_30")], 1200),
    ("sim10k500_pallas", [PY, "-c", bench_code("pallas", "sim10k_500")], 2400),
    ("sim10k500_jax", [PY, "-c", bench_code("jax", "sim10k_500")], 2400),
    # last: the committed parity transcript (9 compiled tests, compile-heavy)
    # must not eat a short window before the decision-critical timings land
    ("onchip_parity", [PY, "-c", PARITY_CODE], 7200),
]


def run_step(name, cmd, timeout):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the tunnel platform win
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(HERE, ".jax_cache"))
    t0 = time.time()
    # own process group so a timeout kills the WHOLE tree: steps spawn
    # grandchildren (pytest -> per-test subprocesses) that would otherwise
    # survive as orphans still holding the chip while the retry contends
    # with them
    import signal
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, env=env, cwd=HERE,
                         start_new_session=True)
    try:
        out, errout = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(p.pid), signal.SIGKILL)
        except Exception:
            pass
        p.wait()
        return None, time.time() - t0, "timeout"
    p = subprocess.CompletedProcess(cmd, p.returncode, out, errout)
    wall = time.time() - t0
    lines = []
    for line in p.stdout.splitlines():
        if line.startswith("MB "):
            try:
                lines.append(json.loads(line[3:]))
            except ValueError:
                pass
    if p.returncode != 0 and not lines:
        return None, wall, (p.stderr or "")[-400:]
    return lines, wall, None


def _is_watcher_pid(pid):
    """True iff `pid` is a live chip_watcher process.

    A bare kill(pid, 0) liveness check is not enough: the pidfile persists
    across reboots/deadline exits, and a recycled PID would make a fresh
    watcher refuse to start for the whole round. /proc cmdline pins the
    identity (this is a Linux-only tool, like the tunnel it watches)."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as fp:
            return b"chip_watcher" in fp.read()
    except OSError:
        return False


def main():
    # VERDICT r4: the old 11 h default could die before a window opened.
    # 72 h outlives any single build round; the driver re-arms each round
    # anyway, and duplicate instances are prevented by the pidfile below.
    deadline = time.time() + float(os.environ.get("WATCHER_HOURS", "72")) * 3600
    pidfile = os.path.join(HERE, ".chip_watcher.pid")
    try:
        with open(pidfile) as fp:
            old = int(fp.read().strip())
        if old != os.getpid() and _is_watcher_pid(old):
            log_probe("watcher-duplicate", pid=os.getpid(), holder=old)
            return
    except (FileNotFoundError, ValueError):
        pass
    with open(pidfile, "w") as fp:
        fp.write(str(os.getpid()))
    st = load_state()
    log_probe("watcher-start", pid=os.getpid())
    was_alive = False
    while time.time() < deadline:
        alive, plat = probe()
        if alive != was_alive:
            log_probe("alive" if alive else "wedged", platform=plat)
            was_alive = alive
        if not alive:
            # each probe burns a cold jax import (~20-40 s CPU on this
            # 1-core host). 90 s (VERDICT r4) keeps a short window from
            # slipping between probes while the duty cycle stays tolerable.
            time.sleep(90)
            continue
        pending = [s for s in STEPS if s[0] not in st["done"]]
        if not pending:
            # everything measured: idle, but RE-READ the state file each
            # lap so an operator's state reset actually triggers a fresh
            # measurement pass (the pidfile blocks arming a second watcher,
            # so this running instance must notice the reset itself)
            time.sleep(300)
            st = load_state()
            continue
        name, cmd, timeout = pending[0]
        log_probe("step-start", step=name)
        lines, wall, err = run_step(name, cmd, timeout)
        if lines:
            record(name, lines, wall)
            st["done"].append(name)
            save_state(st)
            log_probe("step-done", step=name, wall_s=round(wall, 1))
        else:
            log_probe("step-fail", step=name, err=(err or "")[:200],
                      wall_s=round(wall, 1))
            fails = st.setdefault("fails", {})
            fails[name] = fails.get(name, 0) + 1
            if fails[name] >= 3:
                # Never retire silently (VERDICT r4): leave a committed
                # artifact line recording the abandonment so the bench
                # file itself says this step was tried and failed 3x.
                record(name, [{"task": "step-abandoned", "fails": fails[name],
                               "last_err": (err or "")[:200]}], wall)
                st["done"].append(name)  # stop burning the window on it
            save_state(st)
            # re-probe before retrying: the window may have closed mid-step
    log_probe("watcher-exit")
    try:
        os.unlink(pidfile)
    except OSError:
        pass


if __name__ == "__main__":
    main()
