#!/usr/bin/env python
"""Process-pool throughput on the CPU host: the ISSUE-13 measurement.

Runs N copies of the sim2k read set through the `-l` batch path with
``--workers W`` for W in {1, 2, 4, 8} — W=1 is the in-process serial
runner, W>1 the supervised process pool — each in a fresh CLI subprocess
(fair cold-ish comparison; the native engine needs no XLA warm). Judged
against the same 0.7*W rule the round-8 lockstep measurement failed
(BENCH_lockstep_cpu.json): pool speedup at W must reach 0.7*W on a host
with >= W cores, or the shortfall gets analyzed in PERF.md with the
bottleneck named.

Also times one worker spawn (interpreter + package import + ready
handshake) so the per-worker tax is a measured number, not a guess: with
sim2k's per-set wall in the tens of milliseconds, spawn cost dominates
short batches and the JSON says exactly by how much.

    python tools/bench_pool_cpu.py [--sets N] [--out FILE]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS)
SIM2K = os.path.join(REPO, "tests", "data", "sim2k.fa")
sys.path.insert(0, REPO)

WORKERS = (1, 2, 4, 8)
RULE = 0.7


def cpu_count() -> int:
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def measure_spawn_s(device: str) -> float:
    """One worker's spawn tax: process + import + ready handshake."""
    from abpoa_tpu.params import Params
    from abpoa_tpu.parallel import WorkerPool
    abpt = Params()
    abpt.device = device
    abpt.finalize()
    pool = WorkerPool(1, abpt, label="bench-spawn")
    t0 = time.perf_counter()
    pool.start()
    pool.wait_ready(timeout=120)
    dt = time.perf_counter() - t0
    pool.close(graceful=True)
    return dt


def run_config(lst: str, w: int, device: str) -> float:
    env = dict(os.environ, JAX_PLATFORMS="cpu", ABPOA_TPU_SKIP_PROBE="1",
               ABPOA_TPU_ARCHIVE="0", ABPOA_TPU_WORKERS=str(w))
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "abpoa_tpu.cli", "-l", lst,
         "--device", device, "-o", os.devnull],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=1800)
    dt = time.perf_counter() - t0
    if proc.returncode != 0:
        raise RuntimeError(f"W={w} rc={proc.returncode}:\n"
                           f"{proc.stderr[-2000:]}")
    return dt


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sets", type=int, default=16,
                    help="sim2k copies in the batch [%(default)s]")
    ap.add_argument("--device", default="native",
                    help="per-worker engine [%(default)s]")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "BENCH_pool_cpu.json"))
    args = ap.parse_args(argv)

    with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                     delete=False) as fp:
        lst = fp.name
        for _ in range(args.sets):
            fp.write(SIM2K + "\n")
    cpus = cpu_count()
    spawn_s = measure_spawn_s(args.device)
    print(f"[bench-pool] host: {cpus} cpu(s); worker spawn tax "
          f"{spawn_s:.2f}s ({args.device} engine)", flush=True)

    rows = []
    base = None
    for w in WORKERS:
        wall = run_config(lst, w, args.device)
        if base is None:
            base = wall
        speedup = base / wall
        target = RULE * min(w, cpus)
        rows.append({
            "workers": w,
            "wall_s": round(wall, 3),
            "sets_per_s": round(args.sets / wall, 3),
            "speedup_vs_serial": round(speedup, 3),
            "rule_target": round(target, 2),
            "passes_rule": bool(speedup >= target),
        })
        print(f"[bench-pool] W={w}: {wall:.2f}s "
              f"({args.sets / wall:.2f} sets/s, {speedup:.2f}x, "
              f"rule needs >= {target:.2f} on this host)", flush=True)
    os.unlink(lst)

    w4 = next(r for r in rows if r["workers"] == 4)
    result = {
        "bench": "pool_cpu",
        "workload": f"sim2k x {args.sets} sets",
        "device": args.device,
        "host_cpus": cpus,
        "worker_spawn_s": round(spawn_s, 3),
        "rule": f"speedup >= {RULE}*min(W, cpus)",
        "rows": rows,
        "w4_passes": w4["passes_rule"],
        "note": ("pool parallelism needs physical cores: on a host with "
                 "fewer cores than W the rule target is clamped to "
                 "0.7*cpus, and the remaining gap is the measured "
                 "spawn + frame-protocol tax (see PERF.md round 13)"),
    }
    with open(args.out, "w") as fp:
        json.dump(result, fp, indent=2)
        fp.write("\n")
    print(f"[bench-pool] wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
