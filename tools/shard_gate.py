#!/usr/bin/env python
"""Sharded-route gate (CI: shard-smoke job, beside map_gate/churn_gate).

PR 19's claim is a scaling claim with a zero-drift contract: spreading
the split driver's one-dispatch-per-round lane batch over a device mesh
(`parallel/shard.shard_dp_round`) must change NOTHING but the device
axis. On the virtual 8-device CPU mesh (the only mesh every CI host can
build) the gate pins:

- gate 1 (byte identity): sharded consensus output == the unsharded
  split driver == the numpy host oracle, across the linear/affine/convex
  gap-mode grid x lane counts {4, 12} x a churn joiner boarding
  mid-flight; sharded map GAF == unsharded == the per-read host oracle
- gate 2 (dispatch accounting): EXACTLY one sharded dispatch per map
  round (compile-log records vs the map.rounds counter), and every
  dispatch's bucket names the per-shard batch: K == global_Kb / mesh,
  mesh == the gate mesh
- gate 3 (zero misses): no XLA compile inside either timed window —
  `warm --ladder quick` (with ABPOA_TPU_MESH set) plus the untimed
  pre-dispatch covers every rung the timed runs request
- gate 4 (throughput floor): sharded wall >= 0.95x the unsharded wall on
  this 1-core host. Each side runs at ITS route's cap — unsharded at the
  per-chip K, sharded at mesh x per-chip (plan_route's grant), so both
  amortize the same per-lane vmap width. A virtual CPU mesh adds
  partition overhead without adding silicon, so parity-ish is the honest
  bar; on real multi-chip meshes the same harness (--bench) records the
  speedup instead

Exits 0 on pass, 1 on a violation. --inject-slowdown F (test hook)
divides the sharded reads/s by F to prove the gate flips. --bench writes
BENCH_shard.json beside the repo's other BENCH_* records.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

MESH_N = 8
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ABPOA_TPU_SKIP_PROBE", "1")
# the mesh opt-in must land BEFORE the first jax backend init so the
# virtual-device pin can take; it also makes warm_ladder cover the
# sharded rungs (the sharded anchor is a recorded skip without it)
os.environ.setdefault("ABPOA_TPU_MESH", str(MESH_N))

REF_LEN = 2000          # timed shape: the quick-tier anchor (qmax 2200)
GRAPH_READS = 8
K_CAP = 8               # per-chip lane cap (the unsharded driver's K)
# the sharded route prices the whole mesh: global cap = mesh x per-chip
# (scheduler.plan_route's grant, and map_reads_split's own default) —
# running sharded at the PER-CHIP cap would slice each shard down to a
# 1-lane vmap and measure de-batching, not sharding
SHARD_K_CAP = MESH_N * K_CAP
RATIO_FLOOR = 0.95      # sharded wall-clock floor vs unsharded (1-core)

GAP_GRID = (
    ("convex", {}),
    ("affine", {"gap_open2": 0}),
    ("linear", {"gap_open1": 0, "gap_open2": 0}),
)


def _params(device="jax", **kw):
    from abpoa_tpu.params import Params
    abpt = Params()
    abpt.device = device
    for k, v in kw.items():
        setattr(abpt, k, v)
    abpt.finalize()
    return abpt


def _random_sets(rng, sizes, qlen_lo=200, qlen_hi=300, err=0.1):
    import numpy as np
    sets, wsets = [], []
    for n in sizes:
        L = int(rng.integers(qlen_lo, qlen_hi))
        ref = rng.integers(0, 4, L).astype(np.uint8)
        reads = []
        for _ in range(n):
            r = ref.copy()
            posn = rng.integers(0, L, max(1, int(err * L)))
            r[posn] = rng.integers(0, 4, len(posn))
            reads.append(r)
        sets.append(reads)
        wsets.append([np.ones(len(r), dtype=np.int64) for r in reads])
    return sets, wsets


def _consensus_text(abpt, pg, n_reads) -> str:
    import io
    from abpoa_tpu.cons.consensus import generate_consensus
    from abpoa_tpu.io.output import output_fx_consensus
    buf = io.StringIO()
    output_fx_consensus(generate_consensus(pg, abpt, n_reads), abpt, buf)
    return buf.getvalue()


def _host_consensus(gap_kw, seqs, weights) -> str:
    from abpoa_tpu.pipeline import Abpoa, poa
    abpt = _params("numpy", **gap_kw)
    ab = Abpoa()
    for r in seqs:
        ab.append_read(seq="x" * len(r))
    poa(ab, abpt, seqs, weights, 0)
    return _consensus_text(abpt, ab.graph, len(seqs))


class _JoinHook:
    """Boards one scripted joiner and records every retire delivery."""

    def __init__(self, join_round, joiner):
        self.join_round = join_round
        self.joiner = joiner
        self.retired = {}

    def on_round(self, round_i, live_sids):
        if round_i == self.join_round:
            return set(), [self.joiner]
        return set(), []

    def on_retire(self, sid, result, round_i):
        self.retired[sid] = (result, round_i)


def _check_consensus_grid(mesh) -> int:
    """Gate 1, consensus half: gap modes x lane counts x churn join."""
    import numpy as np
    from abpoa_tpu.parallel.lockstep import progressive_poa_split_batch
    rc = 0
    rng = np.random.default_rng(1900)
    for mode, gap_kw in GAP_GRID:
        for n_lanes in (4, 12):
            sizes = [int(rng.integers(3, 7)) for _ in range(n_lanes)]
            sets, wsets = _random_sets(rng, sizes)
            abpt = _params("jax", **gap_kw)
            sharded = progressive_poa_split_batch(sets, wsets, abpt,
                                                  mesh=mesh)
            unsharded = progressive_poa_split_batch(sets, wsets, abpt)
            for i in range(n_lanes):
                if sharded[i] is None or unsharded[i] is None:
                    print(f"[shard-gate] FAIL: {mode} K={n_lanes} set {i} "
                          "fell back", file=sys.stderr)
                    rc = 1
                    continue
                got = _consensus_text(abpt, sharded[i][0], sizes[i])
                flat = _consensus_text(abpt, unsharded[i][0], sizes[i])
                want = _host_consensus(gap_kw, sets[i], wsets[i])
                if got != flat or got != want:
                    print(f"[shard-gate] FAIL: {mode} K={n_lanes} set {i} "
                          "diverged (sharded vs "
                          f"{'unsharded' if got != flat else 'oracle'})",
                          file=sys.stderr)
                    rc = 1
            print(f"[shard-gate] consensus {mode} K={n_lanes}: "
                  f"byte-identical across sharded/unsharded/oracle",
                  file=sys.stderr)
        # churn: a joiner boards round 2 of a divergent sharded group
        sets, wsets = _random_sets(rng, [3, 7])
        j_sets, j_wsets = _random_sets(rng, [4], qlen_hi=260)
        abpt = _params("jax", **gap_kw)
        hook = _JoinHook(2, (100, j_sets[0], j_wsets[0]))
        outs = progressive_poa_split_batch(sets, wsets, abpt, churn=hook,
                                           mesh=mesh)
        ok = all(o is not None for o in outs) and \
            hook.retired.get(100, (None,))[0] is not None
        if ok:
            for i in range(2):
                if _consensus_text(abpt, outs[i][0], len(sets[i])) != \
                        _host_consensus(gap_kw, sets[i], wsets[i]):
                    ok = False
            jres = hook.retired[100][0]
            if _consensus_text(abpt, jres[0], len(j_sets[0])) != \
                    _host_consensus(gap_kw, j_sets[0], j_wsets[0]):
                ok = False
        if not ok:
            print(f"[shard-gate] FAIL: {mode} churn join diverged under "
                  "sharding", file=sys.stderr)
            rc = 1
        else:
            print(f"[shard-gate] consensus {mode} churn join @2: "
                  "byte-identical to the host oracle", file=sys.stderr)
    return rc


def _gaf(names, queries, outcomes, base_by_nid) -> bytes:
    from abpoa_tpu.io.gaf import gaf_record
    lines = [gaf_record(n, q, out[0], base_by_nid, strand=out[1])
             for n, q, out in zip(names, queries, outcomes)]
    return ("\n".join(lines) + "\n").encode()


def _payload(n_map_reads: int):
    """map_gate's split-payload idiom: graph reads and map reads from ONE
    simulated reference."""
    n_total = GRAPH_READS + n_map_reads
    sim = os.path.join("/tmp", f"shard_gate_{n_total}x{REF_LEN}.fa")
    if not os.path.isfile(sim):
        subprocess.run(
            [sys.executable, os.path.join(REPO, "tests", "make_sim.py"),
             "--ref-len", str(REF_LEN), "--n-reads", str(n_total),
             "--err", "0.1", "--seed", "1900", "--out", sim], check=True)
    from abpoa_tpu.io.fastx import read_fastx
    recs = read_fastx(sim)
    graph_fa = os.path.join("/tmp", f"shard_gate_graph_{REF_LEN}.fa")
    with open(graph_fa, "w") as fp:
        for r in recs[:GRAPH_READS]:
            fp.write(f">{r.name}\n{r.seq}\n")
    gfa = os.path.join("/tmp", f"shard_gate_graph_{REF_LEN}.gfa")
    if not os.path.isfile(gfa):
        subprocess.run(
            [sys.executable, "-m", "abpoa_tpu.cli", graph_fa,
             "-r", "4", "--device", "numpy", "-o", gfa],
            cwd=REPO, check=True)
    return gfa, recs[GRAPH_READS:]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--inject-slowdown", type=float, default=None,
                    metavar="F", help="divide sharded reads/s by F (test "
                    "hook proving the gate flips)")
    ap.add_argument("--n-reads", type=int, default=32,
                    help="timed map-stream read count [%(default)s]")
    ap.add_argument("--bench", action="store_true",
                    help="write BENCH_shard.json at the repo root")
    args = ap.parse_args(argv)

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from abpoa_tpu import obs
    from abpoa_tpu.compile.warm import warm_ladder
    from abpoa_tpu.parallel import scheduler
    from abpoa_tpu.parallel.map_driver import (load_static_graph,
                                               map_read_host,
                                               map_reads_split)
    from abpoa_tpu.parallel.shard import discover_mesh

    # build the mesh FIRST: the virtual-device pin must precede backend
    # init, and everything below dispatches against it
    mesh = discover_mesh(MESH_N)
    assert mesh is not None and int(mesh.devices.size) == MESH_N
    print(f"[shard-gate] mesh: {MESH_N} x "
          f"{mesh.devices.flat[0].platform} (axis 'set')", file=sys.stderr)

    t0 = time.perf_counter()
    w = warm_ladder("quick")
    sharded_warm = [r for r in w["records"]
                    if r.get("fn") == "run_dp_chunk[sharded]"
                    or r.get("entry") == "run_dp_chunk[sharded]"]
    print(f"[shard-gate] quick-ladder warm: {w['compiled']} compiled, "
          f"{w['persistent_cache_hits']} cache loads, "
          f"{len(sharded_warm)} sharded rungs, "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
    if not sharded_warm or any("skipped" in r for r in sharded_warm):
        print("[shard-gate] FAIL: warm quick did not cover the sharded "
              "anchor (is ABPOA_TPU_MESH set before backend init?)",
              file=sys.stderr)
        return 1

    rc = _check_consensus_grid(mesh)

    # ---- map half of gate 1 + gate 2 (dispatch accounting) ------------ #
    gfa, map_recs = _payload(args.n_reads)
    abpt = _params("jax")
    ab, static = load_static_graph(gfa, abpt)
    encode = abpt.char_to_code
    queries = [encode[np.frombuffer(r.seq.encode(), dtype=np.uint8)]
               .astype(np.uint8) for r in map_recs]
    names = [r.name for r in map_recs]
    cells = sum(static.n_rows * (2 * len(q) + 1) for q in queries)
    oracle = _gaf(names, queries,
                  [map_read_host(ab.graph, abpt, q) for q in queries],
                  static.base_by_nid)

    # untimed pre-dispatch of BOTH timed shapes (gate 3 holds only the
    # timed windows to zero misses), measured for dispatch accounting
    obs.start_run()
    scheduler.reset()
    sharded_out = map_reads_split(static, queries, abpt,
                                  k_cap=SHARD_K_CAP, mesh=mesh)
    rep = obs.finalize_report()
    rounds = rep["counters"].get("map.rounds", 0)
    recs = [r for r in (rep.get("compiles") or {}).get("records", [])
            if r["fn"] == "run_dp_chunk[sharded]"]
    if len(recs) != rounds or rounds == 0:
        print(f"[shard-gate] FAIL: {len(recs)} sharded dispatches for "
              f"{rounds} map rounds (want exactly one per round)",
              file=sys.stderr)
        rc = 1
    from abpoa_tpu.compile.ladder import k_rung
    global_k = k_rung(min(len(queries), SHARD_K_CAP), MESH_N)
    bad = [r["bucket"] for r in recs
           if r["bucket"]["mesh"] != MESH_N
           or r["bucket"]["K"] * MESH_N != global_k]
    if bad:
        print(f"[shard-gate] FAIL: sharded bucket is not the per-shard "
              f"K/mesh slice: {bad[:3]}", file=sys.stderr)
        rc = 1
    if rc == 0 or not bad:
        print(f"[shard-gate] dispatch accounting: {rounds} rounds, "
              f"{len(recs)} sharded dispatches, per-shard batch "
              f"K={global_k // MESH_N} (= {global_k}/{MESH_N})",
              file=sys.stderr)
    if _gaf(names, queries, sharded_out, static.base_by_nid) != oracle:
        print("[shard-gate] FAIL: sharded map GAF is NOT byte-identical "
              "to the per-read host oracle", file=sys.stderr)
        rc = 1
    map_reads_split(static, queries, abpt, k_cap=K_CAP)  # unsharded warm

    # ---- timed A/B + gates 3 and 4 ------------------------------------ #
    obs.start_run()
    scheduler.reset()
    t0 = time.perf_counter()
    flat_out = map_reads_split(static, queries, abpt, k_cap=K_CAP)
    wall_flat = time.perf_counter() - t0
    scheduler.reset()
    t0 = time.perf_counter()
    sharded_out = map_reads_split(static, queries, abpt,
                                  k_cap=SHARD_K_CAP, mesh=mesh)
    wall_shard = time.perf_counter() - t0
    occ = scheduler.occupancy_mean("sharded")
    rep = obs.finalize_report()
    misses = (rep.get("compiles") or {}).get("misses", 0)

    shard_rps = len(queries) / wall_shard
    flat_rps = len(queries) / wall_flat
    if args.inject_slowdown:
        shard_rps /= args.inject_slowdown
        wall_shard *= args.inject_slowdown
        print(f"[shard-gate] injected {args.inject_slowdown}x sharded "
              "slowdown (test hook)", file=sys.stderr)
    ratio = shard_rps / flat_rps
    print(f"[shard-gate] unsharded (K={K_CAP}):          {flat_rps:8.2f} "
          f"reads/s  {cells / wall_flat / 1e6:8.1f}M CUPS "
          f"({wall_flat:.2f}s)", file=sys.stderr)
    print(f"[shard-gate] sharded   (K={global_k}, mesh={MESH_N}): "
          f"{shard_rps:8.2f} reads/s  "
          f"{cells / wall_shard / 1e6:8.1f}M CUPS ({wall_shard:.2f}s)  "
          f"-> {ratio:.2f}x", file=sys.stderr)
    print(f"[shard-gate] sharded-lane occupancy {occ:.3f} | compile "
          f"misses in timed windows: {misses}", file=sys.stderr)

    if (_gaf(names, queries, sharded_out, static.base_by_nid) != oracle
            or _gaf(names, queries, flat_out,
                    static.base_by_nid) != oracle):
        print("[shard-gate] FAIL: a timed run's GAF drifted from the "
              "host oracle", file=sys.stderr)
        rc = 1
    if misses:
        print(f"[shard-gate] FAIL: {misses} compile misses inside the "
              "timed windows — warm did not cover a sharded rung",
              file=sys.stderr)
        rc = 1
    if ratio < RATIO_FLOOR:
        print(f"[shard-gate] FAIL: sharded throughput {ratio:.2f}x the "
              f"unsharded driver (floor {RATIO_FLOOR}x on the 1-core "
              "virtual mesh)", file=sys.stderr)
        rc = 1

    if args.bench:
        bench = {
            "workload": f"map {args.n_reads} reads x {REF_LEN} bp vs one "
                        f"static graph, per-chip cap {K_CAP} "
                        f"(sharded global cap {SHARD_K_CAP})",
            "mesh": MESH_N,
            "platform": str(mesh.devices.flat[0].platform),
            "sharded": {"wall_s": round(wall_shard, 3),
                        "reads_per_s": round(shard_rps, 2),
                        "cups": round(cells / wall_shard, 0)},
            "unsharded": {"wall_s": round(wall_flat, 3),
                          "reads_per_s": round(flat_rps, 2),
                          "cups": round(cells / wall_flat, 0)},
            "ratio": round(ratio, 3),
            "sharded_lane_occupancy": round(occ, 3),
            "compile_misses_timed": misses,
        }
        out = os.path.join(REPO, "BENCH_shard.json")
        with open(out, "w") as fp:
            json.dump(bench, fp, indent=2)
            fp.write("\n")
        print(f"[shard-gate] wrote {out}", file=sys.stderr)

    try:
        from abpoa_tpu.obs import ledger
        ledger.append_record(ledger.make_record(
            "shard_gate",
            workload=f"shard_map_{args.n_reads}x{REF_LEN}",
            device=str(mesh.devices.flat[0].platform),
            route="sharded",
            rung={"mesh": MESH_N, "K": global_k},
            reads_per_sec=round(shard_rps, 3),
            cell_updates_per_sec=round(cells / wall_shard, 1),
            occupancy=round(occ, 4),
            compile_misses=int(misses or 0),
            verdict="pass" if rc == 0 else "fail",
            extra={"unsharded_reads_per_sec": round(flat_rps, 3),
                   "ratio_vs_unsharded": round(ratio, 4)}))
    except Exception as exc:  # pragma: no cover - best-effort observability
        print(f"[shard-gate] ledger append failed: {exc}", file=sys.stderr)
    print("[shard-gate] " + ("PASS" if rc == 0 else "FAIL"),
          file=sys.stderr)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
