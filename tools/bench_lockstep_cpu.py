#!/usr/bin/env python
"""Lockstep K-scaling on this CPU host (ROADMAP item 2's measurement).

For K in {1, 4, 8}: K independent read sets (n reads x ref-len each,
distinct seeds) run through the SCHEDULER-selected lockstep driver
(parallel/scheduler.py -> the split driver on CPU hosts: host fusion +
batched banded-DP rounds, round 14). K=1 is the serial baseline: the
single-set all-device fused loop, the path a plain run takes. Reports
warm reads/s per K, the scaling ratio vs serial K=1, and the scheduler
route per row.

Decision rules:
- host rule (this bench): K=4 aggregate reads/s >= 1.0x the serial K=1
  path — lockstep must never LOSE throughput vs running the sets
  back-to-back (round 8 measured 0.73x for the all-device vmapped
  lockstep; the round-14 dispatch rewrite is gated on beating 1.0x).
- the 0.7*K rule stays the ON-CHIP gate for the all-device lockstep
  (ROADMAP item 3): scaling >= 0.7*K on a real accelerator mesh keeps
  lockstep the `-l` default there.

Writes BENCH_lockstep_cpu.json (one dict per K + the verdict). Run from
the repo root:

    python tools/bench_lockstep_cpu.py --n-reads 10 --ref-len 2000
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ABPOA_TPU_SKIP_PROBE", "1")


def _sim(path: str, n_reads: int, ref_len: int, seed: int) -> str:
    if not os.path.isfile(path):
        subprocess.run(
            [sys.executable, os.path.join(REPO, "tests", "make_sim.py"),
             "--ref-len", str(ref_len), "--n-reads", str(n_reads),
             "--err", "0.1", "--seed", str(seed), "--out", path], check=True)
    return path


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-reads", type=int, default=10)
    ap.add_argument("--ref-len", type=int, default=2000)
    ap.add_argument("--ks", type=int, nargs="+", default=[1, 4, 8])
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "BENCH_lockstep_cpu.json"))
    args = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")
    from abpoa_tpu import obs
    from abpoa_tpu.align.fused_loop import progressive_poa_fused
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.parallel import scheduler
    from abpoa_tpu.parallel.lockstep import progressive_poa_split_batch
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, _ingest_records

    abpt = Params()
    abpt.device = "jax"
    abpt.lockstep = "on"
    abpt.finalize()

    all_sets, all_wsets = [], []
    for s in range(max(args.ks)):
        p = _sim(os.path.join("/tmp",
                              f"lockstep_{args.n_reads}x{args.ref_len}.{s}.fa"),
                 args.n_reads, args.ref_len, 700 + s)
        seqs, weights = _ingest_records(Abpoa(), abpt, read_fastx(p))
        all_sets.append(seqs)
        all_wsets.append(weights)

    def run_k(k: int):
        """(outs, route_dict): K=1 serial fused baseline, K>1 through the
        scheduler-selected lockstep driver."""
        sets, wsets = all_sets[:k], all_wsets[:k]
        if k == 1:
            pg, _, is_rc = progressive_poa_fused(sets[0], wsets[0], abpt)
            return [(pg, is_rc)], {"kind": "serial", "impl": "fused",
                                   "k_cap": 1}
        scheduler.reset()
        route = scheduler.plan_route(abpt, k)
        impl = route.impl or "split"
        if impl == "split":
            outs = progressive_poa_split_batch(sets, wsets, abpt)
        else:
            from abpoa_tpu.align.fused_loop import (
                progressive_poa_fused_batch)
            outs = progressive_poa_fused_batch(sets, wsets, abpt)
        return outs, {"kind": route.kind, "impl": impl,
                      "k_cap": route.k_cap}

    rows = []
    base_rps = None
    for k in args.ks:
        # cold pass: compiles (persistent-cache assisted) + execution
        t0 = time.perf_counter()
        outs, route = run_k(k)
        cold = time.perf_counter() - t0
        obs.start_run()
        t0 = time.perf_counter()
        outs, route = run_k(k)
        warm = time.perf_counter() - t0
        rep = obs.finalize_report()
        ok = sum(o is not None for o in outs)
        rps = k * args.n_reads / warm
        row = {
            "k": k, "route": route, "sets_ok": ok,
            "n_reads": args.n_reads, "ref_len": args.ref_len,
            "cold_wall_s": round(cold, 3), "warm_wall_s": round(warm, 3),
            "reads_per_sec": round(rps, 3),
            "scaling_vs_k1": None,
            "counters": {c: v for c, v in rep["counters"].items()
                         if c.startswith(("lockstep.", "fused.",
                                          "scheduler."))},
        }
        if base_rps is None:
            base_rps = rps
        else:
            row["scaling_vs_k1"] = round(rps / base_rps, 3)
        rows.append(row)
        print(f"[lockstep-cpu] K={k} route={route['kind']}/{route['impl']}: "
              f"warm {warm:.2f}s, {rps:.2f} reads/s"
              + (f", {row['scaling_vs_k1']}x vs serial"
                 if row["scaling_vs_k1"] else ""), file=sys.stderr)

    verdict = {}
    for row in rows:
        if row["scaling_vs_k1"] is not None:
            verdict[f"k{row['k']}"] = {
                "scaling": row["scaling_vs_k1"],
                "host_rule": 1.0,
                "pass": row["scaling_vs_k1"] >= 1.0,
            }
    out = {
        "bench": "lockstep_k_scaling_cpu",
        "host": "single-core CPU container (scheduler-routed)",
        "decision_rule": ("host: aggregate reads/s >= 1.0x serial K=1; "
                          "0.7*K stays the on-chip gate (ROADMAP item 3)"),
        "rows": rows,
        "verdict": verdict,
    }
    with open(args.out, "w") as fp:
        json.dump(out, fp, indent=2)
        fp.write("\n")
    print(f"[lockstep-cpu] wrote {args.out}: "
          + json.dumps(verdict), file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
