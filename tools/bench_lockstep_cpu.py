#!/usr/bin/env python
"""Lockstep K-scaling on the 8-way virtual CPU mesh (ROADMAP item 3's
no-tunnel half).

For K in {1, 4, 8}: K independent read sets (n reads x ref-len each,
distinct seeds) advance through the fused progressive loop as ONE vmapped
dispatch per chunk, the set axis sharded over min(K, 8) virtual CPU
devices. Reports warm reads/s per K and the scaling ratio vs K=1, judged
against PERF.md's decision rule: warm reads/s scaling >= 0.7*K means
lockstep is the product default for `-l`-shaped workloads; worse means
the vmapped fusion scatter (fused_loop.py) is the suspect and per-chip
process parallelism over sets is the fallback.

Writes BENCH_lockstep_cpu.json (one dict per K + the verdict). Run from
the repo root:

    python tools/bench_lockstep_cpu.py [--n-reads 10] [--ref-len 10000]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ABPOA_TPU_SKIP_PROBE", "1")


def _sim(path: str, n_reads: int, ref_len: int, seed: int) -> str:
    if not os.path.isfile(path):
        subprocess.run(
            [sys.executable, os.path.join(REPO, "tests", "make_sim.py"),
             "--ref-len", str(ref_len), "--n-reads", str(n_reads),
             "--err", "0.1", "--seed", str(seed), "--out", path], check=True)
    return path


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-reads", type=int, default=10)
    ap.add_argument("--ref-len", type=int, default=10000)
    ap.add_argument("--ks", type=int, nargs="+", default=[1, 4, 8])
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "BENCH_lockstep_cpu.json"))
    args = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh
    from abpoa_tpu import obs
    from abpoa_tpu.align.fused_loop import progressive_poa_fused_batch
    from abpoa_tpu.io.fastx import read_fastx
    from abpoa_tpu.params import Params
    from abpoa_tpu.pipeline import Abpoa, _ingest_records

    abpt = Params()
    abpt.device = "jax"
    abpt.finalize()

    all_sets, all_wsets = [], []
    for s in range(max(args.ks)):
        p = _sim(os.path.join("/tmp",
                              f"lockstep_{args.n_reads}x{args.ref_len}.{s}.fa"),
                 args.n_reads, args.ref_len, 700 + s)
        seqs, weights = _ingest_records(Abpoa(), abpt, read_fastx(p))
        all_sets.append(seqs)
        all_wsets.append(weights)

    rows = []
    base_rps = None
    for k in args.ks:
        devs = np.array(jax.devices()[: min(k, 8)])
        mesh = Mesh(devs, ("set",)) if len(devs) > 1 else None
        sets, wsets = all_sets[:k], all_wsets[:k]
        # cold pass: compiles (persistent-cache assisted) + execution
        t0 = time.perf_counter()
        outs = progressive_poa_fused_batch(sets, wsets, abpt, mesh=mesh)
        cold = time.perf_counter() - t0
        obs.start_run()
        t0 = time.perf_counter()
        outs = progressive_poa_fused_batch(sets, wsets, abpt, mesh=mesh)
        warm = time.perf_counter() - t0
        rep = obs.finalize_report()
        ok = sum(o is not None for o in outs)
        rps = k * args.n_reads / warm
        row = {
            "k": k, "mesh_devices": len(devs), "sets_ok": ok,
            "n_reads": args.n_reads, "ref_len": args.ref_len,
            "cold_wall_s": round(cold, 3), "warm_wall_s": round(warm, 3),
            "reads_per_sec": round(rps, 3),
            "scaling_vs_k1": None,
            "counters": {c: v for c, v in rep["counters"].items()
                         if c.startswith(("lockstep.", "fused."))},
        }
        if base_rps is None:
            base_rps = rps
        else:
            row["scaling_vs_k1"] = round(rps / base_rps, 3)
        rows.append(row)
        print(f"[lockstep-cpu] K={k}: warm {warm:.2f}s, {rps:.2f} reads/s"
              + (f", scaling {row['scaling_vs_k1']}x (rule >= {0.7 * k:.1f})"
                 if row["scaling_vs_k1"] else ""), file=sys.stderr)

    verdict = {}
    for row in rows:
        if row["scaling_vs_k1"] is not None:
            verdict[f"k{row['k']}"] = {
                "scaling": row["scaling_vs_k1"],
                "rule": round(0.7 * row["k"], 2),
                "pass": row["scaling_vs_k1"] >= 0.7 * row["k"],
            }
    out = {
        "bench": "lockstep_k_scaling_cpu_mesh",
        "host": "8-way virtual CPU mesh (xla_force_host_platform_device_count)",
        "decision_rule": "warm reads/s scaling >= 0.7*K (PERF.md)",
        "rows": rows,
        "verdict": verdict,
    }
    with open(args.out, "w") as fp:
        json.dump(out, fp, indent=2)
        fp.write("\n")
    print(f"[lockstep-cpu] wrote {args.out}: "
          + json.dumps(verdict), file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
