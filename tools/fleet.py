#!/usr/bin/env python
"""Standalone launcher for the replica fleet: `abpoa-tpu fleet` without
an installed package.

    python tools/fleet.py --replicas 3 --device numpy --warm quick

Everything after the script name is the `abpoa-tpu serve` flag set; the
fleet-level meaning of --host/--port (the ROUTER socket) and --metrics
(the merged fleet exposition textfile) is documented in
abpoa_tpu/serve/fleet.py. SIGHUP rolling-restarts the replicas one at a
time; SIGTERM drains the whole fleet and exits 0.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from abpoa_tpu.serve.fleet import fleet_main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(fleet_main(sys.argv[1:]))
