#!/usr/bin/env python
"""Open-loop load generator for `abpoa-tpu serve`.

Open-loop means arrivals follow a CLOCK, not the server: request i is
launched at t0 + i/rate regardless of whether earlier requests have
answered. That is the only honest way to measure an overloaded service —
a closed loop (wait-then-send) self-throttles to whatever the server can
do and hides the queueing collapse entirely; under an open-loop arrival
rate past capacity, latency and shed rate (429s) show the real knee.
(The coordinated-omission argument; same methodology the chaos soak
uses to claim "survives 2x overload".)

Latency lands in the same `LogSketch` histogram the serve metrics use
(abpoa_tpu/obs/metrics.py), so loadgen percentiles and server-side
percentiles are directly comparable. Output is one JSON summary:

    {"sent": 240, "rate_target": 40.0, "rate_achieved": 39.7,
     "status": {"200": 180, "429": 57, "504": 3},
     "latency_ms": {"p50": 38.2, "p95": 81.0, "p99": 130.5},
     "slowest": [{"ms": 4411.0, "status": "504", "id": "c0ffee123abc"}, ...],
     "errors": 0, ...}

The `slowest` entries carry the server-assigned `X-Abpoa-Request-Id` per
response, so a soak's latency outliers are directly greppable into their
per-request traces / flight dumps: `abpoa-tpu why <id>`.

Usage:
    python tools/loadgen.py --url http://127.0.0.1:8673 \
        --payload tests/data/test.fa --rate 40 --n 240 [--out gen.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from abpoa_tpu.obs.metrics import LogSketch  # noqa: E402


class LoadGen:
    """One open-loop run. Thread-per-in-flight-request (stdlib-only);
    `max_inflight` bounds client-side thread growth when the server falls
    behind — launches past the bound are counted `client_dropped`, which
    is itself a signal the target rate exceeded client capacity."""

    def __init__(self, url: str, payloads: List[bytes], rate: float,
                 n: int, timeout_s: float = 60.0, max_inflight: int = 256,
                 deadline_hdr: Optional[float] = None,
                 fleet: bool = False,
                 endpoint: str = "/align") -> None:
        self.url = url.rstrip("/")
        self.endpoint = endpoint
        self.payloads = payloads
        self.rate = rate
        self.n = n
        self.timeout_s = timeout_s
        self.max_inflight = max_inflight
        self.deadline_hdr = deadline_hdr
        self.fleet = fleet
        self.sketch = LogSketch()
        self.status: dict = {}
        self.errors = 0
        self.client_dropped = 0
        self.bodies_ok: List[bytes] = []
        # (latency_s, status, server-assigned request id) per response —
        # the ids make soak latency outliers directly greppable into
        # their traces/dumps (`abpoa-tpu why <id>`)
        self.requests: List[tuple] = []
        # --fleet attribution from the router's response headers:
        # which replica answered (X-Abpoa-Replica), and how many answers
        # needed a failover hop or a hedge (X-Abpoa-Failovers/-Hedges)
        self.by_replica: dict = {}
        self.failovers = 0
        self.hedges = 0
        self.retried_ok = 0   # 200s whose winning attempt was > 1
        self._lock = threading.Lock()
        self._inflight = 0

    def _one(self, i: int) -> None:
        payload = self.payloads[i % len(self.payloads)]
        headers = {"Content-Type": "text/x-fasta"}
        if self.deadline_hdr is not None:
            headers["X-Abpoa-Deadline-S"] = str(self.deadline_hdr)
        req = urllib.request.Request(self.url + self.endpoint, data=payload,
                                     method="POST", headers=headers)
        t0 = time.perf_counter()
        code, body, rid, hdrs = 0, b"", None, None
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                code, body = r.status, r.read()
                rid = r.headers.get("X-Abpoa-Request-Id")
                hdrs = r.headers
        except urllib.error.HTTPError as e:
            code = e.code
            rid = e.headers.get("X-Abpoa-Request-Id")
            hdrs = e.headers
            e.read()
        except (urllib.error.URLError, OSError, TimeoutError):
            code = 0  # transport error / client timeout
        dt = time.perf_counter() - t0
        with self._lock:
            self.sketch.observe(dt)
            self.status[str(code)] = self.status.get(str(code), 0) + 1
            self.requests.append((dt, str(code), rid))
            if code == 0:
                self.errors += 1
            elif code == 200:
                self.bodies_ok.append(body)
            if self.fleet and hdrs is not None:
                rep = hdrs.get("X-Abpoa-Replica")
                if rep:
                    by = self.by_replica.setdefault(rep, {})
                    by[str(code)] = by.get(str(code), 0) + 1
                self.failovers += int(hdrs.get("X-Abpoa-Failovers") or 0)
                self.hedges += int(hdrs.get("X-Abpoa-Hedges") or 0)
                if code == 200 and int(hdrs.get("X-Abpoa-Attempt") or 1) > 1:
                    self.retried_ok += 1
            self._inflight -= 1

    def run(self) -> dict:
        t0 = time.perf_counter()
        threads = []
        for i in range(self.n):
            target = t0 + i / self.rate
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            with self._lock:
                if self._inflight >= self.max_inflight:
                    self.client_dropped += 1
                    continue
                self._inflight += 1
            t = threading.Thread(target=self._one, args=(i,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(self.timeout_s + 5)
        wall = time.perf_counter() - t0
        return self.summary(wall)

    def summary(self, wall_s: float) -> dict:
        sk = self.sketch

        def ms(q):
            v = sk.quantile(q)
            return round(1e3 * v, 2) if v is not None else None

        launched = self.n - self.client_dropped
        out = {
            "url": self.url,
            "sent": launched,
            "client_dropped": self.client_dropped,
            "rate_target": self.rate,
            "rate_achieved": round(launched / wall_s, 2) if wall_s else None,
            "wall_s": round(wall_s, 2),
            "status": dict(sorted(self.status.items())),
            "ok": self.status.get("200", 0),
            "shed": self.status.get("429", 0),
            "errors": self.errors,
            "latency_ms": {"p50": ms(0.50), "p95": ms(0.95),
                           "p99": ms(0.99),
                           "max": (round(1e3 * sk.max, 2)
                                   if sk.count else None)},
            # slowest responses with their server-assigned request ids:
            # each outlier is one `abpoa-tpu why <id>` away from its
            # trace/flight dump
            "slowest": [{"ms": round(1e3 * dt, 2), "status": code,
                         "id": rid}
                        for dt, code, rid in sorted(
                            self.requests, key=lambda t: -t[0])[:5]],
        }
        if self.fleet:
            # who actually served the traffic, and how often the router
            # had to hop (failover) or race (hedge) to keep the 200s
            # flowing — the chaos soak's "zero failed requests" evidence
            out["fleet"] = {
                "by_replica": {k: dict(sorted(v.items()))
                               for k, v in sorted(self.by_replica.items())},
                "failovers": self.failovers,
                "hedges": self.hedges,
                "retried_ok": self.retried_ok,
            }
        return out


def compare_ab(churn: dict, baseline: dict) -> dict:
    """The churn-gate verdict: p99 AND goodput (200s per wall second) must
    both strictly dominate the static baseline under the same open-loop
    schedule."""

    def goodput(s):
        return round(s["ok"] / s["wall_s"], 3) if s.get("wall_s") else 0.0

    c99 = (churn.get("latency_ms") or {}).get("p99")
    b99 = (baseline.get("latency_ms") or {}).get("p99")
    gc, gb = goodput(churn), goodput(baseline)
    return {
        "p99_ms": {"churn": c99, "baseline": b99},
        "goodput_rps": {"churn": gc, "baseline": gb},
        "dominates": bool(c99 is not None and b99 is not None
                          and c99 < b99 and gc > gb),
    }


def run_ab(url_churn: str, url_baseline: str, payloads: List[bytes],
           rate: float, n: int, timeout_s: float = 60.0,
           deadline_hdr: Optional[float] = None,
           max_inflight: int = 256) -> dict:
    """--churn-baseline mode: the IDENTICAL open-loop schedule against the
    churn server, then the static baseline, plus the comparison verdict."""
    churn = LoadGen(url_churn, payloads, rate, n, timeout_s=timeout_s,
                    max_inflight=max_inflight,
                    deadline_hdr=deadline_hdr).run()
    baseline = LoadGen(url_baseline, payloads, rate, n, timeout_s=timeout_s,
                       max_inflight=max_inflight,
                       deadline_hdr=deadline_hdr).run()
    return {"churn": churn, "baseline": baseline,
            "comparison": compare_ab(churn, baseline)}


def run_sweep(url: str, payloads: List[bytes], rates: List[float],
              n_per_rate: int, timeout_s: float = 60.0,
              fleet: bool = False, endpoint: str = "/align") -> List[dict]:
    """The overload-rejection curve: one open-loop run per arrival rate,
    ascending — PERF.md's served-throughput figure. With `fleet`, each
    pass also attributes responses per replica and counts the router's
    failover/hedge hops at that rate."""
    out = []
    for rate in rates:
        out.append(LoadGen(url, payloads, rate, n_per_rate,
                           timeout_s=timeout_s, fleet=fleet,
                           endpoint=endpoint).run())
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", required=True,
                    help="server base URL (http://host:port)")
    ap.add_argument("--payload", action="append", required=True,
                    metavar="FILE",
                    help="FASTA/FASTQ request body (repeatable; requests "
                         "round-robin over them)")
    ap.add_argument("--rate", type=float, default=10.0,
                    help="open-loop arrival rate, requests/s [%(default)s]")
    ap.add_argument("--n", type=int, default=100,
                    help="total requests [%(default)s]")
    ap.add_argument("--timeout-s", type=float, default=60.0,
                    help="client-side response timeout [%(default)s]")
    ap.add_argument("--deadline-s", type=float, default=None,
                    help="send X-Abpoa-Deadline-S on every request")
    ap.add_argument("--max-inflight", type=int, default=256,
                    help="client concurrency bound [%(default)s]")
    ap.add_argument("--sweep", type=str, default=None, metavar="R1,R2,...",
                    help="run the overload curve: one pass per rate, "
                         "--n requests each; output is a JSON list")
    ap.add_argument("--map", dest="map_mode", action="store_true",
                    help="map mode: POST every payload (FASTQ read "
                         "bodies) to /map against the server's preloaded "
                         "--map-graph instead of /align; responses are "
                         "GAF, one record per read")
    ap.add_argument("--fleet", action="store_true",
                    help="target is an `abpoa-tpu fleet` router: "
                         "attribute every response to its replica "
                         "(X-Abpoa-Replica) and report the router's "
                         "failover/hedge counts in the summary")
    ap.add_argument("--churn-baseline", type=str, default=None,
                    metavar="URL2",
                    help="A/B mode: after the --url run (churn server), "
                         "replay the identical open-loop schedule against "
                         "URL2 (static baseline); output is "
                         "{churn, baseline, comparison}")
    ap.add_argument("--out", type=str, default=None, metavar="FILE",
                    help="write the JSON summary to FILE (stdout always "
                         "gets it too)")
    args = ap.parse_args(argv)
    endpoint = "/map" if args.map_mode else "/align"
    payloads = []
    for p in args.payload:
        with open(p, "rb") as fp:
            payloads.append(fp.read())
    if args.churn_baseline:
        result = run_ab(args.url, args.churn_baseline, payloads,
                        args.rate, args.n, timeout_s=args.timeout_s,
                        deadline_hdr=args.deadline_s,
                        max_inflight=args.max_inflight)
        worst = result["churn"]["errors"] + result["baseline"]["errors"]
    elif args.sweep:
        rates = [float(r) for r in args.sweep.split(",")]
        result = run_sweep(args.url, payloads, rates, args.n,
                           timeout_s=args.timeout_s, fleet=args.fleet,
                           endpoint=endpoint)
        worst = max((r["errors"] for r in result), default=0)
    else:
        result = LoadGen(args.url, payloads, args.rate, args.n,
                         timeout_s=args.timeout_s,
                         max_inflight=args.max_inflight,
                         deadline_hdr=args.deadline_s,
                         fleet=args.fleet, endpoint=endpoint).run()
        worst = result["errors"]
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as fp:
            fp.write(text + "\n")
    # transport errors mean the server dropped connections — the one
    # thing an admission-controlled service must never do
    return 1 if worst else 0


if __name__ == "__main__":
    raise SystemExit(main())
