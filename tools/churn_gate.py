#!/usr/bin/env python
"""Continuous-batching gate (CI: perf-gate job, beside lockstep_gate).

PR 17's claim is operational, not kernel-level: under overload, letting
requests JOIN in-flight lockstep rounds (and finished lanes retire early)
must strictly dominate the static coalesce-at-pickup server. This gate
runs the A/B at smoke scale on every host:

- workload: same-rung sim sets at the bench read length (2 kb — above the
  ~1.5 kb serial-wins crossover, so the serve scheduler actually routes
  lockstep), small read counts so the whole gate stays in CI budget
- calibrate: mean solo service time on a warm server -> capacity
  (workers / mean); the timed runs arrive open-loop at 2x capacity —
  the overload regime where continuous batching earns its keep
- A: churn ON (ABPOA_TPU_SERVE_CHURN=1), B: churn OFF, IDENTICAL
  open-loop schedule (tools/loadgen.py run_ab)
- gate 1: churn p99 < static p99 AND churn goodput > static goodput
  (loadgen's comparison.dominates)
- gate 2: measured lane occupancy (per-round live/capacity, run MEAN —
  the EWMA behind abpoa_lockstep_lane_occupancy is a recency gauge that
  only sees the final group's drain) under churn EXCEEDS the static
  run's — joins must actually backfill drained lanes
- gate 3: zero transport errors on either side (admission answers, never
  drops)

Exits 0 on pass, 1 on a violation. --inject-slowdown F (test hook)
multiplies the churn p99 and divides the churn goodput by F to prove
the gate flips.
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import subprocess
import sys
import time
import urllib.request

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ABPOA_TPU_SKIP_PROBE", "1")
# pin coalescing/joins to the quick-tier warm anchor's K rung (k=4 at the
# 2 kb gate shape, halvings 2 and 1 covered by the repack warmer) — same
# rung discipline as lockstep_gate, so after `warm_ladder("quick")` the
# timed runs never pay an in-band XLA compile
os.environ.setdefault("ABPOA_TPU_LOCKSTEP_K", "4")

# divergent read counts on ONE rung (same ref length): static coalesced
# groups idle the short set's lane while the long set drains — the
# occupancy gap continuous batching exists to close
READ_COUNTS, REF_LEN = (4, 8), 2000
WORKERS = 2


def _loadgen():
    spec = importlib.util.spec_from_file_location(
        "loadgen", os.path.join(HERE, "loadgen.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _payloads():
    out = []
    for s, n_reads in enumerate(READ_COUNTS):
        p = os.path.join("/tmp",
                         f"churn_gate_{n_reads}x{REF_LEN}.{s}.fa")
        if not os.path.isfile(p):
            subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "tests", "make_sim.py"),
                 "--ref-len", str(REF_LEN), "--n-reads", str(n_reads),
                 "--err", "0.1", "--seed", str(1700 + s), "--out", p],
                check=True)
        with open(p, "rb") as fp:
            out.append(fp.read())
    return out


def _post(base: str, body: bytes, timeout: float = 600.0) -> float:
    """One solo request; returns its service wall (calibration probe).
    Carries a generous explicit deadline: the first posts against a fresh
    server pay XLA compiles (or persistent-cache loads) that must not hit
    the 30 s default SLA — only the TIMED loadgen runs measure that."""
    req = urllib.request.Request(
        base + "/align", data=body, method="POST",
        headers={"X-Abpoa-Deadline-S": str(timeout)})
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=timeout) as r:
        r.read()
        assert r.status == 200
    return time.perf_counter() - t0


def _serve(churn_on: bool):
    """In-process server with the churn path toggled; returns (srv, url)."""
    from abpoa_tpu.params import Params
    from abpoa_tpu.serve import AlignServer
    os.environ["ABPOA_TPU_SERVE_CHURN"] = "1" if churn_on else "0"
    abpt = Params()
    abpt.device = "jax"
    abpt.lockstep = "on"
    # loose server default: the warm/calibration posts must survive cold
    # XLA compiles (X-Abpoa-Deadline-S can only TIGHTEN the server cap);
    # the timed loadgen runs send the real 30 s SLA per request
    srv = AlignServer(abpt, port=0, workers=WORKERS, deadline_s=600.0)
    srv.start(warm="off")
    return srv, f"http://{srv.host}:{srv.port}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--inject-slowdown", type=float, default=None,
                    metavar="F", help="multiply churn p99 / divide churn "
                    "goodput by F (test hook proving the gate flips)")
    ap.add_argument("--n", type=int, default=16,
                    help="requests per timed side [%(default)s]")
    args = ap.parse_args(argv)

    import jax
    jax.config.update("jax_platforms", "cpu")
    from abpoa_tpu import obs
    from abpoa_tpu.compile.warm import warm_ladder
    from abpoa_tpu.parallel import scheduler

    loadgen = _loadgen()
    payloads = _payloads()

    # compile (or persistent-cache-load) every rung the timed runs touch
    # BEFORE anything is measured: one cold 20 s+ XLA compile inside the
    # timed window blows every queued request past its 30 s SLA on both
    # sides. In CI the preceding `warm --ladder quick` step makes this a
    # fast cache load.
    t0 = time.perf_counter()
    w = warm_ladder("quick")
    print(f"[churn-gate] quick-ladder warm: {w['compiled']} compiled, "
          f"{w['persistent_cache_hits']} cache loads, "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    # ---- static baseline (churn OFF) --------------------------------- #
    srv_b, url_b = _serve(churn_on=False)
    assert srv_b._lockstep and not srv_b._churn, "static side mis-planned"
    # warm pass: compiles (or persistent-cache loads from CI's preceding
    # `warm --ladder quick` step) land before anything is timed
    for body in payloads:
        _post(url_b, body)
    # calibrate capacity on the warm server: mean solo service
    t_solo = [_post(url_b, payloads[i % len(payloads)]) for i in range(3)]
    mean_s = sum(t_solo) / len(t_solo)
    rate = 2.0 * WORKERS / mean_s   # open-loop 2x calibrated capacity
    print(f"[churn-gate] solo service {mean_s:.2f}s -> "
          f"2x-capacity rate {rate:.2f} req/s, n={args.n}/side",
          file=sys.stderr)
    scheduler.reset()
    obs.start_run()
    base_run = loadgen.LoadGen(url_b, payloads, rate, args.n,
                               timeout_s=300.0, deadline_hdr=30.0).run()
    static_occ = scheduler.occupancy_mean()
    static_noop = scheduler.noop_ewma()
    srv_b.stop()

    # ---- continuous batching (churn ON) ------------------------------ #
    srv_c, url_c = _serve(churn_on=True)
    assert srv_c._churn, "churn side did not plan the split churn route"
    for body in payloads:
        _post(url_c, body)
    scheduler.reset()
    obs.start_run()
    churn_run = loadgen.LoadGen(url_c, payloads, rate, args.n,
                                timeout_s=300.0, deadline_hdr=30.0).run()
    churn_occ = scheduler.occupancy_mean()
    joins = obs.report().counters.get("lockstep.joins", 0)
    retires = obs.report().counters.get("lockstep.early_retires", 0)
    srv_c.stop()

    if args.inject_slowdown:
        f = args.inject_slowdown
        lm = churn_run["latency_ms"]
        lm["p99"] = (lm["p99"] or 0.0) * f
        churn_run["wall_s"] = churn_run["wall_s"] * f
        print(f"[churn-gate] injected {f}x churn slowdown (test hook)",
              file=sys.stderr)

    comp = loadgen.compare_ab(churn_run, base_run)
    print(f"[churn-gate] p99 churn {comp['p99_ms']['churn']} ms vs static "
          f"{comp['p99_ms']['baseline']} ms | goodput churn "
          f"{comp['goodput_rps']['churn']} r/s vs static "
          f"{comp['goodput_rps']['baseline']} r/s", file=sys.stderr)
    print(f"[churn-gate] mean occupancy churn {churn_occ:.3f} vs static "
          f"{static_occ:.3f} (static noop ewma {static_noop:.3f}) | "
          f"joins {joins} early-retires {retires}", file=sys.stderr)

    rc = 0
    if not comp["dominates"]:
        print("[churn-gate] FAIL: churn does not strictly dominate the "
              "static baseline on p99 AND goodput at 2x capacity",
              file=sys.stderr)
        rc = 1
    if not churn_occ > static_occ:
        print(f"[churn-gate] FAIL: measured mean occupancy {churn_occ:.3f} "
              f"does not exceed the static run's {static_occ:.3f} — joins "
              "are not backfilling drained lanes", file=sys.stderr)
        rc = 1
    errors = churn_run["errors"] + base_run["errors"]
    if errors:
        print(f"[churn-gate] FAIL: {errors} transport errors — the "
              "admission boundary dropped connections", file=sys.stderr)
        rc = 1
    if rc == 0:
        print("[churn-gate] PASS", file=sys.stderr)
    try:
        from abpoa_tpu.obs import ledger
        goodput = (comp.get("goodput_rps") or {}).get("churn")
        ledger.append_record(ledger.make_record(
            "churn_gate",
            workload=f"churn_{'x'.join(map(str, READ_COUNTS))}x{REF_LEN}",
            device="jax",
            route="lockstep",
            rung={"K": int(os.environ.get("ABPOA_TPU_LOCKSTEP_K", "4"))},
            reads_per_sec=goodput,
            occupancy=round(churn_occ, 4),
            verdict="pass" if rc == 0 else "fail",
            extra={"p99_ms": (comp.get("p99_ms") or {}).get("churn"),
                   "static_p99_ms": (comp.get("p99_ms") or {}).get("baseline"),
                   "static_occupancy": round(static_occ, 4),
                   "joins": joins, "early_retires": retires}))
    except Exception as exc:  # pragma: no cover - best-effort observability
        print(f"[churn-gate] ledger append failed: {exc}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
