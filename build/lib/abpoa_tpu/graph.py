"""Host-side partial-order alignment DAG.

This is the mutable graph the TPU kernel cannot own: cigar fusion, topological
sort with aligned-group atomicity, band metadata, and read-id bookkeeping all
live here; the DP kernel consumes an immutable CSR snapshot (see
`GraphSnapshot`).

Behavioral parity notes (file:line cite the reference, /root/reference/):
- topo sort keeps mismatch-aligned node groups adjacent (src/abpoa_graph.c:221-266)
- in/out edges are sorted by weight descending with the reference's exact
  (unstable) exchange sort (src/abpoa_graph.c:192-219) — edge *order* feeds the
  DP tie-breaks, so the sort algorithm itself is part of the contract
- max_remain is the longest-heaviest-remaining-path metric driving the adaptive
  band and Z-drop (src/abpoa_graph.c:268-309)
- cigar->graph fusion rules (src/abpoa_graph.c:680-774)
"""
from __future__ import annotations

from collections import deque
from typing import List, Optional

import numpy as np

from . import constants as C
from .params import Params


class Node:
    __slots__ = (
        "node_id", "base", "in_ids", "in_w", "out_ids", "out_w",
        "read_ids", "aligned_ids", "n_read", "n_span_read", "read_weight",
    )

    def __init__(self, node_id: int, base: int = 0):
        self.node_id = node_id
        self.base = base
        self.in_ids: List[int] = []
        self.in_w: List[int] = []
        self.out_ids: List[int] = []
        self.out_w: List[int] = []
        self.read_ids: List[int] = []  # python-int bitset per out edge
        self.aligned_ids: List[int] = []
        self.n_read = 0
        self.n_span_read = 0
        self.read_weight: dict[int, int] = {}  # read_id -> qv weight


class POAGraph:
    def __init__(self) -> None:
        self.nodes: List[Node] = [Node(C.SRC_NODE_ID), Node(C.SINK_NODE_ID)]
        self.is_topological_sorted = False
        self.is_called_cons = False
        self.is_set_msa_rank = False
        self.index_to_node_id: np.ndarray = np.zeros(0, dtype=np.int32)
        self.node_id_to_index: np.ndarray = np.zeros(0, dtype=np.int32)
        self.node_id_to_msa_rank: np.ndarray = np.zeros(0, dtype=np.int32)
        self.node_id_to_max_pos_left: np.ndarray = np.zeros(0, dtype=np.int32)
        self.node_id_to_max_pos_right: np.ndarray = np.zeros(0, dtype=np.int32)
        self.node_id_to_max_remain: np.ndarray = np.zeros(0, dtype=np.int32)

    # ------------------------------------------------------------------ basics
    @property
    def node_n(self) -> int:
        return len(self.nodes)

    def reset(self) -> None:
        """Reuse the container for a fresh read set (src/abpoa_graph.c:783-845)."""
        self.nodes = [Node(C.SRC_NODE_ID), Node(C.SINK_NODE_ID)]
        self.is_topological_sorted = self.is_called_cons = self.is_set_msa_rank = False

    def add_node(self, base: int) -> int:
        node_id = len(self.nodes)
        self.nodes.append(Node(node_id, base))
        return node_id

    def add_edge(self, from_id: int, to_id: int, check_edge: bool, w: int,
                 add_read_id: bool, add_read_weight: bool, read_id: int,
                 tot_read_n: int) -> None:
        """Add or reweight an edge (src/abpoa_graph.c:480-556).

        `n_read` of the source node is incremented unconditionally, matching the
        reference (callers decrement when the edge weight should not count).
        """
        fr, to = self.nodes[from_id], self.nodes[to_id]
        out_edge_i = -1
        if check_edge:
            for i, t in enumerate(to.in_ids):
                if t == from_id:
                    to.in_w[i] += w
                    break
            for i, t in enumerate(fr.out_ids):
                if t == to_id:
                    fr.out_w[i] += w
                    out_edge_i = i
                    break
        if out_edge_i < 0:
            to.in_ids.append(from_id)
            to.in_w.append(w)
            fr.out_ids.append(to_id)
            fr.out_w.append(w)
            fr.read_ids.append(0)
            out_edge_i = len(fr.out_ids) - 1
        if add_read_id:
            fr.read_ids[out_edge_i] |= 1 << read_id
        fr.n_read += 1
        if add_read_weight:
            fr.read_weight[read_id] = w

    def node_base(self, node_id: int) -> int:
        return self.nodes[node_id].base

    def get_aligned_id(self, node_id: int, base: int) -> int:
        for aln_id in self.nodes[node_id].aligned_ids:
            if self.nodes[aln_id].base == base:
                return aln_id
        return -1

    def add_aligned_node(self, node_id: int, aligned_id: int) -> None:
        """Register mutual alignment between `aligned_id` and node_id's group
        (src/abpoa_graph.c:455-463)."""
        node = self.nodes[node_id]
        for ex in node.aligned_ids:
            self.nodes[ex].aligned_ids.append(aligned_id)
            self.nodes[aligned_id].aligned_ids.append(ex)
        node.aligned_ids.append(aligned_id)
        self.nodes[aligned_id].aligned_ids.append(node_id)

    def node_weight(self, node_id: int) -> int:
        return sum(self.nodes[node_id].out_w)

    def incre_path_score(self, node_id: int, in_idx: int) -> int:
        """Log-scaled path score for -G mode (src/abpoa_graph.c:429-437)."""
        import math
        pre_id = self.nodes[node_id].in_ids[in_idx]
        node_w = self.node_weight(pre_id)
        edge_w = self.nodes[node_id].in_w[in_idx]
        if node_w == 0 or edge_w == 0:
            return 0
        # C's round() rounds half away from zero
        v = math.log(edge_w / node_w)
        score = int(math.floor(v + 0.5)) if v >= 0 else int(math.ceil(v - 0.5))
        return max(score, -20)

    # ------------------------------------------------------- topological sort
    def _sort_in_out_ids(self) -> None:
        # exact replication of the reference's exchange sort incl. tie behavior
        for node in self.nodes:
            in_ids, in_w = node.in_ids, node.in_w
            n = len(in_ids)
            for j in range(n - 1):
                for k in range(j + 1, n):
                    if in_w[j] < in_w[k]:
                        in_ids[j], in_ids[k] = in_ids[k], in_ids[j]
                        in_w[j], in_w[k] = in_w[k], in_w[j]
            out_ids, out_w, rids = node.out_ids, node.out_w, node.read_ids
            n = len(out_ids)
            for j in range(n - 1):
                for k in range(j + 1, n):
                    if out_w[j] < out_w[k]:
                        out_ids[j], out_ids[k] = out_ids[k], out_ids[j]
                        out_w[j], out_w[k] = out_w[k], out_w[j]
                        rids[j], rids[k] = rids[k], rids[j]

    def _bfs_set_node_index(self) -> None:
        n = self.node_n
        in_degree = [len(nd.in_ids) for nd in self.nodes]
        if len(self.index_to_node_id) < n:
            self.index_to_node_id = np.zeros(n, dtype=np.int32)
            self.node_id_to_index = np.zeros(n, dtype=np.int32)
        q: deque[int] = deque([C.SRC_NODE_ID])
        index = 0
        while q:
            cur = q.popleft()
            self.index_to_node_id[index] = cur
            self.node_id_to_index[cur] = index
            index += 1
            if cur == C.SINK_NODE_ID:
                return
            for out_id in self.nodes[cur].out_ids:
                in_degree[out_id] -= 1
                if in_degree[out_id] == 0:
                    # aligned-group atomicity: emit the whole mismatch group at once
                    if any(in_degree[a] != 0 for a in self.nodes[out_id].aligned_ids):
                        continue
                    q.append(out_id)
                    for a in self.nodes[out_id].aligned_ids:
                        q.append(a)
        raise RuntimeError("Failed to set node index (cycle in POA graph?)")

    def _bfs_set_node_remain(self) -> None:
        n = self.node_n
        if len(self.node_id_to_max_remain) < n:
            self.node_id_to_max_remain = np.zeros(n, dtype=np.int32)
        remain = self.node_id_to_max_remain
        remain[:n] = 0
        out_degree = [len(nd.out_ids) for nd in self.nodes]
        q: deque[int] = deque([C.SINK_NODE_ID])
        remain[C.SINK_NODE_ID] = -1
        while q:
            cur = q.popleft()
            node = self.nodes[cur]
            if cur != C.SINK_NODE_ID:
                max_w, max_id = -1, -1
                for i, out_id in enumerate(node.out_ids):
                    if node.out_w[i] > max_w:
                        max_w = node.out_w[i]
                        max_id = out_id
                remain[cur] = remain[max_id] + 1
            if cur == C.SRC_NODE_ID:
                return
            for in_id in node.in_ids:
                out_degree[in_id] -= 1
                if out_degree[in_id] == 0:
                    q.append(in_id)
        raise RuntimeError("Failed to set node remain")

    def topological_sort(self, abpt: Params) -> None:
        """(src/abpoa_graph.c:322-357)"""
        n = self.node_n
        if n <= 0:
            return
        if abpt.out_msa or abpt.max_n_cons > 1 or abpt.cons_algrm == C.CONS_MF:
            if len(self.node_id_to_msa_rank) < n:
                self.node_id_to_msa_rank = np.zeros(max(n, 16), dtype=np.int32)
        self._bfs_set_node_index()
        self._sort_in_out_ids()
        if abpt.wb >= 0:
            if len(self.node_id_to_max_pos_left) < n:
                self.node_id_to_max_pos_left = np.zeros(n, dtype=np.int32)
                self.node_id_to_max_pos_right = np.zeros(n, dtype=np.int32)
            self.node_id_to_max_pos_right[:n] = 0
            self.node_id_to_max_pos_left[:n] = n
            self._bfs_set_node_remain()
        elif abpt.zdrop > 0:
            self._bfs_set_node_remain()
        self.is_topological_sorted = True

    # -------------------------------------------------------------- msa rank
    def set_msa_rank(self) -> None:
        """DFS column-rank assignment for RC-MSA (src/abpoa_graph.c:359-419).

        Uses a LIFO stack (kdq_pop in the reference) seeded with the source;
        aligned nodes share the rank of the first group member reached.
        """
        if self.is_set_msa_rank:
            return
        n = self.node_n
        if len(self.node_id_to_msa_rank) < n:
            self.node_id_to_msa_rank = np.zeros(n, dtype=np.int32)
        rank_arr = self.node_id_to_msa_rank
        in_degree = [len(nd.in_ids) for nd in self.nodes]
        stack: List[int] = [C.SRC_NODE_ID]
        rank_arr[C.SRC_NODE_ID] = -1
        msa_rank = 0
        while stack:
            cur = stack.pop()
            if rank_arr[cur] < 0:
                rank_arr[cur] = msa_rank
                for a in self.nodes[cur].aligned_ids:
                    rank_arr[a] = msa_rank
                msa_rank += 1
            if cur == C.SINK_NODE_ID:
                self.is_set_msa_rank = True
                return
            for out_id in self.nodes[cur].out_ids:
                in_degree[out_id] -= 1
                if in_degree[out_id] == 0:
                    if any(in_degree[a] != 0 for a in self.nodes[out_id].aligned_ids):
                        continue
                    stack.append(out_id)
                    rank_arr[out_id] = -1
                    for a in self.nodes[out_id].aligned_ids:
                        stack.append(a)
                        rank_arr[a] = -1
        raise RuntimeError("Error in set_msa_rank")

    def msa_rank_of(self, node_id: int) -> int:
        """Effective MSA column of a node = max rank over its aligned group
        (src/abpoa_output.c:136-142)."""
        rank = int(self.node_id_to_msa_rank[node_id])
        for a in self.nodes[node_id].aligned_ids:
            rank = max(rank, int(self.node_id_to_msa_rank[a]))
        return rank

    # ------------------------------------------------------ subgraph closure
    def _is_full_upstream(self, up_index: int, down_index: int,
                          beg_index: int, end_index: int) -> bool:
        min_index = min(up_index, beg_index)
        max_index = max(down_index, end_index)
        for i in range(up_index + 1, down_index + 1):
            nid = int(self.index_to_node_id[i])
            for in_id in self.nodes[nid].in_ids:
                idx = int(self.node_id_to_index[in_id])
                if idx < min_index or idx > max_index:
                    return False
        return True

    def _upstream_index(self, beg_index: int, end_index: int) -> int:
        while True:
            min_index = beg_index
            for i in range(beg_index, end_index + 1):
                nid = int(self.index_to_node_id[i])
                for in_id in self.nodes[nid].in_ids:
                    min_index = min(min_index, int(self.node_id_to_index[in_id]))
            if self._is_full_upstream(min_index, beg_index, beg_index, end_index):
                return min_index
            end_index = beg_index
            beg_index = min_index

    def _downstream_index(self, beg_index: int, end_index: int) -> int:
        while True:
            max_index = end_index
            for i in range(beg_index, end_index + 1):
                nid = int(self.index_to_node_id[i])
                for out_id in self.nodes[nid].out_ids:
                    max_index = max(max_index, int(self.node_id_to_index[out_id]))
            if self._is_full_upstream(end_index, max_index, beg_index, end_index):
                return max_index
            beg_index = end_index
            end_index = max_index

    def subgraph_nodes(self, abpt: Params, inc_beg: int, inc_end: int) -> tuple[int, int]:
        """Expand [inc_beg, inc_end] to a closed subgraph; returns excluded
        boundary node ids (src/abpoa_graph.c:666-678)."""
        if not self.is_topological_sorted:
            self.topological_sort(abpt)
        beg_index = int(self.node_id_to_index[inc_beg])
        end_index = int(self.node_id_to_index[inc_end])
        exc_beg_index = self._upstream_index(beg_index, end_index)
        exc_end_index = self._downstream_index(beg_index, end_index)
        return int(self.index_to_node_id[exc_beg_index]), int(self.index_to_node_id[exc_end_index])

    # ---------------------------------------------------------------- fusion
    def update_n_span_reads(self, beg_node_id: int, end_node_id: int,
                            inc_both_ends: bool) -> None:
        src_index = int(self.node_id_to_index[beg_node_id])
        sink_index = int(self.node_id_to_index[end_node_id])
        for i in range(src_index + 1, sink_index):
            self.nodes[int(self.index_to_node_id[i])].n_span_read += 1
        if inc_both_ends:
            self.nodes[beg_node_id].n_span_read += 1
            self.nodes[end_node_id].n_span_read += 1

    def add_sequence(self, abpt: Params, seq: np.ndarray, weight: np.ndarray,
                     qpos_to_node_id: Optional[np.ndarray],
                     add_read_id: bool, add_read_weight: bool, read_id: int,
                     tot_read_n: int) -> None:
        """Seed an empty graph with a chain of nodes (src/abpoa_graph.c:573-593)."""
        seq_l = len(seq)
        if seq_l <= 0:
            return
        last_id = C.SRC_NODE_ID
        for i in range(seq_l):
            cur = self.add_node(int(seq[i]))
            if qpos_to_node_id is not None:
                qpos_to_node_id[i] = cur
            self.add_edge(last_id, cur, False, int(weight[i]), add_read_id,
                          add_read_weight, read_id, tot_read_n)
            self.nodes[cur].n_span_read = self.nodes[last_id].n_span_read
            last_id = cur
        self.add_edge(last_id, C.SINK_NODE_ID, False, int(weight[seq_l - 1]),
                      add_read_id, add_read_weight, read_id, tot_read_n)
        self.is_called_cons = self.is_set_msa_rank = self.is_topological_sorted = False
        self.topological_sort(abpt)
        self.update_n_span_reads(C.SRC_NODE_ID, C.SINK_NODE_ID, True)

    def add_subgraph_alignment(self, abpt: Params, beg_node_id: int, end_node_id: int,
                               seq: np.ndarray, weight: Optional[np.ndarray],
                               qpos_to_node_id: Optional[np.ndarray],
                               cigar: list, read_id: int, tot_read_n: int,
                               inc_both_ends: bool) -> None:
        """Fuse one alignment into the graph (src/abpoa_graph.c:689-774).

        cigar is a list of packed 64-bit ops (see cigar.py).
        """
        seq_l = len(seq)
        if weight is None:
            weight = np.ones(seq_l, dtype=np.int64)
        add_read_id = abpt.use_read_ids
        add_read_weight = abpt.use_qv and (abpt.max_n_cons > 1)
        if self.node_n == 2:  # empty graph
            self.add_sequence(abpt, seq, weight, qpos_to_node_id, add_read_id,
                              add_read_weight, read_id, tot_read_n)
            return
        if not cigar:
            return
        query_id = -1
        last_new = False
        last_id = beg_node_id
        for op_pack in cigar:
            op = op_pack & 0xF
            if op == C.CMATCH:
                node_id = (op_pack >> 34) & 0x3FFFFFFF
                query_id += 1
                base = int(seq[query_id])
                add = bool(last_id != beg_node_id or inc_both_ends)
                if self.nodes[node_id].base != base:  # mismatch
                    aligned_id = self.get_aligned_id(node_id, base)
                    if aligned_id != -1:
                        self.add_edge(last_id, aligned_id, not last_new, int(weight[query_id]),
                                      add_read_id and add, add_read_weight, read_id, tot_read_n)
                        if not add:
                            self.nodes[last_id].n_read -= 1
                        last_id, last_new = aligned_id, False
                    else:
                        new_id = self.add_node(base)
                        self.add_edge(last_id, new_id, False, int(weight[query_id]),
                                      add_read_id and add, add_read_weight, read_id, tot_read_n)
                        self.nodes[new_id].n_span_read = self.nodes[last_id].n_span_read
                        if not add:
                            self.nodes[last_id].n_read -= 1
                        last_id, last_new = new_id, True
                        self.add_aligned_node(node_id, new_id)
                else:  # match
                    self.add_edge(last_id, node_id, not last_new, int(weight[query_id]),
                                  add_read_id and add, add_read_weight, read_id, tot_read_n)
                    if not add:
                        self.nodes[last_id].n_read -= 1
                    last_id, last_new = node_id, False
                if qpos_to_node_id is not None:
                    qpos_to_node_id[query_id] = last_id
            elif op in (C.CINS, C.CSOFT_CLIP, C.CHARD_CLIP):
                length = (op_pack >> 4) & 0x3FFFFFFF
                query_id += length
                for j in range(length - 1, -1, -1):
                    new_id = self.add_node(int(seq[query_id - j]))
                    add = bool(last_id != beg_node_id or inc_both_ends)
                    self.add_edge(last_id, new_id, False, int(weight[query_id - j]),
                                  add_read_id and add, add_read_weight, read_id, tot_read_n)
                    self.nodes[new_id].n_span_read = self.nodes[last_id].n_span_read
                    if not add:
                        self.nodes[last_id].n_read -= 1
                    last_id, last_new = new_id, True
                    if qpos_to_node_id is not None:
                        qpos_to_node_id[query_id - j] = last_id
            elif op == C.CDEL:
                continue
        self.add_edge(last_id, end_node_id, not last_new, int(weight[seq_l - 1]),
                      add_read_id, add_read_weight, read_id, tot_read_n)
        self.is_called_cons = self.is_set_msa_rank = self.is_topological_sorted = False
        self.topological_sort(abpt)
        self.update_n_span_reads(beg_node_id, end_node_id, inc_both_ends)

    def add_alignment(self, abpt: Params, seq: np.ndarray, weight: Optional[np.ndarray],
                      qpos_to_node_id: Optional[np.ndarray], cigar: list,
                      read_id: int, tot_read_n: int, inc_both_ends: bool) -> None:
        self.add_subgraph_alignment(abpt, C.SRC_NODE_ID, C.SINK_NODE_ID, seq, weight,
                                    qpos_to_node_id, cigar, read_id, tot_read_n,
                                    inc_both_ends)
