"""abpoa_tpu: TPU-native adaptive banded Partial Order Alignment.

A ground-up reimplementation of the capabilities of abPOA (yangao07/abPOA)
with the banded sequence-to-graph DP lowered to JAX/Pallas kernels on TPU,
and the mutable POA graph, backtrack, consensus, and I/O on host.
"""
__version__ = "0.1.0"

from . import constants
from .params import Params
from .graph import POAGraph
from .pipeline import Abpoa, msa, msa_from_file
from .align import align_sequence_to_graph, align_sequence_to_subgraph, AlignResult

__all__ = [
    "constants", "Params", "POAGraph", "Abpoa", "msa", "msa_from_file",
    "align_sequence_to_graph", "align_sequence_to_subgraph", "AlignResult",
]
