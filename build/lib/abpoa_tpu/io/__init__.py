from .fastx import read_fastx, SeqRecord
