"""Incremental-MSA graph restore: rebuild the POA DAG from an abPOA GFA or an
MSA FASTA (with '-' gaps) so new reads can be aligned onto it.

This is the framework's checkpoint/resume path (reference:
/root/reference/src/abpoa_seq.c:385-673; CLI -i).
"""
from __future__ import annotations

from typing import Dict, List

from .. import constants as C
from ..params import Params
from .fastx import _open


def _parse_gfa(ab, abpt: Params, lines: List[str]) -> None:
    g = ab.graph
    segs: Dict[str, str] = {}
    seg_in_id: Dict[str, int] = {}
    seg_out_id: Dict[str, int] = {}
    add_read_id = abpt.use_read_ids
    encode = abpt.char_to_code
    p_i = -1
    for line in lines:
        if line.startswith("S\t"):
            toks = line.split("\t")
            if len(toks) < 3:
                raise ValueError(f"bad GFA S-line: {line}")
            if toks[1] in segs:
                raise ValueError(f"Duplicated segment: {toks[1]}")
            segs[toks[1]] = toks[2]
        elif line.startswith("P\t"):
            p_i += 1
            p_n = p_i + 1
            toks = line.split("\t")
            if len(toks) < 3:
                raise ValueError(f"bad GFA P-line: {line}")
            path_name = toks[1]
            items = toks[2].split(",")
            is_rc = -1
            last_id = C.SRC_NODE_ID
            next_id = C.SINK_NODE_ID
            for item in items:
                sign = item[-1]
                name = item[:-1]
                if name not in segs:
                    raise ValueError(f"segment {name} not in GFA")
                seq = segs[name]
                if sign == "+":
                    if is_rc == 1:
                        raise ValueError(f"path {path_name} mixes strands")
                    is_rc = 0
                    if name not in seg_in_id:
                        in_id = out_id = -1
                        for i, ch in enumerate(seq):
                            nid = g.add_node(int(encode[ord(ch)]))
                            if i == 0:
                                in_id = nid
                            out_id = nid
                        seg_in_id[name] = in_id
                        seg_out_id[name] = out_id
                    else:
                        in_id = seg_in_id[name]
                        out_id = seg_out_id[name]
                    g.add_edge(last_id, in_id, True, 1, add_read_id, False, p_i, p_n)
                    for i in range(out_id - in_id):
                        g.add_edge(in_id + i, in_id + i + 1, True, 1, add_read_id,
                                   False, p_i, p_n)
                    last_id = out_id
                else:
                    if is_rc == 0:
                        raise ValueError(f"path {path_name} mixes strands")
                    is_rc = 1
                    if name not in seg_in_id:
                        in_id = out_id = -1
                        for i, ch in enumerate(seq):
                            nid = g.add_node(int(encode[ord(ch)]))
                            if i == 0:
                                in_id = nid
                            out_id = nid
                        seg_in_id[name] = in_id
                        seg_out_id[name] = out_id
                    else:
                        in_id = seg_in_id[name]
                        out_id = seg_out_id[name]
                    g.add_edge(out_id, next_id, True, 1, add_read_id, False, p_i, p_n)
                    for i in range(out_id - in_id):
                        g.add_edge(in_id + i, in_id + i + 1, True, 1, add_read_id,
                                   False, p_i, p_n)
                    next_id = in_id
            if is_rc == 1:
                g.add_edge(C.SRC_NODE_ID, next_id, True, 1, add_read_id, False, p_i, p_n)
            else:
                g.add_edge(last_id, C.SINK_NODE_ID, True, 1, add_read_id, False, p_i, p_n)
            ab.names.append(path_name)
            ab.comments.append("")
            ab.quals.append(None)
            ab.seqs.append("")
            ab.is_rc.append(bool(is_rc == 1))


def _parse_msa_fa(ab, abpt: Params, records) -> None:
    """MSA FASTA with '-' gaps: columns map to shared nodes via rank
    (abpoa_seq.c:572-606)."""
    g = ab.graph
    add_read_id = abpt.use_read_ids
    encode = abpt.char_to_code
    rank2node_id: List[int] = []
    for p_i, (name, seq) in enumerate(records):
        p_n = p_i + 1
        if not rank2node_id:
            rank2node_id = [0] * len(seq)
        last_id = C.SRC_NODE_ID
        for rank, ch in enumerate(seq):
            if ch == "-":
                continue
            base = int(encode[ord(ch)])
            cur_id = rank2node_id[rank]
            if cur_id == 0:
                cur_id = g.add_node(base)
                rank2node_id[rank] = cur_id
            elif g.node_base(cur_id) != base:
                aln_id = g.get_aligned_id(cur_id, base)
                if aln_id == -1:
                    aln_id = g.add_node(base)
                    g.add_aligned_node(cur_id, aln_id)
                cur_id = aln_id
            g.add_edge(last_id, cur_id, True, 1, add_read_id, False, p_i, p_n)
            last_id = cur_id
        g.add_edge(last_id, C.SINK_NODE_ID, True, 1, add_read_id, False, p_i, p_n)
        ab.names.append(name)
        ab.comments.append("")
        ab.quals.append(None)
        ab.seqs.append("")
        ab.is_rc.append(False)


def restore_graph(ab, abpt: Params) -> None:
    """(abpoa_seq.c:608-673)"""
    fn = abpt.incr_fn
    if not fn:
        return
    with _open(fn) as fp:
        lines = [ln.rstrip("\n") for ln in fp]
    is_fa = any(ln.startswith(">") for ln in lines if ln)
    if is_fa:
        records = []
        name = None
        seq_parts: List[str] = []
        for ln in lines:
            if ln.startswith(">"):
                if name is not None and seq_parts:
                    records.append((name, "".join(seq_parts)))
                name = ln[1:].split()[0] if len(ln) > 1 else ""
                seq_parts = []
            elif ln:
                seq_parts.append(ln)
        if name is not None:
            records.append((name, "".join(seq_parts)))
        _parse_msa_fa(ab, abpt, records)
    else:
        _parse_gfa(ab, abpt, lines)
    if ab.n_seq == 0:
        print(f"Warning: no graph/sequence restored from '{fn}'.")
    g = ab.graph
    g.is_called_cons = g.is_set_msa_rank = g.is_topological_sorted = False
