"""Partial-order graph visualization: Graphviz .dot + optional png/pdf render.

Reference: /root/reference/src/abpoa_plot.c:34-122 (same node colors, labels,
aligned-node same-rank groups and dashed mismatch links).
"""
from __future__ import annotations

import os
import shutil
import subprocess
import sys

from .. import constants as C
from ..params import Params

NODE_COLOR = ["pink1", "red1", "gold2", "seagreen4", "gray"]  # ACGTN
FONT_SIZE = 24


def dump_pog(ab, abpt: Params) -> None:
    g = ab.graph
    if getattr(g, "is_native", False):
        g = g.to_python(abpt)
    if not g.is_topological_sorted:
        g.topological_sort(abpt)
    out = abpt.out_pog
    assert out is not None
    dot_fn = out + ".dot"
    decode = abpt.code_to_char
    labels = {}
    with open(dot_fn, "w") as fp:
        fp.write(f"// abpoa graph dot file.\n// {g.node_n} nodes.\n")
        fp.write('digraph ABPOA_graph {\n\tgraph [rankdir="LR"];\n'
                 "\tnode [width=1.000000, style=filled, fixedsize=true, "
                 "shape=circle];\n")
        for i in range(g.node_n):
            nid = int(g.index_to_node_id[i])
            if nid == C.SRC_NODE_ID:
                base, color = "S", NODE_COLOR[4]
            elif nid == C.SINK_NODE_ID:
                base, color = "E", NODE_COLOR[4]
            else:
                base = chr(decode[g.nodes[nid].base])
                color = NODE_COLOR[min(g.nodes[nid].base, 4)]
            labels[nid] = f'"{base}\\n{i}"'
            fp.write(f"{labels[nid]} [color={color}, fontsize={FONT_SIZE}]\n")
        x_index = -1
        for i in range(g.node_n):
            nid = int(g.index_to_node_id[i])
            node = g.nodes[nid]
            for j, out_id in enumerate(node.out_ids):
                fp.write(f'\t{labels[nid]} -> {labels[out_id]} '
                         f'[label="{node.out_w[j]}", fontsize=20, fontcolor=red, '
                         f'penwidth={node.out_w[j] + 1}]\n')
            if node.aligned_ids:
                fp.write(f"\t{{rank=same; {labels[nid]} ")
                for a in node.aligned_ids:
                    fp.write(f"{labels[a]} ")
                fp.write("};\n")
                if i > x_index:
                    x_index = i
                    fp.write(f"\t{{ edge [style=dashed, arrowhead=none]; {labels[nid]} ")
                    for a in node.aligned_ids:
                        fp.write(f"-> {labels[a]} ")
                        x_index = max(x_index, int(g.node_id_to_index[a]))
                    fp.write("}\n")
        fp.write("}\n")
    ext = os.path.splitext(out)[1].lstrip(".")
    if ext not in ("pdf", "png"):
        raise SystemExit("POG can only be dumped to a .pdf/.png file")
    if shutil.which("dot") is None:
        print(f"Warning: graphviz 'dot' not found; wrote {dot_fn} only.",
              file=sys.stderr)
        return
    with open(out, "wb") as ofp:
        subprocess.run(["dot", dot_fn, f"-T{ext}"], stdout=ofp, check=True)
