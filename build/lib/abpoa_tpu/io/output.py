"""Text output writers: consensus FASTA/FASTQ, RC-MSA, GFA.

Byte-format parity with /root/reference/src/abpoa_output.c
(abpoa_output_fx_consensus :589-628, abpoa_output_rc_msa :73-104,
abpoa_generate_gfa :196-295).
"""
from __future__ import annotations

from collections import deque
from typing import IO, List

from .. import constants as C
from ..cons.consensus import ConsensusResult
from ..graph import POAGraph
from ..params import Params


def _cons_name(abpt: Params, abc: ConsensusResult, cons_i: int) -> str:
    s = "Consensus_sequence"
    if abpt.batch_index > 0:
        s += f"_{abpt.batch_index}"
    if abc.n_cons > 1:
        s += f"_{cons_i + 1} " + ",".join(str(r) for r in abc.clu_read_ids[cons_i])
    return s


def output_fx_consensus(abc: ConsensusResult, abpt: Params, fp: IO[str]) -> None:
    decode = abpt.code_to_char
    for cons_i in range(abc.n_cons):
        lead = "@" if abpt.out_fq else ">"
        fp.write(f"{lead}{_cons_name(abpt, abc, cons_i)}\n")
        fp.write("".join(chr(decode[b]) for b in abc.cons_base[cons_i]) + "\n")
        if abpt.out_fq:
            fp.write(f"+{_cons_name(abpt, abc, cons_i)}\n")
            fp.write("".join(chr(q) for q in abc.cons_phred[cons_i]) + "\n")


def output_rc_msa(abc: ConsensusResult, abpt: Params, names: List[str],
                  is_rc: List[bool], fp: IO[str]) -> None:
    if abc.msa_len <= 0:
        return
    decode = abpt.code_to_char
    for i in range(abc.n_seq):
        if names[i]:
            sfx = "_reverse_complement" if is_rc[i] else ""
            fp.write(f">{names[i]}{sfx}\n")
        else:
            fp.write(f">Seq_{i + 1}\n")
        fp.write("".join(chr(decode[b]) for b in abc.msa_base[i]) + "\n")
    if abpt.out_cons:
        for cons_i in range(abc.n_cons):
            fp.write(">Consensus_sequence")
            if abc.n_cons > 1:
                fp.write(f"_{cons_i + 1} " + ",".join(str(r) for r in abc.clu_read_ids[cons_i]))
            fp.write("\n")
            fp.write("".join(chr(decode[b]) for b in abc.msa_base[abc.n_seq + cons_i]) + "\n")


def generate_gfa(g: POAGraph, abpt: Params, names: List[str], is_rc: List[bool],
                 abc_provider, fp: IO[str]) -> None:
    """BFS GFA writer with per-read P-lines (src/abpoa_output.c:196-295).

    `abc_provider()` lazily generates the consensus when out_cons is set.
    """
    if g.node_n <= 2:
        return
    n_seq = len(names)
    decode = abpt.code_to_char
    in_degree = [len(nd.in_ids) for nd in g.nodes]
    read_paths: List[List[int]] = [[] for _ in range(n_seq)]
    nl = sum(len(g.nodes[i].in_ids) for i in range(2, g.node_n))
    fp.write(f"H\tVN:Z:1.0\tNS:i:{g.node_n - 2}\t"
             f"NL:i:{nl - len(g.nodes[C.SRC_NODE_ID].out_ids)}\t"
             f"NP:i:{n_seq + (1 if abpt.out_cons else 0)}\n")
    q: deque[int] = deque([C.SRC_NODE_ID])
    while q:
        cur = q.popleft()
        if cur == C.SINK_NODE_ID:
            break
        node = g.nodes[cur]
        if cur != C.SRC_NODE_ID:
            fp.write(f"S\t{cur - 1}\t{chr(decode[node.base])}\n")
            for pre_id in node.in_ids:
                if pre_id != C.SRC_NODE_ID:
                    fp.write(f"L\t{pre_id - 1}\t+\t{cur - 1}\t+\t0M\n")
            for bits in node.read_ids:
                while bits:
                    lsb = bits & -bits
                    read_paths[lsb.bit_length() - 1].append(cur - 1)
                    bits ^= lsb
        for out_id in node.out_ids:
            in_degree[out_id] -= 1
            if in_degree[out_id] == 0:
                q.append(out_id)
    for i in range(n_seq):
        name = names[i] if names[i] else str(i + 1)
        fp.write(f"P\t{name}\t")
        path = read_paths[i]
        if is_rc[i]:
            fp.write(",".join(f"{p}-" for p in reversed(path)) + "\t*\n")
        else:
            fp.write(",".join(f"{p}+" for p in path) + "\t*\n")
    if abpt.out_cons:
        abc = abc_provider()
        for cons_i in range(abc.n_cons):
            fp.write("P\tConsensus_sequence")
            if abc.n_cons > 1:
                fp.write(f"_{cons_i + 1}")
            fp.write("\t")
            fp.write(",".join(f"{nid - 1}+" for nid in abc.cons_node_ids[cons_i]) + "\t*\n")
