"""Packed 64-bit graph cigar (reference include/abpoa.h:45-50, abpoa_align.h:54-96).

M/X ops:   node_id << 34 | query_id << 4 | op        (one entry per base)
I/S/H ops: query_id << 34 | run_len << 4 | op        (run-length merged)
D ops:     node_id << 34 | run_len << 4 | op
"""
from __future__ import annotations

from typing import List

from . import constants as C

_MERGEABLE = (C.CINS, C.CSOFT_CLIP, C.CHARD_CLIP)


def push_cigar(cigar: List[int], op: int, length: int, node_id: int, query_id: int) -> None:
    if cigar and op in _MERGEABLE and (cigar[-1] & 0xF) == op:
        cigar[-1] += length << 4
        return
    if op in (C.CMATCH, C.CDIFF):
        cigar.append((node_id & 0x3FFFFFFF) << 34 | (query_id & 0x3FFFFFFF) << 4 | op)
    elif op in _MERGEABLE:
        cigar.append((query_id & 0x3FFFFFFF) << 34 | (length & 0x3FFFFFFF) << 4 | op)
    elif op == C.CDEL:
        cigar.append((node_id & 0x3FFFFFFF) << 34 | (length & 0x3FFFFFFF) << 4 | op)
    else:
        raise ValueError(f"Unknown cigar op: {op}")


def cigar_str(cigar: List[int]) -> str:
    out = []
    for p in cigar:
        op = p & 0xF
        if op in (C.CMATCH, C.CDIFF):
            out.append(f"1{C.CIGAR_STR[op]}")
        else:
            out.append(f"{(p >> 4) & 0x3FFFFFFF}{C.CIGAR_STR[op]}")
    return "".join(out)
