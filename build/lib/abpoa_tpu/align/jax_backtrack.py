"""Device-side backtrack for the JAX DP backend.

The reference re-reads the whole DP matrix on the host
(/root/reference/src/abpoa_align_simd.c:309-458). Over a slow host link that
transfer dominates, so we instead walk the traceback as a `lax.while_loop` on
the accelerator: each iteration replays the reference's op-priority chain
(M -> E1/E2 -> F1/F2 -> M with put_gap_on_right / put_gap_at_end switches)
using scalar gathers into the resident DP planes, and emits one op into a
bounded op buffer. Only that buffer (a few KB) crosses the link.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .. import constants as C

# op codes in the emitted stream
OP_MATCH = 0
OP_DEL = 1
OP_INS = 2


@functools.partial(jax.jit, static_argnames=(
    "gap_mode", "local", "gap_on_right", "put_gap_at_end", "max_ops"))
def device_backtrack(H, E1, E2, F1, F2, dp_beg, dp_end, pre_idx, pre_msk,
                     base, query_pad, mat, best_i, best_j,
                     e1, oe1, e2, oe2,
                     gap_mode: int, local: bool, gap_on_right: bool,
                     put_gap_at_end: bool, max_ops: int, pre_score=None):
    """Returns (ops[max_ops, 2], n_ops, final_i, final_j, n_aln, n_match,
    start_i, start_j). ops rows: (op_code, dp_i-at-emit).

    pre_score: per-(row, pred-slot) -G path score (abpoa_graph.c:429-437),
    added to every predecessor-crossing score equality."""
    R, P = pre_idx.shape
    if pre_score is None:
        pre_score = jnp.zeros((R, P), jnp.int32)
    linear = gap_mode == C.LINEAR_GAP
    convex = gap_mode == C.CONVEX_GAP
    i32 = jnp.int32

    def gat(A, i, j):
        return lax.dynamic_index_in_dim(
            lax.dynamic_index_in_dim(A, i, 0, keepdims=False), j, 0, keepdims=False)

    def state_tuple(i, j, cur_op, look_gap, n_ops, ops, n_aln, n_match,
                    start_i, start_j, err, done):
        return (i, j, cur_op, look_gap, n_ops, ops, n_aln, n_match,
                start_i, start_j, err, done)

    def cond(st):
        i, j, *_, err, done = st
        return (i > 0) & (j > 0) & (~err) & (~done)

    def body(st):
        (i, j, cur_op, look_gap, n_ops, ops, n_aln, n_match,
         _si, _sj, err, done) = st
        H_ij = gat(H, i, j)
        if local:
            stop = H_ij == 0
        else:
            stop = jnp.bool_(False)
        start_i, start_j = jnp.where(stop, _si, i), jnp.where(stop, _sj, j)
        s = mat[base[i], query_pad[j - 1]]
        is_match = (base[i] == query_pad[j - 1]).astype(i32)

        pidx = pre_idx[i]
        pmsk = pre_msk[i]
        ps = pre_score[i]
        Hp_jm1 = H[pidx, j - 1]
        Hp_j = H[pidx, j]
        beg_p = dp_beg[pidx]
        end_p = dp_end[pidx]
        inb_m = (j - 1 >= beg_p) & (j - 1 <= end_p) & pmsk
        inb_e = (j >= beg_p) & (j <= end_p) & pmsk

        m_hit = inb_m & (Hp_jm1 + s + ps == H_ij)
        any_m = jnp.any(m_hit)
        first_m = jnp.argmax(m_hit).astype(i32)

        has_M = (cur_op & C.M_OP) != 0

        # ---------- eligible match (first pass) ----------
        if linear:
            m1_ok = (not gap_on_right) and True
            m1 = any_m & (look_gap == 0) if m1_ok else jnp.bool_(False)
        else:
            m1 = any_m & has_M & (look_gap == 0) if not gap_on_right else jnp.bool_(False)

        # ---------- deletion ----------
        if linear:
            d_hit = inb_e & (Hp_j - e1 + ps == H_ij)
            any_d = jnp.any(d_hit)
            first_d = jnp.argmax(d_hit).astype(i32)
            d_new_op = jnp.int32(C.ALL_OP)
        else:
            E1_ij = gat(E1, i, j)
            E1p_j = E1[pidx, j]
            has_E1 = (cur_op & C.E1_OP) != 0
            c1 = jnp.where(has_M, H_ij == E1p_j + ps, E1_ij == E1p_j - e1 + ps)
            hit1 = inb_e & c1 & has_E1
            if convex:
                E2_ij = gat(E2, i, j)
                E2p_j = E2[pidx, j]
                has_E2 = (cur_op & C.E2_OP) != 0
                c2 = jnp.where(has_M, H_ij == E2p_j + ps, E2_ij == E2p_j - e2 + ps)
                hit2 = inb_e & c2 & has_E2
            else:
                hit2 = jnp.zeros_like(hit1)
            slot_hit = hit1 | hit2
            any_d = jnp.any(slot_hit)
            first_d = jnp.argmax(slot_hit).astype(i32)
            use_e1 = hit1[first_d]
            p_d = pidx[first_d]
            # next op set depends on whether the pre E equals pre H - oe
            pe1 = E1p_j[first_d]
            ph = Hp_j[first_d]
            op_e1 = jnp.where(ph - oe1 == pe1, i32(C.M_OP | C.F_OP), i32(C.E1_OP))
            if convex:
                pe2 = E2p_j[first_d]
                op_e2 = jnp.where(ph - oe2 == pe2, i32(C.M_OP | C.F_OP), i32(C.E2_OP))
            else:
                op_e2 = i32(C.E1_OP)
            d_new_op = jnp.where(use_e1, op_e1, op_e2)

        # ---------- insertion ----------
        if linear:
            H_ijm1 = gat(H, i, j - 1)
            ins_hit = H_ijm1 - e1 == H_ij
            ins_new_op = jnp.int32(C.ALL_OP)
        else:
            F1_ij = gat(F1, i, j)
            F1_ijm1 = gat(F1, i, j - 1)
            H_ijm1 = gat(H, i, j - 1)
            has_F1 = (cur_op & C.F1_OP) != 0
            f1_open = H_ijm1 - oe1 == F1_ij
            f1_ext = F1_ijm1 - e1 == F1_ij
            f1_gate = jnp.where(has_M, H_ij == F1_ij, True)
            f1_hit = has_F1 & f1_gate & (f1_open | f1_ext)
            f1_op = jnp.where(f1_open, i32(C.M_OP | C.E_OP), i32(C.F1_OP))
            if convex:
                F2_ij = gat(F2, i, j)
                F2_ijm1 = gat(F2, i, j - 1)
                has_F2 = (cur_op & C.F2_OP) != 0
                f2_open = H_ijm1 - oe2 == F2_ij
                f2_ext = F2_ijm1 - e2 == F2_ij
                f2_gate = jnp.where(has_M, H_ij == F2_ij, True)
                f2_hit = has_F2 & f2_gate & (f2_open | f2_ext)
                f2_op = jnp.where(f2_open, i32(C.M_OP | C.E_OP), i32(C.F2_OP))
            else:
                f2_hit = jnp.bool_(False)
                f2_op = i32(C.ALL_OP)
            ins_hit = f1_hit | f2_hit
            ins_new_op = jnp.where(f1_hit, f1_op, f2_op)

        # ---------- final match ----------
        if linear:
            m2 = any_m
        else:
            m2 = any_m & has_M

        # ---------- choose ----------
        # priority: m1, D, I, m2
        d_sel = (~m1) & any_d
        i_sel = (~m1) & (~d_sel) & ins_hit
        m2_sel = (~m1) & (~d_sel) & (~i_sel) & m2
        no_hit = (~m1) & (~d_sel) & (~i_sel) & (~m2)
        m_sel = m1 | m2_sel

        op_code = jnp.where(m_sel, OP_MATCH, jnp.where(d_sel, OP_DEL, OP_INS))
        ops = ops.at[n_ops, 0].set(jnp.where(stop | no_hit, ops[n_ops, 0], op_code))
        ops = ops.at[n_ops, 1].set(jnp.where(stop | no_hit, ops[n_ops, 1], i))

        pre_m = pidx[first_m]
        pre_d = pidx[first_d] if not linear else pidx[first_d]
        new_i = jnp.where(m_sel, pre_m, jnp.where(d_sel, pre_d, i))
        new_j = jnp.where(m_sel | i_sel, j - 1, j)
        new_op = jnp.where(m_sel, i32(C.ALL_OP),
                           jnp.where(d_sel, d_new_op,
                                     jnp.where(i_sel, ins_new_op, cur_op)))
        new_look = jnp.where(m1, look_gap,
                             jnp.where(d_sel | i_sel | m2_sel, i32(0), look_gap))
        new_naln = n_aln + jnp.where(m_sel | i_sel, 1, 0)
        new_nmatch = n_match + jnp.where(m_sel, is_match, 0)
        adv = ~(stop | no_hit)
        return state_tuple(
            jnp.where(adv, new_i, i), jnp.where(adv, new_j, j),
            jnp.where(adv, new_op, cur_op), jnp.where(adv, new_look, look_gap),
            n_ops + jnp.where(adv, 1, 0), ops,
            jnp.where(adv, new_naln, n_aln), jnp.where(adv, new_nmatch, n_match),
            start_i, start_j, err | no_hit, done | stop)

    ops0 = jnp.zeros((max_ops, 2), jnp.int32)
    st0 = state_tuple(best_i, best_j, jnp.int32(C.ALL_OP),
                      jnp.int32(1 if put_gap_at_end else 0), jnp.int32(0), ops0,
                      jnp.int32(0), jnp.int32(0), best_i, best_j,
                      jnp.bool_(False), jnp.bool_(False))
    st = lax.while_loop(cond, body, st0)
    (i, j, _co, _lg, n_ops, ops, n_aln, n_match, si, sj, err, _done) = st
    return ops, n_ops, i, j, n_aln, n_match, si, sj, err
