"""Alignment result container (reference abpoa_res_t, include/abpoa.h:57-64)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List


@dataclass
class AlignResult:
    cigar: List[int] = field(default_factory=list)  # packed 64-bit graph cigar
    node_s: int = -1
    node_e: int = -1
    query_s: int = -1
    query_e: int = -1
    n_aln_bases: int = 0
    n_matched_bases: int = 0
    best_score: int = 0
