"""Prototype all-device progressive POA loop (round 1).

SUPERSEDED by align/fused_loop.py, which wraps the whole read set in one
jitted while_loop with banded storage, capacity growth, int16 promotion and
an optional Pallas kernel; this module remains as the readable stepping-stone
design and is still covered by tests/test_device_pipeline.py.

Composes the device-resident pieces end-to-end for plain (unseeded) global
progressive POA:

  topo_sort (device) -> kernel tables BUILT ON DEVICE from the dense graph
  arrays (pure gathers, no host walk) -> _dp_full (scan + best + backtrack on
  device) -> fuse_alignment (device)

The per-read loop performs NO host synchronization: the backtrack op stream is
reversed into fusion order on device (`reverse_ops_device`), band/sink scalars
stay traced, and the Python loop only enqueues async dispatches. Overflow/error
flags are checked once at the end. Round 2 wraps the loop in a single jitted
`lax.while_loop` to also amortize per-dispatch overhead (see PERF.md).
"""
from __future__ import annotations

from typing import List

import numpy as np

import jax
import jax.numpy as jnp

from .. import constants as C
from ..params import Params
from .device_graph import (DeviceGraph, fuse_alignment, init_device_graph,
                           topo_sort)
from .jax_backend import _bucket, _dp_full
from .oracle import INT32_MIN, dp_inf_min


@jax.jit
def build_tables_device(g: DeviceGraph, i2n, n2i, remain):
    """Kernel tables as pure gathers over the dense graph arrays."""
    N, E = g.in_ids.shape
    n = g.node_n
    rows = jnp.arange(N, dtype=jnp.int32)
    nid = i2n  # topo row -> node id
    base = g.base[nid]
    # predecessors of row i = topo indices of in-edges of its node
    pre_idx = n2i[g.in_ids[nid]]                       # (N, E)
    pre_msk = jnp.arange(E)[None, :] < g.in_cnt[nid][:, None]
    pre_msk = pre_msk & (rows[:, None] > 0) & (rows[:, None] < n)
    out_idx = n2i[g.out_ids[nid]]
    out_msk = jnp.arange(E)[None, :] < g.out_cnt[nid][:, None]
    out_msk = out_msk & (rows[:, None] > 0) & (rows[:, None] < n - 1)
    row_active = (rows > 0) & (rows < n - 1)
    remain_rows = remain[nid]
    # fresh adaptive-band state (the reference re-inits in topological_sort)
    mpl0 = jnp.full(N, n, jnp.int32).at[0].set(0)
    mpr0 = jnp.zeros(N, jnp.int32)
    # first-row seeding: out-neighbors of the source row get mpl=mpr=1
    src_out = out_idx[0]
    src_m = jnp.arange(E) < g.out_cnt[nid[0]]
    tgt = jnp.where(src_m, src_out, N - 1)
    mpl0 = mpl0.at[tgt].set(jnp.where(src_m, 1, mpl0[tgt]))
    mpr0 = mpr0.at[tgt].set(jnp.where(src_m, 1, mpr0[tgt]))
    return (base, pre_idx, pre_msk, out_idx, out_msk, row_active,
            remain_rows, mpl0, mpr0)


@jax.jit
def reverse_ops_device(ops, n_ops, best_j, fin_j, qlen, i2n):
    """Backtrack emits ops from the alignment end backwards; fusion consumes
    them forward with head/tail insertions for unaligned query ends. Runs on
    device — no host roundtrip between backtrack and fusion."""
    max_ops = ops.shape[0]
    k = jnp.arange(max_ops, dtype=jnp.int32)
    head = fin_j                       # leading INS count
    mid = head + n_ops                 # reversed op-stream region
    n_fwd = mid + (qlen - best_j)      # + trailing INS
    src = jnp.clip(n_ops - 1 - (k - head), 0, max_ops - 1)
    in_mid = (k >= head) & (k < mid)
    op = jnp.where(in_mid, ops[src, 0], 2)
    # map dp-row argument to node id for match/del ops
    arg = jnp.where(in_mid, i2n[jnp.clip(ops[src, 1], 0, i2n.shape[0] - 1)], 0)
    fwd = jnp.stack([jnp.where(k < n_fwd, op, 0),
                     jnp.where(k < n_fwd, arg, 0)], axis=1)
    return fwd, n_fwd


def progressive_poa_device(seqs: List[np.ndarray], abpt: Params,
                           N: int = 1024, E: int = 8, A: int = 4
                           ) -> DeviceGraph:
    """Run plain progressive POA with all graph/DP state on device.

    Returns the final (topo-sorted) DeviceGraph; raises on capacity overflow.
    Requires global mode + banded + convex/affine/linear without path scores.
    """
    assert abpt.align_mode == C.GLOBAL_MODE and not abpt.inc_path_score
    inf_min = dp_inf_min(abpt)
    banded = abpt.wb >= 0
    mat = np.ascontiguousarray(abpt.mat.astype(np.int32))

    g = init_device_graph(N, E, A)
    i2n = n2i = remain = None
    err_any = jnp.bool_(False)
    for read_id, seq in enumerate(seqs):
        qlen = len(seq)
        Qp = _bucket(qlen + 1, 128)
        max_ops = N + Qp + 8
        wpad = np.ones(N, dtype=np.int32)
        qpad = np.zeros(N, dtype=np.int32)
        qpad[:qlen] = seq
        if read_id == 0:  # seed the empty graph
            ops = jnp.zeros((max_ops, 2), jnp.int32)
            g = fuse_alignment(g, ops, jnp.int32(0), jnp.asarray(qpad),
                               jnp.int32(qlen), jnp.asarray(wpad),
                               C.SRC_NODE_ID, C.SINK_NODE_ID, max_ops=max_ops)
            g, i2n, n2i, remain, ok = topo_sort(g)
            continue

        # --- everything below is async device work: no host sync per read ---
        base, pre_idx, pre_msk, out_idx, out_msk, row_active, remain_rows, \
            mpl0, mpr0 = build_tables_device(g, i2n, n2i, remain)

        w = qlen if abpt.wb < 0 else abpt.wb + int(abpt.wf * qlen)
        remain_end = remain[C.SINK_NODE_ID]
        r0 = qlen - (remain_rows[0] - remain_end - 1)
        dp_end0 = jnp.minimum(qlen, jnp.maximum(mpr0[0], r0) + w) if banded \
            else jnp.int32(qlen)

        qp = np.zeros((abpt.m, Qp), dtype=np.int32)
        qp[:, 1: qlen + 1] = mat[:, seq]
        sink_rows = pre_idx[g.node_n - 1]
        sink_msk = pre_msk[g.node_n - 1]

        packed = _dp_full(
            base, pre_idx, pre_msk, out_idx, out_msk, row_active,
            remain_rows, mpl0, mpr0, jnp.asarray(qp),
            jnp.asarray(seq.astype(np.int32)), jnp.asarray(mat),
            sink_rows, sink_msk,
            jnp.int32(qlen), jnp.int32(w), remain_end.astype(jnp.int32),
            jnp.int32(inf_min), dp_end0.astype(jnp.int32),
            jnp.int32(abpt.gap_open1), jnp.int32(abpt.gap_ext1),
            jnp.int32(abpt.gap_oe1), jnp.int32(abpt.gap_open2),
            jnp.int32(abpt.gap_ext2), jnp.int32(abpt.gap_oe2),
            gap_mode=abpt.gap_mode, local=False, banded=banded,
            n_steps=N - 1, align_mode=C.GLOBAL_MODE,
            gap_on_right=bool(abpt.put_gap_on_right),
            put_gap_at_end=bool(abpt.put_gap_at_end), max_ops=max_ops,
            ret_cigar=True)
        n_ops = packed[0]
        fin_j = packed[2]
        err_any = err_any | (packed[7] != 0)
        best_j = packed[10]
        ops = packed[11 + 2 * N:].reshape(max_ops, 2)
        fwd_ops, n_fwd = reverse_ops_device(ops, n_ops, best_j, fin_j,
                                            jnp.int32(qlen), i2n)
        g = fuse_alignment(g, fwd_ops, n_fwd, jnp.asarray(qpad),
                           jnp.int32(qlen), jnp.asarray(wpad),
                           C.SRC_NODE_ID, C.SINK_NODE_ID, max_ops=max_ops)
        g, i2n, n2i, remain, ok = topo_sort(g)
    # one sync at the end of the read set
    if bool(err_any):
        raise RuntimeError("device backtrack failed in device pipeline")
    if not bool(g.ok):
        raise RuntimeError("device graph capacity overflow")
    return g


def device_graph_to_python(g: DeviceGraph, abpt: Params):
    """Materialize a host POAGraph (for consensus/output) from device arrays."""
    from ..graph import POAGraph, Node
    n = int(g.node_n)
    base = np.asarray(g.base)
    in_ids = np.asarray(g.in_ids)
    in_w = np.asarray(g.in_w)
    in_cnt = np.asarray(g.in_cnt)
    out_ids = np.asarray(g.out_ids)
    out_w = np.asarray(g.out_w)
    out_cnt = np.asarray(g.out_cnt)
    aligned = np.asarray(g.aligned)
    aligned_cnt = np.asarray(g.aligned_cnt)
    n_read = np.asarray(g.n_read)
    pg = POAGraph()
    pg.nodes = []
    for i in range(n):
        nd = Node(i, int(base[i]))
        nd.in_ids = [int(x) for x in in_ids[i][: in_cnt[i]]]
        nd.in_w = [int(x) for x in in_w[i][: in_cnt[i]]]
        nd.out_ids = [int(x) for x in out_ids[i][: out_cnt[i]]]
        nd.out_w = [int(x) for x in out_w[i][: out_cnt[i]]]
        nd.read_ids = [0] * len(nd.out_ids)
        nd.aligned_ids = [int(x) for x in aligned[i][: aligned_cnt[i]]]
        nd.n_read = int(n_read[i])
        pg.nodes.append(nd)
    pg.topological_sort(abpt)
    return pg
