"""Device-resident POA graph: dense arrays + jitted fusion and topo sort.

Foundation for the all-device progressive loop (PERF.md round-2 plan): keep
the whole POA graph in fixed-capacity device arrays and run
align -> backtrack -> FUSE -> TOPO-SORT for every read inside one jitted loop,
so the high-latency host link is touched once per read set instead of once
per read.

The semantics mirror the host engines exactly (graph.py / native/host_core.cpp,
reference /root/reference/src/abpoa_graph.c:480-774):
- fusion walks the op stream emitted by the device backtrack
  (jax_backtrack.device_backtrack): match reuses/aligns nodes, insertion adds
  node chains, deletion skips;
- edges live in fixed-width slots per node (append-or-reweight);
- aligned-mismatch groups keep the reference's mutual-registration rule;
- Kahn BFS topo sort with aligned-group atomicity, weight-descending exchange
  sort of edge slots, and the reverse-BFS max_remain metric.

Capacities (node count N, edge slots E, aligned slots A) are static; overflow
sets an `ok` flag so callers can fall back to the host engine.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import constants as C


class DeviceGraph(NamedTuple):
    """Dense POA graph state (all int32 unless noted)."""
    base: jnp.ndarray       # (N,)
    in_ids: jnp.ndarray     # (N, E)
    in_w: jnp.ndarray       # (N, E)
    in_cnt: jnp.ndarray     # (N,)
    out_ids: jnp.ndarray    # (N, E)
    out_w: jnp.ndarray      # (N, E)
    out_cnt: jnp.ndarray    # (N,)
    aligned: jnp.ndarray    # (N, A)
    aligned_cnt: jnp.ndarray  # (N,)
    n_read: jnp.ndarray     # (N,)
    n_span: jnp.ndarray     # (N,)
    node_n: jnp.ndarray     # () scalar
    ok: jnp.ndarray         # () bool


def init_device_graph(N: int, E: int, A: int) -> DeviceGraph:
    z = jnp.zeros
    return DeviceGraph(
        base=z(N, jnp.int32),
        in_ids=z((N, E), jnp.int32), in_w=z((N, E), jnp.int32), in_cnt=z(N, jnp.int32),
        out_ids=z((N, E), jnp.int32), out_w=z((N, E), jnp.int32), out_cnt=z(N, jnp.int32),
        aligned=z((N, A), jnp.int32), aligned_cnt=z(N, jnp.int32),
        n_read=z(N, jnp.int32), n_span=z(N, jnp.int32),
        node_n=jnp.int32(2), ok=jnp.bool_(True))


def _add_edge(g: DeviceGraph, fr, to, check, w) -> DeviceGraph:
    """Append-or-reweight an edge fr->to (abpoa_graph.c:480-556)."""
    E = g.in_ids.shape[1]
    slots = jnp.arange(E, dtype=jnp.int32)

    # out slot of `fr` pointing at `to` (valid slots only)
    om = (slots < g.out_cnt[fr]) & (g.out_ids[fr] == to)
    o_exists = check & jnp.any(om)
    o_slot = jnp.where(o_exists, jnp.argmax(om), g.out_cnt[fr]).astype(jnp.int32)
    im = (slots < g.in_cnt[to]) & (g.in_ids[to] == fr)
    i_exists = check & jnp.any(im)
    i_slot = jnp.where(i_exists, jnp.argmax(im), g.in_cnt[to]).astype(jnp.int32)

    ok = g.ok & (o_slot < E) & (i_slot < E)
    out_ids = g.out_ids.at[fr, o_slot].set(to)
    out_w = g.out_w.at[fr, o_slot].set(jnp.where(o_exists, g.out_w[fr, o_slot] + w, w))
    out_cnt = g.out_cnt.at[fr].set(jnp.where(o_exists, g.out_cnt[fr], g.out_cnt[fr] + 1))
    in_ids = g.in_ids.at[to, i_slot].set(fr)
    in_w = g.in_w.at[to, i_slot].set(jnp.where(i_exists, g.in_w[to, i_slot] + w, w))
    in_cnt = g.in_cnt.at[to].set(jnp.where(i_exists, g.in_cnt[to], g.in_cnt[to] + 1))
    n_read = g.n_read.at[fr].add(1)
    return g._replace(out_ids=out_ids, out_w=out_w, out_cnt=out_cnt,
                      in_ids=in_ids, in_w=in_w, in_cnt=in_cnt,
                      n_read=n_read, ok=ok)


def _get_aligned_id(g: DeviceGraph, node_id, b):
    A = g.aligned.shape[1]
    slots = jnp.arange(A, dtype=jnp.int32)
    ids = g.aligned[node_id]
    m = (slots < g.aligned_cnt[node_id]) & (g.base[ids] == b)
    return jnp.where(jnp.any(m), ids[jnp.argmax(m)], -1).astype(jnp.int32)


def _add_aligned(g: DeviceGraph, node_id, new_id) -> DeviceGraph:
    """Mutual registration across the whole mismatch group (abpoa_graph.c:455-463)."""
    A = g.aligned.shape[1]

    def body(k, st):
        aligned, cnt, ok = st
        ex = aligned[node_id, k]
        # ex <-> new_id
        aligned = aligned.at[ex, cnt[ex]].set(new_id)
        aligned = aligned.at[new_id, cnt[new_id]].set(ex)
        ok = ok & (cnt[ex] < A) & (cnt[new_id] < A)
        cnt = cnt.at[ex].add(1).at[new_id].add(1)
        return aligned, cnt, ok

    n0 = g.aligned_cnt[node_id]
    aligned, cnt, ok = lax.fori_loop(0, n0, body, (g.aligned, g.aligned_cnt, g.ok))
    aligned = aligned.at[node_id, cnt[node_id]].set(new_id)
    aligned = aligned.at[new_id, cnt[new_id]].set(node_id)
    ok = ok & (cnt[node_id] < A) & (cnt[new_id] < A)
    cnt = cnt.at[node_id].add(1).at[new_id].add(1)
    return g._replace(aligned=aligned, aligned_cnt=cnt, ok=ok)


@functools.partial(jax.jit, static_argnames=("max_ops",))
def fuse_alignment(g: DeviceGraph, ops, n_ops, query, qlen, weight,
                   beg_node_id, end_node_id, max_ops: int) -> DeviceGraph:
    """Fuse one backtrack op stream into the graph (abpoa_graph.c:689-774).

    ops: (max_ops, 2) int32 rows (op_code, dp_i placeholder) in FORWARD order:
    op_code 0=match-consuming (node_id in column 1), 2=insert (count in col 1),
    1=delete (node_id, no query consumed). Build with `ops_from_cigar`.
    """
    N, E = g.in_ids.shape

    def seed_graph(g):
        # empty graph: chain of qlen nodes (abpoa_graph.c:573-593)
        def body(i, st):
            g, last = st
            nid = g.node_n
            g = g._replace(base=g.base.at[nid].set(query[i]),
                           node_n=g.node_n + 1,
                           ok=g.ok & (nid < N))
            g = _add_edge(g, last, nid, False, weight[i])
            return g, nid
        g, last = lax.fori_loop(0, qlen, body, (g, jnp.int32(C.SRC_NODE_ID)))
        return _add_edge(g, last, jnp.int32(C.SINK_NODE_ID), False,
                         weight[jnp.maximum(qlen - 1, 0)])

    def fuse(g):
        def body(t, st):
            g, last, last_new, qpos = st
            op = ops[t, 0]
            arg = ops[t, 1]
            is_real = t < n_ops

            def do_match(st):
                g, last, last_new, qpos = st
                node_id = arg
                b = query[qpos]
                w = weight[qpos]
                same = g.base[node_id] == b

                def on_same(g):
                    return _add_edge(g, last, node_id, 1 - last_new, w), node_id, jnp.int32(0)

                def on_diff(g):
                    aln = _get_aligned_id(g, node_id, b)

                    def use_aln(g):
                        return _add_edge(g, last, aln, 1 - last_new, w), aln, jnp.int32(0)

                    def new_node(g):
                        nid = g.node_n
                        g = g._replace(base=g.base.at[nid].set(b),
                                       node_n=g.node_n + 1, ok=g.ok & (nid < N))
                        g = _add_edge(g, last, nid, False, w)
                        g = g._replace(n_span=g.n_span.at[nid].set(g.n_span[last]))
                        g = _add_aligned(g, node_id, nid)
                        return g, nid, jnp.int32(1)
                    return lax.cond(aln >= 0, use_aln, new_node, g)
                g, new_last, nn = lax.cond(same, on_same, on_diff, g)
                return g, new_last, nn, qpos + 1

            def do_ins(st):
                g, last, last_new, qpos = st
                b = query[qpos]
                w = weight[qpos]
                nid = g.node_n
                g = g._replace(base=g.base.at[nid].set(b),
                               node_n=g.node_n + 1, ok=g.ok & (nid < N))
                g = _add_edge(g, last, nid, False, w)
                g = g._replace(n_span=g.n_span.at[nid].set(g.n_span[last]))
                return g, nid, jnp.int32(1), qpos + 1

            def do_noop(st):
                return st

            st2 = lax.cond(
                is_real,
                lambda s: lax.switch(jnp.clip(op, 0, 2),
                                     [do_match, do_noop, do_ins], s),
                do_noop, (g, last, last_new, qpos))
            return st2

        g, last, last_new, _ = lax.fori_loop(
            0, max_ops, body,
            (g, jnp.int32(beg_node_id), jnp.int32(0), jnp.int32(0)))
        return _add_edge(g, last, jnp.int32(end_node_id), 1 - last_new,
                         weight[jnp.maximum(qlen - 1, 0)])

    return lax.cond(g.node_n == 2, seed_graph, fuse, g)


@functools.partial(jax.jit, static_argnames=())
def topo_sort(g: DeviceGraph):
    """Kahn BFS with aligned-group atomicity + weight-desc edge sort +
    reverse-BFS max_remain (abpoa_graph.c:192-357).

    Returns (g_sorted, index_to_node_id, node_id_to_index, max_remain, ok).
    """
    N, E = g.in_ids.shape
    A = g.aligned.shape[1]

    # ---- Kahn BFS with aligned-group atomicity ----------------------------
    # NOTE: the reference BFS-orders nodes BEFORE re-sorting edges by weight
    # (abpoa_graph.c:344-345), i.e. the BFS sees the previous call's edge
    # order; the weight sort below applies to the DP / remain pass.
    n = g.node_n
    in_degree = g.in_cnt
    queue = jnp.zeros(N, jnp.int32)
    i2n = jnp.zeros(N, jnp.int32)
    n2i = jnp.zeros(N, jnp.int32)
    queue = queue.at[0].set(C.SRC_NODE_ID)

    def cond(st):
        head, tail, idx, *_ = st
        return (head < tail) & (idx < n)

    def body(st):
        head, tail, idx, queue, i2n, n2i, in_degree = st
        cur = queue[head]
        i2n = i2n.at[idx].set(cur)
        n2i = n2i.at[cur].set(idx)

        def push_outs(st):
            tail, queue, in_degree = st

            def out_body(k, st):
                tail, queue, in_degree = st
                out_id = g.out_ids[cur, k]
                in_degree = in_degree.at[out_id].add(-1)
                ready = in_degree[out_id] == 0
                grp_ok = jnp.all(
                    jnp.where(jnp.arange(A) < g.aligned_cnt[out_id],
                              in_degree[g.aligned[out_id]] == 0, True))

                def push(st):
                    tail, queue = st
                    queue = queue.at[tail].set(out_id)
                    tail = tail + 1

                    def push_al(a, st):
                        tail, queue = st
                        queue = queue.at[tail].set(g.aligned[out_id, a])
                        return tail + 1, queue
                    tail, queue = lax.fori_loop(0, g.aligned_cnt[out_id],
                                                push_al, (tail, queue))
                    return tail, queue
                tail, queue = lax.cond(ready & grp_ok, push,
                                       lambda s: s, (tail, queue))
                return tail, queue, in_degree
            return lax.fori_loop(0, g.out_cnt[cur], out_body, st)

        tail, queue, in_degree = lax.cond(
            cur != C.SINK_NODE_ID, push_outs, lambda s: s,
            (tail, queue, in_degree))
        return head + 1, tail, idx + 1, queue, i2n, n2i, in_degree

    head, tail, idx, queue, i2n, n2i, in_degree = lax.while_loop(
        cond, body, (jnp.int32(0), jnp.int32(1), jnp.int32(0),
                     queue, i2n, n2i, in_degree))
    ok = g.ok & (idx == n)

    # ---- weight-descending exchange sort of edge slots (exact tie behavior)
    def sort_node(ids, w, cnt):
        def outer(j, st):
            ids, w = st

            def inner(k, st):
                ids, w = st
                swap = (k < cnt) & (j < k) & (w[j] < w[k])
                wj, wk = w[j], w[k]
                ij, ik = ids[j], ids[k]
                w = w.at[j].set(jnp.where(swap, wk, wj)).at[k].set(jnp.where(swap, wj, wk))
                ids = ids.at[j].set(jnp.where(swap, ik, ij)).at[k].set(jnp.where(swap, ij, ik))
                return ids, w
            return lax.fori_loop(j + 1, E, inner, st)
        return lax.fori_loop(0, E, outer, (ids, w))

    in_ids, in_w = jax.vmap(sort_node)(g.in_ids, g.in_w, g.in_cnt)
    out_ids, out_w = jax.vmap(sort_node)(g.out_ids, g.out_w, g.out_cnt)
    g = g._replace(in_ids=in_ids, in_w=in_w, out_ids=out_ids, out_w=out_w)

    # ---- reverse BFS max_remain ------------------------------------------
    remain = jnp.zeros(N, jnp.int32).at[C.SINK_NODE_ID].set(-1)
    out_degree = g.out_cnt
    rqueue = jnp.zeros(N, jnp.int32).at[0].set(C.SINK_NODE_ID)

    def rcond(st):
        head, tail, *_ = st
        return head < tail

    def rbody(st):
        head, tail, rqueue, remain, out_degree = st
        cur = rqueue[head]

        def set_remain(remain):
            # argmax-weight out edge: slot 0 after the weight-desc sort is NOT
            # sufficient (the reference scans original order with strict >),
            # but after sorting, slot 0 holds a maximal weight; the reference
            # computes remain AFTER the same sort, scanning slots in order
            # with strict >, which picks slot 0 of equal-max weights too.
            best = g.out_ids[cur, 0]
            return remain.at[cur].set(remain[best] + 1)
        remain = lax.cond(cur != C.SINK_NODE_ID, set_remain,
                          lambda r: r, remain)

        def push_ins(st):
            tail, rqueue, out_degree = st

            def in_body(k, st):
                tail, rqueue, out_degree = st
                in_id = g.in_ids[cur, k]
                out_degree = out_degree.at[in_id].add(-1)

                def push(st):
                    tail, rqueue = st
                    return tail + 1, rqueue.at[tail].set(in_id)
                tail, rqueue = lax.cond(out_degree[in_id] == 0, push,
                                        lambda s: s, (tail, rqueue))
                return tail, rqueue, out_degree
            return lax.fori_loop(0, g.in_cnt[cur], in_body, st)

        tail, rqueue, out_degree = lax.cond(
            cur != C.SRC_NODE_ID, push_ins, lambda s: s,
            (tail, rqueue, out_degree))
        return head + 1, tail, rqueue, remain, out_degree

    _, _, _, remain, _ = lax.while_loop(
        rcond, rbody, (jnp.int32(0), jnp.int32(1), rqueue, remain, out_degree))

    return g._replace(ok=ok), i2n, n2i, remain, ok


def ops_from_cigar(cigar, max_ops: int):
    """Host helper: packed 64-bit cigar -> forward (op, arg) stream rows for
    fuse_alignment. Returns (ops array, n_ops)."""
    import numpy as np
    rows = []
    for p in cigar:
        op = p & 0xF
        if op == C.CMATCH:
            rows.append((0, (p >> 34) & 0x3FFFFFFF))
        elif op in (C.CINS, C.CSOFT_CLIP, C.CHARD_CLIP):
            ln = (p >> 4) & 0x3FFFFFFF
            for _ in range(ln):
                rows.append((2, 0))
        elif op == C.CDEL:
            ln = (p >> 4) & 0x3FFFFFFF
            for _ in range(ln):
                rows.append((1, 0))
    n = min(len(rows), max_ops)
    ops = np.zeros((max_ops, 2), dtype=np.int32)
    if n:
        ops[:n] = rows[:n]
    return ops, n
