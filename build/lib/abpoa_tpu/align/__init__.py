from .result import AlignResult
from .dispatch import align_sequence_to_graph, align_sequence_to_subgraph

__all__ = ["AlignResult", "align_sequence_to_graph", "align_sequence_to_subgraph"]
