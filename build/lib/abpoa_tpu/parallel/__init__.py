from .runner import run_batch, shard_dp_batch
