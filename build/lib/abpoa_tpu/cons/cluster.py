"""Multi-allele read clustering via k-medoids over heterozygous MSA columns.

Reference: /root/reference/src/abpoa_output.c:650-1181. The pipeline:
candidate het columns from the MSA (>=2 alleles within frequency bounds,
deduplicated by identical read partition, priority-sorted by support) ->
het-weighted read-by-read distance matrix -> medoid init from het partitions ->
<=10 k-medoids iterations, with a cluster-count fallback loop.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from ..graph import POAGraph
from ..params import Params

INT_MAX = 2**31 - 1


@dataclass
class CandHetPos:
    pos: int = 0
    depth: int = 0
    var_type: int = 0  # 0: SNP, 1: indel
    count: int = 0
    n_uniq_alles: int = 0
    alle_bases: List[int] = field(default_factory=list)
    n_clu_reads: List[int] = field(default_factory=list)          # indexed by allele value
    clu_read_ids: List[List[int]] = field(default_factory=list)   # indexed by allele value
    read_id_to_allele_idx: List[int] = field(default_factory=list)


def collect_cand_het_pos(msa: List[np.ndarray], msa_l: int, n_seq: int, m: int,
                         min_het: int) -> Tuple[List[CandHetPos], List[int]]:
    """(src/abpoa_output.c:677-822)"""
    cand: List[CandHetPos] = []
    min_het = max(2, min_het // 2)
    min_hom = n_seq - min_het
    for i in range(msa_l):
        depth = [0] * (m + 1)
        appearance = [0] * (m + 1)
        for j in range(n_seq):
            b = int(msa[j][i])
            depth[b] += 1
            if depth[b] == 1:
                appearance[b] = j
        alleles = []
        total_depth = 0
        var_type = 0
        for j in range(m + 1):
            if min_het <= depth[j] <= min_hom:
                alleles.append(j)
                total_depth += depth[j]
                if j == m:
                    var_type = 1
        if len(alleles) < 2:
            continue
        alleles.sort(key=lambda a: appearance[a])
        allele_to_idx = {a: k for k, a in enumerate(alleles)}
        n_clu_reads = [0] * (m + 1)
        clu_read_ids: List[List[int]] = [[] for _ in range(m + 1)]
        for j in range(n_seq):
            b = int(msa[j][i])
            if b in allele_to_idx:
                clu_read_ids[b].append(j)
                n_clu_reads[b] += 1
        # dedup: same partition seen before? (searched newest-first)
        het_i = -1
        for k in range(len(cand) - 1, -1, -1):
            c = cand[k]
            if c.n_uniq_alles != len(alleles):
                continue
            same = True
            for x, y in zip(c.alle_bases, alleles):
                if c.n_clu_reads[x] != n_clu_reads[y] or c.clu_read_ids[x] != clu_read_ids[y]:
                    same = False
                    break
            if same:
                het_i = k
                break
        if het_i >= 0:
            cand[het_i].count += 1
            if var_type == 0:
                cand[het_i].var_type = 0
            continue
        c = CandHetPos(pos=i, depth=total_depth, var_type=var_type, count=1,
                       n_uniq_alles=len(alleles), alle_bases=list(alleles),
                       n_clu_reads=n_clu_reads, clu_read_ids=clu_read_ids,
                       read_id_to_allele_idx=[-1] * n_seq)
        for j in range(m + 1):
            for rid in clu_read_ids[j]:
                c.read_id_to_allele_idx[rid] = allele_to_idx[j]
        cand.append(c)
    # bubble sort priority by (count desc, depth desc, var_type: SNP first)
    prio = list(range(len(cand)))
    swapped = True
    while swapped:
        swapped = False
        for j in range(len(cand) - 1):
            a, b = cand[prio[j]], cand[prio[j + 1]]
            if (a.count < b.count
                    or (a.count == b.count and a.depth < b.depth)
                    or (a.count == b.count and a.depth == b.depth and a.var_type > b.var_type)):
                prio[j], prio[j + 1] = prio[j + 1], prio[j]
                swapped = True
    return cand, prio


def collect_dis_matrix(msa: List[np.ndarray], n_seq: int,
                       cand: List[CandHetPos]) -> np.ndarray:
    """Het-weighted pairwise distances (src/abpoa_output.c:824-863)."""
    dis = np.zeros((n_seq, n_seq), dtype=np.int64)
    for c in cand:
        pos = c.pos
        var_weight = 2 if c.var_type == 0 else 1
        col = np.array([int(msa[j][pos]) for j in range(n_seq)])
        valid = np.isin(col, c.alle_bases)
        for i in range(n_seq):
            if not valid[i]:
                continue
            diff = valid & (col != col[i])
            dis[i, diff] += var_weight * c.count
    return dis


def _partition_index(cand: List[CandHetPos], het_i: int, read_i: int) -> int:
    idx = 0
    for k in range(het_i + 1):
        idx = idx * (cand[k].n_uniq_alles + 1) + cand[k].read_id_to_allele_idx[read_i] + 1
    return idx


def _collect_2medoids(cand: List[CandHetPos], het_i: int, dis: np.ndarray,
                      med: List[int]) -> int:
    c = cand[het_i]
    max_dis, max_i, max_j = 0, -1, -1
    for i in range(c.n_uniq_alles - 1):
        ai = c.alle_bases[i]
        for j in range(i + 1, c.n_uniq_alles):
            aj = c.alle_bases[j]
            for r1 in c.clu_read_ids[ai]:
                for r2 in c.clu_read_ids[aj]:
                    if dis[r1, r2] > max_dis:
                        max_dis, max_i, max_j = int(dis[r1, r2]), r1, r2
    if max_dis > 0:
        med[0], med[1] = max_i, max_j
        return 2
    return 0


def _collect_1medoid(cand: List[CandHetPos], het_i: int, dis: np.ndarray,
                     n_seq: int, med: List[int], n_medoids: int) -> int:
    """(src/abpoa_output.c:904-971)"""
    assert n_medoids > 0
    partition_counts: dict[int, int] = {}
    for i in range(n_seq):
        pi = _partition_index(cand, het_i, i)
        partition_counts[pi] = partition_counts.get(pi, 0) + 1
    max_dis, max_read_i, max_count = 0, -1, -1
    med_partitions = [_partition_index(cand, het_i, med[j]) for j in range(n_medoids)]
    for i in range(n_seq):
        pi = _partition_index(cand, het_i, i)
        if pi in med_partitions:
            continue
        min_dis = min(int(dis[i, med[j]]) for j in range(n_medoids))
        cnt = partition_counts[pi]
        if cnt > max_count or (cnt == max_count and min_dis > max_dis):
            max_dis, max_read_i, max_count = min_dis, i, cnt
    if max_read_i == -1:
        c = cand[het_i]
        for i in range(c.n_uniq_alles):
            allele = c.alle_bases[i]
            for read_i in c.clu_read_ids[allele]:
                min_dis = INT_MAX
                skip = False
                for j in range(n_medoids):
                    if med[j] == read_i:
                        skip = True
                        continue
                    if int(dis[read_i, med[j]]) < min_dis:
                        min_dis = int(dis[read_i, med[j]])
                if min_dis > max_dis and not skip:
                    max_dis, max_read_i = min_dis, read_i
    if max_read_i != -1:
        if len(med) <= n_medoids:
            med.extend([-1] * (n_medoids + 1 - len(med)))
        med[n_medoids] = max_read_i
        return 1
    return 0


def _collect_multi_medoids(cand: List[CandHetPos], het_i: int, dis: np.ndarray,
                           n_seq: int, max_n_cons: int, med: List[int],
                           n_medoids: int) -> int:
    n_to_collect = min(cand[het_i].n_uniq_alles, max_n_cons)
    while n_medoids < n_to_collect:
        if n_medoids == 0:
            new = _collect_2medoids(cand, het_i, dis, med)
        else:
            new = _collect_1medoid(cand, het_i, dis, n_seq, med, n_medoids)
        if new == 0:
            break
        n_medoids += new
    return n_medoids


def _init_kmedoids(cand: List[CandHetPos], prio: List[int], dis: np.ndarray,
                   n_seq: int, max_n_cons: int, med: List[int]) -> int:
    assert max_n_cons >= 2
    n_medoids, het_i = 0, 0
    while n_medoids < max_n_cons:
        if n_medoids == 0:
            n_medoids = _collect_multi_medoids(cand, prio[het_i], dis, n_seq,
                                               max_n_cons, med, n_medoids)
        else:
            n_medoids += _collect_1medoid(cand, prio[het_i], dis, n_seq, med, n_medoids)
        het_i += 1
        if het_i >= len(prio):
            break
    return n_medoids


def _collect_kmedoids0(dis: np.ndarray, max_n_cons: int, clu_reads: List[List[int]],
                       medoids: List[int]) -> None:
    for i in range(max_n_cons):
        min_sum, min_read = INT_MAX, -1
        for j, read_i in enumerate(clu_reads[i]):
            s = sum(int(dis[read_i, r]) for k, r in enumerate(clu_reads[i]) if k != j)
            if s < min_sum:
                min_sum, min_read = s, read_i
        if min_read != -1:
            medoids[i] = min_read
    medoids.sort()


def _update_kmedoids(dis: np.ndarray, n_seq: int, max_n_cons: int,
                     medoids: List[int], clu_reads: List[List[int]],
                     n_clu_seqs: List[int]) -> Tuple[bool, List[int]]:
    new_medoids = [-1] * max_n_cons
    for i in range(max_n_cons):
        n_clu_seqs[i] = 0
        clu_reads[i].clear()
    for i in range(n_seq):
        min_dis, min_clu, tied = INT_MAX, -1, False
        for j in range(max_n_cons):
            d = int(dis[i, medoids[j]])
            if d < min_dis:
                min_dis, min_clu, tied = d, j, False
            elif d == min_dis:
                tied = True
        if min_clu == -1:
            continue
        if tied:
            # reference resolves ties by balancing the first two clusters
            min_clu = 0 if n_clu_seqs[0] < n_clu_seqs[1] else 1
        clu_reads[min_clu].append(i)
        n_clu_seqs[min_clu] += 1
    _collect_kmedoids0(dis, max_n_cons, clu_reads, new_medoids)
    changed = False
    for i in range(max_n_cons):
        if new_medoids[i] == -1:
            changed = False
            break
        if new_medoids[i] != medoids[i]:
            changed = True
    return changed, new_medoids


def clu_reads_kmedoids(cand: List[CandHetPos], prio: List[int], dis: np.ndarray,
                       n_seq: int, min_het: int, max_n_cons: int
                       ) -> Tuple[int, Optional[List[int]]]:
    """(src/abpoa_output.c:1089-1134). Returns (n_clusters, clu bitsets)."""
    to_collect, n_clusters = max_n_cons, 1
    clu_reads: List[List[int]] = [[] for _ in range(max_n_cons)]
    n_clu_seqs = [0] * max_n_cons
    while True:
        medoids = [-1] * to_collect
        if _init_kmedoids(cand, prio, dis, n_seq, to_collect, medoids) <= 0:
            break
        it = 0
        while True:
            changed, medoids = _update_kmedoids(dis, n_seq, to_collect, medoids,
                                                clu_reads, n_clu_seqs)
            it += 1
            if not changed or it >= 10:
                break
        n_clu = sum(1 for i in range(to_collect) if n_clu_seqs[i] >= min_het)
        n_clustered = sum(n_clu_seqs[:to_collect])
        if n_clu != to_collect or n_clustered < math.ceil(n_seq * 0.8):
            to_collect -= 1
            if to_collect < 2:
                break
        else:
            n_clusters = n_clu
            break
    if n_clusters == 1:
        return 1, None
    bits_list = []
    for i in range(n_clusters):
        bits = 0
        for rid in clu_reads[i]:
            bits |= 1 << rid
        bits_list.append(bits)
    return n_clusters, bits_list


def multip_read_clu_kmedoids(g: POAGraph, abpt: Params, n_seq: int
                             ) -> Tuple[int, Optional[List[int]]]:
    """Driver (src/abpoa_output.c:1136-1181)."""
    from .msa import collect_msa
    g.set_msa_rank()
    msa_l, msa = collect_msa(g, abpt, n_seq)
    min_w = max(2, math.ceil(n_seq * abpt.min_freq))
    cand, prio = collect_cand_het_pos(msa, msa_l, n_seq, abpt.m, min_w)
    if len(cand) < 1:
        return 1, None
    dis = collect_dis_matrix(msa, n_seq, cand)
    return clu_reads_kmedoids(cand, prio, dis, n_seq, min_w, abpt.max_n_cons)
