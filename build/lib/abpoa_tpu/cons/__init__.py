from .consensus import ConsensusResult, generate_consensus
from .msa import generate_rc_msa
