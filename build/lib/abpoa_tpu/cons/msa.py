"""Row-column MSA extraction from the POA graph.

Reference: /root/reference/src/abpoa_output.c:106-193 (abpoa_set_msa_seq /
abpoa_collect_msa / abpoa_generate_rc_msa).
"""
from __future__ import annotations

from typing import List

import numpy as np

from .. import constants as C
from ..graph import POAGraph
from ..params import Params
from .consensus import ConsensusResult, generate_consensus


def _scatter_node(g: POAGraph, node_id: int, rank: int, msa: List[np.ndarray]) -> None:
    """Write node base into msa[read][rank-1] for every read on an out edge."""
    node = g.nodes[node_id]
    base = node.base
    for bits in node.read_ids:
        while bits:
            lsb = bits & -bits
            read_id = lsb.bit_length() - 1
            msa[read_id][rank - 1] = base
            bits ^= lsb


def collect_msa(g: POAGraph, abpt: Params, n_seq: int) -> tuple[int, List[np.ndarray]]:
    """uint8 matrix of the MSA, gap encoded as abpt.m (src/abpoa_output.c:125-147)."""
    if g.node_n <= 2:
        return 0, []
    g.set_msa_rank()
    msa_len = int(g.node_id_to_msa_rank[C.SINK_NODE_ID]) - 1
    msa = [np.full(msa_len, abpt.m, dtype=np.uint8) for _ in range(n_seq)]
    for i in range(2, g.node_n):
        _scatter_node(g, i, g.msa_rank_of(i), msa)
    return msa_len, msa


def generate_rc_msa(g: POAGraph, abpt: Params, n_seq: int) -> ConsensusResult:
    """RC-MSA + (optionally) consensus rows (src/abpoa_output.c:150-193)."""
    if g.node_n <= 2:
        return ConsensusResult(n_seq=n_seq)
    g.set_msa_rank()
    if abpt.out_cons:
        abc = generate_consensus(g, abpt, n_seq)
    else:
        abc = ConsensusResult(n_seq=n_seq)
    msa_len, msa = collect_msa(g, abpt, n_seq)
    abc.msa_len = msa_len
    abc.msa_base = msa
    if abpt.out_cons:
        for cons_i in range(abc.n_cons):
            row = np.full(msa_len, abpt.m, dtype=np.uint8)
            for i, cur_id in enumerate(abc.cons_node_ids[cons_i]):
                rank = g.msa_rank_of(cur_id)
                row[rank - 1] = abc.cons_base[cons_i][i]
            abc.msa_base.append(row)
    return abc
