from .logging import vlog, set_verbose, timer, trace_annotation, run_stats
