"""Shared constants and alphabet tables.

Mirrors the reference's public constants (/root/reference/include/abpoa.h:6-50) and
the nucleotide / amino-acid encode/decode tables (/root/reference/src/abpoa_seq.c:15-98).
Tables are re-derived from their stated rules, not copied: nt encoding maps
A/a->0 C/c->1 G/g->2 T/t/U/u->3, everything else ->4, with the low bytes 0..3
mapping to themselves so already-encoded input is idempotent.
"""
from __future__ import annotations

import numpy as np

# alignment modes
GLOBAL_MODE = 0
LOCAL_MODE = 1
EXTEND_MODE = 2

# gap modes
LINEAR_GAP = 0
AFFINE_GAP = 1
CONVEX_GAP = 2

# default extra band parameters
EXTRA_B = 10
EXTRA_F = 0.01

# cigar ops (packed 64-bit cigar, see abpoa.h:45-50)
CIGAR_STR = "MIDXSH"
CMATCH = 0
CINS = 1
CDEL = 2
CDIFF = 3
CSOFT_CLIP = 4
CHARD_CLIP = 5

SRC_NODE_ID = 0
SINK_NODE_ID = 1

# output result modes
OUT_CONS = 0
OUT_MSA = 1
OUT_CONS_MSA = 2
OUT_GFA = 3
OUT_CONS_GFA = 4
OUT_CONS_FQ = 5

# consensus algorithms
CONS_HB = 0  # heaviest bundling
CONS_MF = 1  # most frequent (majority vote)

# verbosity ladder
VERBOSE_NONE = 0
VERBOSE_INFO = 1
VERBOSE_DEBUG = 2
VERBOSE_LONG_DEBUG = 3

# default scoring (abpoa_align.h:9-18)
DEFAULT_MATCH = 2
DEFAULT_MISMATCH = 4
DEFAULT_GAP_OPEN1 = 4
DEFAULT_GAP_OPEN2 = 24
DEFAULT_GAP_EXT1 = 2
DEFAULT_GAP_EXT2 = 1
DEFAULT_MMK = 19
DEFAULT_MMW = 10
DEFAULT_MIN_POA_WIN = 500
MULTIP_MIN_FREQ = 0.25

# supported gap-extension range: penalties must stay BELOW this bound.
# At -E>=64 (a gap column costing 32x a match) the reference binary
# crashes outright ("Error in lg_backtrack", abpoa_align_simd.c:116-194)
# and our native engine and the numpy oracle disagree on the optimal
# alignment (measured boundary: parity through 63, divergence from 64 —
# PERF.md round 10). The contract is therefore an explicit validation
# error, not a silent superset: Params.finalize() rejects the config.
MAX_GAP_EXT = 64

# backtrack op bitmask (abpoa_align.h:20-27)
M_OP = 0x1
E1_OP = 0x2
E2_OP = 0x4
E_OP = 0x6
F1_OP = 0x8
F2_OP = 0x10
F_OP = 0x18
ALL_OP = 0x1F


def _build_nt4_table() -> np.ndarray:
    t = np.full(256, 4, dtype=np.uint8)
    # idempotent for already-encoded bytes 0..3
    t[0], t[1], t[2], t[3] = 0, 1, 2, 3
    for ch, v in (("A", 0), ("C", 1), ("G", 2), ("T", 3), ("U", 3)):
        t[ord(ch)] = v
        t[ord(ch.lower())] = v
    return t


def _build_nt256_table() -> np.ndarray:
    # decode 0..5 -> 'ACGTN-'; printable input letters decode to themselves
    t = np.full(256, ord("N"), dtype=np.uint8)
    for i, ch in enumerate("ACGTN-"):
        t[i] = ord(ch)
    t[27] = ord("-")
    for ch in "ACGT":
        t[ord(ch)] = ord(ch)
        t[ord(ch.lower())] = ord(ch)
    t[ord("T") + 1] = ord("T")  # 'U'
    t[ord("t") + 1] = ord("T")  # 'u'
    return t


def _build_aa26_table() -> np.ndarray:
    # amino acid 5-bit-ish encoding (abpoa_seq.c:57-74): ACGTN share 0..4 with nt,
    # the remaining letters take 5..25 in alphabetical order, unknown -> 26
    t = np.full(256, 26, dtype=np.uint8)
    for i in range(27):
        t[i] = i
    order = {}
    nt = {"A": 0, "C": 1, "G": 2, "T": 3, "N": 4}
    nxt = 5
    for ch in "ABCDEFGHIJKLMNOPQRSTUVWXYZ":
        if ch in nt:
            order[ch] = nt[ch]
        else:
            order[ch] = nxt
            nxt += 1
    for ch, v in order.items():
        t[ord(ch)] = v
        t[ord(ch.lower())] = v
    return t


def _build_aa256_table() -> np.ndarray:
    t = np.full(256, ord("*"), dtype=np.uint8)
    inv = {}
    nt = {0: "A", 1: "C", 2: "G", 3: "T", 4: "N"}
    nxt = 5
    for ch in "ABCDEFGHIJKLMNOPQRSTUVWXYZ":
        if ch in "ACGTN":
            continue
        inv[nxt] = ch
        nxt += 1
    inv.update(nt)
    for v, ch in inv.items():
        t[v] = ord(ch)
    t[26] = ord("*")
    t[27] = ord("-")
    for ch in "ABCDEFGHIJKLMNOPQRSTUVWXYZ":
        t[ord(ch)] = ord(ch)
        t[ord(ch.lower())] = ord(ch)
    return t


NT4_TABLE = _build_nt4_table()
NT256_TABLE = _build_nt256_table()
AA26_TABLE = _build_aa26_table()
AA256_TABLE = _build_aa256_table()
