"""Alignment/consensus parameter object.

Mirrors the reference's 3-stage parameter lifecycle (`abpoa_init_para` defaults at
/root/reference/src/abpoa_align.c:101-158, user mutation, `abpoa_post_set_para`
derivation at :160-185): construct `Params()`, mutate fields, call `finalize()`.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from . import constants as C


def gen_simple_mat(m: int, match: int, mismatch: int) -> np.ndarray:
    """Match/mismatch scoring matrix (reference: src/abpoa_align.c:13-26).

    Row/col m-1 is the ambiguous base ('N'): score 0 against everything.
    """
    match = abs(match)
    mismatch = -abs(mismatch)
    mat = np.full((m, m), mismatch, dtype=np.int32)
    np.fill_diagonal(mat, match)
    mat[:, m - 1] = 0
    mat[m - 1, :] = 0
    return mat


def parse_mat_file(path: str, m: int) -> np.ndarray:
    """Parse a scoring-matrix file (BLOSUM62-style; reference src/abpoa_align.c:35-86)."""
    mat = np.zeros((m, m), dtype=np.int32)
    order: list[int] = []
    first = True
    with open(path) as fp:
        for line in fp:
            if line.startswith("#"):
                continue
            if first:
                first = False
                for ch in line.split():
                    order.append(int(C.AA26_TABLE[ord(ch[0])]))
            else:
                toks = line.split()
                if not toks:
                    continue
                row = int(C.AA26_TABLE[ord(toks[0][0])])
                if row >= m:
                    raise ValueError(f"Unknown base in matrix file: {toks[0]}")
                for n, tok in enumerate(toks[1:]):
                    mat[row, order[n]] = int(tok)
    return mat


@dataclass
class Params:
    # alignment mode
    align_mode: int = C.GLOBAL_MODE
    gap_mode: int = C.CONVEX_GAP  # derived in finalize()
    zdrop: int = -1
    end_bonus: int = -1

    inc_path_score: bool = False
    sort_input_seq: bool = False
    put_gap_on_right: bool = False
    put_gap_at_end: bool = False

    # adaptive band
    wb: int = C.EXTRA_B
    wf: float = C.EXTRA_F

    amb_strand: bool = False
    ret_cigar: bool = True
    rev_cigar: bool = False
    out_cons: bool = True
    out_fq: bool = False
    out_gfa: bool = False
    out_msa: bool = False
    cons_algrm: int = C.CONS_HB
    max_n_cons: int = 1
    sub_aln: bool = False
    min_freq: float = C.MULTIP_MIN_FREQ
    use_read_ids: bool = False
    incr_fn: Optional[str] = None
    out_pog: Optional[str] = None

    # alphabet size: 5 = nucleotide, 27 = amino acid
    m: int = 5

    # scoring
    use_score_matrix: bool = False
    mat_fn: Optional[str] = None
    match: int = C.DEFAULT_MATCH
    mismatch: int = C.DEFAULT_MISMATCH
    gap_open1: int = C.DEFAULT_GAP_OPEN1
    gap_open2: int = C.DEFAULT_GAP_OPEN2
    gap_ext1: int = C.DEFAULT_GAP_EXT1
    gap_ext2: int = C.DEFAULT_GAP_EXT2

    use_qv: bool = False
    disable_seeding: bool = True
    k: int = C.DEFAULT_MMK
    w: int = C.DEFAULT_MMW
    min_w: int = C.DEFAULT_MIN_POA_WIN
    progressive_poa: bool = False

    verbose: int = C.VERBOSE_NONE
    batch_index: int = 0

    # device backend for the DP kernel: "auto" resolves at finalize() to the
    # fastest available engine (accelerator > native C++ > numpy oracle),
    # mirroring the reference's runtime ISA dispatch; explicit "numpy",
    # "native", "jax", "pallas" pin an engine
    device: str = "auto"

    # lockstep multi-set batching policy for `-l`/msa_batch: "auto" vmaps
    # K sets only when a real accelerator mesh is attached (serial K=1 is
    # faster on CPU — ROUND8_NOTES.md / BENCH_lockstep_cpu.json); "on"/
    # "off" force it (see parallel.lockstep_enabled, CLI --lockstep)
    lockstep: str = "auto"

    # supervised worker-process count for `-l` multi-set runs (CLI
    # --workers, env ABPOA_TPU_WORKERS): 0 = auto (one per core on
    # multicore CPU hosts, 1 under lockstep/accelerator), 1 = in-process
    # serial, N = pool of N spawned engines (parallel/pool.py)
    workers: int = 0

    # derived (set by finalize)
    mat: np.ndarray = field(default=None, repr=False)  # type: ignore[assignment]
    max_mat: int = 0
    min_mis: int = 0
    _finalized: bool = field(default=False, repr=False)

    def finalize(self) -> "Params":
        """Derive gap mode / tables / matrices (reference abpoa_post_set_para)."""
        # gap mode inference (src/abpoa_align.c:88-99)
        if min(self.match, self.mismatch, self.gap_open1, self.gap_open2,
               self.gap_ext1, self.gap_ext2) < 0:
            raise ValueError("negative scoring parameters")
        if self.gap_ext1 == 0 and self.gap_ext2 == 0:
            raise ValueError("at least one gap extension must be positive")
        if max(self.gap_ext1, self.gap_ext2) >= C.MAX_GAP_EXT:
            # the documented -E contract (ROADMAP item 5 / PERF.md round
            # 10): the reference crashes in this regime (lg_backtrack) and
            # the in-tree engines diverge from exactly 64 up, so the
            # config is rejected instead of silently mis-scoring
            raise ValueError(
                f"gap extension penalty "
                f"{max(self.gap_ext1, self.gap_ext2)} is outside the "
                f"supported range (must be < {C.MAX_GAP_EXT}): the "
                "reference implementation crashes for -E>=64 and the "
                "banded engines diverge there; use a smaller extension "
                "penalty")
        if self.gap_open1 == 0:
            self.gap_mode = C.LINEAR_GAP
        elif self.gap_open2 == 0:
            self.gap_mode = C.AFFINE_GAP
        else:
            self.gap_mode = C.CONVEX_GAP

        if self.out_msa or self.out_gfa or self.max_n_cons > 1 or self.cons_algrm == C.CONS_MF:
            self.use_read_ids = True
        if self.align_mode == C.LOCAL_MODE:
            self.wb = -1
        if self.m > 5 and self.k > 11:  # aa sequences: smaller minimizers
            self.k, self.w = 7, 4

        if not self.use_score_matrix:
            self.mat = gen_simple_mat(self.m, self.match, self.mismatch)
            self.max_mat = abs(self.match)
            self.min_mis = abs(self.mismatch)
        else:
            assert self.mat_fn is not None
            self.mat = parse_mat_file(self.mat_fn, self.m)
            self.max_mat = int(self.mat.max())
            self.min_mis = int((-self.mat).max())
        if self.device == "auto":
            from .align.dispatch import resolve_auto_device
            self.device = resolve_auto_device()
        self._finalized = True
        return self

    @property
    def is_aa(self) -> bool:
        return self.m > 5

    @property
    def char_to_code(self) -> np.ndarray:
        return C.AA26_TABLE if self.is_aa else C.NT4_TABLE

    @property
    def code_to_char(self) -> np.ndarray:
        return C.AA256_TABLE if self.is_aa else C.NT256_TABLE

    @property
    def gap_oe1(self) -> int:
        return self.gap_open1 + self.gap_ext1

    @property
    def gap_oe2(self) -> int:
        return self.gap_open2 + self.gap_ext2
