"""MSA orchestration: the progressive POA loop and output fan-out.

Reference: /root/reference/src/abpoa_align.c (abpoa_poa :313-353,
abpoa_msa :402-472, abpoa_msa1 :474-540, abpoa_output :355-371).
"""
from __future__ import annotations

import sys
import time
from dataclasses import dataclass, field
from typing import IO, List, Optional

import numpy as np

from . import constants as C
from . import obs
from .align import align_sequence_to_graph, AlignResult
from .cons.consensus import ConsensusResult, generate_consensus
from .cons.msa import generate_rc_msa
from .graph import POAGraph
from .io.fastx import read_fastx
from .io.output import generate_gfa, output_fx_consensus, output_rc_msa
from .params import Params


@dataclass
class Abpoa:
    """Top-level container (reference abpoa_t): graph + sequence metadata."""
    graph: POAGraph = field(default_factory=POAGraph)
    names: List[str] = field(default_factory=list)
    comments: List[str] = field(default_factory=list)
    quals: List[Optional[str]] = field(default_factory=list)
    seqs: List[str] = field(default_factory=list)
    is_rc: List[bool] = field(default_factory=list)
    cons: Optional[ConsensusResult] = None

    @property
    def n_seq(self) -> int:
        return len(self.seqs)

    def reset(self) -> None:
        self.graph.reset()
        self.names, self.comments, self.quals = [], [], []
        self.seqs, self.is_rc = [], []
        self.cons = None

    def append_read(self, name: str = "", comment: str = "",
                    qual: Optional[str] = None, seq: str = "",
                    is_rc: bool = False) -> None:
        self.names.append(name)
        self.comments.append(comment)
        self.quals.append(qual)
        self.seqs.append(seq)
        self.is_rc.append(is_rc)


def _rc_encode(seq: np.ndarray) -> np.ndarray:
    rc = seq[::-1].copy()
    lt4 = rc < 4
    rc[lt4] = 3 - rc[lt4]
    rc[~lt4] = 4
    return rc


def _band_cols(abpt: Params, qlen: int) -> int:
    """Telemetry band-extent model for one per-read dispatch: the adaptive
    band's planned window (2w+1 columns, the reference's band formula),
    clipped to the full query when banding is off or wider than the row."""
    if abpt.wb < 0:
        return qlen + 1
    w = abpt.wb + int(abpt.wf * qlen)
    return min(qlen + 1, 2 * w + 1)


def poa(ab: Abpoa, abpt: Params, seqs: List[np.ndarray], weights: List[np.ndarray],
        exist_n_seq: int, fallback: Optional[str] = None) -> None:
    """Plain progressive POA, input order (src/abpoa_align.c:313-353).

    fallback: per-read-record attribution label when this host loop is
    itself a fallback from a bypassed device path."""
    g = ab.graph
    n_seq = len(seqs)
    tot_n_seq = exist_n_seq + n_seq
    for i in range(n_seq):
        qseq, weight = seqs[i], weights[i]
        qlen = len(qseq)
        read_id = exist_n_seq + i
        t_read = time.perf_counter()
        res = AlignResult()
        if g.node_n > 2:
            obs.record_dp(g.node_n, _band_cols(abpt, qlen), abpt.gap_mode)
            with obs.phase("align"):
                res = align_sequence_to_graph(g, abpt, qseq)
                if abpt.amb_strand and res.best_score < min(qlen, g.node_n - 2) * abpt.max_mat * 0.3333:
                    rc_qseq = _rc_encode(qseq)
                    rc_weight = weight[::-1].copy()
                    # the rc retry is a second full DP pass
                    obs.record_dp(g.node_n, _band_cols(abpt, qlen),
                                  abpt.gap_mode)
                    rc_res = align_sequence_to_graph(g, abpt, rc_qseq)
                    if rc_res.best_score > res.best_score:
                        res = rc_res
                        qseq, weight = rc_qseq, rc_weight
                        ab.is_rc[read_id] = True
        with obs.phase("fusion"):
            g.add_alignment(abpt, qseq, weight, None, res.cigar, read_id, tot_n_seq, True)
        dt = time.perf_counter() - t_read
        from .align.dispatch import telemetry_backend
        backend, auto_fb = telemetry_backend(abpt)
        obs.record_read(dt, qlen, _band_cols(abpt, qlen), backend,
                        fallback=fallback or auto_fb)
        obs.trace.add_span(f"read:{read_id}", "read", t_read, dt,
                           args={"qlen": qlen})


def _run_fused_device(ab: Abpoa, abpt: Params, seqs, weights,
                      exist_n_seq: int) -> bool:
    """Route the plain progressive loop through the single-dispatch all-device
    path when the device backend is selected and the config is in scope
    (align/fused_loop.py). Returns False to fall back to the per-read loop."""
    if abpt.device not in ("jax", "tpu", "pallas"):
        return False
    from .utils.probe import (apply_platform_pin, jax_backend_reachable,
                              warn_unreachable_once)
    if not jax_backend_reachable():
        warn_unreachable_once(
            "Warning: JAX backend probe timed out (wedged accelerator "
            "tunnel?); falling back to the host engine.")
        obs.count("fallback.jax_probe_timeout")
        return False
    apply_platform_pin()
    from . import resilience as rz
    backend = "jax" if abpt.device == "tpu" else abpt.device
    if rz.enabled() and rz.breaker().is_open(backend):
        # the breaker already demoted this backend for the run: go
        # straight to the host loop instead of re-failing the dispatch
        obs.count("fallback.fused_breaker_open")
        return False
    from .align.eligibility import fused_eligible
    if not fused_eligible(abpt, len(seqs)):
        return False
    from .align.fused_loop import plan_dispatch_footprint, progressive_poa_fused
    if rz.enabled():
        # memory admission: a set whose planes exceed the device budget is
        # demoted to the host loop up front instead of OOMing mid-run
        decision, est, budget = rz.memory.admit(
            plan_dispatch_footprint(abpt, [seqs]))
        if decision != "ok":
            obs.record_fault("admission", backend=backend,
                             detail=f"estimated {est} B > budget {budget} B",
                             action="demote_host")
            return False
    init_graph = None
    if exist_n_seq:
        # incremental `-i`: extend the restored graph on device; read-id
        # outputs still need the host loop (bitset replay cannot cover the
        # restored reads' edges)
        if abpt.use_read_ids:
            return False
        g = ab.graph
        if getattr(g, "is_native", False):
            g = g.to_python(abpt)
        if g.node_n > 2:
            init_graph = g
    t0 = time.perf_counter()
    try:
        with obs.phase("align_fused"):
            # the resilience envelope: injection points, watchdog deadline,
            # classified fault records + circuit breaker, bounded retry
            pg, _, is_rc = rz.guarded_device_call(
                "fused_loop", backend,
                lambda: progressive_poa_fused(seqs, weights, abpt,
                                              init_graph=init_graph))
    except (rz.DispatchFailed, RuntimeError) as e:
        print(f"Warning: fused device loop failed ({e}); "
              "falling back to the per-read loop.", file=sys.stderr)
        obs.count("fallback.fused_to_host")
        return False
    # per-read latency records for the one-dispatch path: the fused wall
    # split evenly across its reads (marked amortized — a share, not an
    # independent measurement)
    per_read = (time.perf_counter() - t0) / max(1, len(seqs))
    for s in seqs:
        obs.record_read(per_read, len(s), _band_cols(abpt, len(s)),
                        abpt.device, amortized=True)
    ab.graph = pg
    if abpt.amb_strand:
        for i, flag in enumerate(is_rc):
            ab.is_rc[exist_n_seq + i] = flag
    return True


def _want_native(abpt: Params) -> bool:
    # native host core pairs with the device kernel; the numpy oracle reads
    # Python Node objects directly
    if abpt.device == "native":
        return True
    # device paths with a native host graph: -G needs per-edge path scores
    # the jax table builder only derives from Python graphs
    # (jax_backend.py:306), so those configs keep the Python graph
    return (abpt.device in ("jax", "tpu", "pallas")
            and not abpt.inc_path_score and abpt.zdrop <= 0)


def _ingest_records(ab: Abpoa, abpt: Params, records):
    """Append records to `ab` (sorting per `-s`), encode sequences, derive
    qv weights (reference abpoa_msa1 read/encode block,
    src/abpoa_align.c:493-506). Returns (seqs, weights) for the new reads."""
    exist_n_seq = ab.n_seq
    for rec in records:
        ab.append_read(rec.name, rec.comment, rec.qual, rec.seq)
    n_seq = len(records)
    if abpt.sort_input_seq:
        order = sorted(range(n_seq), key=lambda i: -len(records[i].seq))
        for attr in ("names", "comments", "quals", "seqs"):
            lst = getattr(ab, attr)
            lst[exist_n_seq:] = [lst[exist_n_seq + i] for i in order]

    encode = abpt.char_to_code
    seqs: List[np.ndarray] = []
    weights: List[np.ndarray] = []
    for i in range(n_seq):
        s = ab.seqs[exist_n_seq + i]
        arr = encode[np.frombuffer(s.encode(), dtype=np.uint8)].astype(np.uint8)
        seqs.append(arr)
        qual = ab.quals[exist_n_seq + i]
        if abpt.use_qv and qual:
            weights.append(np.frombuffer(qual.encode(), dtype=np.uint8).astype(np.int64) - 32)
        else:
            weights.append(np.ones(len(arr), dtype=np.int64))
    return seqs, weights


def plain_route(abpt: Params) -> bool:
    """True when the progressive loop runs in input order (no seeding/guide
    tree) — the route the fused device loop covers."""
    return ((abpt.disable_seeding and not abpt.progressive_poa)
            or abpt.align_mode != C.GLOBAL_MODE)


def _device_ineligible_reason(abpt: Params) -> Optional[str]:
    """A device config the fused loop excludes would otherwise fall to
    per-alignment device dispatches — the link-latency regime (~140 ms per
    read over a remote tunnel). Those configs run the native host kernel
    instead (reference behavior: one engine end to end)."""
    if abpt.device not in ("jax", "tpu", "pallas") or not plain_route(abpt):
        return None
    if abpt.incr_fn and abpt.use_read_ids:
        # fused loop can't replay restored reads' edge bitsets; without a
        # reroute this would fall to per-read device dispatches
        return "incremental MSA with read-id outputs"
    from .align.eligibility import fused_config_eligible
    if fused_config_eligible(abpt):
        return None
    if abpt.inc_path_score:
        return "-G/path-score mode"
    if abpt.use_qv and abpt.max_n_cons > 1:
        return "qv-weighted multi-consensus"
    if not abpt.ret_cigar:
        return "cigar-free alignment"
    return "unbanded device config"


_REROUTE_WARNED = False


def _reroute_device_ineligible(abpt: Params) -> Optional[str]:
    """Returns the original device name when rerouted, else None."""
    global _REROUTE_WARNED
    reason = _device_ineligible_reason(abpt)
    if reason is None:
        return None
    try:
        from .native import load
        host = "native" if load() is not None else "numpy"
    except (ImportError, OSError, RuntimeError) as e:
        obs.record_fault("backend_init", backend="native",
                         detail=str(e)[:200], action="numpy")
        host = "numpy"
    if not _REROUTE_WARNED:
        print(f"Warning: {reason} is outside the fused device loop; "
              f"using the {host} host kernel for this configuration.",
              file=sys.stderr)
        _REROUTE_WARNED = True
    obs.count("reroute.device_ineligible")
    obs.count("reroute." + reason.replace(" ", "_"))
    orig, abpt.device = abpt.device, host
    return orig


def msa(ab: Abpoa, abpt: Params, records, out_fp: IO[str]) -> None:
    """File-level driver (reference abpoa_msa1)."""
    assert abpt._finalized, "call Params.finalize() first"
    # malformed-input hardening: a poisoned set raises a structured
    # PoisonedSetError here (quarantined by `-l` / batch callers, a
    # one-line error + rc=1 from the single-file CLI) — never a traceback
    # out of the alignment core, never a partial silent result
    from .resilience import validate_records
    validate_records(records, abpt)
    orig_device = _reroute_device_ineligible(abpt)
    try:
        _msa_inner(ab, abpt, records, out_fp)
    finally:
        if orig_device is not None:
            abpt.device = orig_device


def _msa_inner(ab: Abpoa, abpt: Params, records, out_fp: IO[str]) -> None:
    # first call in a process pays the graph-engine setup (native .so
    # stat/dlopen + ctypes signature registration) — attribute it, or a
    # cold CLI run shows 20-30ms of unexplained wall
    with obs.phase("backend_init"):
        if _want_native(abpt) and not getattr(ab.graph, "is_native", False):
            try:
                from .native.graph import NativePOAGraph
                ab.graph = NativePOAGraph()
            except (ImportError, OSError, RuntimeError) as e:
                # no native build: the Python graph engine serves — counted
                # so a broken .so can't silently eat the fast path
                obs.count("fallback.native_graph_unavailable")
                obs.record_fault("backend_init", backend="native",
                                 detail=str(e)[:200], action="python_graph")
        elif not _want_native(abpt) and getattr(ab.graph, "is_native", False):
            ab.graph = POAGraph()
        ab.reset()
    if abpt.incr_fn:
        from .io.restore import restore_graph
        restore_graph(ab, abpt)
    exist_n_seq = ab.n_seq
    seqs, weights = _ingest_records(ab, abpt, records)

    if plain_route(abpt):
        if not _run_fused_device(ab, abpt, seqs, weights, exist_n_seq):
            # the reads now run per-read dispatches instead of the one
            # fused dispatch — attribute that on every record
            fb = ("fused_bypass"
                  if abpt.device in ("jax", "tpu", "pallas") else None)
            poa(ab, abpt, seqs, weights, exist_n_seq, fallback=fb)
    else:
        from .seed import anchor_poa_pipeline
        anchor_poa_pipeline(ab, abpt, seqs, weights, exist_n_seq)

    output(ab, abpt, out_fp)


def _native_cons_fast_path(ab: Abpoa, abpt: Params, out_fp: IO[str]) -> bool:
    """Default consensus output straight from the native graph (C++
    heaviest bundling, native/host_core.cpp apg_cons_hb): skips the O(V+E)
    to_python export, which dominated short-read-set wall time. Covers the
    single-cluster read-count-weight config only; everything else falls
    through to the Python consensus over the exported graph."""
    g = ab.graph
    from .cons.consensus import native_consensus_hb, native_hb_eligible
    if not native_hb_eligible(g, abpt) or abpt.out_gfa or abpt.out_pog:
        return False
    with obs.phase("consensus"):
        abc = native_consensus_hb(g, ab.n_seq)
    from .resilience import enabled as rz_enabled
    from .resilience.guards import consensus_violation
    if rz_enabled():
        viol = consensus_violation(abc, abpt.m)
        if viol is not None:
            # one-shot re-run on the Python consensus walk (the reference
            # semantics) instead of emitting out-of-alphabet bases
            obs.count("guard.consensus_violation")
            obs.record_fault("garbage_output", backend="native",
                             detail=viol, action="python_consensus")
            return False
    if abc.n_cons == 0:
        print("Warning: no consensus sequence generated.", file=sys.stderr)
    ab.cons = abc
    with obs.phase("output"):
        output_fx_consensus(abc, abpt, out_fp)
    return True


def output(ab: Abpoa, abpt: Params, out_fp: IO[str]) -> None:
    """(src/abpoa_align.c:355-371)"""
    if _native_cons_fast_path(ab, abpt, out_fp):
        return
    g = ab.graph
    if getattr(g, "is_native", False):
        with obs.phase("graph_export"):
            g = g.to_python(abpt)  # output-time consumers walk Python nodes
    if abpt.out_gfa:
        with obs.phase("output"):
            generate_gfa(g, abpt, ab.names, ab.is_rc,
                         lambda: generate_consensus(g, abpt, ab.n_seq), out_fp)
    else:
        with obs.phase("consensus"):
            if abpt.out_msa:
                ab.cons = generate_rc_msa(g, abpt, ab.n_seq)
            elif abpt.out_cons:
                ab.cons = generate_consensus(g, abpt, ab.n_seq)
                if not g.is_called_cons:
                    print("Warning: no consensus sequence generated.",
                          file=sys.stderr)
        with obs.phase("output"):
            if abpt.out_msa:
                output_rc_msa(ab.cons, abpt, ab.names, ab.is_rc, out_fp)
            elif abpt.out_cons:
                output_fx_consensus(ab.cons, abpt, out_fp)
    if abpt.out_pog:
        from .io.plot import dump_pog
        dump_pog(ab, abpt)


def msa_from_file(ab: Abpoa, abpt: Params, path: str, out_fp: IO[str]) -> None:
    if not (abpt.out_msa or abpt.out_cons or abpt.out_gfa):
        return
    records = read_fastx(path)
    msa(ab, abpt, records, out_fp)
