"""Backend dispatch for the DP kernel.

TPU-native analog of the reference's runtime CPUID dispatch
(/root/reference/src/abpoa_dispatch_simd.c:59-82): the `device` field of
`Params` selects the kernel implementation. "numpy" is the host oracle;
"jax"/"pallas" run the banded DP on the accelerator (registered lazily so the
package imports without a TPU present).

Every dispatch runs through the resilience envelope (abpoa_tpu/resilience):
resolution consults the per-backend circuit breaker (an open breaker demotes
pallas -> jax -> native -> numpy for the rest of the run), device dispatches
run under a watchdog deadline with classified-fault retry, results pass the
output sanity guards, and any absorbed failure triggers a one-shot host
re-run plus a `faults` record — never a silent wrong answer, never a dropped
read.
"""
from __future__ import annotations

from typing import Callable, Dict

import numpy as np

from .. import constants as C
from ..graph import POAGraph
from ..params import Params
from .oracle import align_sequence_to_subgraph_numpy
from .result import AlignResult

_BACKENDS: Dict[str, Callable] = {}

# backend name the most recent _resolve/_host_rerun actually selected —
# differs from Params.device after a probe-timeout fallback, a circuit-
# breaker demotion, or a fault-triggered host re-run, and telemetry labels
# (per-read records, dp spans) must use it, not the requested device.
# `reason` says why they differ.
_LAST_RESOLVED = {"name": "", "reason": None}


def last_resolved(default: str = "") -> str:
    return _LAST_RESOLVED["name"] or default


def telemetry_backend(abpt: Params) -> tuple:
    """(backend, fallback_reason) for per-read records: the kernel the
    last dispatch actually ran, plus the reroute reason when a different
    backend was requested ('probe_timeout' for the liveness-probe
    fallback, 'breaker_open' after a circuit-breaker demotion,
    'host_rerun' for a one-shot fault/guard re-run). The resolution state
    is reset by start_run so runs don't inherit stale labels."""
    req = "jax" if abpt.device == "tpu" else abpt.device
    got = last_resolved(req)
    if got == req:
        return got, None
    return got, _LAST_RESOLVED["reason"] or "rerouted"


def resolve_auto_device() -> str:
    """Pick the fastest available engine, the analog of the reference's
    startup ISA auto-selection (src/abpoa_dispatch_simd.c:59-82): a live
    accelerator wins, then the native C++ host kernel, then the numpy
    oracle. Called once per `Params.finalize()` for `device="auto"`; the
    probe result is process-cached so repeated finalizes stay cheap."""
    from ..utils.probe import has_accelerator
    if has_accelerator():
        # "jax" (the fused XLA-scan loop) until on-chip measurements prove
        # the Pallas kernels faster end-to-end (BENCH_onchip.json)
        return "jax"
    try:
        from ..native import load
        if load() is not None:
            return "native"
    except (ImportError, OSError, RuntimeError) as e:
        from ..obs import record_fault
        record_fault("backend_init", backend="native",
                     detail=str(e)[:200], action="auto_numpy")
    return "numpy"


def register_backend(name: str, fn: Callable) -> None:
    _BACKENDS[name] = fn


register_backend("numpy", align_sequence_to_subgraph_numpy)


def _load_native_or_numpy() -> str:
    """Register and return the best host backend name; faults are counted,
    never eaten (the satellite contract: a broken native build is a
    `faults` record + numpy fallback, not a silent pass)."""
    try:
        from . import native_backend  # noqa: F401  registers "native"
        return "native"
    except (ImportError, OSError, RuntimeError) as e:
        from ..obs import count, record_fault
        count("fallback.native_unavailable")
        record_fault("backend_init", backend="native",
                     detail=str(e)[:200], action="numpy")
        return "numpy"


def _resolve(abpt: Params) -> Callable:
    from ..obs import count
    from ..resilience.breaker import breaker
    name = "jax" if abpt.device == "tpu" else abpt.device
    reason = None
    # the circuit breaker demotes a failing backend until its half-open
    # cooldown elapses (resilience/breaker.py warns + reports the open,
    # once); effective() names the original backend again once a probe
    # is allowed, so guarded_device_call can claim the permit from here
    eff = breaker().effective(name)
    if eff != name:
        count(f"breaker.demoted.{name}")
        name = eff
        reason = "breaker_open"
    if name in _BACKENDS:
        _LAST_RESOLVED["name"] = name
        _LAST_RESOLVED["reason"] = reason
        count(f"dispatch.{name}")
        return _BACKENDS[name]
    if name in ("jax", "pallas", "native"):
        if name == "native":
            name = _load_native_or_numpy()
        else:
            # a wedged accelerator tunnel hangs the first in-process
            # jax.devices() forever; probe out-of-process first so the CLI
            # degrades to the host kernel instead (the reference's dispatch
            # can never hang, src/abpoa_dispatch_simd.c:56-78)
            from ..utils.probe import (apply_platform_pin,
                                       jax_backend_reachable,
                                       warn_unreachable_once)
            if not jax_backend_reachable():
                warn_unreachable_once(
                    "Warning: JAX backend probe timed out (wedged "
                    "accelerator tunnel?); using the host kernel.")
                count("fallback.jax_probe_timeout")
                name = _load_native_or_numpy()
                _LAST_RESOLVED["name"] = name
                _LAST_RESOLVED["reason"] = "probe_timeout"
                count(f"dispatch.{name}")
                return _BACKENDS[name]
            apply_platform_pin()
            from . import jax_backend  # lazy: registers "jax"
            if name == "pallas":
                from . import pallas_backend  # registers "pallas"
        if name in _BACKENDS:
            _LAST_RESOLVED["name"] = name
            _LAST_RESOLVED["reason"] = reason
            count(f"dispatch.{name}")
            return _BACKENDS[name]
    raise ValueError(f"Unknown DP backend: {abpt.device}")


def _numpy_view(g: POAGraph, abpt: Params) -> POAGraph:
    """The oracle walks Python Node objects; when the run's graph engine
    is native (a device/native config deep in the degradation ladder),
    export a read-only copy. Node ids are preserved, so the resulting
    cigar fuses back into the original graph. Re-sorted on the Python
    side: the export carries the topo order but not the adaptive-band
    position arrays the oracle needs. Fault path only — never hot."""
    if not getattr(g, "is_native", False):
        return g
    g2 = g.to_python(abpt)
    g2.is_topological_sorted = False
    g2.topological_sort(abpt)
    return g2


def _host_rerun(g: POAGraph, abpt: Params, beg_node_id: int,
                end_node_id: int, query: np.ndarray,
                exclude: str = "") -> AlignResult:
    """One-shot host re-run after a failed/garbage dispatch: native when
    available (and not itself the failed backend), else the numpy oracle
    — the authoritative floor of the degradation ladder."""
    from ..obs import count
    for cand in ("native", "numpy"):
        if cand == exclude:
            continue
        if cand == "native" and _load_native_or_numpy() != "native":
            continue
        fn = _BACKENDS.get(cand)
        if fn is None:
            continue
        g2 = _numpy_view(g, abpt) if cand == "numpy" else g
        count(f"dispatch.rerun.{cand}")
        _LAST_RESOLVED["name"] = cand
        _LAST_RESOLVED["reason"] = "host_rerun"
        return fn(g2, abpt, beg_node_id, end_node_id, query)
    raise RuntimeError("no host backend available for the re-run")


def _dispatch_resilient(fn: Callable, name: str, g: POAGraph, abpt: Params,
                        beg_node_id: int, end_node_id: int,
                        query: np.ndarray) -> AlignResult:
    """One DP dispatch under the resilience envelope: injection points,
    watchdog (device backends only — host kernels cannot hang and must
    not pay a thread spawn per read), fault classification + breaker, the
    output guards, and the one-shot host re-run."""
    from .. import resilience as rz
    if name == "numpy":
        # the numpy oracle is the degradation ladder's floor and the
        # correctness reference: nothing to demote to, nothing to guard
        # against — its errors are real bugs and must propagate. It can
        # be reached with a native graph engine (breaker walked the whole
        # ladder mid-run), hence the view shim.
        return fn(_numpy_view(g, abpt), abpt, beg_node_id, end_node_id,
                  query)
    if not rz.enabled():
        return fn(g, abpt, beg_node_id, end_node_id, query)
    from ..obs import count, record_fault
    try:
        res = rz.guarded_device_call(
            f"dp:{name}", name,
            lambda: fn(g, abpt, beg_node_id, end_node_id, query))
    except rz.DispatchFailed:
        count("fallback.dp_host_rerun")
        return _host_rerun(g, abpt, beg_node_id, end_node_id, query,
                           exclude=name)
    res = rz.inject.corrupt_result(res)
    viol = rz.guards.align_result_violation(res, len(query), g.node_n, abpt)
    if viol is not None:
        count("guard.dp_violation")
        record_fault("garbage_output", backend=name, detail=viol,
                     action="host_rerun")
        rz.breaker().record_failure(name, "garbage_output")
        return _host_rerun(g, abpt, beg_node_id, end_node_id, query,
                           exclude=name)
    return res


def align_sequence_to_subgraph(g: POAGraph, abpt: Params, beg_node_id: int,
                               end_node_id: int, query: np.ndarray) -> AlignResult:
    if g.node_n <= 2:  # empty graph: nothing to align to (abpoa_align.c:196)
        return AlignResult()
    if not g.is_topological_sorted:
        g.topological_sort(abpt)
    fn = _resolve(abpt)
    name = last_resolved(abpt.device)
    from ..obs import trace
    with trace.span("dp:" + name, "dp",
                    args={"rows": g.node_n, "qlen": len(query)}):
        return _dispatch_resilient(fn, name, g, abpt, beg_node_id,
                                   end_node_id, query)


def align_windows(g: POAGraph, abpt: Params, windows) -> list:
    """Align independent subgraph windows [(beg_id, end_id, query), ...].

    Device backends batch all windows into one dispatch
    (jax_backend.align_windows_jax); host backends run them sequentially.
    Results are identical either way. The batched device dispatch runs
    under the same resilience envelope as single dispatches; on failure
    the windows re-run sequentially on the host kernels.
    """
    if not windows:
        return []
    if g.node_n <= 2:
        return [AlignResult() for _ in windows]
    if not g.is_topological_sorted:
        g.topological_sort(abpt)
    fn = _resolve(abpt)  # also validates the backend name + breaker state
    name = last_resolved(abpt.device)
    if len(windows) > 1 and name in ("jax", "pallas"):
        # _resolve may have fallen back to a host kernel on a failed probe
        # or an open breaker; the batched-window path must honor that too
        # or it would hang on the same wedged backend init the probe just
        # detected
        from ..utils.probe import apply_platform_pin, jax_backend_reachable
        if jax_backend_reachable():
            apply_platform_pin()
            from .jax_backend import align_windows_jax
            from .. import resilience as rz
            if not rz.enabled():
                return align_windows_jax(g, abpt, windows)
            from ..obs import count, record_fault
            try:
                outs = rz.guarded_device_call(
                    "dp:windows", name,
                    lambda: align_windows_jax(g, abpt, windows))
            except rz.DispatchFailed:
                count("fallback.windows_host_rerun")
                return [_host_rerun(g, abpt, b, e, q, exclude=name)
                        for b, e, q in windows]
            # same per-result guard contract as the single-dispatch path:
            # a garbage window re-runs alone on the host, the rest keep
            # their device results
            checked = []
            for (b, e, q), res in zip(windows, outs):
                res = rz.inject.corrupt_result(res)
                viol = rz.guards.align_result_violation(
                    res, len(q), g.node_n, abpt)
                if viol is not None:
                    count("guard.dp_violation")
                    record_fault("garbage_output", backend=name,
                                 detail=viol, action="host_rerun")
                    rz.breaker().record_failure(name, "garbage_output")
                    res = _host_rerun(g, abpt, b, e, q, exclude=name)
                checked.append(res)
            return checked
    from ..obs import trace
    with trace.span("dp:" + name, "dp",
                    args={"rows": g.node_n, "windows": len(windows)}):
        return [_dispatch_resilient(fn, name, g, abpt, b, e, q)
                for b, e, q in windows]


def align_sequence_to_graph(g: POAGraph, abpt: Params, query: np.ndarray) -> AlignResult:
    return align_sequence_to_subgraph(g, abpt, C.SRC_NODE_ID, C.SINK_NODE_ID, query)
