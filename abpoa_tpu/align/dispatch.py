"""Backend dispatch for the DP kernel.

TPU-native analog of the reference's runtime CPUID dispatch
(/root/reference/src/abpoa_dispatch_simd.c:59-82): the `device` field of
`Params` selects the kernel implementation. "numpy" is the host oracle;
"jax"/"pallas" run the banded DP on the accelerator (registered lazily so the
package imports without a TPU present).
"""
from __future__ import annotations

from typing import Callable, Dict

import numpy as np

from .. import constants as C
from ..graph import POAGraph
from ..params import Params
from .oracle import align_sequence_to_subgraph_numpy
from .result import AlignResult

_BACKENDS: Dict[str, Callable] = {}

# backend name the most recent _resolve actually selected — differs from
# Params.device after a probe-timeout fallback, and telemetry labels
# (per-read records, dp spans) must use it, not the requested device
_LAST_RESOLVED = {"name": ""}


def last_resolved(default: str = "") -> str:
    return _LAST_RESOLVED["name"] or default


def telemetry_backend(abpt: Params) -> tuple:
    """(backend, fallback_reason) for per-read records: the kernel the
    last dispatch actually ran, plus 'probe_timeout' when an accelerator
    was requested but the probe rerouted to a host kernel. Host devices
    always dispatch themselves, so only accelerator requests consult the
    resolution state (which start_run resets between runs)."""
    req = "jax" if abpt.device == "tpu" else abpt.device
    if req not in ("jax", "pallas"):
        return req, None
    got = last_resolved(req)
    if got != req:
        return got, "probe_timeout"
    return got, None


def resolve_auto_device() -> str:
    """Pick the fastest available engine, the analog of the reference's
    startup ISA auto-selection (src/abpoa_dispatch_simd.c:59-82): a live
    accelerator wins, then the native C++ host kernel, then the numpy
    oracle. Called once per `Params.finalize()` for `device="auto"`; the
    probe result is process-cached so repeated finalizes stay cheap."""
    from ..utils.probe import has_accelerator
    if has_accelerator():
        # "jax" (the fused XLA-scan loop) until on-chip measurements prove
        # the Pallas kernels faster end-to-end (BENCH_onchip.json)
        return "jax"
    try:
        from ..native import load
        if load() is not None:
            return "native"
    except Exception:
        pass
    return "numpy"


def register_backend(name: str, fn: Callable) -> None:
    _BACKENDS[name] = fn


register_backend("numpy", align_sequence_to_subgraph_numpy)


def _resolve(abpt: Params) -> Callable:
    from ..obs import count
    name = abpt.device
    if name in _BACKENDS:
        _LAST_RESOLVED["name"] = name
        count(f"dispatch.{name}")
        return _BACKENDS[name]
    if name in ("jax", "tpu", "pallas", "native"):
        if name == "native":
            from . import native_backend  # registers "native"
        else:
            # a wedged accelerator tunnel hangs the first in-process
            # jax.devices() forever; probe out-of-process first so the CLI
            # degrades to the host kernel instead (the reference's dispatch
            # can never hang, src/abpoa_dispatch_simd.c:56-78)
            from ..utils.probe import (apply_platform_pin,
                                       jax_backend_reachable,
                                       warn_unreachable_once)
            if not jax_backend_reachable():
                warn_unreachable_once(
                    "Warning: JAX backend probe timed out (wedged "
                    "accelerator tunnel?); using the host kernel.")
                count("fallback.jax_probe_timeout")
                try:
                    from . import native_backend  # registers "native"
                    name = "native"
                except Exception:
                    name = "numpy"
                _LAST_RESOLVED["name"] = name
                count(f"dispatch.{name}")
                return _BACKENDS[name]
            apply_platform_pin()
            from . import jax_backend  # lazy: registers "jax"
            if name == "pallas":
                from . import pallas_backend  # registers "pallas"
            if name == "tpu":
                name = "jax"
        if name in _BACKENDS:
            _LAST_RESOLVED["name"] = name
            count(f"dispatch.{name}")
            return _BACKENDS[name]
    raise ValueError(f"Unknown DP backend: {abpt.device}")


def align_sequence_to_subgraph(g: POAGraph, abpt: Params, beg_node_id: int,
                               end_node_id: int, query: np.ndarray) -> AlignResult:
    if g.node_n <= 2:  # empty graph: nothing to align to (abpoa_align.c:196)
        return AlignResult()
    if not g.is_topological_sorted:
        g.topological_sort(abpt)
    fn = _resolve(abpt)
    from ..obs import trace
    with trace.span("dp:" + last_resolved(abpt.device), "dp",
                    args={"rows": g.node_n, "qlen": len(query)}):
        return fn(g, abpt, beg_node_id, end_node_id, query)


def align_windows(g: POAGraph, abpt: Params, windows) -> list:
    """Align independent subgraph windows [(beg_id, end_id, query), ...].

    Device backends batch all windows into one dispatch
    (jax_backend.align_windows_jax); host backends run them sequentially.
    Results are identical either way.
    """
    if not windows:
        return []
    if g.node_n <= 2:
        return [AlignResult() for _ in windows]
    if not g.is_topological_sorted:
        g.topological_sort(abpt)
    fn = _resolve(abpt)  # also validates the backend name
    if len(windows) > 1 and abpt.device in ("jax", "tpu", "pallas"):
        # _resolve may have fallen back to a host kernel on a failed probe;
        # the batched-window path must honor that too or it would hang on
        # the same wedged backend init the probe just detected
        from ..utils.probe import apply_platform_pin, jax_backend_reachable
        if jax_backend_reachable():
            apply_platform_pin()
            from .jax_backend import align_windows_jax
            return align_windows_jax(g, abpt, windows)
    from ..obs import trace
    with trace.span("dp:" + last_resolved(abpt.device), "dp",
                    args={"rows": g.node_n, "windows": len(windows)}):
        return [fn(g, abpt, b, e, q) for b, e, q in windows]


def align_sequence_to_graph(g: POAGraph, abpt: Params, query: np.ndarray) -> AlignResult:
    return align_sequence_to_subgraph(g, abpt, C.SRC_NODE_ID, C.SINK_NODE_ID, query)
