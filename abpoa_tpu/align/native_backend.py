"""`device=native` backend: the C++ scalar DP kernel in the host core.

The fast all-host path (reference-speed, no accelerator required): graph,
fusion, topo sort AND the banded DP + backtrack all run in C++; Python only
orchestrates, including -G path scores (reference abpoa_graph.c:429-437).
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

from .. import constants as C
from ..params import Params
from .dispatch import register_backend
from .result import AlignResult


def align_sequence_to_subgraph_native(g, abpt: Params, beg_node_id: int,
                                      end_node_id: int, query: np.ndarray) -> AlignResult:
    if not getattr(g, "is_native", False):
        from ..obs import count
        from .oracle import align_sequence_to_subgraph_numpy
        count("fallback.native_to_numpy")
        return align_sequence_to_subgraph_numpy(g, abpt, beg_node_id, end_node_id, query)

    lib = g._lib
    qlen = len(query)
    q = np.ascontiguousarray(query, dtype=np.uint8)
    mat = np.ascontiguousarray(abpt.mat, dtype=np.int32)
    params = np.array([
        abpt.align_mode, abpt.gap_mode, abpt.wb, int(abpt.wf * 1e6),
        abpt.zdrop, abpt.m, abpt.gap_open1, abpt.gap_ext1, abpt.gap_open2,
        abpt.gap_ext2, abpt.min_mis, 1 if abpt.put_gap_on_right else 0,
        1 if abpt.put_gap_at_end else 0, 1 if abpt.ret_cigar else 0,
        1 if abpt.inc_path_score else 0,
        # width selection inputs (the kernel picks int16 plane storage per
        # the reference's score bound, abpoa_align_simd.c:1284-1302);
        # ABPOA_TPU_NATIVE_I32=1 forces int32 planes (parity testing)
        int(abpt.max_mat),
        1 if os.environ.get("ABPOA_TPU_NATIVE_I32") else 0,
    ], dtype=np.int32)
    cap = 2 * qlen + g.node_n + 16
    cig = np.zeros(cap, dtype=np.uint64)
    meta = np.zeros(8, dtype=np.int64)
    rc = lib.apg_align(
        g._h, beg_node_id, end_node_id,
        q.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), qlen,
        mat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        params.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cig.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), cap,
        meta.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if rc != 0:
        raise RuntimeError(f"native DP kernel failed (rc={rc})")
    res = AlignResult()
    res.best_score = int(meta[0])
    n_c = int(meta[7])
    res.cigar = [int(x) for x in cig[:n_c]]
    res.cigar_arr = cig[:n_c]  # guards validate the array, no re-convert
    if abpt.rev_cigar:
        res.cigar.reverse()
    res.node_s, res.node_e = int(meta[1]), int(meta[2])
    res.query_s, res.query_e = int(meta[3]), int(meta[4])
    res.n_aln_bases, res.n_matched_bases = int(meta[5]), int(meta[6])
    return res


register_backend("native", align_sequence_to_subgraph_native)
