"""Alignment result container (reference abpoa_res_t, include/abpoa.h:57-64)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List


@dataclass
class AlignResult:
    cigar: List[int] = field(default_factory=list)  # packed 64-bit graph cigar
    node_s: int = -1
    node_e: int = -1
    query_s: int = -1
    query_e: int = -1
    n_aln_bases: int = 0
    n_matched_bases: int = 0
    best_score: int = 0
    # optional uint64 ndarray view of `cigar`, attached by backends that
    # already hold one (native): the output guards validate the array
    # instead of re-converting the Python list (~300 us per 2 kb read —
    # 10% of warm sim2k wall, resilience overhead guard)
    cigar_arr: object = field(default=None, repr=False, compare=False)
