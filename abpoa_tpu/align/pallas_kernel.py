"""Pallas TPU kernel: banded adaptive-band POA DP forward pass.

Where the XLA-scan backend (jax_backend._dp_scan) computes full-width rows and
relies on masking, this kernel keeps only a fixed-width band window per row —
the reference's actual working set — entirely on-chip:

- sequential grid over topologically-ordered graph rows (later rows read
  earlier rows' results; Pallas's in-order TPU grid guarantees ordering);
- a VMEM ring buffer holds the last D rows' H/E1/E2 band windows (predecessor
  fan-in on POA graphs is a short-range dependency: mismatch bubbles), so the
  forward pass never re-reads HBM;
- predecessor windows are realigned to the current row's band offset with a
  padded dynamic slice (the band drifts rightward along the main diagonal);
- the F gap chains are log-step doubling prefix-maxes over the band lanes;
- adaptive-band state (max_pos_left/right, band begin/end) lives in SMEM
  scratch and is updated in-kernel, matching the reference's per-row
  propagation (abpoa_align_simd.c:1107-1130);
- banded H/E1/E2/F1/F2 windows stream to HBM in B-row VMEM blocks with the
  revisiting index map (Mosaic requires >=8-sublane blocks) for the
  traceback; an `ok` flag reports band/ring overflow so the wrapper can fall
  back to the full-width scan backend.

Scope: convex-gap global banded mode (the default headline config); other
modes/regimes run on the XLA-scan backend. Row 0 (the source row) is patched
in by the host wrapper.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .oracle import INT32_MIN
from .pallas_common import BLOCK_B, band_extents, make_ring_gather, qp_band_row


def _make_kernel(R, W, P, O, D, Qp):
    B = BLOCK_B
    def kernel(sc_ref, base_ref, pre_idx_ref, pre_cnt_ref, out_idx_ref,
               out_cnt_ref, remain_ref, mpl0_ref, mpr0_ref, qp_ref,
               row0H_ref, row0E1_ref, row0E2_ref,
               H_out, E1_out, E2_out, F1_out, F2_out,
               begend_out, mplr_out, ok_out,
               ringH, ringE1, ringE2, dp_beg_s, dp_end_s, mpl_s, mpr_s, ok_s):
        i = pl.program_id(0)
        n = pl.num_programs(0)
        qlen = sc_ref[0]
        w = sc_ref[1]
        remain_end = sc_ref[2]
        inf = sc_ref[3]
        e1, oe1 = sc_ref[5], sc_ref[6]
        e2, oe2 = sc_ref[8], sc_ref[9]
        gn = sc_ref[10]
        end0 = sc_ref[11]

        col = lax.broadcasted_iota(jnp.int32, (1, W), 1)

        @pl.when(i == 0)
        def _init():
            ok_s[0] = jnp.where(end0 + 1 > W, 0, 1)
            # seed SMEM band state from host-provided arrays
            def seed(k, _):
                mpl_s[k] = mpl0_ref[k]
                mpr_s[k] = mpr0_ref[k]
                dp_beg_s[k] = 0
                dp_end_s[k] = 0
                return 0
            lax.fori_loop(0, R, seed, 0)
            dp_beg_s[0] = 0
            dp_end_s[0] = end0
            ringH[0, :] = row0H_ref[0, :]
            ringE1[0, :] = row0E1_ref[0, :]
            ringE2[0, :] = row0E2_ref[0, :]

        row = i + 1  # dp row computed by this grid step
        sub = row % B  # row's slot inside the current B-row output block
        active = (row < gn - 1) & (ok_s[0] == 1)

        neg_row = jnp.full((1, W), inf, jnp.int32)

        @pl.when(active)
        def _row():
            r = qlen - (remain_ref[row] - remain_end - 1)
            beg = jnp.maximum(0, jnp.minimum(mpl_s[row], r) - w)
            end = jnp.minimum(qlen, jnp.maximum(mpr_s[row], r) + w)
            npre = pre_cnt_ref[row]

            def mpb_body(k, acc):
                return jnp.minimum(acc, dp_beg_s[pre_idx_ref[row * P + k]])
            min_pre_beg = lax.fori_loop(0, npre, mpb_body, jnp.int32(2**30))
            beg = jnp.maximum(beg, min_pre_beg)

            # overflow checks: band wider than W, or a pred outside the ring
            def ovf_body(k, acc):
                return acc | (row - pre_idx_ref[row * P + k] >= D)
            ovf = lax.fori_loop(0, npre, ovf_body, end - beg + 1 > W)

            @pl.when(ovf)
            def _():
                ok_s[0] = 0
            dp_beg_s[row] = beg
            dp_end_s[row] = end

            cols = beg + col
            in_band = cols <= end

            gather = make_ring_gather(col, neg_row, W, D)

            def pred_body(k, acc):
                Mq, E1r, E2r = acc
                p = pre_idx_ref[row * P + k]
                pbeg = dp_beg_s[p]
                pend = dp_end_s[p]
                hs = gather(ringH, p, beg - 1 - pbeg)
                hs = jnp.where((cols - 1 >= pbeg) & (cols - 1 <= pend), hs, inf)
                Mq = jnp.maximum(Mq, hs)
                e1s = gather(ringE1, p, beg - pbeg)
                e2s = gather(ringE2, p, beg - pbeg)
                eok = (cols >= pbeg) & (cols <= pend)
                E1r = jnp.maximum(E1r, jnp.where(eok, e1s, inf))
                E2r = jnp.maximum(E2r, jnp.where(eok, e2s, inf))
                return (Mq, E1r, E2r)

            Mq, E1r, E2r = lax.fori_loop(
                0, npre, pred_body, (neg_row, neg_row, neg_row))

            qprow = qp_band_row(qp_ref, base_ref[row], beg, W)
            Mq = jnp.where(in_band, Mq + qprow, inf)
            E1r = jnp.where(in_band, E1r, inf)
            E2r = jnp.where(in_band, E2r, inf)
            Hhat = jnp.maximum(jnp.maximum(Mq, E1r), E2r)

            def chain(A, ext):
                F = A
                shift = 1
                while shift < W:
                    rolled = pltpu.roll(F, shift, axis=1)
                    prev = jnp.where(col >= shift, rolled, inf)
                    F = jnp.maximum(
                        F, jnp.maximum(prev, inf + shift * ext) - shift * ext)
                    shift <<= 1
                return F

            Hm1 = jnp.where(col >= 1, pltpu.roll(Hhat, 1, axis=1), inf)
            A1 = jnp.where(in_band, jnp.where(col == 0, Mq - oe1, Hm1 - oe1), inf)
            A2 = jnp.where(in_band, jnp.where(col == 0, Mq - oe2, Hm1 - oe2), inf)
            F1 = chain(A1, e1)
            F2 = chain(A2, e2)
            Hrow = jnp.maximum(Hhat, jnp.maximum(F1, F2))
            E1n = jnp.maximum(E1r - e1, Hrow - oe1)
            E2n = jnp.maximum(E2r - e2, Hrow - oe2)
            Hrow = jnp.where(in_band, Hrow, inf)
            E1n = jnp.where(in_band, E1n, inf)
            E2n = jnp.where(in_band, E2n, inf)
            F1 = jnp.where(in_band, F1, inf)
            F2 = jnp.where(in_band, F2, inf)

            ringH[row % D, :] = Hrow[0]
            ringE1[row % D, :] = E1n[0]
            ringE2[row % D, :] = E2n[0]
            H_out[sub, :] = Hrow[0]
            E1_out[sub, :] = E1n[0]
            E2_out[sub, :] = E2n[0]
            F1_out[sub, :] = F1[0]
            F2_out[sub, :] = F2[0]

            left, right, _, _ = band_extents(Hrow, in_band, cols, inf)

            def out_body(k, _):
                t = out_idx_ref[row * O + k]
                mpr_s[t] = jnp.maximum(mpr_s[t], right + 1)
                mpl_s[t] = jnp.minimum(mpl_s[t], left + 1)
                return 0
            lax.fori_loop(0, out_cnt_ref[row], out_body, 0)

        @pl.when(~active)
        def _pad():
            H_out[sub, :] = neg_row[0]
            E1_out[sub, :] = neg_row[0]
            E2_out[sub, :] = neg_row[0]
            F1_out[sub, :] = neg_row[0]
            F2_out[sub, :] = neg_row[0]

        @pl.when(i == n - 1)
        def _flush():
            def body(k, _):
                begend_out[k] = dp_beg_s[k]
                begend_out[R + k] = dp_end_s[k]
                mplr_out[k] = mpl_s[k]
                mplr_out[R + k] = mpr_s[k]
                return 0
            lax.fori_loop(0, R, body, 0)
            ok_out[0] = ok_s[0]

    return kernel


def smem_words(R: int, P: int, O: int) -> int:
    """int32 words of SMEM the kernel allocates (inputs + outputs + scratch).
    Kept next to the specs below; pallas_backend guards its calls with this
    so oversized graphs fall back to the scan backend instead of failing at
    Mosaic compile time (v5e SMEM is 1 MB/core)."""
    inputs = 16 + R * (P + O + 6)   # scalars, base, tables, cnts, remain, mpl0/r0
    outputs = 2 * R + 2 * R + 1     # begend, mplr, ok
    scratch_ = 4 * R + 1            # dp_beg/end, mpl/mpr, ok
    return inputs + outputs + scratch_


def pallas_banded_dp(scalars: np.ndarray, base, pre_idx, pre_cnt, out_idx,
                     out_cnt, remain, mpl0, mpr0, qp_pad,
                     row0H, row0E1, row0E2,
                     R: int, W: int, P: int, O: int, D: int, Qp: int,
                     interpret: bool = False):
    """Banded forward DP. Returns (H, E1, E2, F1, F2) banded planes (R, W),
    begend (2R,), mplr (2R,), ok (1,)."""
    kernel = _make_kernel(R, W, P, O, D, Qp)
    smem = lambda shape: pl.BlockSpec(shape, lambda i: tuple(0 for _ in shape),
                                      memory_space=pltpu.SMEM)
    plane = pl.BlockSpec((BLOCK_B, W), lambda i: ((i + 1) // BLOCK_B, 0),
                         memory_space=pltpu.VMEM)
    out_shapes = (
        [jax.ShapeDtypeStruct((R, W), jnp.int32)] * 5
        + [jax.ShapeDtypeStruct((2 * R,), jnp.int32),
           jax.ShapeDtypeStruct((2 * R,), jnp.int32),
           jax.ShapeDtypeStruct((1,), jnp.int32)])
    out_specs = [plane] * 5 + [smem((2 * R,)), smem((2 * R,)), smem((1,))]
    in_specs = [
        smem((16,)),            # scalars
        smem((R,)),             # base
        smem((R * P,)),         # pre_idx (flattened: 2-D SMEM rows pad 512B)
        smem((R,)),             # pre_cnt
        smem((R * O,)),         # out_idx (flattened)
        smem((R,)),             # out_cnt
        smem((R,)),             # remain
        smem((R,)),             # mpl0
        smem((R,)),             # mpr0
        pl.BlockSpec((qp_pad.shape[0], Qp + W), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, W), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, W), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, W), lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]
    scratch = [
        pltpu.VMEM((D, W), jnp.int32),  # ringH
        pltpu.VMEM((D, W), jnp.int32),  # ringE1
        pltpu.VMEM((D, W), jnp.int32),  # ringE2
        pltpu.SMEM((R,), jnp.int32),    # dp_beg
        pltpu.SMEM((R,), jnp.int32),    # dp_end
        pltpu.SMEM((R,), jnp.int32),    # mpl
        pltpu.SMEM((R,), jnp.int32),    # mpr
        pltpu.SMEM((1,), jnp.int32),    # ok
    ]
    fn = pl.pallas_call(
        kernel,
        grid=(R - 1,),
        out_shape=out_shapes,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
        interpret=interpret,
    )
    return fn(scalars, base, pre_idx.reshape(-1), pre_cnt,
              out_idx.reshape(-1), out_cnt, remain,
              mpl0, mpr0, qp_pad, row0H, row0E1, row0E2)
