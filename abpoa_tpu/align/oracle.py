"""NumPy oracle for banded sequence-to-graph DP.

This is the CPU reference backend: a faithful scalar-semantics re-derivation of
the reference's SIMD kernel (/root/reference/src/abpoa_align_simd.c, readable
scalar spec in /root/reference/src/abpoa_simd.c:85-622), vectorized along the
band with NumPy. It is the correctness oracle for the TPU (JAX/Pallas) kernels
and the default host fallback.

Semantics replicated exactly (so consensus output is byte-identical):
- adaptive band [GET_AD_DP_BEGIN, GET_AD_DP_END] (abpoa_align.h:34-35), with
  clamp-to-min-predecessor-begin (abpoa_align_simd.c:957-959)
- int16/int32 score-width promotion rule (abpoa_align_simd.c:1293-1302)
- F gap chains: F[beg] = (M+q)[beg]-oe, F[j] = max(H[j-1]-oe, F[j-1]-e)
- affine-gap conditional E kill when F dominates H (abpoa_align_simd.c:926-930)
- backtrack op order M -> E(1,2) -> F(1,2) -> M with put_gap_on_right /
  put_gap_at_end switches (abpoa_align_simd.c:309-458)
- row-max left/right tie split for adaptive band propagation
  (abpoa_align_simd.c:1107-1130)
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .. import constants as C
from ..cigar import push_cigar
from ..graph import POAGraph
from ..params import Params
from .result import AlignResult

INT16_MAX = 32767
INT16_MIN = -32768
INT32_MIN = -2147483648


def dp_inf_min(abpt: Params, dtype_min: int = INT32_MIN) -> int:
    """-inf clamp for DP cells: far enough below any reachable score that
    subtraction chains cannot wrap (the 512-step margin mirrors the
    reference's underflow headroom, abpoa_align_simd.c:1293-1302)."""
    return (max(dtype_min + abpt.min_mis, dtype_min + abpt.gap_oe1,
                dtype_min + abpt.gap_oe2)
            + 512 * max(abpt.gap_ext1, abpt.gap_ext2))


def int16_score_limit(abpt: Params) -> int:
    """Largest worst-case score that still fits 16-bit lanes
    (abpoa_align_simd.c:1284-1302)."""
    return INT16_MAX - abpt.min_mis - abpt.gap_oe1 - abpt.gap_oe2


def max_score_bound(abpt: Params, qlen: int, gn: int) -> int:
    """Worst-case alignment score used for width selection
    (abpoa_align_simd.c:1293-1302). The fused loop's on-device promote check
    (fused_loop.run_fused_chunk) evaluates the same formula with traced
    values; keep them in sync."""
    ln = max(qlen, gn)
    return max(qlen * abpt.max_mat, ln * abpt.gap_ext1 + abpt.gap_open1)


def _select_dtype(abpt: Params, qlen: int, gn: int) -> Tuple[np.dtype, int]:
    """Score width promotion (abpoa_align_simd.c:1284-1302)."""
    max_score = max_score_bound(abpt, qlen, gn)
    if max_score <= int16_score_limit(abpt):
        return np.dtype(np.int16), dp_inf_min(abpt, INT16_MIN)
    return np.dtype(np.int32), dp_inf_min(abpt, INT32_MIN)


def _build_index_map(g: POAGraph, beg_index: int, end_index: int) -> np.ndarray:
    """BFS-reachable subgraph mask (abpoa_align_simd.c:1259-1269)."""
    index_map = np.zeros(g.node_n, dtype=np.uint8)
    index_map[beg_index] = index_map[end_index] = 1
    for i in range(beg_index, end_index - 1):
        if not index_map[i]:
            continue
        node = g.nodes[int(g.index_to_node_id[i])]
        for out_id in node.out_ids:
            index_map[int(g.node_id_to_index[out_id])] = 1
    return index_map


def _prefix_max_chain(a: np.ndarray, ext: int) -> np.ndarray:
    """F[k] = max(a[k], F[k-1]-ext): running max of a decaying chain.

    Computed in int64 (the reference stays in the narrow dtype and relies on
    its inf_min margin to avoid wrap; results agree on all non-wrapped cells).
    """
    n = len(a)
    t = a.astype(np.int64) + np.arange(n, dtype=np.int64) * ext
    np.maximum.accumulate(t, out=t)
    return t - np.arange(n, dtype=np.int64) * ext


class _DPState:
    """Per-call DP planes + band bookkeeping."""

    def __init__(self, rows: int, qlen: int, n_planes: int, dtype: np.dtype, inf_min: int):
        self.qlen = qlen
        self.inf_min = inf_min
        self.dtype = dtype
        shape = (rows, qlen + 1)
        self.H = np.full(shape, inf_min, dtype=dtype)
        self.E1 = np.full(shape, inf_min, dtype=dtype) if n_planes >= 3 else None
        self.F1 = np.full(shape, inf_min, dtype=dtype) if n_planes >= 3 else None
        self.E2 = np.full(shape, inf_min, dtype=dtype) if n_planes >= 5 else None
        self.F2 = np.full(shape, inf_min, dtype=dtype) if n_planes >= 5 else None
        self.dp_beg = np.zeros(rows, dtype=np.int32)
        self.dp_end = np.zeros(rows, dtype=np.int32)


def align_sequence_to_subgraph_numpy(g: POAGraph, abpt: Params, beg_node_id: int,
                                     end_node_id: int, query: np.ndarray) -> AlignResult:
    res = AlignResult()
    qlen = len(query)
    beg_index = int(g.node_id_to_index[beg_node_id])
    end_index = int(g.node_id_to_index[end_node_id])
    gn = end_index - beg_index + 1
    index_map = _build_index_map(g, beg_index, end_index)
    dtype, inf_min = _select_dtype(abpt, qlen, gn)

    mat = abpt.mat
    m = abpt.m
    o1, e1, oe1 = abpt.gap_open1, abpt.gap_ext1, abpt.gap_oe1
    o2, e2, oe2 = abpt.gap_open2, abpt.gap_ext2, abpt.gap_oe2
    gap_mode = abpt.gap_mode
    local = abpt.align_mode == C.LOCAL_MODE
    extend = abpt.align_mode == C.EXTEND_MODE
    w = qlen if abpt.wb < 0 else abpt.wb + int(abpt.wf * qlen)
    banded = abpt.wb >= 0

    remain = g.node_id_to_max_remain
    mpl = g.node_id_to_max_pos_left
    mpr = g.node_id_to_max_pos_right
    remain_end = int(remain[end_node_id]) if (banded or abpt.zdrop > 0) else 0

    def ad_beg(node_id: int) -> int:
        r = qlen - (int(remain[node_id]) - remain_end - 1)
        return max(0, min(int(mpl[node_id]), r) - w)

    def ad_end(node_id: int) -> int:
        r = qlen - (int(remain[node_id]) - remain_end - 1)
        return min(qlen, max(int(mpr[node_id]), r) + w)

    # query profile: qp[k][0] = 0, qp[k][j] = mat[k][query[j-1]]
    qp = np.zeros((m, qlen + 1), dtype=dtype)
    if qlen:
        qp[:, 1:] = mat[:, query].astype(dtype)

    # per-row predecessor dp indices, restricted to the subgraph
    rows = gn
    pre_index: List[List[int]] = [[] for _ in range(rows)]
    pre_ids: List[List[int]] = [[] for _ in range(rows)]  # in-edge idx for path score
    for index_i in range(beg_index + 1, end_index + 1):
        dp_i = index_i - beg_index
        node = g.nodes[int(g.index_to_node_id[index_i])]
        for j, in_id in enumerate(node.in_ids):
            p_idx = int(g.node_id_to_index[in_id])
            if index_map[p_idx]:
                pre_index[dp_i].append(p_idx - beg_index)
                pre_ids[dp_i].append(j)

    n_planes = {C.LINEAR_GAP: 1, C.AFFINE_GAP: 3, C.CONVEX_GAP: 5}[gap_mode]
    st = _DPState(rows, qlen, n_planes, dtype, inf_min)
    H, E1, E2, F1, F2 = st.H, st.E1, st.E2, st.F1, st.F2
    dp_beg, dp_end = st.dp_beg, st.dp_end

    # ---------------------------------------------------------- first row init
    if banded:
        mpl[beg_node_id] = mpr[beg_node_id] = 0
        for out_id in g.nodes[beg_node_id].out_ids:
            if index_map[int(g.node_id_to_index[out_id])]:
                mpl[out_id] = mpr[out_id] = 1
        dp_beg[0] = 0
        dp_end[0] = ad_end(beg_node_id)
    else:
        dp_beg[0], dp_end[0] = 0, qlen
    e0 = int(dp_end[0])
    if local:
        H[0, :] = 0
        if E1 is not None:
            E1[0, :] = 0
            F1[0, :] = 0
        if E2 is not None:
            E2[0, :] = 0
            F2[0, :] = 0
    else:
        idx = np.arange(0, e0 + 1, dtype=np.int64)
        if gap_mode == C.LINEAR_GAP:
            H[0, : e0 + 1] = (-e1 * idx).astype(dtype)
        elif gap_mode == C.AFFINE_GAP:
            H[0, 0] = 0
            E1[0, 0] = -oe1
            F1[0, 0] = inf_min
            if e0 >= 1:
                f1 = (-o1 - e1 * idx[1:]).astype(dtype)
                F1[0, 1: e0 + 1] = f1
                H[0, 1: e0 + 1] = f1
        else:
            H[0, 0] = 0
            E1[0, 0] = -oe1
            E2[0, 0] = -oe2
            F1[0, 0] = F2[0, 0] = inf_min
            if e0 >= 1:
                f1 = (-o1 - e1 * idx[1:]).astype(dtype)
                f2 = (-o2 - e2 * idx[1:]).astype(dtype)
                F1[0, 1: e0 + 1] = f1
                F2[0, 1: e0 + 1] = f2
                H[0, 1: e0 + 1] = np.maximum(f1, f2)

    # --------------------------------------------------------------- row loop
    best_score = inf_min
    best_i = best_j = 0
    best_id = 0
    zdropped = False

    for index_i in range(beg_index + 1, end_index):
        if not index_map[index_i]:
            continue
        dp_i = index_i - beg_index
        node_id = int(g.index_to_node_id[index_i])
        node = g.nodes[node_id]
        preds = pre_index[dp_i]
        if banded:
            beg, end = ad_beg(node_id), ad_end(node_id)
            min_pre_beg = min(int(dp_beg[p]) for p in preds)
            if beg < min_pre_beg:
                beg = min_pre_beg
        else:
            beg, end = 0, qlen
        dp_beg[dp_i], dp_end[dp_i] = beg, end

        ps_list = [0] * len(preds)
        if abpt.inc_path_score:
            ps_list = [g.incre_path_score(node_id, pre_ids[dp_i][k]) for k in range(len(preds))]

        # M from pre H shifted by one column; E from pre E at same column
        lead = dtype.type(0) if local else dtype.type(inf_min)
        p0 = preds[0]
        shifted = np.empty(qlen + 1, dtype=dtype)
        shifted[0] = lead
        shifted[1:] = H[p0, :-1]
        Mq = shifted + dtype.type(ps_list[0])
        if gap_mode != C.LINEAR_GAP:
            e1row = E1[p0] + dtype.type(ps_list[0])
            e2row = (E2[p0] + dtype.type(ps_list[0])) if gap_mode == C.CONVEX_GAP else None
        else:
            e1row = H[p0] - dtype.type(e1) + dtype.type(ps_list[0])
            e2row = None
        for k in range(1, len(preds)):
            p = preds[k]
            ps = dtype.type(ps_list[k])
            shifted[0] = lead
            shifted[1:] = H[p, :-1]
            np.maximum(Mq, shifted + ps, out=Mq)
            if gap_mode != C.LINEAR_GAP:
                np.maximum(e1row, E1[p] + ps, out=e1row)
                if e2row is not None:
                    np.maximum(e2row, E2[p] + ps, out=e2row)
            else:
                np.maximum(e1row, H[p] - dtype.type(e1) + ps, out=e1row)

        # add query profile
        Mq = Mq + qp[node.base]
        if gap_mode == C.LINEAR_GAP:
            # H/E fused in one plane for linear gaps
            Hhat = np.maximum(Mq, e1row)
            bHhat = Hhat[beg: end + 1].astype(np.int64)
            # in-row chain: H[j] = max(H[j], H[j-1]-e1)
            chain = _prefix_max_chain(bHhat, e1)
            brow = chain.astype(dtype)
            if local:
                np.maximum(brow, 0, out=brow)
            H[dp_i, :] = inf_min
            H[dp_i, beg: end + 1] = brow
        else:
            Hhat = np.maximum(Mq, e1row)
            if e2row is not None:
                np.maximum(Hhat, e2row, out=Hhat)
            # F chains over the band
            bH = Hhat[beg: end + 1]
            bMq = Mq[beg: end + 1]
            n = end - beg + 1
            a1 = np.empty(n, dtype=np.int64)
            a1[0] = int(bMq[0]) - oe1
            if n > 1:
                a1[1:] = bH[:-1].astype(np.int64) - oe1
            f1 = _prefix_max_chain(a1, e1).astype(dtype)
            if e2row is not None:
                a2 = np.empty(n, dtype=np.int64)
                a2[0] = int(bMq[0]) - oe2
                if n > 1:
                    a2[1:] = bH[:-1].astype(np.int64) - oe2
                f2 = _prefix_max_chain(a2, e2).astype(dtype)
            # H = max(Hhat, F)
            bfinal = np.maximum(bH, f1)
            if e2row is not None:
                np.maximum(bfinal, f2, out=bfinal)
            if local:
                np.maximum(bfinal, 0, out=bfinal)
            # E for next row
            if gap_mode == C.AFFINE_GAP:
                # E' killed where F strictly dominated H (abpoa_align_simd.c:926-930)
                be1 = np.maximum(e1row[beg: end + 1] - dtype.type(e1), bfinal - dtype.type(oe1))
                dead = dtype.type(0) if local else dtype.type(inf_min)
                be1 = np.where(bfinal == bH, be1, dead)
            else:
                be1 = np.maximum(e1row[beg: end + 1] - dtype.type(e1), bfinal - dtype.type(oe1))
                be2 = np.maximum(e2row[beg: end + 1] - dtype.type(e2), bfinal - dtype.type(oe2))
                if local:
                    np.maximum(be1, 0, out=be1)
                    np.maximum(be2, 0, out=be2)
            H[dp_i, :] = inf_min
            E1[dp_i, :] = inf_min
            F1[dp_i, :] = inf_min
            H[dp_i, beg: end + 1] = bfinal
            E1[dp_i, beg: end + 1] = be1
            F1[dp_i, beg: end + 1] = f1
            if e2row is not None:
                E2[dp_i, :] = inf_min
                F2[dp_i, :] = inf_min
                E2[dp_i, beg: end + 1] = be2
                F2[dp_i, beg: end + 1] = f2

        # row max for local/extend scoring and adaptive band propagation
        if local or extend or banded:
            brow = H[dp_i, beg: end + 1]
            mx = int(brow.max()) if end >= beg else inf_min
            if mx > inf_min:
                eq = np.flatnonzero(brow == dtype.type(mx))
                left_max_i = beg + int(eq[0])
                right_max_i = beg + int(eq[-1])
                row_max = mx
            else:
                left_max_i = right_max_i = -1
                row_max = inf_min
            if local:
                if row_max > best_score:
                    best_score, best_i, best_j = row_max, dp_i, left_max_i
            elif extend:
                if row_max > best_score:
                    best_score, best_i, best_j, best_id = row_max, dp_i, right_max_i, node_id
                elif abpt.zdrop > 0:
                    delta = int(remain[best_id]) - int(remain[node_id])
                    if best_score - row_max > abpt.zdrop + e1 * abs(delta - (right_max_i - best_j)):
                        zdropped = True
                        break
            if banded:
                for out_id in node.out_ids:
                    if right_max_i + 1 > mpr[out_id]:
                        mpr[out_id] = right_max_i + 1
                    if left_max_i + 1 < mpl[out_id]:
                        mpl[out_id] = left_max_i + 1

    # ------------------------------------------------------------- best score
    if abpt.align_mode == C.GLOBAL_MODE:
        for i, in_id in enumerate(g.nodes[end_node_id].in_ids):
            in_index = int(g.node_id_to_index[in_id])
            if not index_map[in_index]:
                continue
            dp_i = in_index - beg_index
            end = min(qlen, int(dp_end[dp_i]))
            v = int(H[dp_i, end])
            if v > best_score:
                best_score, best_i, best_j = v, dp_i, end
    res.best_score = best_score

    # -V3 kernel-debug dump (the reference's __SIMD_DEBUG__ path,
    # src/abpoa_align_simd.c:46-95); no-op below VERBOSE_LONG_DEBUG
    from ..utils.logging import dump_dp_matrix
    dump_dp_matrix(H, dp_beg, dp_end, g.index_to_node_id, beg_index,
                   planes=(None if gap_mode == C.LINEAR_GAP
                           else {"E1": E1, "F1": F1}))

    if abpt.ret_cigar:
        _backtrack(g, abpt, st, pre_index, pre_ids, beg_index, best_i, best_j,
                   qlen, query, res, gap_mode, inf_min)
    return res


def _backtrack(g: POAGraph, abpt: Params, st: _DPState, pre_index, pre_ids,
               beg_index: int, best_i: int, best_j: int, qlen: int,
               query: np.ndarray, res: AlignResult, gap_mode: int, inf_min: int) -> None:
    """Scalar backtrack, replicating the reference's op priority + tie-breaks
    (abpoa_align_simd.c:116-458)."""
    H, E1, E2, F1, F2 = st.H, st.E1, st.E2, st.F1, st.F2
    dp_beg, dp_end = st.dp_beg, st.dp_end
    mat = abpt.mat
    m = abpt.m
    e1, oe1 = abpt.gap_ext1, abpt.gap_oe1
    e2, oe2 = abpt.gap_ext2, abpt.gap_oe2
    local = abpt.align_mode == C.LOCAL_MODE

    cigar: List[int] = []
    dp_i, dp_j = best_i, best_j
    start_i, start_j = best_i, best_j
    node_id = int(g.index_to_node_id[dp_i + beg_index])
    if best_j < qlen:
        push_cigar(cigar, C.CINS, qlen - best_j, -1, qlen - 1)
    look_gap_at_end = 1 if abpt.put_gap_at_end else 0
    gap_on_right = 1 if abpt.put_gap_on_right else 0
    cur_op = C.ALL_OP
    linear = gap_mode == C.LINEAR_GAP
    convex = gap_mode == C.CONVEX_GAP

    def ps_of(nid: int, k: int) -> int:
        if abpt.inc_path_score:
            return g.incre_path_score(nid, pre_ids[dp_i][k])
        return 0

    while dp_i > 0 and dp_j > 0:
        if local and H[dp_i, dp_j] == 0:
            break
        start_i, start_j = dp_i, dp_j
        preds = pre_index[dp_i]
        s = int(mat[g.nodes[node_id].base, query[dp_j - 1]])
        is_match = g.nodes[node_id].base == int(query[dp_j - 1])
        hit = False

        def try_match() -> bool:
            nonlocal dp_i, dp_j, node_id, cur_op, look_gap_at_end
            for k, pre_i in enumerate(preds):
                ps = ps_of(node_id, k)
                if dp_j - 1 < dp_beg[pre_i] or dp_j - 1 > dp_end[pre_i]:
                    continue
                if int(H[pre_i, dp_j - 1]) + s + ps == int(H[dp_i, dp_j]):
                    push_cigar(cigar, C.CMATCH, 1, node_id, dp_j - 1)
                    dp_i = pre_i
                    dp_j -= 1
                    node_id = int(g.index_to_node_id[dp_i + beg_index])
                    cur_op = C.ALL_OP
                    res.n_aln_bases += 1
                    res.n_matched_bases += 1 if is_match else 0
                    return True
            return False

        if gap_on_right == 0 and look_gap_at_end == 0 and (linear or cur_op & C.M_OP):
            hit = try_match()
            if hit and linear:
                continue

        if not hit:  # deletion
            if linear:
                for k, pre_i in enumerate(preds):
                    ps = ps_of(node_id, k)
                    if dp_j < dp_beg[pre_i] or dp_j > dp_end[pre_i]:
                        continue
                    if int(H[pre_i, dp_j]) - e1 + ps == int(H[dp_i, dp_j]):
                        push_cigar(cigar, C.CDEL, 1, node_id, dp_j - 1)
                        dp_i = pre_i
                        node_id = int(g.index_to_node_id[dp_i + beg_index])
                        hit = True
                        look_gap_at_end = 0
                        break
            elif cur_op & C.E_OP:
                for k, pre_i in enumerate(preds):
                    ps = ps_of(node_id, k)
                    if dp_j < dp_beg[pre_i] or dp_j > dp_end[pre_i]:
                        continue
                    done = False
                    if cur_op & C.E1_OP:
                        if cur_op & C.M_OP:
                            cond = int(H[dp_i, dp_j]) == int(E1[pre_i, dp_j]) + ps
                        else:
                            cond = int(E1[dp_i, dp_j]) == int(E1[pre_i, dp_j]) - e1 + ps
                        if cond:
                            if int(H[pre_i, dp_j]) - oe1 == int(E1[pre_i, dp_j]):
                                cur_op = C.M_OP | C.F_OP
                            else:
                                cur_op = C.E1_OP
                            push_cigar(cigar, C.CDEL, 1, node_id, dp_j - 1)
                            dp_i = pre_i
                            node_id = int(g.index_to_node_id[dp_i + beg_index])
                            hit = done = True
                            look_gap_at_end = 0
                    if not done and convex and cur_op & C.E2_OP:
                        if cur_op & C.M_OP:
                            cond = int(H[dp_i, dp_j]) == int(E2[pre_i, dp_j]) + ps
                        else:
                            cond = int(E2[dp_i, dp_j]) == int(E2[pre_i, dp_j]) - e2 + ps
                        if cond:
                            if int(H[pre_i, dp_j]) - oe2 == int(E2[pre_i, dp_j]):
                                cur_op = C.M_OP | C.F_OP
                            else:
                                cur_op = C.E2_OP
                            push_cigar(cigar, C.CDEL, 1, node_id, dp_j - 1)
                            dp_i = pre_i
                            node_id = int(g.index_to_node_id[dp_i + beg_index])
                            hit = done = True
                            look_gap_at_end = 0
                    if done:
                        break

        if not hit:  # insertion
            if linear:
                if int(H[dp_i, dp_j - 1]) - e1 == int(H[dp_i, dp_j]):
                    push_cigar(cigar, C.CINS, 1, node_id, dp_j - 1)
                    dp_j -= 1
                    look_gap_at_end = 0
                    hit = True
                    res.n_aln_bases += 1
            elif cur_op & C.F_OP:
                got = False
                if cur_op & C.F1_OP:
                    if cur_op & C.M_OP:
                        if int(H[dp_i, dp_j]) == int(F1[dp_i, dp_j]):
                            if int(H[dp_i, dp_j - 1]) - oe1 == int(F1[dp_i, dp_j]):
                                cur_op = C.M_OP | C.E_OP
                                got = True
                            elif int(F1[dp_i, dp_j - 1]) - e1 == int(F1[dp_i, dp_j]):
                                cur_op = C.F1_OP
                                got = True
                    else:
                        if int(H[dp_i, dp_j - 1]) - oe1 == int(F1[dp_i, dp_j]):
                            cur_op = C.M_OP | C.E_OP
                            got = True
                        elif int(F1[dp_i, dp_j - 1]) - e1 == int(F1[dp_i, dp_j]):
                            cur_op = C.F1_OP
                            got = True
                if not got and convex and cur_op & C.F2_OP:
                    if cur_op & C.M_OP:
                        if int(H[dp_i, dp_j]) == int(F2[dp_i, dp_j]):
                            if int(H[dp_i, dp_j - 1]) - oe2 == int(F2[dp_i, dp_j]):
                                cur_op = C.M_OP | C.E_OP
                                got = True
                            elif int(F2[dp_i, dp_j - 1]) - e2 == int(F2[dp_i, dp_j]):
                                cur_op = C.F2_OP
                                got = True
                    else:
                        if int(H[dp_i, dp_j - 1]) - oe2 == int(F2[dp_i, dp_j]):
                            cur_op = C.M_OP | C.E_OP
                            got = True
                        elif int(F2[dp_i, dp_j - 1]) - e2 == int(F2[dp_i, dp_j]):
                            cur_op = C.F2_OP
                            got = True
                if got:
                    push_cigar(cigar, C.CINS, 1, node_id, dp_j - 1)
                    dp_j -= 1
                    look_gap_at_end = 0
                    hit = True
                    res.n_aln_bases += 1

        if not hit and (linear or cur_op & C.M_OP):
            hit = try_match()
            if hit:
                look_gap_at_end = 0

        if not hit:
            raise RuntimeError(
                f"Error in backtrack at dp_i={dp_i}, dp_j={dp_j} (gap_mode={gap_mode})")

    if dp_j > 0:
        push_cigar(cigar, C.CINS, dp_j, -1, dp_j - 1)
    if not abpt.rev_cigar:
        cigar.reverse()
    res.cigar = cigar
    res.node_e = int(g.index_to_node_id[best_i + beg_index])
    res.query_e = best_j - 1
    res.node_s = int(g.index_to_node_id[start_i + beg_index])
    res.query_s = start_j - 1
