"""JAX/TPU backend for the banded sequence-to-graph DP.

TPU-first design (NOT a port of the reference's SIMD layout):
- one `lax.scan` over topologically-ordered graph rows (the row recursion is
  inherently sequential: each row reads its predecessor rows);
- each row is a full-width vector over query columns, mapped onto the TPU's
  8x128 vector lanes by XLA; band semantics are enforced by masking, so the
  numeric results match the reference's adaptive-band kernel exactly
  (/root/reference/src/abpoa_align_simd.c) while the compute stays static-shape;
- the gap-open F chain is a log-step prefix-max (doubling) instead of the
  reference's per-vector carry loop;
- adaptive-band state (max_pos_left/right per node) lives in the scan carry and
  is scatter-updated through padded out-edge tables — no host round trips;
- DP planes are returned to the host for the (cheap, pointer-chasing) scalar
  backtrack, mirroring the reference's matrix-persists-for-backtrack design.

Shapes are bucketed (rows, columns, degree) to bound XLA recompilation.
"""
from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import constants as C
from ..cigar import push_cigar
from ..compile import registry
# the single bucket definition site (compile/buckets.py); the historical
# underscore names are kept because fused_loop/pallas_backend and tests
# import them from here
from ..compile.buckets import bucket as _bucket
from ..compile.buckets import bucket_pow2 as _bucket_pow2
from ..compile.cache import enable_persistent_cache
from ..graph import POAGraph
from ..params import Params
from .oracle import _build_index_map, INT32_MIN, dp_inf_min
from .result import AlignResult
from .dispatch import register_backend

# every device path imports this module before its first compile, so this
# is the one place the persistent compilation cache gets wired
enable_persistent_cache()


@functools.partial(
    jax.jit,
    static_argnames=("gap_mode", "local", "banded", "n_steps", "extend",
                     "zdrop_on"))
def _dp_scan(base, pre_idx, pre_msk, out_idx, out_msk, row_active,
             remain_rows, mpl0, mpr0, qp,
             qlen, w, remain_end, inf_min, dp_end0,
             o1, e1, oe1, o2, e2, oe2,
             gap_mode: int, local: bool, banded: bool, n_steps: int,
             extend: bool = False, zdrop_on: bool = False,
             pre_score=None, zdrop=0):
    """Scan the DP over graph rows. Returns (H, E1, E2, F1, F2, dp_beg, dp_end,
    mpl, mpr, row_max, row_left, row_right, best_score, best_i, best_j).

    pre_score[(R, P)] holds the -G log-scaled path score per predecessor slot
    (reference abpoa_graph.c:429-437); zeros when inc_path_score is off.
    extend-mode best tracking (with optional Z-drop,
    abpoa_align_simd.c:1076-1090) runs in the scan carry so the sequential
    best-so-far/stop semantics match the reference exactly."""
    R, P = pre_idx.shape
    if pre_score is None:
        pre_score = jnp.zeros((R, P), jnp.int32)
    Qp = qp.shape[1]
    cols = jnp.arange(Qp, dtype=jnp.int32)
    inf = inf_min
    convex = gap_mode == C.CONVEX_GAP
    linear = gap_mode == C.LINEAR_GAP

    nplanes = 1 if linear else (3 if gap_mode == C.AFFINE_GAP else 5)

    # ---- first row (host passed dp_end0) -------------------------------------
    col_valid0 = cols <= dp_end0
    if local:
        H0 = jnp.zeros(Qp, jnp.int32)
        E10 = jnp.zeros(Qp, jnp.int32)
        E20 = jnp.zeros(Qp, jnp.int32)
        F10 = jnp.zeros(Qp, jnp.int32)
        F20 = jnp.zeros(Qp, jnp.int32)
    else:
        if linear:
            H0 = jnp.where(col_valid0, -e1 * cols, inf)
            E10 = E20 = F10 = F20 = jnp.full(Qp, inf, jnp.int32)
        else:
            f1r = -o1 - e1 * cols
            f2r = -o2 - e2 * cols
            F10 = jnp.where(col_valid0 & (cols >= 1), f1r, inf)
            F10 = F10.at[0].set(inf)
            F20 = jnp.where(col_valid0 & (cols >= 1), f2r, inf) if convex \
                else jnp.full(Qp, inf, jnp.int32)
            F20 = F20.at[0].set(inf)
            h0 = jnp.maximum(f1r, f2r) if convex else f1r
            H0 = jnp.where(col_valid0 & (cols >= 1), h0, inf).at[0].set(0)
            E10 = jnp.full(Qp, inf, jnp.int32).at[0].set(-oe1)
            E20 = jnp.full(Qp, inf, jnp.int32).at[0].set(-oe2) if convex \
                else jnp.full(Qp, inf, jnp.int32)

    Hb = jnp.full((R, Qp), inf, jnp.int32).at[0].set(H0)
    E1b = jnp.full((R, Qp), inf, jnp.int32).at[0].set(E10)
    E2b = jnp.full((R, Qp), inf, jnp.int32).at[0].set(E20)
    F1b = jnp.full((R, Qp), inf, jnp.int32).at[0].set(F10)
    F2b = jnp.full((R, Qp), inf, jnp.int32).at[0].set(F20)
    dp_beg = jnp.zeros(R, jnp.int32)
    dp_end = jnp.zeros(R, jnp.int32).at[0].set(dp_end0)
    # extra slot at index R for masked scatter targets
    mpl = jnp.concatenate([mpl0, jnp.zeros(1, jnp.int32)])
    mpr = jnp.concatenate([mpr0, jnp.zeros(1, jnp.int32)])

    n_chain_steps = max(1, (Qp - 1).bit_length())

    def chain_max(A, ext):
        # F[j] = max_k (A[j-k] - k*ext): log-step doubling. Decayed values are
        # floored at inf_min so long all-inf prefixes cannot wrap int32 (the
        # reference instead relies on its 512-step inf_min margin).
        F = A
        shift = 1
        for _ in range(n_chain_steps):
            prev = jnp.concatenate([jnp.full(shift, inf, jnp.int32), F[:-shift]])
            # floor before subtracting so inf-region cells cannot wrap int32
            shifted = jnp.maximum(prev, inf + shift * ext) - shift * ext
            F = jnp.maximum(F, shifted)
            shift <<= 1
            if shift >= Qp:
                break
        return F

    def body(carry, i):
        (Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, mpl, mpr,
         bs, bi, bj, brem, zdropped) = carry
        active = row_active[i]
        pm = pre_msk[i]
        pidx = pre_idx[i]
        ps = pre_score[i]

        # ---- band ----------------------------------------------------------
        if banded:
            r = qlen - (remain_rows[i] - remain_end - 1)
            beg = jnp.maximum(0, jnp.minimum(mpl[i], r) - w)
            end = jnp.minimum(qlen, jnp.maximum(mpr[i], r) + w)
            min_pre_beg = jnp.min(jnp.where(pm, dp_beg[pidx], jnp.int32(2**30)))
            beg = jnp.maximum(beg, min_pre_beg)
        else:
            beg = jnp.int32(0)
            end = qlen
        in_band = (cols >= beg) & (cols <= end)

        # ---- M / E from predecessors --------------------------------------
        lead = jnp.int32(0) if local else inf
        Hpre = Hb[pidx]                      # (P, Qp)
        shifted = jnp.concatenate(
            [jnp.full((P, 1), lead, jnp.int32), Hpre[:, :-1]], axis=1)
        shifted = jnp.where(pm[:, None], shifted + ps[:, None], inf)
        Mq = jnp.max(shifted, axis=0)
        if linear:
            Erow = jnp.max(jnp.where(pm[:, None], Hpre - e1 + ps[:, None], inf),
                           axis=0)
        else:
            Erow = jnp.max(jnp.where(pm[:, None], E1b[pidx] + ps[:, None], inf),
                           axis=0)
            if convex:
                E2row = jnp.max(jnp.where(pm[:, None], E2b[pidx] + ps[:, None],
                                          inf), axis=0)

        Mq = Mq + qp[base[i]]
        Mq = jnp.where(in_band, Mq, inf)
        Erow = jnp.where(in_band, Erow, inf)
        Hhat = jnp.maximum(Mq, Erow)
        if convex:
            E2row = jnp.where(in_band, E2row, inf)
            Hhat = jnp.maximum(Hhat, E2row)

        if linear:
            Hrow = chain_max(Hhat, e1)
            if local:
                Hrow = jnp.maximum(Hrow, 0)
            Hrow = jnp.where(in_band, Hrow, inf)
            E1n = E2n = F1n = F2n = jnp.full(Qp, inf, jnp.int32)
        else:
            # F chains: F[beg] = Mq[beg]-oe; F[j] = max(Hhat[j-1]-oe, F[j-1]-e)
            Hm1 = jnp.concatenate([jnp.full(1, inf, jnp.int32), Hhat[:-1]])
            A1 = jnp.where(cols == beg, Mq - oe1, Hm1 - oe1)
            A1 = jnp.where(in_band, A1, inf)
            F1n = chain_max(A1, e1)
            Hrow = jnp.maximum(Hhat, F1n)
            if convex:
                A2 = jnp.where(cols == beg, Mq - oe2, Hm1 - oe2)
                A2 = jnp.where(in_band, A2, inf)
                F2n = chain_max(A2, e2)
                Hrow = jnp.maximum(Hrow, F2n)
            else:
                F2n = jnp.full(Qp, inf, jnp.int32)
            if local:
                Hrow = jnp.maximum(Hrow, 0)
            dead = jnp.int32(0) if local else inf
            if gap_mode == C.AFFINE_GAP:
                E1n = jnp.maximum(Erow - e1, Hrow - oe1)
                E1n = jnp.where(Hrow == Hhat, E1n, dead)
                E2n = jnp.full(Qp, inf, jnp.int32)
            else:
                E1n = jnp.maximum(Erow - e1, Hrow - oe1)
                E2n = jnp.maximum(E2row - e2, Hrow - oe2)
                if local:
                    E1n = jnp.maximum(E1n, 0)
                    E2n = jnp.maximum(E2n, 0)
            E1n = jnp.where(in_band, E1n, inf)
            E2n = jnp.where(in_band, E2n, inf)
            F1n = jnp.where(in_band, F1n, inf)
            F2n = jnp.where(in_band, F2n, inf)
            Hrow = jnp.where(in_band, Hrow, inf)

        # ---- row max (adaptive band + local/extend best) ------------------
        vals = jnp.where(in_band, Hrow, inf)
        mx = jnp.max(vals)
        has = mx > inf
        eq = (vals == mx) & in_band
        left = jnp.where(has, jnp.argmax(eq), -1).astype(jnp.int32)
        right = jnp.where(has, Qp - 1 - jnp.argmax(eq[::-1]), -1).astype(jnp.int32)
        if extend:
            has_row = mx > inf
            better = active & (~zdropped) & (mx > bs)
            if zdrop_on:
                delta = brem - remain_rows[i]
                # empty-band rows (mx == -inf) Z-drop whenever any real best
                # exists (the oracle's Python-int arithmetic, oracle.py:336);
                # splitting the case avoids int32 wrap in bs - mx
                zd_real = has_row & \
                    (bs - mx > zdrop + e1 * jnp.abs(delta - (right - bj)))
                zd = active & (~zdropped) & (~better) & \
                    (zd_real | ((~has_row) & (bs > inf)))
                zdropped = zdropped | zd
            bs = jnp.where(better, mx, bs)
            bi = jnp.where(better, i, bi)
            bj = jnp.where(better, right, bj)
            brem = jnp.where(better, remain_rows[i], brem)
        if banded:
            om = out_msk[i] & active & (~zdropped)
            tgt = jnp.where(om, out_idx[i], R)
            mpr = mpr.at[tgt].max(jnp.where(om, right + 1, -(2**30)))
            mpl = mpl.at[tgt].min(jnp.where(om, left + 1, 2**30))

        # ---- commit row (masked by active) --------------------------------
        keep = active
        Hb = Hb.at[i].set(jnp.where(keep, Hrow, Hb[i]))
        if not linear:
            E1b = E1b.at[i].set(jnp.where(keep, E1n, E1b[i]))
            F1b = F1b.at[i].set(jnp.where(keep, F1n, F1b[i]))
            if convex:
                E2b = E2b.at[i].set(jnp.where(keep, E2n, E2b[i]))
                F2b = F2b.at[i].set(jnp.where(keep, F2n, F2b[i]))
        dp_beg = dp_beg.at[i].set(jnp.where(keep, beg, dp_beg[i]))
        dp_end = dp_end.at[i].set(jnp.where(keep, end, dp_end[i]))
        return (Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, mpl, mpr,
                bs, bi, bj, brem, zdropped), \
            (jnp.where(keep, mx, inf), jnp.where(keep, left, -1),
             jnp.where(keep, right, -1))

    carry = (Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, mpl, mpr,
             inf, jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.bool_(False))
    carry, rows = lax.scan(body, carry, jnp.arange(1, n_steps + 1, dtype=jnp.int32))
    (Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, mpl, mpr,
     bs, bi, bj, _brem, _zd) = carry
    row_max, row_left, row_right = rows
    return (Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, mpl[:-1], mpr[:-1],
            row_max, row_left, row_right, bs, bi, bj)


def _build_snapshot(g: POAGraph, abpt: Params, beg_node_id: int,
                    end_node_id: int, query: np.ndarray) -> dict:
    """Dense kernel tables for one subgraph alignment (per-window buckets).

    Mirrors the reference's per-call setup (index_map BFS
    abpoa_align_simd.c:1259-1269, band seeding :617-626). Mutates the graph's
    band arrays exactly like the sequential path; windows of one read touch
    disjoint index ranges, so batched builds commute with sequential ones.
    """
    qlen = len(query)
    extend = abpt.align_mode == C.EXTEND_MODE
    zdrop_on = extend and abpt.zdrop > 0
    banded = abpt.wb >= 0
    w = qlen if abpt.wb < 0 else abpt.wb + int(abpt.wf * qlen)
    Qp = _bucket(qlen + 1, 128)

    if getattr(g, "is_native", False):
        t = g.build_tables(beg_node_id, end_node_id, banded,
                           lambda n: _bucket(n, 64), _bucket_pow2)
        (base, row_active_scan, pre_idx, pre_msk, out_idx, out_msk,
         remain_rows, mpl0, mpr0) = (
            t["base"], t["row_active"], t["pre_idx"], t["pre_msk"],
            t["out_idx"], t["out_msk"], t["remain_rows"], t["mpl0"], t["mpr0"])
        gn, R, beg_index, remain_end = t["gn"], t["R"], t["beg_index"], t["remain_end"]
        pre_score = None  # native graphs are never used with -G (_want_native)
        if banded:
            r0 = qlen - (int(remain_rows[0]) - remain_end - 1)
            dp_end0 = min(qlen, max(int(mpr0[0]), r0) + w)
        else:
            dp_end0 = qlen
    else:
        beg_index = int(g.node_id_to_index[beg_node_id])
        end_index = int(g.node_id_to_index[end_node_id])
        gn = end_index - beg_index + 1
        index_map = _build_index_map(g, beg_index, end_index)
        R = _bucket(gn, 64)
        nodes = g.nodes
        idx2nid = g.index_to_node_id
        base = np.zeros(R, dtype=np.int32)
        row_active = np.zeros(R, dtype=bool)
        max_p = 1
        max_o = 1
        pre_lists = []
        slot_lists = []
        out_lists = []
        for i in range(gn):
            nid = int(idx2nid[beg_index + i])
            base[i] = nodes[nid].base
            row_active[i] = bool(index_map[beg_index + i])
            if i == 0 or not row_active[i]:
                pre_lists.append([])
                slot_lists.append([])
                out_lists.append([])
                continue
            pl = []
            slots = []
            for k_in, p in enumerate(nodes[nid].in_ids):
                if index_map[int(g.node_id_to_index[p])]:
                    pl.append(int(g.node_id_to_index[p]) - beg_index)
                    slots.append(k_in)
            pre_lists.append(pl)
            slot_lists.append(slots)
            if banded and i < gn - 1:
                ol = [int(g.node_id_to_index[o]) - beg_index for o in nodes[nid].out_ids]
                out_lists.append(ol)
            else:
                out_lists.append([])
            max_p = max(max_p, len(pl))
            max_o = max(max_o, len(ol) if banded and i < gn - 1 else 1)
        P = _bucket_pow2(max_p)
        O = _bucket_pow2(max_o)
        pre_idx = np.zeros((R, P), dtype=np.int32)
        pre_msk = np.zeros((R, P), dtype=bool)
        out_idx = np.zeros((R, O), dtype=np.int32)
        out_msk = np.zeros((R, O), dtype=bool)
        pre_score = np.zeros((R, P), dtype=np.int32) if abpt.inc_path_score else None
        for i in range(gn):
            pl = pre_lists[i]
            pre_idx[i, : len(pl)] = pl
            pre_msk[i, : len(pl)] = True
            if pre_score is not None and pl:
                nid = int(idx2nid[beg_index + i])
                pre_score[i, : len(pl)] = [
                    g.incre_path_score(nid, k_in) for k_in in slot_lists[i]]
            ol = out_lists[i]
            out_idx[i, : len(ol)] = ol
            out_msk[i, : len(ol)] = True
        # last row (end node) is computed like the reference: loop stops before it
        row_active_scan = row_active.copy()
        row_active_scan[gn - 1:] = False

        remain_rows = np.zeros(R, dtype=np.int32)
        mpl0 = np.zeros(R, dtype=np.int32)
        mpr0 = np.zeros(R, dtype=np.int32)
        remain_end = 0
        if zdrop_on and not banded:
            # Z-drop needs max_remain even without banding (oracle.py:126)
            remain = g.node_id_to_max_remain
            for i in range(gn):
                remain_rows[i] = remain[int(idx2nid[beg_index + i])]
            remain_end = int(remain[end_node_id])
        if banded:
            remain = g.node_id_to_max_remain
            mpl_g = g.node_id_to_max_pos_left
            mpr_g = g.node_id_to_max_pos_right
            # first-row seeding (abpoa_align_simd.c:617-626)
            mpl_g[beg_node_id] = mpr_g[beg_node_id] = 0
            for out_id in nodes[beg_node_id].out_ids:
                if index_map[int(g.node_id_to_index[out_id])]:
                    mpl_g[out_id] = mpr_g[out_id] = 1
            for i in range(gn):
                nid = int(idx2nid[beg_index + i])
                remain_rows[i] = remain[nid]
                mpl0[i] = mpl_g[nid]
                mpr0[i] = mpr_g[nid]
            remain_end = int(remain[end_node_id])
            r0 = qlen - (int(remain[beg_node_id]) - remain_end - 1)
            dp_end0 = min(qlen, max(int(mpr_g[beg_node_id]), r0) + w)
        else:
            dp_end0 = qlen

    mat = abpt.mat
    qp = np.zeros((abpt.m, Qp), dtype=np.int32)
    if qlen:
        qp[:, 1: qlen + 1] = mat[:, query]

    # sink-predecessor candidates for global best = the end row's pre slots
    sink_rows = [int(x) for x in pre_idx[gn - 1][pre_msk[gn - 1]]]
    if not sink_rows:
        sink_rows = [0]
    SR = _bucket_pow2(len(sink_rows))
    sink_rows_a = np.zeros(SR, dtype=np.int32)
    sink_rows_a[: len(sink_rows)] = sink_rows
    sink_msk = np.zeros(SR, dtype=bool)
    sink_msk[: len(sink_rows)] = True

    if pre_score is None:
        pre_score = np.zeros_like(pre_idx)
    return dict(base=base, pre_idx=pre_idx, pre_msk=pre_msk, out_idx=out_idx,
                out_msk=out_msk, row_active=row_active_scan,
                remain_rows=remain_rows, mpl0=mpl0, mpr0=mpr0, qp=qp,
                query=query.astype(np.int32), pre_score=pre_score,
                sink_rows=sink_rows_a, sink_msk=sink_msk,
                qlen=qlen, w=w, remain_end=remain_end, dp_end0=dp_end0,
                gn=gn, R=R, Qp=Qp, beg_index=beg_index)


def _pad_snapshot(s: dict, R: int, P: int, O: int, Qp: int, SR: int) -> dict:
    """Pad one snapshot's arrays to the batch's common bucket sizes; padding
    rows/slots are masked off, so results are unchanged."""
    def pad(a, shape):
        out = np.zeros(shape, dtype=a.dtype)
        out[tuple(slice(0, d) for d in a.shape)] = a
        return out
    return dict(
        base=pad(s["base"], (R,)), pre_idx=pad(s["pre_idx"], (R, P)),
        pre_msk=pad(s["pre_msk"], (R, P)), out_idx=pad(s["out_idx"], (R, O)),
        out_msk=pad(s["out_msk"], (R, O)),
        row_active=pad(s["row_active"], (R,)),
        remain_rows=pad(s["remain_rows"], (R,)),
        mpl0=pad(s["mpl0"], (R,)), mpr0=pad(s["mpr0"], (R,)),
        qp=pad(s["qp"], (s["qp"].shape[0], Qp)),
        query=pad(s["query"], (Qp,)), pre_score=pad(s["pre_score"], (R, P)),
        sink_rows=pad(s["sink_rows"], (SR,)), sink_msk=pad(s["sink_msk"], (SR,)),
        qlen=s["qlen"], w=s["w"], remain_end=s["remain_end"],
        dp_end0=s["dp_end0"])


def _result_from_packed(g: POAGraph, abpt: Params, packed: np.ndarray,
                        snap: dict, R: int, max_ops: int) -> AlignResult:
    """Unpack one window's device output: band write-back + cigar rebuild."""
    res = AlignResult()
    qlen = snap["qlen"]
    gn, beg_index = snap["gn"], snap["beg_index"]
    idx2nid = g.index_to_node_id
    banded = abpt.wb >= 0
    (n_ops, fin_i, fin_j, n_aln, n_match, si, sj, err,
     best_score, best_i, best_j) = [int(x) for x in packed[:11]]
    off = 11
    mpl_j = packed[off: off + R]
    mpr_j = packed[off + R: off + 2 * R]
    ops = packed[off + 2 * R:].reshape(max_ops, 2)

    if banded:
        if getattr(g, "is_native", False):
            g.write_band(beg_index, gn, mpl_j[:gn], mpr_j[:gn])
        else:
            nids = idx2nid[beg_index: beg_index + gn]
            g.node_id_to_max_pos_left[nids] = mpl_j[:gn]
            g.node_id_to_max_pos_right[nids] = mpr_j[:gn]

    res.best_score = best_score
    if not abpt.ret_cigar:
        return res
    if err:
        raise RuntimeError(
            f"device backtrack failed at ({fin_i},{fin_j}) gap_mode={abpt.gap_mode}")
    res.n_aln_bases = n_aln
    res.n_matched_bases = n_match

    # rebuild the packed cigar from the op stream (reference order: reversed)
    cigar: list = []
    if best_j < qlen:
        push_cigar(cigar, C.CINS, qlen - best_j, -1, qlen - 1)
    jj = best_j
    for t in range(n_ops):
        opc, dpi = int(ops[t, 0]), int(ops[t, 1])
        nid = int(idx2nid[beg_index + dpi])
        if opc == 0:
            push_cigar(cigar, C.CMATCH, 1, nid, jj - 1)
            jj -= 1
        elif opc == 1:
            push_cigar(cigar, C.CDEL, 1, nid, jj - 1)
        else:
            push_cigar(cigar, C.CINS, 1, nid, jj - 1)
            jj -= 1
    if fin_j > 0:
        push_cigar(cigar, C.CINS, fin_j, -1, fin_j - 1)
    if not abpt.rev_cigar:
        cigar.reverse()
    res.cigar = cigar
    res.node_e = int(idx2nid[best_i + beg_index])
    res.query_e = best_j - 1
    res.node_s = int(idx2nid[si + beg_index])
    res.query_s = sj - 1
    return res


_ARRAY_KEYS = ("base", "pre_idx", "pre_msk", "out_idx", "out_msk",
               "row_active", "remain_rows", "mpl0", "mpr0", "qp", "query",
               "pre_score", "sink_rows", "sink_msk")
_SCALAR_KEYS = ("qlen", "w", "remain_end", "dp_end0")


@functools.partial(jax.jit, static_argnames=(
    "gap_mode", "local", "banded", "n_steps", "align_mode", "gap_on_right",
    "put_gap_at_end", "max_ops", "ret_cigar", "zdrop_on"))
def _dp_full_batch(arrays, scalars, inf_min, scores, zdrop, **statics):
    """vmap of _dp_full over the window axis: all windows of one seeded read
    are independent alignments against the same frozen graph
    (/root/reference/src/abpoa_align.c:209-310), so one dispatch covers them."""
    o1, e1, oe1, o2, e2, oe2 = scores

    def one(arr, sc):
        return _dp_full(
            arr["base"], arr["pre_idx"], arr["pre_msk"], arr["out_idx"],
            arr["out_msk"], arr["row_active"], arr["remain_rows"],
            arr["mpl0"], arr["mpr0"], arr["qp"], arr["query"], arr["mat"],
            arr["sink_rows"], arr["sink_msk"],
            sc["qlen"], sc["w"], sc["remain_end"], inf_min, sc["dp_end0"],
            o1, e1, oe1, o2, e2, oe2,
            pre_score=arr["pre_score"], zdrop=zdrop, **statics)

    return jax.vmap(one, in_axes=({k: 0 for k in list(_ARRAY_KEYS) + ["mat"]},
                                  {k: 0 for k in _SCALAR_KEYS}))(arrays, scalars)


def _window_mesh_size(B: int) -> int:
    """Largest power-of-two device count that divides the (power-of-two)
    window batch; 1 disables sharding (single chip / single window)."""
    try:
        n_avail = len(jax.devices())
    except RuntimeError:  # backend init failed: single-slot fallback
        return 1
    n = 1
    while n * 2 <= min(n_avail, B):
        n *= 2
    return n


def _dp_full_batch_sharded(arrays, scalars, inf_min, scores, zdrop,
                           n_dev: int, **statics):
    """Shard the window batch over an n_dev-device mesh.

    Seeded windows are independent alignments against the same frozen graph
    (reference src/abpoa_align.c:268-290), so the batch splits across chips
    with no collectives — this is the v5e-8 scaling axis for one read set
    (`-S` mode): all 8 chips work on one read's windows at once.
    """
    from jax.sharding import Mesh, PartitionSpec as P
    import numpy as _np
    mesh = Mesh(_np.array(jax.devices()[:n_dev]), ("w",))
    fn = functools.partial(_dp_full_batch, **statics)
    from ..utils.jaxcompat import shard_map
    sharded = shard_map(fn, mesh=mesh,
                        in_specs=(P("w"), P("w"), P(), P(), P()),
                        out_specs=P("w"))
    return sharded(arrays, scalars, inf_min, scores, zdrop)


def align_windows_jax(g: POAGraph, abpt: Params,
                      windows) -> list:
    """Align a batch of independent subgraph windows in ONE device dispatch,
    sharded across all available devices when more than one is attached.

    windows: list of (beg_node_id, end_node_id, query) tuples. Returns one
    AlignResult per window, byte-identical to aligning them sequentially.
    """
    snaps = [_build_snapshot(g, abpt, b, e, q) for b, e, q in windows]
    R = max(s["R"] for s in snaps)
    Qp = max(s["Qp"] for s in snaps)
    P = max(s["pre_idx"].shape[1] for s in snaps)
    O = max(s["out_idx"].shape[1] for s in snaps)
    SR = max(s["sink_rows"].shape[0] for s in snaps)
    max_ops = R + Qp + 8
    padded = [_pad_snapshot(s, R, P, O, Qp, SR) for s in snaps]
    # bucket the batch dim like every other dim (bounds recompiles); dummy
    # entries duplicate the last window and their outputs are discarded
    B = _bucket_pow2(len(padded))
    padded.extend(padded[-1:] * (B - len(padded)))
    mat = np.ascontiguousarray(abpt.mat.astype(np.int32))
    arrays = {k: jnp.asarray(np.stack([p[k] for p in padded]))
              for k in _ARRAY_KEYS}
    arrays["mat"] = jnp.broadcast_to(jnp.asarray(mat),
                                     (len(padded),) + mat.shape)
    scalars = {k: jnp.asarray(np.array([p[k] for p in padded], dtype=np.int32))
               for k in _SCALAR_KEYS}
    inf_min = dp_inf_min(abpt)
    extend = abpt.align_mode == C.EXTEND_MODE
    zdrop_on = extend and abpt.zdrop > 0

    statics = dict(
        gap_mode=abpt.gap_mode, local=abpt.align_mode == C.LOCAL_MODE,
        banded=abpt.wb >= 0, n_steps=R - 1, align_mode=abpt.align_mode,
        gap_on_right=bool(abpt.put_gap_on_right),
        put_gap_at_end=bool(abpt.put_gap_at_end), max_ops=max_ops,
        ret_cigar=bool(abpt.ret_cigar), zdrop_on=zdrop_on)
    args = (arrays, scalars, jnp.int32(inf_min),
            (jnp.int32(abpt.gap_open1), jnp.int32(abpt.gap_ext1),
             jnp.int32(abpt.gap_oe1), jnp.int32(abpt.gap_open2),
             jnp.int32(abpt.gap_ext2), jnp.int32(abpt.gap_oe2)),
            jnp.int32(max(abpt.zdrop, 0)))
    n_dev = _window_mesh_size(len(padded))
    from ..obs import device_capture, trace
    bucket = dict(B=B, R=R, Qp=Qp, P=P, O=O, SR=SR, n_dev=n_dev,
                  gap_mode=abpt.gap_mode, align_mode=abpt.align_mode,
                  banded=statics["banded"])
    with trace.span("align_windows", "dp",
                    args={"windows": len(snaps), "B": B, "R": R, "Qp": Qp}):
        # the sharded variant rebuilds its shard_map per call, so only the
        # unsharded path has a jit cache handle; the sharded path falls back
        # to first-sight-of-bucket compile detection
        with device_capture("window_batch"):
            with registry.watch("dp_full_batch", bucket,
                                use_handle=n_dev == 1):
                if n_dev > 1:
                    packed = _dp_full_batch_sharded(*args, n_dev=n_dev,
                                                    **statics)
                else:
                    packed = _dp_full_batch(*args, **statics)
                # ONE device->host transfer for all windows (inside the
                # compile bracket so its wall covers execution, not just
                # the async dispatch)
                packed = np.asarray(packed)
    return [_result_from_packed(g, abpt, packed[i], snaps[i], R, max_ops)
            for i in range(len(snaps))]


def align_sequence_to_subgraph_jax(g: POAGraph, abpt: Params, beg_node_id: int,
                                   end_node_id: int, query: np.ndarray) -> AlignResult:
    return align_windows_jax(g, abpt, [(beg_node_id, end_node_id, query)])[0]


@functools.partial(jax.jit, static_argnames=(
    "gap_mode", "local", "banded", "n_steps", "align_mode", "gap_on_right",
    "put_gap_at_end", "max_ops", "ret_cigar", "zdrop_on"))
def _dp_full(base, pre_idx, pre_msk, out_idx, out_msk, row_active,
             remain_rows, mpl0, mpr0, qp, query_pad, mat, sink_rows, sink_msk,
             qlen, w, remain_end, inf_min, dp_end0,
             o1, e1, oe1, o2, e2, oe2,
             gap_mode: int, local: bool, banded: bool, n_steps: int,
             align_mode: int, gap_on_right: bool, put_gap_at_end: bool,
             max_ops: int, ret_cigar: bool,
             zdrop_on: bool = False, pre_score=None, zdrop=0):
    """DP scan + best selection + device backtrack, one packed int32 output."""
    from .jax_backtrack import device_backtrack

    extend = align_mode == C.EXTEND_MODE
    (Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, mpl, mpr,
     row_max, row_left, row_right, bs, bi, bj) = _dp_scan(
        base, pre_idx, pre_msk, out_idx, out_msk, row_active,
        remain_rows, mpl0, mpr0, qp,
        qlen, w, remain_end, inf_min, dp_end0,
        o1, e1, oe1, o2, e2, oe2,
        gap_mode=gap_mode, local=local, banded=banded, n_steps=n_steps,
        extend=extend, zdrop_on=zdrop_on, pre_score=pre_score, zdrop=zdrop)

    if align_mode == C.GLOBAL_MODE:
        ends = jnp.minimum(qlen, dp_end[sink_rows])
        vals = jnp.where(sink_msk, Hb[sink_rows, ends], inf_min)
        k = jnp.argmax(vals)  # first max wins, like the strict > in the reference
        best_score = vals[k]
        best_i = sink_rows[k]
        best_j = ends[k]
    elif align_mode == C.EXTEND_MODE:
        # best-so-far carried in the scan (required for Z-drop stop semantics)
        best_score, best_i, best_j = bs, bi, bj
    else:
        k = jnp.argmax(row_max)  # first row achieving the max
        best_score = row_max[k]
        best_i = (k + 1).astype(jnp.int32)
        best_j = row_left[k].astype(jnp.int32)

    if ret_cigar:
        ops, n_ops, fi, fj, n_aln, n_match, si, sj, err = device_backtrack(
            Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, pre_idx, pre_msk,
            base, query_pad, mat, best_i, best_j,
            e1, oe1, e2, oe2,
            gap_mode=gap_mode, local=local, gap_on_right=gap_on_right,
            put_gap_at_end=put_gap_at_end, max_ops=max_ops,
            pre_score=pre_score)
    else:
        ops = jnp.zeros((max_ops, 2), jnp.int32)
        n_ops = fi = fj = n_aln = n_match = si = sj = jnp.int32(0)
        err = jnp.bool_(False)

    head = jnp.stack([n_ops, fi, fj, n_aln, n_match, si, sj,
                      err.astype(jnp.int32), best_score,
                      best_i.astype(jnp.int32), best_j.astype(jnp.int32)])
    return jnp.concatenate([head, mpl, mpr, ops.reshape(-1)])


register_backend("jax", align_sequence_to_subgraph_jax)


# --------------------------------------------------------------------------- #
# compile-ladder integration (abpoa_tpu/compile)                              #
# --------------------------------------------------------------------------- #

def _warm_window_batch(abpt: Params, anchor) -> list:
    """AOT-compile the seeded-window batch (`_dp_full_batch`) for the
    anchor's window shape: zero-filled inputs with every row inactive and
    qlen 0, so the DP scan sweeps masked rows and the backtrack exits at
    (0, 0) — the dispatch cost is the compile. Shapes mirror
    align_windows_jax's planner (R/Qp geometric rungs, pow2 degree axes)."""
    from ..obs import compile_log
    R = _bucket(anchor.qmax + 2, 64)
    Qp = _bucket(anchor.qmax + 1, 128)
    P = O = 4       # typical POA in/out-degree rung
    SR = 2
    B = _bucket_pow2(max(1, anchor.windows or 1))
    max_ops = R + Qp + 8
    m = abpt.m
    arrays = {
        "base": jnp.zeros((B, R), jnp.int32),
        "pre_idx": jnp.zeros((B, R, P), jnp.int32),
        "pre_msk": jnp.zeros((B, R, P), bool),
        "out_idx": jnp.zeros((B, R, O), jnp.int32),
        "out_msk": jnp.zeros((B, R, O), bool),
        "row_active": jnp.zeros((B, R), bool),
        "remain_rows": jnp.zeros((B, R), jnp.int32),
        "mpl0": jnp.zeros((B, R), jnp.int32),
        "mpr0": jnp.zeros((B, R), jnp.int32),
        "qp": jnp.zeros((B, m, Qp), jnp.int32),
        "query": jnp.zeros((B, Qp), jnp.int32),
        "pre_score": jnp.zeros((B, R, P), jnp.int32),
        "sink_rows": jnp.zeros((B, SR), jnp.int32),
        "sink_msk": jnp.zeros((B, SR), bool),
        "mat": jnp.zeros((B, m, m), jnp.int32),
    }
    scalars = {k: jnp.zeros(B, jnp.int32) for k in _SCALAR_KEYS}
    extend = abpt.align_mode == C.EXTEND_MODE
    statics = dict(
        gap_mode=abpt.gap_mode, local=abpt.align_mode == C.LOCAL_MODE,
        banded=abpt.wb >= 0, n_steps=R - 1, align_mode=abpt.align_mode,
        gap_on_right=bool(abpt.put_gap_on_right),
        put_gap_at_end=bool(abpt.put_gap_at_end), max_ops=max_ops,
        ret_cigar=True, zdrop_on=extend and abpt.zdrop > 0)
    bucket = dict(B=B, R=R, Qp=Qp, P=P, O=O, SR=SR, n_dev=1,
                  gap_mode=abpt.gap_mode, align_mode=abpt.align_mode,
                  banded=statics["banded"])
    scores = (jnp.int32(abpt.gap_open1), jnp.int32(abpt.gap_ext1),
              jnp.int32(abpt.gap_oe1), jnp.int32(abpt.gap_open2),
              jnp.int32(abpt.gap_ext2), jnp.int32(abpt.gap_oe2))
    with registry.watch("dp_full_batch", bucket) as cw:
        out = _dp_full_batch(arrays, scalars, jnp.int32(dp_inf_min(abpt)),
                             scores, jnp.int32(max(abpt.zdrop, 0)), **statics)
        np.asarray(out)  # sync inside the bracket
    recs = compile_log.run_records()
    rec = (recs[-1] if recs and recs[-1]["fn"] == "dp_full_batch"
           else {"fn": "dp_full_batch", "bucket": bucket,
                 "cache_hit": not cw["compiled"]})
    return [rec]


registry.register_entry("dp_full_batch", handle=lambda: _dp_full_batch,
                        warmer=_warm_window_batch)
