"""Pallas TPU kernel for the fused loop's banded DP forward pass.

Differences from pallas_kernel.py (the per-alignment prototype):
- sized for fused-loop graphs (R up to ~100k rows): per-row tables arrive as
  blocked VMEM streams (one (1, x) block per grid step) instead of R-sized
  SMEM arrays, which would blow the ~1 MB SMEM budget;
- band metadata lives in small SMEM rings: measured predecessor/successor
  topo-distances on real 10 kb read sets peak at 18-31 rows (PERF.md), so a
  D=512 ring gives ~16x headroom and the overflow flag fires effectively
  never (the caller falls back to the XLA-scan kernel in-jit when it does);
- dp_beg/dp_end stream out per row (the windowed device backtrack needs
  them); mpl/mpr are NOT output — the fused loop rebuilds adaptive-band
  state from the graph each read, matching the reference's re-init in
  abpoa_topological_sort;
- covers all three gap regimes (linear/affine/convex, global banded) and
  both plane widths (int16 while the reference promotion bound allows,
  int32 after — /root/reference/src/abpoa_align_simd.c:1293-1302). int16
  planes double the effective VPU lanes exactly where most reads live.

Semantics are identical to fused_loop._dp_banded row for row; reference:
/root/reference/src/abpoa_align_simd.c:727-1074 (lg/ag/cg kernels), band
macros src/abpoa_align.h:34-35.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import constants as C

# ring capacity (rows) for predecessor windows and band scalars
RING_D = 512


def _make_kernel(W: int, P: int, O: int, D: int, gap_mode: int, plane16: bool):
    linear = gap_mode == C.LINEAR_GAP
    convex = gap_mode == C.CONVEX_GAP
    dt = jnp.int16 if plane16 else jnp.int32

    def kernel(sc_ref, base_ref, pre_idx_ref, pre_cnt_ref, out_idx_ref,
               out_cnt_ref, remain_ref, row0H_ref, row0E1_ref, row0E2_ref,
               qp_ref,
               H_out, E1_out, E2_out, F1_out, F2_out, beg_out, end_out,
               ok_out, *scratch):
        if convex:
            (ringH, ringE1, ringE2, beg_s, end_s, mpl_s, mpr_s, ok_s) = scratch
        elif linear:
            (ringH, beg_s, end_s, mpl_s, mpr_s, ok_s) = scratch
            ringE1 = ringE2 = None
        else:
            (ringH, ringE1, beg_s, end_s, mpl_s, mpr_s, ok_s) = scratch
            ringE2 = None
        i = pl.program_id(0)
        n_steps = pl.num_programs(0)
        qlen = sc_ref[0]
        w = sc_ref[1]
        remain_end = sc_ref[2]
        inf = sc_ref[3].astype(dt)
        e1, oe1 = sc_ref[4].astype(dt), sc_ref[5].astype(dt)
        e2, oe2 = sc_ref[6].astype(dt), sc_ref[7].astype(dt)
        gn = sc_ref[8]
        end0 = sc_ref[9]

        col = lax.broadcasted_iota(jnp.int32, (1, W), 1)
        neg_row = jnp.full((1, W), inf, dt)

        @pl.when(i == 0)
        def _init():
            ok_s[0] = jnp.where(end0 + 1 > W, 0, 1)

            def seed(k, _):
                # mpl/mpr ring defaults (reference re-init: mpl=n, mpr=0);
                # src-out seeding to 1 is patched below via the row-1.. blocks
                mpl_s[k] = gn
                mpr_s[k] = 0
                beg_s[k] = 0
                end_s[k] = 0
                return 0
            lax.fori_loop(0, D, seed, 0)
            beg_s[0] = 0
            end_s[0] = end0
            ringH[0, :] = row0H_ref[0, :]
            if not linear:
                ringE1[0, :] = row0E1_ref[0, :]
            if convex:
                ringE2[0, :] = row0E2_ref[0, :]

        row = i + 1
        active = (row < gn - 1) & (ok_s[0] == 1)

        # the src's out rows get mpl=mpr=1 (first-row band seeding); the host
        # packs that flag into base's high bits to stay block-streamed
        b_packed = base_ref[0, 0]
        is_src_out = (b_packed & 0x100) != 0
        base_v = b_packed & 0xFF

        @pl.when(active & is_src_out)
        def _seed_src_out():
            # src-out rows are seeded mpl=mpr=1 BEFORE the row loop in the
            # sequential kernel; earlier rows may already have scattered onto
            # this slot, so combine (min/max against the seed) instead of
            # assigning — identical to seeding first and scattering after
            mpl_s[row % D] = jnp.minimum(mpl_s[row % D], 1)
            mpr_s[row % D] = jnp.maximum(mpr_s[row % D], 1)

        @pl.when(active)
        def _row():
            r = qlen - (remain_ref[0, 0] - remain_end - 1)
            mpl_v = mpl_s[row % D]
            mpr_v = mpr_s[row % D]
            beg = jnp.maximum(0, jnp.minimum(mpl_v, r) - w)
            end = jnp.minimum(qlen, jnp.maximum(mpr_v, r) + w)
            npre = pre_cnt_ref[0, 0]

            def mpb(k, acc):
                p = pre_idx_ref[0, k]
                return jnp.minimum(acc, beg_s[p % D])
            min_pre_beg = lax.fori_loop(0, npre, mpb, jnp.int32(2**30))
            beg = jnp.maximum(beg, min_pre_beg)

            # overflow: band wider than W, pred outside the ring, or a
            # successor further than the ring can scatter
            def povf(k, acc):
                return acc | (row - pre_idx_ref[0, k] >= D)
            ovf = lax.fori_loop(0, npre, povf, end - beg + 1 > W)

            def sovf(k, acc):
                return acc | (out_idx_ref[0, k] - row >= D)
            ovf = lax.fori_loop(0, out_cnt_ref[0, 0], sovf, ovf)

            @pl.when(ovf)
            def _():
                ok_s[0] = 0
            beg_s[row % D] = beg
            end_s[row % D] = end

            cols = beg + col
            in_band = cols <= end

            def gather(ring_ref, p, shift):
                win = ring_ref[pl.ds(p % D, 1), :]
                sh = jnp.clip(shift, -W, W)
                padded = jnp.concatenate([neg_row, win, neg_row], axis=1)
                return lax.dynamic_slice(padded, (0, W + sh), (1, W))

            def pred_body(k, acc):
                Mq, E1r, E2r = acc
                p = pre_idx_ref[0, k]
                pbeg = beg_s[p % D]
                pend = end_s[p % D]
                hs = gather(ringH, p, beg - 1 - pbeg)
                hs = jnp.where((cols - 1 >= pbeg) & (cols - 1 <= pend), hs, inf)
                Mq = jnp.maximum(Mq, hs)
                eok = (cols >= pbeg) & (cols <= pend)
                if linear:
                    # E contribution reads the predecessor H plane directly
                    # (lg regime: no E plane exists)
                    hj = gather(ringH, p, beg - pbeg)
                    E1r = jnp.maximum(E1r, jnp.where(eok, hj, inf))
                else:
                    e1s = gather(ringE1, p, beg - pbeg)
                    E1r = jnp.maximum(E1r, jnp.where(eok, e1s, inf))
                    if convex:
                        e2s = gather(ringE2, p, beg - pbeg)
                        E2r = jnp.maximum(E2r, jnp.where(eok, e2s, inf))
                return (Mq, E1r, E2r)

            Mq, E1r, E2r = lax.fori_loop(
                0, npre, pred_body, (neg_row, neg_row, neg_row))

            qprow = qp_ref[pl.ds(base_v, 1), pl.ds(beg, W)]
            Mq = jnp.where(in_band, Mq + qprow, inf)

            def chain(A, ext):
                F = A
                shift = 1
                while shift < W:
                    rolled = pltpu.roll(F, shift, axis=1)
                    prev = jnp.where(col >= shift, rolled, inf)
                    F = jnp.maximum(
                        F, jnp.maximum(prev, inf + shift * ext) - shift * ext)
                    shift <<= 1
                return F

            if linear:
                # lg regime: Erow = max over preds of H[pre][j] - e1; H row is
                # an in-row gap chain over max(M, E) (fused_loop._dp_banded
                # linear branch; reference simd_abpoa_lg_dp :727-815)
                Erow = jnp.where(in_band, E1r - e1, inf)
                Hhat = jnp.maximum(Mq, Erow)
                Hrow = jnp.where(in_band, chain(Hhat, e1), inf)
                E1n = E2n = F1 = F2 = neg_row
            else:
                E1r = jnp.where(in_band, E1r, inf)
                Hhat = jnp.maximum(Mq, E1r)
                if convex:
                    E2r = jnp.where(in_band, E2r, inf)
                    Hhat = jnp.maximum(Hhat, E2r)
                Hm1 = jnp.where(col >= 1, pltpu.roll(Hhat, 1, axis=1), inf)
                A1 = jnp.where(in_band,
                               jnp.where(col == 0, Mq - oe1, Hm1 - oe1), inf)
                F1 = chain(A1, e1)
                Hrow = jnp.maximum(Hhat, F1)
                if convex:
                    A2 = jnp.where(in_band,
                                   jnp.where(col == 0, Mq - oe2, Hm1 - oe2),
                                   inf)
                    F2 = chain(A2, e2)
                    Hrow = jnp.maximum(Hrow, F2)
                    E1n = jnp.maximum(E1r - e1, Hrow - oe1)
                    E2n = jnp.maximum(E2r - e2, Hrow - oe2)
                else:
                    F2 = neg_row
                    # ag regime gates E on H == Hhat (reference
                    # simd_abpoa_ag_dp :817-933; _dp_banded affine branch)
                    E1n = jnp.maximum(E1r - e1, Hrow - oe1)
                    E1n = jnp.where(Hrow == Hhat, E1n, inf)
                    E2n = neg_row
                Hrow = jnp.where(in_band, Hrow, inf)
                E1n = jnp.where(in_band, E1n, inf)
                E2n = jnp.where(in_band, E2n, inf)
                F1 = jnp.where(in_band, F1, inf)
                F2 = jnp.where(in_band, F2, inf)

            ringH[row % D, :] = Hrow[0]
            if not linear:
                ringE1[row % D, :] = E1n[0]
            if convex:
                ringE2[row % D, :] = E2n[0]
            H_out[0, :] = Hrow[0]
            E1_out[0, :] = E1n[0]
            E2_out[0, :] = E2n[0]
            F1_out[0, :] = F1[0]
            F2_out[0, :] = F2[0]
            beg_out[0] = beg
            end_out[0] = end

            mx = jnp.max(Hrow)
            eq = (Hrow == mx) & in_band
            has = mx > inf
            left = jnp.where(has, beg + jnp.argmax(eq[0]).astype(jnp.int32), -1)
            right = jnp.where(
                has, beg + W - 1 - jnp.argmax(eq[0, ::-1]).astype(jnp.int32), -1)

            def out_body(k, _):
                t = out_idx_ref[0, k]
                mpr_s[t % D] = jnp.maximum(mpr_s[t % D], right + 1)
                mpl_s[t % D] = jnp.minimum(mpl_s[t % D], left + 1)
                return 0
            lax.fori_loop(0, out_cnt_ref[0, 0], out_body, 0)

            # this row's mpl/mpr ring slot now belongs to row+D: reset it
            # AFTER all reads/writes of row's own value (successors of rows
            # < row have already scattered; writers to row+D are rows
            # > row, which run later)
            mpl_s[row % D] = gn
            mpr_s[row % D] = 0

        @pl.when(~active)
        def _pad():
            H_out[0, :] = neg_row[0]
            E1_out[0, :] = neg_row[0]
            E2_out[0, :] = neg_row[0]
            F1_out[0, :] = neg_row[0]
            F2_out[0, :] = neg_row[0]
            beg_out[0] = 0
            end_out[0] = 0

        @pl.when(i == n_steps - 1)
        def _flush():
            ok_out[0] = ok_s[0]

    return kernel


@functools.partial(jax.jit, static_argnames=(
    "R", "W", "P", "O", "gap_mode", "plane16", "interpret"))
def pallas_fused_dp(scalars, base_packed, pre_idx, pre_cnt, out_idx, out_cnt,
                    remain_rows, row0H, row0E1, row0E2, qp_pad,
                    R: int, W: int, P: int, O: int,
                    gap_mode: int = C.CONVEX_GAP, plane16: bool = False,
                    interpret: bool = False):
    """Banded global forward DP for the fused loop (all gap regimes).

    base_packed: base | (is_src_out << 8) per row. qp_pad: (m, Qp + W) in the
    plane dtype. row0*: (1, W) plane dtype. scalars: (16,) int32.
    Returns (H, E1, E2, F1, F2, dp_beg, dp_end, ok); planes are (R, W) in the
    plane dtype (int16 when plane16). Unused planes for the lighter regimes
    are -inf filled, matching _dp_banded.
    """
    D = RING_D
    linear = gap_mode == C.LINEAR_GAP
    convex = gap_mode == C.CONVEX_GAP
    dt = jnp.int16 if plane16 else jnp.int32
    kernel = _make_kernel(W, P, O, D, gap_mode, plane16)
    m = qp_pad.shape[0]
    row_i32 = lambda width: pl.BlockSpec((1, width), lambda i: (i + 1, 0),
                                         memory_space=pltpu.SMEM)
    out_shapes = (
        [jax.ShapeDtypeStruct((R, W), dt)] * 5
        + [jax.ShapeDtypeStruct((R,), jnp.int32),
           jax.ShapeDtypeStruct((R,), jnp.int32),
           jax.ShapeDtypeStruct((1,), jnp.int32)])
    plane = pl.BlockSpec((1, W), lambda i: (i + 1, 0), memory_space=pltpu.VMEM)
    scalar_out = pl.BlockSpec((1,), lambda i: (i + 1,), memory_space=pltpu.SMEM)
    out_specs = [plane] * 5 + [scalar_out, scalar_out,
                               pl.BlockSpec((1,), lambda i: (0,),
                                            memory_space=pltpu.SMEM)]
    in_specs = [
        pl.BlockSpec((16,), lambda i: (0,), memory_space=pltpu.SMEM),
        row_i32(1),                 # base_packed (1,1) per row
        row_i32(P),                 # pre_idx
        row_i32(1),                 # pre_cnt
        row_i32(O),                 # out_idx
        row_i32(1),                 # out_cnt
        row_i32(1),                 # remain
        pl.BlockSpec((1, W), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, W), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, W), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, qp_pad.shape[1]), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),
    ]
    rings = [pltpu.VMEM((D, W), dt)]            # H ring
    if not linear:
        rings.append(pltpu.VMEM((D, W), dt))    # E1 ring
    if convex:
        rings.append(pltpu.VMEM((D, W), dt))    # E2 ring
    scratch = rings + [
        pltpu.SMEM((D,), jnp.int32),   # beg ring
        pltpu.SMEM((D,), jnp.int32),   # end ring
        pltpu.SMEM((D,), jnp.int32),   # mpl ring
        pltpu.SMEM((D,), jnp.int32),   # mpr ring
        pltpu.SMEM((1,), jnp.int32),   # ok
    ]
    fn = pl.pallas_call(
        kernel,
        grid=(R - 1,),
        out_shape=out_shapes,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
        interpret=interpret,
    )
    return fn(scalars, base_packed.reshape(R, 1), pre_idx, pre_cnt.reshape(R, 1),
              out_idx, out_cnt.reshape(R, 1), remain_rows.reshape(R, 1),
              row0H, row0E1, row0E2, qp_pad)
