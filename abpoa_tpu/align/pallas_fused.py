"""Pallas TPU kernel for the fused loop's banded DP forward pass.

Differences from pallas_kernel.py (the per-alignment prototype):
- sized for fused-loop graphs (R up to ~100k rows): per-row tables arrive as
  one packed (R, L) int32 metadata array streamed through VMEM in B-row
  blocks (Mosaic requires >=8-sublane blocks; (1, x) SMEM streams do not
  lower), DMAed block-at-a-time into SMEM for dynamic scalar reads, and the
  DP planes stream out in matching B-row blocks with the standard revisiting
  index map;
- K rows compute per grid step (static unroll): rows still run strictly in
  topo order inside the step, reading earlier rows through the VMEM rings,
  so the per-step grid/pipelining overhead amortizes K-fold without touching
  the sequential semantics;
- band metadata lives in small SMEM rings: measured predecessor/successor
  topo-distances on real 10 kb read sets peak at 18-31 rows (PERF.md), so a
  D=512 ring gives ~16x headroom and the overflow flag fires effectively
  never (the caller falls back to the XLA-scan kernel in-jit when it does);
- dp_beg/dp_end stream out per row (the windowed device backtrack needs
  them); mpl/mpr are NOT output — the fused loop rebuilds adaptive-band
  state from the graph each read, matching the reference's re-init in
  abpoa_topological_sort;
- covers all three gap regimes (linear/affine/convex), all three align
  modes (global banded; extend with Z-drop and local with best-anywhere
  tracking, both in SMEM scalars) and
  both plane widths (int16 while the reference promotion bound allows,
  int32 after — /root/reference/src/abpoa_align_simd.c:1293-1302). All
  in-kernel math runs in int32 (i16 vector ops do not legalize on Mosaic;
  the promotion bound guarantees every value fits int16, so int32 math is
  bit-identical) — int16 survives at the HBM interface via staged casts,
  halving plane traffic exactly where most reads live.

Semantics are identical to fused_loop._dp_banded row for row; reference:
/root/reference/src/abpoa_align_simd.c:727-1074 (lg/ag/cg kernels), band
macros src/abpoa_align.h:34-35.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import constants as C
from .pallas_common import (BLOCK_B, band_extents, make_ring_gather,
                            qp_band_row, roll_any)

# ring capacity (rows) for predecessor windows and band scalars
RING_D = 512
# rows computed per grid step (must divide BLOCK_B)
UNROLL_K = 8

# packed per-row metadata lane layout (see pallas_fused_dp)
_M_BASE, _M_NPRE, _M_NOUT, _M_REMAIN, _M_TAB = 0, 1, 2, 3, 4


def _row_dp_math(gap_mode, local, col, inf, neg_row, chain,
                 e1, oe1, e2, oe2, ext1_ref, ext2_ref):
    """Regime DP math for ONE row, shared by the VMEM-ring kernel and the
    HBM-resident local kernel: given the gathered predecessor maxima
    (Mq pre-qp, E1r, E2r), produce the five plane rows. Mirrors
    fused_loop._dp_banded row for row; reference lg/ag/cg kernels
    /root/reference/src/abpoa_align_simd.c:727-1074."""
    linear = gap_mode == C.LINEAR_GAP
    convex = gap_mode == C.CONVEX_GAP

    def math(Mq, E1r, E2r, in_band):
        if linear:
            # lg regime: Erow = max over preds of H[pre][j] - e1;
            # H row is an in-row gap chain over max(M, E)
            # (fused_loop._dp_banded linear branch; reference
            # simd_abpoa_lg_dp :727-815)
            Erow = jnp.where(in_band, E1r - e1, inf)
            Hhat = jnp.maximum(Mq, Erow)
            Hrow = chain(Hhat, ext1_ref)
            if local:
                Hrow = jnp.maximum(Hrow, 0)
            Hrow = jnp.where(in_band, Hrow, inf)
            E1n = E2n = F1 = F2 = neg_row
            return Hrow, E1n, E2n, F1, F2
        E1r = jnp.where(in_band, E1r, inf)
        Hhat = jnp.maximum(Mq, E1r)
        if convex:
            E2r = jnp.where(in_band, E2r, inf)
            Hhat = jnp.maximum(Hhat, E2r)
        Hm1 = jnp.where(col >= 1, roll_any(Hhat, 1), inf)
        A1 = jnp.where(in_band,
                       jnp.where(col == 0, Mq - oe1, Hm1 - oe1),
                       inf)
        F1 = chain(A1, ext1_ref)
        Hrow = jnp.maximum(Hhat, F1)
        if convex:
            A2 = jnp.where(in_band,
                           jnp.where(col == 0, Mq - oe2, Hm1 - oe2), inf)
            F2 = chain(A2, ext2_ref)
            Hrow = jnp.maximum(Hrow, F2)
            if local:  # clamp BEFORE deriving E (oracle order)
                Hrow = jnp.maximum(Hrow, 0)
            E1n = jnp.maximum(E1r - e1, Hrow - oe1)
            E2n = jnp.maximum(E2r - e2, Hrow - oe2)
            if local:
                E1n = jnp.maximum(E1n, 0)
                E2n = jnp.maximum(E2n, 0)
        else:
            F2 = neg_row
            if local:
                Hrow = jnp.maximum(Hrow, 0)
            # ag regime gates E on H == Hhat (reference simd_abpoa_ag_dp
            # :817-933; affine branch); the killed-E value is 0 in local
            E1n = jnp.maximum(E1r - e1, Hrow - oe1)
            W = col.shape[1]
            E1n = jnp.where(Hrow == Hhat, E1n,
                            jnp.zeros((1, W), jnp.int32)
                            if local else inf)
            E2n = neg_row
        Hrow = jnp.where(in_band, Hrow, inf)
        E1n = jnp.where(in_band, E1n, inf)
        E2n = jnp.where(in_band, E2n, inf)
        F1 = jnp.where(in_band, F1, inf)
        F2 = jnp.where(in_band, F2, inf)
        return Hrow, E1n, E2n, F1, F2

    return math


def _make_kernel(W: int, P: int, O: int, D: int, gap_mode: int, plane16: bool,
                 K: int, extend: bool = False, zdrop_on: bool = False,
                 local: bool = False):
    linear = gap_mode == C.LINEAR_GAP
    convex = gap_mode == C.CONVEX_GAP
    dt = jnp.int16 if plane16 else jnp.int32
    B = BLOCK_B
    steps_per_block = B // K

    def kernel(sc_ref, meta_ref, row0H_ref, row0E1_ref, row0E2_ref, qp_ref,
               H_out, E1_out, E2_out, F1_out, F2_out, beg_out, end_out,
               ok_out, ext_out, *scratch):
        if extend or local:
            # best-cell tracking state (extend: set_extend_max_score,
            # src/abpoa_align_simd.c:1082-1090; local: max-anywhere,
            # leftmost/earliest): [bs, bi, bj, brem, zdropped]
            best_s = scratch[-1]
            scratch = scratch[:-1]
        if plane16:
            # i16 plane rows cannot be stored at dynamic sublane offsets:
            # rows accumulate in i32 staging blocks, flushed (cast + whole-
            # block store, static index) once per B rows
            stag = scratch[-5:]
            scratch = scratch[:-5]
        if convex:
            (ringH, ringE1, ringE2, beg_s, end_s, mpl_s, mpr_s, ok_s,
             smeta, sem) = scratch
        elif linear:
            (ringH, beg_s, end_s, mpl_s, mpr_s, ok_s, smeta, sem) = scratch
            ringE1 = ringE2 = None
        else:
            (ringH, ringE1, beg_s, end_s, mpl_s, mpr_s, ok_s,
             smeta, sem) = scratch
            ringE2 = None
        g = pl.program_id(0)
        n_steps = pl.num_programs(0)
        qlen = sc_ref[0]
        w = sc_ref[1]
        remain_end = sc_ref[2]
        inf = sc_ref[3]
        e1, oe1 = sc_ref[4], sc_ref[5]
        e2, oe2 = sc_ref[6], sc_ref[7]
        gn = sc_ref[8]
        end0 = sc_ref[9]

        col = lax.broadcasted_iota(jnp.int32, (1, W), 1)
        neg_row = jnp.full((1, W), inf, jnp.int32)
        gather = make_ring_gather(col, neg_row, W, D)

        @pl.when(g == 0)
        def _init():
            ok_s[0] = jnp.where(end0 + 1 > W, 0, 1)
            if extend or local:
                best_s[0] = inf
                best_s[1] = 0
                best_s[2] = 0
                best_s[3] = 0
                best_s[4] = 0

            def seed(k, _):
                # mpl/mpr ring defaults (reference re-init: mpl=n, mpr=0);
                # src-out seeding to 1 is patched below via the row-1.. blocks
                mpl_s[k] = gn
                mpr_s[k] = 0
                beg_s[k] = 0
                end_s[k] = 0
                return 0
            lax.fori_loop(0, D, seed, 0)
            beg_s[0] = 0
            end_s[0] = end0
            ringH[0, :] = row0H_ref[0, :]
            if not linear:
                ringE1[0, :] = row0E1_ref[0, :]
            if convex:
                ringE2[0, :] = row0E2_ref[0, :]

        # one DMA per B-row block (not per row): the whole resident metadata
        # block drops into SMEM, where dynamic scalar reads are free
        @pl.when(g % steps_per_block == 0)
        def _load_meta():
            cp = pltpu.make_async_copy(meta_ref, smeta, sem)
            cp.start()
            cp.wait()

        def chain(A, ext32):
            # scalar ALU is i32-only on Mosaic: the clamp/step scalars stay
            # i32 splats (identical to the scan path by the promotion bound)
            F = A
            shift = 1
            while shift < W:
                rolled = roll_any(F, shift)
                prev = jnp.where(col >= shift, rolled, inf)
                clampv = jnp.full((1, W), sc_ref[3] + shift * ext32,
                                  jnp.int32)
                subv = jnp.full((1, W), shift * ext32, jnp.int32)
                F = jnp.maximum(F, jnp.maximum(prev, clampv) - subv)
                shift <<= 1
            return F

        def emit_row(j):
            """Row g*K + j: band update + DP + plane/ring writes. Rows run in
            order inside the step; later rows read earlier rows' ring slots
            exactly as across steps."""
            row = g * K + j
            sub = row % B
            active = (row >= 1) & (row < gn - 1) & (ok_s[0] == 1)

            # the src's out rows get mpl=mpr=1 (first-row band seeding); the
            # host packs that flag into base's high bits to stay streamed
            b_packed = smeta[sub, _M_BASE]
            is_src_out = (b_packed & 0x100) != 0
            base_v = b_packed & 0xFF

            @pl.when(active & is_src_out)
            def _seed_src_out():
                # src-out rows are seeded mpl=mpr=1 BEFORE the row loop in
                # the sequential kernel; earlier rows may already have
                # scattered onto this slot, so combine (min/max against the
                # seed) instead of assigning — identical to seeding first
                # and scattering after
                mpl_s[row % D] = jnp.minimum(mpl_s[row % D], 1)
                mpr_s[row % D] = jnp.maximum(mpr_s[row % D], 1)

            @pl.when(active)
            def _row():
                npre = smeta[sub, _M_NPRE]
                nout = smeta[sub, _M_NOUT]
                if local:
                    # local mode disables banding: full-width rows [0, qlen]
                    beg = jnp.int32(0)
                    end = qlen
                else:
                    r = qlen - (smeta[sub, _M_REMAIN] - remain_end - 1)
                    mpl_v = mpl_s[row % D]
                    mpr_v = mpr_s[row % D]
                    beg = jnp.maximum(0, jnp.minimum(mpl_v, r) - w)
                    end = jnp.minimum(qlen, jnp.maximum(mpr_v, r) + w)

                    def mpb(k, acc):
                        p = smeta[sub, _M_TAB + k]
                        return jnp.minimum(acc, beg_s[p % D])
                    min_pre_beg = lax.fori_loop(0, npre, mpb, jnp.int32(2**30))
                    beg = jnp.maximum(beg, min_pre_beg)

                # overflow: band wider than W, pred outside the ring, or a
                # successor further than the ring can scatter
                def povf(k, acc):
                    return acc | (row - smeta[sub, _M_TAB + k] >= D)
                ovf = lax.fori_loop(0, npre, povf, end - beg + 1 > W)

                def sovf(k, acc):
                    return acc | (smeta[sub, _M_TAB + P + k] - row >= D)
                ovf = lax.fori_loop(0, nout, sovf, ovf)

                @pl.when(ovf)
                def _():
                    ok_s[0] = 0
                beg_s[row % D] = beg
                end_s[row % D] = end

                cols = beg + col
                in_band = cols <= end

                def pred_body(k, acc):
                    Mq, E1r, E2r = acc
                    p = smeta[sub, _M_TAB + k]
                    pbeg = beg_s[p % D]
                    pend = end_s[p % D]
                    hs = gather(ringH, p, beg - 1 - pbeg)
                    hs = jnp.where((cols - 1 >= pbeg) & (cols - 1 <= pend),
                                   hs, inf)
                    Mq = jnp.maximum(Mq, hs)
                    eok = (cols >= pbeg) & (cols <= pend)
                    if linear:
                        # E contribution reads the predecessor H plane
                        # directly (lg regime: no E plane exists)
                        hj = gather(ringH, p, beg - pbeg)
                        E1r = jnp.maximum(E1r, jnp.where(eok, hj, inf))
                    else:
                        e1s = gather(ringE1, p, beg - pbeg)
                        E1r = jnp.maximum(E1r, jnp.where(eok, e1s, inf))
                        if convex:
                            e2s = gather(ringE2, p, beg - pbeg)
                            E2r = jnp.maximum(E2r, jnp.where(eok, e2s, inf))
                    return (Mq, E1r, E2r)

                Mq, E1r, E2r = lax.fori_loop(
                    0, npre, pred_body, (neg_row, neg_row, neg_row))

                if local:
                    # the lead cell (absolute col -1) counts as 0
                    Mq = jnp.where(cols == 0, jnp.maximum(Mq, 0), Mq)
                qprow = qp_band_row(qp_ref, base_v, beg, W)
                Mq = jnp.where(in_band, Mq + qprow, inf)

                math = _row_dp_math(gap_mode, local, col, inf, neg_row,
                                    chain, e1, oe1, e2, oe2,
                                    sc_ref[4], sc_ref[6])
                Hrow, E1n, E2n, F1, F2 = math(Mq, E1r, E2r, in_band)

                ringH[row % D, :] = Hrow[0]
                if not linear:
                    ringE1[row % D, :] = E1n[0]
                if convex:
                    ringE2[row % D, :] = E2n[0]
                plane_rows = (Hrow, E1n, E2n, F1, F2)
                plane_outs = (H_out, E1_out, E2_out, F1_out, F2_out)
                if plane16:
                    for st, val in zip(stag, plane_rows):
                        st[sub, :] = val[0]
                else:
                    for o, val in zip(plane_outs, plane_rows):
                        o[sub, :] = val[0]
                beg_out[pl.ds(sub, 1), :] = jnp.full((1, 1), beg, jnp.int32)
                end_out[pl.ds(sub, 1), :] = jnp.full((1, 1), end, jnp.int32)

                left, right, mx, has_row = band_extents(Hrow, in_band, cols,
                                                        sc_ref[3])

                if local:
                    # best-anywhere cell: leftmost column, earliest row on
                    # ties (strict >), mirroring _dp_banded's local branch
                    bs = best_s[0]
                    better = mx > bs
                    best_s[0] = jnp.where(better, mx, bs)
                    best_s[1] = jnp.where(better, row, best_s[1])
                    best_s[2] = jnp.where(better, left, best_s[2])
                if extend:
                    # sequential best/Z-drop bookkeeping in SMEM scalars,
                    # mirroring _dp_banded's extend branch row for row. Rows
                    # after a Z-drop keep computing planes (the grid cannot
                    # break) but never touch best state or the band scatter,
                    # so every backtrack-reachable output matches the scan's.
                    rrem = smeta[sub, _M_REMAIN]
                    bs, bj, brem = best_s[0], best_s[2], best_s[3]
                    zdr = best_s[4] == 1
                    better = (~zdr) & (mx > bs)
                    if zdrop_on:
                        delta = brem - rrem
                        zd_real = has_row & \
                            (bs - mx > sc_ref[10]
                             + sc_ref[4] * jnp.abs(delta - (right - bj)))
                        zd = (~zdr) & (~better) & \
                            (zd_real | ((~has_row) & (bs > inf)))
                        best_s[4] = jnp.where(zd, 1, best_s[4])
                    best_s[0] = jnp.where(better, mx, bs)
                    best_s[1] = jnp.where(better, row, best_s[1])
                    best_s[2] = jnp.where(better, right, bj)
                    best_s[3] = jnp.where(better, rrem, brem)

                if not local:  # local bypasses the band formula entirely
                    def out_body(k, _):
                        t = smeta[sub, _M_TAB + P + k]
                        mpr_s[t % D] = jnp.maximum(mpr_s[t % D], right + 1)
                        mpl_s[t % D] = jnp.minimum(mpl_s[t % D], left + 1)
                        return 0

                    if extend and zdrop_on:
                        # the scan gates the scatter on the POST-update flag
                        # (a row that trips Z-drop does not scatter)
                        @pl.when(best_s[4] == 0)
                        def _scatter():
                            lax.fori_loop(0, nout, out_body, 0)
                    else:
                        lax.fori_loop(0, nout, out_body, 0)

                    # this row's mpl/mpr ring slot now belongs to row+D:
                    # reset it AFTER all reads/writes of row's own value
                    # (successors of rows < row have already scattered;
                    # writers to row+D are rows > row, which run later)
                    mpl_s[row % D] = gn
                    mpr_s[row % D] = 0

            @pl.when(~active)
            def _pad():
                if plane16:
                    for st in stag:
                        st[sub, :] = neg_row[0]
                else:
                    for o in (H_out, E1_out, E2_out, F1_out, F2_out):
                        o[sub, :] = neg_row[0]
                zero11 = jnp.zeros((1, 1), jnp.int32)
                beg_out[pl.ds(sub, 1), :] = zero11
                end_out[pl.ds(sub, 1), :] = zero11

        for j in range(K):
            emit_row(j)

        if plane16:
            @pl.when((g % steps_per_block == steps_per_block - 1)
                     | (g == n_steps - 1))
            def _flush_planes():
                for o, st in zip((H_out, E1_out, E2_out, F1_out, F2_out),
                                 stag):
                    o[:, :] = st[:, :].astype(dt)

        @pl.when(g == n_steps - 1)
        def _flush():
            ok_out[0] = ok_s[0]
            if extend or local:
                ext_out[0] = best_s[0]
                ext_out[1] = best_s[1]
                ext_out[2] = best_s[2]
                ext_out[3] = best_s[4]
            else:
                ext_out[0] = inf
                ext_out[1] = 0
                ext_out[2] = 0
                ext_out[3] = 0

    return kernel


def _make_local_hbm_kernel(W: int, P: int, gap_mode: int, plane16: bool):
    """Local-mode kernel for band widths past the VMEM ring budget
    (10 kb+ reads): the plane OUTPUTS in HBM double as the row history —
    the reference's own storage plan (the full DP matrix lives in DRAM,
    src/abpoa_simd.c:52-83) — and each row DMAs just its predecessors'
    rows into small VMEM scratch buffers. No rings, so there is no
    predecessor-distance limit and ok is always 1; rows are full-width
    (local disables banding, src/abpoa_align.c:167), so all plane rows
    share column origin 0 and pred reads need no band realignment."""
    linear = gap_mode == C.LINEAR_GAP
    convex = gap_mode == C.CONVEX_GAP
    dt = jnp.int16 if plane16 else jnp.int32
    B = BLOCK_B

    def kernel(sc_ref, meta_ref, row0H_ref, row0E1_ref, row0E2_ref, qp_ref,
               H_out, E1_out, E2_out, F1_out, F2_out, beg_out, end_out,
               ok_out, ext_out, *scratch):
        best_s = scratch[-1]
        (predH, predE1, predE2, rowbufH, rowbufE1, rowbufE2,
         rowbufF1, rowbufF2, smeta, sem, wsem) = scratch[:-1]
        row = pl.program_id(0)
        n_steps = pl.num_programs(0)
        sub = row % B
        qlen = sc_ref[0]
        inf = sc_ref[3]
        e1, oe1 = sc_ref[4], sc_ref[5]
        e2, oe2 = sc_ref[6], sc_ref[7]
        gn = sc_ref[8]

        col = lax.broadcasted_iota(jnp.int32, (1, W), 1)
        neg_row = jnp.full((1, W), inf, jnp.int32)

        def chain(A, ext32):
            F = A
            shift = 1
            while shift < W:
                rolled = roll_any(F, shift)
                prev = jnp.where(col >= shift, rolled, inf)
                clampv = jnp.full((1, W), inf + shift * ext32, jnp.int32)
                subv = jnp.full((1, W), shift * ext32, jnp.int32)
                F = jnp.maximum(F, jnp.maximum(prev, clampv) - subv)
                shift <<= 1
            return F

        @pl.when(row == 0)
        def _init():
            best_s[0] = inf
            best_s[1] = 0
            best_s[2] = 0
            # row 0 planes land in HBM so row 1+ can DMA them back like any
            # other predecessor row
            for o, r0 in ((H_out, row0H_ref), (E1_out, row0E1_ref),
                          (E2_out, row0E2_ref)):
                rowbufH[0, :] = r0[0, :].astype(dt)
                cp = pltpu.make_async_copy(
                    rowbufH.at[pl.ds(0, 1)], o.at[pl.ds(0, 1)], wsem)
                cp.start()
                cp.wait()
            rowbufH[0, :] = neg_row[0].astype(dt)
            for o in (F1_out, F2_out):
                cp = pltpu.make_async_copy(
                    rowbufH.at[pl.ds(0, 1)], o.at[pl.ds(0, 1)], wsem)
                cp.start()
                cp.wait()

        @pl.when(row % B == 0)
        def _load_meta():
            cp = pltpu.make_async_copy(meta_ref, smeta, sem)
            cp.start()
            cp.wait()

        active = (row >= 1) & (row < gn - 1)

        @pl.when(active)
        def _row():
            b_packed = smeta[sub, _M_BASE]
            base_v = b_packed & 0xFF
            npre = smeta[sub, _M_NPRE]
            in_band = col <= qlen

            def pred_body(k, acc):
                Mq, E1r, E2r = acc
                p = smeta[sub, _M_TAB + k]
                cp = pltpu.make_async_copy(
                    H_out.at[pl.ds(p, 1)], predH, sem)
                cp.start()
                cp.wait()
                hrow = predH[0, :][None].astype(jnp.int32)
                hs = jnp.where(col >= 1, roll_any(hrow, 1), 0)
                # absolute col-1 == -1 is the lead cell, score 0 in local
                Mq = jnp.maximum(Mq, jnp.where(col == 0, 0, hs))
                if linear:
                    E1r = jnp.maximum(E1r, hrow)
                else:
                    cp = pltpu.make_async_copy(
                        E1_out.at[pl.ds(p, 1)], predE1, sem)
                    cp.start()
                    cp.wait()
                    E1r = jnp.maximum(E1r, predE1[0, :][None]
                                      .astype(jnp.int32))
                    if convex:
                        cp = pltpu.make_async_copy(
                            E2_out.at[pl.ds(p, 1)], predE2, sem)
                        cp.start()
                        cp.wait()
                        E2r = jnp.maximum(E2r, predE2[0, :][None]
                                          .astype(jnp.int32))
                return (Mq, E1r, E2r)

            Mq, E1r, E2r = lax.fori_loop(
                0, npre, pred_body, (neg_row, neg_row, neg_row))

            qprow = qp_band_row(qp_ref, base_v, jnp.int32(0), W)
            Mq = jnp.where(in_band, Mq + qprow, inf)

            math = _row_dp_math(gap_mode, True, col, inf, neg_row,
                                chain, e1, oe1, e2, oe2,
                                sc_ref[4], sc_ref[6])
            Hrow, E1n, E2n, F1, F2 = math(Mq, E1r, E2r, in_band)

            for buf, val in ((rowbufH, Hrow), (rowbufE1, E1n),
                             (rowbufE2, E2n), (rowbufF1, F1),
                             (rowbufF2, F2)):
                buf[0, :] = val[0].astype(dt)
            for buf, o in ((rowbufH, H_out), (rowbufE1, E1_out),
                           (rowbufE2, E2_out), (rowbufF1, F1_out),
                           (rowbufF2, F2_out)):
                cp = pltpu.make_async_copy(
                    buf.at[pl.ds(0, 1)], o.at[pl.ds(row, 1)], wsem)
                cp.start()
                cp.wait()

            left, right, mx, has_row = band_extents(Hrow, in_band, col,
                                                    sc_ref[3])
            bs = best_s[0]
            better = mx > bs
            best_s[0] = jnp.where(better, mx, bs)
            best_s[1] = jnp.where(better, row, best_s[1])
            best_s[2] = jnp.where(better, left, best_s[2])

        beg_out[pl.ds(sub, 1), :] = jnp.zeros((1, 1), jnp.int32)
        end_out[pl.ds(sub, 1), :] = jnp.full((1, 1), qlen, jnp.int32)

        @pl.when(row == n_steps - 1)
        def _flush():
            ok_out[0] = 1
            ext_out[0] = best_s[0]
            ext_out[1] = best_s[1]
            ext_out[2] = best_s[2]
            ext_out[3] = 0

    return kernel


def meta_lanes(P: int, O: int) -> int:
    """Packed per-row metadata width, rounded up to full 128-lane registers."""
    return -(-(_M_TAB + P + O) // 128) * 128


def fits_vmem_local_hbm(W: int, gap_mode: int, plane16: bool,
                        m: int = 32, Qp: int = 0) -> bool:
    """VMEM working set of the HBM-resident local kernel: 8 single-row
    scratch buffers + the resident query profile + streamed beg/end blocks.
    Scales with W (one row), not D x W (the ring) — a 10 kb local read
    (W=16384) needs ~1.2 MB of rows + ~650 KB of profile."""
    itemsize = 2 if plane16 else 4
    row_bytes = 8 * W * itemsize
    qp_bytes = m * (Qp + W) * 4
    blk_bytes = 2 * 2 * BLOCK_B * 4  # beg/end (B,1) blocks, double-buffered
    return row_bytes + qp_bytes + blk_bytes <= 11 * 2**20


@functools.partial(jax.jit, static_argnames=(
    "R", "W", "P", "O", "gap_mode", "plane16", "interpret"))
def pallas_fused_dp_local_hbm(scalars, base_packed, pre_idx, pre_cnt,
                              out_idx, out_cnt, remain_rows,
                              row0H, row0E1, row0E2, qp_pad,
                              R: int, W: int, P: int, O: int,
                              gap_mode: int = C.CONVEX_GAP,
                              plane16: bool = False,
                              interpret: bool = False):
    """Local-mode forward DP with HBM-resident plane history (see
    _make_local_hbm_kernel). Same signature contract as pallas_fused_dp
    restricted to local mode; ok is always 1."""
    B = BLOCK_B
    dt = jnp.int16 if plane16 else jnp.int32
    kernel = _make_local_hbm_kernel(W, P, gap_mode, plane16)
    m = qp_pad.shape[0]
    L = meta_lanes(P, O)
    meta = jnp.concatenate(
        [base_packed[:, None], pre_cnt[:, None], out_cnt[:, None],
         remain_rows[:, None], pre_idx, out_idx], axis=1)
    meta = jnp.pad(meta, ((0, 0), (0, L - meta.shape[1])))
    out_shapes = (
        [jax.ShapeDtypeStruct((R, W), dt)] * 5
        + [jax.ShapeDtypeStruct((R, 1), jnp.int32),
           jax.ShapeDtypeStruct((R, 1), jnp.int32),
           jax.ShapeDtypeStruct((1,), jnp.int32),
           jax.ShapeDtypeStruct((4,), jnp.int32)])
    blk1 = pl.BlockSpec((B, 1), lambda g: (g // B, 0),
                        memory_space=pltpu.VMEM)
    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    out_specs = [any_spec] * 5 + [
        blk1, blk1,
        pl.BlockSpec((1,), lambda g: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec((4,), lambda g: (0,), memory_space=pltpu.SMEM)]
    in_specs = [
        pl.BlockSpec((16,), lambda g: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec((B, L), lambda g: (g // B, 0),
                     memory_space=pltpu.VMEM),  # DMAed into SMEM per block
        pl.BlockSpec((1, W), lambda g: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, W), lambda g: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, W), lambda g: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, qp_pad.shape[1]), lambda g: (0, 0),
                     memory_space=pltpu.VMEM),
    ]
    scratch = (
        [pltpu.VMEM((1, W), dt)] * 3      # pred H/E1/E2 fetch buffers
        + [pltpu.VMEM((1, W), dt)] * 5    # row output staging H/E1/E2/F1/F2
        + [pltpu.SMEM((B, L), jnp.int32),  # metadata block
           pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA,
           pltpu.SMEM((5,), jnp.int32)])   # best-cell state
    fn = pl.pallas_call(
        kernel,
        grid=(R,),
        out_shape=out_shapes,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
        interpret=interpret,
    )
    (H, E1, E2, F1, F2, beg, end, ok, ext) = fn(
        scalars, meta, row0H.astype(jnp.int32), row0E1.astype(jnp.int32),
        row0E2.astype(jnp.int32), qp_pad)
    return H, E1, E2, F1, F2, beg[:, 0], end[:, 0], ok, ext


def fits_vmem(W: int, gap_mode: int, plane16: bool,
              m: int = 32, Qp: int = 0) -> bool:
    """Static check that the kernel's VMEM working set (rings + streamed
    blocks + the fully-resident (m, Qp+W) query profile) fits the ~16 MB
    budget with headroom. Local mode's full-width rows can push W to the
    query length; callers fall back to the XLA scan when it would not fit.
    The (BLOCK_B, meta_lanes) metadata block is KBs — ignored."""
    rings = {C.LINEAR_GAP: 1, C.AFFINE_GAP: 2, C.CONVEX_GAP: 3}[gap_mode]
    ring_bytes = rings * RING_D * W * 4
    # 5 plane output blocks, double-buffered, plus i32 staging for int16
    blk_bytes = (5 * 2 + (5 if plane16 else 0)) * BLOCK_B * W * 4
    qp_bytes = m * (Qp + W) * 4
    return ring_bytes + blk_bytes + qp_bytes <= 11 * 2**20


@functools.partial(jax.jit, static_argnames=(
    "R", "W", "P", "O", "gap_mode", "plane16", "extend", "zdrop_on",
    "local", "interpret"))
def pallas_fused_dp(scalars, base_packed, pre_idx, pre_cnt, out_idx, out_cnt,
                    remain_rows, row0H, row0E1, row0E2, qp_pad,
                    R: int, W: int, P: int, O: int,
                    gap_mode: int = C.CONVEX_GAP, plane16: bool = False,
                    extend: bool = False, zdrop_on: bool = False,
                    local: bool = False, interpret: bool = False):
    """Banded forward DP for the fused loop (all gap regimes; global,
    extend with optional Z-drop — set_extend_max_score,
    src/abpoa_align_simd.c:1076-1090 — and local mode: full-width rows,
    0-clamped cells, best-anywhere cell in the ext output).

    base_packed: base | (is_src_out << 8) per row. qp_pad: (m, Qp + W) int32.
    row0*: (1, W) plane dtype (widened to int32 internally). scalars: (16,)
    int32 with the Z-drop threshold at slot 10.
    Returns (H, E1, E2, F1, F2, dp_beg, dp_end, ok, ext); planes are (R, W)
    in the plane dtype (int16 when plane16), ext is (4,) int32
    [best_score, best_i, best_j, zdropped] (inf/0/0/0 when not extend).
    Unused planes for the lighter regimes are -inf filled, matching
    _dp_banded.
    """
    D = RING_D
    B = BLOCK_B
    K = UNROLL_K
    assert B % K == 0
    linear = gap_mode == C.LINEAR_GAP
    convex = gap_mode == C.CONVEX_GAP
    dt = jnp.int16 if plane16 else jnp.int32
    kernel = _make_kernel(W, P, O, D, gap_mode, plane16, K,
                          extend=extend, zdrop_on=zdrop_on, local=local)
    m = qp_pad.shape[0]
    L = meta_lanes(P, O)
    meta = jnp.concatenate(
        [base_packed[:, None], pre_cnt[:, None], out_cnt[:, None],
         remain_rows[:, None], pre_idx, out_idx], axis=1)
    meta = jnp.pad(meta, ((0, 0), (0, L - meta.shape[1])))
    out_shapes = (
        [jax.ShapeDtypeStruct((R, W), dt)] * 5
        + [jax.ShapeDtypeStruct((R, 1), jnp.int32),
           jax.ShapeDtypeStruct((R, 1), jnp.int32),
           jax.ShapeDtypeStruct((1,), jnp.int32),
           jax.ShapeDtypeStruct((4,), jnp.int32)])
    # rows g*K..g*K+K-1 of grid step g stay inside one B-row block (K | B)
    blk = lambda width: pl.BlockSpec((B, width),
                                     lambda g: (g * K // B, 0),
                                     memory_space=pltpu.VMEM)
    out_specs = [blk(W)] * 5 + [blk(1), blk(1),
                                pl.BlockSpec((1,), lambda g: (0,),
                                             memory_space=pltpu.SMEM),
                                pl.BlockSpec((4,), lambda g: (0,),
                                             memory_space=pltpu.SMEM)]
    in_specs = [
        pl.BlockSpec((16,), lambda g: (0,), memory_space=pltpu.SMEM),
        blk(L),                     # packed per-row metadata
        pl.BlockSpec((1, W), lambda g: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, W), lambda g: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, W), lambda g: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, qp_pad.shape[1]), lambda g: (0, 0),
                     memory_space=pltpu.VMEM),
    ]
    # rings are int32 regardless of plane width: Mosaic cannot address i16
    # VMEM rows at dynamic sublane offsets (packed tiling); ring values are
    # exact int16 so the read/write casts are lossless
    rings = [pltpu.VMEM((D, W), jnp.int32)]            # H ring
    if not linear:
        rings.append(pltpu.VMEM((D, W), jnp.int32))    # E1 ring
    if convex:
        rings.append(pltpu.VMEM((D, W), jnp.int32))    # E2 ring
    scratch = rings + [
        pltpu.SMEM((D,), jnp.int32),   # beg ring
        pltpu.SMEM((D,), jnp.int32),   # end ring
        pltpu.SMEM((D,), jnp.int32),   # mpl ring
        pltpu.SMEM((D,), jnp.int32),   # mpr ring
        pltpu.SMEM((1,), jnp.int32),   # ok
        pltpu.SMEM((B, L), jnp.int32),  # current metadata block (DMA target)
        pltpu.SemaphoreType.DMA,
    ]
    if plane16:
        # i32 staging blocks for the five plane outputs (see kernel)
        scratch += [pltpu.VMEM((B, W), jnp.int32)] * 5
    if extend or local:
        scratch.append(pltpu.SMEM((5,), jnp.int32))  # best-cell state
    fn = pl.pallas_call(
        kernel,
        grid=(pl.cdiv(R, K),),
        out_shape=out_shapes,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
        interpret=interpret,
    )
    (H, E1, E2, F1, F2, beg, end, ok, ext) = fn(
        scalars, meta, row0H.astype(jnp.int32), row0E1.astype(jnp.int32),
        row0E2.astype(jnp.int32), qp_pad)
    return H, E1, E2, F1, F2, beg[:, 0], end[:, 0], ok, ext
