"""Fused-loop eligibility predicates.

Kept in a module with NO jax dependency: callers consult these BEFORE the
accelerator liveness probe (pipeline device rerouting, the `-l` lockstep
runner), and importing any jax-touching module at that point could
initialize a wedged backend and hang (utils/probe.py).
"""
from __future__ import annotations

from .. import constants as C
from ..params import Params


def fused_config_eligible(abpt: Params) -> bool:
    """Config-only part of fused-loop eligibility: the fused device loop
    covers the reference's progressive-POA configurations in all three
    align modes (global banded, extend with Z-drop, local unbanded);
    remaining corners (-G path scores, qv-weighted multi-consensus) use
    the host kernels (pipeline._reroute_device_ineligible)."""
    return ((abpt.align_mode == C.LOCAL_MODE  # unbanded by definition
             or (abpt.align_mode in (C.GLOBAL_MODE, C.EXTEND_MODE)
                 and abpt.wb >= 0))
            and not abpt.inc_path_score
            and not (abpt.use_qv and abpt.max_n_cons > 1)
            and abpt.ret_cigar)


def fused_eligible(abpt: Params, n_seq: int) -> bool:
    return (fused_config_eligible(abpt)
            and not (abpt.incr_fn and abpt.use_read_ids)
            and n_seq >= 2)
