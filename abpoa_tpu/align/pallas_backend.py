"""`device=pallas` backend: banded Pallas forward kernel + host traceback.

Covers the default headline config (convex gap, global mode, adaptive band);
everything else falls through to the XLA-scan backend. On non-TPU hosts the
kernel runs in interpret mode so the whole path stays testable on the CPU
mesh. The band-overflow / ring-overflow flag triggers a transparent fallback.
"""
from __future__ import annotations

import numpy as np

import jax

from .. import constants as C
from ..compile.buckets import bucket as _bucket
from ..compile.buckets import bucket_pow2 as _bucket_pow2
from ..graph import POAGraph
from ..params import Params
from .dispatch import register_backend
from .jax_backend import align_sequence_to_subgraph_jax
from .oracle import INT32_MIN, _DPState, _backtrack, _build_index_map, dp_inf_min
from .result import AlignResult


class _NodeView:
    __slots__ = ("base",)

    def __init__(self, base):
        self.base = base


class _Nodes:
    def __init__(self, gv):
        self._gv = gv

    def __getitem__(self, node_id):
        i = int(self._gv._n2i[node_id]) - self._gv._beg_index
        return _NodeView(int(self._gv._base[i]))


class _GraphView:
    """Minimal graph facade so the host traceback can run off the native
    core's snapshot tables (base per dp row + index maps)."""

    def __init__(self, g, base_rows, beg_index):
        self.index_to_node_id = g.index_to_node_id
        self._n2i = g.node_id_to_index
        self._base = base_rows
        self._beg_index = beg_index
        self.nodes = _Nodes(self)


def _is_tpu() -> bool:
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        return False


def align_sequence_to_subgraph_pallas(g: POAGraph, abpt: Params, beg_node_id: int,
                                      end_node_id: int, query: np.ndarray) -> AlignResult:
    if (abpt.gap_mode != C.CONVEX_GAP or abpt.align_mode != C.GLOBAL_MODE
            or abpt.wb < 0 or abpt.inc_path_score):
        return align_sequence_to_subgraph_jax(g, abpt, beg_node_id, end_node_id, query)

    from .pallas_kernel import pallas_banded_dp

    qlen = len(query)
    w = abpt.wb + int(abpt.wf * qlen)
    inf_min = dp_inf_min(abpt)

    # ---- snapshot tables (native core when available) -----------------------
    if getattr(g, "is_native", False):
        t = g.build_tables(beg_node_id, end_node_id, True,
                           lambda n: _bucket(n, 64), _bucket_pow2)
        base, pre_idx, pre_msk = t["base"], t["pre_idx"], t["pre_msk"]
        out_idx, out_msk = t["out_idx"], t["out_msk"]
        remain_rows, mpl0, mpr0 = t["remain_rows"], t["mpl0"], t["mpr0"]
        gn, R, beg_index, remain_end = t["gn"], t["R"], t["beg_index"], t["remain_end"]
        idx2nid = g.index_to_node_id
        row_active = t["row_active"]
    else:
        # reuse the python snapshot path from the jax backend by calling its
        # internals through a tiny local rebuild
        beg_index = int(g.node_id_to_index[beg_node_id])
        end_index = int(g.node_id_to_index[end_node_id])
        gn = end_index - beg_index + 1
        index_map = _build_index_map(g, beg_index, end_index)
        R = _bucket(gn, 64)
        idx2nid = g.index_to_node_id
        nodes = g.nodes
        base = np.zeros(R, dtype=np.int32)
        row_active = np.zeros(R, dtype=bool)
        pre_lists, out_lists = [], []
        max_p = max_o = 1
        for i in range(gn):
            nid = int(idx2nid[beg_index + i])
            base[i] = nodes[nid].base
            row_active[i] = bool(index_map[beg_index + i]) and 0 < i < gn - 1
            if i == 0 or not index_map[beg_index + i]:
                pre_lists.append([])
                out_lists.append([])
                continue
            pl_ = [int(g.node_id_to_index[p]) - beg_index for p in nodes[nid].in_ids
                   if index_map[int(g.node_id_to_index[p])]]
            ol = [int(g.node_id_to_index[o]) - beg_index for o in nodes[nid].out_ids] \
                if i < gn - 1 else []
            pre_lists.append(pl_)
            out_lists.append(ol)
            max_p = max(max_p, len(pl_))
            max_o = max(max_o, max(1, len(ol)))
        P = _bucket_pow2(max_p)
        O = _bucket_pow2(max_o)
        pre_idx = np.zeros((R, P), dtype=np.int32)
        pre_msk = np.zeros((R, P), dtype=bool)
        out_idx = np.zeros((R, O), dtype=np.int32)
        out_msk = np.zeros((R, O), dtype=bool)
        for i in range(gn):
            pre_idx[i, : len(pre_lists[i])] = pre_lists[i]
            pre_msk[i, : len(pre_lists[i])] = True
            out_idx[i, : len(out_lists[i])] = out_lists[i]
            out_msk[i, : len(out_lists[i])] = True
        remain = g.node_id_to_max_remain
        mpl_g, mpr_g = g.node_id_to_max_pos_left, g.node_id_to_max_pos_right
        mpl_g[beg_node_id] = mpr_g[beg_node_id] = 0
        for out_id in nodes[beg_node_id].out_ids:
            if index_map[int(g.node_id_to_index[out_id])]:
                mpl_g[out_id] = mpr_g[out_id] = 1
        remain_rows = np.zeros(R, dtype=np.int32)
        mpl0 = np.zeros(R, dtype=np.int32)
        mpr0 = np.zeros(R, dtype=np.int32)
        for i in range(gn):
            nid = int(idx2nid[beg_index + i])
            remain_rows[i] = remain[nid]
            mpl0[i] = mpl_g[nid]
            mpr0[i] = mpr_g[nid]
        remain_end = int(remain[end_node_id])

    P = pre_idx.shape[1]
    O = out_idx.shape[1]
    pre_cnt = pre_msk.sum(axis=1).astype(np.int32)
    out_cnt = out_msk.sum(axis=1).astype(np.int32)

    # band width: the adaptive band spans ~2w+1 plus drift slack; bucket to
    # lanes and fall back on overflow
    W = max(256, ((4 * w + 2 + 127) // 128) * 128)
    D = 64
    Qp = _bucket(qlen + 1, 128)

    # the kernel keeps all per-row tables in SMEM (1 MB/core on v5e): guard
    # the footprint and fall back to the full-width scan for huge graphs
    from .pallas_kernel import smem_words
    if 4 * smem_words(R, P, O) > 650_000:
        return align_sequence_to_subgraph_jax(g, abpt, beg_node_id, end_node_id, query)

    # row 0 init (source row), host-side
    r0 = qlen - (int(remain_rows[0]) - remain_end - 1)
    dp_end0 = min(qlen, max(int(mpr0[0]), r0) + w)
    if dp_end0 + 1 > W:
        return align_sequence_to_subgraph_jax(g, abpt, beg_node_id, end_node_id, query)
    o1, e1, oe1 = abpt.gap_open1, abpt.gap_ext1, abpt.gap_oe1
    o2, e2, oe2 = abpt.gap_open2, abpt.gap_ext2, abpt.gap_oe2
    cols = np.arange(W, dtype=np.int64)
    f1 = np.where((cols >= 1) & (cols <= dp_end0), -o1 - e1 * cols, inf_min)
    f2 = np.where((cols >= 1) & (cols <= dp_end0), -o2 - e2 * cols, inf_min)
    row0H = np.maximum(f1, f2)
    row0H[0] = 0
    row0H[dp_end0 + 1:] = inf_min
    row0E1 = np.full(W, inf_min, dtype=np.int64)
    row0E2 = np.full(W, inf_min, dtype=np.int64)
    row0E1[0], row0E2[0] = -oe1, -oe2
    row0F1 = f1.copy()
    row0F1[0] = inf_min
    row0F2 = f2.copy()
    row0F2[0] = inf_min

    qp_pad = np.zeros((abpt.m, Qp + W), dtype=np.int32)
    if qlen:
        qp_pad[:, 1: qlen + 1] = abpt.mat[:, query]

    scalars = np.zeros(16, dtype=np.int32)
    scalars[:12] = [qlen, w, remain_end, inf_min, o1, e1, oe1, o2, e2, oe2,
                    gn, dp_end0]

    out = pallas_banded_dp(
        scalars, base.astype(np.int32), pre_idx.astype(np.int32), pre_cnt,
        out_idx.astype(np.int32), out_cnt, remain_rows.astype(np.int32),
        mpl0.astype(np.int32), mpr0.astype(np.int32), qp_pad,
        row0H.astype(np.int32).reshape(1, W),
        row0E1.astype(np.int32).reshape(1, W),
        row0E2.astype(np.int32).reshape(1, W),
        R=R, W=W, P=P, O=O, D=D, Qp=Qp, interpret=not _is_tpu())
    Hb, E1b, E2b, F1b, F2b, begend, mplr, ok = [np.array(x) for x in out]
    if int(ok[0]) != 1:  # band or ring overflow: full-width fallback
        return align_sequence_to_subgraph_jax(g, abpt, beg_node_id, end_node_id, query)

    dp_beg = begend[:R].copy()
    dp_end = begend[R:].copy()
    mpl_fin = mplr[:R]
    mpr_fin = mplr[R:]
    # row 0 banded planes (host-computed)
    Hb[0], E1b[0], E2b[0] = row0H, row0E1, row0E2
    F1b[0], F2b[0] = row0F1, row0F2

    if getattr(g, "is_native", False):
        g.write_band(beg_index, gn, mpl_fin[:gn], mpr_fin[:gn])
    else:
        nids = idx2nid[beg_index: beg_index + gn]
        g.node_id_to_max_pos_left[nids] = mpl_fin[:gn]
        g.node_id_to_max_pos_right[nids] = mpr_fin[:gn]

    # ---- reconstruct full-width planes for the host traceback --------------
    st = _DPState(1, 0, 5, np.dtype(np.int32), inf_min)
    st.qlen = qlen
    full = lambda: np.full((gn, qlen + 1), inf_min, dtype=np.int32)
    H, E1, E2, F1, F2 = full(), full(), full(), full(), full()
    for i in range(gn):
        b, e = int(dp_beg[i]), int(dp_end[i])
        if e < b:
            continue
        n = e - b + 1
        H[i, b: e + 1] = Hb[i, :n]
        E1[i, b: e + 1] = E1b[i, :n]
        E2[i, b: e + 1] = E2b[i, :n]
        F1[i, b: e + 1] = F1b[i, :n]
        F2[i, b: e + 1] = F2b[i, :n]
    st.H, st.E1, st.E2, st.F1, st.F2 = H, E1, E2, F1, F2
    st.dp_beg, st.dp_end = dp_beg, dp_end

    pre_index = [list(pre_idx[i][pre_msk[i]]) for i in range(gn)]
    pre_ids = [list(range(len(p))) for p in pre_index]

    if getattr(g, "is_native", False):
        g = _GraphView(g, base, beg_index)

    res = AlignResult()
    best_score = inf_min
    best_i = best_j = 0
    for dp_i in pre_index[gn - 1]:
        end = min(qlen, int(dp_end[dp_i]))
        v = int(H[dp_i, end])
        if v > best_score:
            best_score, best_i, best_j = v, dp_i, end
    res.best_score = best_score
    if abpt.ret_cigar:
        _backtrack(g, abpt, st, pre_index, pre_ids, beg_index, best_i, best_j,
                   qlen, query, res, abpt.gap_mode, inf_min)
    return res


register_backend("pallas", align_sequence_to_subgraph_pallas)
