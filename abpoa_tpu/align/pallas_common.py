"""Helpers shared by the two Pallas TPU kernels (pallas_kernel.py,
pallas_fused.py) so Mosaic workarounds stay in one place and the kernels
cannot silently diverge."""
from __future__ import annotations

import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# rows per streamed VMEM block: divisible by the int32 (8) and int16 (16)
# sublane tilings, small enough that edge blocks stay cheap on tiny graphs
BLOCK_B = 32


def roll_any(x, shift, axis: int = 1):
    """pltpu.roll for any integer dtype: Mosaic's rotate is 32-bit only, so
    narrower vectors round-trip through int32 (lossless)."""
    if x.dtype == jnp.int32:
        return pltpu.roll(x, shift, axis=axis)
    return pltpu.roll(x.astype(jnp.int32), shift, axis=axis).astype(x.dtype)


def make_ring_gather(col, neg_row, W: int, D: int):
    """Band-realignment gather from a (D, W) VMEM ring.

    out[k] = win[k + sh] if 0 <= k + sh < W else -inf, expressed as a dynamic
    rotate + mask: Mosaic has no value-level dynamic_slice and no dynamic
    lane starts for VMEM loads, but tpu.dynamic_rotate takes traced shifts.
    """
    def gather(ring_ref, p, shift):
        win = ring_ref[pl.ds(p % D, 1), :]
        sh = jnp.clip(shift, -W, W)
        rolled = pltpu.roll(win, jnp.mod(-sh, W), axis=1)
        okc = (col + sh >= 0) & (col + sh < W)
        return jnp.where(okc, rolled, neg_row)
    return gather


def band_extents(Hrow, in_band, cols, inf32):
    """(left, right, mx, has): leftmost/rightmost band column achieving the
    row max (or -1 when the row is all -inf), the int32 row max, and whether
    a real max exists. Reductions run in int32 (Mosaic has no int16
    reductions) as min/max over the masked column index (no reversal, which
    does not lower)."""
    Hrow32 = Hrow.astype(jnp.int32)
    mx = jnp.max(Hrow32)
    eq = (Hrow32 == mx) & in_band
    has = mx > inf32
    left = jnp.where(has, jnp.min(jnp.where(eq, cols, 2**30)), -1)
    right = jnp.where(has, jnp.max(jnp.where(eq, cols, -1)), -1)
    return left, right, mx, has


def qp_band_row(qp_ref, base_v, beg, W: int):
    """The (1, W) query-profile band window for row base `base_v` starting at
    column `beg`: whole-row load + dynamic rotate (dynamic lane starts do not
    lower for VMEM loads). Never wraps: the row carries W lanes of padding."""
    qp_full = qp_ref[pl.ds(base_v, 1), :]
    return pltpu.roll(qp_full, jnp.mod(-beg, qp_full.shape[1]), axis=1)[:, :W]
