"""Single-dispatch all-device progressive POA.

The round-1 device path paid ~140 ms of link latency per read (one dispatch +
one download). This module removes the per-read link round-trips entirely: the
whole progressive loop — banded DP, device backtrack, cigar fusion, topological
order maintenance, band metadata — runs inside ONE jitted `lax.while_loop` over
the read set. The host uploads the padded read batch once and downloads the
final graph once; consensus/MSA generation stays on host (cheap, and needs the
reference's exact output walk anyway).

Design notes (what is different from the reference, and why it is safe):

- Banded plane storage. The reference allocates full-width DP rows and computes
  only the adaptive band segment (/root/reference/src/abpoa_align_simd.c:946-959).
  Here each row stores exactly one W-wide window starting at its band begin;
  predecessor cells are fetched by per-row window-relative gathers. Cells
  outside a row's band are -inf in both designs, so results are identical while
  HBM footprint drops from O(rows x qlen) to O(rows x W).

- Topological order maintenance by splicing, not per-read BFS. The reference
  re-runs a Kahn BFS after every fusion (/root/reference/src/abpoa_graph.c:322-357)
  because it is cheap in C. A sequential BFS on the TPU scalar core would
  dominate the loop, and — key observation — none of the DP/backtrack/fusion
  semantics depend on WHICH valid topological order is used: every tie-break in
  the kernel rides edge-slot order (weight-sorted, maintained exactly) or
  column positions, never the topo position of a node. Because backtrack paths
  walk rows in strictly increasing topo position, all new nodes of a read can
  be spliced into the existing order right after their path predecessor, a pure
  vectorized operation. Edges introduced by aligned-node reuse can (rarely)
  violate the spliced order; the loop detects this and falls back to the exact
  device Kahn sort (device_graph.topo_sort) for that read. The final
  host-side output pass re-runs the reference BFS order on the downloaded
  graph, so all emitted bytes match the reference exactly.

- max_remain by pointer doubling. remain[v] is the length of the
  heaviest-out-edge chain from v to the sink (abpoa_graph.c:268-309) — a
  function of the graph only. The chain pointers (slot 0 after the weight sort)
  form a forest into the sink, so remain is computed with log2(N) rounds of
  pointer jumping instead of a sequential reverse BFS.

- Vectorized fusion. One read's backtrack ops touch each graph node at most
  once (the alignment is a path), so all edge appends/reweights hit distinct
  slots and are scattered in parallel; new node ids are assigned by prefix sums
  (matching the reference's sequential allocation order,
  abpoa_graph.c:689-774). The only sequential hazard — two mismatch columns of
  the same read interacting with the same aligned-node group — is detected (by
  group-root collision counting) and routed to the sequential in-jit fusion
  fallback (device_graph.fuse_alignment).

Capacities (N nodes, E edge slots, W band window, Qp padded query) are static;
the loop exits with an error code when one is exceeded and the host wrapper
grows the bucket and resumes from the returned device state (no work is lost).
"""
from __future__ import annotations

import functools
import os
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import constants as C
from ..compile import registry
from ..compile.buckets import bucket as _bucket
from ..compile.buckets import bucket_pow2 as _bucket_pow2
from ..compile.buckets import grow_node_cap
from ..compile.ladder import (chunk_node_cap, k_rung, plan_chunk_buckets,
                              qp_rung, reads_rung)
from ..params import Params
from .device_graph import DeviceGraph, fuse_alignment, init_device_graph, topo_sort
# re-exported for device-path callers; defined in a jax-free module so
# pre-probe callers never import this one
from .eligibility import fused_config_eligible, fused_eligible  # noqa: F401
# imported for its side effects: persistent-cache wiring + the
# dp_full_batch registry entry land before this module's first compile
from . import jax_backend  # noqa: F401
from .oracle import (INT16_MIN, INT32_MIN, dp_inf_min, int16_score_limit,
                     max_score_bound)

# error codes reported by the fused loop (state.err)
ERR_OK = 0
ERR_NODE_CAP = 1     # node capacity N exhausted -> grow N
ERR_BAND_CAP = 2     # band wider than W -> grow W
ERR_EDGE_CAP = 3     # edge slots E exhausted -> grow E
ERR_BACKTRACK = 4    # device backtrack diverged (bug) -> host fallback
ERR_OPS_CAP = 5      # op stream longer than max_ops -> grow N (max_ops tracks N)
ERR_ALIGN_CAP = 6    # aligned-group slots A exhausted -> grow A (aa alphabets)
ERR_GRAPH_CAP = 7    # capacity hit inside the sequential fusion/Kahn fallback
#                      (no specific dimension reported) -> grow N, E and A
ERR_PROMOTE = 8      # int16 score bound exceeded -> switch planes to int32

# While-loop body unrolling. Each loop iteration processes this many DP rows /
# backtrack ops, masked at boundaries. Semantics are identical (overshoot rows
# are inactive no-ops; overshoot ops are predicated off); the win is k x fewer
# sequential loop iterations. Measured on the CPU backend (PERF.md):
# BT_UNROLL=6 is free (1.9s -> 2.0s on sim2k) and cuts the ~5M backtrack
# iterations of the north-star run 6x; DP unrolling is superlinearly SLOWER
# on CPU even with block commits (K=2: 1.4x, K=4: 4x), so it defaults off
# until it can be measured on a real chip — flip via ABPOA_TPU_DP_UNROLL.
# Chain-run carrying (VERDICT r2 idea) was measured unviable: only 4% of rows
# in the spliced order qualify (single pred at i-1 AND prev out-degree 1)
# because saturated POA backbone nodes keep multiple out-edges — see PERF.md.
DP_UNROLL = max(1, int(os.environ.get("ABPOA_TPU_DP_UNROLL", "1")))
BT_UNROLL = max(1, int(os.environ.get("ABPOA_TPU_BT_UNROLL", "6")))


class FusedState(NamedTuple):
    g: DeviceGraph
    order: jnp.ndarray    # (N,) topo index -> node id
    n2i: jnp.ndarray      # (N,) node id -> topo index
    remain: jnp.ndarray   # (N,) max_remain per node id
    read_idx: jnp.ndarray  # () int32: number of reads fused so far
    err: jnp.ndarray      # () int32 error code
    kahn_runs: jnp.ndarray  # () int32: spliced-order violations repaired
    paths: jnp.ndarray    # (n_reads, Pcap) each read's fusion path node ids
    path_lens: jnp.ndarray  # (n_reads,)
    collisions: jnp.ndarray  # () int32: sequential-fusion fallbacks taken
    rc_flags: jnp.ndarray  # (n_rc,) int32: 1 where amb-strand used the RC


def init_fused_state(N: int, E: int, A: int, n_reads: int = 1,
                     Pcap: int = 8, n_rc: int = 1) -> FusedState:
    return FusedState(
        g=init_device_graph(N, E, A),
        order=jnp.zeros(N, jnp.int32),
        n2i=jnp.zeros(N, jnp.int32),
        remain=jnp.zeros(N, jnp.int32),
        read_idx=jnp.int32(0),
        err=jnp.int32(ERR_OK),
        kahn_runs=jnp.int32(0),
        paths=jnp.zeros((n_reads, Pcap), jnp.int32),
        path_lens=jnp.zeros(n_reads, jnp.int32),
        collisions=jnp.int32(0),
        rc_flags=jnp.zeros(max(n_rc, 1), jnp.int32))


# --------------------------------------------------------------------------- #
# graph-order utilities                                                       #
# --------------------------------------------------------------------------- #

def _edge_sort(g: DeviceGraph) -> DeviceGraph:
    """Weight-descending exchange sort of every node's edge slots — the exact
    (unstable) tie behavior of the reference (abpoa_graph.c:192-219)."""
    E = g.in_ids.shape[1]

    def sort_node(ids, w, cnt):
        def outer(j, st):
            ids, w = st

            def inner(k, st):
                ids, w = st
                swap = (k < cnt) & (w[j] < w[k])
                wj, wk = w[j], w[k]
                ij, ik = ids[j], ids[k]
                w = w.at[j].set(jnp.where(swap, wk, wj)).at[k].set(jnp.where(swap, wj, wk))
                ids = ids.at[j].set(jnp.where(swap, ik, ij)).at[k].set(jnp.where(swap, ij, ik))
                return ids, w
            return lax.fori_loop(j + 1, E, inner, st)
        return lax.fori_loop(0, E, outer, (ids, w))

    in_ids, in_w = jax.vmap(sort_node)(g.in_ids, g.in_w, g.in_cnt)
    out_ids, out_w = jax.vmap(sort_node)(g.out_ids, g.out_w, g.out_cnt)
    return g._replace(in_ids=in_ids, in_w=in_w, out_ids=out_ids, out_w=out_w)


def _remain_doubling(g: DeviceGraph) -> jnp.ndarray:
    """max_remain via pointer jumping over the heaviest-out-edge forest.

    remain[sink] = -1; remain[v] = remain[argmax-w out-edge] + 1
    (slot 0 after the weight sort picks the same edge as the reference's
    strict-> scan, abpoa_graph.c:196-205). Values equal the reference's
    reverse-BFS results because remain is a pure graph function.
    """
    N = g.base.shape[0]
    nodes = jnp.arange(N, dtype=jnp.int32)
    active = nodes < g.node_n
    ptr = jnp.where(active & (nodes != C.SINK_NODE_ID), g.out_ids[:, 0],
                    C.SINK_NODE_ID).astype(jnp.int32)
    ptr = ptr.at[C.SINK_NODE_ID].set(C.SINK_NODE_ID)
    steps = jnp.where(nodes == C.SINK_NODE_ID, 0, 1).astype(jnp.int32)
    n_rounds = max(1, int(N - 1).bit_length())
    for _ in range(n_rounds):
        steps = steps + steps[ptr]
        ptr = ptr[ptr]
    return jnp.where(active, steps - 1, 0).astype(jnp.int32)


# --------------------------------------------------------------------------- #
# banded DP over graph rows                                                   #
# --------------------------------------------------------------------------- #

def _row0_planes(W, dp_end0, o1, e1, oe1, o2, e2, oe2, inf,
                 gap_mode: int = C.CONVEX_GAP, local: bool = False):
    """Row-0 (source row) plane windows per gap regime
    (abpoa_align_simd.c:582-688). Single source of truth — used by both
    _dp_banded's init and the Pallas path. Dtype follows the scalars.
    Local mode zero-fills every plane across the (full-width) band
    (oracle.py:178-185; reference first-row local init)."""
    dt = jnp.asarray(o1).dtype
    kw = jnp.arange(W, dtype=jnp.int32)
    kw_dt = kw.astype(dt)
    colv = kw <= dp_end0
    if local:
        z = jnp.where(colv, jnp.zeros(W, dt), inf)
        return z, z, z, z, z
    if gap_mode == C.LINEAR_GAP:
        H0 = jnp.where(colv, -e1 * kw_dt, inf)
        E10 = E20 = F10 = F20 = jnp.full(W, inf, dt)
    elif gap_mode == C.CONVEX_GAP:
        f1r = -o1 - e1 * kw_dt
        f2r = -o2 - e2 * kw_dt
        F10 = jnp.where(colv & (kw >= 1), f1r, inf)
        F20 = jnp.where(colv & (kw >= 1), f2r, inf)
        H0 = jnp.where(colv & (kw >= 1), jnp.maximum(f1r, f2r), inf).at[0].set(0)
        E10 = jnp.full(W, inf, dt).at[0].set(-oe1)
        E20 = jnp.full(W, inf, dt).at[0].set(-oe2)
    else:  # affine
        f1r = -o1 - e1 * kw_dt
        F10 = jnp.where(colv & (kw >= 1), f1r, inf)
        F20 = jnp.full(W, inf, dt)
        H0 = jnp.where(colv & (kw >= 1), f1r, inf).at[0].set(0)
        E10 = jnp.full(W, inf, dt).at[0].set(-oe1)
        E20 = jnp.full(W, inf, dt)
    return H0, E10, E20, F10, F20

@functools.partial(jax.jit, static_argnames=("gap_mode", "W", "plane16",
                                              "extend", "zdrop_on", "local",
                                              "static_rows"))
def _dp_banded(base_r, pre_idx, pre_msk, out_idx, out_msk, row_active,
               remain_rows, mpl0, mpr0, qp, n_rows,
               qlen, w, remain_end, inf_min, dp_end0,
               o1, e1, oe1, o2, e2, oe2,
               gap_mode: int, W: int, plane16: bool = False,
               extend: bool = False, zdrop_on: bool = False, zdrop=0,
               local: bool = False, static_rows: bool = False):
    """Adaptive-banded DP with W-wide windowed plane storage.

    Row i stores plane cells for absolute columns [dp_beg[i], dp_beg[i]+W);
    in-band cells outside [dp_beg, dp_end] and window cells past dp_end are
    -inf, matching the reference full-width semantics
    (/root/reference/src/abpoa_align_simd.c:935-1074, band macros
    src/abpoa_align.h:34-35). Global, extend, and local modes; extend tracks
    the running best cell with optional Z-drop termination
    (set_extend_max_score, abpoa_align_simd.c:1082-1090) in int32 scalar
    bookkeeping regardless of plane width, like the reference's scalar
    best-score variables. Local mode (reference: banding disabled,
    abpoa_post_set_para) runs full-width rows [0, qlen] with cells clamped
    at 0, the M lead treated as 0, and the best (leftmost, earliest-row)
    max-anywhere cell tracked in the same scalar slots.

    Returns (H, E1, E2, F1, F2, dp_beg, dp_end, row_left, row_right,
    band_overflow, best_score, best_i, best_j) — row_left/row_right are the
    realized per-row band extremes (formerly the push-accumulated mpl/mpr
    slots; band propagation is pull-based now, see the loop comment).
    """
    R = base_r.shape[0]
    P = pre_idx.shape[1]
    # int16 planes double the effective VPU lanes when the score bound allows
    # (the reference's width promotion, abpoa_align_simd.c:1293-1302)
    dt = jnp.int16 if plane16 else jnp.int32
    inf = inf_min.astype(dt)
    inf32 = jnp.int32(inf_min)
    e1_32 = jnp.int32(e1)
    o1, e1, oe1, o2, e2, oe2 = [x.astype(dt) for x in (o1, e1, oe1, o2, e2, oe2)]
    qp = qp.astype(dt)
    convex = gap_mode == C.CONVEX_GAP
    linear = gap_mode == C.LINEAR_GAP
    kw = jnp.arange(W, dtype=jnp.int32)

    # ---- first row: absolute cols [0, dp_end0] ------------------------------
    # single source of truth shared with the Pallas caller (_row0_planes)
    H0, E10, E20, F10, F20 = _row0_planes(
        W, dp_end0, o1, e1, oe1, o2, e2, oe2, inf, gap_mode=gap_mode,
        local=local)

    Hb = jnp.full((R, W), inf, dt).at[0].set(H0)
    E1b = jnp.full((R, W), inf, dt).at[0].set(E10)
    E2b = jnp.full((R, W), inf, dt).at[0].set(E20)
    F1b = jnp.full((R, W), inf, dt).at[0].set(F10)
    F2b = jnp.full((R, W), inf, dt).at[0].set(F20)
    dp_beg = jnp.zeros(R, jnp.int32)
    dp_end = jnp.zeros(R, jnp.int32).at[0].set(dp_end0)
    # Realized band extremes per row (leftmost/rightmost max column, -1 when
    # the row's band is empty). Band propagation is PULL-based: row i gathers
    # its predecessors' left/right instead of rows scattering into their
    # successors' mpl/mpr slots. The push formulation used two masked
    # `.at[tgt].max/min` scatters per row, and XLA:CPU lowers a vmapped
    # masked scatter to a per-element loop — measured 200x slower at K=4
    # (ROUND8_NOTES.md); the pull gather rides the predecessor gathers the
    # row already performs. Semantics are identical: edge (p -> i) appears
    # in both p's out slots and i's pre slots, the source row's seed (1 on
    # its out-edges) is precomputed into mpl0/mpr0, and a Z-drop exit stops
    # the row loop before any successor could have pulled from the dropped
    # row (with DP_UNROLL > 1 an unread same-block overshoot row may pull a
    # band the push form would have suppressed — those rows are never read
    # back; DP_UNROLL defaults to 1).
    left_r = jnp.zeros(R, jnp.int32)
    right_r = jnp.zeros(R, jnp.int32)

    n_chain_steps = max(1, (W - 1).bit_length())

    def chain_max(A, ext):
        # F[k] = max_d (A[k-d] - d*ext), log-step doubling within the window
        F = A
        shift = 1
        for _ in range(n_chain_steps):
            prev = jnp.concatenate([jnp.full(shift, inf, dt), F[:-shift]])
            shifted = jnp.maximum(prev, inf + shift * ext) - shift * ext
            F = jnp.maximum(F, shifted)
            shift <<= 1
            if shift >= W:
                break
        return F

    # Block-commit unrolling: each while-loop iteration computes DP_UNROLL
    # consecutive rows. Every read of the big plane buffers uses their
    # start-of-iteration version; sub-rows see each other through small
    # register-level overlays, and the iteration ends with ONE contiguous
    # (K, W) dynamic-update-slice per buffer. This keeps XLA's in-place
    # update of the loop-carried planes intact (chained per-row .at[i].set
    # inside one body forced full-plane copies: measured 25x slower on the
    # CPU backend) and avoids TPU read-after-write on just-written HBM.
    # The planes carry K padding rows so the final block write never clamps.
    K = DP_UNROLL
    pad_rows = jnp.full((K, W), inf, dt)
    Hb = jnp.concatenate([Hb, pad_rows])
    E1b = jnp.concatenate([E1b, pad_rows])
    E2b = jnp.concatenate([E2b, pad_rows])
    F1b = jnp.concatenate([F1b, pad_rows])
    F2b = jnp.concatenate([F2b, pad_rows])
    pad_i = jnp.zeros(K, jnp.int32)
    dp_beg = jnp.concatenate([dp_beg, pad_i])
    dp_end = jnp.concatenate([dp_end, pad_i])
    left_r = jnp.concatenate([left_r, pad_i])
    right_r = jnp.concatenate([right_r, pad_i])

    def pre_window(plane, pidx, pm, pb, abs_cols, inf):
        """Gather predecessor plane cells at absolute columns (P, W).

        pb holds each predecessor row's CURRENT band begin (big-array value
        overlaid with this iteration's local sub-rows by the caller)."""
        pw = plane[pidx]                                   # (P, W)
        idx = abs_cols[None, :] - pb[:, None]              # (P, W) window index
        ok = pm[:, None] & (idx >= 0) & (idx < W)
        v = jnp.take_along_axis(pw, jnp.clip(idx, 0, W - 1), axis=1)
        return jnp.where(ok, v, inf)

    def overlay(v, lrows, pidx, pm, i0, t, lbeg, abs_cols, inf):
        """Replace predecessor windows that refer to rows computed earlier in
        this same iteration (local sub-rows) with their register values."""
        for s in range(t):
            m = pm & (pidx == i0 + s)
            idx_s = abs_cols - lbeg[s]
            ok_s = (idx_s >= 0) & (idx_s < W)
            v_s = jnp.where(ok_s, lrows[s][jnp.clip(idx_s, 0, W - 1)], inf)
            v = jnp.where(m[:, None], v_s[None, :], v)
        return v

    def body(st):
        (i0, Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, left_r, right_r,
         overflow, bs, bi, bj, brem, zdropped) = st
        # In while-loop mode the cond has already exited on overflow/zdrop,
        # so this fold is a no-op; in static_rows mode (fixed trip count,
        # see below) it predicates the remaining rows off exactly where the
        # while loop would have stopped.
        stopped = overflow | zdropped
        lH = []
        lE1 = []
        lE2 = []
        lF1 = []
        lF2 = []
        lbeg = []
        lend = []
        lleft = []
        lright = []
        for t in range(K):
            i = i0 + t
            active = row_active[i] & (~stopped)
            pm = pre_msk[i]
            pidx = pre_idx[i]

            # ---- band ------------------------------------------------------
            if local:
                # local mode disables banding (abpoa_post_set_para): every
                # row covers the full query, [0, qlen]
                beg = jnp.int32(0)
                end = qlen
                pb = jnp.zeros_like(dp_beg[pidx])
            else:
                r = qlen - (remain_rows[i] - remain_end - 1)
                # pull the predecessors' realized extremes (the push form
                # accumulated left/right+1 into this row's mpl/mpr slots);
                # the source row (pidx == 0) contributes via the mpl0/mpr0
                # seed instead — it never pushed (out_msk[0] is False)
                pull = pm & (pidx > 0)
                pl_v = left_r[pidx]
                pr_v = right_r[pidx]
                for s in range(t):
                    m_s = pidx == i0 + s
                    pl_v = jnp.where(m_s, lleft[s], pl_v)
                    pr_v = jnp.where(m_s, lright[s], pr_v)
                mpl_i = jnp.minimum(mpl0[i], jnp.min(
                    jnp.where(pull, pl_v + 1, jnp.int32(2**30))))
                mpr_i = jnp.maximum(mpr0[i], jnp.max(
                    jnp.where(pull, pr_v + 1, jnp.int32(-(2**30)))))
                beg = jnp.maximum(0, jnp.minimum(mpl_i, r) - w)
                end = jnp.minimum(qlen, jnp.maximum(mpr_i, r) + w)
                pb = dp_beg[pidx]
                for s in range(t):
                    pb = jnp.where(pidx == i0 + s, lbeg[s], pb)
                min_pre_beg = jnp.min(jnp.where(pm, pb, jnp.int32(2**30)))
                beg = jnp.maximum(beg, min_pre_beg)
            overflow = overflow | (active & (end - beg + 1 > W))
            abs_cols = beg + kw
            in_band = abs_cols <= end

            # ---- M / E from predecessors -----------------------------------
            # the lead cell (absolute col -1) of a predecessor row never
            # exists; global first col handled by row-0 init, so OOB stays inf
            Hm1 = overlay(pre_window(Hb, pidx, pm, pb, abs_cols - 1, inf),
                          lH, pidx, pm, i0, t, lbeg, abs_cols - 1, inf)
            Mq = jnp.max(Hm1, axis=0)
            if local:
                # the lead cell (absolute col -1) counts as 0 in local mode
                # (oracle.py lead; reference local first-col semantics)
                Mq = jnp.where(abs_cols == 0, jnp.maximum(Mq, 0), Mq)
            if linear:
                Hj = overlay(pre_window(Hb, pidx, pm, pb, abs_cols, inf),
                             lH, pidx, pm, i0, t, lbeg, abs_cols, inf)
                Erow = jnp.max(Hj - e1, axis=0)
            else:
                Erow = jnp.max(
                    overlay(pre_window(E1b, pidx, pm, pb, abs_cols, inf),
                            lE1, pidx, pm, i0, t, lbeg, abs_cols, inf), axis=0)
                if convex:
                    E2row = jnp.max(
                        overlay(pre_window(E2b, pidx, pm, pb, abs_cols, inf),
                                lE2, pidx, pm, i0, t, lbeg, abs_cols, inf),
                        axis=0)

            Mq = Mq + qp[base_r[i], jnp.clip(abs_cols, 0, qp.shape[1] - 1)]
            Mq = jnp.where(in_band, Mq, inf)
            Erow = jnp.where(in_band, Erow, inf)
            Hhat = jnp.maximum(Mq, Erow)
            if convex:
                E2row = jnp.where(in_band, E2row, inf)
                Hhat = jnp.maximum(Hhat, E2row)

            if linear:
                Hrow = chain_max(Hhat, e1)
                if local:
                    Hrow = jnp.maximum(Hrow, 0)
                Hrow = jnp.where(in_band, Hrow, inf)
                E1n = E2n = F1n = F2n = jnp.full(W, inf, dt)
            else:
                Hm1w = jnp.concatenate([jnp.full(1, inf, dt), Hhat[:-1]])
                A1 = jnp.where(kw == 0, Mq - oe1, Hm1w - oe1)
                A1 = jnp.where(in_band, A1, inf)
                F1n = chain_max(A1, e1)
                Hrow = jnp.maximum(Hhat, F1n)
                if convex:
                    A2 = jnp.where(kw == 0, Mq - oe2, Hm1w - oe2)
                    A2 = jnp.where(in_band, A2, inf)
                    F2n = chain_max(A2, e2)
                    Hrow = jnp.maximum(Hrow, F2n)
                else:
                    F2n = jnp.full(W, inf, dt)
                if local:
                    # local clamp BEFORE deriving E (oracle.py:298-311): the
                    # E recursion reads the clamped H
                    Hrow = jnp.maximum(Hrow, 0)
                if gap_mode == C.AFFINE_GAP:
                    E1n = jnp.maximum(Erow - e1, Hrow - oe1)
                    # local: the killed-E value is 0, not -inf (oracle "dead")
                    E1n = jnp.where(Hrow == Hhat, E1n,
                                    jnp.zeros(W, dt) if local else inf)
                    E2n = jnp.full(W, inf, dt)
                else:
                    E1n = jnp.maximum(Erow - e1, Hrow - oe1)
                    E2n = jnp.maximum(E2row - e2, Hrow - oe2)
                    if local:
                        E1n = jnp.maximum(E1n, 0)
                        E2n = jnp.maximum(E2n, 0)
                E1n = jnp.where(in_band, E1n, inf)
                E2n = jnp.where(in_band, E2n, inf)
                F1n = jnp.where(in_band, F1n, inf)
                F2n = jnp.where(in_band, F2n, inf)
                Hrow = jnp.where(in_band, Hrow, inf)

            # ---- row max -> adaptive band propagation ----------------------
            vals = jnp.where(in_band, Hrow, inf)
            mx = jnp.max(vals)
            has = mx > inf
            eq = (vals == mx) & in_band
            left = jnp.where(has, beg + jnp.argmax(eq), -1).astype(jnp.int32)
            right = jnp.where(has, beg + W - 1 - jnp.argmax(eq[::-1]),
                              -1).astype(jnp.int32)
            if local:
                # best-anywhere cell, leftmost column, earliest row on ties
                # (oracle.py:336-338; reference local argmax tracking)
                mx32 = mx.astype(jnp.int32)
                better = active & (mx32 > bs)
                bs = jnp.where(better, mx32, bs)
                bi = jnp.where(better, i, bi)
                bj = jnp.where(better, left, bj)
            if extend:
                mx32 = mx.astype(jnp.int32)
                has_row = mx > inf
                better = active & (~zdropped) & (mx32 > bs)
                if zdrop_on:
                    delta = brem - remain_rows[i]
                    # empty-band rows Z-drop whenever a real best exists;
                    # splitting the case avoids int32 wrap in bs - mx
                    zd_real = has_row & \
                        (bs - mx32 > zdrop
                         + e1_32 * jnp.abs(delta - (right - bj)))
                    zd = active & (~zdropped) & (~better) & \
                        (zd_real | ((~has_row) & (bs > inf32)))
                    zdropped = zdropped | zd
                bs = jnp.where(better, mx32, bs)
                bi = jnp.where(better, i, bi)
                bj = jnp.where(better, right, bj)
                brem = jnp.where(better, remain_rows[i], brem)
            # ---- local commit (inactive rows write discarded padding) ------
            lH.append(jnp.where(active, Hrow, inf))
            lE1.append(jnp.where(active, E1n, inf))
            lE2.append(jnp.where(active, E2n, inf))
            lF1.append(jnp.where(active, F1n, inf))
            lF2.append(jnp.where(active, F2n, inf))
            lbeg.append(jnp.where(active, beg, 0))
            lend.append(jnp.where(active, end, 0))
            if local:
                lleft.append(jnp.int32(0))
                lright.append(jnp.int32(0))
            else:
                lleft.append(jnp.where(active, left, 0))
                lright.append(jnp.where(active, right, 0))

        # ---- block commit: one contiguous write per buffer -----------------
        Hb = lax.dynamic_update_slice(Hb, jnp.stack(lH), (i0, 0))
        if not linear:
            E1b = lax.dynamic_update_slice(E1b, jnp.stack(lE1), (i0, 0))
            F1b = lax.dynamic_update_slice(F1b, jnp.stack(lF1), (i0, 0))
            if convex:
                E2b = lax.dynamic_update_slice(E2b, jnp.stack(lE2), (i0, 0))
                F2b = lax.dynamic_update_slice(F2b, jnp.stack(lF2), (i0, 0))
        dp_beg = lax.dynamic_update_slice(dp_beg, jnp.stack(lbeg), (i0,))
        dp_end = lax.dynamic_update_slice(dp_end, jnp.stack(lend), (i0,))
        if not local:
            left_r = lax.dynamic_update_slice(left_r, jnp.stack(lleft), (i0,))
            right_r = lax.dynamic_update_slice(right_r, jnp.stack(lright),
                                               (i0,))
        return (i0 + K, Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, left_r,
                right_r, overflow, bs, bi, bj, brem, zdropped)

    def cond(st):
        i = st[0]
        overflow = st[10]
        zdropped = st[15]
        # Z-drop exits the row loop like the reference's break
        # (set_extend_max_score); rows past the drop are never read back
        # (backtrack starts at best_i, whose predecessors all precede it)
        return (i < n_rows - 1) & (~overflow) & (~zdropped)

    st = (jnp.int32(1), Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, left_r,
          right_r, jnp.bool_(False), inf32, jnp.int32(0), jnp.int32(0),
          jnp.int32(0), jnp.bool_(False))
    if static_rows:
        # Fixed trip count over every padded row (rows past n_rows-1 are
        # inactive; rows past an overflow/Z-drop are predicated off via
        # `stopped`). A while_loop's traced cond becomes BATCHED under vmap,
        # and jax's batching rule then wraps every carry — including the
        # (R, W) planes — in a per-iteration select: measured ~200x slower
        # at K=4 on XLA:CPU. A fori_loop's cond stays unbatched, so the
        # lockstep DP chunk (run_dp_chunk) requests this mode; the single-
        # set fused path keeps the early-exiting while_loop.
        n_iters = max(1, -(-(R - 2) // K))
        st = lax.fori_loop(0, n_iters, lambda _, s: body(s), st)
    else:
        st = lax.while_loop(cond, body, st)
    (_, Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, left_r, right_r, overflow,
     bs, bi, bj, _brem, _zd) = st
    # the mpl/mpr output slots now carry each row's realized band extremes
    # (left/right of the row max) — no fused consumer reads them; the split
    # lockstep driver's packed output forwards them for observability only
    return (Hb[:R], E1b[:R], E2b[:R], F1b[:R], F2b[:R],
            dp_beg[:R], dp_end[:R], left_r[:R], right_r[:R], overflow,
            bs, bi, bj)


# --------------------------------------------------------------------------- #
# windowed device backtrack                                                   #
# --------------------------------------------------------------------------- #

@functools.partial(jax.jit, static_argnames=(
    "gap_mode", "gap_on_right", "put_gap_at_end", "max_ops", "local"))
def _backtrack_w(H, E1, E2, F1, F2, dp_beg, dp_end, pre_idx, pre_msk,
                 base_r, query_pad, mat, best_i, best_j,
                 e1, oe1, e2, oe2, inf_min,
                 gap_mode: int, gap_on_right: bool, put_gap_at_end: bool,
                 max_ops: int, local: bool = False):
    """Backtrack over windowed planes (global/extend; local stops at H == 0,
    oracle.py:411-412).

    Mirrors jax_backtrack.device_backtrack but indexes plane cell (i, j) at
    window position j - dp_beg[i]; out-of-window cells read as -inf, which is
    exactly their full-width value. Op priority chain replicates
    /root/reference/src/abpoa_align_simd.c:309-458.
    """
    dt = H.dtype
    mat = mat.astype(dt)
    e1, oe1, e2, oe2 = [x.astype(dt) for x in (e1, oe1, e2, oe2)]
    inf_min = inf_min.astype(dt)
    R, W = H.shape
    P = pre_idx.shape[1]
    linear = gap_mode == C.LINEAR_GAP
    convex = gap_mode == C.CONVEX_GAP
    i32 = jnp.int32
    inf = inf_min

    def gat(A, i, j):
        k = j - dp_beg[i]
        ok = (k >= 0) & (k < W) & (j <= dp_end[i])
        row = lax.dynamic_index_in_dim(A, i, 0, keepdims=False)
        v = lax.dynamic_index_in_dim(row, jnp.clip(k, 0, W - 1), 0, keepdims=False)
        return jnp.where(ok, v, inf)

    def gat_rows(A, rows, j):
        k = j - dp_beg[rows]
        ok = (k >= 0) & (k < W)
        v = jnp.take_along_axis(A[rows], jnp.clip(k, 0, W - 1)[:, None],
                                axis=1)[:, 0]
        return jnp.where(ok, v, inf)

    def cond(st):
        i, j, *_, err, done = st
        return (i > 0) & (j > 0) & (~err) & (~done)

    def body1(st):
        (i, j, cur_op, look_gap, n_ops, ops, n_aln, n_match, err, done) = st
        # predication for unrolling: sub-steps after the walk has logically
        # ended (or errored) pass the state through unchanged; all gathers
        # below are clamp-safe for any (i, j)
        c = (i > 0) & (j > 0) & (~err) & (~done)
        H_ij = gat(H, i, j)
        if local:
            # a zero cell ends the local walk BEFORE emitting any op
            stop = c & (H_ij == 0)
            c = c & (~stop)
        else:
            stop = jnp.bool_(False)
        s = mat[base_r[i], query_pad[j - 1]]
        is_match = (base_r[i] == query_pad[j - 1]).astype(i32)

        pidx = pre_idx[i]
        pmsk = pre_msk[i]
        Hp_jm1 = gat_rows(H, pidx, j - 1)
        Hp_j = gat_rows(H, pidx, j)
        beg_p = dp_beg[pidx]
        end_p = dp_end[pidx]
        inb_m = (j - 1 >= beg_p) & (j - 1 <= end_p) & pmsk
        inb_e = (j >= beg_p) & (j <= end_p) & pmsk

        m_hit = inb_m & (Hp_jm1 + s == H_ij)
        any_m = jnp.any(m_hit)
        first_m = jnp.argmax(m_hit).astype(i32)
        has_M = (cur_op & C.M_OP) != 0

        if linear:
            m1 = any_m & (look_gap == 0) if not gap_on_right else jnp.bool_(False)
        else:
            m1 = any_m & has_M & (look_gap == 0) if not gap_on_right else jnp.bool_(False)

        # ---------- deletion ----------
        if linear:
            d_hit = inb_e & (Hp_j - e1 == H_ij)
            any_d = jnp.any(d_hit)
            first_d = jnp.argmax(d_hit).astype(i32)
            d_new_op = i32(C.ALL_OP)
        else:
            E1_ij = gat(E1, i, j)
            E1p_j = gat_rows(E1, pidx, j)
            has_E1 = (cur_op & C.E1_OP) != 0
            c1 = jnp.where(has_M, H_ij == E1p_j, E1_ij == E1p_j - e1)
            hit1 = inb_e & c1 & has_E1
            if convex:
                E2_ij = gat(E2, i, j)
                E2p_j = gat_rows(E2, pidx, j)
                has_E2 = (cur_op & C.E2_OP) != 0
                c2 = jnp.where(has_M, H_ij == E2p_j, E2_ij == E2p_j - e2)
                hit2 = inb_e & c2 & has_E2
            else:
                hit2 = jnp.zeros_like(hit1)
            slot_hit = hit1 | hit2
            any_d = jnp.any(slot_hit)
            first_d = jnp.argmax(slot_hit).astype(i32)
            use_e1 = hit1[first_d]
            pe1 = E1p_j[first_d]
            ph = Hp_j[first_d]
            op_e1 = jnp.where(ph - oe1 == pe1, i32(C.M_OP | C.F_OP), i32(C.E1_OP))
            if convex:
                pe2 = E2p_j[first_d]
                op_e2 = jnp.where(ph - oe2 == pe2, i32(C.M_OP | C.F_OP), i32(C.E2_OP))
            else:
                op_e2 = i32(C.E1_OP)
            d_new_op = jnp.where(use_e1, op_e1, op_e2)

        # ---------- insertion ----------
        if linear:
            H_ijm1 = gat(H, i, j - 1)
            ins_hit = H_ijm1 - e1 == H_ij
            ins_new_op = i32(C.ALL_OP)
        else:
            F1_ij = gat(F1, i, j)
            F1_ijm1 = gat(F1, i, j - 1)
            H_ijm1 = gat(H, i, j - 1)
            has_F1 = (cur_op & C.F1_OP) != 0
            f1_open = H_ijm1 - oe1 == F1_ij
            f1_ext = F1_ijm1 - e1 == F1_ij
            f1_gate = jnp.where(has_M, H_ij == F1_ij, True)
            f1_hit = has_F1 & f1_gate & (f1_open | f1_ext)
            f1_op = jnp.where(f1_open, i32(C.M_OP | C.E_OP), i32(C.F1_OP))
            if convex:
                F2_ij = gat(F2, i, j)
                F2_ijm1 = gat(F2, i, j - 1)
                has_F2 = (cur_op & C.F2_OP) != 0
                f2_open = H_ijm1 - oe2 == F2_ij
                f2_ext = F2_ijm1 - e2 == F2_ij
                f2_gate = jnp.where(has_M, H_ij == F2_ij, True)
                f2_hit = has_F2 & f2_gate & (f2_open | f2_ext)
                f2_op = jnp.where(f2_open, i32(C.M_OP | C.E_OP), i32(C.F2_OP))
            else:
                f2_hit = jnp.bool_(False)
                f2_op = i32(C.ALL_OP)
            ins_hit = f1_hit | f2_hit
            ins_new_op = jnp.where(f1_hit, f1_op, f2_op)

        m2 = any_m if linear else (any_m & has_M)

        d_sel = (~m1) & any_d
        i_sel = (~m1) & (~d_sel) & ins_hit
        m2_sel = (~m1) & (~d_sel) & (~i_sel) & m2
        no_hit = (~m1) & (~d_sel) & (~i_sel) & (~m2)
        m_sel = m1 | m2_sel

        op_code = jnp.where(m_sel, 0, jnp.where(d_sel, 1, 2))
        # masked write via dynamic-update-slice into the spill row (max_ops,
        # sliced off at return): inactive or dead-end sub-steps record
        # nothing. DUS, not a masked `.at` scatter — XLA:CPU serializes
        # vmapped masked scatters per element (ROUND8_NOTES.md) and this
        # backtrack runs vmapped inside the lockstep DP chunk.
        wr = jnp.where(c & (~no_hit), n_ops, jnp.int32(max_ops))
        ops = lax.dynamic_update_slice(
            ops, jnp.stack([op_code, i]).reshape(1, 2), (wr, jnp.int32(0)))

        pre_m = pidx[first_m]
        pre_d = pidx[first_d]
        new_i = jnp.where(m_sel, pre_m, jnp.where(d_sel, pre_d, i))
        new_j = jnp.where(m_sel | i_sel, j - 1, j)
        new_op = jnp.where(m_sel, i32(C.ALL_OP),
                           jnp.where(d_sel, d_new_op,
                                     jnp.where(i_sel, ins_new_op, cur_op)))
        new_look = jnp.where(m1, look_gap,
                             jnp.where(d_sel | i_sel | m2_sel, i32(0), look_gap))
        new_naln = n_aln + jnp.where(m_sel | i_sel, 1, 0)
        new_nmatch = n_match + jnp.where(m_sel, is_match, 0)
        adv = (~no_hit) & c
        cap = n_ops + 1 >= max_ops
        return ((jnp.where(adv, new_i, i)), jnp.where(adv, new_j, j),
                jnp.where(adv, new_op, cur_op), jnp.where(adv, new_look, look_gap),
                n_ops + jnp.where(adv, 1, 0), ops,
                jnp.where(adv, new_naln, n_aln), jnp.where(adv, new_nmatch, n_match),
                err | (c & (no_hit | cap)), done | stop)

    def body(st):
        for _ in range(BT_UNROLL):
            st = body1(st)
        return st

    ops0 = jnp.zeros((max_ops + 1, 2), jnp.int32)  # +1: the DUS spill row
    st0 = (best_i, best_j, i32(C.ALL_OP),
           i32(1 if put_gap_at_end else 0), i32(0), ops0,
           i32(0), i32(0), jnp.bool_(False), jnp.bool_(False))
    st = lax.while_loop(cond, body, st0)
    (i, j, _co, _lg, n_ops, ops, n_aln, n_match, err, _done) = st
    return ops[:max_ops], n_ops, i, j, n_aln, n_match, err


# --------------------------------------------------------------------------- #
# vectorized fusion                                                           #
# --------------------------------------------------------------------------- #

def spill_scatter(arr, idx, valid, vals, op: str = "set"):
    """THE extra-slot masked-scatter convention, in one place.

    Scatter `vals` into `arr` along axis 0 at `idx` where `valid`; rows with
    `valid` False are routed to a spill slot appended past the end for the
    write and sliced off before returning, so they drop without branching.
    Every fused-loop scatter site used to re-derive this `T + 1`/`N + 1`
    pad-route-slice dance inline; the drift test (tests/test_fused_loop.py)
    pins the convention here.

    op: "set" | "add" | "max" | "min" — the `.at[...]` update applied.
    """
    S = arr.shape[0]
    tgt = jnp.where(valid, idx, jnp.int32(S)).astype(jnp.int32)
    padded = jnp.pad(arr, [(0, 1)] + [(0, 0)] * (arr.ndim - 1))
    return getattr(padded.at[tgt], op)(vals)[:S]


def _fuse_vectorized(g: DeviceGraph, fwd_op, fwd_arg, n_fwd, query, qlen,
                     weight):
    """Fuse one read's forward op stream in O(1) vector steps.

    fwd_op[t]: 0=match (fwd_arg = node id), 1=delete (skipped), 2=insert.
    Safe because an alignment is a simple path: each graph node is touched at
    most once, so every edge append/reweight lands in a distinct slot
    (semantics: abpoa_graph.c:689-774 with inc_both_ends=1, no read-id bitsets).

    Scatter budget: the whole update lowers to EXACTLY four scatter sites
    (the structural jaxpr test pins <= 4) — one rank-indexed path-plane
    scatter, one out-adjacency scatter-add, one in-adjacency scatter-add,
    and one aligned-group scatter-add. Everything else that used to scatter
    (base/n_span writes, edge counts, n_read, collision counting) now rides
    those four as extra plane columns, or became sort/gather/contiguous-
    dynamic-update-slice work: XLA:CPU lowers a vmapped masked scatter to a
    per-element loop, and the ~15 scatters this function used to perform
    were the measured reason K=4 lockstep ran 1.37x slower than serial
    (ROUND8_NOTES.md, BENCH_lockstep_cpu.json).

    Returns (g', path_nodes, path_len, path_new, collision) where collision
    means two ops interacted with one aligned group (caller must use the
    sequential fallback for exact reference behavior).
    """
    N, E = g.in_ids.shape
    A = g.aligned.shape[1]
    T = fwd_op.shape[0]
    t = jnp.arange(T, dtype=jnp.int32)
    valid = t < n_fwd
    is_match = valid & (fwd_op == 0)
    is_ins = valid & (fwd_op == 2)
    consumes = is_match | is_ins

    qpos = jnp.cumsum(consumes.astype(jnp.int32)) - consumes.astype(jnp.int32)
    qpos = jnp.clip(qpos, 0, query.shape[0] - 1)
    b = query[qpos]
    wt = weight[qpos]

    node = jnp.clip(fwd_arg, 0, N - 1)
    same = is_match & (g.base[node] == b)
    # aligned lookup against PRE-read group state
    grp_ids = g.aligned[node]                                   # (T, A)
    grp_ok = jnp.arange(A)[None, :] < g.aligned_cnt[node][:, None]
    grp_hit = grp_ok & (g.base[grp_ids] == b[:, None])
    has_aln = jnp.any(grp_hit, axis=1)
    aln_id = grp_ids[t, jnp.argmax(grp_hit, axis=1)]
    mm = is_match & ~same
    reuse = mm & has_aln
    mm_new = mm & ~has_aln

    # collision: two ops of this read touching the same aligned group would
    # need sequential semantics (a node created by op k visible to op k' > k).
    # Scatter-free duplicate detection: sort the touched group roots (with
    # distinct >= N fillers for untouched ops) and look for equal neighbors.
    grp_root = jnp.where(
        g.aligned_cnt[node] > 0,
        jnp.minimum(node, jnp.min(jnp.where(grp_ok, grp_ids, N), axis=1)),
        node).astype(jnp.int32)
    touch = mm
    root_keys = jnp.sort(jnp.where(touch, grp_root, jnp.int32(N) + t))
    collision = jnp.any(root_keys[1:] == root_keys[:-1])

    is_new = is_ins | mm_new
    new_rank = jnp.cumsum(is_new.astype(jnp.int32)) - is_new.astype(jnp.int32)
    n_new = jnp.sum(is_new.astype(jnp.int32))
    new_id = g.node_n + new_rank

    path_node = jnp.where(same, node,
                          jnp.where(reuse, aln_id,
                                    jnp.where(is_new, new_id, 0))).astype(jnp.int32)
    is_path = consumes
    rank = jnp.cumsum(is_path.astype(jnp.int32)) - is_path.astype(jnp.int32)
    L = jnp.sum(is_path.astype(jnp.int32))

    # dense rank-indexed path plane: node id / edge weight / is-new per
    # path rank, built by ONE scatter (scatter site 1 of 4)
    path_plane = spill_scatter(
        jnp.zeros((T + 1, 3), jnp.int32), rank, is_path,
        jnp.stack([path_node, wt, is_new.astype(jnp.int32)], axis=1))
    path_nodes = path_plane[:, 0]
    path_w = path_plane[:, 1]
    path_new = path_plane[:, 2]

    # ---- new node bases + n_span (value of nearest old path node before) ----
    # both are node-indexed writes into previously-zero rows (ids >= the old
    # node_n), so they ride the aligned-group scatter-add below as two extra
    # plane columns — a dynamic-update-slice at the contiguous new-id range
    # would stay cheap unbatched but lowers to a scatter under vmap (batched
    # start index), breaking the 4-site budget on the mesh path
    r_ = jnp.arange(T + 1, dtype=jnp.int32)
    is_old_path = (r_ < L) & (path_new == 0)
    # lax.cummax, not jnp.maximum.accumulate: the ufunc .accumulate methods
    # are absent on jax 0.4.x (jnp.maximum is a plain PjitFunction there)
    last_old = lax.cummax(jnp.where(is_old_path, r_, -1))
    span_src = jnp.where(last_old >= 0, path_nodes[jnp.clip(last_old, 0, T)],
                         C.SRC_NODE_ID)
    n_span_val = g.n_span[span_src]          # (T+1,) by path rank
    n_span_t = n_span_val[jnp.clip(rank, 0, T)]  # per-op value (t domain)

    # ---- edges: (fr, to, w, check) for ranks 0..L (L+1 edges) ---------------
    # Adjacency updates ride ONE scatter-add per direction (scatter sites 2
    # and 3 of 4) into a merged (N, E+1, 2) plane: columns 0..E-1 hold
    # (edge id, edge weight) pairs, column E holds the per-node counters
    # (slot count, n_read for out / unused for in). Additive updates are
    # exact because edge ranks hit distinct nodes (the path property), slots
    # past a node's count are invariantly zero (edges are never removed),
    # an existing edge's id delta is 0, and a new edge adds its id into a
    # zero slot. One-hot update rows are built by rank — the "rank-indexed
    # dense planes" of ROUND8_NOTES.md.
    er = jnp.arange(T + 1, dtype=jnp.int32)
    e_valid = er <= L
    fr = jnp.where(er == 0, C.SRC_NODE_ID, path_nodes[jnp.clip(er - 1, 0, T)])
    to = jnp.where(er == L, C.SINK_NODE_ID, path_nodes[er])
    wlast = weight[jnp.clip(qlen - 1, 0, weight.shape[0] - 1)]
    ew = jnp.where(er == L, wlast, path_w[er])
    prev_new = jnp.where(er == 0, 0, path_new[jnp.clip(er - 1, 0, T)])
    check = (prev_new == 0)

    fr_c = jnp.clip(fr, 0, N - 1)
    to_c = jnp.clip(to, 0, N - 1)
    ecols = jnp.arange(E, dtype=jnp.int32)

    def _adj_update(ids, w, cnt, extra, row, other):
        """One-direction adjacency update: returns the updated
        (ids, w, cnt, extra) after a single scatter-add of one-hot rank
        rows. `row`/`other` are the indexed node and the far endpoint;
        `extra` rides the counter column's second feature (n_read)."""
        rc = jnp.clip(row, 0, N - 1)
        cnt_r = cnt[rc]
        m = (ecols[None, :] < cnt_r[:, None]) & (ids[rc] == other[:, None])
        exists = check & jnp.any(m, axis=1) & e_valid
        slot = jnp.where(exists, jnp.argmax(m, axis=1), cnt_r).astype(
            jnp.int32)
        cap = jnp.any(e_valid & (slot >= E))
        slot_c = jnp.clip(slot, 0, E - 1)
        hot = ecols[None, :] == slot_c[:, None]                    # (T+1, E)
        new_e = (~exists) & e_valid
        upd_id = jnp.where(hot & new_e[:, None], other[:, None], 0)
        upd_w = jnp.where(hot, ew[:, None], 0)
        upd_slot = jnp.stack([upd_id, upd_w], axis=-1)             # (T+1,E,2)
        upd_cnt = jnp.stack([new_e.astype(jnp.int32),
                             jnp.ones(T + 1, jnp.int32)], axis=-1)
        upd = jnp.concatenate([upd_slot, upd_cnt[:, None, :]], axis=1)
        plane = jnp.concatenate([
            jnp.stack([ids, w], axis=-1),
            jnp.stack([cnt, extra], axis=-1)[:, None, :]], axis=1)
        plane = spill_scatter(plane, row, e_valid, upd, op="add")
        return (plane[:, :E, 0], plane[:, :E, 1], plane[:, E, 0],
                plane[:, E, 1], cap)

    oids, ow, ocnt, n_read, o_cap = _adj_update(
        g.out_ids, g.out_w, g.out_cnt, g.n_read, fr_c, to)
    iids, iw, icnt, _unused, i_cap = _adj_update(
        g.in_ids, g.in_w, g.in_cnt, jnp.zeros(N, jnp.int32), to_c, fr)
    edge_cap = o_cap | i_cap

    # ---- aligned-group registration + new-node base/n_span ------------------
    # Each op's group is distinct (collision excluded), members within a
    # group are distinct, and a new node's rows start all-zero — so every
    # update is an append into a zero slot plus a count bump, and the four
    # update kinds (existing members gain the new node; the group node gains
    # the new node; the new node's row gains members + node + its count; a
    # new node's base/n_span) flatten into ONE scatter-add of one-hot rows
    # over a merged (N, A+3) plane: cols 0..A-1 aligned ids, col A count,
    # col A+1 base, col A+2 n_span (scatter site 4 of 4).
    acnt_node = g.aligned_cnt[node]                             # (T,) pre-read
    memb_ok = (jnp.arange(A)[None, :] < acnt_node[:, None]) & mm_new[:, None]
    memb = jnp.where(memb_ok, grp_ids, N)                       # (T, A)
    memb_c = jnp.clip(memb, 0, N - 1)
    acnt_memb = g.aligned_cnt[memb_c]                           # (T, A)
    grp_full = jnp.any(mm_new & (acnt_node + 1 > A)) | \
        jnp.any(memb_ok & (acnt_memb + 1 > A))
    k_a = jnp.arange(A, dtype=jnp.int32)[None, :]
    z1 = jnp.zeros((T, 2), jnp.int32)       # base/n_span cols, untouched
    # (a) member rows: one-hot new_id at slot acnt[member], count +1
    m_slot = jnp.clip(acnt_memb, 0, A - 1).reshape(T * A)       # (T*A,)
    m_upd_ids = jnp.where(
        jnp.arange(A)[None, :] == m_slot[:, None],
        jnp.repeat(new_id, A)[:, None], 0)                      # (T*A, A)
    m_upd = jnp.concatenate(
        [m_upd_ids, jnp.ones((T * A, 1), jnp.int32),
         jnp.zeros((T * A, 2), jnp.int32)], axis=1)             # (T*A, A+3)
    # (b) the group node's row: one-hot new_id at slot acnt[node], count +1
    n_slot = jnp.clip(acnt_node, 0, A - 1)
    n_upd = jnp.concatenate(
        [jnp.where(k_a == n_slot[:, None], new_id[:, None], 0),
         jnp.ones((T, 1), jnp.int32), z1], axis=1)              # (T, A+3)
    # (c) the new node's row: members, then node, count = acnt[node] + 1
    c_vals = jnp.where(k_a < acnt_node[:, None], jnp.where(memb_ok, grp_ids, 0),
                       jnp.where(k_a == acnt_node[:, None], node[:, None], 0))
    c_upd = jnp.concatenate(
        [c_vals, (acnt_node + 1)[:, None], z1], axis=1)         # (T, A+3)
    # (d) every new node's base/n_span (insertions included — not just
    # mismatch-new), zeros in the aligned columns
    d_upd = jnp.concatenate(
        [jnp.zeros((T, A + 1), jnp.int32), b[:, None],
         n_span_t[:, None]], axis=1)                            # (T, A+3)
    a_idx = jnp.concatenate([memb.reshape(T * A), node, new_id, new_id])
    a_valid = jnp.concatenate([memb_ok.reshape(T * A), mm_new, mm_new,
                               is_new])
    a_upd = jnp.concatenate([m_upd, n_upd, c_upd, d_upd], axis=0)
    a_plane = jnp.concatenate(
        [g.aligned, g.aligned_cnt[:, None], g.base[:, None],
         g.n_span[:, None]], axis=1)                            # (N, A+3)
    a_plane = spill_scatter(a_plane, jnp.clip(a_idx, 0, N - 1), a_valid,
                            a_upd, op="add")
    aids = a_plane[:, :A]
    acnt = a_plane[:, A]
    base = a_plane[:, A + 1]
    n_span = a_plane[:, A + 2]

    node_n = g.node_n + n_new
    g2 = g._replace(
        base=base, n_span=n_span, n_read=n_read,
        in_ids=iids, in_w=iw, in_cnt=icnt,
        out_ids=oids, out_w=ow, out_cnt=ocnt,
        aligned=aids, aligned_cnt=acnt,
        node_n=node_n, ok=g.ok & (node_n <= N))
    return g2, path_nodes, L, path_new, collision, edge_cap, grp_full


def _splice_order(order, n2i, old_n, new_n, path_nodes, path_len, path_new):
    """Insert a read's new nodes into the topo order right after their path
    predecessor. Valid because backtrack paths walk strictly increasing topo
    positions; cross-group reuse edges are validated by the caller."""
    N = order.shape[0]
    T1 = path_nodes.shape[0]
    r = jnp.arange(T1, dtype=jnp.int32)
    on_path = r < path_len
    is_new = on_path & (path_new == 1)
    is_old = on_path & (path_new == 0)

    # old position of nearest old path node before each rank (SRC for none)
    last_old_rank = lax.cummax(jnp.where(is_old, r, -1))
    anchor_node = jnp.where(last_old_rank >= 0,
                            path_nodes[jnp.clip(last_old_rank, 0, T1 - 1)],
                            C.SRC_NODE_ID)
    anchor_pos = n2i[anchor_node]                                 # (T1,)

    # per-gap new-node counts -> position shifts for old nodes
    counts = spill_scatter(jnp.zeros(N, jnp.int32), anchor_pos, is_new,
                           jnp.ones(T1, jnp.int32), op="add")
    shift = jnp.cumsum(counts)              # shift[p] = #new at gaps <= p
    shift_excl = shift - counts             # #new at gaps < p
    # old nodes at position p move past all new nodes of earlier gaps; their
    # own gap's new nodes come directly after them
    pos = jnp.arange(N, dtype=jnp.int32)
    old_active = pos < old_n
    new_pos_old = pos + shift_excl
    order2 = spill_scatter(jnp.zeros(N, jnp.int32), new_pos_old, old_active,
                           jnp.where(old_active, order, 0))
    # rank of a new node within its gap = running count among new ranks since
    # the last old path node
    cum_new = jnp.cumsum(is_new.astype(jnp.int32))
    within = cum_new - 1 - lax.cummax(jnp.where(is_old, cum_new, 0))
    # position of a new node = anchor's shifted position + 1 + within-gap rank
    shift_before = jnp.where(anchor_pos > 0,
                             shift[jnp.clip(anchor_pos - 1, 0, N - 1)], 0)
    npos = anchor_pos + shift_before + 1 + within
    order2 = spill_scatter(order2, npos, is_new,
                           jnp.where(is_new, path_nodes, 0))
    active2 = pos < new_n
    n2i2 = spill_scatter(jnp.zeros(N, jnp.int32), order2, active2,
                         jnp.where(active2, pos, 0))
    return order2, n2i2


# --------------------------------------------------------------------------- #
# per-read body and the fused while-loop                                      #
# --------------------------------------------------------------------------- #

def _build_tables(g: DeviceGraph, order, n2i, remain):
    """Kernel tables as pure gathers over the dense graph arrays (same
    construction as device_pipeline.build_tables_device)."""
    N, E = g.in_ids.shape
    n = g.node_n
    rows = jnp.arange(N, dtype=jnp.int32)
    nid = order
    base_r = g.base[nid]
    pre_idx = n2i[g.in_ids[nid]]
    pre_msk = jnp.arange(E)[None, :] < g.in_cnt[nid][:, None]
    pre_msk = pre_msk & (rows[:, None] > 0) & (rows[:, None] < n)
    out_idx = n2i[g.out_ids[nid]]
    out_msk = jnp.arange(E)[None, :] < g.out_cnt[nid][:, None]
    out_msk = out_msk & (rows[:, None] > 0) & (rows[:, None] < n - 1)
    row_active = (rows > 0) & (rows < n - 1)
    remain_rows = remain[nid]
    mpl0 = jnp.full(N, n, jnp.int32).at[0].set(0)
    mpr0 = jnp.zeros(N, jnp.int32)
    src_out = out_idx[0]
    src_m = jnp.arange(E) < g.out_cnt[nid[0]]
    ones_e = jnp.ones(E, jnp.int32)
    mpl0 = spill_scatter(mpl0, src_out, src_m, ones_e)
    mpr0 = spill_scatter(mpr0, src_out, src_m, ones_e)
    return (base_r, pre_idx, pre_msk, out_idx, out_msk, row_active,
            remain_rows, mpl0, mpr0)


def _seed_state(state: FusedState, query, qlen, weight) -> FusedState:
    """Seed the empty graph with the first read as a node chain
    (abpoa_graph.c:573-593), fully vectorized."""
    g = state.g
    N, E = g.in_ids.shape
    nodes = jnp.arange(N, dtype=jnp.int32)
    # node ids 2..qlen+1 hold query bases
    is_seq = (nodes >= 2) & (nodes < qlen + 2)
    qi = jnp.clip(nodes - 2, 0, query.shape[0] - 1)
    base = jnp.where(is_seq, query[qi], 0).astype(jnp.int32)
    wv = weight[qi].astype(jnp.int32)
    wlast = weight[jnp.clip(qlen - 1, 0, weight.shape[0] - 1)].astype(jnp.int32)

    in_ids = jnp.zeros((N, E), jnp.int32)
    in_w = jnp.zeros((N, E), jnp.int32)
    out_ids = jnp.zeros((N, E), jnp.int32)
    out_w = jnp.zeros((N, E), jnp.int32)
    # chain: SRC -> 2 -> 3 ... -> qlen+1 -> SINK
    first = jnp.int32(2)
    last = qlen + 1
    in_ids = in_ids.at[:, 0].set(jnp.where(is_seq, jnp.where(nodes == first, C.SRC_NODE_ID, nodes - 1), 0))
    in_w = in_w.at[:, 0].set(jnp.where(is_seq, wv, 0))
    out_ids = out_ids.at[:, 0].set(jnp.where(is_seq, jnp.where(nodes == last, C.SINK_NODE_ID, nodes + 1), 0))
    out_w = out_w.at[:, 0].set(jnp.where(
        is_seq, jnp.where(nodes == last, wlast,
                          weight[jnp.clip(qi + 1, 0, weight.shape[0] - 1)].astype(jnp.int32)), 0))
    # SRC/SINK rows
    in_ids = in_ids.at[C.SINK_NODE_ID, 0].set(last)
    in_w = in_w.at[C.SINK_NODE_ID, 0].set(wlast)
    out_ids = out_ids.at[C.SRC_NODE_ID, 0].set(first)
    out_w = out_w.at[C.SRC_NODE_ID, 0].set(weight[0].astype(jnp.int32))
    in_cnt = jnp.where(is_seq | (nodes == C.SINK_NODE_ID), 1, 0).astype(jnp.int32)
    out_cnt = jnp.where(is_seq | (nodes == C.SRC_NODE_ID), 1, 0).astype(jnp.int32)
    n_read = out_cnt  # one edge-add per source node (abpoa_graph.c add_edge)
    n_span = jnp.where(is_seq | (nodes < 2), 1, 0).astype(jnp.int32)

    node_n = qlen + 2
    ok = g.ok & (node_n <= N)
    g2 = DeviceGraph(base=base, in_ids=in_ids, in_w=in_w, in_cnt=in_cnt,
                     out_ids=out_ids, out_w=out_w, out_cnt=out_cnt,
                     aligned=jnp.zeros((N, g.aligned.shape[1]), jnp.int32),
                     aligned_cnt=jnp.zeros(N, jnp.int32),
                     n_read=n_read, n_span=n_span,
                     node_n=node_n.astype(jnp.int32), ok=ok)
    # topo order: SRC, 2, 3, ..., qlen+1, SINK
    pos = jnp.arange(N, dtype=jnp.int32)
    order = jnp.where(pos == 0, C.SRC_NODE_ID,
                      jnp.where(pos < node_n - 1, pos + 1,
                                jnp.where(pos == node_n - 1, C.SINK_NODE_ID, 0)))
    order = order.astype(jnp.int32)
    active = pos < node_n
    n2i = spill_scatter(jnp.zeros(N, jnp.int32), order, active,
                        jnp.where(active, pos, 0))
    # remain along the chain: remain[v] = node_n - 2 - position(v)
    # (src qlen+1 ... last seq node 0, sink -1), no override needed
    remain_by_node = jnp.where(jnp.arange(N) < node_n,
                               node_n - 2 - n2i, 0).astype(jnp.int32)
    # seed read path = the chain nodes 2..qlen+1 (for read-id replay);
    # harmless no-op when the dummy (1, 8) buffer is in use (out-of-bounds
    # scatters drop, and replay only runs when the real buffer was sized)
    Pcap = state.paths.shape[1]
    pk = jnp.arange(Pcap, dtype=jnp.int32)
    seed_path = jnp.where(pk < qlen, pk + 2, 0)
    paths = state.paths.at[state.read_idx].set(seed_path)
    path_lens = state.path_lens.at[state.read_idx].set(qlen)
    return FusedState(g=g2, order=order, n2i=n2i, remain=remain_by_node,
                      read_idx=state.read_idx + 1, err=state.err,
                      kahn_runs=state.kahn_runs, paths=paths,
                      path_lens=path_lens, collisions=state.collisions,
                      rc_flags=state.rc_flags)


@functools.partial(jax.jit, static_argnames=(
    "gap_mode", "W", "max_ops", "gap_on_right", "put_gap_at_end", "plane16",
    "max_mat", "int16_limit", "use_pallas", "pl_interpret", "record_paths",
    "amb_strand", "extend", "zdrop_on", "local", "pallas_hbm"))
def run_fused_chunk(state: FusedState, seqs_pad, wgts_pad, lens, n_reads,
                    qp_mat, mat, w_scalar_b, w_scalar_f, inf_min,
                    o1, e1, oe1, o2, e2, oe2,
                    gap_mode: int, W: int, max_ops: int,
                    gap_on_right: bool, put_gap_at_end: bool,
                    plane16: bool = False, max_mat: int = 0,
                    int16_limit: int = 0, use_pallas: bool = False,
                    pl_interpret: bool = False,
                    record_paths: bool = False,
                    amb_strand: bool = False,
                    extend: bool = False, zdrop_on: bool = False,
                    zdrop=0, local: bool = False,
                    pallas_hbm: bool = False) -> FusedState:
    """The single-dispatch progressive loop: while reads remain and no
    capacity/error exit, align + fuse the next read entirely on device."""
    N, E = state.g.in_ids.shape
    Qp = seqs_pad.shape[1]

    def cond(st: FusedState):
        return (st.read_idx < n_reads) & (st.err == ERR_OK) & st.g.ok

    def body(st: FusedState) -> FusedState:
        k = st.read_idx
        qlen = lens[k]
        query = seqs_pad[k]
        weight = wgts_pad[k]

        def seed(st):
            return _seed_state(st, query, qlen, weight)

        def align_and_fuse(st: FusedState) -> FusedState:
            g, order, n2i, remain = st.g, st.order, st.n2i, st.remain
            n = g.node_n
            # capacity pre-check: a read can add at most qlen+1 nodes
            over_cap = n + qlen + 1 > N
            if plane16:
                # score-width promotion bound: traced twin of
                # oracle.max_score_bound — once the graph (or query) outgrows
                # the int16 budget, exit so the host re-enters with int32
                ln = jnp.maximum(qlen, n)
                max_score = jnp.maximum(qlen * max_mat, ln * e1 + o1)
                need_promote = max_score > int16_limit
            else:
                need_promote = jnp.bool_(False)

            (base_r, pre_idx, pre_msk, out_idx, out_msk, row_active,
             remain_rows, mpl0, mpr0) = _build_tables(g, order, n2i, remain)

            w = w_scalar_b + jnp.int32(w_scalar_f * qlen)
            remain_end = remain[C.SINK_NODE_ID]
            r0 = qlen - (remain_rows[0] - remain_end - 1)
            if local:  # unbanded: the source row spans the whole query
                dp_end0 = qlen
            else:
                dp_end0 = jnp.minimum(qlen, jnp.maximum(mpr0[0], r0) + w)
            tt = jnp.arange(max_ops, dtype=jnp.int32)

            def align_strand(query_s, qp_s):
                """Banded DP + device backtrack + forward-op assembly for one
                strand of the read against the current graph tables. Returns
                (fwd_op, fwd_arg, n_fwd, best_score, overflow, bt_err,
                ops_cap)."""
                def dp_scan_path(_):
                    return _dp_banded(
                        base_r, pre_idx, pre_msk, out_idx, out_msk, row_active,
                        remain_rows, mpl0, mpr0, qp_s, n,
                        qlen, w, remain_end, inf_min, dp_end0,
                        o1, e1, oe1, o2, e2, oe2, gap_mode=gap_mode, W=W,
                        plane16=plane16, extend=extend, zdrop_on=zdrop_on,
                        zdrop=zdrop, local=local)

                if use_pallas or pallas_hbm:
                    # Pallas banded kernel (VMEM ring, pallas_fused.py); falls
                    # back in-jit to the XLA scan on ring/band overflow
                    # (measured rate on sim10k graphs: 0.0%, PERF.md). Covers
                    # all three gap regimes, both plane widths, and all three
                    # align modes (global; extend/Z-drop and local best-cell
                    # state tracked in SMEM scalars).
                    from .pallas_fused import pallas_fused_dp
                    dtp = jnp.int16 if plane16 else jnp.int32
                    N_, E_ = pre_idx.shape
                    is_src_out = (mpl0 == 1) & (mpr0 == 1) & \
                        (jnp.arange(N_) > 0)
                    base_packed = base_r | (is_src_out.astype(jnp.int32) << 8)
                    pre_cnt = jnp.sum(pre_msk.astype(jnp.int32), axis=1)
                    out_cnt_r = jnp.sum(out_msk.astype(jnp.int32), axis=1)
                    infp = inf_min.astype(dtp)
                    H0, E10, E20, F10, F20 = _row0_planes(
                        W, dp_end0, o1.astype(dtp), e1.astype(dtp),
                        oe1.astype(dtp), o2.astype(dtp), e2.astype(dtp),
                        oe2.astype(dtp), infp, gap_mode=gap_mode, local=local)
                    row0H, row0E1, row0E2 = H0[None], E10[None], E20[None]
                    qp_padW = jnp.pad(qp_s, ((0, 0), (0, W)))
                    sc = jnp.stack([qlen, w, remain_end, inf_min, e1, oe1,
                                    e2, oe2, n, dp_end0, jnp.int32(zdrop)]
                                   + [jnp.int32(0)] * 5)
                    if pallas_hbm:
                        # local at VMEM-breaking widths: HBM-resident plane
                        # history, no rings, no overflow conditions
                        from .pallas_fused import pallas_fused_dp_local_hbm
                        (Hp, E1p, E2p, F1p, F2p, beg_p, end_p, ok_p,
                         ext_p) = pallas_fused_dp_local_hbm(
                            sc, base_packed, pre_idx, pre_cnt, out_idx,
                            out_cnt_r, remain_rows, row0H, row0E1, row0E2,
                            qp_padW, R=N_, W=W, P=E_, O=E_,
                            gap_mode=gap_mode, plane16=plane16,
                            interpret=pl_interpret)
                    else:
                        (Hp, E1p, E2p, F1p, F2p, beg_p, end_p, ok_p,
                         ext_p) = pallas_fused_dp(
                            sc, base_packed, pre_idx, pre_cnt, out_idx,
                            out_cnt_r, remain_rows, row0H, row0E1, row0E2,
                            qp_padW,
                            R=N_, W=W, P=E_, O=E_, gap_mode=gap_mode,
                            plane16=plane16, extend=extend, zdrop_on=zdrop_on,
                            local=local, interpret=pl_interpret)
                    # the kernel writes rows 1..: patch the source row in
                    end_p = end_p.at[0].set(dp_end0)
                    beg_p = beg_p.at[0].set(0)

                    def take_pl(_):
                        zeros = jnp.zeros(N_, jnp.int32)
                        return (Hp.at[0].set(H0), E1p.at[0].set(E10),
                                E2p.at[0].set(E20), F1p.at[0].set(F10),
                                F2p.at[0].set(F20), beg_p, end_p,
                                zeros, zeros, jnp.bool_(False),
                                ext_p[0], ext_p[1], ext_p[2])

                    if pallas_hbm:  # ok is always 1: no fallback branch
                        (Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, mpl, mpr,
                         overflow, ext_sc, ext_i, ext_j) = take_pl(None)
                    else:
                        (Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, mpl, mpr,
                         overflow, ext_sc, ext_i, ext_j) = lax.cond(
                             ok_p[0] == 1, take_pl, dp_scan_path, None)
                else:
                    (Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, mpl, mpr,
                     overflow, ext_sc, ext_i, ext_j) = dp_scan_path(None)

                if extend or local:
                    # extend/local end at the tracked best cell (extend:
                    # set_extend_max_score, abpoa_align_simd.c:1082-1090;
                    # local: max-anywhere, leftmost/earliest)
                    best_i, best_j, best_sc = ext_i, ext_j, ext_sc
                else:
                    # global best over the sink's pred rows at their band ends
                    sink_rows = pre_idx[n - 1]
                    sink_msk = pre_msk[n - 1]
                    ends = jnp.minimum(qlen, dp_end[sink_rows])
                    kidx = jnp.clip(ends - dp_beg[sink_rows], 0, W - 1)
                    vals = jnp.where(sink_msk & (ends - dp_beg[sink_rows] >= 0)
                                     & (ends - dp_beg[sink_rows] < W),
                                     jnp.take_along_axis(Hb[sink_rows],
                                                         kidx[:, None],
                                                         axis=1)[:, 0],
                                     inf_min)
                    kk = jnp.argmax(vals)
                    best_i = sink_rows[kk]
                    best_j = ends[kk]
                    best_sc = vals[kk].astype(jnp.int32)

                ops, n_ops, fin_i, fin_j, n_aln, n_match, bt_err = _backtrack_w(
                    Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, pre_idx, pre_msk,
                    base_r, query_s, mat, best_i, best_j,
                    e1, oe1, e2, oe2, inf_min,
                    gap_mode=gap_mode, gap_on_right=gap_on_right,
                    put_gap_at_end=put_gap_at_end, max_ops=max_ops,
                    local=local)

                # reverse into forward order (+ head/tail INS for the ends)
                head = fin_j
                mid = head + n_ops
                n_fwd = mid + (qlen - best_j)
                src = jnp.clip(n_ops - 1 - (tt - head), 0, max_ops - 1)
                in_mid = (tt >= head) & (tt < mid)
                fwd_op = jnp.where(in_mid, ops[src, 0], 2)
                fwd_arg = jnp.where(in_mid,
                                    order[jnp.clip(ops[src, 1], 0, N - 1)], 0)
                ops_cap = n_fwd > max_ops
                return (fwd_op, fwd_arg, n_fwd, best_sc, overflow, bt_err,
                        ops_cap)

            (fwd_op, fwd_arg, n_fwd, best_sc, overflow, bt_err,
             ops_cap) = align_strand(query, qp_mat[k])
            if amb_strand:
                # in-loop ambiguous-strand rescue (src/abpoa_align.c:324-345):
                # when the forward score is below min(qlen, n-2)*max_mat*
                # 0.3333, align the reverse complement in the same dispatch
                # and keep the better strand. The threshold compare is done
                # in exact integers — proven equal to the reference's double
                # arithmetic for every realistic operand (PERF.md).
                Kthr = jnp.minimum(qlen, n - 2) * jnp.int32(max_mat)

                def mul_lt(a, am, b, bm):
                    # exact a*am < b*bm for 0 <= a,b < 2^31 and small
                    # multipliers, via 16-bit limbs (the straight int32
                    # products overflow past ~214k-base reads)
                    def limbs(x, m):
                        lo = (x & 0xffff) * m
                        hi = (x >> 16) * m + (lo >> 16)
                        return hi, lo & 0xffff
                    ah, al = limbs(a, am)
                    bh, bl = limbs(b, bm)
                    return (ah < bh) | ((ah == bh) & (al < bl))

                need_rc = (best_sc < 0) | mul_lt(jnp.maximum(best_sc, 0),
                                                 10000, Kthr, 3333)
                cols = jnp.arange(Qp, dtype=jnp.int32)
                ridx = jnp.clip(qlen - 1 - cols, 0, Qp - 1)
                okq = cols < qlen
                rb = query[ridx]
                rc_query = jnp.where(okq, jnp.where(rb < 4, 3 - rb, 4), 0)
                rc_weight = jnp.where(okq, weight[ridx], 1)
                qsrc = jnp.clip(cols - 1, 0, Qp - 1)
                rc_qp = jnp.where((cols >= 1) & (cols <= qlen),
                                  mat[:, rc_query[qsrc]], 0)

                def rc_path(_):
                    return align_strand(rc_query, rc_qp)

                def no_rc(_):
                    return (jnp.zeros(max_ops, jnp.int32),
                            jnp.zeros(max_ops, jnp.int32),
                            jnp.int32(0), jnp.int32(-(2**30)),
                            jnp.bool_(False), jnp.bool_(False),
                            jnp.bool_(False))

                (r_op, r_arg, r_nfwd, r_sc, r_ovf, r_bt,
                 r_cap) = lax.cond(need_rc, rc_path, no_rc, None)
                use_rc = need_rc & (r_sc > best_sc)
                fwd_op = jnp.where(use_rc, r_op, fwd_op)
                fwd_arg = jnp.where(use_rc, r_arg, fwd_arg)
                n_fwd = jnp.where(use_rc, r_nfwd, n_fwd)
                overflow = overflow | r_ovf
                bt_err = bt_err | r_bt
                ops_cap = ops_cap | r_cap
                query_u = jnp.where(use_rc, rc_query, query)
                weight_u = jnp.where(use_rc, rc_weight, weight)
            else:
                use_rc = jnp.bool_(False)
                query_u = query
                weight_u = weight

            old_n = n

            g2, path_nodes, path_len, path_new, collision, edge_cap, grp_full = \
                _fuse_vectorized(g, fwd_op, fwd_arg, n_fwd, query_u, qlen,
                                 weight_u)

            def seq_fuse(_):
                fwd = jnp.stack([jnp.where(tt < n_fwd, fwd_op, 0),
                                 jnp.where(tt < n_fwd, fwd_arg, 0)], axis=1)
                gs = fuse_alignment(g, fwd, n_fwd, query_u, qlen, weight_u,
                                    C.SRC_NODE_ID, C.SINK_NODE_ID,
                                    max_ops=max_ops)
                return gs

            g2 = lax.cond(collision, seq_fuse, lambda _: g2, None)
            # whole-graph span update (abpoa_graph.c:559-571, inc_both_ends=1)
            nodes_r = jnp.arange(N, dtype=jnp.int32)
            g2 = g2._replace(n_span=jnp.where(nodes_r < g2.node_n,
                                              g2.n_span + 1, g2.n_span))

            g2 = _edge_sort(g2)

            # ---- topo order: splice, validate, Kahn-repair on violation -----
            order2, n2i2 = _splice_order(order, n2i, old_n, g2.node_n,
                                         path_nodes, path_len, path_new)
            # validate: every edge must go forward in the spliced order
            src_pos = n2i2[:, None]
            dst = jnp.clip(g2.out_ids, 0, N - 1)
            em = (jnp.arange(E)[None, :] < g2.out_cnt[:, None]) & \
                (nodes_r[:, None] < g2.node_n)
            bad = jnp.any(em & (n2i2[dst] <= src_pos))

            def kahn(_):
                gk, i2nk, n2ik, remk, okk = topo_sort(g2)
                return gk._replace(ok=gk.ok & okk), i2nk, n2ik, remk

            def splice_ok(_):
                rem = _remain_doubling(g2)
                return g2, order2, n2i2, rem

            # collision-path fusion may create nodes the splice didn't see;
            # always Kahn-repair in that case
            need_kahn = bad | collision
            g3, order3, n2i3, remain3 = lax.cond(need_kahn, kahn, splice_ok, None)

            err = jnp.where(need_promote, ERR_PROMOTE,
                  jnp.where(over_cap | (g2.node_n + 2 > N), ERR_NODE_CAP,
                  jnp.where(overflow, ERR_BAND_CAP,
                  jnp.where(edge_cap, ERR_EDGE_CAP,
                  jnp.where(grp_full, ERR_ALIGN_CAP,
                  jnp.where(bt_err, ERR_BACKTRACK,
                  jnp.where(ops_cap, ERR_OPS_CAP, ERR_OK))))))).astype(jnp.int32)
            # capacity overflow inside the sequential fallbacks (fuse_alignment
            # / topo_sort set only a boolean ok) has no dimension attached
            err = jnp.where((err == ERR_OK) & ~g3.ok,
                            jnp.int32(ERR_GRAPH_CAP), err)
            # on any error, keep the pre-read state so the host can resume
            keep = err != ERR_OK

            def pick(a, b):
                return jax.tree_util.tree_map(
                    lambda x, y: jnp.where(keep, x, y), a, b)

            g_out = pick(st.g, g3)
            if record_paths:
                Pcap = st.paths.shape[1]
                path_slice = lax.dynamic_slice(path_nodes, (0,), (Pcap,))
                paths = st.paths.at[st.read_idx].set(
                    jnp.where(keep, st.paths[st.read_idx], path_slice))
                path_lens = st.path_lens.at[st.read_idx].set(
                    jnp.where(keep, st.path_lens[st.read_idx], path_len))
            else:
                paths, path_lens = st.paths, st.path_lens
            # dummy (1,)-sized buffer when amb is off: read_idx past its end
            # routes to the spill slot and drops
            rc_flags = spill_scatter(
                st.rc_flags, jnp.minimum(st.read_idx,
                                         jnp.int32(st.rc_flags.shape[0])),
                ~keep, use_rc.astype(jnp.int32))
            return FusedState(
                g=g_out,
                order=jnp.where(keep, order, order3),
                n2i=jnp.where(keep, n2i, n2i3),
                remain=jnp.where(keep, remain, remain3),
                read_idx=jnp.where(keep, st.read_idx, st.read_idx + 1),
                err=err,
                kahn_runs=st.kahn_runs + jnp.where(~keep & need_kahn, 1, 0),
                paths=paths, path_lens=path_lens,
                collisions=st.collisions + jnp.where(~keep & collision, 1, 0),
                rc_flags=rc_flags)

        return lax.cond(st.g.node_n == 2, seed, align_and_fuse, st)

    return lax.while_loop(cond, body, state)


# --------------------------------------------------------------------------- #
# host wrapper: capacity growth + resume + download                           #
# --------------------------------------------------------------------------- #

def _grow_state(state: FusedState, N2: int, E2: int, A2: int) -> FusedState:
    """Copy device state into larger-capacity arrays (device-side, jitted)."""
    g = state.g
    N, E = g.in_ids.shape
    A = g.aligned.shape[1]

    def grow1(x):
        if x.ndim == 0:
            return x
        pads = [(0, N2 - N)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, pads)

    def grow2(x):
        return jnp.pad(x, ((0, N2 - N), (0, E2 - E)))

    g2 = DeviceGraph(
        base=grow1(g.base),
        in_ids=grow2(g.in_ids), in_w=grow2(g.in_w), in_cnt=grow1(g.in_cnt),
        out_ids=grow2(g.out_ids), out_w=grow2(g.out_w), out_cnt=grow1(g.out_cnt),
        aligned=jnp.pad(g.aligned, ((0, N2 - N), (0, A2 - A))),
        aligned_cnt=grow1(g.aligned_cnt),
        n_read=grow1(g.n_read), n_span=grow1(g.n_span),
        node_n=g.node_n, ok=g.ok)
    return FusedState(
        g=g2, order=grow1(state.order), n2i=grow1(state.n2i),
        remain=grow1(state.remain), read_idx=state.read_idx,
        err=jnp.int32(ERR_OK), kahn_runs=state.kahn_runs,
        paths=state.paths, path_lens=state.path_lens,
        collisions=state.collisions, rc_flags=state.rc_flags)




def _state_from_host_graph(pg, N: int, E: int, A: int,
                           n_reads: int, Pcap: int, n_rc: int) -> FusedState:
    """Upload a restored host graph as the fused loop's starting state
    (incremental MSA `-i`, reference abpoa_restore_graph
    src/abpoa_seq.c:608-673). The host graph must be topologically sorted:
    its reference BFS order is a valid topo order for the loop, its edge
    slots are weight-sorted, and max_remain comes along unchanged."""
    n = pg.node_n
    base = np.zeros(N, np.int32)
    in_ids = np.zeros((N, E), np.int32)
    in_w = np.zeros((N, E), np.int32)
    in_cnt = np.zeros(N, np.int32)
    out_ids = np.zeros((N, E), np.int32)
    out_w = np.zeros((N, E), np.int32)
    out_cnt = np.zeros(N, np.int32)
    aligned = np.zeros((N, A), np.int32)
    aligned_cnt = np.zeros(N, np.int32)
    n_read = np.zeros(N, np.int32)
    n_span = np.zeros(N, np.int32)
    for i in range(n):
        nd = pg.nodes[i]
        base[i] = nd.base
        ic, oc, ac = len(nd.in_ids), len(nd.out_ids), len(nd.aligned_ids)
        in_ids[i, :ic] = nd.in_ids
        in_w[i, :ic] = nd.in_w
        in_cnt[i] = ic
        out_ids[i, :oc] = nd.out_ids
        out_w[i, :oc] = nd.out_w
        out_cnt[i] = oc
        aligned[i, :ac] = nd.aligned_ids
        aligned_cnt[i] = ac
        n_read[i] = nd.n_read
        n_span[i] = nd.n_span_read
    order = np.zeros(N, np.int32)
    order[:n] = pg.index_to_node_id[:n]
    n2i = np.zeros(N, np.int32)
    n2i[order[:n]] = np.arange(n, dtype=np.int32)
    remain = np.zeros(N, np.int32)
    remain[:n] = pg.node_id_to_max_remain[:n]
    g = DeviceGraph(
        base=jnp.asarray(base),
        in_ids=jnp.asarray(in_ids), in_w=jnp.asarray(in_w),
        in_cnt=jnp.asarray(in_cnt),
        out_ids=jnp.asarray(out_ids), out_w=jnp.asarray(out_w),
        out_cnt=jnp.asarray(out_cnt),
        aligned=jnp.asarray(aligned), aligned_cnt=jnp.asarray(aligned_cnt),
        n_read=jnp.asarray(n_read), n_span=jnp.asarray(n_span),
        node_n=jnp.int32(n), ok=jnp.bool_(True))
    return FusedState(
        g=g, order=jnp.asarray(order), n2i=jnp.asarray(n2i),
        remain=jnp.asarray(remain),
        read_idx=jnp.int32(0), err=jnp.int32(ERR_OK),
        kahn_runs=jnp.int32(0),
        paths=jnp.zeros((n_reads, Pcap), jnp.int32),
        path_lens=jnp.zeros(n_reads, jnp.int32),
        collisions=jnp.int32(0),
        rc_flags=jnp.zeros(max(n_rc, 1), jnp.int32))


# shared between the single-set and lockstep-batch drivers: bucket planning,
# input padding, the 20-argument chunk call, and the growth policy live in
# ONE place so the two paths cannot drift apart

_RECOVERABLE_ERRS = (ERR_PROMOTE, ERR_NODE_CAP, ERR_OPS_CAP, ERR_BAND_CAP,
                     ERR_EDGE_CAP, ERR_ALIGN_CAP, ERR_GRAPH_CAP)


def _plan_buckets(abpt: Params, qmax: int) -> Tuple[int, int, bool]:
    """(Qp, W, local_mode) for a workload whose longest read is qmax.
    Delegates to the shared definition site (compile/ladder.py) that
    serve admission pricing also reads."""
    return plan_chunk_buckets(abpt, qmax)


def partition_by_length_bucket(entries):
    """Group (key, seqs, weights) triples by the ladder's Qp rung — the
    SAME `qp_rung` the chunk planner (_plan_buckets) keys on, so lockstep
    sub-batching and the planner can never disagree about a read's
    bucket — keeping the shared padding honest: a short set must not pay
    a long set's planes. Returns the groups in ascending rung order."""
    parts: dict = {}
    for entry in entries:
        qmax = max((len(s) for s in entry[1]), default=0)
        parts.setdefault(qp_rung(qmax), []).append(entry)
    return [parts[k] for k in sorted(parts)]


def plan_dispatch_footprint(abpt: Params, seq_sets) -> dict:
    """The compile-ladder rung a fused/lockstep dispatch over `seq_sets`
    (list of lists of encoded reads) would start from — the memory-
    admission model's input (resilience/memory.py). Pure host math through
    the SAME planner functions the drivers call, so the admission estimate
    and the dispatch can never disagree about the shapes."""
    qmax = max((len(s) for ss in seq_sets for s in ss), default=1)
    Qp, W, _local = _plan_buckets(abpt, qmax)
    R = reads_rung(max((len(ss) for ss in seq_sets), default=1))
    K = len(seq_sets)
    Kb = k_rung(K) if K > 1 else 1
    N = chunk_node_cap(qmax)
    plane16 = max_score_bound(abpt, qmax, 2) <= int16_score_limit(abpt)
    return dict(N=N, E=8, A=8, W=W, Qp=Qp, reads=R, K=Kb,
                plane16=plane16, gap_mode=abpt.gap_mode, m=abpt.m)


def _pad_read_set(seqs, weights, Qp: int, mat: np.ndarray, m: int,
                  n_rows: int = None):
    """-> (seqs_pad, wgts_pad, lens, qp) host arrays for one read set.
    n_rows pads the read axis to a ladder rung (reads_rung); padding rows
    are zero-length and never touched — the loop stops at the traced
    n_reads scalar — so sets of nearby sizes share one compiled chunk."""
    n = len(seqs)
    if n_rows is None:
        n_rows = n
    seqs_pad = np.zeros((n_rows, Qp), dtype=np.int32)
    wgts_pad = np.ones((n_rows, Qp), dtype=np.int32)
    lens = np.zeros(n_rows, dtype=np.int32)
    qp = np.zeros((n_rows, m, Qp), dtype=np.int32)
    for i, s in enumerate(seqs):
        seqs_pad[i, : len(s)] = s
        wgts_pad[i, : len(s)] = weights[i]
        lens[i] = len(s)
        qp[i, :, 1: len(s) + 1] = mat[:, s]
    return seqs_pad, wgts_pad, lens, qp


def _scalar_chunk_args(abpt: Params, inf_min: int):
    """The per-chunk traced scalars, in run_fused_chunk positional order."""
    return (jnp.int32(abpt.wb), jnp.float32(abpt.wf), jnp.int32(inf_min),
            jnp.int32(abpt.gap_open1), jnp.int32(abpt.gap_ext1),
            jnp.int32(abpt.gap_oe1), jnp.int32(abpt.gap_open2),
            jnp.int32(abpt.gap_ext2), jnp.int32(abpt.gap_oe2))


def _static_chunk_kwargs(abpt: Params, *, W: int, max_ops: int, plane16: bool,
                         int16_limit: int, use_pallas: bool,
                         pl_interpret: bool, record_paths: bool, amb: bool,
                         local_m: bool, pallas_hbm: bool = False) -> dict:
    extend_m = abpt.align_mode == C.EXTEND_MODE
    return dict(gap_mode=abpt.gap_mode, W=W, max_ops=max_ops,
                gap_on_right=bool(abpt.put_gap_on_right),
                put_gap_at_end=bool(abpt.put_gap_at_end),
                plane16=plane16, max_mat=int(abpt.max_mat),
                int16_limit=int(int16_limit),
                use_pallas=bool(use_pallas), pl_interpret=pl_interpret,
                record_paths=record_paths, amb_strand=amb,
                extend=extend_m,
                zdrop_on=extend_m and abpt.zdrop > 0,
                zdrop=jnp.int32(max(abpt.zdrop, 0)), local=local_m,
                pallas_hbm=bool(pallas_hbm))


def _pallas_variant(abpt: Params, use_pallas: bool, local_m: bool, W: int,
                    plane16: bool, Qp: int) -> Tuple[bool, bool]:
    """(up, up_hbm): which Pallas kernel variant (if any) this chunk's
    statics select — the VMEM guard, shared by the single-set driver, the
    lockstep driver and the AOT warmer so the compiled statics can never
    drift apart."""
    if not use_pallas:
        return False, False
    from .pallas_fused import fits_vmem, fits_vmem_local_hbm
    up = fits_vmem(W, abpt.gap_mode, plane16, m=abpt.m, Qp=Qp)
    up_hbm = (not up and local_m
              and fits_vmem_local_hbm(W, abpt.gap_mode, plane16,
                                      m=abpt.m, Qp=Qp))
    return up, up_hbm


def _grown_caps(errs, N: int, E: int, A: int, W: int, plane16: bool):
    """Collective growth policy: recoverable error codes -> new capacities.
    Returns (N, E, A, W, plane16, grew) where `grew` means the device state
    needs _grow_state (pure padding); W/plane16 changes need only an err
    reset (the next chunk recompiles with the new statics)."""
    from ..obs import count
    grew = False
    if any(e in (ERR_NODE_CAP, ERR_OPS_CAP, ERR_GRAPH_CAP) for e in errs):
        N = grow_node_cap(N)
        grew = True
        count("fused.grow.node")
    if any(e in (ERR_EDGE_CAP, ERR_GRAPH_CAP) for e in errs):
        E *= 2
        grew = True
        count("fused.grow.edge")
    if any(e in (ERR_ALIGN_CAP, ERR_GRAPH_CAP) for e in errs):
        A *= 2
        grew = True
        count("fused.grow.aligned")
    if ERR_BAND_CAP in errs:
        W *= 2
        count("fused.grow.band")
    if ERR_PROMOTE in errs:
        plane16 = False
        count("fused.promote_int32")
    return N, E, A, W, plane16, grew


def _record_fused_dp(abpt: Params, n_reads: int, qmax: int, n_final: int,
                     W: int, Qp: int) -> None:
    """Telemetry cell-total model for one finished fused run: reads 2..R
    each sweep a graph whose row count ramps ~linearly from the first
    read's chain (qmax+2) to the final node count, each row computing one
    W-wide window (clipped to the padded query). Host-side arithmetic over
    scalars the driver already downloaded — no extra device syncs."""
    if n_reads <= 1:
        return
    from ..obs import report
    band = min(W, Qp)
    avg_rows = (qmax + 2 + n_final) / 2.0
    cells = int((n_reads - 1) * avg_rows * band)
    report().record_dp_cells(cells, n_reads - 1, band, abpt.gap_mode)


def progressive_poa_fused(seqs: List[np.ndarray],
                          weights: List[np.ndarray],
                          abpt: Params,
                          max_chunks: int = 24,
                          use_pallas: bool = None,
                          init_graph=None):
    """Run the fused loop over a read set; returns a host POAGraph ready for
    consensus/output (reference abpoa_poa, src/abpoa_align.c:313-353).

    init_graph: a restored host POAGraph to extend (incremental MSA `-i`);
    None starts from the empty graph."""
    n_reads = len(seqs)
    n_rung = reads_rung(n_reads)  # padded read rows (ladder rung)
    qmax = max(len(s) for s in seqs)
    Qp, W, local_m = _plan_buckets(abpt, qmax)
    n0 = 0
    E = 8
    A = 8
    if init_graph is not None and init_graph.node_n > 2:
        if not init_graph.is_topological_sorted:
            init_graph.topological_sort(abpt)
        n0 = init_graph.node_n
        maxdeg = max(max(len(nd.in_ids), len(nd.out_ids))
                     for nd in init_graph.nodes[:n0])
        maxaln = max(len(nd.aligned_ids) for nd in init_graph.nodes[:n0])
        E = max(E, _bucket_pow2(maxdeg + 1))
        A = max(A, _bucket_pow2(maxaln + 1))
    else:
        init_graph = None
    N = _bucket(n0 + 2 * (qmax + 2) + 64, 1024)

    mat = np.ascontiguousarray(abpt.mat.astype(np.int32))
    seqs_pad, wgts_pad, lens, qp_all = _pad_read_set(
        seqs, weights, Qp, mat, abpt.m, n_rows=n_rung)

    seqs_d = jnp.asarray(seqs_pad)
    wgts_d = jnp.asarray(wgts_pad)
    lens_d = jnp.asarray(lens)
    qp_d = jnp.asarray(qp_all)
    mat_d = jnp.asarray(mat)

    # int16 planes while the promotion bound allows (checked per read on
    # device; ERR_PROMOTE flips to int32 once the graph outgrows the budget)
    int16_limit = int16_score_limit(abpt)
    plane16 = max_score_bound(abpt, qmax, 2) <= int16_limit
    if use_pallas is None:
        use_pallas = abpt.device == "pallas"
    pl_interpret = jax.default_backend() != "tpu"

    record_paths = bool(abpt.use_read_ids)
    amb = bool(abpt.amb_strand)
    if init_graph is not None and record_paths:
        # replayed bitsets cannot reconstruct the restored reads' edge sets
        raise RuntimeError(
            "fused loop: incremental restore with read-id outputs "
            "needs the host loop")
    if init_graph is not None:
        state = _state_from_host_graph(
            init_graph, N, E, A,
            n_reads=n_rung if record_paths else 1,
            Pcap=Qp + 2 if record_paths else 8,
            n_rc=n_rung if amb else 1)
    else:
        state = init_fused_state(N, E, A,
                                 n_reads=n_rung if record_paths else 1,
                                 Pcap=Qp + 2 if record_paths else 8,
                                 n_rc=n_rung if amb else 1)
    from ..obs import count, device_capture, trace
    kahn_total = 0
    with device_capture("fused_loop"):
        for chunk_i in range(max_chunks):
            max_ops = N + Qp + 8
            inf_min = dp_inf_min(abpt, INT16_MIN if plane16 else INT32_MIN)
            # static VMEM guard: local mode (and band growth) can push W past
            # what the kernel's rings fit; local falls to the HBM-resident
            # variant, everything else to the XLA scan
            up, up_hbm = _pallas_variant(abpt, use_pallas, local_m, W,
                                         plane16, Qp)
            count("fused.chunks")
            if use_pallas and not up and not up_hbm:
                count("fallback.pallas_vmem")
            count("fused.dispatch.pallas" if up else
                  ("fused.dispatch.pallas_hbm" if up_hbm
                   else "fused.dispatch.xla"))
            bucket = dict(N=N, E=E, A=A, W=W, Qp=Qp, reads=n_rung, K=1,
                          plane16=plane16, pallas=bool(up),
                          pallas_hbm=bool(up_hbm), gap_mode=abpt.gap_mode)
            with trace.span("fused_chunk", "fused",
                            args=dict(bucket, chunk=chunk_i)):
                # the err/read_idx readback is the chunk's host sync: inside
                # the bracket so the compile record's wall covers execution
                with registry.watch("run_fused_chunk", bucket) as cw:
                    state = run_fused_chunk(
                        state, seqs_d, wgts_d, lens_d, jnp.int32(n_reads),
                        qp_d, mat_d, *_scalar_chunk_args(abpt, inf_min),
                        **_static_chunk_kwargs(
                            abpt, W=W, max_ops=max_ops, plane16=plane16,
                            int16_limit=int16_limit, use_pallas=up,
                            pl_interpret=pl_interpret,
                            record_paths=record_paths,
                            amb=amb, local_m=local_m, pallas_hbm=up_hbm))
                    err = int(state.err)
                    done = int(state.read_idx)
            if chunk_i > 0 and cw["compiled"]:
                # a grow-and-resume re-entry whose bucket XLA had not
                # already compiled this process (ground truth from the jit
                # cache, not the re-entry count: a warm run replaying the
                # same growth ladder hits the cache and recompiles nothing)
                count("fused.recompiles")
            if err == ERR_OK and done >= n_reads:
                break
            if err == ERR_BACKTRACK:
                raise RuntimeError(
                    f"fused loop: device backtrack failed at read {done}")
            if err not in _RECOVERABLE_ERRS:
                raise RuntimeError(
                    f"fused loop: unknown error {err} at read {done}")
            N, E, A, W, plane16, grew = _grown_caps((err,), N, E, A, W,
                                                    plane16)
            if grew:
                state = _grow_state(state, N, E, A)
            else:
                state = state._replace(err=jnp.int32(ERR_OK))
        else:
            raise RuntimeError("fused loop: capacity growth did not converge")
    kahn_total = int(state.kahn_runs)
    count("fused.kahn_resorts", kahn_total)
    count("fused.collisions", int(state.collisions))

    if abpt.use_read_ids and int(state.collisions) > 0:
        # a sequential-fusion fallback may have taken a different path than
        # the recorded one (same-group interactions); the replayed bitsets
        # would be wrong for those reads — let the caller use the host loop
        raise RuntimeError(
            f"fused loop: {int(state.collisions)} sequential-fusion "
            "fallbacks; read-id replay unavailable")

    # only after the collision check: a raise above sends the caller to the
    # per-read host loop, which records every read itself — recording here
    # first would double-count the run's dp.cells
    _record_fused_dp(abpt, n_reads, qmax, int(state.g.node_n), W, Qp)

    pg = _download_graph(state, abpt)
    if abpt.use_read_ids:
        _replay_read_ids(pg, state, n_reads)
    is_rc = ([bool(x) for x in np.asarray(state.rc_flags)[:n_reads]]
             if amb else [False] * n_reads)
    return pg, kahn_total, is_rc


def _replay_read_ids(pg, state: FusedState, n_reads: int) -> None:
    """Reconstruct per-edge read-id bitsets from the recorded fusion paths
    (reference: abpoa_set_read_id during fusion, abpoa_graph.c:465-469).
    Each read's path visits each node once, so its edge set is exactly the
    consecutive pairs SRC -> p0 -> ... -> p(L-1) -> SINK. Vectorized: the
    (edge, read) pairs accumulate into a uint64 word matrix with
    np.bitwise_or.at, then one Python pass converts per-edge words to the
    graph's arbitrary-precision int bitsets."""
    paths = np.asarray(state.paths)
    lens = np.asarray(state.path_lens)
    n_nodes = pg.node_n
    frs, tos, rids = [], [], []
    for r in range(n_reads):
        L = int(lens[r])
        p = paths[r, :L].astype(np.int64)
        fr = np.concatenate(([C.SRC_NODE_ID], p))
        to = np.concatenate((p, [C.SINK_NODE_ID]))
        frs.append(fr)
        tos.append(to)
        rids.append(np.full(L + 1, r, np.int64))
    fr = np.concatenate(frs)
    to = np.concatenate(tos)
    rid = np.concatenate(rids)
    keys = fr * n_nodes + to
    uniq, inverse = np.unique(keys, return_inverse=True)
    n_words = (n_reads + 63) >> 6
    words = np.zeros((len(uniq), n_words), np.uint64)
    np.bitwise_or.at(words, (inverse, rid >> 6),
                     np.uint64(1) << (rid & 63).astype(np.uint64))
    for e, key in enumerate(uniq):
        nd = pg.nodes[int(key) // n_nodes]
        slot = nd.out_ids.index(int(key) % n_nodes)
        nd.read_ids[slot] = int.from_bytes(words[e].tobytes(), "little")


def progressive_poa_fused_batch(seq_sets: List[List[np.ndarray]],
                                weight_sets: List[List[np.ndarray]],
                                abpt: Params,
                                max_chunks: int = 24,
                                use_pallas: bool = None,
                                mesh=None,
                                _initial_caps: Optional[Tuple] = None):
    """Lockstep multi-set batching: K independent read sets advance through
    the fused progressive loop as ONE vmapped device dispatch per chunk.

    The reference's `-l` file-list mode (src/abpoa.c:148-168) is
    embarrassingly parallel across read sets; running K sets in lockstep on
    a single chip amortizes the sequential per-step dispatch cost K-fold —
    the one throughput lever that needs no cross-set communication. A set
    that finishes early no-ops inside the vmapped while_loop (its `cond` is
    already false); a set that trips a capacity code makes the WHOLE batch
    grow (buckets are shared static shapes) and every unfinished set then
    resumes exactly where it stopped, so results stay byte-identical to
    sequential processing.

    Returns a list of K entries, each `(host_graph, is_rc_flags)` or `None`
    where that set must be re-run by the caller on a sequential path
    (device backtrack divergence, or read-id replay unavailable after a
    sequential-fusion collision).

    mesh: an optional 1-axis `jax.sharding.Mesh`; the set axis is sharded
    over its devices (GSPMD partitions the vmapped chunk, one set group per
    device) — the multi-chip `-l` fleet path. Host-driven capacity growth
    re-enters under the same sharding. K should be a multiple of the mesh
    size. _initial_caps=(N, E, A, W) overrides the starting buckets
    (tests/dryrun: force growth cheaply; undersized caps are recovered by
    the normal grow-and-resume cycle).
    """
    K = len(seq_sets)
    # ladder rungs for the set axis (pow2, padded with empty sets that
    # finish before their first device step) and the per-set read axis —
    # nearby group/set sizes share ONE compiled lockstep chunk
    Kb = k_rung(K, mesh.size if mesh is not None else 1)
    n_reads_v = np.zeros(Kb, np.int32)
    n_reads_v[:K] = [len(s) for s in seq_sets]
    R = reads_rung(int(n_reads_v.max()))
    qmax = max(len(s) for ss in seq_sets for s in ss)
    Qp, W, local_m = _plan_buckets(abpt, qmax)
    E = 8
    A = 8
    N = chunk_node_cap(qmax)
    if _initial_caps is not None:
        N, E, A, W = _initial_caps

    seqs_pad = np.zeros((Kb, R, Qp), dtype=np.int32)
    wgts_pad = np.ones((Kb, R, Qp), dtype=np.int32)
    lens = np.zeros((Kb, R), dtype=np.int32)
    mat = np.ascontiguousarray(abpt.mat.astype(np.int32))
    qp_all = np.zeros((Kb, R, abpt.m, Qp), dtype=np.int32)
    for k, ss in enumerate(seq_sets):
        n = len(ss)
        (seqs_pad[k, :n], wgts_pad[k, :n], lens[k, :n],
         qp_all[k, :n]) = _pad_read_set(ss, weight_sets[k], Qp, mat, abpt.m)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        def _shard(x):
            # every per-set leaf has leading dim K: split it over the mesh
            x = jnp.asarray(x)
            spec = (PartitionSpec(mesh.axis_names[0]) if x.ndim >= 1
                    else PartitionSpec())
            return jax.device_put(x, NamedSharding(mesh, spec))
    else:
        def _shard(x):
            return jnp.asarray(x)

    seqs_d = _shard(seqs_pad)
    wgts_d = _shard(wgts_pad)
    lens_d = _shard(lens)
    nreads_d = _shard(n_reads_v)
    qp_d = _shard(qp_all)
    mat_d = jnp.asarray(mat)

    int16_limit = int16_score_limit(abpt)
    plane16 = max_score_bound(abpt, qmax, 2) <= int16_limit
    if use_pallas is None:
        use_pallas = abpt.device == "pallas"
    pl_interpret = jax.default_backend() != "tpu"
    record_paths = bool(abpt.use_read_ids)
    amb = bool(abpt.amb_strand)

    def init_one():
        return init_fused_state(N, E, A,
                                n_reads=R if record_paths else 1,
                                Pcap=Qp + 2 if record_paths else 8,
                                n_rc=R if amb else 1)

    state = jax.tree.map(lambda x: _shard(jnp.stack([x] * Kb)), init_one())
    # sets frozen by an unrecoverable per-set error; their err stays
    # non-OK so the vmapped while_loop skips them in later chunks
    failed = np.zeros(Kb, dtype=bool)
    from ..obs import count, device_capture, observe, trace
    observe("lockstep.k", K)
    finished_prev = np.zeros(Kb, dtype=bool)
    with device_capture("fused_lockstep_batch"):
        for chunk_i in range(max_chunks):
            max_ops = N + Qp + 8
            inf_min = dp_inf_min(abpt, INT16_MIN if plane16 else INT32_MIN)
            up, up_hbm = _pallas_variant(abpt, use_pallas, local_m, W,
                                         plane16, Qp)
            count("lockstep.chunks")
            # a chunk re-entered while some sets are already finished only
            # drains the stragglers: finished sets no-op inside the vmapped
            # while_loop but still occupy their batch slot (real sets only:
            # K-rung padding slots are born finished and don't count)
            if finished_prev[:K].any():
                count("lockstep.drain_chunks")
            noop = float(finished_prev[:K].mean())
            observe("lockstep.noop_set_fraction", noop)
            # divergence feedback for the scheduler's K cap — the device
            # impl must feed the EWMA too, or the serve/-l re-cap loops
            # would only ever engage on the split driver
            from ..parallel import scheduler as _sched
            _sched.observe_noop_fraction(noop)

            kwargs = _static_chunk_kwargs(
                abpt, W=W, max_ops=max_ops, plane16=plane16,
                int16_limit=int16_limit, use_pallas=up,
                pl_interpret=pl_interpret, record_paths=record_paths,
                amb=amb, local_m=local_m, pallas_hbm=up_hbm)

            def chunk_one(st, sq, wg, ln, nr, qp):
                return run_fused_chunk(
                    st, sq, wg, ln, nr, qp, mat_d,
                    *_scalar_chunk_args(abpt, inf_min), **kwargs)

            bucket = dict(N=N, E=E, A=A, W=W, Qp=Qp, reads=R, K=Kb,
                          plane16=plane16, pallas=bool(up),
                          pallas_hbm=bool(up_hbm), gap_mode=abpt.gap_mode)
            with trace.span("lockstep_chunk", "fused",
                            args=dict(bucket, chunk=chunk_i)):
                # the jit cache doesn't track compiles under vmap, so the
                # lockstep bracket passes no cache handle and compile
                # detection falls back to first-sight-of-bucket
                with registry.watch("run_fused_chunk[lockstep]", bucket,
                                    use_handle=False) as cw:
                    state = jax.vmap(chunk_one)(state, seqs_d, wgts_d,
                                                lens_d, nreads_d, qp_d)
                    errs = np.asarray(state.err)
                    done = np.asarray(state.read_idx)
            if chunk_i > 0 and cw["compiled"]:
                count("fused.recompiles")
            failed |= ~np.isin(errs, (ERR_OK,) + _RECOVERABLE_ERRS)
            finished_prev = failed | ((errs == ERR_OK) & (done >= n_reads_v))
            if finished_prev.all():
                break
            # collective growth: shared buckets mean one set's capacity need
            # grows every set (pure padding — device state is preserved)
            N, E, A, W, plane16, grew = _grown_caps(
                set(errs[~failed].tolist()), N, E, A, W, plane16)
            if grew:
                state = jax.vmap(lambda s: _grow_state(s, N, E, A))(state)
            # clear recoverable codes; re-freeze failed sets (_grow_state
            # resets every err to OK)
            new_err = np.where(failed, np.int32(ERR_BACKTRACK),
                               np.where(np.isin(errs, _RECOVERABLE_ERRS),
                                        np.int32(ERR_OK), errs))
            state = state._replace(err=_shard(new_err.astype(np.int32)))
        else:
            raise RuntimeError(
                "fused lockstep batch: capacity growth did not converge")

    host = jax.device_get(state)
    node_ns = np.asarray(host.g.node_n)
    for k in range(K):
        if not failed[k]:
            _record_fused_dp(abpt, int(n_reads_v[k]), qmax,
                             int(node_ns[k]), W, Qp)
    out = []
    from ..resilience.guards import GarbageOutput
    for k in range(K):
        if failed[k]:
            out.append(None)
            continue
        st_k = jax.tree.map(lambda x: x[k], host)
        if record_paths and int(host.collisions[k]) > 0:
            out.append(None)  # read-id replay unavailable for this set
            continue
        try:
            pg = _download_graph(st_k, abpt)
        except GarbageOutput as e:
            # per-set isolation: one set's garbage output re-runs that set
            # on the caller's sequential path; the rest keep their results
            from ..obs import record_fault
            record_fault("garbage_output", backend=abpt.device, set_index=k,
                         detail=str(e)[:300], action="sequential_rerun")
            from ..resilience.breaker import breaker
            breaker().record_failure(
                "jax" if abpt.device == "tpu" else abpt.device,
                "garbage_output")
            out.append(None)
            continue
        if record_paths:
            _replay_read_ids(pg, st_k, int(n_reads_v[k]))
        n_k = int(n_reads_v[k])
        is_rc = ([bool(x) for x in np.asarray(st_k.rc_flags)[:n_k]]
                 if amb else [False] * n_k)
        out.append((pg, is_rc))
    return out


# --------------------------------------------------------------------------- #
# compile-ladder integration (abpoa_tpu/compile): AOT warmers               #
# --------------------------------------------------------------------------- #

def _fused_anchor_signatures(abpt: Params, anchor) -> list:
    """Map one warm anchor to the exact chunk signatures the planner can
    request anywhere in the anchor's Qp-rung interval, plus `growth` rungs
    of the node-capacity chain each start replays when the graph outgrows
    its start bucket. Pure host math through the SAME planner functions
    the drivers call, so warm and runtime cannot disagree."""
    from ..compile.ladder import qmax_interval
    Qp = qp_rung(anchor.qmax)
    lo, hi = qmax_interval(Qp)
    n_rung = reads_rung(anchor.n_reads)
    int16_limit = int16_score_limit(abpt)
    sigs, starts = [], set()
    q = lo
    while True:
        Qp_q, W, _local = _plan_buckets(abpt, q)
        assert Qp_q == Qp
        N0 = _bucket(2 * (q + 2) + 64, 1024)
        plane16 = max_score_bound(abpt, q, 2) <= int16_limit
        if (N0, W, plane16) not in starts:
            starts.add((N0, W, plane16))
            N = N0
            for _g in range(anchor.growth + 1):
                sigs.append(dict(N=N, E=8, A=8, W=W, Qp=Qp, reads=n_rung,
                                 plane16=plane16))
                N = grow_node_cap(N)
        if q >= hi:
            break
        q = min(q + 64, hi)  # catches every N/W/plane16 breakpoint
    out, seen = [], set()
    for s in sigs:
        t = tuple(sorted(s.items()))
        if t not in seen:
            seen.add(t)
            out.append(s)
    return out


def _warm_chunk_signature(abpt: Params, N: int, E: int, A: int, W: int,
                          Qp: int, reads: int, plane16: bool,
                          k: int = None) -> dict:
    """Dispatch one fused-chunk signature on zero inputs with n_reads=0:
    the while_loop exits before its first step, so the cost is pure XLA
    compile (or a persistent-cache load). Argument construction mirrors
    the drivers leaf for leaf — the zero-miss regression test would catch
    any drift."""
    from ..obs import compile_log
    local_m = abpt.align_mode == C.LOCAL_MODE
    use_pallas = abpt.device == "pallas"
    pl_interpret = jax.default_backend() != "tpu"
    record_paths = bool(abpt.use_read_ids)
    amb = bool(abpt.amb_strand)
    int16_limit = int16_score_limit(abpt)
    inf_min = dp_inf_min(abpt, INT16_MIN if plane16 else INT32_MIN)
    max_ops = N + Qp + 8
    up, up_hbm = _pallas_variant(abpt, use_pallas, local_m, W, plane16, Qp)
    kwargs = _static_chunk_kwargs(
        abpt, W=W, max_ops=max_ops, plane16=plane16,
        int16_limit=int16_limit, use_pallas=up, pl_interpret=pl_interpret,
        record_paths=record_paths, amb=amb, local_m=local_m,
        pallas_hbm=up_hbm)
    mat = jnp.asarray(np.ascontiguousarray(abpt.mat.astype(np.int32)))

    def one_state():
        return init_fused_state(N, E, A,
                                n_reads=reads if record_paths else 1,
                                Pcap=Qp + 2 if record_paths else 8,
                                n_rc=reads if amb else 1)

    name = "run_fused_chunk" if k is None else "run_fused_chunk[lockstep]"
    bucket = dict(N=N, E=E, A=A, W=W, Qp=Qp, reads=reads,
                  K=1 if k is None else k, plane16=plane16,
                  pallas=bool(up), pallas_hbm=bool(up_hbm),
                  gap_mode=abpt.gap_mode)
    if k is None:
        with registry.watch(name, bucket) as cw:
            st = run_fused_chunk(
                one_state(), jnp.zeros((reads, Qp), jnp.int32),
                jnp.ones((reads, Qp), jnp.int32),
                jnp.zeros(reads, jnp.int32), jnp.int32(0),
                jnp.zeros((reads, abpt.m, Qp), jnp.int32), mat,
                *_scalar_chunk_args(abpt, inf_min), **kwargs)
            int(st.err)  # sync inside the bracket
    else:
        state = jax.tree.map(lambda x: jnp.stack([x] * k), one_state())

        def chunk_one(st, sq, wg, ln, nr, qp):
            return run_fused_chunk(st, sq, wg, ln, nr, qp, mat,
                                   *_scalar_chunk_args(abpt, inf_min),
                                   **kwargs)

        with registry.watch(name, bucket, use_handle=False) as cw:
            st = jax.vmap(chunk_one)(
                state, jnp.zeros((k, reads, Qp), jnp.int32),
                jnp.ones((k, reads, Qp), jnp.int32),
                jnp.zeros((k, reads), jnp.int32),
                jnp.zeros(k, jnp.int32),
                jnp.zeros((k, reads, abpt.m, Qp), jnp.int32))
            np.asarray(st.err)  # sync inside the bracket
    recs = compile_log.run_records()
    if recs and recs[-1]["fn"] == name:
        return recs[-1]
    return {"fn": name, "bucket": bucket, "cache_hit": not cw["compiled"]}


def _warm_fused(abpt: Params, anchor) -> list:
    return [_warm_chunk_signature(abpt, **sig)
            for sig in _fused_anchor_signatures(abpt, anchor)]


def _warm_fused_lockstep(abpt: Params, anchor) -> list:
    k = k_rung(anchor.k or 8)
    return [_warm_chunk_signature(abpt, k=k, **sig)
            for sig in _fused_anchor_signatures(abpt, anchor)]


registry.register_entry("run_fused_chunk",
                        handle=lambda: run_fused_chunk, warmer=_warm_fused)
registry.register_entry("run_fused_chunk[lockstep]",
                        warmer=_warm_fused_lockstep)


def _download_graph(state: FusedState, abpt: Params):
    """One device->host transfer; rebuild the host POAGraph for output."""
    from ..graph import POAGraph, Node
    g = state.g
    n = int(g.node_n)
    base, in_ids, in_w, in_cnt, out_ids, out_w, out_cnt, aligned, aligned_cnt, \
        n_read, n_span = [np.asarray(x) for x in (
            g.base[:n], g.in_ids[:n], g.in_w[:n], g.in_cnt[:n],
            g.out_ids[:n], g.out_w[:n], g.out_cnt[:n],
            g.aligned[:n], g.aligned_cnt[:n], g.n_read[:n], g.n_span[:n])]
    # output sanity guard on the already-downloaded host array (no extra
    # sync): a mis-DMA'd kernel output must fail loudly here, not become a
    # wrong consensus. The garbage injector corrupts exactly this array.
    from .. import resilience as rz
    if rz.enabled():
        base = base.copy() if rz.inject.armed("garbage") else base
        rz.inject.corrupt_graph_base(base)
        rz.guards.check_graph_bases(base[2:], abpt.m)  # skip src/sink
    pg = POAGraph()
    pg.nodes = []
    for i in range(n):
        nd = Node(i, int(base[i]))
        ic, oc, ac = int(in_cnt[i]), int(out_cnt[i]), int(aligned_cnt[i])
        nd.in_ids = [int(x) for x in in_ids[i][:ic]]
        nd.in_w = [int(x) for x in in_w[i][:ic]]
        nd.out_ids = [int(x) for x in out_ids[i][:oc]]
        nd.out_w = [int(x) for x in out_w[i][:oc]]
        nd.read_ids = [0] * oc
        nd.aligned_ids = [int(x) for x in aligned[i][:ac]]
        nd.n_read = int(n_read[i])
        nd.n_span_read = int(n_span[i])
        pg.nodes.append(nd)
    pg.topological_sort(abpt)   # reference BFS order for all output walks
    return pg
