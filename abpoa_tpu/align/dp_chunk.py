"""DP-only batched chunk: the lockstep dispatch with fusion OFF the batch axis.

ROUND8_NOTES.md measured K=4 all-device lockstep 1.37x SLOWER than serial on
CPU hosts: the vmapped fusion scatters (and the vmapped while_loop's
per-iteration full-plane selects) multiplied trip counts instead of widening
lanes. Fusion is host-cheap (~24 ms/read measured) and sequential-per-read
anyway — so the split lockstep driver (parallel/lockstep.py) keeps each
set's graph on the HOST and batches only what vectorizes: the banded DP
scan + device backtrack, one vmapped dispatch per read round across K sets.

This module owns that dispatch:

- `run_dp_chunk`: jit(vmap) of fused_loop's `_dp_banded` (static_rows mode —
  a fori_loop, because a vmapped while_loop's batched cond wraps every carry
  in a per-iteration select: measured ~200x at K=4 on XLA:CPU) plus best-cell
  selection and `_backtrack_w`, returning one packed int32 row per set.
- `build_lockstep_tables`: numpy mirror of fused_loop._build_tables for a
  host POAGraph — same masks, same band seeding, same remain semantics, so
  the batched DP sees exactly the tables the fused loop would have built.
- `cigar_from_ops`: the reference-order cigar rebuild (the same walk as
  jax_backend._result_from_packed), feeding the host graph's add_alignment.

Compile ladder: entry "run_dp_chunk" with axes R (row rung, GEOM_64 like the
window batch), Qp/W (shared chunk buckets), P (degree slots, pow2 floor 8)
and K (set axis, pow2); `abpoa-tpu warm` precompiles the anchors.
"""
from __future__ import annotations

import functools
import itertools
from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import constants as C
from ..compile import registry
from ..compile.buckets import bucket as _bucket
from ..compile.buckets import bucket_pow2 as _bucket_pow2
from ..params import Params
from .fused_loop import _backtrack_w, _dp_banded
from .oracle import (INT16_MIN, INT32_MIN, dp_inf_min, int16_score_limit,
                     max_score_bound)
from .result import AlignResult
from ..cigar import push_cigar

# degree-slot floor: POA in/out-degrees sit at <= 8 for realistic data, and a
# fixed floor keeps the (R, K) compile grid deterministic for the warmer
P_FLOOR = 8


# --------------------------------------------------------------------------- #
# device entry point                                                          #
# --------------------------------------------------------------------------- #

@functools.partial(jax.jit, static_argnames=(
    "gap_mode", "W", "max_ops", "plane16", "extend", "zdrop_on", "local",
    "gap_on_right", "put_gap_at_end"))
def run_dp_chunk(base_r, pre_idx, pre_msk, out_idx, out_msk, row_active,
                 remain_rows, mpl0, mpr0, qp, query, n_rows, qlen, w,
                 remain_end, dp_end0, mat, inf_min,
                 o1, e1, oe1, o2, e2, oe2, zdrop,
                 gap_mode: int, W: int, max_ops: int, plane16: bool,
                 extend: bool, zdrop_on: bool, local: bool,
                 gap_on_right: bool, put_gap_at_end: bool):
    """One read round for K sets: banded DP + backtrack, no graph update.

    Leading axis of every table/scalar array is the set axis K. Returns a
    (K, 10 + 2*max_ops) int32 pack per set:
    [n_ops, fin_i, fin_j, n_aln, n_match, bt_err, overflow, best_score,
     best_i, best_j] + ops.flat — the host rebuilds the cigar and fuses.
    """

    def one(base_r, pre_idx, pre_msk, out_idx, out_msk, row_active,
            remain_rows, mpl0, mpr0, qp, query, n_rows, qlen, w,
            remain_end, dp_end0):
        (Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, _ml, _mr, overflow,
         bs, bi, bj) = _dp_banded(
            base_r, pre_idx, pre_msk, out_idx, out_msk, row_active,
            remain_rows, mpl0, mpr0, qp, n_rows,
            qlen, w, remain_end, inf_min, dp_end0,
            o1, e1, oe1, o2, e2, oe2,
            gap_mode=gap_mode, W=W, plane16=plane16, extend=extend,
            zdrop_on=zdrop_on, zdrop=zdrop, local=local, static_rows=True)
        if extend or local:
            best_i, best_j, best_sc = bi, bj, bs
        else:
            # global best over the sink's pred rows at their band ends
            # (mirror of fused_loop.align_strand's selection)
            sink_rows = pre_idx[n_rows - 1]
            sink_msk = pre_msk[n_rows - 1]
            ends = jnp.minimum(qlen, dp_end[sink_rows])
            kidx = jnp.clip(ends - dp_beg[sink_rows], 0, W - 1)
            vals = jnp.where(sink_msk & (ends - dp_beg[sink_rows] >= 0)
                             & (ends - dp_beg[sink_rows] < W),
                             jnp.take_along_axis(Hb[sink_rows],
                                                 kidx[:, None],
                                                 axis=1)[:, 0],
                             inf_min.astype(Hb.dtype))
            kk = jnp.argmax(vals)
            best_i = sink_rows[kk]
            best_j = ends[kk]
            best_sc = vals[kk].astype(jnp.int32)
        ops, n_ops, fin_i, fin_j, n_aln, n_match, bt_err = _backtrack_w(
            Hb, E1b, E2b, F1b, F2b, dp_beg, dp_end, pre_idx, pre_msk,
            base_r, query, mat, best_i, best_j,
            e1, oe1, e2, oe2, inf_min,
            gap_mode=gap_mode, gap_on_right=gap_on_right,
            put_gap_at_end=put_gap_at_end, max_ops=max_ops, local=local)
        head = jnp.stack([n_ops, fin_i, fin_j, n_aln, n_match,
                          bt_err.astype(jnp.int32),
                          overflow.astype(jnp.int32),
                          best_sc, best_i.astype(jnp.int32),
                          best_j.astype(jnp.int32)])
        return jnp.concatenate([head, ops.reshape(-1)])

    return jax.vmap(one)(base_r, pre_idx, pre_msk, out_idx, out_msk,
                         row_active, remain_rows, mpl0, mpr0, qp, query,
                         n_rows, qlen, w, remain_end, dp_end0)


# --------------------------------------------------------------------------- #
# host-side table builder (numpy mirror of fused_loop._build_tables)          #
# --------------------------------------------------------------------------- #

def build_graph_tables(g, abpt: Params) -> dict:
    """The GRAPH half of the kernel tables: everything that depends only
    on host POAGraph `g` (adjacency scatters, band seeds, remain rows),
    at the graph's exact row count.

    Mirrors fused_loop._build_tables mask for mask (pre rows > 0 and < n,
    out rows > 0 and < n-1, row_active (0, n-1), mpl0 = n everywhere except
    source 0 / source-outs 1) so the batched DP computes exactly what the
    fused loop would. Any valid topological order yields identical results
    (fused_loop module docstring) — the host graph's reference BFS order is
    used directly.

    The build is a numpy batch scatter over the flattened adjacency: one
    pass collects the per-row edge lists (Python-object graph, so the list
    gather itself cannot vectorize), then every table lands in a handful
    of whole-array ops instead of 2n per-row assignments. The split
    driver rebuilds these tables for every set of every round (consensus
    graphs grow); the map driver builds them ONCE per static graph
    (`StaticGraphTables`) and only re-stamps the query half per read.
    """
    if not g.is_topological_sorted:
        g.topological_sort(abpt)
    n = g.node_n
    nodes = g.nodes
    idx2nid = np.asarray(g.index_to_node_id[:n], dtype=np.int64)
    n2i = np.asarray(g.node_id_to_index)
    remain = np.asarray(g.node_id_to_max_remain)

    ordered = [nodes[nid] for nid in idx2nid.tolist()]
    # mask semantics: pre rows exclude the source row 0, out rows exclude
    # source AND sink (0, n-1) — empty lists instead of slicing later so
    # the flattened scatter below needs no row filtering
    pre_lists = [nd.in_ids for nd in ordered]
    out_lists = [nd.out_ids for nd in ordered]
    pre_lists[0] = []
    out_lists[0] = []
    out_lists[-1] = []
    pre_lens = np.fromiter(map(len, pre_lists), np.int64, count=n)
    out_lens = np.fromiter(map(len, out_lists), np.int64, count=n)
    d_max = max(1, int(pre_lens.max(initial=0)),
                int(out_lens.max(initial=0)))
    P = max(P_FLOOR, _bucket_pow2(d_max))

    def _scatter(lists, lens):
        """(n, P) idx/msk tables from ragged per-row node-id lists: flat
        gather + one fancy-indexed scatter (no per-row assignments)."""
        idx = np.zeros((n, P), np.int32)
        msk = np.zeros((n, P), bool)
        total = int(lens.sum())
        if total:
            flat = np.fromiter(
                itertools.chain.from_iterable(lists), np.int64, count=total)
            rows = np.repeat(np.arange(n), lens)
            starts = np.concatenate(([0], np.cumsum(lens)[:-1]))
            cols = np.arange(total) - np.repeat(starts, lens)
            idx[rows, cols] = n2i[flat]
            msk[rows, cols] = True
        return idx, msk

    pre_idx, pre_msk = _scatter(pre_lists, pre_lens)
    out_idx, out_msk = _scatter(out_lists, out_lens)
    base_r = np.fromiter((nd.base for nd in ordered), np.int32, count=n)
    remain_rows = remain[idx2nid].astype(np.int32)
    row_active = np.zeros(n, bool)
    row_active[1:n - 1] = True
    mpl0 = np.full(n, n, np.int32)
    mpl0[0] = 0
    mpr0 = np.zeros(n, np.int32)
    src_rows = n2i[np.asarray(nodes[C.SRC_NODE_ID].out_ids, np.int64)]
    mpl0[src_rows] = 1
    mpr0[src_rows] = 1

    return dict(base_r=base_r, pre_idx=pre_idx, pre_msk=pre_msk,
                out_idx=out_idx, out_msk=out_msk, row_active=row_active,
                remain_rows=remain_rows, mpl0=mpl0, mpr0=mpr0, n_rows=n,
                remain_end=int(remain[C.SINK_NODE_ID]))


def stamp_query(gt: dict, abpt: Params, query: np.ndarray, Qp: int) -> dict:
    """Stamp the QUERY half (qp profile, query pad, band scalars) onto one
    graph-table dict, returning the complete kernel table set. The graph
    arrays are shared by reference — `_pad_tables`/`dispatch_dp_chunk`
    never mutate them, and the rc rescue path copies the dict before
    re-stamping — so a cached graph can serve arbitrarily many reads."""
    if len(query) + 2 > Qp:
        # the lane-churn rung contract: every read of every lane —
        # initial or mid-flight joiner — must fit the group's planned Qp
        # (qp_rung guarantees qmax + 2 <= Qp; the split driver rejects
        # off-rung joiners before they reach a table build)
        raise ValueError(
            f"query len {len(query)} does not fit Qp {Qp} (needs qlen + 2 "
            "<= Qp): an off-rung lane slipped past the driver's join gate")
    qlen = len(query)
    # band scalars: the python-float w of the per-read host path (the
    # oracle's arithmetic), not the fused loop's traced f32 twin
    w = abpt.wb + int(abpt.wf * qlen)
    remain_end = gt["remain_end"]
    if abpt.align_mode == C.LOCAL_MODE:
        dp_end0 = qlen
    else:
        r0 = qlen - (int(gt["remain_rows"][0]) - remain_end - 1)
        dp_end0 = min(qlen, max(int(gt["mpr0"][0]), r0) + w)

    qp = np.zeros((abpt.m, Qp), np.int32)
    query_pad = np.zeros(Qp, np.int32)
    if qlen:
        qp[:, 1: qlen + 1] = abpt.mat[:, query]
        query_pad[:qlen] = query
    out = dict(gt)
    out.update(qp=qp, query=query_pad, qlen=qlen, w=w, dp_end0=dp_end0)
    return out


def build_lockstep_tables(g, abpt: Params, query: np.ndarray,
                          Qp: int) -> dict:
    """Kernel tables for one whole-graph global alignment of `query`
    against host POAGraph `g` — the graph half (`build_graph_tables`)
    plus the query stamp (`stamp_query`). The split consensus driver
    calls this per lane per round because its graphs grow; fixed-graph
    consumers cache the graph half in a `StaticGraphTables` instead."""
    if len(query) + 2 > Qp:
        raise ValueError(
            f"query len {len(query)} does not fit Qp {Qp} (needs qlen + 2 "
            "<= Qp): an off-rung lane slipped past the driver's join gate")
    return stamp_query(build_graph_tables(g, abpt), abpt, query, Qp)


class StaticGraphTables:
    """Immutable per-graph DP tables for the map workload: built once from
    a restored GFA/MSA graph, then stamped per read by the map driver.

    Caches everything read-to-read invariant — the graph-table dict, the
    index->node-id map the cigar rebuild walks, the degree rung P, the
    row rung R, and a node-id-indexed base array for GAF match counting —
    so streaming N reads pays ONE adjacency scatter instead of N (the
    consensus path's per-round rebuild cost, deleted by a graph that
    never grows)."""

    __slots__ = ("graph", "abpt", "tables", "idx2nid", "n_rows", "P", "R",
                 "base_by_nid")

    def __init__(self, g, abpt: Params) -> None:
        self.graph = g
        self.abpt = abpt
        self.tables = build_graph_tables(g, abpt)
        self.n_rows = self.tables["n_rows"]
        self.idx2nid = np.asarray(g.index_to_node_id[:self.n_rows],
                                  dtype=np.int64)
        self.P = self.tables["pre_idx"].shape[1]
        self.R = plan_row_rung(self.n_rows)
        base = np.zeros(int(self.idx2nid.max(initial=0)) + 1, np.int32)
        base[self.idx2nid] = self.tables["base_r"]
        self.base_by_nid = base

    def tables_for(self, query: np.ndarray, Qp: int) -> dict:
        """Complete kernel tables for one read (graph arrays shared)."""
        return stamp_query(self.tables, self.abpt, query, Qp)


def chunk_plane16(abpt: Params, qlen: int, n: int) -> bool:
    """int16 planes while the score bound allows — the host-side twin of
    the fused loop's in-loop ERR_PROMOTE check (oracle.max_score_bound)."""
    limit = int16_score_limit(abpt)
    ln = max(qlen, n)
    bound = max(qlen * int(abpt.max_mat),
                ln * int(abpt.gap_ext1) + int(abpt.gap_open1))
    return bound <= limit


# --------------------------------------------------------------------------- #
# packed-output unpack: cigar rebuild + AlignResult                           #
# --------------------------------------------------------------------------- #

HEAD_LEN = 10


def result_from_chunk(abpt: Params, packed: np.ndarray, tables: dict,
                      idx2nid) -> Tuple[AlignResult, dict]:
    """One set's packed row -> (AlignResult with cigar, status flags).

    The cigar walk is jax_backend._result_from_packed's reference-order
    rebuild; flags report band overflow (grow W and retry the round) and
    backtrack divergence (set falls back to the sequential path). The op
    count is derived from the row length, so it cannot drift from the
    max_ops dispatch_dp_chunk sized the row with.
    """
    max_ops = (len(packed) - HEAD_LEN) // 2
    (n_ops, fin_i, fin_j, n_aln, n_match, bt_err, overflow, best_score,
     best_i, best_j) = [int(x) for x in packed[:HEAD_LEN]]
    flags = {"overflow": bool(overflow), "bt_err": bool(bt_err)}
    res = AlignResult()
    res.best_score = best_score
    if overflow or bt_err:
        return res, flags
    qlen = tables["qlen"]
    ops = packed[HEAD_LEN:].reshape(max_ops, 2)
    res.n_aln_bases = n_aln
    res.n_matched_bases = n_match
    cigar: list = []
    if best_j < qlen:
        push_cigar(cigar, C.CINS, qlen - best_j, -1, qlen - 1)
    jj = best_j
    for ti in range(n_ops):
        opc, dpi = int(ops[ti, 0]), int(ops[ti, 1])
        nid = int(idx2nid[dpi])
        if opc == 0:
            push_cigar(cigar, C.CMATCH, 1, nid, jj - 1)
            jj -= 1
        elif opc == 1:
            push_cigar(cigar, C.CDEL, 1, nid, jj - 1)
        else:
            push_cigar(cigar, C.CINS, 1, nid, jj - 1)
            jj -= 1
    if fin_j > 0:
        push_cigar(cigar, C.CINS, fin_j, -1, fin_j - 1)
    if not abpt.rev_cigar:
        cigar.reverse()
    res.cigar = cigar
    res.node_e = int(idx2nid[best_i]) if best_i < len(idx2nid) else -1
    res.query_e = best_j - 1
    return res, flags


# --------------------------------------------------------------------------- #
# dispatch helper: pad/stack K table dicts and run one chunk                  #
# --------------------------------------------------------------------------- #

_TABLE_KEYS = ("base_r", "pre_idx", "pre_msk", "out_idx", "out_msk",
               "row_active", "remain_rows", "mpl0", "mpr0", "qp", "query")
_SCALAR_KEYS = ("n_rows", "qlen", "w", "remain_end", "dp_end0")


def chunk_statics(abpt: Params, W: int, max_ops: int, plane16: bool) -> dict:
    extend_m = abpt.align_mode == C.EXTEND_MODE
    return dict(gap_mode=abpt.gap_mode, W=W, max_ops=max_ops,
                plane16=plane16,
                extend=extend_m, zdrop_on=extend_m and abpt.zdrop > 0,
                local=abpt.align_mode == C.LOCAL_MODE,
                gap_on_right=bool(abpt.put_gap_on_right),
                put_gap_at_end=bool(abpt.put_gap_at_end))


def _pad_tables(t: dict, R: int, P: int) -> dict:
    """Pad one set's exact-size tables to the round's shared (R, P) rungs.
    Padding rows are inactive/unmasked; their band seeds are never read."""
    out = dict(t)
    n = t["base_r"].shape[0]
    p0 = t["pre_idx"].shape[1]
    for key in ("base_r", "row_active", "remain_rows", "mpl0", "mpr0"):
        a = t[key]
        out[key] = np.concatenate([a, np.zeros(R - n, a.dtype)]) \
            if R > n else a
    for key in ("pre_idx", "pre_msk", "out_idx", "out_msk"):
        a = t[key]
        a = np.pad(a, ((0, R - n), (0, P - p0))) if (R > n or P > p0) else a
        out[key] = a
    return out


def dispatch_dp_chunk(abpt: Params, table_list: List[dict], Kb: int, R: int,
                      P: int, Qp: int, W: int, plane16: bool,
                      mesh=None) -> np.ndarray:
    """Pad `table_list` to the shared (R, P) rungs and Kb set slots (zero
    no-op sets), dispatch ONE run_dp_chunk, return the
    (len(table_list), ...) packed rows. Padding slots carry
    n_rows=2/qlen=0: the backtrack exits at (0, 0) and the row loop sees
    every row inactive.

    With a `mesh` (jax.sharding.Mesh of >= 2 devices) the round runs
    sharded instead: `parallel.shard.shard_dp_round` reshapes the lane
    axis to (mesh, Kb/mesh) and dispatches ONE shard_map(vmap) round —
    same padding, same packing, byte-identical rows. The drivers stay
    mesh-agnostic: every dispatch site threads its mesh through here."""
    if mesh is not None and mesh.devices.size > 1:
        from ..parallel.shard import shard_dp_round
        return shard_dp_round(abpt, table_list, Kb, R, P, Qp, W, plane16,
                              mesh)
    max_ops = R + Qp + 8
    k_real = len(table_list)
    padded = [_pad_tables(t, R, P) for t in table_list]
    arrays = {}
    for key in _TABLE_KEYS:
        stacked = np.stack([t[key] for t in padded])
        if k_real < Kb:
            pad = np.zeros((Kb - k_real,) + stacked.shape[1:],
                           stacked.dtype)
            stacked = np.concatenate([stacked, pad])
        arrays[key] = jnp.asarray(stacked)
    scalars = {}
    for key in _SCALAR_KEYS:
        vec = np.asarray([t[key] for t in table_list], np.int32)
        if k_real < Kb:
            fill = 2 if key == "n_rows" else 0
            vec = np.concatenate([vec, np.full(Kb - k_real, fill, np.int32)])
        scalars[key] = jnp.asarray(vec)
    inf_min = dp_inf_min(abpt, INT16_MIN if plane16 else INT32_MIN)
    mat = jnp.asarray(np.ascontiguousarray(abpt.mat.astype(np.int32)))
    statics = chunk_statics(abpt, W, max_ops, plane16)
    bucket = dict(R=R, P=P, Qp=Qp, W=W, K=Kb, plane16=plane16,
                  gap_mode=abpt.gap_mode, align_mode=abpt.align_mode)
    import time as _time

    from ..obs import rounds, trace
    t_dp = _time.perf_counter()
    with trace.span("dp_chunk", "dp", args=dict(bucket, sets=k_real)):
        with registry.watch("run_dp_chunk", bucket):
            packed = run_dp_chunk(
                arrays["base_r"], arrays["pre_idx"], arrays["pre_msk"],
                arrays["out_idx"], arrays["out_msk"], arrays["row_active"],
                arrays["remain_rows"], arrays["mpl0"], arrays["mpr0"],
                arrays["qp"], arrays["query"], scalars["n_rows"],
                scalars["qlen"], scalars["w"], scalars["remain_end"],
                scalars["dp_end0"], mat, jnp.int32(inf_min),
                jnp.int32(abpt.gap_open1), jnp.int32(abpt.gap_ext1),
                jnp.int32(abpt.gap_oe1), jnp.int32(abpt.gap_open2),
                jnp.int32(abpt.gap_ext2), jnp.int32(abpt.gap_oe2),
                jnp.int32(max(abpt.zdrop, 0)), **statics)
            out = np.asarray(packed)  # sync inside the compile bracket
    # the rounds timeline's dispatch wall brackets the same region as the
    # dp_chunk trace span, so the two reconcile by construction
    rounds.note_dispatch(_time.perf_counter() - t_dp)
    return out[:k_real]


def plan_row_rung(n_max: int) -> int:
    """Row rung for the largest active graph this round (GEOM_64 chain —
    the declared R axis of the run_dp_chunk ladder entry)."""
    return _bucket(max(n_max, 8), 64)


def plan_degree_rung(d_max: int) -> int:
    return max(P_FLOOR, _bucket_pow2(d_max))


# --------------------------------------------------------------------------- #
# compile-ladder integration: AOT warmer                                      #
# --------------------------------------------------------------------------- #

def _warm_dp_chunk(abpt: Params, anchor) -> list:
    """Precompile the split-lockstep DP chunk for one anchor: the start row
    rung of the anchor's qmax plus `growth` rungs of graph growth, at the
    anchor's K rung and its repack halvings. Zero-filled no-op inputs (every
    row inactive, qlen 0) make the dispatch cost pure compile."""
    from ..compile.ladder import k_rung, plan_chunk_buckets, qp_rung
    from ..obs import compile_log
    recs = []
    Qp = qp_rung(anchor.qmax)
    _qp, W, _local = plan_chunk_buckets(abpt, anchor.qmax)
    plane16 = max_score_bound(abpt, anchor.qmax, 2) <= int16_score_limit(abpt)
    ks = []
    k = k_rung(anchor.k or 4)
    while k >= 1:
        ks.append(k)
        k //= 2
    rungs = []
    R = plan_row_rung(anchor.qmax + 2)
    stop = plan_row_rung(2 * (anchor.qmax + 2) + 64)
    for _g in range(anchor.growth + 1):
        rungs.append(R)
        if R >= stop:
            break
        R = plan_row_rung(R + 1)
    for R in rungs:
        for Kb in ks:
            tables = [dict(
                base_r=np.zeros(R, np.int32),
                pre_idx=np.zeros((R, P_FLOOR), np.int32),
                pre_msk=np.zeros((R, P_FLOOR), bool),
                out_idx=np.zeros((R, P_FLOOR), np.int32),
                out_msk=np.zeros((R, P_FLOOR), bool),
                row_active=np.zeros(R, bool),
                remain_rows=np.zeros(R, np.int32),
                mpl0=np.zeros(R, np.int32), mpr0=np.zeros(R, np.int32),
                qp=np.zeros((abpt.m, Qp), np.int32),
                query=np.zeros(Qp, np.int32),
                n_rows=2, qlen=0, w=0, remain_end=0, dp_end0=0)] * Kb
            dispatch_dp_chunk(abpt, tables, Kb, R, P_FLOOR, Qp, W, plane16)
            rr = compile_log.run_records()
            recs.append(rr[-1] if rr and rr[-1]["fn"] == "run_dp_chunk"
                        else {"fn": "run_dp_chunk",
                              "bucket": dict(R=R, K=Kb, Qp=Qp, W=W)})
    return recs


registry.register_entry("run_dp_chunk", handle=lambda: run_dp_chunk,
                        warmer=_warm_dp_chunk)
