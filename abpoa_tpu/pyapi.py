"""pyabpoa-compatible Python API.

Mirrors /root/reference/python/pyabpoa.pyx: `msa_aligner` with one-shot
`msa()` and incremental `msa_align()` / `msa_add()` / `msa_output()`, returning
`msa_result` objects. Drives the same per-sequence granularity as the binding
(align one read, fuse it, repeat) rather than the file-level driver.
"""
from __future__ import annotations

import time
from typing import List

import numpy as np

from . import constants as C
from . import obs
from .align import align_sequence_to_graph
from .cons.consensus import generate_consensus
from .cons.msa import generate_rc_msa
from .graph import POAGraph
from .params import Params
from .pipeline import Abpoa


class msa_result:
    def __init__(self, n_seq, n_cons, clu_n_seq, clu_read_ids, cons_len,
                 cons_seq, cons_cov, cons_qv, msa_len, msa_seq):
        self.n_seq = n_seq
        self.n_cons = n_cons
        self.clu_n_seq = clu_n_seq
        self.clu_read_ids = clu_read_ids
        self.cons_len = cons_len
        self.cons_seq = cons_seq
        self.cons_cov = cons_cov
        self.cons_qv = cons_qv
        self.msa_len = msa_len
        self.msa_seq = msa_seq

    def print_msa(self) -> None:
        if not self.msa_seq:
            return
        for i, s in enumerate(self.msa_seq):
            if i < self.n_seq:
                print(f">Seq_{i + 1}")
            else:
                cons_id = ""
                if self.n_cons > 1:
                    ids = ",".join(map(str, self.clu_read_ids[i - self.n_seq]))
                    cons_id = f"_{i - self.n_seq + 1} {ids}"
                print(f">Consensus_sequence{cons_id}")
            print(s)


class msa_aligner:
    def __init__(self, aln_mode="g", is_aa=False, match=2, mismatch=4,
                 score_matrix="", gap_open1=4, gap_open2=24, gap_ext1=2,
                 gap_ext2=1, extra_b=10, extra_f=0.01, cons_algrm="HB",
                 device="numpy", lockstep="auto"):
        abpt = Params()
        modes = {"g": C.GLOBAL_MODE, "l": C.LOCAL_MODE, "e": C.EXTEND_MODE}
        if aln_mode not in modes:
            raise ValueError(f"Unknown alignment mode: {aln_mode}")
        abpt.align_mode = modes[aln_mode]
        if is_aa:
            abpt.m = 27
        abpt.match = match
        abpt.mismatch = mismatch
        if score_matrix:
            abpt.use_score_matrix = True
            abpt.mat_fn = score_matrix if isinstance(score_matrix, str) \
                else score_matrix.decode()
        abpt.gap_open1, abpt.gap_open2 = gap_open1, gap_open2
        abpt.gap_ext1, abpt.gap_ext2 = gap_ext1, gap_ext2
        abpt.wb, abpt.wf = extra_b, extra_f
        abpt.disable_seeding = True
        abpt.progressive_poa = False
        if cons_algrm.upper() == "MF":
            abpt.cons_algrm = C.CONS_MF
        elif cons_algrm.upper() == "HB":
            abpt.cons_algrm = C.CONS_HB
        else:
            raise ValueError(f"Unknown consensus algorithm: {cons_algrm}")
        abpt.device = device
        # msa_batch lockstep policy: "auto" vmaps K sets only on a real
        # accelerator mesh (serial is faster on CPU, ROUND8_NOTES.md);
        # "on"/"off" force it (parallel.lockstep_enabled)
        abpt.lockstep = lockstep
        self.abpt = abpt
        self.ab = Abpoa()
        self._last_report = None

    @property
    def last_report(self):
        """Structured run-telemetry dict (obs schema, versioned) for the
        most recent msa()/msa_batch()/msa_output() call; None before the
        first call. See abpoa_tpu/obs/report.py for the schema."""
        return self._last_report

    # ------------------------------------------------------------- internals
    def _add_sequences(self, seqs: List[str], qscores, exist_n: int, tot_n: int):
        abpt = self.abpt
        enc = abpt.char_to_code
        g = self.ab.graph
        if qscores is not None and len(qscores) != len(seqs):
            raise ValueError("qscores must contain one entry per input sequence.")
        from .resilience import PoisonedSetError
        for read_i, seq in enumerate(seqs):
            if not seq:
                raise PoisonedSetError(
                    f"sequence {read_i} is empty")
            bseq = enc[np.frombuffer(seq.encode(), dtype=np.uint8)].astype(np.uint8)
            weights = None
            if qscores is not None:
                q = qscores[read_i]
                if len(q) != len(seq):
                    raise ValueError(
                        "Each qscore array must have the same length as its sequence.")
                weights = np.asarray(q, dtype=np.int64)
                if (weights < 0).any():
                    raise ValueError("Qscores must be non-negative integers.")
            from .pipeline import _band_cols
            if g.node_n > 2:
                obs.record_dp(g.node_n, _band_cols(abpt, len(bseq)),
                              abpt.gap_mode)
            t_read = time.perf_counter()
            with obs.phase("align"):
                res = align_sequence_to_graph(g, abpt, bseq)
            with obs.phase("fusion"):
                g.add_alignment(abpt, bseq, weights, None, res.cigar,
                                exist_n + read_i, tot_n, True)
            dt = time.perf_counter() - t_read
            from .align.dispatch import telemetry_backend
            backend, auto_fb = telemetry_backend(abpt)
            obs.record_read(dt, len(bseq), _band_cols(abpt, len(bseq)),
                            backend, fallback=auto_fb)
            obs.trace.add_span(f"read:{exist_n + read_i}", "read", t_read,
                               dt, args={"qlen": len(bseq)})
            self.ab.append_read(seq=seq)

    def _collect(self, n_seq: int, ab: Abpoa = None) -> msa_result:
        abpt = self.abpt
        if ab is None:
            ab = self.ab
        g = ab.graph
        from .cons.consensus import native_consensus_hb, native_hb_eligible
        with obs.phase("consensus"):
            if native_hb_eligible(g, abpt):
                abc = native_consensus_hb(g, n_seq)
            else:
                if getattr(g, "is_native", False):
                    g = g.to_python(abpt)
                if abpt.out_msa:
                    abc = generate_rc_msa(g, abpt, n_seq)
                elif abpt.out_cons:
                    abc = generate_consensus(g, abpt, n_seq)
                else:
                    from .cons.consensus import ConsensusResult
                    abc = ConsensusResult(n_seq=n_seq)
        decode = abpt.code_to_char
        cons_seq = ["".join(chr(decode[b]) for b in row) for row in abc.cons_base]
        cons_qv = ["".join(chr(q) for q in row) for row in abc.cons_phred]
        msa_seq = []
        if abc.msa_len > 0:
            for row in abc.msa_base:
                msa_seq.append("".join(chr(decode[b]) for b in row))
        ab.cons = abc
        return msa_result(n_seq, abc.n_cons, list(abc.clu_n_seq),
                          [list(x) for x in abc.clu_read_ids], abc.cons_len,
                          cons_seq, [list(c) for c in abc.cons_cov], cons_qv,
                          abc.msa_len, msa_seq)

    def _prepare(self, seqs, out_cons, out_msa, max_n_cons, min_freq, incr_fn,
                 qscores):
        abpt = self.abpt
        abpt.out_cons = bool(out_cons)
        abpt.out_msa = bool(out_msa)
        if not 1 <= max_n_cons <= 2:
            raise Exception("Error: max number of consensus sequences should be 1 or 2.")
        abpt.max_n_cons = max_n_cons
        abpt.min_freq = min_freq
        abpt.use_qv = qscores is not None
        abpt.finalize()
        self.ab.reset()
        exist_n = 0
        if incr_fn:
            abpt.incr_fn = incr_fn if isinstance(incr_fn, str) else incr_fn.decode()
            from .io.restore import restore_graph
            restore_graph(self.ab, abpt)  # works on both graph engines
            exist_n = self.ab.n_seq
        else:
            abpt.incr_fn = None
        return exist_n

    # ------------------------------------------------------------ public API
    def msa(self, seqs, out_cons, out_msa, max_n_cons=1, min_freq=0.25,
            out_pog="", incr_fn="", qscores=None) -> msa_result:
        # nested call from msa_batch's sequential fallback keeps the
        # batch-level report instead of starting its own
        nested = getattr(self, "_in_batch", False)
        if not nested:
            obs.start_run()
        abpt = self.abpt
        abpt.out_pog = (out_pog if isinstance(out_pog, str) else out_pog.decode()) or None
        exist_n = self._prepare(seqs, out_cons, out_msa, max_n_cons, min_freq,
                                incr_fn, qscores)
        tot_n = exist_n + len(seqs)
        self._add_sequences(seqs, qscores, exist_n, tot_n)
        result = self._collect(tot_n)
        if abpt.out_pog:
            from .io.plot import dump_pog
            dump_pog(self.ab, abpt)
        if not nested:
            self._last_report = obs.finalize_report()
        return result

    def msa_batch(self, seq_sets, out_cons, out_msa, max_n_cons=1,
                  min_freq=0.25, qscores_sets=None) -> List[msa_result]:
        """Lockstep multi-set batching: K independent read sets advance
        through the fused progressive loop as one vmapped device dispatch
        per chunk (the CLI's `-l` file-list mode; the reference processes
        sets sequentially, src/abpoa.c:148-168). Sets outside fused-loop
        scope — or when no device backend is selected — fall back to the
        sequential `msa()` path; results are identical either way."""
        if qscores_sets is not None and len(qscores_sets) != len(seq_sets):
            raise ValueError("qscores_sets must contain one entry per set.")
        obs.start_run()
        # batch-progress gauges (same family the -l runner publishes):
        # a live `top` over the exporter shows sets done / total
        obs.metrics.publish_batch_progress(0, total=len(seq_sets))
        self._in_batch = True
        try:
            return self._msa_batch_inner(seq_sets, out_cons, out_msa,
                                         max_n_cons, min_freq, qscores_sets)
        finally:
            self._in_batch = False
            self._last_report = obs.finalize_report()

    def _msa_batch_inner(self, seq_sets, out_cons, out_msa, max_n_cons,
                         min_freq, qscores_sets) -> List[msa_result]:
        abpt = self.abpt
        abpt.out_cons = bool(out_cons)
        abpt.out_msa = bool(out_msa)
        if not 1 <= max_n_cons <= 2:
            raise Exception(
                "Error: max number of consensus sequences should be 1 or 2.")
        abpt.max_n_cons = max_n_cons
        abpt.min_freq = min_freq
        abpt.use_qv = qscores_sets is not None
        abpt.incr_fn = None
        abpt.finalize()
        from . import resilience as rz
        from .align.eligibility import fused_eligible

        def seq_fallback(k):
            qs = qscores_sets[k] if qscores_sets is not None else None
            # per-set quarantine: one poisoned set (malformed record,
            # empty sequence) returns None in its slot — reported as a
            # `faults` record with the set index — and the rest complete
            try:
                return self.msa(seq_sets[k], out_cons, out_msa, max_n_cons,
                                min_freq, qscores=qs)
            except rz.QUARANTINE_EXCEPTIONS as e:
                rz.quarantine_set(k, f"set {k}", e)
                return None

        _mark_set_done = obs.metrics.bump_batch_set_done
        results: List[msa_result] = [None] * len(seq_sets)
        lockstep: List[int] = []
        enc_sets, wgt_sets = [], []
        eligible = abpt.device in ("jax", "tpu", "pallas")
        if eligible:
            from .parallel import lockstep_enabled
            from .pipeline import plain_route
            from .utils.probe import jax_backend_reachable
            eligible = (lockstep_enabled(abpt) and plain_route(abpt)
                        and jax_backend_reachable())
            if eligible:
                from .utils.probe import apply_platform_pin
                apply_platform_pin()
        enc = abpt.char_to_code
        for k, seqs in enumerate(seq_sets):
            if not (eligible and fused_eligible(abpt, len(seqs))):
                continue
            if any(len(s) == 0 for s in seqs):
                continue  # poisoned: let seq_fallback quarantine it
            if (qscores_sets is not None
                    and len(qscores_sets[k]) != len(seqs)):
                raise ValueError(
                    "qscores must contain one entry per input sequence.")
            bseqs, wgts = [], []
            for i, seq in enumerate(seqs):
                b = enc[np.frombuffer(seq.encode(),
                                      dtype=np.uint8)].astype(np.uint8)
                bseqs.append(b)
                if qscores_sets is not None:
                    q = np.asarray(qscores_sets[k][i], dtype=np.int64)
                    if len(q) != len(seq):
                        raise ValueError(
                            "Each qscore array must have the same length "
                            "as its sequence.")
                    if (q < 0).any():
                        raise ValueError(
                            "Qscores must be non-negative integers.")
                    wgts.append(q)
                else:
                    wgts.append(np.ones(len(b), dtype=np.int64))
            lockstep.append(k)
            enc_sets.append(bseqs)
            wgt_sets.append(wgts)
        if lockstep:
            from .align.fused_loop import (partition_by_length_bucket,
                                           progressive_poa_fused_batch)
            from .parallel import scheduler
            from .parallel.lockstep import progressive_poa_split_batch
            # the scheduler's lockstep implementation pick (ONE decision
            # site with the -l/serve paths): all-device vmapped groups on
            # real accelerator meshes, split host-fusion driver on hosts
            impl = scheduler.lockstep_impl(abpt)
            drv = (progressive_poa_fused_batch if impl == "device"
                   else progressive_poa_split_batch)
            order, outs = [], []
            # same-Qp-bucket sub-batches; a failed bucket falls back alone.
            # The outer device_capture makes the whole msa_batch ONE XProf
            # capture under --profile-dir (multi-set coverage): the inner
            # per-sub-batch brackets degrade to trace annotations inside it.
            with obs.trace.span("msa_batch", "fused",
                                args={"sets": len(lockstep)}), \
                    obs.device_capture("msa_batch"):
                from .pipeline import _band_cols
                backend = "jax" if abpt.device == "tpu" else abpt.device
                for sub in partition_by_length_bucket(
                        list(zip(lockstep, enc_sets, wgt_sets))):
                    # memory admission (resilience/memory.py): over-budget
                    # groups dispatch in smaller K pieces; sets too big for
                    # K=1 demote to the sequential fallback
                    pieces = (rz.memory.admission_plan(abpt, sub,
                                                       lambda e: e[1])
                              if rz.enabled() else [(list(sub), "dispatch")])
                    for piece, action in pieces:
                        order.extend(e[0] for e in piece)
                        if action == "demote":
                            obs.count("fallback.admission_demote",
                                      len(piece))
                            outs.extend([None] * len(piece))
                            continue
                        t0 = time.perf_counter()
                        # the split driver times its own align/fusion
                        # phases and per-read records; only the all-device
                        # chunk gets the blanket align_fused phase
                        import contextlib
                        ph = (obs.phase("align_fused") if impl == "device"
                              else contextlib.nullcontext())
                        try:
                            with ph:
                                outs.extend(rz.guarded_device_call(
                                    "msa_batch", backend,
                                    lambda p=piece: drv(
                                        [e[1] for e in p],
                                        [e[2] for e in p], abpt)))
                        except (rz.DispatchFailed, RuntimeError):
                            outs.extend([None] * len(piece))
                            continue
                        if impl != "device":
                            continue
                        # amortized per-read SLO records: the sub-batch
                        # wall split evenly across every read it carried
                        n_sub = sum(len(e[1]) for e in piece)
                        share = (time.perf_counter() - t0) / max(1, n_sub)
                        for e in piece:
                            for b in e[1]:
                                obs.record_read(share, len(b),
                                                _band_cols(abpt, len(b)),
                                                abpt.device, amortized=True)
            for k, res in zip(order, outs):
                if res is None:
                    continue
                pg, _is_rc = res
                ab = Abpoa()
                for seq in seq_sets[k]:
                    ab.append_read(seq=seq)
                ab.graph = pg
                results[k] = self._collect(len(seq_sets[k]), ab=ab)
                _mark_set_done()
        for k in range(len(seq_sets)):
            if results[k] is None:
                results[k] = seq_fallback(k)
                _mark_set_done()
        return results

    def msa_align(self, seqs, out_cons, out_msa, max_n_cons=1, min_freq=0.25,
                  incr_fn="", qscores=None) -> "msa_aligner":
        obs.start_run()
        exist_n = self._prepare(seqs, out_cons, out_msa, max_n_cons, min_freq,
                                incr_fn, qscores)
        tot_n = exist_n + len(seqs)
        self._add_sequences(seqs, qscores, exist_n, tot_n)
        return self

    def msa_add(self, new_seqs, qscores=None) -> "msa_aligner":
        if isinstance(new_seqs, str):
            raise TypeError(
                'Expected a list of strings. If you want to add a single sequence, '
                'pass it as a list: ["ACGT..."]')
        exist_n = self.ab.n_seq
        if exist_n == 0:
            raise Exception("Error: no existing sequences in the graph. "
                            "Please run msa() or msa_align() first.")
        if qscores is not None:
            self.abpt.use_qv = True
        tot_n = exist_n + len(new_seqs)
        self._add_sequences(new_seqs, qscores, exist_n, tot_n)
        return self

    def msa_output(self) -> msa_result:
        result = self._collect(self.ab.n_seq)
        self._last_report = obs.finalize_report()
        return result
