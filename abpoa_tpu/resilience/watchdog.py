"""Dispatch watchdog: a wall-clock deadline on device dispatches.

A wedged accelerator can hang mid-run in ways the out-of-process liveness
probe (utils/probe.py) cannot see: the probe answered at startup, then the
tunnel died under a kernel. The reference's CPU dispatch can never hang
(src/abpoa_dispatch_simd.c:56-78); the device analog is to run every
dispatch in a supervised worker thread and abandon it past a deadline —
the thread cannot be killed, but the run can degrade to a host kernel
instead of blocking forever (a hung device call blocks in C with the GIL
released, so the main thread stays live).

Host-kernel dispatches (native/numpy) never route through here: they
cannot hang by construction, and the quick tier must not pay a thread
spawn per read (the resilience overhead guard in tests/test_resilience.py
asserts exactly that).
"""
from __future__ import annotations

import atexit
import os
import threading
from typing import Callable


class DispatchTimeout(RuntimeError):
    """A supervised dispatch produced no result within its deadline."""


# abandoned workers (deadline expired, dispatch still running). A daemon
# thread executing native device code during interpreter teardown can
# crash the exiting process (observed: XLA compile -> "terminate called
# without an active exception" + SIGSEGV at exit), which would turn a
# successfully-degraded run into rc=-11. At exit, grant stragglers a
# bounded grace to finish; a truly wedged thread is abandoned for real
# after the grace — by then all output and the exit status are flushed.
#
# Abandonment is an unbounded leak (each wedged thread pins its stack and
# whatever device handle it blocks on), so it is bounded two ways:
# `abpoa_watchdog_abandoned_threads` gauges the live leak for the fleet
# exporter, a stderr warning fires past ABPOA_TPU_WATCHDOG_ABANDON_MAX —
# and dispatches running inside a process-pool worker never abandon at
# all: the pool supervisor SIGKILLs the whole worker process on deadline
# expiry (parallel/pool.py), which reclaims thread, stack and device
# handle in one stroke.
_ABANDONED: list = []
_EXIT_GRACE_S = float(os.environ.get("ABPOA_TPU_WATCHDOG_EXIT_GRACE_S", "15"))
_WARNED_LEAK = False


def abandon_max() -> int:
    """Abandoned-thread count past which the leak warning fires."""
    return int(os.environ.get("ABPOA_TPU_WATCHDOG_ABANDON_MAX", "8"))


def abandoned_count() -> int:
    """Live abandoned watchdog threads (finished stragglers drop out)."""
    return sum(1 for t in _ABANDONED if t.is_alive())


def in_pool_worker() -> bool:
    """Is this process a pool worker (parallel/pool_worker.py)? Set by the
    supervisor in the worker's environment; the hard-kill deadline it
    enforces from outside replaces thread abandonment here."""
    return os.environ.get("ABPOA_TPU_POOL_WORKER") == "1"


def _publish_abandoned(reg) -> None:
    """Render-time republish: the gauge must track the LIVE count back
    down when stragglers finish, not freeze at the high-water mark the
    last abandonment wrote."""
    reg.gauge(
        "abpoa_watchdog_abandoned_threads",
        "Live abandoned watchdog worker threads (deadline expired, "
        "dispatch still running)").set(abandoned_count())


def _note_abandoned(t: threading.Thread) -> None:
    global _WARNED_LEAK
    _ABANDONED.append(t)
    n = abandoned_count()
    from ..obs import metrics
    if metrics.enabled():
        # _ABANDONED is process-lifetime state, so the collector is
        # global (survives registry resets); it re-derives the gauge at
        # every exposition render
        metrics.register_global_collector(_publish_abandoned)
        metrics.registry().gauge(
            "abpoa_watchdog_abandoned_threads",
            "Live abandoned watchdog worker threads (deadline expired, "
            "dispatch still running)").set(n)
    if n > abandon_max() and not _WARNED_LEAK:
        _WARNED_LEAK = True
        import sys
        from ..obs import count
        count("watchdog.abandon_warnings")
        print(f"Warning: {n} abandoned watchdog threads exceed "
              f"ABPOA_TPU_WATCHDOG_ABANDON_MAX={abandon_max()} — the "
              "process is leaking wedged dispatch threads; route batch "
              "work through the process pool (--workers N), whose "
              "deadline is a hard worker SIGKILL instead of an "
              "abandonment.", file=sys.stderr)


def _drain_abandoned() -> None:
    import time
    deadline = time.monotonic() + _EXIT_GRACE_S
    for t in _ABANDONED:
        t.join(max(0.0, deadline - time.monotonic()))


atexit.register(_drain_abandoned)


def deadline_seconds() -> float:
    """Per-dispatch deadline. Generous by default: a cold first-sight XLA
    compile of a 10 kb-workload fused chunk is minutes (PERF.md round 8),
    and a deadline must never fire on honest work. 0 disables supervision
    (direct call)."""
    return float(os.environ.get("ABPOA_TPU_WATCHDOG_S", "900"))


def supervision_needed(backend: str) -> bool:
    """Should this dispatch run in the supervised worker?

    Only device backends can hang, and only through a wedged accelerator
    tunnel — the CPU jax backend cannot (the same reasoning that scopes
    the liveness probe, utils/probe.py), and thread-supervised XLA:CPU
    compiles measure ~2x slower than main-thread ones (PERF.md round 9).
    So supervision arms for real accelerator platforms, when a fault
    injector is armed (tests/chaos need the deadline on CPU), or under
    ABPOA_TPU_WATCHDOG_FORCE=1."""
    if backend not in ("jax", "tpu", "pallas"):
        return False
    if os.environ.get("ABPOA_TPU_WATCHDOG_FORCE") == "1":
        return True
    if in_pool_worker():
        # pool-routed dispatches take the hard-kill path: the supervisor
        # SIGKILLs this whole process past the job deadline, so a thread
        # worker here would only add the abandonment leak the pool exists
        # to remove (and the ~2x off-main-thread XLA:CPU compile tax)
        return False
    from .inject import any_armed
    if any_armed():
        return True
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        # not imported yet: the dispatch itself would initialize jax;
        # supervise, since we cannot rule out an accelerator platform
        return True
    try:
        return jax.default_backend() != "cpu"
    except RuntimeError:
        return True


def call_with_deadline(fn: Callable, deadline_s: float = None,
                       label: str = "dispatch"):
    """Run fn() in a daemon worker; raise DispatchTimeout past the
    deadline. Exceptions from fn propagate unchanged. On timeout the
    worker is abandoned (counted), never joined — a genuinely hung device
    call cannot be interrupted, only routed around."""
    if deadline_s is None:
        deadline_s = deadline_seconds()
    if deadline_s <= 0:
        return fn()
    box: dict = {}
    done = threading.Event()

    def worker():
        try:
            box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised in the caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=worker, daemon=True,
                         name=f"abpoa-watchdog:{label}")
    t.start()
    if not done.wait(deadline_s):
        from ..obs import count, instant
        count("watchdog.timeouts")
        count("watchdog.abandoned_threads")
        # the expiry lands in the request's trace (the instant inherits
        # the thread-local request context), so a 504's span tree shows
        # WHERE the deadline fired, not just that it did
        instant("watchdog_timeout", "fault",
                args={"label": label, "deadline_s": deadline_s})
        _note_abandoned(t)
        raise DispatchTimeout(
            f"{label}: no result within {deadline_s:.1f}s watchdog deadline "
            "(wedged device dispatch?)")
    if "error" in box:
        raise box["error"]
    return box["result"]
