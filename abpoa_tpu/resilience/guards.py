"""Output sanity guards: cheap invariant checks on dispatch results.

A kernel that silently produces garbage (mis-DMA'd planes, a bad compile,
bit-flipped HBM) is worse than one that crashes: the run "succeeds" with a
wrong consensus. These guards check invariants every correct backend
satisfies by construction, on host-side data the driver already holds —
no device syncs, O(|cigar|) / O(nodes) host arithmetic:

- scores are finite int32 (the kernels' own plane width);
- the CIGAR consumes the query exactly once (global mode) and never more
  bases/nodes than exist;
- graph and consensus bases stay inside the alphabet.

A violation raises/returns so the dispatch layer can record a `faults`
entry and re-run the work once on a host kernel (`align/dispatch.py`,
`pipeline._run_fused_device`) — the "one-shot native re-run" of the
resilient-dispatch contract.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .. import constants as C


class GarbageOutput(RuntimeError):
    """A dispatch result failed an output sanity guard."""


_INT32_BOUND = 1 << 31


def align_result_violation(res, qlen: int, node_n: int,
                           abpt) -> Optional[str]:
    """Invariant check for one AlignResult; None when sane, else a short
    reason string. Never raises. Vectorized: a Python per-op walk over a
    2 kb read's cigar measured ~10% of the warm sim2k wall — the numpy
    pass is three masked reductions."""
    s = res.best_score
    try:
        s = int(s)
    except (TypeError, ValueError):
        return f"non-integer best_score {s!r}"
    if not -_INT32_BOUND < s < _INT32_BOUND:
        return f"best_score {s} outside int32 plane range"
    if res.cigar:
        # prefer the backend-attached ndarray (op totals are order-
        # independent, so a reversed list view is equally valid)
        cig = getattr(res, "cigar_arr", None)
        if cig is None:
            try:
                cig = np.asarray(res.cigar, dtype=np.uint64)
            except (OverflowError, ValueError, TypeError) as e:
                # negative / out-of-range entries are themselves garbage
                # (the bit-flip threat model): a violation, not a crash
                return f"cigar not packable as uint64: {e}"
        ops = (cig & np.uint64(0xF)).astype(np.int64)
        if int(ops.max()) > C.CHARD_CLIP:
            return f"unknown cigar op {int(ops.max())}"
        runs = ((cig >> np.uint64(4)) & np.uint64(0x3FFFFFFF)).astype(
            np.int64)
        is_base = (ops == C.CMATCH) | (ops == C.CDIFF)
        is_qrun = ((ops == C.CINS) | (ops == C.CSOFT_CLIP)
                   | (ops == C.CHARD_CLIP))
        consumed_q = int(is_base.sum() + runs[is_qrun].sum())
        consumed_n = int(is_base.sum() + runs[ops == C.CDEL].sum())
        if consumed_q > qlen:
            return f"cigar consumes {consumed_q} query bases of {qlen}"
        if consumed_n > node_n:
            return f"cigar consumes {consumed_n} graph nodes of {node_n}"
        if abpt.align_mode == C.GLOBAL_MODE and consumed_q != qlen:
            return (f"global-mode cigar consumes {consumed_q} of {qlen} "
                    "query bases")
    return None


def check_graph_bases(base_arr: np.ndarray, m: int) -> None:
    """Alphabet guard over a downloaded fused-loop graph (host array, one
    vectorized min/max). Raises GarbageOutput on violation."""
    if base_arr.size == 0:
        return
    lo, hi = int(base_arr.min()), int(base_arr.max())
    if lo < 0 or hi >= max(m, 5):
        raise GarbageOutput(
            f"graph base range [{lo}, {hi}] outside alphabet of {m}")


def consensus_violation(abc, m: int) -> Optional[str]:
    """Alphabet/shape guard over a ConsensusResult; None when sane."""
    for i, row in enumerate(abc.cons_base):
        arr = np.asarray(row)
        if arr.size and (int(arr.min()) < 0 or int(arr.max()) >= max(m, 5)):
            return (f"consensus {i} base range [{int(arr.min())}, "
                    f"{int(arr.max())}] outside alphabet of {m}")
    return None
