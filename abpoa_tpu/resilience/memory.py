"""Memory admission control: estimate before dispatching, never OOM blind.

The fused loop's device footprint is a pure function of its compile-ladder
rung — the bucketed capacities (N nodes, E edge slots, A aligned slots, W
band window, Qp padded query, R padded reads, K lockstep sets) plus plane
width. That makes OOM *predictable*: estimate the bytes a dispatch will
ask for BEFORE dispatching, and when it exceeds the budget, proactively
chunk the lockstep group into smaller K (linear in K) or demote the set to
the host kernel — instead of letting the allocator discover it mid-run.

The model is deliberately simple (the same order-of-magnitude arithmetic
`lockstep_group_size()`'s docstring did by hand): per-set DP planes
(n_planes x N x W cells), graph tables (N x E edges in/out, N x A aligned),
and the padded read batch. It only needs to be right within ~2x — the
budget carries the safety margin.

Budget: ``ABPOA_TPU_MEM_BUDGET_MB`` (0 disables admission). Without the
env var, admission is active only when the jax default backend is a real
accelerator (fixed HBM); host RAM is elastic and the host backends
allocate nothing on-device.
"""
from __future__ import annotations

import os
import sys
from typing import List, Optional, Tuple

from .. import constants as C

# DP planes per gap mode: H (+E/F per affine level). Conservative by one —
# the scan keeps score and direction state per plane.
_N_PLANES = {C.LINEAR_GAP: 2, C.AFFINE_GAP: 4, C.CONVEX_GAP: 6}

_DEFAULT_ACCEL_BUDGET_MB = 14_000   # 16 GB HBM minus runtime slack


def budget_bytes() -> Optional[int]:
    """None = admission disabled."""
    env = os.environ.get("ABPOA_TPU_MEM_BUDGET_MB")
    if env is not None:
        mb = float(env)
        return int(mb * 1e6) if mb > 0 else None
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        if jax.default_backend() != "cpu":
            return _DEFAULT_ACCEL_BUDGET_MB * 10 ** 6
    except RuntimeError:
        pass
    return None


def estimate_bytes(caps: dict) -> int:
    """Device bytes one fused/lockstep dispatch will hold, from its
    compile-ladder rung (fused_loop.plan_dispatch_footprint)."""
    N, E, A = caps["N"], caps["E"], caps["A"]
    W, Qp, R = caps["W"], caps["Qp"], caps["reads"]
    K = caps.get("K", 1)
    m = caps.get("m", 5)
    cell = 2 if caps.get("plane16") else 4
    planes = _N_PLANES.get(caps.get("gap_mode", C.CONVEX_GAP), 6)
    per_set = (planes * N * min(W, Qp + 1) * cell   # DP planes
               + N * E * 4 * 4                      # in/out ids + weights
               + N * A * 4                          # aligned groups
               + N * 12 * 4                         # per-node scalars/order
               + R * Qp * (8 + 4 * m))              # reads, weights, qp table
    return K * per_set


def per_set_bytes(caps: dict) -> int:
    return estimate_bytes(dict(caps, K=1))


def admit(caps: dict) -> Tuple[str, int, Optional[int]]:
    """-> (decision, estimated_bytes, budget_bytes).

    "ok"     fits (or admission disabled)
    "chunk"  the K-set group exceeds the budget but single sets fit:
             dispatch in smaller sub-groups (`max_sets_within`)
    "demote" even one set exceeds the budget: run it on the host kernel
    """
    from ..obs import count, metrics
    budget = budget_bytes()
    est = estimate_bytes(caps)
    if metrics.enabled():
        g = metrics.registry().gauge(
            "abpoa_admission_last_estimate_bytes",
            "Device-byte estimate of the most recent admission decision")
        g.set(est)
        if budget is not None:
            metrics.registry().gauge(
                "abpoa_admission_budget_bytes",
                "Device-memory admission budget").set(budget)
    if budget is None or est <= budget:
        return "ok", est, budget
    count("admission.over_budget")
    if caps.get("K", 1) > 1 and per_set_bytes(caps) <= budget:
        count("admission.chunk")
        return "chunk", est, budget
    count("admission.demote")
    return "demote", est, budget


def max_sets_within(caps: dict) -> int:
    """Largest lockstep K whose estimate fits the budget (>= 1).

    Accounts for the set-axis rung padding: the lockstep dispatch snaps K
    up to `k_rung` (pow2) and the padding slots allocate full plane
    stacks even though they are born finished — so a piece is admitted
    only if its PADDED K fits, or the "admitted" chunk would OOM exactly
    like the unchunked group."""
    budget = budget_bytes()
    k_req = max(1, caps.get("K", 1))
    if budget is None:
        return k_req
    from ..compile.ladder import k_rung
    per_set = max(1, per_set_bytes(caps))
    best = 1
    for k in range(1, k_req + 1):
        if k_rung(k) * per_set <= budget:
            best = k
    return best


def admission_plan(abpt, entries, seqs_of) -> List[Tuple[list, str]]:
    """Partition a same-bucket lockstep sub-batch into admissible pieces.

    entries: planner tuples; seqs_of(entry) -> that entry's encoded reads.
    Returns [(piece, action)] in input order, action "dispatch" (run on
    device) or "demote" (route to the host path — even a K=1 dispatch of
    these sets would exceed the budget; chunking cannot help because
    planes scale with the set's own Qp/N, not with K). The common case —
    everything fits — costs one footprint estimate and returns one
    dispatchable piece."""
    from ..align.fused_loop import plan_dispatch_footprint
    sets = [seqs_of(e) for e in entries]
    caps = plan_dispatch_footprint(abpt, sets)
    decision, est, budget = admit(caps)
    if decision == "ok":
        return [(list(entries), "dispatch")]
    if decision == "demote":
        from ..obs import report
        report().record_fault(
            "admission", backend=getattr(abpt, "device", None),
            detail=f"estimated {est} B > budget {budget} B per set",
            action="demote")
        return [(list(entries), "demote")]
    k_fit = max_sets_within(caps)
    return [(list(entries[i:i + k_fit]), "dispatch")
            for i in range(0, len(entries), k_fit)]
