"""Per-backend circuit breaker: the degradation ladder's memory.

Generalizes the probe-timeout special case (utils/probe.py falls back once,
at resolution time) into a run-scoped policy: every classified dispatch
failure is recorded against the backend that failed; past a threshold the
breaker OPENS and the backend is demoted — pallas -> jax -> native ->
numpy — instead of re-failing (and re-paying retries, watchdog deadlines,
or re-compiles) on every subsequent read.

Recovery (the long-lived-process story `abpoa-tpu serve` depends on): an
open breaker is not open forever. After ``ABPOA_TPU_BREAKER_COOLDOWN_S``
(default 300 s) the breaker goes HALF-OPEN: exactly one dispatch is allowed
through as a probe (`acquire` hands out the single permit; every other
caller keeps short-circuiting to the demoted backend while the probe is in
flight). A successful probe RECLOSES the breaker — the backend is
reclaimed, its failure count zeroed — while a failed probe reopens it and
restarts the cooldown. Batch runs never notice (a run is usually shorter
than the cooldown); a serve process that lost pallas/jax to a transient
tunnel fault gets it back without a restart.

State transitions are never silent: opens/recloses warn on stderr once,
tick `breaker.open.<backend>` / `breaker.reclose.<backend>` /
`breaker.half_open.<backend>`, and land in the run report's `degraded`
block (schema v3; a reclosed backend leaves the block — it reports
breakers open NOW). `obs.start_run()` resets the breaker wholesale.
All transitions hold one lock: server threads race dispatches against
each other and against the cooldown clock.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, Optional

# the degradation ladder: who serves when a backend is demoted. "numpy"
# (the host oracle) is the floor and is never demoted — it is the
# correctness reference everything else is judged against.
DEMOTION = {"pallas": "jax", "tpu": "jax", "jax": "native",
            "native": "numpy"}


def _threshold() -> int:
    return max(1, int(os.environ.get("ABPOA_TPU_BREAKER_THRESHOLD", "3")))


def cooldown_s() -> float:
    """Seconds an open breaker waits before allowing the half-open probe.
    <= 0 means a probe is allowed immediately (tests); the 300 s default
    is sized so a batch run never probes but a serve process retries a
    demoted accelerator a few times an hour."""
    return float(os.environ.get("ABPOA_TPU_BREAKER_COOLDOWN_S", "300"))


class CircuitBreaker:
    def __init__(self) -> None:
        self.failures: Dict[str, int] = {}
        # backend -> {"to", "kind", "failures", "opened_t", "probing"}
        self.open: Dict[str, dict] = {}
        self._lock = threading.RLock()

    def reset(self) -> None:
        # fleet registry: an open breaker from the previous run reads as
        # closed again the moment the next run starts (run-scoped state)
        from ..obs import metrics
        with self._lock:
            for backend in self.open:
                metrics.set_breaker_state(backend, False)
            self.failures.clear()
            self.open.clear()

    def _demoted_now_locked(self, backend: str) -> bool:
        """Is this backend demoted RIGHT NOW (cooldown-aware)? False once
        the cooldown elapsed with no probe in flight — the next `acquire`
        will claim the probe permit. Callers hold self._lock."""
        st = self.open.get(backend)
        if st is None:
            return False
        if st["probing"]:
            return True  # someone else is probing; stay demoted
        return (time.monotonic() - st["opened_t"]) < cooldown_s()

    def is_open(self, backend: str) -> bool:
        """Pure state query (no transition)."""
        with self._lock:
            return self._demoted_now_locked(backend)

    def acquire(self, backend: str) -> Optional[str]:
        """Claim the right to dispatch on `backend`.

        "closed"  breaker closed: dispatch normally
        "probe"   breaker half-open and THIS caller holds the single probe
                  permit: dispatch, then report success/failure
        None      breaker open (or a probe is already in flight): short-
                  circuit to the demoted backend
        """
        with self._lock:
            st = self.open.get(backend)
            if st is None:
                return "closed"
            if st["probing"]:
                return None
            if (time.monotonic() - st["opened_t"]) >= cooldown_s():
                st["probing"] = True
                from ..obs import count
                count(f"breaker.half_open.{backend}")
                return "probe"
            return None

    def effective(self, backend: str) -> str:
        """Walk the demotion ladder past every CURRENTLY-demoted breaker.
        Cooldown-aware on purpose: once a backend's cooldown elapses,
        resolution (align/dispatch._resolve) names it again, so the next
        guarded dispatch reaches `acquire()` and can claim the half-open
        probe — otherwise the per-read path would stay demoted forever
        and only the fused route could ever recover a backend."""
        with self._lock:
            seen = set()
            while self._demoted_now_locked(backend) and backend not in seen:
                seen.add(backend)
                backend = DEMOTION.get(backend, "numpy")
            return backend

    def abort_probe(self, backend: str) -> None:
        """Release a claimed probe permit without a verdict (the probe
        died on an unclassified exception — a real bug that will
        propagate). ONLY the permit holder may call this (guarded by the
        `permit == "probe"` check at the call site): a stale closed-era
        dispatch must not reset another thread's probe. The breaker stays
        open and the cooldown restarts, so the stuck-probing state can
        never outlive its dispatch."""
        with self._lock:
            st = self.open.get(backend)
            if st is not None and st["probing"]:
                st["probing"] = False
                st["opened_t"] = time.monotonic()

    def record_success(self, backend: str, probe: bool = False) -> None:
        """A dispatch on `backend` completed healthy. With `probe=True`
        (the caller holds the half-open permit) a success RECLOSES the
        breaker; without it this is a no-op — a dispatch that started
        before the breaker opened proves nothing about recovery, and must
        not reclose on behalf of someone else's in-flight probe."""
        if not probe:
            return
        with self._lock:
            st = self.open.get(backend)
            if st is None or not st["probing"]:
                return
            del self.open[backend]
            self.failures[backend] = 0
        from ..obs import count, report
        count(f"breaker.reclose.{backend}")
        report().mark_reclosed(backend)
        from ..obs import metrics
        metrics.set_breaker_state(backend, False)
        print(f"Warning: backend '{backend}' circuit breaker reclosed "
              "(half-open probe succeeded); resuming normal dispatch.",
              file=sys.stderr)

    def record_failure(self, backend: str, kind: str,
                       probe: bool = False) -> None:
        from ..obs import count, report
        with self._lock:
            st = self.open.get(backend)
            if st is not None:
                if probe:
                    # the half-open probe failed: reopen, restart the
                    # cooldown, keep the demotion in force
                    st["probing"] = False
                    st["opened_t"] = time.monotonic()
                    st["kind"] = kind
                    st["failures"] += 1
                    count(f"breaker.probe_fail.{backend}")
                    report().mark_degraded(backend, st["to"], kind,
                                           st["failures"])
                else:
                    # a stale dispatch that started before the breaker
                    # opened (or a direct guard-path report): count it,
                    # but never touch someone else's probe state
                    count(f"breaker.failures.{backend}")
                return
            n = self.failures[backend] = self.failures.get(backend, 0) + 1
            count(f"breaker.failures.{backend}")
            if n < _threshold():
                return
            to = self.effective(DEMOTION.get(backend, "numpy"))
            self.open[backend] = {"to": to, "kind": kind, "failures": n,
                                  "opened_t": time.monotonic(),
                                  "probing": False}
        count(f"breaker.open.{backend}")
        report().mark_degraded(backend, to, kind, n)
        from ..obs import metrics
        metrics.set_breaker_state(backend, True)
        print(f"Warning: backend '{backend}' circuit breaker opened "
              f"after {n} dispatch failures (last: {kind}); using "
              f"'{to}' until the {cooldown_s():.0f}s cooldown allows a "
              "probe.", file=sys.stderr)


_BREAKER = CircuitBreaker()


def breaker() -> CircuitBreaker:
    return _BREAKER
