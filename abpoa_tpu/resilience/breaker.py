"""Per-backend circuit breaker: the degradation ladder's memory.

Generalizes the probe-timeout special case (utils/probe.py falls back once,
at resolution time) into a run-scoped policy: every classified dispatch
failure is recorded against the backend that failed; past a threshold the
breaker OPENS and the backend is demoted for the remainder of the run —
pallas -> jax -> native -> numpy — instead of re-failing (and re-paying
retries, watchdog deadlines, or re-compiles) on every subsequent read.

Openings are never silent: each one warns on stderr once, increments
`breaker.open.<backend>`, and lands in the run report's `degraded` block
(schema v3). `obs.start_run()` resets the breaker, so demotion is per-run
state, exactly like the probe verdict's telemetry labels.
"""
from __future__ import annotations

import os
import sys
from typing import Dict

# the degradation ladder: who serves when a backend is demoted. "numpy"
# (the host oracle) is the floor and is never demoted — it is the
# correctness reference everything else is judged against.
DEMOTION = {"pallas": "jax", "tpu": "jax", "jax": "native",
            "native": "numpy"}


def _threshold() -> int:
    return max(1, int(os.environ.get("ABPOA_TPU_BREAKER_THRESHOLD", "3")))


class CircuitBreaker:
    def __init__(self) -> None:
        self.failures: Dict[str, int] = {}
        self.open: Dict[str, dict] = {}   # backend -> {"to", "kind", "failures"}

    def reset(self) -> None:
        # fleet registry: an open breaker from the previous run reads as
        # closed again the moment the next run starts (run-scoped state)
        from ..obs import metrics
        for backend in self.open:
            metrics.set_breaker_state(backend, False)
        self.failures.clear()
        self.open.clear()

    def is_open(self, backend: str) -> bool:
        return backend in self.open

    def effective(self, backend: str) -> str:
        """Walk the demotion ladder past every open breaker."""
        seen = set()
        while backend in self.open and backend not in seen:
            seen.add(backend)
            backend = DEMOTION.get(backend, "numpy")
        return backend

    def record_failure(self, backend: str, kind: str) -> None:
        from ..obs import count, report
        n = self.failures[backend] = self.failures.get(backend, 0) + 1
        count(f"breaker.failures.{backend}")
        if n >= _threshold() and backend not in self.open:
            to = self.effective(DEMOTION.get(backend, "numpy"))
            self.open[backend] = {"to": to, "kind": kind, "failures": n}
            count(f"breaker.open.{backend}")
            report().mark_degraded(backend, to, kind, n)
            from ..obs import metrics
            metrics.set_breaker_state(backend, True)
            print(f"Warning: backend '{backend}' circuit breaker opened "
                  f"after {n} dispatch failures (last: {kind}); using "
                  f"'{to}' for the remainder of the run.", file=sys.stderr)


_BREAKER = CircuitBreaker()


def breaker() -> CircuitBreaker:
    return _BREAKER
