"""Per-set quarantine: one poisoned read set must never drop the batch.

The `-l` file-list mode and `msa_batch` process independent read sets; the
reference aborts the whole process on the first bad file (src/abpoa.c:148-
168 has no error path). Here a set that fails validation — malformed
record, empty sequence, truncated FASTQ, unreadable/corrupt file, a size
past the admission cap — is quarantined: it produces a structured per-set
error (a `faults` record with the set index plus one stderr line), the
counters tick, and every healthy set completes normally. A traceback or a
partial silent result is a bug; tests/test_resilience.py fuzzes exactly
that contract.
"""
from __future__ import annotations

import os
import sys
from typing import Optional


class PoisonedSetError(ValueError):
    """A read set rejected by input validation (quarantinable)."""


# exception types the per-set boundary converts into quarantine instead of
# propagating: malformed input and I/O decay. Anything else (TypeError,
# KeyError, ...) is a real bug and must surface.
QUARANTINE_EXCEPTIONS = (PoisonedSetError, OSError, EOFError,
                         UnicodeDecodeError)


def max_reads_per_set() -> int:
    """Admission cap on reads per set (matches the per-read telemetry
    stream's READS_CAP by default): an input claiming millions of reads is
    quarantined up front instead of exhausting host memory mid-ingest."""
    return int(os.environ.get("ABPOA_TPU_MAX_READS", "100000"))


def validate_records(records, abpt=None, label: str = "") -> None:
    """Structural validation of parsed FASTA/FASTQ records; raises
    PoisonedSetError with a reason a user can act on. O(records) host
    checks on lengths only — never re-scans sequence bytes."""
    from .inject import check_poison_set
    check_poison_set()
    if not records:
        raise PoisonedSetError("no sequence records parsed "
                               "(empty or malformed file)")
    cap = max_reads_per_set()
    if len(records) > cap:
        raise PoisonedSetError(
            f"{len(records)} reads exceeds the per-set cap of {cap} "
            "(ABPOA_TPU_MAX_READS)")
    for i, rec in enumerate(records):
        if not rec.seq:
            raise PoisonedSetError(
                f"record {i} ({rec.name or 'unnamed'}): empty sequence")
        if rec.qual is not None and len(rec.qual) != len(rec.seq):
            raise PoisonedSetError(
                f"record {i} ({rec.name or 'unnamed'}): FASTQ quality "
                f"length {len(rec.qual)} != sequence length {len(rec.seq)} "
                "(truncated record?)")


def quarantine_set(index: int, label: str, exc: Exception) -> None:
    """Record one quarantined set: a `faults` entry keyed by set index, a
    counter, and a single structured stderr line."""
    from ..obs import count, report
    count("quarantine.sets")
    reason = f"{type(exc).__name__}: {exc}"
    report().record_fault("poisoned_set", set_index=index,
                          detail=reason[:300], action="quarantined")
    print(f"[abpoa-tpu] set {index} ({label}) quarantined: {reason}",
          file=sys.stderr)
