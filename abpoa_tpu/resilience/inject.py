"""Fault-injection harness: every failure mode the dispatch layer defends
against, armable deterministically on any host (no TPU, no broken hardware
needed).

Arm via the environment (read once per process, at first use):

    ABPOA_TPU_INJECT=compile_fail             # fire on every device dispatch
    ABPOA_TPU_INJECT=oom:2,hang               # oom twice, then hang forever
    ABPOA_TPU_INJECT=garbage:1                # corrupt one dispatch result

or programmatically with `configure("kind[:count],...")` (tests). A bare
kind fires on every matching dispatch; `kind:N` fires N times and then
disarms itself. Each firing is counted (`inject.<kind>` in the run report)
so a chaos run can assert the injector actually fired.

Kinds and where they fire:

- ``compile_fail``  device dispatch (jax/pallas): raises a compile-shaped
                    RuntimeError before the kernel runs
- ``oom``           device dispatch: raises RESOURCE_EXHAUSTED-shaped error
- ``hang``          device dispatch: sleeps ABPOA_TPU_INJECT_HANG_S (default
                    30 s) inside the watchdog-supervised worker, so the
                    dispatch deadline trips exactly like a wedged kernel
- ``garbage``       after a dispatch: corrupts the result (absurd score +
                    truncated CIGAR, or an out-of-alphabet graph base) so
                    the output guards must catch it
- ``native_crash``  native host-kernel dispatch: raises the same error shape
                    as a non-zero ``apg_align`` return
- ``poison_set``    set ingestion: raises PoisonedSetError, exercising the
                    per-set quarantine path
- ``worker_kill``   process pool (parallel/pool.py): the worker process a
                    job lands on SIGKILLs itself at job start — the
                    supervisor must contain the death, requeue the job
                    exactly once, and keep the batch alive
- ``worker_sigsegv`` process pool: the worker raises SIGSEGV against
                    itself (what a native-kernel crash looks like to the
                    supervisor: death by signal, no Python cleanup)

The two ``worker_*`` kinds fire from the pool SUPERVISOR, not from
`pre_dispatch`: the parent consumes the shot budget centrally and tags the
doomed job's dispatch frame, so ``worker_sigsegv:2`` means two firings
across the whole pool run (bound to one job and its retry) — the same
count semantics a single process would give — instead of every spawned
worker re-arming its own budget from the environment.

For the same reason the pool brokers ALL count-limited kinds across its
worker processes: `lease()` hands the remaining budget of a kind to one
in-flight job at a time, the worker arms exactly that lease
(`configure()`), and `refund()` returns whatever the job did not consume.
Unlimited kinds are simply forwarded — every worker firing them matches
single-process behavior already.

Everything here is inert when disarmed: the hot-path check is one global
boolean (`_ANY`).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional


class InjectedFault(RuntimeError):
    """Base for injected failures; `kind` routes classification."""
    kind = "injected"


class InjectedCompileFailure(InjectedFault):
    kind = "compile_fail"


class InjectedDeviceOOM(InjectedFault):
    kind = "oom"


class InjectedNativeCrash(InjectedFault):
    kind = "native_crash"


class InjectedHang(InjectedFault):
    kind = "hang"


KINDS = ("compile_fail", "oom", "hang", "garbage", "native_crash",
         "poison_set", "worker_kill", "worker_sigsegv")

# fired by the pool supervisor via lease(), never by pre_dispatch
WORKER_KINDS = ("worker_kill", "worker_sigsegv")

# kind -> remaining shots (-1 = unlimited); absent = disarmed
_SPEC: Dict[str, int] = {}
_ANY = False
_CONFIGURED = False
# serializes every _SPEC read-modify-write: fire() runs on serve handler
# threads while the pool supervisor lease()s/refund()s the same budget —
# without one lock a ':1' spec can fire twice (or lose its shot)
_LOCK = threading.Lock()


def configure(spec: Optional[str] = None) -> None:
    """Parse an injection spec ("kind[:count],..."); None reads
    ABPOA_TPU_INJECT. Unknown kinds raise (a typo'd chaos run must not
    silently test nothing)."""
    global _ANY, _CONFIGURED
    if spec is None:
        spec = os.environ.get("ABPOA_TPU_INJECT", "")
    parsed = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        kind, _, cnt = part.partition(":")
        if kind not in KINDS:
            raise ValueError(f"unknown fault-injection kind: {kind!r} "
                             f"(known: {', '.join(KINDS)})")
        parsed[kind] = int(cnt) if cnt else -1
    with _LOCK:
        _SPEC.clear()
        _SPEC.update(parsed)
        _ANY = bool(_SPEC)
        _CONFIGURED = True


def reset() -> None:
    """Disarm every injector (tests)."""
    configure("")


def _ensure_configured() -> None:
    if not _CONFIGURED:
        configure(None)


def armed(kind: str) -> bool:
    _ensure_configured()
    return _SPEC.get(kind, 0) != 0


def any_armed() -> bool:
    _ensure_configured()
    return _ANY


def fire(kind: str) -> bool:
    """Consume one shot of `kind` if armed. Counted in the run report so
    chaos tests can assert the injector really fired. The hot-path cost
    when disarmed is the two boolean checks."""
    if not _CONFIGURED:
        configure(None)
    if not _ANY:
        return False
    with _LOCK:
        left = _SPEC.get(kind, 0)
        if left == 0:
            return False
        if left > 0:
            _SPEC[kind] = left - 1
    from ..obs import count
    count(f"inject.{kind}")
    return True


def snapshot() -> Dict[str, int]:
    """Effective spec as {kind: remaining} (-1 = unlimited). The pool
    supervisor reads this — programmatic `configure()` arms never reach
    os.environ, so forwarding the env var alone would miss them."""
    _ensure_configured()
    with _LOCK:
        return dict(_SPEC)


def lease(kind: str, n: int = -1) -> int:
    """Consume up to `n` shots of a count-limited `kind` (-1 = all that
    remain) WITHOUT firing: the pool supervisor leases the budget to one
    job, whose worker process does the actual (counted) firing. Returns
    the number leased; 0 when disarmed. An UNLIMITED budget grants `n`
    without decrementing (for `n` >= 0 — the worker-kill kinds lease one
    shot per dispatch, so a bare ``worker_kill`` kills every job's
    worker rather than silently doing nothing); a refund against an
    unlimited budget is a no-op."""
    _ensure_configured()
    with _LOCK:
        left = _SPEC.get(kind, 0)
        if left == -1:
            return max(0, n)
        if left <= 0:
            return 0
        take = left if n < 0 else min(left, n)
        _SPEC[kind] = left - take
        return take


def refund(kind: str, n: int) -> None:
    """Return unconsumed leased shots to the central budget (the job
    completed having fired fewer than it held)."""
    if n <= 0:
        return
    _ensure_configured()
    global _ANY
    with _LOCK:
        left = _SPEC.get(kind, 0)
        if left >= 0:
            _SPEC[kind] = left + n
            _ANY = True


def hang_seconds() -> float:
    return float(os.environ.get("ABPOA_TPU_INJECT_HANG_S", "30"))


def pre_dispatch(backend: str) -> None:
    """Injection point at the top of a dispatch attempt. Runs INSIDE the
    watchdog-supervised worker for device backends, so an injected hang
    trips the deadline exactly like a real wedged kernel."""
    if not _CONFIGURED:
        configure(None)
    if not _ANY:
        return
    if backend in ("jax", "tpu", "pallas"):
        if fire("compile_fail"):
            raise InjectedCompileFailure(
                f"injected XLA compilation failure ({backend})")
        if fire("oom"):
            raise InjectedDeviceOOM(
                "RESOURCE_EXHAUSTED: injected device OOM while allocating "
                "DP planes")
        if fire("hang"):
            # sleep past the watchdog deadline (the main thread times out
            # and degrades), then raise instead of falling through: the
            # abandoned worker must not burn CPU on a dispatch whose
            # result is already discarded
            time.sleep(hang_seconds())
            raise InjectedHang(
                f"injected dispatch hang ({hang_seconds():.1f}s)")
    elif backend == "native":
        if fire("native_crash"):
            raise InjectedNativeCrash(
                "native DP kernel failed (rc=-11, injected crash)")


def corrupt_result(res):
    """Garbage injector for per-read dispatch results: an absurd score and
    a truncated CIGAR — both invariants the output guards must catch."""
    if fire("garbage"):
        res.best_score = 1 << 40
        res.cigar = list(res.cigar)[: max(0, len(res.cigar) // 2)]
        res.cigar_arr = None  # the guards must see the corrupted list
    return res


def corrupt_graph_base(base_arr):
    """Garbage injector for the fused path: poison one downloaded graph
    base out of the alphabet (what a mis-DMA'd kernel output looks like).
    Mutates the host array in place; returns True when it fired."""
    if fire("garbage") and base_arr.size > 2:
        base_arr[2] = 99
        return True
    return False


def check_poison_set() -> None:
    """Set-ingestion injection point: raise a poisoned-set error so the
    quarantine path runs without needing a malformed file on disk."""
    if fire("poison_set"):
        from .quarantine import PoisonedSetError
        raise PoisonedSetError("injected poisoned read set")
