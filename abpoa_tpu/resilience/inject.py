"""Fault-injection harness: every failure mode the dispatch layer defends
against, armable deterministically on any host (no TPU, no broken hardware
needed).

Arm via the environment (read once per process, at first use):

    ABPOA_TPU_INJECT=compile_fail             # fire on every device dispatch
    ABPOA_TPU_INJECT=oom:2,hang               # oom twice, then hang forever
    ABPOA_TPU_INJECT=garbage:1                # corrupt one dispatch result

or programmatically with `configure("kind[:count],...")` (tests). A bare
kind fires on every matching dispatch; `kind:N` fires N times and then
disarms itself. Each firing is counted (`inject.<kind>` in the run report)
so a chaos run can assert the injector actually fired.

Kinds and where they fire:

- ``compile_fail``  device dispatch (jax/pallas): raises a compile-shaped
                    RuntimeError before the kernel runs
- ``oom``           device dispatch: raises RESOURCE_EXHAUSTED-shaped error
- ``hang``          device dispatch: sleeps ABPOA_TPU_INJECT_HANG_S (default
                    30 s) inside the watchdog-supervised worker, so the
                    dispatch deadline trips exactly like a wedged kernel
- ``garbage``       after a dispatch: corrupts the result (absurd score +
                    truncated CIGAR, or an out-of-alphabet graph base) so
                    the output guards must catch it
- ``native_crash``  native host-kernel dispatch: raises the same error shape
                    as a non-zero ``apg_align`` return
- ``poison_set``    set ingestion: raises PoisonedSetError, exercising the
                    per-set quarantine path

Everything here is inert when disarmed: the hot-path check is one global
boolean (`_ANY`).
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional


class InjectedFault(RuntimeError):
    """Base for injected failures; `kind` routes classification."""
    kind = "injected"


class InjectedCompileFailure(InjectedFault):
    kind = "compile_fail"


class InjectedDeviceOOM(InjectedFault):
    kind = "oom"


class InjectedNativeCrash(InjectedFault):
    kind = "native_crash"


class InjectedHang(InjectedFault):
    kind = "hang"


KINDS = ("compile_fail", "oom", "hang", "garbage", "native_crash",
         "poison_set")

# kind -> remaining shots (-1 = unlimited); absent = disarmed
_SPEC: Dict[str, int] = {}
_ANY = False
_CONFIGURED = False


def configure(spec: Optional[str] = None) -> None:
    """Parse an injection spec ("kind[:count],..."); None reads
    ABPOA_TPU_INJECT. Unknown kinds raise (a typo'd chaos run must not
    silently test nothing)."""
    global _ANY, _CONFIGURED
    if spec is None:
        spec = os.environ.get("ABPOA_TPU_INJECT", "")
    _SPEC.clear()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        kind, _, cnt = part.partition(":")
        if kind not in KINDS:
            raise ValueError(f"unknown fault-injection kind: {kind!r} "
                             f"(known: {', '.join(KINDS)})")
        _SPEC[kind] = int(cnt) if cnt else -1
    _ANY = bool(_SPEC)
    _CONFIGURED = True


def reset() -> None:
    """Disarm every injector (tests)."""
    configure("")


def _ensure_configured() -> None:
    if not _CONFIGURED:
        configure(None)


def armed(kind: str) -> bool:
    _ensure_configured()
    return _SPEC.get(kind, 0) != 0


def any_armed() -> bool:
    _ensure_configured()
    return _ANY


def fire(kind: str) -> bool:
    """Consume one shot of `kind` if armed. Counted in the run report so
    chaos tests can assert the injector really fired. The hot-path cost
    when disarmed is the two boolean checks."""
    if not _CONFIGURED:
        configure(None)
    if not _ANY:
        return False
    left = _SPEC.get(kind, 0)
    if left == 0:
        return False
    if left > 0:
        _SPEC[kind] = left - 1
    from ..obs import count
    count(f"inject.{kind}")
    return True


def hang_seconds() -> float:
    return float(os.environ.get("ABPOA_TPU_INJECT_HANG_S", "30"))


def pre_dispatch(backend: str) -> None:
    """Injection point at the top of a dispatch attempt. Runs INSIDE the
    watchdog-supervised worker for device backends, so an injected hang
    trips the deadline exactly like a real wedged kernel."""
    if not _CONFIGURED:
        configure(None)
    if not _ANY:
        return
    if backend in ("jax", "tpu", "pallas"):
        if fire("compile_fail"):
            raise InjectedCompileFailure(
                f"injected XLA compilation failure ({backend})")
        if fire("oom"):
            raise InjectedDeviceOOM(
                "RESOURCE_EXHAUSTED: injected device OOM while allocating "
                "DP planes")
        if fire("hang"):
            # sleep past the watchdog deadline (the main thread times out
            # and degrades), then raise instead of falling through: the
            # abandoned worker must not burn CPU on a dispatch whose
            # result is already discarded
            time.sleep(hang_seconds())
            raise InjectedHang(
                f"injected dispatch hang ({hang_seconds():.1f}s)")
    elif backend == "native":
        if fire("native_crash"):
            raise InjectedNativeCrash(
                "native DP kernel failed (rc=-11, injected crash)")


def corrupt_result(res):
    """Garbage injector for per-read dispatch results: an absurd score and
    a truncated CIGAR — both invariants the output guards must catch."""
    if fire("garbage"):
        res.best_score = 1 << 40
        res.cigar = list(res.cigar)[: max(0, len(res.cigar) // 2)]
        res.cigar_arr = None  # the guards must see the corrupted list
    return res


def corrupt_graph_base(base_arr):
    """Garbage injector for the fused path: poison one downloaded graph
    base out of the alphabet (what a mis-DMA'd kernel output looks like).
    Mutates the host array in place; returns True when it fired."""
    if fire("garbage") and base_arr.size > 2:
        base_arr[2] = 99
        return True
    return False


def check_poison_set() -> None:
    """Set-ingestion injection point: raise a poisoned-set error so the
    quarantine path runs without needing a malformed file on disk."""
    if fire("poison_set"):
        from .quarantine import PoisonedSetError
        raise PoisonedSetError("injected poisoned read set")
