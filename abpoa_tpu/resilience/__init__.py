"""Resilient dispatch: the fault story of the alignment engine.

A long-lived service (ROADMAP item 1) must survive compile failures,
device OOMs, kernel hangs, garbage outputs and malformed inputs without
dropping the batch. This package is the mechanism layer the dispatch
sites (align/dispatch.py, pipeline._run_fused_device, parallel/runner.py,
pyapi.msa_batch) wire together:

- inject.py     deterministic fault injectors (ABPOA_TPU_INJECT=...)
- watchdog.py   wall-clock deadline on device dispatches
- breaker.py    per-backend circuit breaker + the demotion ladder
                (pallas -> jax -> native -> numpy)
- guards.py     output sanity invariants (scores/CIGAR/alphabet)
- memory.py     admission control from the compile-ladder rung
- quarantine.py per-set isolation for `-l` / batch runs

`guarded_device_call` below is the common envelope: injection points,
watchdog, classified fault records, breaker bookkeeping, bounded retry
with exponential backoff. Every absorbed failure lands in the run
report's `faults` block (obs schema v3) — nothing is swallowed silently —
and unclassifiable exceptions (TypeError and friends: real bugs) always
propagate.

Overhead contract: with injection disarmed, a host-kernel run takes the
direct-call path — no worker threads, no device syncs, O(|cigar|) guard
arithmetic per read. tests/test_resilience.py guards warm-run wall like
the obs overhead guard does. ABPOA_TPU_RESILIENCE=0 (or set_enabled)
bypasses the envelope entirely for A/B measurement.
"""
from __future__ import annotations

import os
import time
from typing import Callable, Optional, Tuple

from . import guards, inject, memory, watchdog
from .breaker import DEMOTION, CircuitBreaker, breaker
from .guards import GarbageOutput
from .inject import (InjectedCompileFailure, InjectedDeviceOOM,
                     InjectedFault, InjectedNativeCrash)
from .quarantine import (PoisonedSetError, QUARANTINE_EXCEPTIONS,
                         quarantine_set, validate_records)
from .watchdog import DispatchTimeout

__all__ = [
    "guards", "inject", "memory", "watchdog",
    "DEMOTION", "CircuitBreaker", "breaker",
    "GarbageOutput", "DispatchTimeout", "DispatchFailed",
    "InjectedFault", "InjectedCompileFailure", "InjectedDeviceOOM",
    "InjectedNativeCrash",
    "PoisonedSetError", "QUARANTINE_EXCEPTIONS", "quarantine_set",
    "validate_records",
    "classify", "guarded_device_call", "enabled", "set_enabled",
]


class DispatchFailed(RuntimeError):
    """All attempts of a guarded dispatch failed; `kind` is the last
    classified fault. Subclasses RuntimeError so pre-existing fallback
    paths (`except RuntimeError`) degrade exactly as before."""

    def __init__(self, kind: str, msg: str) -> None:
        super().__init__(msg)
        self.kind = kind


_ENABLED = os.environ.get("ABPOA_TPU_RESILIENCE", "1") not in ("0", "off")


def enabled() -> bool:
    return _ENABLED


def set_enabled(flag: bool) -> None:
    """Kill switch (the overhead guard's control arm)."""
    global _ENABLED
    _ENABLED = bool(flag)


# (kind, retryable, counts_against_breaker) per failure class. Retry only
# where a second attempt is cheap and could differ (allocation races,
# transient compile-service errors); a hang already cost a full watchdog
# deadline and a guard violation is deterministic.
def classify(exc: BaseException) -> Optional[Tuple[str, bool, bool]]:
    """Classify a dispatch exception; None = not a fault shape we absorb
    (a real bug: let it propagate)."""
    if isinstance(exc, InjectedFault):
        return exc.kind, exc.kind in ("compile_fail", "oom"), True
    if isinstance(exc, DispatchTimeout):
        return "hang", False, True
    if isinstance(exc, GarbageOutput):
        return "garbage_output", False, True
    if isinstance(exc, RuntimeError):
        msg = str(exc)
        low = msg.lower()
        if msg.startswith(("fused loop", "fused lockstep")):
            # the fused driver's own structural bails (read-id replay
            # unavailable, growth non-convergence): deterministic host
            # fallbacks, not backend faults — don't retry, don't demote
            return "fused_bail", False, False
        if ("resource_exhausted" in low or "out of memory" in low
                or "oom" in low):
            return "oom", True, True
        if "compil" in low:
            return "compile_fail", True, True
        if "native dp kernel failed" in low:
            return "native_crash", False, True
        return "dispatch_error", False, True
    return None


def _retries() -> int:
    return max(0, int(os.environ.get("ABPOA_TPU_DISPATCH_RETRIES", "1")))


def _backoff_base_s() -> float:
    return float(os.environ.get("ABPOA_TPU_BACKOFF_S", "0.05"))


def guarded_device_call(label: str, backend: str, fn: Callable,
                        deadline_s: float = None):
    """Run one dispatch under the resilience envelope.

    Device backends (jax/tpu/pallas) run inside the watchdog worker with
    the injection points armed; host backends run inline (they cannot
    hang) with only the native-crash injector in front. Classified
    failures are recorded (`faults` + breaker) and retried with
    exponential backoff while the classification says a retry could help;
    exhaustion raises DispatchFailed(kind) for the caller's fallback path.
    """
    if not _ENABLED:
        return fn()
    from ..obs import count
    br = breaker()
    # acquire() is the half-open gate: "closed" dispatches normally,
    # "probe" means THIS call is the single cooldown probe of an open
    # breaker, None means the demotion stands — fail fast to the caller's
    # fallback path instead of re-paying the first attempt (on a wedged
    # accelerator that attempt is a full watchdog deadline per dispatch —
    # hours over a long `-l` run)
    permit = br.acquire(backend)
    if permit is None:
        count("breaker.short_circuit")
        raise DispatchFailed(
            "breaker_open",
            f"{label}: circuit breaker open for '{backend}' "
            f"(serving as '{br.effective(backend)}')")
    is_probe = permit == "probe"
    # supervision costs a worker thread (and XLA:CPU compiles run ~2x
    # slower off the main thread, PERF.md round 9): arm it only where a
    # hang is possible — real accelerator platforms — or demanded
    # (injection, ABPOA_TPU_WATCHDOG_FORCE)
    supervised = watchdog.supervision_needed(backend)

    def attempt():
        inject.pre_dispatch(backend)
        return fn()

    tries = 1 + _retries()
    delay = _backoff_base_s()
    last_exc: BaseException = None
    last_kind = "dispatch_error"
    for i in range(tries):
        try:
            if supervised:
                result = watchdog.call_with_deadline(attempt, deadline_s,
                                                     label=label)
            else:
                result = attempt()
            # recloses a half-open breaker when this call holds the probe
            # permit; a no-op for everyone else (a stale pre-open dispatch
            # must not reclose on another thread's behalf)
            br.record_success(backend, probe=is_probe)
            return result
        except Exception as e:  # noqa: BLE001 — classified, unknowns re-raise
            cls = classify(e)
            if cls is None:
                # unclassified = real bug: release OUR held probe permit
                # so the breaker cannot wedge in "probing" forever, then
                # let the exception surface
                if is_probe:
                    br.abort_probe(backend)
                raise
            kind, retryable, breaks = cls
            last_exc, last_kind = e, kind
            if breaks:
                br.record_failure(backend, kind, probe=is_probe)
            elif is_probe:
                # a non-breaker fault (fused_bail) still ends our probe
                br.abort_probe(backend)
            # no retry once the breaker opened: the demotion is decided
            retrying = retryable and i + 1 < tries and not br.is_open(backend)
            if kind == "fused_bail":
                # a structural bail is a healthy-run fallback, not a fault:
                # counter only, no faults record
                count("resilience.fused_bail")
            else:
                from ..obs import report
                report().record_fault(
                    kind, backend=backend, detail=str(e)[:300],
                    action="retry" if retrying else "fallback")
            if not retrying:
                break
            count("resilience.retries")
            time.sleep(delay)
            delay *= 2
    raise DispatchFailed(
        last_kind, f"{label}: {last_kind}: {last_exc}") from last_exc
