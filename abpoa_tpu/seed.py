"""Minimizer seeding, guide tree, DP chaining, and anchored (windowed) POA.

Reference: /root/reference/src/abpoa_seed.c (mm_sketch :97-168 from minimap2,
guide tree :244-337, anchor merge-join :344-377, DP chaining :500-591) and the
anchored POA driver /root/reference/src/abpoa_align.c:209-310.

The window partition produced here is the long-context strategy: one long
read x graph alignment is split at minimizer anchors into >= min_w windows,
each solved independently by the DP kernel — the TPU batching unit.
"""
from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np

from . import constants as C
from .align import align_sequence_to_subgraph
from .cigar import push_cigar
from .params import Params

U64_MAX = (1 << 64) - 1
_MASK64 = U64_MAX


def _hash64(key: int, mask: int) -> int:
    key = (~key + (key << 21)) & mask
    key = (key ^ (key >> 24)) & mask
    key = (key + (key << 3) + (key << 8)) & mask
    key = (key ^ (key >> 14)) & mask
    key = (key + (key << 2) + (key << 4)) & mask
    key = (key ^ (key >> 28)) & mask
    key = (key + (key << 31)) & mask
    return key


def mm_sketch(seq: np.ndarray, w: int, k: int, rid: int, both_strand: bool,
              out: List[Tuple[int, int]], aa: bool = False) -> None:
    """(w,k)-minimizer sketch, minimap2 algorithm (abpoa_seed.c:97-236).

    out entries: (x, y) with x = hash<<8|span, y = rid<<32|lastPos<<1|strand.
    """
    length = len(seq)
    if length <= 0:
        return
    bits = 5 if aa else 2
    sigma = 26 if aa else 4
    shift1 = bits * (k - 1)
    mask = (1 << (bits * k)) - 1
    kmer = [0, 0]
    buf: List[Tuple[int, int]] = [(U64_MAX, U64_MAX)] * w
    mn = (U64_MAX, U64_MAX)
    min_pos = 0
    l = 0
    buf_pos = 0
    i = 0
    while i < length:
        c = int(seq[i])
        info = (U64_MAX, U64_MAX)
        if c < sigma:
            kmer_span = min(l + 1, k)
            if both_strand and not aa:
                kmer[0] = ((kmer[0] << 2) | c) & mask
                kmer[1] = (kmer[1] >> 2) | ((3 ^ c) << shift1)
                if kmer[0] == kmer[1]:
                    i += 1
                    continue
                z = 0 if kmer[0] < kmer[1] else 1
            else:
                kmer[0] = ((kmer[0] << bits) | c) & mask
                z = 0
            l += 1
            if l >= k and kmer_span < 256:
                info = (_hash64(kmer[z], mask) << 8 | kmer_span,
                        (rid << 32) | (i << 1) | z)
        else:
            l = 0
            kmer[0] = kmer[1] = 0
        buf[buf_pos] = info
        if l == w + k - 1 and mn[0] != U64_MAX:
            for j in range(buf_pos + 1, w):
                if mn[0] == buf[j][0] and buf[j][1] != mn[1]:
                    out.append(buf[j])
            for j in range(buf_pos):
                if mn[0] == buf[j][0] and buf[j][1] != mn[1]:
                    out.append(buf[j])
        if info[0] <= mn[0]:
            if l >= w + k and mn[0] != U64_MAX:
                out.append(mn)
            mn, min_pos = info, buf_pos
        elif buf_pos == min_pos:
            if l >= w + k - 1 and mn[0] != U64_MAX:
                out.append(mn)
            mn = (U64_MAX, U64_MAX)
            for j in range(buf_pos + 1, w):
                if mn[0] >= buf[j][0]:
                    mn, min_pos = buf[j], j
            for j in range(buf_pos + 1):
                if mn[0] >= buf[j][0]:
                    mn, min_pos = buf[j], j
            if l >= w + k - 1 and mn[0] != U64_MAX:
                for j in range(buf_pos + 1, w):
                    if mn[0] == buf[j][0] and mn[1] != buf[j][1]:
                        out.append(buf[j])
                for j in range(buf_pos + 1):
                    if mn[0] == buf[j][0] and mn[1] != buf[j][1]:
                        out.append(buf[j])
        buf_pos += 1
        if buf_pos == w:
            buf_pos = 0
        i += 1
    if mn[0] != U64_MAX:
        out.append(mn)


def collect_mm(seqs: List[np.ndarray], abpt: Params
               ) -> Tuple[List[Tuple[int, int]], List[int]]:
    mm: List[Tuple[int, int]] = []
    mm_c = [0]
    for rid, seq in enumerate(seqs):
        mm_sketch(seq, abpt.w, abpt.k, rid, bool(abpt.amb_strand) and abpt.m <= 5,
                  mm, aa=abpt.m > 5)
        mm_c.append(len(mm))
    return mm, mm_c


def build_guide_tree(abpt: Params, n_seq: int, mm: List[Tuple[int, int]]) -> List[int]:
    """Jaccard-similarity greedy ordering (abpoa_seed.c:244-337)."""
    tree = list(range(n_seq))
    if not mm:
        return tree
    mm_sorted = sorted(mm, key=lambda t: t[0])
    # per-pair min-count hit accumulation over identical-hash buckets
    hit = np.zeros((n_seq, n_seq), dtype=np.int64)  # [i>=j]
    self_cnt = np.zeros(n_seq, dtype=np.int64)
    i0 = 0
    n = len(mm_sorted)
    for i in range(1, n + 1):
        if i == n or mm_sorted[i][0] != mm_sorted[i0][0]:
            cnt: dict[int, int] = {}
            for j in range(i0, i):
                rid = mm_sorted[j][1] >> 32
                cnt[rid] = cnt.get(rid, 0) + 1
                self_cnt[rid] += 1
            rids = sorted(cnt)
            for a in range(len(rids)):
                for b in range(a + 1, len(rids)):
                    r1, r2 = rids[a], rids[b]
                    hit[r2, r1] += min(cnt[r1], cnt[r2])
            i0 = i
    jac = np.zeros((n_seq, n_seq), dtype=np.float64)
    max_jac, max_i, max_j = -1.0, -1, -1
    for i in range(1, n_seq):
        for j in range(i):
            tot = self_cnt[i] + self_cnt[j] - hit[i, j]
            v = 0.0 if tot == 0 else float(hit[i, j]) / tot
            jac[i, j] = jac[j, i] = v
            if v > max_jac:
                max_jac, max_i, max_j = v, i, j
    order = [max_j, max_i]
    in_map = set(order)
    while len(order) < n_seq:
        best_jac, best = -1.0, n_seq
        for rid in range(n_seq):
            if rid in in_map:
                continue
            v = float(sum(jac[rid, r2] for r2 in order))
            if v > best_jac:
                best_jac, best = v, rid
        order.append(best)
        in_map.add(best)
    return order


def collect_anchors(mm: List[Tuple[int, int]], mm_c: List[int], tid: int, qid: int,
                    qlen: int, k: int, t_sorted: List[Tuple[int, int]],
                    q_cache: dict) -> List[int]:
    """Merge-join of sorted minimizer buckets (abpoa_seed.c:344-377).

    anchors: strand<<63 | t_lastPos<<32 | q_lastPos (sorted ascending).
    """
    if qid in q_cache:
        q_sorted = q_cache[qid]
    else:
        q_sorted = sorted(mm[mm_c[qid]: mm_c[qid + 1]], key=lambda t: t[0])
        q_cache.clear()
        q_cache[qid] = q_sorted
    anchors: List[int] = []
    i = j = 0
    nt, nq = len(t_sorted), len(q_sorted)
    while i < nt and j < nq:
        xi, xj = t_sorted[i][0], q_sorted[j][0]
        if xi == xj:
            _i = i
            while _i < nt and t_sorted[_i][0] == xi:
                yi = t_sorted[_i][1]
                _j = j
                while _j < nq and q_sorted[_j][0] == xj:
                    yj = q_sorted[_j][1]
                    if (yi & 1) == (yj & 1):
                        a = ((yi & 0xFFFFFFFF) >> 1) << 32 | ((yj & 0xFFFFFFFF) >> 1)
                    else:
                        a = (1 << 63) | ((yi & 0xFFFFFFFF) >> 1) << 32 \
                            | (qlen - (((yj & 0xFFFFFFFF) >> 1) + 1 - k) - 1)
                    anchors.append(a)
                    _j += 1
                _i += 1
            i, j = _i, _j
        elif xi < xj:
            i += 1
        else:
            j += 1
    anchors.sort()
    return anchors


def _ilog2_32(v: int) -> int:
    return v.bit_length() - 1 if v > 0 else -1


def _get_chain_score(max_bw: int, i_qpos: int, i_tpos: int, j_qpos: int,
                     j_tpos: int, k: int) -> Optional[int]:
    delta_q = i_qpos - j_qpos
    delta_t = i_tpos - j_tpos
    score = min(delta_q, delta_t, k)
    delta_tq = abs(delta_q - delta_t)
    if delta_tq > max_bw:
        return None
    # C semantics: `score -= (double)` truncates the RESULT toward zero
    return int(score - ((_ilog2_32(delta_tq) >> 1) + delta_tq * 0.01 * k))


def _get_local_chain_score(j_end_tpos, j_end_qpos, i_end, anchors, pre_id, score):
    i = i_end
    while i != -1:
        i_tpos = (anchors[i] >> 32) & 0x7FFFFFFF
        i_qpos = anchors[i] & 0xFFFFFFFF
        if i_tpos <= j_end_tpos and i_qpos <= j_end_qpos:
            break
        i = pre_id[i]
    if i == -1:
        return score[i_end]
    return score[i_end] - score[i]


def dp_chaining(anchors: List[int], abpt: Params, tlen: int, qlen: int,
                par_anchors: List[int]) -> None:
    """minimap2-style DP chaining + second-level chaining (abpoa_seed.c:500-591)."""
    n_a = len(anchors)
    if n_a == 0:
        return
    max_bw, max_dis = 100, 100
    max_skip_anchors, max_non_best_anchors = 25, 50
    min_local_chain_score = 100
    min_w = abpt.min_w + abpt.k
    k = abpt.k
    score = [0] * n_a
    pre_id = [0] * n_a
    end_pos = [0] * n_a
    st = 0
    for i in range(n_a):
        ia = anchors[i]
        i_qpos = ia & 0xFFFFFFFF
        i_tpos = (ia >> 32) & 0x7FFFFFFF
        i_strand = ia >> 63
        max_j, n_skip, non_best, max_score = -1, 0, 0, k
        while st < i:
            sa = anchors[st]
            if (sa >> 63) != i_strand or ((sa >> 32) & 0x7FFFFFFF) + max_dis < i_tpos:
                st += 1
            else:
                break
        for j in range(i - 1, st - 1, -1):
            ja = anchors[j]
            j_qpos = ja & 0xFFFFFFFF
            j_tpos = (ja >> 32) & 0x7FFFFFFF
            if j_qpos >= i_qpos or j_qpos + max_dis < i_qpos:
                continue
            s = _get_chain_score(max_bw, i_qpos, i_tpos, j_qpos, j_tpos, k)
            if s is None:
                continue
            s += score[j]
            if s > max_score:
                max_score, max_j = s, j
                non_best = 0
                if n_skip > 0:
                    n_skip -= 1
            elif end_pos[j] == i:
                n_skip += 1
                if n_skip > max_skip_anchors:
                    break
            else:
                non_best += 1
                if non_best > max_non_best_anchors:
                    break
            if pre_id[j] >= 0:
                end_pos[pre_id[j]] = i
        score[i] = max_score
        pre_id[i] = max_j

    end_pos = [0] * n_a
    for i in range(n_a - 1, -1, -1):
        if pre_id[i] >= 0:
            end_pos[pre_id[i]] = 1
        if end_pos[i] == 0 and score[i] >= min_local_chain_score:
            end_pos[i] = 2
    # local chains sorted by score
    chains = sorted((score[i], i) for i in range(n_a) if end_pos[i] == 2)
    n_local = len(chains)
    anchor_map = [0] * n_a
    # walk back each chain (best first), claim anchors; keep unbranched chains
    out_chains: List[Tuple[int, int]] = []  # (x, y) like local_chains
    for idx in range(n_local - 1, -1, -1):
        j = chains[idx][1]
        end_id = j
        # NOTE: reference reads the strand from anchors[idx] (loop variable i),
        # not from the chain end anchor — replicated verbatim
        strand = anchors[idx] >> 63
        tpos = (anchors[j] >> 32) & 0x7FFFFFFF
        qpos = anchors[j] & 0xFFFFFFFF
        while True:
            start_id = j
            anchor_map[j] = 1
            j = pre_id[j]
            if not (j >= 0 and anchor_map[j] == 0):
                break
        if j < 0:
            out_chains.append((strand << 63 | tpos << 32 | qpos,
                               end_id << 32 | start_id))
    out_chains.sort(key=lambda t: t[0])
    _chain_of_local_chains(out_chains, anchors, score, pre_id, par_anchors,
                           min_w, tlen, qlen)


def _chain_of_local_chains(local_chains, anchors, score, pre_id, par_anchors,
                           min_w, tlen, qlen) -> None:
    """(abpoa_seed.c:398-479)"""
    n = len(local_chains)
    if n == 0:
        return
    chain_score = [0] * n
    pre_chain_id = [0] * n
    global_max_score, global_max_i = -(1 << 31), -1
    st = 0
    for i in range(n):
        ix, iy = local_chains[i]
        istrand = ix >> 63
        i_end_qpos = ix & 0xFFFFFFFF
        i_end_anchor = iy >> 32
        i_start_anchor = iy & 0xFFFFFFFF
        i_start_tpos = (anchors[i_start_anchor] >> 32) & 0x7FFFFFFF
        i_start_qpos = anchors[i_start_anchor] & 0xFFFFFFFF
        max_j, max_score = -1, score[i_end_anchor]
        while st < i:
            if (local_chains[st][0] >> 63) != istrand:
                st += 1
            else:
                break
        for j in range(i - 1, st - 1, -1):
            jx = local_chains[j][0]
            j_end_tpos = (jx >> 32) & 0x7FFFFFFF
            j_end_qpos = jx & 0xFFFFFFFF
            if j_end_qpos >= i_end_qpos:
                continue
            if i_start_tpos > j_end_tpos and i_start_qpos > j_end_qpos:
                s1 = chain_score[j] + score[i_end_anchor]
            else:
                s1 = chain_score[j] + _get_local_chain_score(
                    j_end_tpos, j_end_qpos, i_end_anchor, anchors, pre_id, score)
            if s1 > max_score:
                max_score, max_j = s1, j
        chain_score[i] = max_score
        pre_chain_id[i] = max_j
        if max_score > global_max_score:
            global_max_score, global_max_i = max_score, i
    if global_max_i < 0:
        return
    start_n = len(par_anchors)
    cur_i = global_max_i
    pre_i = pre_chain_id[cur_i]
    cur_y = local_chains[cur_i][1]
    last_tpos, last_qpos = tlen, qlen
    while pre_i != -1:
        pre_x, pre_y = local_chains[pre_i]
        pre_end_tpos = (pre_x >> 32) & 0x7FFFFFFF
        pre_end_qpos = pre_x & 0xFFFFFFFF
        i = cur_y >> 32
        while i != -1:
            cur_tpos = (anchors[i] >> 32) & 0x7FFFFFFF
            cur_qpos = anchors[i] & 0xFFFFFFFF
            if cur_tpos > pre_end_tpos and cur_qpos > pre_end_qpos:
                if last_tpos - cur_tpos >= min_w and last_qpos - cur_qpos >= min_w:
                    par_anchors.append(anchors[i])
                    last_tpos, last_qpos = cur_tpos, cur_qpos
            else:
                break
            i = pre_id[i]
        cur_i, pre_i, cur_y = pre_i, pre_chain_id[pre_i], pre_y
    i = cur_y >> 32
    while i != -1:
        cur_tpos = (anchors[i] >> 32) & 0x7FFFFFFF
        cur_qpos = anchors[i] & 0xFFFFFFFF
        if last_tpos - cur_tpos >= min_w and last_qpos - cur_qpos >= min_w:
            par_anchors.append(anchors[i])
            last_tpos, last_qpos = cur_tpos, cur_qpos
        i = pre_id[i]
    # collected back-to-front: reverse into ascending order
    par_anchors[start_n:] = par_anchors[start_n:][::-1]


def lis_chaining(anchors: List[int], min_w: int) -> List[int]:
    """Longest-increasing-subsequence chaining, the reference's alternative to
    DP chaining for global mode (abpoa_seed.c:593-701): split anchors by
    strand, LIS over qpos-sorted tpos-ranks per strand, keep the strand with
    the longer chain, then enforce >= min_w spacing."""
    n_a = len(anchors)
    if n_a == 0:
        return []
    fwd, rev = [], []
    for i, a in enumerate(anchors):
        (rev if a >> 63 else fwd).append(((a & 0xFFFFFFFF) << 32) | (i + 1))

    def lis(rank: List[int], tot_n: int) -> List[int]:
        rank = sorted(rank)
        pre = [0] * (tot_n + 1)
        tails = [rank[0] & 0xFFFFFFFF]
        for v in rank[1:]:
            r = v & 0xFFFFFFFF
            if r < tails[0]:
                tails[0] = r
            elif r > tails[-1]:
                pre[r] = tails[-1]
                tails.append(r)
            else:
                lo, hi = -1, len(tails) - 1
                while hi - lo > 1:
                    mid = (lo + hi) // 2
                    if tails[mid] >= r:
                        hi = mid
                    else:
                        lo = mid
                tails[hi] = r
                if hi > 0:
                    pre[r] = tails[hi - 1]
        out = []
        r = tails[-1]
        while r != 0:
            out.append(r)
            r = pre[r]
        return out[::-1]

    best = []
    if fwd:
        best = lis(fwd, n_a)
    if rev:
        cand = lis(rev, n_a)
        if len(cand) > len(best):
            best = cand
    out: List[int] = []
    last_t = last_q = -1
    for r in best:
        a = anchors[r - 1]
        t = (a >> 32) & 0x7FFFFFFF
        q = a & 0xFFFFFFFF
        if t - last_t < min_w or q - last_q < min_w:
            continue
        out.append(a)
        last_t, last_q = t, q
    return out


def build_guide_tree_partition(seqs: List[np.ndarray], abpt: Params
                               ) -> Tuple[List[int], List[int], List[int]]:
    """(abpoa_seed.c:717-756). Returns (read_id_map, par_anchors, par_c)."""
    from .obs import phase
    n_seq = len(seqs)
    read_id_map = list(range(n_seq))
    with phase("seeding"):
        mm, mm_c = collect_mm(seqs, abpt)
    if abpt.progressive_poa and n_seq > 2:
        with phase("guide_tree"):
            read_id_map = build_guide_tree(abpt, n_seq, mm)
    par_anchors: List[int] = []
    par_c = [0] * n_seq
    if abpt.disable_seeding or n_seq < 2:
        return read_id_map, par_anchors, par_c
    with phase("seeding"):
        q_cache: dict = {}
        t_sorted = sorted(mm[mm_c[read_id_map[0]]: mm_c[read_id_map[0] + 1]],
                          key=lambda t: t[0])
        for i in range(1, n_seq):
            tid, qid = read_id_map[i - 1], read_id_map[i]
            if i > 1:
                t_sorted = q_cache.get(tid) or sorted(
                    mm[mm_c[tid]: mm_c[tid + 1]], key=lambda t: t[0])
            anchors = collect_anchors(mm, mm_c, tid, qid, len(seqs[qid]),
                                      abpt.k, t_sorted, q_cache)
            dp_chaining(anchors, abpt, len(seqs[tid]), len(seqs[qid]),
                        par_anchors)
            par_c[i] = len(par_anchors)
    return read_id_map, par_anchors, par_c


def anchor_poa(ab, abpt: Params, seqs: List[np.ndarray], weights: List[np.ndarray],
               par_anchors: List[int], par_c: List[int], read_id_map: List[int],
               exist_n_seq: int) -> None:
    """Anchored windowed POA (/root/reference/src/abpoa_align.c:209-310)."""
    from .pipeline import _rc_encode
    g = ab.graph
    n_seq = len(seqs)
    tot_n_seq = exist_n_seq + n_seq
    k = abpt.k
    max_len = max((len(s) for s in seqs), default=0)
    tpos_to_node_id = np.zeros(max_len, dtype=np.int64)
    qpos_to_node_id = np.zeros(max_len, dtype=np.int64)
    last_read_id = -1
    for _i in range(n_seq):
        i = read_id_map[_i]
        read_id = exist_n_seq + i
        qlen = len(seqs[i])
        t_read = time.perf_counter()
        whole_cigar: List[int] = []
        ai = 0 if _i == 0 else par_c[_i - 1]
        beg_id, beg_qpos = C.SRC_NODE_ID, 0
        if ai < par_c[_i]:
            ab.is_rc[read_id] = bool(ab.is_rc[last_read_id]) ^ bool(par_anchors[ai] >> 63)
            if ab.is_rc[read_id]:
                qseq = _rc_encode(seqs[i])
                weight = weights[i][::-1].copy()
            else:
                qseq, weight = seqs[i], weights[i]
            if ab.is_rc[last_read_id]:  # remap anchors into last read's rc coords
                last_qlen = len(seqs[read_id_map[_i - 1]])
                for j in range(ai, par_c[_i]):
                    a = par_anchors[j]
                    end_tpos = (a >> 32) & 0x7FFFFFFF
                    end_qpos = a & 0xFFFFFFFF
                    par_anchors[j] = (a >> 63) << 63 \
                        | (last_qlen - end_tpos + k) << 32 | (qlen - end_qpos + k)
                par_anchors[ai: par_c[_i]] = par_anchors[ai: par_c[_i]][::-1]
        else:
            ab.is_rc[read_id] = False
            qseq, weight = seqs[i], weights[i]

        # window specs are fully determined by the PREVIOUS read's graph
        # (anchors + tpos map), so all of this read's windows are independent
        # alignments against the frozen graph and can run as one device batch
        # (/root/reference/src/abpoa_align.c:209-310)
        specs = []          # (beg_id, end_id, beg_qpos, end_qpos)
        kmer_runs = []      # anchor k-mer node ids between windows
        while ai < par_c[_i]:
            a = par_anchors[ai]
            end_tpos = ((a >> 32) & 0x7FFFFFFF) - k + 1
            end_id = int(tpos_to_node_id[end_tpos])
            end_qpos = (a & 0xFFFFFFFF) - k + 1
            specs.append((beg_id, end_id, beg_qpos, end_qpos))
            kmer_runs.append([int(tpos_to_node_id[end_tpos + j])
                              for j in range(k)])
            beg_id = int(tpos_to_node_id[end_tpos + k - 1])
            beg_qpos = end_qpos + k
            ai += 1
        if g.node_n > 2:
            specs.append((beg_id, C.SINK_NODE_ID, beg_qpos, qlen))

        from .align.dispatch import align_windows
        from .obs import phase, record_dp
        from .pipeline import _band_cols
        for _b, _e, lo, hi in specs:
            # row count of an anchored window subgraph is not known host-side;
            # model it as the window's target span (~= query span) like the
            # reference's banded window DP
            record_dp((hi - lo) + 2, _band_cols(abpt, hi - lo), abpt.gap_mode)
        with phase("align"):
            results = align_windows(
                g, abpt, [(b, e, qseq[lo:hi]) for b, e, lo, hi in specs])
        for wi, res in enumerate(results):
            whole_cigar.extend(res.cigar)
            if wi < len(kmer_runs):
                for j, nid in enumerate(kmer_runs[wi]):
                    push_cigar(whole_cigar, C.CMATCH, 1, nid, j)
        with phase("fusion"):
            g.add_subgraph_alignment(abpt, C.SRC_NODE_ID, C.SINK_NODE_ID, qseq,
                                     weight, qpos_to_node_id, whole_cigar,
                                     read_id, tot_n_seq, True)
        from .align.dispatch import telemetry_backend
        from .obs import record_read, trace
        dt = time.perf_counter() - t_read
        backend, auto_fb = telemetry_backend(abpt)
        record_read(dt, qlen, _band_cols(abpt, qlen), backend,
                    fallback=auto_fb)
        trace.add_span(f"read:{read_id}", "read", t_read, dt,
                       args={"qlen": qlen, "windows": len(specs)})
        tpos_to_node_id, qpos_to_node_id = qpos_to_node_id, tpos_to_node_id
        last_read_id = read_id


def anchor_poa_pipeline(ab, abpt: Params, seqs: List[np.ndarray],
                        weights: List[np.ndarray], exist_n_seq: int) -> None:
    read_id_map, par_anchors, par_c = build_guide_tree_partition(seqs, abpt)
    anchor_poa(ab, abpt, seqs, weights, par_anchors, par_c, read_id_map, exist_n_seq)
