"""Consensus calling: heaviest bundling and majority vote.

Reference: /root/reference/src/abpoa_output.c (heaviest bundling :478-548,
majority voting :394-452,550-587, phred :297-303, coverage :347-374,
driver :1184-1215).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from .. import constants as C
from ..graph import POAGraph, Node
from ..params import Params

NAT_E = 2.718281828459045


@dataclass
class ConsensusResult:
    n_cons: int = 0
    n_seq: int = 0
    clu_n_seq: List[int] = field(default_factory=list)
    clu_read_ids: List[List[int]] = field(default_factory=list)
    cons_node_ids: List[List[int]] = field(default_factory=list)
    cons_base: List[List[int]] = field(default_factory=list)
    cons_cov: List[List[int]] = field(default_factory=list)
    cons_phred: List[List[int]] = field(default_factory=list)
    msa_len: int = 0
    msa_base: List[np.ndarray] = field(default_factory=list)  # n_seq + n_cons rows

    @property
    def cons_len(self) -> List[int]:
        return [len(x) for x in self.cons_base]


def phred_score(n_cov: int, n_seq: int) -> int:
    """Sigmoid-mapped phred+33 (src/abpoa_output.c:297-303)."""
    if n_cov > n_seq:
        raise ValueError(f"unexpected n_cov/n_seq ({n_cov}/{n_seq})")
    x = 13.8 * (1.25 * n_cov / n_seq - 0.25)
    p = 1 - 1.0 / (1.0 + math.pow(NAT_E, -x))
    return 33 + int(-10 * math.log10(p) + 0.499)


def phred_score_vec(n_cov: np.ndarray, n_seq: int) -> np.ndarray:
    """phred_score over a coverage vector, computed via the scalar path.

    np.power/np.log10 are NOT guaranteed bit-identical to math.pow/
    math.log10 on every libm build; a one-ULP divergence flips the +0.499
    truncation and changes an emitted phred character (ADVICE r5 #2).
    Consensus rows are short, so the scalar loop costs nothing."""
    n_cov = np.asarray(n_cov)
    if n_cov.size and (n_cov > n_seq).any():
        raise ValueError(f"unexpected n_cov/n_seq (max {n_cov.max()}/{n_seq})")
    return np.fromiter((phred_score(int(c), n_seq) for c in n_cov.ravel()),
                       dtype=np.int64, count=n_cov.size)


def _popcount(x: int) -> int:
    return bin(x).count("1")


def _edge_inclu_read_count(node: Node, edge_i: int, clu_bits: int) -> int:
    return _popcount(node.read_ids[edge_i] & clu_bits)


def _edge_weight(node: Node, edge_i: int, clu_bits: Optional[int], use_qv: bool,
                 n_clu: int) -> int:
    if n_clu == 1:
        return node.out_w[edge_i]
    assert clu_bits is not None
    if not use_qv:
        return _edge_inclu_read_count(node, edge_i, clu_bits)
    w = 0
    bits = node.read_ids[edge_i] & clu_bits
    for rid, rw in node.read_weight.items():
        if rw > 0 and (bits >> rid) & 1:
            w += rw
    return w


def _node_out_cov(node: Node, clu_bits: Optional[int], n_cons: int) -> int:
    if n_cons == 1:
        return node.n_read
    assert clu_bits is not None
    return sum(_edge_inclu_read_count(node, i, clu_bits) for i in range(len(node.out_ids)))


def _node_in_cov(g: POAGraph, node_id: int, clu_bits: int) -> int:
    node = g.nodes[node_id]
    cov = 0
    for in_id in node.in_ids:
        pre = g.nodes[in_id]
        for j, out_id in enumerate(pre.out_ids):
            if out_id == node_id:
                cov += _edge_inclu_read_count(pre, j, clu_bits)
                break
    return cov


def _node_cov(g: POAGraph, node_id: int, clu_bits: Optional[int], n_cons: int) -> int:
    if n_cons == 1:
        return g.nodes[node_id].n_read
    assert clu_bits is not None
    return max(_node_in_cov(g, node_id, clu_bits),
               _node_out_cov(g.nodes[node_id], clu_bits, n_cons))


def _set_clu_read_ids(abc: ConsensusResult, clu_bits_list: Optional[List[int]],
                      n_clu: int, n_seq: int) -> None:
    abc.clu_n_seq = []
    abc.clu_read_ids = []
    if n_clu == 1:
        abc.clu_n_seq.append(n_seq)
        abc.clu_read_ids.append(list(range(n_seq)))
        return
    assert clu_bits_list is not None
    for bits in clu_bits_list:
        ids = [i for i in range(n_seq) if (bits >> i) & 1]
        abc.clu_n_seq.append(len(ids))
        abc.clu_read_ids.append(ids)


def heaviest_bundling(g: POAGraph, abpt: Params, n_clu: int,
                      clu_bits_list: Optional[List[int]], abc: ConsensusResult) -> None:
    """Reverse-BFS argmax-out-edge consensus (src/abpoa_output.c:478-548)."""
    from collections import deque
    n = g.node_n
    src, sink = C.SRC_NODE_ID, C.SINK_NODE_ID
    _set_clu_read_ids(abc, clu_bits_list, n_clu, abc.n_seq)
    abc.n_cons = n_clu
    abc.cons_node_ids, abc.cons_base, abc.cons_cov, abc.cons_phred = [], [], [], []

    score = [0] * n
    for cons_i in range(n_clu):
        clu_bits = clu_bits_list[cons_i] if clu_bits_list else None
        max_out_id = [-1] * n
        out_degree = [len(nd.out_ids) for nd in g.nodes]
        q: deque[int] = deque([sink])
        while q:
            cur = q.popleft()
            node = g.nodes[cur]
            if cur == sink:
                max_out_id[cur] = -1
                score[cur] = 0
            elif cur == src:
                path_score, path_max_w, max_id = -1, -1, -1
                for i, out_id in enumerate(node.out_ids):
                    out_w = _edge_weight(node, i, clu_bits, abpt.use_qv, n_clu)
                    if out_w > path_max_w or (out_w == path_max_w and score[out_id] > path_score):
                        max_id = out_id
                        path_score = score[out_id]
                        path_max_w = out_w
                max_out_id[cur] = max_id
                break
            else:
                max_w, max_id = -(1 << 31), -1
                for i, out_id in enumerate(node.out_ids):
                    out_w = _edge_weight(node, i, clu_bits, abpt.use_qv, n_clu)
                    if max_w < out_w:
                        max_w, max_id = out_w, out_id
                    elif max_w == out_w and score[max_id] <= score[out_id]:
                        max_id = out_id
                score[cur] = max_w + score[max_id]
                max_out_id[cur] = max_id
            for in_id in node.in_ids:
                out_degree[in_id] -= 1
                if out_degree[in_id] == 0:
                    q.append(in_id)

        # walk the max path (src/abpoa_output.c:376-392)
        ids: List[int] = []
        bases: List[int] = []
        covs: List[int] = []
        phreds: List[int] = []
        cur = max_out_id[src]
        while cur != sink:
            ids.append(cur)
            bases.append(g.nodes[cur].base)
            cov = _node_cov(g, cur, clu_bits, n_clu)
            covs.append(cov)
            phreds.append(phred_score(cov, abc.clu_n_seq[cons_i]))
            cur = max_out_id[cur]
        abc.cons_node_ids.append(ids)
        abc.cons_base.append(bases)
        abc.cons_cov.append(covs)
        abc.cons_phred.append(phreds)


def most_frequent(g: POAGraph, abpt: Params, n_clu: int,
                  clu_bits_list: Optional[List[int]], abc: ConsensusResult) -> None:
    """Column majority-vote consensus (src/abpoa_output.c:394-452,550-587)."""
    use_span = abpt.sub_aln
    g.set_msa_rank()
    m = abpt.m
    msa_l = int(g.node_id_to_msa_rank[C.SINK_NODE_ID]) - 1
    abc.n_cons = n_clu
    _set_clu_read_ids(abc, clu_bits_list, n_clu, abc.n_seq)
    # per-cluster column weights; gap column (m-1) starts at cluster size
    rc_weight = [np.zeros((msa_l, m), dtype=np.int64) for _ in range(n_clu)]
    for cons_i in range(n_clu):
        rc_weight[cons_i][:, m - 1] = abc.clu_n_seq[cons_i]
    msa_node_id = np.zeros((msa_l, m), dtype=np.int64)
    for i in range(2, g.node_n):
        rank = g.msa_rank_of(i)
        node = g.nodes[i]
        msa_node_id[rank - 1, node.base] = i
        for cons_i in range(n_clu):
            clu_bits = clu_bits_list[cons_i] if clu_bits_list else None
            node_w = _node_out_cov(node, clu_bits, n_clu)
            rc_weight[cons_i][rank - 1, node.base] = node_w
            rc_weight[cons_i][rank - 1, m - 1] -= node_w

    abc.cons_node_ids, abc.cons_base, abc.cons_cov, abc.cons_phred = [], [], [], []
    for cons_i in range(n_clu):
        ids, bases, covs, phreds = [], [], [], []
        for i in range(msa_l):
            max_c, total_c, max_base = 0, 0, m
            for j in range(m - 1):
                cnt = int(rc_weight[cons_i][i, j])
                if cnt > max_c:
                    max_c = cnt
                    max_base = j
                total_c += cnt
            if use_span:
                gap_c = g.nodes[int(msa_node_id[i, max_base])].n_span_read - total_c
            else:
                gap_c = abc.clu_n_seq[cons_i] - total_c
            if max_c >= gap_c:
                cur_id = int(msa_node_id[i, max_base])
                ids.append(cur_id)
                bases.append(max_base)
                covs.append(max_c)
                phreds.append(phred_score(max_c, abc.clu_n_seq[cons_i]))
        abc.cons_node_ids.append(ids)
        abc.cons_base.append(bases)
        abc.cons_cov.append(covs)
        abc.cons_phred.append(phreds)


def native_hb_eligible(g, abpt: Params) -> bool:
    """True when the C++ heaviest-bundling fast path covers this config:
    native graph, single cluster, HB algorithm, consensus requested.
    Callers add their own output-mode exclusions (gfa/pog) on top."""
    return (getattr(g, "is_native", False)
            and abpt.out_cons and not abpt.out_msa
            and abpt.cons_algrm == C.CONS_HB
            and abpt.max_n_cons == 1)


def native_consensus_hb(g, n_seq: int) -> ConsensusResult:
    """ConsensusResult straight from the native graph's C++ heaviest
    bundling (native/host_core.cpp apg_cons_hb) — the default single-
    cluster read-count-weight config, skipping the O(V+E) to_python
    export. Callers gate on that config themselves."""
    abc = ConsensusResult(n_seq=n_seq)
    if g.node_n <= 2:
        return abc
    ids, bases, covs = g.consensus_hb()
    abc.n_cons = 1
    abc.clu_n_seq = [n_seq]
    abc.clu_read_ids = [list(range(n_seq))]
    abc.cons_node_ids = [ids.tolist()]
    abc.cons_base = [bases.tolist()]
    abc.cons_cov = [covs.tolist()]
    abc.cons_phred = [phred_score_vec(covs, n_seq).tolist()]
    return abc


def generate_consensus(g: POAGraph, abpt: Params, n_seq: int) -> ConsensusResult:
    """Driver (src/abpoa_output.c:1184-1215)."""
    abc = ConsensusResult(n_seq=n_seq)
    if g.node_n <= 2:
        return abc
    n_clu = 1
    clu_bits_list: Optional[List[int]] = None
    if abpt.max_n_cons > 1:
        from .cluster import multip_read_clu_kmedoids
        n_clu, clu_bits_list = multip_read_clu_kmedoids(g, abpt, n_seq)
    if abpt.cons_algrm == C.CONS_HB:
        heaviest_bundling(g, abpt, n_clu, clu_bits_list, abc)
    else:
        most_frequent(g, abpt, n_clu, clu_bits_list, abc)
    g.is_called_cons = True
    return abc
