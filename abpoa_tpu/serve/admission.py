"""Bounded, memory-priced admission for the serve front end.

Two limits, both checked BEFORE a request costs anything:

- queue depth (``ABPOA_TPU_SERVE_QUEUE``, default 64): the knee of the
  open-loop overload curve. Arrivals past a full queue are shed as 429 —
  latency stays bounded instead of building an unbounded backlog.
- DP-plane bytes (``ABPOA_TPU_SERVE_MEM_BUDGET_MB``): each request is
  priced with `resilience/memory.py`'s footprint model over its
  compile-ladder rung (the same arithmetic the dispatch admission uses),
  and the sum over queued + in-flight requests must fit the budget. A
  single request is always admissible on an empty system — at dispatch
  time `memory.admit` still chunks or demotes it if it alone exceeds the
  device budget — so the byte gate bounds *concurrency*, it can never
  wedge the service on one large set.

Rejections carry a Retry-After derived from the observed service rate
(queue depth x recent mean service time), so a well-behaved client backs
off proportionally to the actual backlog.

Continuous batching (PR 17) adds a second exit from the queue:
`claim_joiners` lets an in-flight lockstep group pull same-rung jobs onto
freed lanes at a round boundary, priced against the group's LIVE byte
footprint (early-retired lanes have already released their share) rather
than the pickup-time snapshot.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

from ..resilience import memory


def queue_limit() -> int:
    return max(1, int(os.environ.get("ABPOA_TPU_SERVE_QUEUE", "64")))


def default_deadline_s() -> float:
    """Per-request wall deadline (admission wait + execution). Sized for
    warm rungs: a cold first-sight compile belongs to startup warm, not
    to a request."""
    return float(os.environ.get("ABPOA_TPU_SERVE_DEADLINE_S", "30"))


def serve_budget_bytes() -> Optional[int]:
    """Byte budget over queued + in-flight DP planes. Defaults to the
    dispatch-layer budget when one is active (accelerator HBM), else a
    4 GB host-RAM bound; 0 disables the byte gate (depth still holds)."""
    env = os.environ.get("ABPOA_TPU_SERVE_MEM_BUDGET_MB")
    if env is not None:
        mb = float(env)
        return int(mb * 1e6) if mb > 0 else None
    return memory.budget_bytes() or 4_000 * 10 ** 6


def request_caps(abpt, records) -> dict:
    """The compile-ladder rung caps one request's dispatch would start
    from. Qp/W/N come from the SAME definition site the fused planner
    reads (`compile.ladder.plan_chunk_buckets`/`chunk_node_cap` — jax-
    free, so a host-device serve process prices admission without a jax
    import), so the byte gate cannot drift from the dispatched shapes.
    plane16 is left False: one cell-width step of conservatism in a
    model that only needs to be right within ~2x."""
    from ..compile.ladder import (chunk_node_cap, plan_chunk_buckets,
                                  reads_rung)
    qmax = max((len(r.seq) for r in records), default=1)
    Qp, W, _local = plan_chunk_buckets(abpt, qmax)
    return dict(N=chunk_node_cap(qmax), E=8, A=8, W=W, Qp=Qp,
                reads=reads_rung(max(1, len(records))), K=1, plane16=False,
                gap_mode=abpt.gap_mode, m=abpt.m)


def map_request_bytes(abpt, records, n_rows: int) -> int:
    """Admission price for ONE /map request: per-read bytes ONLY. The
    graph half of the map tables (adjacency scatter, base rows) is
    immutable and shared by every lane for the server's lifetime — it was
    priced once when the graph was restored — so a map request pays just
    its reads' share of the run_dp_chunk dispatch: the banded DP planes
    over the graph's row rung plus each read's qp profile. jax-free, same
    contract as `request_caps`."""
    from ..compile.buckets import bucket
    from ..compile.ladder import plan_chunk_buckets
    qmax = max((len(r.seq) for r in records), default=1)
    Qp, W, _local = plan_chunk_buckets(abpt, qmax)
    R = bucket(max(n_rows, 8), 64)
    planes = memory._N_PLANES.get(abpt.gap_mode, 6)
    per_read = (planes * R * min(W, Qp + 1) * 4   # banded DP planes
                + Qp * (8 + 4 * abpt.m))          # query + qp profile
    return len(records) * per_read


class Job:
    """One admitted alignment request moving through the queue."""

    _ids = itertools.count(1)

    __slots__ = ("id", "label", "records", "n_reads", "rung", "est_bytes",
                 "eligible", "deadline_s", "t_arrive", "done", "status",
                 "body", "error", "_lock", "_done_marked",
                 "rid", "t_pickup", "dumps", "attempt", "qmax",
                 "join_round", "join_group", "kind")

    def __init__(self, records, rung: int, est_bytes: int, eligible: bool,
                 deadline_s: float, rid: str = "",
                 attempt: int = 1, qmax: int = 0,
                 kind: str = "consensus") -> None:
        self.id = next(self._ids)
        self.label = f"req-{self.id}"
        # the request id minted at ingress (PR 15): rides the response
        # header, every span down to the pool worker, the archive record
        # and the flight dump — `abpoa-tpu why <rid>` joins them back up
        self.rid = rid
        # which delivery of this request id we are (PR 16): the fleet
        # router re-sends a request after a replica death (attempt 2) and
        # for hedges; the archive record keeps it so `why` can explain
        # the hop
        self.attempt = max(1, attempt)
        self.t_pickup: Optional[float] = None   # set when a worker pops us
        self.dumps: list = []                   # harvested flight dumps
        # raw max query length (bp): the scheduler's serial-vs-lockstep
        # crossover input — rung alone is too coarse (geom-128 snapped)
        self.qmax = qmax
        # continuous batching (PR 17): set when this request boarded an
        # in-flight lockstep group at a round boundary instead of being
        # coalesced at pickup — `why` renders "joined group g at round r"
        self.join_round: Optional[int] = None
        self.join_group: Optional[int] = None
        # workload class (PR 18): "consensus" (POST /align) or "map"
        # (POST /map — fixed-graph read mapping). Groups are kind-
        # homogeneous: a map lane retires every round while a consensus
        # lane drains for many, so mixing them would re-create exactly
        # the divergence the noop K cap exists to suppress.
        self.kind = kind
        self.records = records
        self.n_reads = len(records)
        self.rung = rung
        self.est_bytes = est_bytes
        self.eligible = eligible
        self.deadline_s = deadline_s
        self.t_arrive = time.perf_counter()
        self.done = threading.Event()
        self.status: Optional[str] = None
        self.body = ""
        self.error = ""
        self._lock = threading.Lock()
        self._done_marked = False   # owned by AdmissionController._cv

    def remaining_s(self) -> float:
        return self.deadline_s - (time.perf_counter() - self.t_arrive)

    def wall_s(self) -> float:
        return time.perf_counter() - self.t_arrive

    def finish(self, status: str, body: str = "", error: str = "") -> bool:
        """First writer wins: the worker and the handler's safety-net
        timeout can both try to conclude a job; exactly one does."""
        with self._lock:
            if self.status is not None:
                return False
            self.status = status
            self.body = body
            self.error = error
        self.done.set()
        return True


class AdmissionController:
    """The bounded queue + its accounting. All state under one condition
    variable; every mutation republishes the queue/inflight gauges."""

    def __init__(self, abpt, max_depth: Optional[int] = None,
                 budget_bytes: Optional[int] = None,
                 mesh: int = 1) -> None:
        self._abpt = abpt
        self._cv = threading.Condition()
        self._queue: Deque[Job] = deque()
        self._max_depth = max_depth if max_depth is not None else queue_limit()
        self._budget = (budget_bytes if budget_bytes is not None
                        else serve_budget_bytes())
        # sharded route: the byte gate prices the WHOLE mesh — each of the
        # mesh's devices holds only its K/mesh lane slice of the planes, so
        # the per-device budget scales to mesh x budget globally
        if self._budget and mesh > 1:
            self._budget *= int(mesh)
        self._bytes = 0          # queued + in-flight estimate
        self._inflight = 0
        self._closed = False
        self._service_ewma_s = 0.05   # Retry-After seed, updated on done

    # ------------------------------------------------------------- intake
    def try_admit(self, job: Job) -> Tuple[bool, str, float]:
        """-> (admitted, reason, retry_after_s). Reasons: "", "draining",
        "queue_full", "memory"."""
        from ..obs import metrics
        with self._cv:
            if self._closed:
                return False, "draining", 0.0
            if len(self._queue) >= self._max_depth:
                return False, "queue_full", self._retry_after_locked()
            if (self._budget and self._bytes > 0
                    and self._bytes + job.est_bytes > self._budget):
                return False, "memory", self._retry_after_locked()
            self._queue.append(job)
            self._bytes += job.est_bytes
            self._publish_locked()
            self._cv.notify()
        metrics.publish_serve_admitted()
        return True, "", 0.0

    def _retry_after_locked(self) -> float:
        # the backlog's expected drain time: what a 429 tells the client
        backlog = len(self._queue) + self._inflight
        return max(1.0, round(backlog * self._service_ewma_s, 1))

    def close_intake(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    # ------------------------------------------------------------- workers
    def next_group(self, max_k: int = 1, coalesce: bool = False,
                   timeout: float = 0.25, min_qlen: int = 0) -> List[Job]:
        """Pop the head job, plus (when coalescing) up to max_k-1 more
        queued jobs sharing its Qp rung — the lockstep pack. Returns []
        on timeout or closed-and-empty so workers can re-check shutdown.

        min_qlen is the scheduler's serial-vs-lockstep crossover: a head
        below it runs serial, so coalescing it into a lockstep pack would
        only slow it down (jobs with unknown qmax=0 are not gated)."""
        with self._cv:
            if not self._queue:
                if self._closed:
                    return []
                self._cv.wait(timeout)
                if not self._queue:
                    return []
            head = self._queue.popleft()
            group = [head]
            # the serial-wins qlen crossover is a consensus-path economy
            # (per-round host fusion to amortize); a map round has no
            # fusion, so short map reads still batch
            if (coalesce and head.kind != "map"
                    and head.qmax and head.qmax < min_qlen):
                coalesce = False
            if coalesce and head.eligible and max_k > 1:
                for job in list(self._queue):
                    if len(group) >= max_k:
                        break
                    if (job.eligible and job.rung == head.rung
                            and job.kind == head.kind):
                        self._queue.remove(job)
                        group.append(job)
            self._inflight += len(group)
            now = time.perf_counter()
            for job in group:
                # admission wait ends here; the server records the
                # admission_wait span from (t_arrive, t_pickup) so queue
                # time is attributable per request
                job.t_pickup = now
            self._publish_locked()
            return group

    def claim_joiners(self, rung: int, max_n: int,
                      live_bytes: int = 0,
                      min_remaining_s: float = 0.5,
                      kind: str = "consensus") -> List[Job]:
        """Continuous batching (PR 17): pull up to max_n queued jobs onto
        the free lanes of an in-flight lockstep group at its round
        boundary. A joiner must share the group's Qp rung, be lockstep-
        eligible, have at least min_remaining_s of deadline left (a
        near-dead request boarding a multi-round group would just 504 on a
        lane), and fit the byte budget priced against the LIVE group
        (live_bytes = sum over the group's currently-live lanes — early
        retires have already released their share), not the pickup-time
        snapshot. Claimed jobs leave the queue and count in-flight, same
        accounting as next_group."""
        claimed: List[Job] = []
        with self._cv:
            if max_n <= 0:
                return claimed
            priced = live_bytes
            for job in list(self._queue):
                if len(claimed) >= max_n:
                    break
                if (not job.eligible or job.rung != rung
                        or job.kind != kind):
                    continue
                if job.remaining_s() <= min_remaining_s:
                    continue
                if (self._budget and priced > 0
                        and priced + job.est_bytes > self._budget):
                    continue
                self._queue.remove(job)
                priced += job.est_bytes
                claimed.append(job)
            if claimed:
                self._inflight += len(claimed)
                now = time.perf_counter()
                for job in claimed:
                    job.t_pickup = now
                self._publish_locked()
        return claimed

    def mark_done(self, job: Job, service_s: Optional[float] = None) -> None:
        """Release one job's accounting. Idempotent per job: the worker's
        catch-all sweep can overlap the per-job finally blocks, and a
        double release would drive _inflight/_bytes negative — silently
        disabling the byte gate and wedging wait_drained."""
        with self._cv:
            if job._done_marked:
                return
            job._done_marked = True
            self._bytes -= job.est_bytes
            self._inflight -= 1
            if service_s is not None:
                self._service_ewma_s += 0.2 * (service_s
                                               - self._service_ewma_s)
            self._publish_locked()
            self._cv.notify_all()

    # ------------------------------------------------------------- state
    def _publish_locked(self) -> None:
        from ..obs import metrics
        metrics.publish_serve_state(len(self._queue), self._inflight)

    def snapshot(self) -> Tuple[int, int]:
        with self._cv:
            return len(self._queue), self._inflight

    def drained(self) -> bool:
        with self._cv:
            return not self._queue and self._inflight == 0

    def wait_drained(self, timeout: float) -> bool:
        """Block until queue + in-flight are empty (the drain barrier)."""
        with self._cv:
            return self._cv.wait_for(
                lambda: not self._queue and self._inflight == 0, timeout)
