"""The serve front end: HTTP endpoints, worker pool, drain machinery.

Endpoints (stdlib http.server, one ThreadingHTTPServer):

- ``POST /align``   body = FASTA/FASTQ text, response = the same bytes
                    the CLI would write for that input (consensus/MSA/GFA
                    per the server's configured mode). Status codes ARE
                    the robustness contract: 200 aligned, 400 poisoned
                    set, 413 oversized body, 429 shed (Retry-After
                    header), 503 draining, 504 deadline expired.
                    ``X-Abpoa-Deadline-S`` caps this request tighter
                    than the server default.
- ``POST /map``     (with ``--map-graph``) body = FASTA/FASTQ reads,
                    response = one GAF-style record per read mapped
                    against the fixed restored graph (PR 18). Same
                    status-code contract as /align, plus 400 for a read
                    over the map length cap; the graph is never mutated.
- ``GET /healthz``  liveness + the degradation story: 200 always while
                    the process lives, JSON body with status
                    ok|degraded|draining, open breakers, queue depth,
                    in-flight and per-status served counts.
- ``GET /readyz``   admission readiness: 200 once warmed and admitting,
                    503 while warming or draining (the LB drain signal).
- ``GET /metrics``  Prometheus exposition (obs/metrics.py registry).

Worker model: N daemon workers pull coalesced same-rung groups from the
admission queue. Execution always happens under a watchdog deadline
(`resilience/watchdog.call_with_deadline`): expiry answers 504 and
abandons the executing thread — a wedged alignment never wedges the
worker, which moves on to the next request. Every terminal disposition
publishes `abpoa_serve_requests_total{status}` + the request-latency
sketch and appends one archive record for `abpoa-tpu slo`.

With ``--pool-workers N`` (ABPOA_TPU_SERVE_POOL) requests execute in N
supervised worker PROCESSES instead (parallel/pool.py): the per-request
deadline becomes a hard worker SIGKILL (no abandoned-thread leak), a
native SIGSEGV/OOM costs one request's process — the supervisor respawns
it warm from the persistent XLA cache — and a request that crashes its
worker twice is quarantined as a poison job. /healthz grows a `pool`
block (live workers, pids, restarts/kills/requeues) so operators can
watch containment work.
"""
from __future__ import annotations

import argparse
import copy
import io
import json
import os
import re
import signal
import sys
import threading
import time
from typing import Dict, List, Optional

from .. import obs
from ..params import Params
from .admission import (AdmissionController, Job, default_deadline_s,
                        request_caps)

DEFAULT_PORT = 8673


def drain_grace_s() -> float:
    """How long SIGTERM waits for queued + in-flight work before giving
    up and exiting anyway (still rc=0: by then every answerable request
    has been answered or timed out)."""
    return float(os.environ.get("ABPOA_TPU_SERVE_DRAIN_S", "30"))


def max_body_bytes() -> int:
    return int(float(os.environ.get("ABPOA_TPU_SERVE_MAX_BODY_MB", "32"))
               * 1e6)


def spawn_ready_grace_s() -> float:
    """How long start() waits for pool workers' ready handshakes before
    admitting anyway (jobs queue safely against a still-spawning pool)."""
    return float(os.environ.get("ABPOA_TPU_SERVE_POOL_READY_S", "120"))


def _test_delay_s() -> float:
    """Artificial per-request service time (ABPOA_TPU_SERVE_DELAY_S) —
    the load/drain-test shim, same spirit as ABPOA_TPU_INJECT_HANG_S:
    makes "a request is in flight" a deterministic window instead of a
    race against a millisecond alignment."""
    return float(os.environ.get("ABPOA_TPU_SERVE_DELAY_S", "0"))


def map_max_qlen() -> int:
    """Longest read POST /map accepts (400 past it): bounds the Qp rungs
    a map deployment can be asked to serve, so the warmed signature set
    stays finite. ABPOA_TPU_MAP_MAX_QLEN overrides."""
    return int(os.environ.get("ABPOA_TPU_MAP_MAX_QLEN", "100000"))


def replica_name() -> Optional[str]:
    """This process's fleet replica name (ABPOA_TPU_REPLICA, set by the
    fleet supervisor at spawn). None outside a fleet."""
    return os.environ.get("ABPOA_TPU_REPLICA") or None


def churn_enabled_env() -> bool:
    """Continuous batching (PR 17): may in-flight split-lockstep groups
    accept same-rung joiners at round boundaries? Default on whenever the
    split lockstep path serves; ABPOA_TPU_SERVE_CHURN=0 pins the static
    pickup-time-only coalescing (the churn_gate baseline)."""
    return os.environ.get("ABPOA_TPU_SERVE_CHURN", "1").strip().lower() \
        not in ("0", "off", "false")


# inbound request ids (fleet router hop) must look like our own minted
# ids: hex-ish tokens, bounded — anything else is ignored and re-minted
_RID_RE = re.compile(r"^[A-Za-z0-9_-]{1,64}$")


def _inbound_rid(hdr: Optional[str]) -> Optional[str]:
    return hdr if hdr and _RID_RE.match(hdr) else None


def _inbound_attempt(hdr: Optional[str]) -> int:
    try:
        return max(1, min(99, int(hdr or 1)))
    except ValueError:
        return 1


def _request_record(job: Job, status: str, device: str) -> dict:
    """One archive record per terminal request — the field shapes
    `obs/slo.py` evaluates (reads, read_wall_ms, faults, total_wall_s),
    so a served window answers `abpoa-tpu slo` exactly like a batch
    window. 400/504 count one fault; a 429/503 is load shedding doing
    its job, not a fault."""
    wall = job.wall_s()
    per_read_ms = (round(1e3 * wall / job.n_reads, 4) if job.n_reads
                   and status == "ok" else None)
    rec = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "kind": "serve_request",
        "label": job.label,
        "device": device,
        "status": status,
        "total_wall_s": round(wall, 6),
        "deadline_s": job.deadline_s,
        "reads": job.n_reads if status == "ok" else 0,
        "read_wall_ms": ({"p50": per_read_ms, "p95": per_read_ms,
                          "p99": per_read_ms, "amortized": True}
                         if per_read_ms is not None else None),
        "faults": 1 if status in ("poisoned", "timeout", "error") else 0,
        "quarantined": 1 if status == "poisoned" else 0,
    }
    rep = replica_name()
    if rep:
        rec["replica"] = rep
    rec["attempt"] = job.attempt
    if job.kind == "map":
        # /map requests archive the same record shape (slo/why read them
        # verbatim); the workload tag lets a window be split by kind
        rec["workload"] = "map"
    if job.join_round is not None:
        # continuous batching: this request boarded an in-flight lockstep
        # group at a round boundary — `why` names the round it boarded
        # (the pickup-time coalesced_k tag is stale under churn)
        rec["join_round"] = job.join_round
        rec["join_group"] = job.join_group
    return rec


class _ServeChurnHook:
    """Round-boundary churn driver for ONE in-flight serve lockstep group
    (parallel/lockstep.ChurnHook protocol). Per round it evicts lanes
    whose deadline expired (504 at the boundary, not at group end), claims
    same-rung joiners from the admission queue onto freed lanes — priced
    against the LIVE group's bytes — and finishes each job the round its
    lane retires. Runs entirely on the worker thread driving the group."""

    def __init__(self, server: "AlignServer", abpt: Params, gid: int,
                 rung: int, k_cap: int) -> None:
        import itertools
        self.server = server
        self.abpt = abpt
        self.gid = gid
        self.rung = rung
        self.k_cap = max(1, k_cap)
        self.jobs: Dict[object, Job] = {}    # sid -> live job
        self.abs: Dict[object, object] = {}  # sid -> Abpoa container
        self.fallbacks: List[Job] = []       # need the sequential path
        self.closed = False
        self._sids = itertools.count(10_000)  # joiner sids, clear of 0..K-1

    def add_initial(self, sid, job: Job, ab) -> None:
        self.jobs[sid] = job
        self.abs[sid] = ab

    def live_bytes(self) -> int:
        return sum(j.est_bytes for j in self.jobs.values())

    # ------------------------------------------------- ChurnHook protocol
    def on_round(self, round_i: int, live_sids: list) -> tuple:
        server = self.server
        evict = set()
        for sid in live_sids:
            job = self.jobs.get(sid)
            if job is not None and job.remaining_s() <= 0:
                evict.add(sid)
                self.jobs.pop(sid, None)
                self.abs.pop(sid, None)
                obs.record_fault(
                    "request_timeout", detail=job.label,
                    action="evicted_at_round",
                    extra={"request_id": job.rid} if job.rid else None)
                if job.finish("timeout",
                              error="request deadline expired "
                                    f"(evicted at round {round_i})"):
                    server.account(job, "timeout")
                server.admission.mark_done(job)
        free = self.k_cap - (len(live_sids) - len(evict))
        joiners = []
        if free > 0 and not self.closed and not server.admission.closed:
            claimed = server.admission.claim_joiners(
                self.rung, free, live_bytes=self.live_bytes())
            for job in claimed:
                boarded = self._board(job, round_i)
                if boarded is not None:
                    joiners.append(boarded)
        server._open_group_update(
            self.gid, self.rung,
            self.k_cap - (len(live_sids) - len(evict)) - len(joiners),
            round_i, len(live_sids) - len(evict) + len(joiners))
        return evict, joiners

    def _board(self, job: Job, round_i: int):
        """Ingest one claimed joiner onto a lane; returns the driver
        (sid, seqs, weights) tuple or None (poisoned -> 400 here)."""
        from ..pipeline import Abpoa, _ingest_records
        from ..resilience import QUARANTINE_EXCEPTIONS
        from ..obs import metrics
        server = self.server
        try:
            ab = Abpoa()
            seqs, weights = _ingest_records(ab, self.abpt, job.records)
        except QUARANTINE_EXCEPTIONS as e:
            obs.record_fault("poisoned_set", detail=str(e)[:300],
                             action="rejected_400")
            if job.finish("poisoned", error=f"{type(e).__name__}: {e}"):
                server.account(job, "poisoned")
            server.admission.mark_done(job)
            return None
        sid = next(self._sids)
        self.jobs[sid] = job
        self.abs[sid] = ab
        job.join_round = round_i
        job.join_group = self.gid
        wait = max(0.0, (job.t_pickup or time.perf_counter())
                   - job.t_arrive)
        metrics.publish_join_wait(wait)
        if obs.trace_enabled():
            obs.trace.add_span(
                "admission_wait", "serve", job.t_arrive, wait,
                args={"coalesced_k": len(self.jobs), "rung": job.rung,
                      "join_round": round_i, "join_group": self.gid},
                req=(job.rid, 0) if job.rid else None)
        return (sid, seqs, weights)

    def on_retire(self, sid, result, round_i: int) -> None:
        from ..pipeline import output
        server = self.server
        job = self.jobs.pop(sid, None)
        ab = self.abs.pop(sid, None)
        if job is None:
            return
        service = max(0.0, time.perf_counter()
                      - (job.t_pickup or job.t_arrive))
        if result is None:
            # backtrack divergence (or off-rung reject): sequential path,
            # swept by _run_lockstep_churn after the group returns
            self.fallbacks.append(job)
            return
        try:
            pg, is_rc = result
            ab.graph = pg
            if self.abpt.amb_strand:
                for j, flag in enumerate(is_rc):
                    ab.is_rc[j] = flag
            ab.seqs = [""] * len(ab.seqs)
            buf = io.StringIO()
            output(ab, self.abpt, buf)
            if job.finish("ok", body=buf.getvalue()):
                server.account(job, "ok")
        except Exception as e:  # noqa: BLE001 — group must survive
            obs.record_fault("request_error", detail=str(e)[:300],
                             action="rejected_500")
            if job.finish("error", error=f"{type(e).__name__}: {e}"):
                server.account(job, "error")
        finally:
            server.admission.mark_done(job, service)


class _ServeMapHook:
    """Round-boundary streaming driver for ONE serve map group
    (parallel/map_driver.MapHook protocol). A map lane is a single READ,
    not a request: each /map request's reads queue onto lanes, every lane
    retires every round (zero fusion barrier — every boundary is a join
    point), and a request is answered the round its LAST read retires.
    Between rounds the hook claims queued same-rung map requests
    (admission.claim_joiners kind="map") so the group keeps serving as
    long as compatible reads keep arriving."""

    def __init__(self, server: "AlignServer", abpt: Params, gid: int,
                 rung: int, k_cap: int) -> None:
        from collections import deque
        self.server = server
        self.abpt = abpt
        self.gid = gid
        self.rung = rung
        self.k_cap = max(1, k_cap)
        self.states: Dict[int, dict] = {}   # job.id -> per-request state
        self.lane_q = deque()               # (job_id, read_idx) to board
        self.closed = False

    def add_job(self, job: Job) -> None:
        import numpy as np
        encode = self.abpt.char_to_code
        queries = [
            encode[np.frombuffer(r.seq.encode(), dtype=np.uint8)
                   ].astype(np.uint8)
            for r in job.records]
        self.states[job.id] = {"job": job, "queries": queries,
                               "results": [None] * len(queries),
                               "left": len(queries)}
        for idx in range(len(queries)):
            self.lane_q.append((job.id, idx))

    def live_bytes(self) -> int:
        return sum(st["job"].est_bytes for st in self.states.values())

    def _expire(self, job: Job) -> None:
        server = self.server
        obs.record_fault("request_timeout", detail=job.label,
                         action="evicted_at_round",
                         extra={"request_id": job.rid} if job.rid else None)
        if job.finish("timeout", error="request deadline expired "
                                       "(map reads still queued)"):
            server.account(job, "timeout")
        server.admission.mark_done(job)

    def _fill(self, out: list, free_slots: int) -> None:
        while self.lane_q and len(out) < free_slots:
            jid, idx = self.lane_q.popleft()
            st = self.states.get(jid)
            if st is None:
                continue
            job = st["job"]
            if job.remaining_s() <= 0:
                # boundary 504: drop the whole request — its other queued
                # reads are dead work (lanes already in flight this round
                # still retire into a finished job, harmlessly)
                self.states.pop(jid, None)
                self._expire(job)
                continue
            out.append(((jid, idx), st["queries"][idx]))

    # -------------------------------------------------- MapHook protocol
    def on_round(self, round_i: int, free_slots: int) -> list:
        from ..obs import metrics
        server = self.server
        out: list = []
        self._fill(out, free_slots)
        free = free_slots - len(out)
        if free > 0 and not self.closed and not server.admission.closed:
            claimed = server.admission.claim_joiners(
                self.rung, free, live_bytes=self.live_bytes(), kind="map")
            for job in claimed:
                job.join_round = round_i
                job.join_group = self.gid
                self.add_job(job)
                wait = max(0.0, (job.t_pickup or time.perf_counter())
                           - job.t_arrive)
                metrics.publish_join_wait(wait)
                if obs.trace_enabled():
                    obs.trace.add_span(
                        "admission_wait", "serve", job.t_arrive, wait,
                        args={"rung": job.rung, "join_round": round_i,
                              "join_group": self.gid, "kind": "map"},
                        req=(job.rid, 0) if job.rid else None)
            self._fill(out, free_slots)
        server._open_group_update(
            self.gid, self.rung, free_slots - len(out), round_i, len(out),
            kind="map")
        return out

    def on_retire(self, rid, outcome, round_i: int) -> None:
        jid, idx = rid
        st = self.states.get(jid)
        if st is None:
            return
        st["results"][idx] = outcome  # None = off-rung (host sweep below)
        st["left"] -= 1
        if st["left"] > 0:
            return
        self.states.pop(jid, None)
        self._answer(st)

    def _answer(self, st: dict) -> None:
        from ..io import gaf_record
        from ..parallel import map_read_host
        server = self.server
        job = st["job"]
        static = server._map_static
        service = max(0.0, time.perf_counter()
                      - (job.t_pickup or job.t_arrive))
        try:
            lines = []
            for rec, q, outcome in zip(job.records, st["queries"],
                                       st["results"]):
                if outcome is None:
                    # off-rung lane reject (can't normally happen: the
                    # request's rung bounds every read) — host alignment
                    # keeps the answer complete
                    res, strand = map_read_host(static.graph, self.abpt, q)
                    fallback = "map_off_rung"
                else:
                    res, strand, fallback = outcome
                lines.append(gaf_record(rec.name, q, res,
                                        static.base_by_nid, strand,
                                        comment=rec.comment or None))
            if job.finish("ok", body="".join(ln + "\n" for ln in lines)):
                server.account(job, "ok")
        except Exception as e:  # noqa: BLE001 — group must survive
            obs.record_fault("request_error", detail=str(e)[:300],
                             action="rejected_500")
            if job.finish("error", error=f"{type(e).__name__}: {e}"):
                server.account(job, "error")
        finally:
            server.admission.mark_done(job, service)


class AlignServer:
    """Owns the admission queue, the worker pool and the HTTP front.
    `start()` binds + warms + marks ready; `begin_drain()`/`drain()` is
    the SIGTERM path; `stop()` is the test-friendly full teardown."""

    def __init__(self, abpt: Params, host: str = "127.0.0.1", port: int = 0,
                 workers: int = 2, queue_depth: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 pool_workers: Optional[int] = None,
                 trace_dir: Optional[str] = None,
                 map_graph: Optional[str] = None,
                 mesh: Optional[int] = None) -> None:
        if not abpt._finalized:
            abpt = abpt.finalize()
        self.abpt = abpt
        # per-request tracing (PR 15): with --trace-dir, every sampled
        # request's span slice (ingress -> admission wait -> dispatch ->
        # pool worker and back) exports as one Perfetto-viewable Chrome
        # trace, cross-referenced from its archive record
        self._trace_dir = trace_dir or os.environ.get(
            "ABPOA_TPU_SERVE_TRACE_DIR") or None
        self.deadline_s = (deadline_s if deadline_s is not None
                           else default_deadline_s())
        # sharded route (PR 19): --mesh N / ABPOA_TPU_MESH spreads each
        # coalesced group's per-round dispatch over an N-device mesh; the
        # admission byte gate prices the whole mesh (each device holds
        # only its lane slice), and /healthz advertises the mesh shape
        from ..parallel.shard import requested_mesh_size
        self._mesh_req = requested_mesh_size(mesh)
        self._mesh = None           # jax Mesh, discovered in start()
        self.admission = AdmissionController(abpt, max_depth=queue_depth,
                                             mesh=max(self._mesh_req, 1))
        # process-isolated execution backend (parallel/pool.py): requests
        # run in supervised worker PROCESSES — a native crash or wedged
        # dispatch costs one job's process, never a serve worker thread.
        # 0 = execute in-thread as before (ABPOA_TPU_SERVE_POOL /
        # --pool-workers opt in).
        if pool_workers is None:
            pool_workers = int(os.environ.get("ABPOA_TPU_SERVE_POOL",
                                              "0") or 0)
        self._pool_n = max(0, pool_workers)
        self._pool = None
        self.draining = threading.Event()
        self.ready = threading.Event()
        self._stats: Dict[str, int] = {}
        self._stats_lock = threading.Lock()
        self._workers: List[threading.Thread] = []
        self._n_workers = max(1, workers)
        self._devices = None        # jax devices, set after warm
        self._lockstep = False
        self._lockstep_impl = ""    # "split" | "device" once routed
        # continuous batching (PR 17): in-flight split-lockstep groups
        # accept same-rung joiners at round boundaries. The open-group
        # registry backs /healthz's `open_groups` block (fleet routers
        # prefer replicas with a boardable group on the request's rung).
        self._churn = False
        # map workload (PR 18): a fixed graph restored ONCE at startup
        # (--map-graph), wrapped in StaticGraphTables, served by POST /map
        self._map_graph = map_graph or os.environ.get(
            "ABPOA_TPU_SERVE_MAP_GRAPH") or None
        self._map_static = None
        self._map_coalesce = False   # batched map groups (split driver)
        self._open_groups: Dict[int, dict] = {}
        self._open_lock = threading.Lock()
        import itertools
        self._group_ids = itertools.count()  # atomic across workers
        self.t_start = time.time()
        from http.server import ThreadingHTTPServer

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            # the default accept backlog (5) drops SYNs under an open-loop
            # burst long before admission control can answer 429 — shed
            # load must be shed with a status code, not a TCP reset
            request_queue_size = 128

        self._httpd = _Server((host, port), _make_handler(self))
        self.host, self.port = self._httpd.server_address[:2]

    # ---------------------------------------------------------- lifecycle
    def start(self, warm: str = "auto") -> None:
        """Bind is already done (constructor); spin the HTTP thread (so
        /healthz answers while warming), AOT-warm the ladder, then admit.
        warm: "quick" | "full" | "off" | "auto" (= quick on device
        backends, off on host kernels — there is nothing to compile)."""
        threading.Thread(target=self._httpd.serve_forever, daemon=True,
                         name="abpoa-serve-http").start()
        obs.start_run()
        if self._trace_dir:
            os.makedirs(self._trace_dir, exist_ok=True)
            obs.trace_enable()
            # per-request indexing: exports read the request's own slice
            # instead of scanning the whole ring per request
            obs.tracer().index_requests = True
            # materialize the family so "zero traces" reads as 0
            obs.count("serve.traces", 0)
        device_backend = self.abpt.device in ("jax", "tpu", "pallas")
        if warm == "auto":
            warm = "quick" if device_backend else "off"
        if device_backend:
            from ..utils.probe import apply_platform_pin, jax_backend_reachable
            if jax_backend_reachable():
                apply_platform_pin()
                if self._mesh_req >= 2:
                    # mesh discovery BEFORE warm/jax.devices(): the virtual
                    # CPU mesh pin must land before backend init. An
                    # unbuildable requested mesh is a startup error, not a
                    # silent unsharded fallback.
                    from ..obs import metrics as _metrics
                    from ..parallel.shard import discover_mesh
                    self._mesh = discover_mesh(self._mesh_req)
                    _metrics.publish_mesh(
                        self._mesh_req,
                        self._mesh.devices.flat[0].platform)
                if warm != "off":
                    from ..compile import warm_ladder
                    t0 = time.perf_counter()
                    summary = warm_ladder(tier=warm, abpt=self.abpt)
                    print(f"[abpoa-tpu serve] warm({warm}): "
                          f"{summary['signatures']} signatures, "
                          f"{summary['compiled']} compiled, "
                          f"{summary['persistent_cache_hits']} "
                          f"persistent-cache hits in "
                          f"{time.perf_counter() - t0:.1f}s",
                          file=sys.stderr)
                import jax
                self._devices = jax.devices()
                # ONE decision site with the -l batch path: the scheduler
                # plans whether coalesced groups form and which lockstep
                # implementation runs them (parallel/scheduler.py)
                from ..parallel import lockstep_group_size, plan_route
                route = plan_route(self.abpt, lockstep_group_size(),
                                   serve=True, mesh=self._mesh_req)
                self._lockstep = route.kind in ("lockstep", "sharded")
                self._lockstep_impl = route.impl
                # churn needs the split driver's host-side round
                # boundaries (the all-device loop has none to board at)
                self._churn = (self._lockstep
                               and self._lockstep_impl == "split"
                               and churn_enabled_env())
            else:
                print("[abpoa-tpu serve] Warning: JAX backend probe timed "
                      "out; serving on the host engine.", file=sys.stderr)
        if self._map_graph:
            # restore the map graph ONCE — every /map request maps
            # against these immutable tables; the restore (not the
            # requests) pays the graph-plane price
            from ..parallel import load_static_graph, plan_route
            t0 = time.perf_counter()
            _ab, self._map_static = load_static_graph(self._map_graph,
                                                      self.abpt)
            route = plan_route(self.abpt, 1, workload="map",
                               mesh=self._mesh_req)
            self._map_coalesce = route.kind in ("map", "sharded")
            print(f"[abpoa-tpu serve] map graph {self._map_graph}: "
                  f"{self._map_static.n_rows - 2} nodes restored in "
                  f"{time.perf_counter() - t0:.1f}s "
                  f"(route {route.kind}: {route.reason})", file=sys.stderr)
        if self._pool_n:
            # spawned AFTER the warm so fresh workers (including every
            # respawn after a kill) load the rungs the warm just wrote to
            # the persistent XLA cache instead of recompiling
            from ..parallel import WorkerPool
            self._pool = WorkerPool(self._pool_n, self.abpt, label="serve")
            self._pool.start()
            self._pool.wait_ready(timeout=spawn_ready_grace_s())
            # coalesced lockstep groups stay in-process; the pool is the
            # per-request containment backend (CPU hosts foremost)
            self._lockstep = False
            self._churn = False
        for i in range(self._n_workers):
            t = threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"abpoa-serve-worker-{i}")
            t.start()
            self._workers.append(t)
        self.ready.set()

    def begin_drain(self) -> None:
        self.draining.set()
        self.admission.close_intake()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait for queued + in-flight work to finish; returns True when
        fully drained within the grace."""
        if timeout is None:
            timeout = drain_grace_s()
        ok = self.admission.wait_drained(timeout)
        for t in self._workers:
            t.join(timeout=2.0)
        if self._pool is not None:
            # queue already drained above: workers finish their frame,
            # answer the shutdown handshake, and exit clean
            self._pool.close(graceful=True)
        return ok

    def shutdown_http(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def stop(self) -> bool:
        """Full teardown (tests): drain, then close the socket."""
        self.begin_drain()
        ok = self.drain()
        self.shutdown_http()
        return ok

    # ---------------------------------------------------------- accounting
    def bump(self, status: str, wall_s: float) -> None:
        """One terminal disposition that never became a Job (handler-side
        429/503/parse-400): stats + metric families, no archive record."""
        from ..obs import metrics
        with self._stats_lock:
            self._stats[status] = self._stats.get(status, 0) + 1
        metrics.publish_serve_request(status, wall_s)

    def account(self, job: Job, status: str) -> None:
        """Single definition of an admitted job's terminal disposition:
        per-status stats, the serve metric families, one archive record
        carrying the request id and (when produced) the per-request trace
        and harvested flight-dump paths — the cross-references `abpoa-tpu
        slo` offenders and `abpoa-tpu why` resolve."""
        self.bump(status, job.wall_s())
        rec = _request_record(job, status, self.abpt.device)
        if self._mesh is not None:
            # `why` renders "sharded K=<cap> over mesh=<n>" from these
            rec["mesh"] = int(self._mesh.devices.size)
            rec["route"] = "sharded"
            from ..parallel import lockstep_group_size
            rec["k_cap"] = self._sharded_k_cap(
                lockstep_group_size(),
                "map" if job.kind == "map" else "lockstep")
            # shard-skew attribution (obs/rounds.py): the newest sharded
            # round's straggler + skew land on the record so `why` can
            # name the slowest shard without access to this process's ring
            skew = obs.rounds.skew_summary()
            if skew:
                rec["slowest_shard"] = skew["slowest_shard"]
                rec["shard_skew"] = skew["shard_skew"]
                rec["round_wall_ms"] = skew["round_wall_ms"]
        rec["request_id"] = job.rid or None
        if job.dumps:
            rec["dump_file"] = job.dumps[-1]
        tf = self._export_trace(job, status)
        if tf:
            rec["trace_file"] = tf
        obs.archive.append_record(rec)

    def _traced(self, rid: str) -> bool:
        """THE per-request tracing decision — one definition site for the
        ingress registration, the pool ship-spans flag, and the export
        (three copies would drift and produce traces with missing
        halves)."""
        return bool(self._trace_dir and rid and obs.sampled(rid))

    def _export_trace(self, job: Job, status: str) -> Optional[str]:
        """Write this request's span slice as one Chrome trace under
        --trace-dir (sampled, bounded; obs/trace.export_request_trace).
        The terminal `request` envelope span is recorded here so every
        exported trace brackets ingress -> terminal disposition."""
        if not self._traced(job.rid):
            if job.rid:
                obs.tracer().take_request(job.rid)  # drop any indexed slice
            return None
        obs.trace.add_span("request", "serve", job.t_arrive, job.wall_s(),
                           args={"status": status, "reads": job.n_reads,
                                 "deadline_s": job.deadline_s},
                           req=(job.rid, 0))
        taken = obs.tracer().take_request(job.rid)
        evs, idx_dropped = taken if taken is not None else (None, 0)
        meta = {"status": status, "label": job.label,
                "device": self.abpt.device}
        if idx_dropped:
            meta["indexed_events_dropped"] = idx_dropped
        path = obs.export_request_trace(
            self._trace_dir, job.rid, extra_meta=meta, events=evs)
        if path:
            obs.count("serve.traces")
        return path

    def stats(self) -> Dict[str, int]:
        with self._stats_lock:
            return dict(self._stats)

    def health(self) -> dict:
        from ..resilience import breaker
        depth, inflight = self.admission.snapshot()
        # snapshot first: a half-open probe may reclose (delete a key)
        # while we iterate
        degraded = {b: st["to"] for b, st in dict(breaker().open).items()}
        status = ("draining" if self.draining.is_set()
                  else "degraded" if degraded else "ok")
        out = {"status": status, "degraded": degraded or None,
               "queue_depth": depth, "inflight": inflight,
               "served": self.stats(), "device": self.abpt.device,
               "uptime_s": round(time.time() - self.t_start, 1)}
        rep = replica_name()
        if rep:
            out["replica"] = rep
            out["pid"] = os.getpid()
        if self._pool is not None:
            # worker pids included so an operator (or the smoke harness)
            # can kill a worker and watch the supervisor respawn it
            out["pool"] = self._pool.snapshot()
        if self._churn or self._map_coalesce:
            # boardable in-flight lockstep groups: the fleet router's
            # rung-affinity signal (plan_placement prefers a replica whose
            # open group can seat the request's rung without a new group
            # — and only same-KIND groups: a map request can't board a
            # consensus group or vice versa)
            out["open_groups"] = self.open_groups_snapshot()
        if self._map_static is not None:
            out["map_graph"] = {"path": self._map_graph,
                                "nodes": self._map_static.n_rows - 2,
                                "batched": self._map_coalesce}
        if self._mesh is not None:
            # the fleet router's capacity signal: a sharded replica's
            # group K-caps (and its byte budget) span the whole mesh
            out["mesh"] = {"devices": int(self._mesh.devices.size),
                           "platform": self._mesh.devices.flat[0].platform,
                           "axis": "set"}
        return out

    # ------------------------------------------------- open-group registry
    def _open_group_update(self, gid: int, rung: int, free: int,
                           round_i: int, live: int,
                           kind: str = "consensus") -> None:
        with self._open_lock:
            self._open_groups[gid] = {"id": gid, "rung": rung,
                                      "free": max(0, free),
                                      "round": round_i, "live": live,
                                      "kind": kind}

    def _open_group_close(self, gid: int) -> None:
        with self._open_lock:
            self._open_groups.pop(gid, None)

    def open_groups_snapshot(self) -> List[dict]:
        with self._open_lock:
            return [dict(g) for g in self._open_groups.values()]

    # ---------------------------------------------------------- execution
    def _sharded_k_cap(self, base_k: int, route: str) -> int:
        """The coalesced group's K cap: the per-chip noop cap, scaled to
        the whole mesh under the sharded route (per-route feedback:
        scheduler state is keyed by the observing route)."""
        from ..parallel import scheduler as _sched
        if self._mesh is not None:
            return (int(self._mesh.devices.size)
                    * _sched.noop_k_cap(base_k, route="sharded"))
        return _sched.noop_k_cap(base_k, route=route)

    def _worker_loop(self) -> None:
        from ..parallel import lockstep_group_size
        from ..parallel import scheduler as _sched
        coalescing = self._lockstep or self._map_coalesce
        base_k = lockstep_group_size() if coalescing else 1
        route = "map" if (self._map_coalesce
                          and not self._lockstep) else "lockstep"
        while True:
            # divergence feedback: measured noop_set_fraction re-caps the
            # next coalesced group's K (scheduler.noop_k_cap). Groups are
            # kind-homogeneous (next_group filters on head.kind), so one
            # loop serves both /align and /map pickups.
            max_k = (self._sharded_k_cap(base_k, route)
                     if coalescing else 1)
            group = self.admission.next_group(
                max_k=max_k, coalesce=coalescing,
                min_qlen=(_sched.lockstep_min_qlen()
                          if self._lockstep else 0))
            if not group:
                # intake closed + queue empty = no work can ever arrive
                # again: exit NOW, even while a sibling worker still has
                # a request in flight — spinning here would steal CPU
                # from the very request the drain is waiting on
                if (self.admission.closed
                        and self.admission.snapshot()[0] == 0):
                    return
                continue
            try:
                self._process_group(group)
            except BaseException:  # noqa: BLE001 — the worker must survive
                import traceback
                traceback.print_exc()
                for job in group:
                    if job.finish("error", error="internal worker error"):
                        self.account(job, "error")
                    self.admission.mark_done(job)

    def _process_group(self, group: List[Job]) -> None:
        """Run one coalesced group to terminal status. Never raises for
        per-request fault shapes: poisoned -> 400, deadline -> 504,
        anything else -> 500 + fault record, and the worker lives on."""
        # the admission wait ends at pickup: record it per request (with
        # the coalesced group size — a 504 whose budget drained here must
        # say so, and behind WHAT), before expiry decides anything
        if obs.trace_enabled():
            for job in group:
                end = job.t_pickup or time.perf_counter()
                obs.trace.add_span(
                    "admission_wait", "serve", job.t_arrive,
                    max(0.0, end - job.t_arrive),
                    args={"coalesced_k": len(group), "rung": job.rung},
                    req=(job.rid, 0) if job.rid else None)
        # expire jobs that aged out while queued — their client already
        # gave up; executing them would burn capacity on dead work
        live: List[Job] = []
        for job in group:
            if job.remaining_s() <= 0:
                obs.record_fault("request_timeout", detail=job.label,
                                 action="expired_in_queue",
                                 extra={"request_id": job.rid} if job.rid
                                 else None)
                if job.finish("timeout",
                              error="deadline expired in admission queue"):
                    self.account(job, "timeout")
                self.admission.mark_done(job)
            else:
                live.append(job)
        if not live:
            return
        # per-group Params copy: msa() mutates its Params (device reroute,
        # batch bookkeeping) and workers run concurrently
        abpt = copy.deepcopy(self.abpt)
        if live[0].kind == "map":
            self._run_map_group(live, abpt)
            return
        if self._churn and all(j.eligible for j in live):
            from ..parallel import scheduler as _sched
            head = live[0]
            # below the serial-wins crossover a lockstep lane only slows
            # the request down — static serial path, no group to board
            if not head.qmax or head.qmax >= _sched.lockstep_min_qlen():
                self._run_lockstep_churn(live, abpt)
                return
        if len(live) > 1:
            self._run_lockstep(live, abpt)
            return
        job = live[0]
        t0 = time.perf_counter()
        try:
            self._finish_single(job, abpt)
        finally:
            self.admission.mark_done(job, time.perf_counter() - t0)

    def _finish_single(self, job: Job, abpt: Params) -> None:
        """Execute ONE job to terminal status under its deadline. No
        admission bookkeeping here — the caller owns mark_done (the
        lockstep fallback path re-enters with accounting already open)."""
        from ..resilience import QUARANTINE_EXCEPTIONS
        from ..resilience.watchdog import DispatchTimeout, call_with_deadline
        remaining = job.remaining_s()
        if remaining <= 0:
            # the budget is already spent (e.g. a group dispatch consumed
            # it before this fallback): answer 504 NOW — passing <= 0 to
            # call_with_deadline would mean "unsupervised", the opposite
            obs.record_fault("request_timeout", detail=job.label,
                             action="expired_before_fallback")
            if job.finish("timeout", error="request deadline expired"):
                self.account(job, "timeout")
            return
        if self._pool is not None:
            self._finish_single_pool(job, remaining)
            return
        rid_extra = {"request_id": job.rid} if job.rid else None
        try:
            # in-thread execution runs under the request context so every
            # span down to dp:<backend>/compile:<fn> carries the id (the
            # executing thread re-enters the context in _run_single; the
            # outer ctx here tags the watchdog's own expiry instant)
            with obs.request_ctx(job.rid):
                body = call_with_deadline(
                    lambda: self._run_single(job, abpt),
                    deadline_s=remaining, label=job.label)
            if job.finish("ok", body=body):
                self.account(job, "ok")
        except DispatchTimeout:
            obs.record_fault("request_timeout", detail=job.label,
                            action="worker_abandoned", extra=rid_extra)
            if job.finish("timeout", error="request deadline expired"):
                self.account(job, "timeout")
        except QUARANTINE_EXCEPTIONS as e:
            # quarantine semantics: a poisoned set is a 400 for THIS
            # request, never a crashed worker
            obs.record_fault("poisoned_set", detail=str(e)[:300],
                            action="rejected_400", extra=rid_extra)
            if job.finish("poisoned", error=f"{type(e).__name__}: {e}"):
                self.account(job, "poisoned")
        except Exception as e:  # noqa: BLE001 — worker must survive
            obs.record_fault("request_error", detail=str(e)[:300],
                            action="rejected_500", extra=rid_extra)
            print(f"[abpoa-tpu serve] {job.label} failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            if job.finish("error", error=f"{type(e).__name__}: {e}"):
                self.account(job, "error")

    def _finish_single_pool(self, job: Job, remaining: float) -> None:
        """Execute ONE job in the process pool. The pool's deadline is a
        hard worker SIGKILL (504, thread AND process reclaimed — the
        in-thread path could only abandon); a crashed worker retries the
        job once on a fresh process, a second crash quarantines it as a
        poison job (500 + structured fault record). Worker-side
        quarantine exceptions keep their 400 contract."""
        pj = self._pool.submit("records", (list(job.records),),
                               label=job.label, deadline_s=remaining,
                               est_bytes=job.est_bytes,
                               rid=job.rid, trace=self._traced(job.rid))
        pj.done.wait()
        # harvested flight dumps (killed/crashed attempts) follow the
        # request into its archive record
        job.dumps.extend(pj.dumps)
        if pj.status == "ok":
            q = pj.result.get("quarantined")
            if q:
                # the fault record was written in the worker (run_records)
                # and already merged into this report — recording here
                # would double-count the same event against the SLO
                # fault budget
                if job.finish("poisoned", error=f"{q[0]}: {q[1]}"):
                    self.account(job, "poisoned")
            else:
                if job.finish("ok", body=pj.result.get("text", "")):
                    self.account(job, "ok")
        elif pj.status == "timeout":
            # the pool already recorded this event (worker_killed at the
            # deadline SIGKILL, or job_deadline when the budget expired
            # before dispatch) — a serve-side record would double-count
            # one 504 against the SLO fault budget, unlike the in-thread
            # path whose single request_timeout record is the only one
            if job.finish("timeout", error="request deadline expired "
                                           "(worker hard-killed)"):
                self.account(job, "timeout")
        elif pj.status == "poison":
            # fault record already written by the pool supervisor
            if job.finish("error", error=f"poison job quarantined: "
                                         f"{pj.error}"):
                self.account(job, "error")
        else:  # "error" / "cancelled"
            obs.record_fault("request_error", detail=str(pj.error)[:300],
                             action="rejected_500")
            if job.finish("error", error=pj.error or "pool unavailable"):
                self.account(job, "error")

    def _run_single(self, job: Job, abpt: Params) -> str:
        from ..pipeline import Abpoa, msa
        with obs.request_ctx(job.rid), \
                obs.span("execute", "serve", args={"label": job.label}):
            delay = _test_delay_s()
            if delay:
                time.sleep(delay)
            buf = io.StringIO()
            msa(Abpoa(), abpt, job.records, buf)
            return buf.getvalue()

    def _run_lockstep(self, jobs: List[Job], abpt: Params) -> None:
        """Coalesced same-rung group on an accelerator mesh: ingest each
        request into its own graph container, dispatch ONE vmapped
        lockstep group (`parallel.flush_lockstep_group` — the exact `-l`
        batch path, watchdog/breaker/guards included), emit each result
        independently. Jobs the device path dropped fall back to the
        sequential runner one by one."""
        from ..pipeline import Abpoa, _ingest_records, output
        from ..resilience import QUARANTINE_EXCEPTIONS
        from ..resilience.watchdog import DispatchTimeout, call_with_deadline
        from ..parallel import flush_lockstep_group
        t0 = time.perf_counter()
        entries = []
        by_idx: Dict[int, Job] = {}
        for i, job in enumerate(jobs):
            try:
                ab = Abpoa()
                seqs, weights = _ingest_records(ab, abpt, job.records)
                entries.append((i, ab, seqs, weights))
                by_idx[i] = job
            except QUARANTINE_EXCEPTIONS as e:
                obs.record_fault("poisoned_set", detail=str(e)[:300],
                                 action="rejected_400")
                if job.finish("poisoned", error=f"{type(e).__name__}: {e}"):
                    self.account(job, "poisoned")
                self.admission.mark_done(job)
        if not entries:
            return
        gi = next(self._group_ids)
        # the group dispatch is bounded by the TIGHTEST member's budget
        # (it must not overshoot anyone's deadline); on expiry only the
        # out-of-budget jobs answer 504 — the rest still have time and
        # fall back to sequential execution under their own deadlines
        deadline = min(by_idx[i].remaining_s() for i, *_ in entries)
        if deadline <= 0:
            # ingest already consumed the tightest budget: a <= 0
            # deadline would run the group UNSUPERVISED (watchdog treats
            # it as disabled) — route everyone through the sequential
            # path instead, where expiry is an immediate 504 and live
            # jobs keep their own supervised deadlines
            for i, *_ in entries:
                job = by_idx[i]
                try:
                    self._finish_single(job, copy.deepcopy(self.abpt))
                finally:
                    self.admission.mark_done(job)
            return
        try:
            results = call_with_deadline(
                lambda: flush_lockstep_group(
                    entries, abpt, self._devices, gi,
                    impl=self._lockstep_impl or None, mesh=self._mesh),
                deadline_s=deadline, label=f"serve_group:{gi}")
        except DispatchTimeout:
            for i, *_ in entries:
                job = by_idx[i]
                try:
                    if job.remaining_s() <= 0:
                        obs.record_fault("request_timeout",
                                         detail=job.label,
                                         action="worker_abandoned")
                        if job.finish("timeout",
                                      error="request deadline expired"):
                            self.account(job, "timeout")
                    else:
                        self._finish_single(job, copy.deepcopy(self.abpt))
                finally:
                    self.admission.mark_done(job)
            return
        share = (time.perf_counter() - t0) / max(1, len(entries))
        for i, ab, _seqs, _weights in entries:
            job = by_idx[i]
            try:
                if i in results:
                    buf = io.StringIO()
                    output(results[i], abpt, buf)
                    if job.finish("ok", body=buf.getvalue()):
                        self.account(job, "ok")
                else:
                    # device path dropped this set: the sequential path
                    # is the same fallback the -l batch runner takes
                    self._finish_single(job, copy.deepcopy(self.abpt))
            finally:
                self.admission.mark_done(job, share)

    def _run_lockstep_churn(self, jobs: List[Job], abpt: Params) -> None:
        """Continuous batching: run the picked group through the split
        driver with a round-boundary churn hook. Lanes retire the round
        they finish (their jobs are answered mid-group), expired lanes are
        evicted as boundary 504s, and same-rung queue arrivals board freed
        lanes (admission.claim_joiners, live-group byte pricing) — the
        group keeps serving as long as compatible work keeps arriving.
        Accepts a single-job group: it OPENS a group that later arrivals
        join, which is the whole point. No outer call_with_deadline: the
        per-lane boundary eviction answers individual deadlines, and a
        wedged dispatch is contained by the dispatch-level watchdog inside
        guarded_device_call (failure -> per-job sweep below)."""
        from ..pipeline import Abpoa, _ingest_records
        from ..resilience import DispatchFailed, QUARANTINE_EXCEPTIONS
        from ..parallel import flush_lockstep_group_churn
        entries = []
        gi = next(self._group_ids)
        from ..parallel import lockstep_group_size
        hook = _ServeChurnHook(self, abpt, gi, jobs[0].rung,
                               self._sharded_k_cap(lockstep_group_size(),
                                                   "lockstep"))
        for i, job in enumerate(jobs):
            try:
                ab = Abpoa()
                seqs, weights = _ingest_records(ab, abpt, job.records)
                entries.append((i, ab, seqs, weights))
                hook.add_initial(i, job, ab)
            except QUARANTINE_EXCEPTIONS as e:
                obs.record_fault("poisoned_set", detail=str(e)[:300],
                                 action="rejected_400")
                if job.finish("poisoned", error=f"{type(e).__name__}: {e}"):
                    self.account(job, "poisoned")
                self.admission.mark_done(job)
        if not entries:
            return
        self._open_group_update(gi, hook.rung,
                                hook.k_cap - len(entries), 0, len(entries))
        try:
            flush_lockstep_group_churn(entries, abpt, self._devices, gi,
                                       hook, mesh=self._mesh)
        except (DispatchFailed, RuntimeError) as e:
            print(f"Warning: churn lockstep group {gi} failed ({e}); "
                  "sweeping members to the sequential path.",
                  file=sys.stderr)
            obs.count("fallback.lockstep_to_sequential")
        finally:
            hook.closed = True
            self._open_group_close(gi)
        # sweep: bt-err fallbacks, plus any lane the dispatch failure left
        # unanswered — each runs the sequential path under its own
        # remaining deadline (_finish_single answers 504 when spent)
        leftovers = hook.fallbacks + list(hook.jobs.values())
        hook.fallbacks = []
        hook.jobs.clear()
        hook.abs.clear()
        for job in leftovers:
            try:
                self._finish_single(job, copy.deepcopy(self.abpt))
            finally:
                self.admission.mark_done(job)

    # ----------------------------------------------------------- map (/map)
    def _run_map_group(self, jobs: List[Job], abpt: Params) -> None:
        """Run one picked map group: every request's reads stream through
        the shared static-graph driver (parallel/map_driver.py) with a
        round-boundary hook that answers each request the round its last
        read retires and claims queued same-rung /map requests onto freed
        lanes — every round, because every map lane frees every round."""
        from ..parallel import lockstep_group_size, map_reads_split
        from ..resilience import DispatchFailed
        if not self._map_coalesce:
            # host route (no batched DP backend): per-read oracle, one
            # request at a time under its own deadline
            for job in jobs:
                try:
                    self._finish_map_single(job, abpt)
                finally:
                    self.admission.mark_done(job)
            return
        gid = next(self._group_ids)
        hook = _ServeMapHook(self, abpt, gid, jobs[0].rung,
                             self._sharded_k_cap(lockstep_group_size(),
                                                 "map"))
        for job in jobs:
            hook.add_job(job)
        self._open_group_update(gid, hook.rung, hook.k_cap, 0, 0,
                                kind="map")
        try:
            map_reads_split(self._map_static, [], abpt,
                            k_cap=hook.k_cap, hook=hook, Qp=hook.rung,
                            mesh=self._mesh)
        except (DispatchFailed, RuntimeError) as e:
            print(f"Warning: map group {gid} failed ({e}); sweeping "
                  "members to the host path.", file=sys.stderr)
            obs.count("fallback.map_to_host")
        finally:
            hook.closed = True
            self._open_group_close(gid)
        # sweep: any request the dispatch failure left unanswered runs
        # the per-read host path under its own remaining deadline
        leftovers = [st["job"] for st in hook.states.values()]
        hook.states.clear()
        for job in leftovers:
            try:
                self._finish_map_single(job, abpt)
            finally:
                self.admission.mark_done(job)

    def _finish_map_single(self, job: Job, abpt: Params) -> None:
        """ONE /map request on the host path (no batched backend, or the
        group dispatch failed): per-read oracle alignments under the
        request deadline — same GAF bytes as the batched route."""
        from ..resilience.watchdog import DispatchTimeout, call_with_deadline
        remaining = job.remaining_s()
        if remaining <= 0:
            obs.record_fault("request_timeout", detail=job.label,
                             action="expired_in_queue",
                             extra={"request_id": job.rid} if job.rid
                             else None)
            if job.finish("timeout", error="request deadline expired"):
                self.account(job, "timeout")
            return
        rid_extra = {"request_id": job.rid} if job.rid else None
        try:
            with obs.request_ctx(job.rid):
                body = call_with_deadline(
                    lambda: self._run_map_host(job, abpt),
                    deadline_s=remaining, label=job.label)
            if job.finish("ok", body=body):
                self.account(job, "ok")
        except DispatchTimeout:
            obs.record_fault("request_timeout", detail=job.label,
                             action="worker_abandoned", extra=rid_extra)
            if job.finish("timeout", error="request deadline expired"):
                self.account(job, "timeout")
        except Exception as e:  # noqa: BLE001 — worker must survive
            obs.record_fault("request_error", detail=str(e)[:300],
                             action="rejected_500", extra=rid_extra)
            if job.finish("error", error=f"{type(e).__name__}: {e}"):
                self.account(job, "error")

    def _run_map_host(self, job: Job, abpt: Params) -> str:
        import numpy as np
        from ..io import gaf_record
        from ..parallel import map_read_host
        static = self._map_static
        encode = abpt.char_to_code
        lines = []
        with obs.request_ctx(job.rid), \
                obs.span("execute", "serve", args={"label": job.label,
                                                   "kind": "map"}):
            for rec in job.records:
                q = encode[np.frombuffer(rec.seq.encode(), dtype=np.uint8)
                           ].astype(np.uint8)
                t_r = time.perf_counter()
                with obs.phase("align"):
                    res, strand = map_read_host(static.graph, abpt, q)
                obs.count("map.reads")
                obs.record_read(time.perf_counter() - t_r, len(q),
                                2 * len(q) + 1, abpt.device)
                lines.append(gaf_record(rec.name, q, res,
                                        static.base_by_nid, strand,
                                        comment=rec.comment or None))
        return "".join(ln + "\n" for ln in lines)


def _make_handler(server: AlignServer):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # ------------------------------------------------------ plumbing
        def _send(self, code: int, body: bytes, ctype: str,
                  headers: Optional[dict] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass  # client gave up; its job already reached terminal

        def _json(self, code: int, obj: dict,
                  headers: Optional[dict] = None) -> None:
            self._send(code, (json.dumps(obj) + "\n").encode(),
                       "application/json", headers)

        def log_message(self, *a):  # request spam stays off stderr
            pass

        # ------------------------------------------------------ GET
        def do_GET(self):  # noqa: N802 — http.server API
            path = self.path.rstrip("/")
            if path == "/healthz":
                self._json(200, server.health())
            elif path == "/readyz":
                if server.draining.is_set():
                    self._json(503, {"status": "draining"})
                elif not server.ready.is_set():
                    self._json(503, {"status": "warming"})
                else:
                    self._json(200, {"status": "ready"})
            elif path == "/metrics":
                from ..obs import metrics
                self._send(200, metrics.registry().render().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            else:
                self._json(404, {"error": f"unknown path {self.path!r}"})

        # ------------------------------------------------------ POST
        def do_POST(self):  # noqa: N802 — http.server API
            path = self.path.rstrip("/")
            if path not in ("/align", "/map"):
                self._json(404, {"error": f"unknown path {self.path!r}"})
                return
            is_map = path == "/map"
            # the request id is minted at INGRESS — before parsing, before
            # admission — and every disposition (shed, poisoned, served)
            # answers with it, so a client-side latency outlier is
            # directly greppable into traces/dumps/archive records. A
            # fleet router hop carries the id it already minted (plus the
            # attempt number) so failover/hedge deliveries share one id
            # across replica archives.
            rid = (_inbound_rid(self.headers.get("X-Abpoa-Request-Id"))
                   or obs.new_request_id())
            attempt = _inbound_attempt(self.headers.get("X-Abpoa-Attempt"))
            rh = {"X-Abpoa-Request-Id": rid,
                  "X-Abpoa-Attempt": str(attempt)}
            rep = replica_name()
            if rep:
                rh["X-Abpoa-Replica"] = rep
            if server.draining.is_set():
                # the body was never read: close the connection, or a
                # keep-alive client's unread bytes would parse as its
                # next request line
                self.close_connection = True
                server.bump("draining", 0.0)
                self._json(503, {"error": "server is draining"},
                           {"Retry-After": "30", **rh})
                return
            try:
                n = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                # body length unknowable -> body unread -> must close
                self.close_connection = True
                server.bump("poisoned", 0.0)
                self._json(400, {"error": "malformed Content-Length"}, rh)
                return
            if n > max_body_bytes():
                self.close_connection = True  # body unread, same as above
                server.bump("oversized", 0.0)
                self._json(413, {"error": f"body {n} B exceeds the "
                                          f"{max_body_bytes()} B limit"},
                           rh)
                return
            if is_map and server._map_static is None:
                self.close_connection = True  # body unread
                server.bump("poisoned", 0.0)
                self._json(400, {"error": "no map graph loaded; start "
                                          "serve with --map-graph FILE"},
                           rh)
                return
            raw = self.rfile.read(n) if n else b""
            t0 = time.perf_counter()
            try:
                job = (self._parse_map_job(raw, rid, attempt) if is_map
                       else self._parse_job(raw, rid, attempt))
            except Exception as e:  # malformed body: 400, never a crash
                server.bump("poisoned", time.perf_counter() - t0)
                obs.record_fault("poisoned_set", detail=str(e)[:300],
                                 action="rejected_400",
                                 extra={"request_id": rid})
                self._json(400, {"error": f"{type(e).__name__}: {e}"}, rh)
                return
            # register for indexed span collection BEFORE the job becomes
            # visible to dispatch workers: a fast request could otherwise
            # be fully accounted (slice taken) before registration, and
            # the late-registered entry would leak forever
            traced = server._traced(rid)
            if traced:
                obs.tracer().begin_request(rid)
            admitted, reason, retry_after = server.admission.try_admit(job)
            if not admitted:
                if traced:
                    obs.tracer().take_request(rid)   # never dispatched
                status = "draining" if reason == "draining" else "rejected"
                server.bump(status, job.wall_s())
                code = 503 if reason == "draining" else 429
                self._json(code, {"error": f"admission rejected: {reason}"},
                           {"Retry-After": str(int(max(1, retry_after))),
                            **rh})
                return
            # wait for the worker verdict; the slack covers worker pickup
            # and the watchdog's own bookkeeping — the worker-side
            # deadline is authoritative
            if not job.done.wait(job.deadline_s + 10.0):
                if job.finish("timeout", error="server lost the request"):
                    server.account(job, "timeout")
            status = job.status
            if status == "ok":
                self._send(200, job.body.encode(),
                           "text/x-gaf" if is_map else "text/x-fasta",
                           {"X-Abpoa-Reads": str(job.n_reads), **rh})
            elif status == "poisoned":
                self._json(400, {"error": job.error}, rh)
            elif status == "timeout":
                self._json(504, {"error": job.error or
                                 "request deadline expired"}, rh)
            else:
                self._json(500, {"error": job.error or "internal error"},
                           rh)

        def _parse_job(self, raw: bytes, rid: str = "",
                       attempt: int = 1) -> Job:
            from ..io.fastx import read_fastx_text
            from ..resilience import validate_records
            from ..resilience.memory import estimate_bytes
            from ..align.eligibility import fused_eligible
            from ..compile.ladder import qp_rung
            records = read_fastx_text(raw.decode("utf-8", errors="strict"))
            # same validation the -l quarantine boundary applies — a
            # poisoned set costs a parse, never a worker
            validate_records(records, server.abpt)
            caps = request_caps(server.abpt, records)
            deadline = server.deadline_s
            hdr = self.headers.get("X-Abpoa-Deadline-S")
            if hdr:
                try:
                    deadline = min(deadline, float(hdr))
                except ValueError:
                    pass
            qmax = max(len(r.seq) for r in records)
            return Job(records, rung=qp_rung(qmax),
                       est_bytes=estimate_bytes(caps),
                       eligible=fused_eligible(server.abpt, len(records)),
                       deadline_s=deadline, rid=rid, attempt=attempt,
                       qmax=qmax)

        def _parse_map_job(self, raw: bytes, rid: str = "",
                           attempt: int = 1) -> Job:
            from ..io.fastx import read_fastx_text
            from ..resilience import validate_records
            from ..compile.ladder import qp_rung
            from .admission import map_request_bytes
            records = read_fastx_text(raw.decode("utf-8", errors="strict"))
            validate_records(records, server.abpt)
            cap = map_max_qlen()
            for r in records:
                if len(r.seq) > cap:
                    # oversized-read 400: a read past the map length cap
                    # would force an off-ladder Qp rung the warmer never
                    # precompiled — reject at the door, not on a lane
                    raise ValueError(
                        f"read {r.name!r} is {len(r.seq)} bp, over the "
                        f"map read cap {cap} bp "
                        "(ABPOA_TPU_MAP_MAX_QLEN)")
            deadline = server.deadline_s
            hdr = self.headers.get("X-Abpoa-Deadline-S")
            if hdr:
                try:
                    deadline = min(deadline, float(hdr))
                except ValueError:
                    pass
            qmax = max(len(r.seq) for r in records)
            # per-read pricing: the static graph plane is NOT in this
            # request's bill — it was paid once at restore
            return Job(records, rung=qp_rung(qmax),
                       est_bytes=map_request_bytes(
                           server.abpt, records,
                           server._map_static.n_rows),
                       eligible=server._map_coalesce,
                       deadline_s=deadline, rid=rid, attempt=attempt,
                       qmax=qmax, kind="map")

    return Handler


# --------------------------------------------------------------------------- #
# CLI entry                                                                   #
# --------------------------------------------------------------------------- #

def _build_parser() -> argparse.ArgumentParser:
    from .. import constants as C
    ap = argparse.ArgumentParser(
        prog="abpoa-tpu serve",
        description="persistent aligner service: POST FASTA/FASTQ to "
                    "/align, scrape /metrics, watch /healthz//readyz; "
                    "admission-bounded (429 + Retry-After past the queue "
                    "or memory budget), per-request deadlines (504), "
                    "poisoned-set isolation (400), graceful drain on "
                    "SIGTERM")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT,
                    help="listen port; 0 picks an ephemeral port "
                         "[%(default)s]")
    ap.add_argument("--workers", type=int,
                    default=min(4, os.cpu_count() or 1),
                    help="alignment worker threads [%(default)s]")
    ap.add_argument("--replicas", type=int, default=None, metavar="N",
                    help="run N supervised serve replicas behind a "
                         "failover router instead of one process "
                         "(serve/fleet.py); SIGHUP rolling-restarts the "
                         "fleet [single process]")
    ap.add_argument("--pool-workers", type=int, default=None, metavar="N",
                    help="execute requests in N supervised worker "
                         "PROCESSES (parallel/pool.py): crash "
                         "containment, hard-kill deadlines instead of "
                         "thread abandonment, poison-job quarantine; "
                         "0 = in-thread execution "
                         "[ABPOA_TPU_SERVE_POOL or 0]")
    ap.add_argument("--queue-depth", type=int, default=None,
                    help="admission queue bound "
                         "[ABPOA_TPU_SERVE_QUEUE or 64]")
    ap.add_argument("--deadline-s", type=float, default=None,
                    help="per-request wall deadline "
                         "[ABPOA_TPU_SERVE_DEADLINE_S or 30]")
    ap.add_argument("--warm", choices=["auto", "quick", "full", "off"],
                    default="auto",
                    help="AOT-precompile the bucket ladder before "
                         "admitting [auto: quick on device backends]")
    ap.add_argument("--metrics", type=str, nargs="?", metavar="FILE",
                    default=None, const="",
                    help="also maintain the Prometheus textfile exporter "
                         "(the `abpoa-tpu top` feed) "
                         "[FILE defaults to ~/.cache/abpoa_tpu/"
                         "metrics.prom]")
    ap.add_argument("--trace-dir", type=str, default=None, metavar="DIR",
                    help="write one Perfetto-viewable Chrome trace per "
                         "sampled request (ABPOA_TPU_TRACE_SAMPLE, "
                         "default 1.0) into DIR — spans cross the "
                         "admission queue and the pool-worker pipe under "
                         "one request id; `abpoa-tpu why <id>` renders "
                         "them [ABPOA_TPU_SERVE_TRACE_DIR]")
    ap.add_argument("--map-graph", type=str, default=None, metavar="FILE",
                    help="restore FILE (abPOA GFA or MSA FASTA — the -i "
                         "formats) ONCE at startup and serve POST /map: "
                         "fixed-graph read mapping, one GAF record per "
                         "read [ABPOA_TPU_SERVE_MAP_GRAPH]")
    ap.add_argument("--device", type=str, default="auto",
                    help="DP backend: auto | numpy | native | jax | "
                         "pallas [%(default)s]")
    ap.add_argument("--lockstep", type=str, default="auto",
                    choices=["auto", "on", "off"],
                    help="coalesce same-rung requests into vmapped "
                         "lockstep dispatches [auto: accelerator only]")
    ap.add_argument("--mesh", type=int, default=None, metavar="N",
                    help="shard each coalesced group's per-round dispatch "
                         "over an N-device lane mesh (the sharded route; "
                         "K caps and the admission byte gate scale by N; "
                         "1-core hosts get the virtual CPU mesh only on "
                         "this explicit request) [ABPOA_TPU_MESH]")
    ap.add_argument("-m", "--aln-mode", type=int, default=C.GLOBAL_MODE)
    ap.add_argument("-M", "--match", type=int, default=C.DEFAULT_MATCH)
    ap.add_argument("-X", "--mismatch", type=int, default=C.DEFAULT_MISMATCH)
    ap.add_argument("-O", "--gap-open", type=str, default=None)
    ap.add_argument("-E", "--gap-ext", type=str, default=None)
    ap.add_argument("-r", "--result", type=int, default=C.OUT_CONS)
    ap.add_argument("-a", "--cons-algrm", type=int, default=C.CONS_HB)
    ap.add_argument("-d", "--maxnum-cons", type=int, default=1)
    ap.add_argument("-q", "--min-freq", type=float, default=C.MULTIP_MIN_FREQ)
    return ap


def _params_from_args(args) -> Params:
    # the -O/-E/-r decoding is cli.py's, shared — serve flags can never
    # silently diverge from the batch CLI's meaning of the same flag
    from ..cli import apply_gap_args, apply_result_mode
    abpt = Params()
    abpt.align_mode = args.aln_mode
    abpt.match = args.match
    abpt.mismatch = args.mismatch
    apply_gap_args(abpt, args.gap_open, args.gap_ext)
    if not apply_result_mode(abpt, args.result):
        raise ValueError(f"unknown output result mode: {args.result}")
    abpt.cons_algrm = args.cons_algrm
    if not 1 <= args.maxnum_cons <= 10:
        # same bound the batch CLI enforces for -d
        raise ValueError("max number of consensus sequences should be 1~10")
    abpt.max_n_cons = args.maxnum_cons
    abpt.min_freq = args.min_freq
    abpt.device = args.device
    abpt.lockstep = args.lockstep
    return abpt


def serve_main(argv) -> int:
    """`abpoa-tpu serve` — run the service until SIGTERM/SIGINT, then
    drain: stop admitting (503), finish in-flight, flush metrics and the
    report archive, exit 0."""
    args = _build_parser().parse_args(argv)
    if args.replicas is not None and args.replicas > 1:
        # multi-replica service: same flags, fleet supervisor + router
        from .fleet import fleet_main
        return fleet_main(argv)
    try:
        abpt = _params_from_args(args).finalize()
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    metrics_path = None
    try:
        server = AlignServer(abpt, host=args.host, port=args.port,
                             workers=args.workers,
                             queue_depth=args.queue_depth,
                             deadline_s=args.deadline_s,
                             pool_workers=args.pool_workers,
                             trace_dir=args.trace_dir,
                             map_graph=args.map_graph,
                             mesh=args.mesh)
    except OSError as e:
        print(f"Error: cannot bind {args.host}:{args.port}: {e}",
              file=sys.stderr)
        return 1
    stop_evt = threading.Event()

    def _on_signal(signum, _frame):
        print(f"[abpoa-tpu serve] signal {signum}: draining "
              "(no new admissions; in-flight requests finish)",
              file=sys.stderr)
        server.begin_drain()
        stop_evt.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    if hasattr(signal, "SIGHUP"):
        # the fleet's rolling restart drains one replica at a time with
        # SIGHUP: for a single process it is the same graceful drain the
        # LB-friendly SIGTERM path runs (finish in-flight, then exit 0)
        signal.signal(signal.SIGHUP, _on_signal)
    try:
        # the line operators (and the smoke harness) wait for: the bind
        # already happened in the constructor, so the port is
        # authoritative here (--port 0 picks ephemeral) — printed BEFORE
        # the AOT warm, which can take minutes on a cold cache; /readyz
        # answers 503 until warm completes
        pool_note = (f", pool={server._pool_n} procs" if server._pool_n
                     else "")
        print(f"[abpoa-tpu serve] listening on "
              f"http://{server.host}:{server.port} "
              f"(workers={args.workers}{pool_note}, queue="
              f"{server.admission._max_depth}, "
              f"deadline={server.deadline_s:.0f}s, device={abpt.device})",
              file=sys.stderr, flush=True)
        server.start(warm=args.warm)
        if args.metrics is not None:
            metrics_path = args.metrics or obs.metrics.default_textfile_path()
            os.makedirs(os.path.dirname(metrics_path) or ".", exist_ok=True)
            obs.metrics.start_textfile_exporter(metrics_path)
        stop_evt.wait()
        drained = server.drain()
        server.shutdown_http()
        if not drained:
            print("[abpoa-tpu serve] Warning: drain grace expired with "
                  "work still in flight (answers already sent or timed "
                  "out)", file=sys.stderr)
    finally:
        if metrics_path is not None:
            obs.metrics.stop_textfile_exporter()
        # the final process report is one more archive record: the
        # served window's roll-up next to its per-request records
        rep = obs.finalize_report()
        obs.archive.append_report(rep, label="serve", device=abpt.device)
    served = server.stats()
    total = sum(served.values())
    print(f"[abpoa-tpu serve] drained clean: {total} requests "
          + " ".join(f"{k}={v}" for k, v in sorted(served.items())),
          file=sys.stderr)
    return 0
