"""Replica fleet supervisor: `abpoa-tpu fleet --replicas N`.

Spawns N `abpoa-tpu serve` processes (same flags, same persistent XLA
compile cache — replica 1 pays each rung's compile once, the rest hit
the cache), fronted by one serve/router.py FleetRouter that owns the
public socket. The supervisor is the process-lifecycle half:

- **spawn**: each replica gets ``--port 0`` (the supervisor learns the
  ephemeral port from the replica's own "listening on" line),
  ``ABPOA_TPU_REPLICA=rI`` so its archive records, response headers and
  /healthz name it, and ``ABPOA_TPU_ARCHIVE_DIR=<base>/replica-rI`` so
  replica archives never interleave (`slo --fleet` / `why` merge them
  back).
- **liveness**: a dead process (crash, OOM-kill, SIGKILL chaos) is
  respawned under the same exponential backoff the worker pool uses
  (`parallel.pool.restart_backoff_s`); a WEDGED replica — process alive
  but /healthz unanswered past ABPOA_TPU_FLEET_STALL_S — is SIGKILLed
  first, then respawned. Fast-crash loops back off instead of spinning.
- **rolling restart**: SIGHUP to the supervisor drains and restarts one
  replica at a time — each waits for the fleet to be back at FULL
  strength before the next drain begins, so ready capacity never drops
  below N-1. The replica itself gets SIGHUP, which serve treats as the
  same graceful drain as SIGTERM.
- **fleet drain**: SIGTERM/SIGINT stops router admissions (503 +
  Retry-After), SIGTERMs every replica, waits for their graceful
  drains, and exits 0 — the single-process contract, fleet-wide.

`--metrics` maintains a textfile with the MERGED fleet exposition
(router scrape roll-up via `metrics.merge_expositions`), so one
`abpoa-tpu top` watches the whole fleet.
"""
from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from .. import obs
from ..obs import archive
from ..parallel.pool import WorkerPool, restart_backoff_s

# the same spawn budget the worker pool retires a slot under
MAX_SPAWN_FAILURES = WorkerPool.MAX_SPAWN_FAILURES
from .router import FleetRouter
from .server import _build_parser

_LISTEN_RE = re.compile(r"listening on http://([^\s:]+):(\d+)")

# a replica that survives this long has left its crash loop behind
_STABLE_S = 30.0
# grace for a SIGHUP/SIGTERM drain before the supervisor hard-kills
_DRAIN_GRACE_S = 45.0


def stall_s() -> float:
    """Heartbeat ceiling: a live process whose /healthz has not answered
    for this long is wedged and gets SIGKILL + respawn. 0 disables."""
    return float(os.environ.get("ABPOA_TPU_FLEET_STALL_S", "60"))


def _replica_argv(argv: List[str]) -> List[str]:
    """The serve argv a replica inherits: everything the operator passed
    minus the fleet-level flags (--replicas, --host/--port which belong
    to the ROUTER socket, and --metrics which the fleet rolls up)."""
    out: List[str] = []
    skip = False
    for i, a in enumerate(argv):
        if skip:
            skip = False
            continue
        if a.startswith(("--replicas=", "--host=", "--port=", "--metrics=")):
            continue
        if a in ("--replicas", "--host", "--port"):
            skip = True
            continue
        if a == "--metrics":
            # nargs="?": consume the value only when one follows
            nxt = argv[i + 1] if i + 1 < len(argv) else None
            skip = nxt is not None and not nxt.startswith("-")
            continue
        out.append(a)
    return out


class Replica:
    """One supervised serve process."""

    __slots__ = ("index", "name", "proc", "port", "base_url",
                 "consec_deaths", "spawned_at", "respawn_at", "respawns",
                 "gone")

    def __init__(self, index: int) -> None:
        self.index = index
        self.name = f"r{index}"
        self.proc: Optional[subprocess.Popen] = None
        self.port = 0
        self.base_url = ""
        self.consec_deaths = 0
        self.spawned_at = 0.0
        self.respawn_at = 0.0
        self.respawns = 0
        self.gone = False            # crash-looped past the spawn budget


def default_replica_cmd(index: int, name: str,
                        serve_argv: List[str]) -> List[str]:
    return [sys.executable, "-m", "abpoa_tpu.cli", "serve",
            "--host", "127.0.0.1", "--port", "0"] + serve_argv


class FleetSupervisor:
    """Owns the router + N replica processes until the fleet drains.

    `replica_cmd(index, name, serve_argv) -> argv` is injectable so
    tests can supervise a fake replica (anything that prints the
    "listening on http://host:port" line on stderr and serves HTTP)
    without paying serve startup N times.
    """

    def __init__(self, n: int, serve_argv: Optional[List[str]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 replica_cmd: Optional[Callable] = None,
                 archive_base: Optional[str] = None,
                 timeout_s: float = 75.0) -> None:
        if n < 2:
            raise ValueError("a fleet needs --replicas >= 2")
        self.n = n
        self.serve_argv = list(serve_argv or [])
        self.replica_cmd = replica_cmd or default_replica_cmd
        self.archive_base = archive_base or archive.archive_dir()
        self.router = FleetRouter(host=host, port=port, timeout_s=timeout_s)
        self.router.health_extra = self._health_extra
        self.replicas = [Replica(i) for i in range(n)]
        self.stop_evt = threading.Event()
        self.hup_evt = threading.Event()
        self._rolling = False
        self._lock = threading.Lock()

    # ------------------------------------------------------------ health
    def _health_extra(self) -> dict:
        return {"fleet": {
            "replicas": self.n,
            "respawns": sum(r.respawns for r in self.replicas),
            "rolling_restart": self._rolling,
            "pids": {r.name: (r.proc.pid if r.proc else None)
                     for r in self.replicas},
        }}

    # ------------------------------------------------------------ spawn
    def _spawn(self, r: Replica) -> None:
        env = dict(os.environ)
        env["ABPOA_TPU_REPLICA"] = r.name
        env["ABPOA_TPU_ARCHIVE_DIR"] = os.path.join(
            self.archive_base, f"replica-{r.name}")
        cmd = self.replica_cmd(r.index, r.name, self.serve_argv)
        try:
            r.proc = subprocess.Popen(cmd, env=env, text=True,
                                      stderr=subprocess.PIPE)
        except OSError as e:
            print(f"[abpoa-tpu fleet] {r.name}: spawn failed: {e}",
                  file=sys.stderr)
            r.proc = None
            r.consec_deaths += 1
            r.respawn_at = (time.monotonic()
                            + restart_backoff_s(r.consec_deaths))
            return
        r.port = 0
        r.base_url = ""
        r.spawned_at = time.monotonic()
        threading.Thread(target=self._pump_stderr, args=(r, r.proc),
                         daemon=True,
                         name=f"abpoa-fleet-stderr-{r.name}").start()

    def _pump_stderr(self, r: Replica, proc: subprocess.Popen) -> None:
        # forward replica stderr under its name; the first "listening on"
        # line is the port handshake that puts the replica into placement
        assert proc.stderr is not None
        for line in proc.stderr:
            line = line.rstrip("\n")
            m = _LISTEN_RE.search(line)
            if m and not r.base_url and proc is r.proc:
                r.port = int(m.group(2))
                r.base_url = f"http://{m.group(1)}:{r.port}"
                self.router.set_replica(r.name, r.base_url, pid=proc.pid)
            print(f"[{r.name}] {line}", file=sys.stderr)

    # ------------------------------------------------------------ deaths
    def _on_death(self, r: Replica, rc: Optional[int],
                  expected: bool = False) -> None:
        self.router.drop_replica(r.name)
        now = time.monotonic()
        if expected or now - r.spawned_at > _STABLE_S:
            r.consec_deaths = 1
        else:
            r.consec_deaths += 1
        r.proc = None
        r.respawns += 1
        if not expected and r.consec_deaths > MAX_SPAWN_FAILURES:
            # the pool's spawn budget: a replica that can't survive its
            # own startup is quarantined so the rest of the fleet keeps
            # serving instead of burning CPU on a crash loop
            r.gone = True
            print(f"[abpoa-tpu fleet] {r.name}: died {r.consec_deaths}x "
                  "in a row during startup — giving up on this replica "
                  "slot (fleet continues degraded)", file=sys.stderr)
            return
        backoff = 0.0 if expected else restart_backoff_s(r.consec_deaths)
        r.respawn_at = now + backoff
        print(f"[abpoa-tpu fleet] {r.name}: "
              + ("drained for restart" if expected
                 else f"died rc={rc} (respawn in {backoff:.1f}s, "
                      f"attempt {r.consec_deaths})"),
              file=sys.stderr)

    def _tick(self) -> None:
        now = time.monotonic()
        limit = stall_s()
        for r in self.replicas:
            if r.gone:
                continue
            if r.proc is None:
                if now >= r.respawn_at:
                    self._spawn(r)
                continue
            rc = r.proc.poll()
            if rc is not None:
                self._on_death(r, rc)
                continue
            if limit > 0 and r.base_url and now - r.spawned_at > limit:
                view = next((v for v in self.router.views()
                             if v.name == r.name), None)
                last = max(view.last_ok if view else 0.0, r.spawned_at)
                if now - last > limit:
                    print(f"[abpoa-tpu fleet] {r.name}: wedged "
                          f"(no heartbeat for {now - last:.0f}s) — "
                          "SIGKILL + respawn", file=sys.stderr)
                    try:
                        r.proc.kill()
                    except OSError:
                        pass

    # ------------------------------------------------------------ rolling
    def _alive(self) -> List[Replica]:
        return [r for r in self.replicas if not r.gone]

    def _wait_ready(self, name: str, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and not self.stop_evt.is_set():
            if any(v.name == name and v.ready and not v.draining
                   for v in self.router.views()):
                return True
            time.sleep(0.1)
        return False

    def rolling_restart(self, ready_timeout: float = 300.0) -> None:
        """Drain + respawn one replica at a time; the next drain waits
        for the previous replica to be READY again, so the fleet never
        serves with fewer than N-1 ready replicas."""
        self._rolling = True
        try:
            for r in self._alive():
                if self.stop_evt.is_set():
                    return
                proc = r.proc
                if proc is None:
                    continue
                self.router.mark_draining(r.name, True)
                try:
                    proc.send_signal(signal.SIGHUP)
                except OSError:
                    pass
                try:
                    proc.wait(timeout=_DRAIN_GRACE_S)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
                self._on_death(r, 0, expected=True)
                self._spawn(r)
                if not self._wait_ready(r.name, ready_timeout):
                    print(f"[abpoa-tpu fleet] {r.name}: not ready "
                          f"{ready_timeout:.0f}s after rolling respawn — "
                          "halting the rolling restart (fleet stays at "
                          "current strength)", file=sys.stderr)
                    return
                print(f"[abpoa-tpu fleet] {r.name}: rolled", file=sys.stderr)
        finally:
            self._rolling = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        self.router.start()
        for r in self.replicas:
            self._spawn(r)

    def shutdown(self) -> None:
        """Fleet drain: stop router admissions, SIGTERM every replica,
        wait for their graceful drains (hard-kill past the grace)."""
        self.stop_evt.set()
        self.router.begin_drain()
        procs = [(r, r.proc) for r in self.replicas if r.proc is not None]
        for _r, p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + _DRAIN_GRACE_S
        for r, p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
            r.proc = None
        self.router.stop()

    def run_forever(self, tick_s: float = 0.2) -> None:
        while not self.stop_evt.is_set():
            if self.hup_evt.is_set() and not self._rolling:
                self.hup_evt.clear()
                threading.Thread(target=self.rolling_restart, daemon=True,
                                 name="abpoa-fleet-rolling").start()
            self._tick()
            self.stop_evt.wait(tick_s)


def fleet_main(argv) -> int:
    """`abpoa-tpu fleet` (also `serve --replicas N`) — supervise N serve
    replicas behind the failover router until SIGTERM, then drain the
    whole fleet and exit 0. SIGHUP rolling-restarts one replica at a
    time, never dropping below N-1 ready."""
    ap = _build_parser()
    ap.prog = "abpoa-tpu fleet"
    args = ap.parse_args(argv)
    n = args.replicas if args.replicas is not None else 2
    try:
        sup = FleetSupervisor(n, serve_argv=_replica_argv(list(argv)),
                              host=args.host, port=args.port)
    except (ValueError, OSError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except Exception as e:  # router bind failures surface as OSError
        print(f"Error: {e}", file=sys.stderr)
        return 1

    def _on_stop(signum, _frame):
        print(f"[abpoa-tpu fleet] signal {signum}: draining the fleet",
              file=sys.stderr)
        sup.stop_evt.set()

    signal.signal(signal.SIGTERM, _on_stop)
    signal.signal(signal.SIGINT, _on_stop)
    if hasattr(signal, "SIGHUP"):
        signal.signal(signal.SIGHUP,
                      lambda *_: sup.hup_evt.set())
    # router socket is bound in the FleetRouter constructor, so this line
    # is authoritative — printed before any replica is ready, same
    # contract as serve's own listening line
    print(f"[abpoa-tpu fleet] listening on "
          f"http://{sup.router.host}:{sup.router.port} "
          f"(replicas={n}, archive base={sup.archive_base})",
          file=sys.stderr, flush=True)
    sup.start()

    metrics_stop: Optional[threading.Event] = None
    if args.metrics is not None:
        path = args.metrics or obs.metrics.default_textfile_path()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        metrics_stop = threading.Event()

        def _roll():
            while not metrics_stop.wait(2.0):
                try:
                    tmp = path + ".tmp"
                    with open(tmp, "w") as fp:
                        fp.write(sup.router.merged_exposition())
                    os.replace(tmp, path)
                except OSError:
                    pass

        threading.Thread(target=_roll, daemon=True,
                         name="abpoa-fleet-metrics").start()

    try:
        sup.run_forever()
    finally:
        sup.shutdown()
        if metrics_stop is not None:
            metrics_stop.set()
            try:
                with open(path, "w") as fp:
                    fp.write(obs.metrics.registry().render())
            except OSError:
                pass
    routed = sup.router.stats()
    total = sum(routed.values())
    print(f"[abpoa-tpu fleet] drained clean: {total} requests "
          + " ".join(f"{k}={v}" for k, v in sorted(routed.items()))
          + f"  respawns={sum(r.respawns for r in sup.replicas)}",
          file=sys.stderr)
    return 0
