"""Fleet router: the HTTP front that makes N serve replicas one service.

One stdlib ThreadingHTTPServer forwards ``POST /align`` to ready
replicas; everything that makes a fleet better than N ports is here:

- **placement**: ready replicas ranked by observed load (the
  `abpoa_serve_queue_depth`/inflight the health poller scrapes, plus the
  router's own in-flight deltas — the same queue-pressure inputs
  `scheduler.plan_route` weighs), with compile-rung affinity as the
  tie-break so same-rung requests keep hitting warm caches.
- **failover**: a transport error (connection reset, replica death
  mid-request) triggers exactly ONE retry on a sibling, re-sent under
  the SAME request id with the attempt number bumped — both replicas'
  archives record their attempt, and `abpoa-tpu why --fleet` narrates
  the hop. Alignment is pure, so a duplicate execution is harmless; the
  first terminal response wins and the loser is read and discarded.
- **hedged retries**: past a latency-sketch-derived delay (p95-based,
  ABPOA_TPU_FLEET_HEDGE_S overrides) a single duplicate goes to the next
  candidate; first response wins, the duplicate's answer is discarded
  idempotently. Bounded: at most one hedge per request, never while a
  failover is already in flight.
- **shed propagation**: a replica's 429/503 spills the request to the
  next untried candidate; when every candidate sheds, the LAST shed
  response's status and Retry-After propagate verbatim — the fleet's
  backpressure story is exactly the single process's.
- connection semantics match the single-process path bit for bit:
  draining 503 / malformed Content-Length 400 / oversized 413 are
  answered by the ROUTER with `Connection: close` (the body was never
  read); proxied responses keep the connection alive (the router always
  read the client body first), so a keep-alive client can never desync
  through the proxy hop.

`GET /metrics` answers the FLEET exposition: every ready replica's
scrape merged with the router's own families through
`metrics.merge_expositions` — counters sum, LogSketch histograms merge
bucket-wise, quantile gauges are recomputed from the merged sketch.
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from .. import obs
from ..obs import metrics
from .server import _inbound_rid, max_body_bytes


def poll_interval_s() -> float:
    return float(os.environ.get("ABPOA_TPU_FLEET_POLL_S", "0.3"))


def hedge_delay_s(sketch) -> Optional[float]:
    """When to launch the straggler hedge: ABPOA_TPU_FLEET_HEDGE_S forces
    a delay ("off"/"0" disables); otherwise 2x the router's observed p95
    once the sketch has enough mass to mean anything. None = no hedging
    (cold router: better no hedge than a hedge storm at the wrong
    threshold)."""
    env = os.environ.get("ABPOA_TPU_FLEET_HEDGE_S")
    if env is not None:
        env = env.strip().lower()
        if env in ("", "0", "off", "none"):
            return None
        return float(env)
    if sketch.count < 20:
        return None
    return max(0.05, 2.0 * sketch.quantile(0.95))


def _body_rung(body: bytes) -> Optional[int]:
    """Placement-affinity rung from the raw request body: the longest
    non-header line approximates qmax well enough to pick the replica
    whose compile cache is already warm at that rung (jax-free, like
    admission's own pricing)."""
    try:
        from ..compile.ladder import qp_rung
        qmax = max((len(ln) for ln in body.split(b"\n")
                    if ln and not ln.startswith((b">", b"@", b"+", b";"))),
                   default=0)
        return qp_rung(max(1, qmax)) if qmax else None
    except Exception:
        return None


class ReplicaView:
    """The router's health-poller view of one replica."""

    __slots__ = ("name", "base_url", "pid", "ready", "draining",
                 "queue_depth", "inflight", "local_inflight", "last_rung",
                 "last_ok", "health")

    def __init__(self, name: str, base_url: str, pid: int = 0) -> None:
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.pid = pid
        self.ready = False
        self.draining = False
        self.queue_depth = 0
        self.inflight = 0
        self.local_inflight = 0     # router-launched, not yet answered
        self.last_rung: Optional[int] = None
        self.last_ok = 0.0          # monotonic ts of the last health poll
        self.health: dict = {}

    def snapshot(self) -> dict:
        return {"name": self.name, "url": self.base_url, "pid": self.pid,
                "ready": self.ready, "draining": self.draining,
                "queue_depth": self.queue_depth, "inflight": self.inflight,
                "local_inflight": self.local_inflight}

    def open_group_rungs(self, kind: str = "consensus") -> set:
        """Rungs with a boardable in-flight group of this KIND on this
        replica (free lane + continuous batching on), from the last
        scraped /healthz `open_groups` block. A request placed here joins
        at the group's next round boundary instead of waiting out a fresh
        one. Groups are kind-homogeneous (PR 18): a /map request can only
        board a map group, so affinity filters on the advertised kind
        (absent = consensus, pre-PR-18 replicas)."""
        try:
            return {int(g["rung"]) for g in self.health.get(
                "open_groups") or ()
                if int(g.get("free") or 0) > 0
                and str(g.get("kind") or "consensus") == kind}
        except (TypeError, ValueError, KeyError):
            return set()


def plan_placement(views: List[ReplicaView],
                   rung: Optional[int] = None,
                   kind: str = "consensus") -> List[ReplicaView]:
    """Candidate order for one request: ready, non-draining replicas by
    ascending observed load (scraped queue depth + inflight + the
    router's own unanswered sends), rung affinity breaking ties.

    Affinity is three-tiered (PR 17): a replica advertising an OPEN
    same-rung lockstep group with a free lane (healthz `open_groups`)
    outranks one that merely served this rung last (warm compile cache),
    which outranks the rest — a request placed on tier 0 boards an
    in-flight group at its next round boundary. Load still dominates:
    affinity never outranks a shorter queue. Tier 0 only matches groups
    of the request's KIND (map vs consensus, PR 18): seating a /map
    request behind a consensus group's drain would be anti-affinity."""
    ready = [v for v in views if v.ready and not v.draining]

    def key(v: ReplicaView):
        if rung is None:
            affinity = 2
        elif rung in v.open_group_rungs(kind):
            affinity = 0
        elif v.last_rung == rung:
            affinity = 1
        else:
            affinity = 2
        return (v.queue_depth + v.inflight + v.local_inflight,
                affinity, v.name)

    return sorted(ready, key=key)


class _Outcome:
    """One routed request's terminal answer."""

    __slots__ = ("code", "body", "headers", "replica", "attempt",
                 "failovers", "hedges", "hedge_won")

    def __init__(self, code: int, body: bytes, headers: Dict[str, str],
                 replica: str = "", attempt: int = 1, failovers: int = 0,
                 hedges: int = 0, hedge_won: bool = False) -> None:
        self.code = code
        self.body = body
        self.headers = headers
        self.replica = replica
        self.attempt = attempt
        self.failovers = failovers
        self.hedges = hedges
        self.hedge_won = hedge_won


class FleetRouter:
    """Owns the front socket, the replica views and the health poller.
    The fleet supervisor (serve/fleet.py) registers replicas as it spawns
    them and re-registers on respawn (new port)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout_s: float = 75.0) -> None:
        self.timeout_s = timeout_s
        self.draining = threading.Event()
        self._views: Dict[str, ReplicaView] = {}
        self._lock = threading.Lock()
        self._stats: Dict[str, int] = {}
        self.health_extra: Optional[Callable[[], dict]] = None
        reg = metrics.registry()
        self._c_requests = reg.counter(
            "abpoa_fleet_requests_total",
            "Routed fleet requests by terminal status")
        self._c_failovers = reg.counter(
            "abpoa_fleet_failovers_total",
            "Requests re-sent to a sibling after a replica transport "
            "failure (exactly once per request)")
        self._c_hedges = reg.counter(
            "abpoa_fleet_hedges_total",
            "Straggler hedges launched (duplicate send, first wins)")
        self._c_hedge_wins = reg.counter(
            "abpoa_fleet_hedge_wins_total",
            "Hedged duplicates that answered before the primary")
        self._c_spills = reg.counter(
            "abpoa_fleet_shed_spills_total",
            "Requests spilled to a sibling after a replica shed (429/503)")
        self._g_ready = reg.gauge(
            "abpoa_fleet_replicas_ready",
            "Replicas currently passing /readyz")
        self._hist = reg.histogram(
            "abpoa_fleet_request_seconds",
            "Router-side end-to-end request latency (log-bucket sketch, "
            f"~{int(metrics.LogSketch.RELATIVE_ERROR * 100)}% quantile "
            "tolerance)")
        self.sketch = self._hist.sketch
        from http.server import ThreadingHTTPServer

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            request_queue_size = 128  # same shed-not-reset story as serve

        self._httpd = _Server((host, port), _make_router_handler(self))
        self.host, self.port = self._httpd.server_address[:2]
        self._poll_stop = threading.Event()
        self._poller: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        threading.Thread(target=self._httpd.serve_forever, daemon=True,
                         name="abpoa-fleet-http").start()
        self._poller = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="abpoa-fleet-poller")
        self._poller.start()

    def begin_drain(self) -> None:
        self.draining.set()

    def stop(self) -> None:
        self._poll_stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()

    # ------------------------------------------------------------ replicas
    def set_replica(self, name: str, base_url: str, pid: int = 0) -> None:
        """Register/replace one replica endpoint (respawn = new port)."""
        with self._lock:
            self._views[name] = ReplicaView(name, base_url, pid)

    def drop_replica(self, name: str) -> None:
        with self._lock:
            self._views.pop(name, None)

    def mark_draining(self, name: str, draining: bool) -> None:
        """Rolling restart: take one replica out of placement before its
        SIGHUP so no request races the drain window."""
        with self._lock:
            v = self._views.get(name)
            if v is not None:
                v.draining = draining

    def views(self) -> List[ReplicaView]:
        with self._lock:
            return list(self._views.values())

    def ready_count(self) -> int:
        return sum(1 for v in self.views() if v.ready and not v.draining)

    # ------------------------------------------------------------ polling
    def _poll_once(self, v: ReplicaView) -> None:
        try:
            with urllib.request.urlopen(v.base_url + "/readyz",
                                        timeout=2.0) as r:
                ready = r.status == 200
                r.read()
        except urllib.error.HTTPError as e:
            e.read()
            ready = False
        except (urllib.error.URLError, OSError):
            v.ready = False
            return
        try:
            with urllib.request.urlopen(v.base_url + "/healthz",
                                        timeout=2.0) as r:
                doc = json.loads(r.read().decode())
            v.queue_depth = int(doc.get("queue_depth") or 0)
            v.inflight = int(doc.get("inflight") or 0)
            v.health = doc
            v.last_ok = time.monotonic()
        except (urllib.error.URLError, OSError, ValueError):
            v.ready = False
            return
        v.ready = ready

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(poll_interval_s()):
            for v in self.views():
                self._poll_once(v)
            self._g_ready.set(self.ready_count())

    def poll_now(self) -> None:
        """One synchronous poll sweep (tests, startup)."""
        for v in self.views():
            self._poll_once(v)
        self._g_ready.set(self.ready_count())

    # ------------------------------------------------------------ stats
    def bump(self, status: str) -> None:
        with self._lock:
            self._stats[status] = self._stats.get(status, 0) + 1
        self._c_requests.inc(1, status=status)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def health(self) -> dict:
        out = {"status": ("draining" if self.draining.is_set() else "ok"),
               "role": "fleet-router",
               "replicas": [v.snapshot() for v in self.views()],
               "ready": self.ready_count(),
               "routed": self.stats()}
        if self.health_extra is not None:
            try:
                out.update(self.health_extra())
            except Exception:
                pass
        return out

    def merged_exposition(self) -> str:
        """The fleet /metrics body: every ready replica's scrape merged
        with the router's own registry."""
        texts = []
        for v in self.views():
            try:
                with urllib.request.urlopen(v.base_url + "/metrics",
                                            timeout=2.0) as r:
                    texts.append(r.read().decode())
            except (urllib.error.URLError, OSError):
                continue
        texts.append(metrics.registry().render())
        try:
            return metrics.merge_expositions(texts)
        except ValueError:
            # one torn scrape must not blank the endpoint
            return metrics.registry().render()

    # ------------------------------------------------------------ routing
    def _post_replica(self, v: ReplicaView, body: bytes,
                      fwd: Dict[str, str], rid: str,
                      attempt: int,
                      path: str = "/align") -> Tuple[str, int, bytes, Dict]:
        req = urllib.request.Request(
            v.base_url + path, data=body, method="POST",
            headers={**fwd, "X-Abpoa-Request-Id": rid,
                     "X-Abpoa-Attempt": str(attempt)})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return ("http", r.status, r.read(), dict(r.headers))
        except urllib.error.HTTPError as e:
            data = e.read()
            return ("http", e.code, data, dict(e.headers))
        except (urllib.error.URLError, OSError) as e:
            # RemoteDisconnected subclasses ConnectionResetError; urllib
            # wraps most socket deaths in URLError — all of them mean the
            # replica never delivered a status line: failover material
            return ("transport", 0, b"", {"error": str(e)})

    def route(self, body: bytes, fwd: Dict[str, str], rid: str,
              path: str = "/align") -> _Outcome:
        """Race one request to a terminal answer across the fleet. The
        winner is the first non-shed HTTP response; transport errors
        trigger the exactly-once failover, sheds spill to untried
        siblings, and one bounded hedge covers stragglers. `path` is the
        inbound endpoint, forwarded verbatim (/align or /map); /map
        placement only gets tier-0 affinity from open MAP groups."""
        t0 = time.perf_counter()
        kind = "map" if path == "/map" else "consensus"
        rung = _body_rung(body)
        resq: "queue.Queue" = queue.Queue()
        outstanding = 0
        attempts = 0
        failovers = hedges = spills = 0
        tried: set = set()
        shed: List[Tuple[int, bytes, Dict]] = []
        lost_transport = 0

        def launch(v: ReplicaView, attempt_no: int, label: str) -> None:
            nonlocal outstanding, attempts
            outstanding += 1
            attempts = max(attempts, attempt_no)
            tried.add(v.name)
            with self._lock:
                v.local_inflight += 1

            def run():
                res = self._post_replica(v, body, fwd, rid, attempt_no,
                                         path)
                with self._lock:
                    v.local_inflight = max(0, v.local_inflight - 1)
                resq.put((v, attempt_no, label, res))

            threading.Thread(target=run, daemon=True,
                             name=f"abpoa-fleet-{label}").start()

        def next_candidate() -> Optional[ReplicaView]:
            for v in plan_placement(self.views(), rung, kind):
                if v.name not in tried:
                    return v
            return None

        first = plan_placement(self.views(), rung, kind)
        if not first:
            return _Outcome(503, b"", {"Retry-After": "5"},
                            failovers=0, hedges=0)
        launch(first[0], 1, "primary")
        hedge_after = hedge_delay_s(self.sketch)
        hedge_done = hedge_after is None

        while outstanding > 0:
            timeout: Optional[float] = None
            if not hedge_done:
                remaining = (t0 + hedge_after) - time.perf_counter()
                if remaining <= 0:
                    hedge_done = True
                    cand = next_candidate()
                    # never hedge on top of an in-flight failover: the
                    # retry is already the second copy
                    if cand is not None and failovers == 0:
                        hedges += 1
                        self._c_hedges.inc()
                        launch(cand, attempts + 1, "hedge")
                    continue
                timeout = remaining
            try:
                v, attempt_no, label, (tk, code, rbody, rheaders) = \
                    resq.get(timeout=timeout)
            except queue.Empty:
                continue
            outstanding -= 1
            if tk == "transport":
                lost_transport += 1
                if failovers == 0:
                    cand = next_candidate()
                    if cand is None:
                        # nowhere untried left — a sibling that only shed
                        # may still accept the retry
                        ready = [w for w in
                                 plan_placement(self.views(), rung,
                                                kind)
                                 if w.name != v.name]
                        cand = ready[0] if ready else None
                    if cand is not None:
                        failovers += 1
                        self._c_failovers.inc()
                        launch(cand, attempt_no + 1, "failover")
                        continue
                if outstanding:
                    continue
                break
            if code in (429, 503):
                shed.append((code, rbody, rheaders))
                cand = next_candidate()
                if cand is not None:
                    spills += 1
                    self._c_spills.inc()
                    launch(cand, attempt_no + 1, "spill")
                    continue
                if outstanding:
                    continue
                break
            # terminal answer: first writer wins; outstanding duplicates
            # drain in their daemon threads and are discarded
            self.sketch.observe(time.perf_counter() - t0)
            if label == "hedge":
                self._c_hedge_wins.inc()
            replica = rheaders.get("X-Abpoa-Replica") or v.name
            v.last_rung = rung
            return _Outcome(code, rbody, rheaders, replica=replica,
                            attempt=attempt_no, failovers=failovers,
                            hedges=hedges, hedge_won=(label == "hedge"))
        # no replica produced a terminal answer
        if shed:
            code, rbody, rheaders = shed[-1]
            return _Outcome(code, rbody, rheaders, failovers=failovers,
                            hedges=hedges)
        return _Outcome(
            502, (json.dumps({"error": "replica connection lost and no "
                                       "sibling available"}) + "\n")
            .encode(), {"Content-Type": "application/json"},
            failovers=failovers, hedges=hedges)


# --------------------------------------------------------------------------- #
# HTTP front                                                                  #
# --------------------------------------------------------------------------- #

# client request headers forwarded to the replica verbatim
_FWD_REQUEST = ("Content-Type", "X-Abpoa-Deadline-S")
# replica response headers forwarded to the client verbatim
_FWD_RESPONSE = ("Content-Type", "Retry-After", "X-Abpoa-Reads")


def _make_router_handler(router: FleetRouter):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _send(self, code: int, body: bytes, ctype: str,
                  headers: Optional[dict] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, val in (headers or {}).items():
                self.send_header(k, val)
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass

        def _json(self, code: int, obj: dict,
                  headers: Optional[dict] = None) -> None:
            self._send(code, (json.dumps(obj) + "\n").encode(),
                       "application/json", headers)

        def log_message(self, *a):
            pass

        # -------------------------------------------------------- GET
        def do_GET(self):  # noqa: N802 — http.server API
            path = self.path.rstrip("/")
            if path == "/healthz":
                self._json(200, router.health())
            elif path == "/readyz":
                if router.draining.is_set():
                    self._json(503, {"status": "draining"})
                elif router.ready_count() == 0:
                    self._json(503, {"status": "no ready replicas"})
                else:
                    self._json(200, {"status": "ready",
                                     "replicas": router.ready_count()})
            elif path == "/metrics":
                self._send(200, router.merged_exposition().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            else:
                self._json(404, {"error": f"unknown path {self.path!r}"})

        # -------------------------------------------------------- POST
        def do_POST(self):  # noqa: N802 — http.server API
            path = self.path.rstrip("/")
            if path not in ("/align", "/map"):
                self._json(404, {"error": f"unknown path {self.path!r}"})
                return
            # ingress id, minted here so every delivery attempt across
            # replicas shares one id (the client may also supply its own)
            rid = (_inbound_rid(self.headers.get("X-Abpoa-Request-Id"))
                   or obs.new_request_id())
            rh = {"X-Abpoa-Request-Id": rid}
            # the three body-unread dispositions mirror serve/server.py
            # exactly: same codes, same Retry-After, same Connection:
            # close (an unread body on a keep-alive socket would parse
            # as the next request line)
            if router.draining.is_set():
                self.close_connection = True
                router.bump("draining")
                self._json(503, {"error": "fleet is draining"},
                           {"Retry-After": "30", **rh})
                return
            try:
                n = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                self.close_connection = True
                router.bump("poisoned")
                self._json(400, {"error": "malformed Content-Length"}, rh)
                return
            if n > max_body_bytes():
                self.close_connection = True
                router.bump("oversized")
                self._json(413, {"error": f"body {n} B exceeds the "
                                          f"{max_body_bytes()} B limit"},
                           rh)
                return
            raw = self.rfile.read(n) if n else b""
            fwd = {k: self.headers[k] for k in _FWD_REQUEST
                   if self.headers.get(k)}
            out = router.route(raw, fwd, rid, path)
            status_key = {200: "ok", 429: "shed", 503: "shed",
                          400: "poisoned", 504: "timeout"}.get(
                out.code, "error" if out.code >= 500 else "other")
            router.bump(status_key)
            headers = {k: out.headers[k] for k in _FWD_RESPONSE
                       if out.headers.get(k)}
            headers.update(rh)
            if out.replica:
                headers["X-Abpoa-Replica"] = out.replica
            headers["X-Abpoa-Attempt"] = str(out.attempt)
            headers["X-Abpoa-Failovers"] = str(out.failovers)
            headers["X-Abpoa-Hedges"] = str(out.hedges)
            ctype = headers.pop("Content-Type",
                                out.headers.get("Content-Type")
                                or "application/json")
            self._send(out.code, out.body, ctype, headers)

    return Handler
