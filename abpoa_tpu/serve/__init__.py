"""`abpoa-tpu serve`: the persistent, fault-contained aligner service.

ROADMAP item 1's front end over the substrate PRs 7-10 built: a
stdlib-first HTTP server (ThreadingHTTPServer, the `--metrics-port`
idiom — no framework dependency) that accepts read-set alignment jobs
and stays correct and alive under overload and injected faults.

The robustness contract, mechanism by mechanism:

- **Admission** (admission.py): a bounded queue priced by
  `resilience/memory.py`'s DP-plane byte model. Past the queue depth or
  the byte budget a request is shed as 429 + Retry-After — the server
  never OOMs discovering its limit.
- **Deadlines**: every request carries one (default
  ``ABPOA_TPU_SERVE_DEADLINE_S``, per-request override via the
  ``X-Abpoa-Deadline-S`` header, capped by the server's). Expiry rides
  the `resilience/watchdog.py` envelope: the request answers 504 with a
  fault record and the executing thread is abandoned, not joined — a
  wedged alignment can never wedge a worker.
- **Coalescing** (server.py): queued requests are grouped by their
  declared `compile/ladder.py` Qp rung, so arriving sets pack into
  shapes the startup AOT warm already compiled (zero-recompile steady
  state); on an accelerator mesh a same-rung group runs as ONE vmapped
  lockstep dispatch (`parallel.flush_lockstep_group`).
- **Isolation**: a poisoned set (malformed records, injected
  `poison_set`) is a 400 for that request — `quarantine.py` semantics,
  never a crashed worker; an unexpected execution error is a 500 plus a
  fault record, and the worker survives.
- **Degradation**: dispatch failures flow through the circuit breaker
  exactly as in batch runs; `/healthz` reports degraded-but-serving and
  the half-open cooldown (resilience/breaker.py) reclaims a demoted
  backend without a restart.
- **Drain**: SIGTERM/SIGINT stops admission (new requests get 503),
  finishes in-flight work, flushes metrics and the report archive, and
  exits 0.
- **Replication** (fleet.py + router.py): `--replicas N` (or
  `abpoa-tpu fleet`) runs N supervised serve processes behind one
  failover router — crash respawn with backoff, exactly-once retry of a
  request whose replica died mid-flight (same request id, attempt N+1,
  `why` narrates the hop), bounded p99 hedging, shed/Retry-After
  propagation, SIGHUP rolling restarts that never drop below N-1
  ready, and a merged fleet /metrics exposition.

Each terminal request lands one `obs/archive.py` record, so
`abpoa-tpu slo` evaluates the served window the same way it evaluates
batch runs; `tools/loadgen.py` + `tools/serve_smoke.py` are the measured
proof (CI `serve-smoke`).
"""
from .admission import AdmissionController, Job, request_caps
from .server import AlignServer, serve_main

__all__ = ["AdmissionController", "Job", "request_caps", "AlignServer",
           "serve_main", "fleet_main"]


def fleet_main(argv):  # lazy: the fleet pulls in router + supervisor
    from .fleet import fleet_main as _fm
    return _fm(argv)
