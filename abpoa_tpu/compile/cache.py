"""Persistent XLA compilation-cache wiring.

Every cold process used to pay full first-sight XLA compiles (minutes per
growth ladder on the fused loop). jax ships a persistent on-disk cache;
this module points it at a stable per-user directory and lowers the
entry thresholds so *all* of our entry points persist (the defaults skip
compiles under 1 s and small executables — exactly the warm rungs
`abpoa-tpu warm` exists to keep).

Resolution order for the directory:

1. ``ABPOA_TPU_XLA_CACHE=0``            -> disabled entirely
2. pre-set jax config / ``JAX_COMPILATION_CACHE_DIR``  -> respected as-is
3. ``ABPOA_TPU_XLA_CACHE_DIR``          -> used
4. default                               -> ``~/.cache/abpoa_tpu/xla``

Called from jax_backend at import (so every device path gets it before
its first compile), from the warm CLI, and idempotent everywhere.
"""
from __future__ import annotations

import os
from typing import Optional

DEFAULT_SUBDIR = os.path.join("abpoa_tpu", "xla")

_ENABLED: Optional[str] = None
_DONE = False


def _default_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
    return os.path.join(base, DEFAULT_SUBDIR)


def cache_dir() -> Optional[str]:
    """The directory the persistent cache resolves to, or None when
    disabled. Pure env/config inspection — does not enable anything."""
    if os.environ.get("ABPOA_TPU_XLA_CACHE", "") in ("0", "off", "false"):
        return None
    env = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if env:
        return env
    override = os.environ.get("ABPOA_TPU_XLA_CACHE_DIR")
    if override:
        return override
    return _default_dir()


def enable_persistent_cache() -> Optional[str]:
    """Wire the jax persistent compilation cache (idempotent). Returns the
    directory in effect, or None when disabled / jax unavailable. Lazy
    jax import: host-only runs never pay it through here."""
    global _ENABLED, _DONE
    if _DONE:
        return _ENABLED
    _DONE = True
    target = cache_dir()
    if target is None:
        return None
    try:
        import jax
        # respect a dir the user already configured (env var above, or an
        # explicit jax.config.update before we ran)
        current = jax.config.jax_compilation_cache_dir
        ours = not current and not os.environ.get("JAX_COMPILATION_CACHE_DIR")
        if not current:
            os.makedirs(target, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", target)
            current = target
        if ours:
            # cache EVERY entry: our warm rungs are exactly the compiles
            # the default 1 s / min-size thresholds would refuse to
            # persist. Only when WE chose the directory — a host app that
            # configured its own cache keeps its own persistence policy
            # (importing this library must not bloat a foreign cache dir
            # with every sub-second helper compile of unrelated jax code;
            # our own >1 s entry-point compiles persist either way).
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            try:
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", -1)
            except Exception:
                pass  # knob absent on older jax: size gating stays default
        _ENABLED = current
    except Exception:
        _ENABLED = None
    return _ENABLED


def reset_for_tests() -> None:
    """Forget the idempotence latch (test hook)."""
    global _ENABLED, _DONE
    _ENABLED = None
    _DONE = False
