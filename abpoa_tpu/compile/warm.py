"""AOT bucket-ladder precompilation.

`warm_ladder` maps each declared anchor workload (ladder.QUICK_TIER /
FULL_TIER) through the *same planner code the drivers use* to the exact
jit signatures they will request, then dispatches each entry point once
on zero-filled inputs whose loop trip-count is zero — the dispatch cost
is pure XLA compile (or a persistent-cache load on a warmed machine).
Dispatches run inside the registry's `compile_watch` brackets, so the
compile log records every signature with wall / xla_compile_s /
persistent-cache verdict, and the in-process jit caches end up populated
exactly as a real run would populate them: a subsequent workload in this
process reports `compiles.misses == 0`, and a workload in a *fresh*
process loads the rungs from the persistent cache instead of compiling.

Drivers register their warmers in compile.registry at import; this module
only orchestrates (and lazily imports the jax-bearing driver modules).
"""
from __future__ import annotations

import time
from typing import Iterable, Optional

from . import registry
from .cache import enable_persistent_cache
from .ladder import TIERS, WarmAnchor


def _default_params(device: str = "jax"):
    from ..params import Params
    abpt = Params()
    abpt.device = device
    return abpt.finalize()


def warm_ladder(tier: str = "quick", abpt=None,
                anchors: Optional[Iterable[WarmAnchor]] = None,
                verbose: bool = False) -> dict:
    """Precompile the ladder tier. Returns a summary dict:
    {tier, signatures, compiled, cache_hits, persistent_cache_hits,
     xla_compile_s, wall_s, records}."""
    from .. import obs
    enable_persistent_cache()
    if abpt is None:
        abpt = _default_params()
    if anchors is None:
        anchors = TIERS[tier]
    # importing the drivers registers their entry points + warmers
    from ..align import dp_chunk  # noqa: F401
    from ..align import fused_loop  # noqa: F401
    from ..align import jax_backend  # noqa: F401
    from ..parallel import shard  # noqa: F401

    t0 = time.perf_counter()
    records = []
    seen = set()
    for anchor in anchors:
        w = registry.warmer(anchor.entry)
        if w is None:
            records.append({"entry": anchor.entry, "skipped": "no warmer"})
            continue
        for rec in w(abpt, anchor):
            if "fn" not in rec:
                # a warmer may decline an anchor (e.g. the sharded rungs
                # with no mesh requested) by yielding a skipped record
                records.append(rec)
                continue
            key = (rec["fn"], tuple(sorted(
                (k, str(v)) for k, v in rec["bucket"].items())))
            if key in seen:
                continue
            seen.add(key)
            records.append(rec)
            if verbose:
                import sys
                pc = rec.get("persistent_cache_hit")
                print("[warm] {fn} {bucket} wall={wall_s:.2f}s{extra}".format(
                    fn=rec["fn"], bucket=rec["bucket"],
                    wall_s=rec.get("wall_s") or 0.0,
                    extra=(" (persistent-cache hit)" if pc
                           else (" (compiled)" if not rec.get("cache_hit")
                                 else " (jit-cache hit)"))),
                    file=sys.stderr)
    wall = time.perf_counter() - t0
    compiled = sum(1 for r in records if not r.get("cache_hit", True))
    return {
        "tier": tier,
        "signatures": len(records),
        "compiled": compiled,
        "cache_hits": sum(1 for r in records if r.get("cache_hit")),
        "persistent_cache_hits": sum(
            1 for r in records if r.get("persistent_cache_hit")),
        "xla_compile_s": round(sum(
            r.get("xla_compile_s") or 0.0 for r in records), 3),
        "wall_s": round(wall, 3),
        "cache_dir": enable_persistent_cache(),
        "records": records,
    }
