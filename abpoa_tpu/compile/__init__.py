"""Shared shape/compile management (ROADMAP item 2).

One definition site for every shape-bucket decision the device paths make
(`buckets`), an explicit declared rung ladder per jitted entry point
(`ladder`), a registry of those entry points so `compile_watch` brackets
them automatically (`registry`), AOT bucket-ladder precompilation
(`warm`, the `abpoa-tpu warm` CLI), and persistent compilation-cache
wiring (`cache`) so warmed rungs survive process restarts.

Import of this package is jax-free: `cache` and `warm` import jax lazily
so host-only runs (numpy/native) never pay a jax import through here.
"""
from .buckets import bucket, bucket_pow2, grow_node_cap, snap
from .cache import cache_dir, enable_persistent_cache
from .ladder import (LADDER, QUICK_TIER, FULL_TIER, WarmAnchor, k_rung,
                     ladder_axes, mesh_rung, on_ladder, qp_rung, reads_rung)
from .registry import entry_names, jit_handle, register_entry, watch

__all__ = [
    "bucket", "bucket_pow2", "grow_node_cap", "snap",
    "cache_dir", "enable_persistent_cache",
    "LADDER", "QUICK_TIER", "FULL_TIER", "WarmAnchor",
    "ladder_axes", "on_ladder", "qp_rung", "reads_rung", "k_rung",
    "mesh_rung",
    "entry_names", "jit_handle", "register_entry", "watch",
    "warm_ladder",
]


def warm_ladder(tier="quick", abpt=None, anchors=None, verbose=False):
    """AOT-precompile the ladder (lazy import: pulls in jax)."""
    from .warm import warm_ladder as _warm
    return _warm(tier=tier, abpt=abpt, anchors=anchors, verbose=verbose)
