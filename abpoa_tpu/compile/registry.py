"""Registry of jitted entry points.

Each device driver registers its jitted entry point(s) at import time:
a lazy handle to the jit wrapper (so `compile_watch` gets ground-truth
compile detection from the jit cache, and monkeypatched spies in tests
are honored), plus an optional warmer the AOT ladder uses. Drivers then
bracket dispatches with `watch(name, bucket)` instead of threading the
handle themselves — the registry IS the list of things `abpoa-tpu warm`
knows how to precompile.
"""
from __future__ import annotations

from typing import Callable, Dict, NamedTuple, Optional


class Entry(NamedTuple):
    handle: Optional[Callable]  # () -> jit wrapper (or None: no stable cache)
    warmer: Optional[Callable]  # (abpt, anchor) -> list of warmed records


_ENTRIES: Dict[str, Entry] = {}


def register_entry(name: str, handle: Optional[Callable] = None,
                   warmer: Optional[Callable] = None) -> None:
    _ENTRIES[name] = Entry(handle, warmer)


def entry_names() -> list:
    return sorted(_ENTRIES)


def jit_handle(name: str):
    """The current jit wrapper for a registered entry point (None when the
    entry has no stable in-process cache handle, e.g. vmapped lockstep)."""
    e = _ENTRIES.get(name)
    if e is None or e.handle is None:
        return None
    try:
        return e.handle()
    except Exception:
        return None


def warmer(name: str) -> Optional[Callable]:
    e = _ENTRIES.get(name)
    return e.warmer if e else None


def watch(name: str, bucket: dict, use_handle: bool = True):
    """compile_watch bracket for a registered entry point, with the jit
    handle resolved automatically."""
    from ..obs import compile_watch
    return compile_watch(name, jit_handle(name) if use_handle else None,
                         bucket)
