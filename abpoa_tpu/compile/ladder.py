"""The declared bucket ladder: explicit rung tables per jitted entry point.

Before this module the growth rungs were implicit in scattered x1.3/x1.7/
pow2 call sites; a planner and a warmer could silently disagree about what
shapes exist. Now each entry point declares its signature axes and the
chain each axis draws from, `on_ladder` answers membership (the property
test asserts every planner-requestable shape is a declared rung — no
silent off-ladder compiles), and the warm tiers below declare the anchor
workloads `abpoa-tpu warm` precompiles.

Axes (fused chunk / lockstep / seeded-window batch):

- Qp   padded query columns          GEOM_128 chain
- N    node capacity                 GEOM_1024 chain (growth: x1.7 snapped)
- W    band window width             pow2 >= 128 (growth: x2)
- E/A  edge / aligned-group slots    pow2 (growth: x2)
- R    window rows (seeded path)     GEOM_64 chain
- P/O/SR/B  degree & batch axes      pow2
- reads  padded read rows            pow2 >= 8 (new in round 8: the read
         count used to be an unbucketed traced shape, so every distinct
         set size compiled its own fused chunk)
- K    lockstep set axis             pow2 (padding sets are empty: they
         finish before their first device step)
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

from .buckets import bucket, bucket_pow2, geom_chain, pow2_chain, snap

# declared chain caps: generous for the workloads the paper targets
# (reads to ~128 kb, graphs to ~4M nodes); beyond these the planners
# would raise in snap(), which the property test would catch first.
GEOM_128 = geom_chain(128, 1 << 18)     # Qp: query columns
GEOM_64 = geom_chain(64, 1 << 18)       # R: seeded-window rows
GEOM_1024 = geom_chain(1024, 1 << 24)   # N: fused node capacity (+growth)
POW2 = pow2_chain(1, 1 << 24)           # E/A/P/O/SR/B/K and W growth
POW2_128 = pow2_chain(128, 1 << 24)     # W: band window width
POW2_READS = pow2_chain(8, 1 << 17)     # padded read rows
MESH = pow2_chain(1, 256)               # sharded lane-mesh width (devices)

LADDER = {
    "run_fused_chunk": {
        "Qp": GEOM_128, "N": GEOM_1024, "W": POW2_128,
        "E": POW2, "A": POW2, "reads": POW2_READS,
    },
    "run_fused_chunk[lockstep]": {
        "Qp": GEOM_128, "N": GEOM_1024, "W": POW2_128,
        "E": POW2, "A": POW2, "reads": POW2_READS, "K": POW2,
    },
    "dp_full_batch": {
        "R": GEOM_64, "Qp": GEOM_128, "P": POW2, "O": POW2,
        "SR": POW2, "B": POW2,
    },
    # split lockstep (fusion off the batch axis): banded DP + backtrack
    # only, vmapped over the K set axis; graphs live on the host
    "run_dp_chunk": {
        "R": GEOM_64, "Qp": GEOM_128, "W": POW2_128, "P": POW2, "K": POW2,
    },
    # map workload (PR 18): the SAME jitted entry as run_dp_chunk — a
    # fixed restored graph pins R and P for the stream's lifetime, so a
    # map deployment occupies exactly one (R, P) point of this grid per
    # graph, times the Qp/W read rungs and the pow2 K read-batch axis.
    # Declared separately so membership checks and the warm tiers can
    # name the map shape; the registry still keys compiles under
    # "run_dp_chunk" (one cache, shared with the consensus split driver).
    "run_dp_chunk[map]": {
        "R": GEOM_64, "Qp": GEOM_128, "W": POW2_128, "P": POW2, "K": POW2,
    },
    # sharded route (PR 19): shard_map(vmap(run_dp_chunk)) over a 1-axis
    # lane mesh. K here is the PER-SHARD lane rung (pow2, same chain as
    # the unsharded K axis); the mesh axis is the device width, so the
    # global lane count of a sharded dispatch is mesh x K — exactly the
    # rung grammar parallel/shard.shard_dp_round buckets under.
    "run_dp_chunk[sharded]": {
        "R": GEOM_64, "Qp": GEOM_128, "W": POW2_128, "P": POW2, "K": POW2,
        "mesh": MESH,
    },
}


def ladder_axes(entry: str) -> dict:
    return LADDER[entry]


def on_ladder(entry: str, axis: str, value: int) -> bool:
    """Is `value` a declared rung of `entry`'s `axis`?"""
    return value in LADDER[entry][axis]


# ---- planner rung helpers (the shared definitions drivers consume) ------- #

def qp_rung(qmax: int) -> int:
    """Padded-query rung for a workload whose longest read is qmax.
    THE bucket key: _plan_buckets, partition_by_length_bucket and the
    window planner all key through here, so lockstep sub-batching and
    the chunk planner can never disagree about a read's bucket.
    Snapped onto the declared chain: a read beyond the ladder cap
    (~262 kb) raises here instead of compiling an off-ladder shape the
    warmer can never precompile."""
    return snap(qmax + 2, GEOM_128)


def reads_rung(n: int) -> int:
    """Padded read-row rung (>= 8, declared cap 131072 rows). Padding
    rows are never touched: the fused loop stops at the traced n_reads
    scalar. Raises past the cap — never a silent off-ladder compile."""
    return snap(max(8, n), POW2_READS)


def k_rung(k: int, mesh_size: int = 1) -> int:
    """Lockstep set-axis rung; a mesh requires K divisible by its size.
    For pow2 mesh sizes (every real mesh we target) the result stays on
    the declared POW2 chain; a non-pow2 mesh's divisibility rounding can
    leave it, which is accepted (the mesh, not the planner, fixes K)."""
    r = snap(max(k, 1), POW2)
    if mesh_size > 1:
        r = ((max(r, mesh_size) + mesh_size - 1) // mesh_size) * mesh_size
    return r


def mesh_rung(n: int) -> int:
    """Sharded lane-mesh width rung: pow2 up to the declared 256-device
    cap. Raises past the cap (snap's "beyond the declared ladder cap")
    instead of silently compiling an off-ladder mesh shape — the cap-raise
    property test pins this."""
    return snap(max(n, 1), MESH)


def plan_chunk_buckets(abpt, qmax: int):
    """(Qp, W, local_mode) for a fused-chunk workload whose longest read
    is qmax — THE definition site shared by the fused planner
    (fused_loop._plan_buckets) and serve admission pricing
    (serve/admission.request_caps), so the byte gate can never drift
    from the shapes the dispatch actually allocates. jax-free on
    purpose: admission prices before/without a jax import."""
    from .. import constants as C
    Qp = qp_rung(qmax)
    local_m = abpt.align_mode == C.LOCAL_MODE
    if local_m:
        # local disables banding: every row spans the full query
        W = max(128, bucket_pow2(qmax + 2))
    else:
        w_full = abpt.wb + int(abpt.wf * qmax)
        W = max(128, bucket_pow2(2 * w_full + 4))
    return Qp, W, local_m


def chunk_node_cap(qmax: int) -> int:
    """Start node capacity of a fused chunk (shared with admission)."""
    return bucket(2 * (qmax + 2) + 64, 1024)


# ---- warm tiers ---------------------------------------------------------- #

class WarmAnchor(NamedTuple):
    """One workload the AOT warmer precompiles: entry point + the workload
    coordinates the planner maps to signatures. `growth` warms that many
    node-capacity growth rungs past the start bucket (the chain a run
    replays when the graph outgrows its start N); the warmer enumerates
    every distinct start signature across the anchor's whole Qp-rung
    interval, so any qmax landing in the same rung hits a warmed compile."""
    entry: str
    qmax: int
    n_reads: int
    growth: int = 1
    k: Optional[int] = None       # lockstep only (sharded: PER-SHARD k)
    windows: Optional[int] = None  # dp_full_batch only: window batch B
    mesh: Optional[int] = None     # sharded only: declared mesh width


# quick: the smoke/test scale plus the sim2k serve shape (2 kb reads).
# Growth depth is deliberately shallow: each growth rung is its own XLA
# compile whose cost grows with N (measured on the dev container: ~35 s
# at N=4096, ~90-140 s at N>=6144 per signature), and a 20 x 2 kb
# workload tops out one rung past its start bucket — deeper rungs would
# double the quick tier's cold wall to warm shapes no 2 kb run reaches.
QUICK_TIER: Tuple[WarmAnchor, ...] = (
    WarmAnchor("run_fused_chunk", qmax=240, n_reads=8, growth=2),
    WarmAnchor("run_fused_chunk", qmax=2200, n_reads=20, growth=1),
    # split-lockstep DP chunk at the bench/gate protocol shape (2 kb
    # reads, K=4 + repack halvings): covers tools/lockstep_gate.py and
    # the BENCH_lockstep_cpu K=4 row, same Qp rung as the 2200 fused
    # anchor above
    WarmAnchor("run_dp_chunk", qmax=2200, n_reads=20, growth=2, k=4),
    # map workload at the gate shape: K=8 read batches (the default map
    # K cap) against a static ~2 kb graph. Same jitted entry and R/Qp/W
    # rungs as the k=4 anchor above, so only the K=8 signatures compile
    # fresh — the 4/2/1 halvings are in-process cache hits.
    WarmAnchor("run_dp_chunk", qmax=2200, n_reads=16, growth=2, k=8),
    # sharded route at the shard-gate protocol shape: per-shard K rungs
    # {2, 1} (global lanes = mesh x {2, 1}: 16 and 8 on the virtual
    # 8-mesh) over the same 2 kb Qp/R rungs as the anchors above. The
    # warmer sizes the mesh from the OPERATOR'S request
    # (ABPOA_TPU_MESH/--mesh) and is a recorded skip when none is set —
    # sharded warm shapes exist only where sharded dispatches can.
    WarmAnchor("run_dp_chunk[sharded]", qmax=2200, n_reads=16, growth=2,
               k=2, mesh=8),
)

# full: quick + the north-star 10 kb consensus shape, the lockstep `-l`
# group shapes (all-device and split), and the seeded-window batch.
FULL_TIER: Tuple[WarmAnchor, ...] = QUICK_TIER + (
    WarmAnchor("run_fused_chunk", qmax=10000, n_reads=500, growth=4),
    WarmAnchor("run_fused_chunk[lockstep]", qmax=10000, n_reads=10,
               growth=2, k=8),
    WarmAnchor("run_dp_chunk", qmax=2200, n_reads=10, growth=3, k=8),
    WarmAnchor("dp_full_batch", qmax=1000, n_reads=1, growth=0, windows=8),
)

TIERS = {"quick": QUICK_TIER, "full": FULL_TIER}


def qmax_interval(qp: int) -> Tuple[int, int]:
    """The [lo, hi] qmax interval that maps onto Qp rung `qp`."""
    i = GEOM_128.index(qp)
    lo = 1 if i == 0 else GEOM_128[i - 1] - 1  # qmax+2 > previous rung
    return lo, qp - 2
