"""Bucket math, defined ONCE.

Every static shape the device paths compile for is a rung of a fixed
per-axis chain (ladder.py declares the chains). Three roundings exist:

- `bucket(n, step)`: geometric x1.3 rounded up to `step` — the query/row
  axes, where x1.3 bounds recompiles to O(log n) while capping padding
  waste at 30%.
- `bucket_pow2(n)`: power of two — degree/batch axes, where values are
  tiny and pow2 keeps scatter tables lane-friendly.
- `grow_node_cap(n)`: the node-capacity growth policy (x1.7 then snapped
  to the 1024-step geometric chain) — deliberately faster than x1.3 so a
  graph that outgrew its start bucket re-enters the loop few times.

All three land on chain members by construction: `bucket(x, step)` walks
the fixed chain step, step*1.3, ... regardless of x, so growth and start
values share one rung table per axis and the AOT warmer (warm.py) can
enumerate exactly the signatures the planners will request.

This module is dependency-free (no jax, no numpy): the CLI parses
`abpoa-tpu warm` arguments and perf_gate reads ladders without importing
an accelerator stack.
"""
from __future__ import annotations

from typing import Tuple


def bucket(n: int, step: int) -> int:
    """Smallest rung of the `step`-chain (x1.3, rounded up to `step`)
    that is >= n. Single definition site — formerly triplicated across
    jax_backend/fused_loop/pallas_backend."""
    b = step
    while b < n:
        b = ((int(b * 1.3) + step - 1) // step) * step
    return b


def bucket_pow2(n: int) -> int:
    """Smallest power of two >= n."""
    p = 1
    while p < n:
        p <<= 1
    return p


def grow_node_cap(n: int) -> int:
    """Node-capacity growth rung: x1.7 snapped onto the 1024-step chain
    (the fused loop's ERR_NODE_CAP/ERR_OPS_CAP/ERR_GRAPH_CAP policy)."""
    return bucket(int(n * 1.7), 1024)


def geom_chain(step: int, cap: int) -> Tuple[int, ...]:
    """The explicit rung chain bucket(., step) draws from, up to cap."""
    rungs = [step]
    while rungs[-1] < cap:
        rungs.append(((int(rungs[-1] * 1.3) + step - 1) // step) * step)
    return tuple(rungs)


def pow2_chain(lo: int, cap: int) -> Tuple[int, ...]:
    rungs = []
    p = 1
    while p <= cap:
        if p >= lo:
            rungs.append(p)
        p <<= 1
    return tuple(rungs)


def snap(n: int, rungs: Tuple[int, ...]) -> int:
    """Smallest declared rung >= n (falls through to the last rung's
    successor pattern only via the caller's bucket fn; planners never
    exceed the declared caps in practice — the ladder property test
    enforces it)."""
    for r in rungs:
        if r >= n:
            return r
    raise ValueError(f"value {n} beyond the declared ladder cap {rungs[-1]}")
