"""Tracing / profiling utilities.

TPU-native equivalent of the reference's verbosity ladder + stderr traces +
end-of-run resource line (SURVEY.md §5; reference include/abpoa.h:40-43,
src/utils.h:120-126, src/abpoa.c:166): a `verbose` ladder gating structured
stderr logs, wall/CPU timers, peak-RSS reporting, and `jax.profiler` trace
annotations around kernel dispatches for profiling with TensorBoard/XProf.
"""
from __future__ import annotations

import contextlib
import resource
import sys
import time
from typing import Iterator

from .. import constants as C

_VERBOSE = C.VERBOSE_NONE


def set_verbose(level: int) -> None:
    global _VERBOSE
    _VERBOSE = level


def vlog(level: int, msg: str, func: str = "") -> None:
    """Verbosity-gated stderr log (reference err_func_printf style)."""
    if _VERBOSE >= level:
        prefix = f"[abpoa_tpu::{func}] " if func else "[abpoa_tpu] "
        print(prefix + msg, file=sys.stderr)


@contextlib.contextmanager
def timer(label: str, level: int = C.VERBOSE_INFO) -> Iterator[None]:
    t0 = time.time()
    c0 = time.process_time()
    try:
        yield
    finally:
        vlog(level, f"{label}: real {time.time() - t0:.3f} s; "
                    f"CPU {time.process_time() - c0:.3f} s")


@contextlib.contextmanager
def trace_annotation(name: str) -> Iterator[None]:
    """jax.profiler annotation (no-op if jax missing/uninitialized)."""
    try:
        import jax
        with jax.profiler.TraceAnnotation(name):
            yield
    except Exception:
        yield


def peak_rss_gb() -> float:
    ru = resource.getrusage(resource.RUSAGE_SELF)
    return ru.ru_maxrss / 1024.0 / 1024.0  # linux reports KB


def run_stats(t0: float, c0: float) -> str:
    """End-of-run line mirroring the reference's wall/CPU/RSS report."""
    return (f"Real time: {time.time() - t0:.3f} sec; "
            f"CPU: {time.process_time() - c0:.3f} sec; "
            f"Peak RSS: {peak_rss_gb():.3f} GB.")


def dump_dp_matrix(H, dp_beg, dp_end, index_to_node_id, beg_index,
                   planes=None, max_rows: int = 0) -> None:
    """`-V3` DP-matrix dump for kernel debugging: per row, the in-band H
    (and optionally E/F) cells with their absolute columns — the analog of
    the reference's __SIMD_DEBUG__ print path
    (/root/reference/src/abpoa_align_simd.c:46-95). Gated on
    VERBOSE_LONG_DEBUG so production runs never pay the host sync."""
    if _VERBOSE < C.VERBOSE_LONG_DEBUG:
        return
    n = H.shape[0] if max_rows <= 0 else min(max_rows, H.shape[0])
    for i in range(n):
        b, e = int(dp_beg[i]), int(dp_end[i])
        nid = int(index_to_node_id[beg_index + i])
        cells = " ".join(f"{j}:{int(H[i, j])}" for j in range(b, e + 1))
        print(f"[abpoa_tpu::dp] row {i} (node {nid}) band [{b},{e}] "
              f"H: {cells}", file=sys.stderr)
        if planes:
            for name, P in planes.items():
                cells = " ".join(f"{j}:{int(P[i, j])}"
                                 for j in range(b, e + 1))
                print(f"[abpoa_tpu::dp]   {name}: {cells}", file=sys.stderr)
