"""Version-bridging shims for jax APIs that moved between 0.4.x and 0.5+.

The container fleet pins different jax versions; the kernels must run on
all of them. Keep every cross-version alias here so call sites stay
single-form (see also fused_loop.py's lax.cummax note: jnp ufunc methods
like `.accumulate` do not exist on 0.4.x).
"""
from __future__ import annotations


def shard_map(f, mesh, in_specs, out_specs):
    """`jax.shard_map(..., check_vma=False)` on new jax; the experimental
    module (check_rep=False spelling) on 0.4.x."""
    import jax
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)
