"""Accelerator liveness probe.

The reference's runtime dispatch can never hang: a CPUID read either succeeds
or the ISA is absent (/root/reference/src/abpoa_dispatch_simd.c:56-78). The
TPU analog is weaker — a wedged device tunnel makes the very first
`jax.devices()` call block forever, and by then the process has already
committed to the jax backend. So every device path (CLI `--device jax/tpu/
pallas`, the fused progressive loop) first probes JAX **in a subprocess with a
hard wall-clock timeout**; only a probe that answers lets the in-process jax
initialization proceed. On timeout/failure the caller falls back to the host
backends, which is the documented behavior instead of a silent hang.

The probe result is cached for the life of the process (one subprocess spawn,
~2-4 s, paid only on device paths).

Test hook: ABPOA_TPU_TEST_WEDGE=1 makes the probe child block forever,
simulating the wedged tunnel without needing one.
"""
from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

_PROBE_RESULT: Optional[bool] = None

# generous enough for a cold jax import + backend init on a loaded host;
# a wedged tunnel blocks far past this
_DEFAULT_TIMEOUT = float(os.environ.get("ABPOA_TPU_PROBE_TIMEOUT", "60"))

_PROBE_CODE = (
    "import os, time\n"
    "if os.environ.get('ABPOA_TPU_TEST_WEDGE'):\n"
    "    time.sleep(10**6)\n"
    "import jax\n"
    # the env var alone loses the platform race against site-hook device
    # plugins (round-2 finding); the config-level pin wins, so replicate the
    # strongest pin the in-process code could apply
    "p = os.environ.get('JAX_PLATFORMS')\n"
    "if p:\n"
    "    jax.config.update('jax_platforms', p)\n"
    "d = jax.devices()\n"
    "print('PLATFORMS', ','.join(sorted({x.platform for x in d})))\n"
)


def jax_backend_reachable(timeout: float = None) -> bool:
    """True iff `jax.devices()` answers (any platform) within the timeout.

    A CPU-only answer still counts as reachable: the fused loop runs fine on
    the CPU backend (that is how the test suite exercises it). Only a probe
    that hangs or crashes routes callers to the host fallback.
    """
    global _PROBE_RESULT
    if _PROBE_RESULT is not None:
        return _PROBE_RESULT
    if os.environ.get("ABPOA_TPU_SKIP_PROBE"):
        _PROBE_RESULT = True
        return True
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True, text=True,
            timeout=timeout if timeout is not None else _DEFAULT_TIMEOUT)
        _PROBE_RESULT = proc.returncode == 0 and "PLATFORMS" in proc.stdout
    except Exception:
        _PROBE_RESULT = False
    return _PROBE_RESULT


_WARNED = False


def warn_unreachable_once(msg: str) -> None:
    """Print the fallback warning once per process (callers probe per
    alignment; the user needs the message once, not per read)."""
    global _WARNED
    if not _WARNED:
        print(msg, file=sys.stderr)
        _WARNED = True


def reset_probe_cache() -> None:
    global _PROBE_RESULT, _WARNED
    _PROBE_RESULT = None
    _WARNED = False
