"""Accelerator liveness probe.

The reference's runtime dispatch can never hang: a CPUID read either succeeds
or the ISA is absent (/root/reference/src/abpoa_dispatch_simd.c:56-78). The
TPU analog is weaker — a wedged device tunnel makes the very first
`jax.devices()` call block forever, and by then the process has already
committed to the jax backend. So every device path (CLI `--device jax/tpu/
pallas`, the fused progressive loop) first probes JAX **in a subprocess with a
hard wall-clock timeout**; only a probe that answers lets the in-process jax
initialization proceed. On timeout/failure the caller falls back to the host
backends, which is the documented behavior instead of a silent hang.

The probe result is cached for the life of the process (one subprocess spawn,
~2-4 s, paid only on device paths).

Test hook: ABPOA_TPU_TEST_WEDGE=1 makes the probe child block forever,
simulating the wedged tunnel without needing one.
"""
from __future__ import annotations

import json
import os
import subprocess
import stat as stat_mod
import sys
import time
from typing import Optional

_PROBE_RESULT: Optional[bool] = None
_PLATFORMS: Optional[frozenset] = None

# generous enough for a cold jax import + backend init on a loaded host;
# a wedged tunnel blocks far past this
_DEFAULT_TIMEOUT = float(os.environ.get("ABPOA_TPU_PROBE_TIMEOUT", "60"))

_PROBE_CODE = (
    "import os, time\n"
    "if os.environ.get('ABPOA_TPU_TEST_WEDGE'):\n"
    "    time.sleep(10**6)\n"
    "import jax\n"
    # the env var alone loses the platform race against site-hook device
    # plugins (round-2 finding); the config-level pin wins, so replicate the
    # strongest pin the in-process code could apply
    "p = os.environ.get('JAX_PLATFORMS')\n"
    "if p:\n"
    "    jax.config.update('jax_platforms', p)\n"
    "d = jax.devices()\n"
    "print('PLATFORMS', ','.join(sorted({x.platform for x in d})))\n"
)


# cross-process probe verdict cache: a CLI run on a host without an
# accelerator would otherwise pay the full cold-jax-import subprocess probe
# (seconds, up to the timeout on a wedged tunnel) on EVERY invocation now
# that device="auto" is the default. TTL 0 disables the file cache.
_CACHE_TTL = float(os.environ.get("ABPOA_TPU_PROBE_CACHE_TTL", "300"))


def _cache_path() -> Optional[str]:
    # a user-private directory, NOT world-writable /tmp: a predictable /tmp
    # path could be pre-created by another user with a planted verdict or a
    # symlink (ADVICE r4). If the private dir cannot be created the file
    # cache is disabled outright — callers just re-probe.
    base = os.environ.get("XDG_RUNTIME_DIR") or os.path.expanduser("~/.cache")
    d = os.path.join(base, "abpoa_tpu")
    try:
        os.makedirs(d, mode=0o700, exist_ok=True)
    except Exception:
        return None
    return os.path.join(d, "probe_verdict.json")


def _cache_fingerprint() -> str:
    # the verdict depends on the environment the probe child ran under; a
    # pinned run's verdict must not be replayed for an unpinned run
    return "|".join([os.environ.get("JAX_PLATFORMS", ""),
                     os.environ.get("ABPOA_TPU_PROBE_TIMEOUT", "")])


def _cache_read():
    if _CACHE_TTL <= 0 or os.environ.get("ABPOA_TPU_TEST_WEDGE"):
        return None
    path = _cache_path()
    if path is None:
        return None
    try:
        # O_NOFOLLOW|O_NONBLOCK: a planted symlink or FIFO at the cache path
        # must fail the open, not follow it or block forever (blocking here
        # would be the exact hang this module exists to prevent). Then fstat
        # the OPEN fd — a stat-then-open pair is a TOCTOU window where the
        # file could be swapped between the uid check and the read (ADVICE
        # r4) — and require a regular file owned by us.
        fd = os.open(path, os.O_RDONLY
                     | getattr(os, "O_NOFOLLOW", 0)
                     | getattr(os, "O_NONBLOCK", 0))
        with os.fdopen(fd) as fp:
            st = os.fstat(fp.fileno())
            if not stat_mod.S_ISREG(st.st_mode):
                return None
            if hasattr(os, "getuid") and st.st_uid != os.getuid():
                return None
            d = json.load(fp)
        age = time.time() - d["ts"]
        if 0 <= age <= _CACHE_TTL and d.get("env") == _cache_fingerprint():
            return bool(d["reachable"]), frozenset(d.get("platforms", []))
    except Exception:
        pass
    return None


def _cache_write(reachable: bool, platforms) -> None:
    if _CACHE_TTL <= 0 or os.environ.get("ABPOA_TPU_TEST_WEDGE"):
        return
    path = _cache_path()
    if path is None:
        return
    try:
        # per-pid tmp name: a writer SIGKILLed mid-write (the watcher kills
        # whole process groups on step timeout) leaves a stale tmp behind;
        # with a shared name the O_EXCL below would then fail every future
        # write forever. O_NOFOLLOW|O_EXCL: refuse to traverse a pre-planted
        # symlink at a predictable name (ADVICE r4).
        tmp = f"{path}.{os.getpid()}.tmp"
        # a recycled pid can inherit a predecessor's SIGKILL-orphaned tmp;
        # clear it so O_EXCL means "no races NOW", not "no crashes EVER"
        try:
            os.unlink(tmp)
        except OSError:
            pass
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL
                     | getattr(os, "O_NOFOLLOW", 0), 0o600)
        try:
            with os.fdopen(fd, "w") as fp:
                json.dump({"ts": time.time(), "reachable": reachable,
                           "platforms": sorted(platforms or []),
                           "env": _cache_fingerprint()}, fp)
            os.replace(tmp, path)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    except Exception:
        pass


def jax_backend_reachable(timeout: float = None) -> bool:
    """True iff `jax.devices()` answers (any platform) within the timeout.

    A CPU-only answer still counts as reachable: the fused loop runs fine on
    the CPU backend (that is how the test suite exercises it). Only a probe
    that hangs or crashes routes callers to the host fallback.
    """
    global _PROBE_RESULT, _PLATFORMS
    if _PROBE_RESULT is not None:
        return _PROBE_RESULT
    if os.environ.get("ABPOA_TPU_SKIP_PROBE"):
        _PROBE_RESULT = True
        return True
    cached = _cache_read()
    if cached is not None:
        _PROBE_RESULT, _PLATFORMS = cached
        return _PROBE_RESULT
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True, text=True,
            timeout=timeout if timeout is not None else _DEFAULT_TIMEOUT)
        _PROBE_RESULT = proc.returncode == 0 and "PLATFORMS" in proc.stdout
        if _PROBE_RESULT:
            for line in proc.stdout.splitlines():
                if line.startswith("PLATFORMS "):
                    _PLATFORMS = frozenset(line.split()[1].split(","))
    except Exception:
        _PROBE_RESULT = False
    _cache_write(_PROBE_RESULT, _PLATFORMS)
    return _PROBE_RESULT


def accelerator_platforms() -> frozenset:
    """Platforms the liveness probe observed (e.g. {'tpu'} or {'cpu'}).

    Under ABPOA_TPU_SKIP_PROBE the platforms are read in-process — the flag
    is the caller's assertion that jax initialization is safe (the test
    conftest pins JAX_PLATFORMS=cpu before setting it)."""
    global _PLATFORMS
    if _PLATFORMS is not None:
        return _PLATFORMS
    if os.environ.get("ABPOA_TPU_SKIP_PROBE"):
        # only inspect jax in-process when JAX_PLATFORMS pins a platform:
        # the config-level pin (applied below) is what makes init safe —
        # without it the site hook's device plugin wins and a wedged tunnel
        # hangs jax.devices() forever (round-2 finding). SKIP_PROBE with no
        # pin therefore claims no accelerator instead of risking the hang.
        p = os.environ.get("JAX_PLATFORMS")
        if not p:
            _PLATFORMS = frozenset()
            return _PLATFORMS
        try:
            import jax
            jax.config.update("jax_platforms", p)
            _PLATFORMS = frozenset(x.platform for x in jax.devices())
        except Exception:
            _PLATFORMS = frozenset()
        return _PLATFORMS
    if not jax_backend_reachable():
        return frozenset()
    if _PLATFORMS is None:
        # cache hole: reachability was decided under ABPOA_TPU_SKIP_PROBE
        # (no platform list) and the flag has since been unset — run the
        # real probe once for the platform list instead of guessing
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True, text=True, timeout=_DEFAULT_TIMEOUT)
            for line in proc.stdout.splitlines():
                if line.startswith("PLATFORMS "):
                    _PLATFORMS = frozenset(line.split()[1].split(","))
        except Exception:
            pass
    return _PLATFORMS if _PLATFORMS is not None else frozenset()


def has_accelerator() -> bool:
    """True iff the probe saw a non-CPU platform (a real chip, not the
    CPU fallback backend)."""
    return any(p != "cpu" for p in accelerator_platforms())


_WARNED = False


def warn_unreachable_once(msg: str) -> None:
    """Print the fallback warning once per process (callers probe per
    alignment; the user needs the message once, not per read)."""
    global _WARNED
    if not _WARNED:
        print(msg, file=sys.stderr)
        _WARNED = True


def apply_platform_pin() -> None:
    """Mirror the probe child's platform pin in-process. The probe child
    applies JAX_PLATFORMS via `jax.config.update` because the env var alone
    loses the race against a site hook's device plugin (round-2 finding).
    A caller that trusts the probe verdict and then initializes jax
    in-process must apply the SAME pin, or its init can land on the wedged
    platform the probe child never touched. No-op without the env var."""
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        try:
            import jax
            jax.config.update("jax_platforms", p)
        except Exception:
            pass


def reset_probe_cache() -> None:
    global _PROBE_RESULT, _WARNED, _PLATFORMS
    _PROBE_RESULT = None
    _WARNED = False
    _PLATFORMS = None


# --------------------------------------------------------------------------- #
# bounded probe-transition log                                                #
# --------------------------------------------------------------------------- #

# entry cap for append_jsonl_bounded callers (TPU_PROBE_LOG.jsonl): the
# watcher probes every 90 s, so an unbounded append-only log grows without
# limit on a long-lived host. 2000 entries ≈ 2 days of continuous probing
# — recent history survives, ancient transitions age out.
_PROBE_LOG_MAX = int(os.environ.get("ABPOA_TPU_PROBE_LOG_MAX", "2000"))


def append_jsonl_bounded(path: str, obj: dict,
                         max_entries: Optional[int] = None) -> None:
    """Append one JSON line to `path`, keeping only the newest
    `max_entries` lines (atomic rewrite past the cap — a reader never
    sees a torn file). Logging must never fail the caller: any I/O error
    is swallowed."""
    if max_entries is None:
        max_entries = _PROBE_LOG_MAX
    try:
        with open(path, "a") as fp:
            fp.write(json.dumps(obj) + "\n")
        with open(path) as fp:
            lines = fp.read().splitlines()
        if len(lines) > max_entries:
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as fp:
                fp.write("\n".join(lines[-max_entries:]) + "\n")
            os.replace(tmp, path)
    except OSError:
        pass
