"""Sharded DP route: shard_map the lockstep/map batch across a device mesh.

ROADMAP item 2a. The split-lockstep and map drivers already have the
one-dispatch-per-round data-parallel shape — K independent lanes, one
vmapped `run_dp_chunk` per round, zero cross-lane collectives — and
`__graft_entry__.py`'s multichip dryrun proved byte-identical set-, growth-
and map-batch sharding on a virtual 8-device mesh. This module promotes
that dryrun pattern into the product path:

- `discover_mesh`: `jax.devices()` grouped by platform (real silicon
  preferred over the host cpu platform), sized by `ABPOA_TPU_MESH` /
  `--mesh N`. 1-core hosts get the `--xla_force_host_platform_device_count`
  virtual mesh ONLY on that explicit request (`pin_virtual_cpu_mesh`,
  promoted from the dryrun, must run before the first backend init).
- `shard_dp_round`: the sharded twin of `align.dp_chunk.dispatch_dp_chunk`
  — pad/stack K lane tables exactly as the unsharded dispatch does, then
  reshape the lane axis (K,) -> (mesh, K/mesh) and run ONE
  `shard_map(jax.vmap(run_dp_chunk))` over the 1-axis lane mesh. Graph
  scoring constants (`mat`, gap penalties) replicate into every shard
  (the dryrun phase-4 pattern: `StaticGraphTables` replicated, reads
  sharded); per-shard K stays on the pow2 rung chain, so global
  K = mesh x per-shard rung and padding lanes are born finished
  (n_rows=2/qlen=0) just like the unsharded path.
- `shard_vmap`: the `shard_map(jax.vmap(f))` spec boilerplate the dryrun
  phases used to repeat inline, in one place.

Byte parity falls out of construction: each shard computes the same
vmapped `run_dp_chunk` lanes the unsharded dispatch would, on a disjoint
contiguous slice of the lane axis — `tools/shard_gate.py` pins it against
the unsharded driver AND the numpy oracle, with churn joins in flight.

jax is imported lazily throughout: `abpoa_tpu.parallel` must stay
importable on host-only paths that never pay a jax import (runner.py's
contract).
"""
from __future__ import annotations

import functools
import os
import re
from typing import List, Optional

import numpy as np

from ..compile import registry
from ..params import Params

# the 1-axis lane mesh axis name — the same axis the multichip dryrun and
# runner.shard_dp_batch shard over (data parallelism over lanes/sets)
AXIS = "set"


def pin_virtual_cpu_mesh(n_devices: int) -> None:
    """Force the CPU platform with >= n_devices virtual devices BEFORE any
    backend initialization. The environment may preset JAX_PLATFORMS to a
    real accelerator tunnel (axon); merely overriding the env var is not
    enough once the site hook has read it, so pin via jax.config (same
    approach as tests/conftest.py). Idempotent: an existing larger
    `--xla_force_host_platform_device_count` wins."""
    import jax
    flags = os.environ.get("XLA_FLAGS", "")
    kept = [f for f in flags.split()
            if not re.match(r"--xla_force_host_platform_device_count=", f)]
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    count = max(n_devices, int(m.group(1)) if m else 0)
    kept.append(f"--xla_force_host_platform_device_count={count}")
    os.environ["XLA_FLAGS"] = " ".join(kept)
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")


def requested_mesh_size(cli: Optional[int] = None) -> int:
    """The operator's mesh request: an explicit CLI value wins, else the
    ABPOA_TPU_MESH env var. 0 or 1 (or unset/garbage) means OFF — the
    sharded route is strictly opt-in, and a 1-device "mesh" is just the
    unsharded dispatch with extra steps."""
    if cli is not None:
        return max(0, int(cli))
    raw = os.environ.get("ABPOA_TPU_MESH", "").strip()
    if not raw:
        return 0
    try:
        return max(0, int(raw))
    except ValueError:
        return 0


def mesh_size(mesh) -> int:
    """Lane-mesh width; 1 for the unsharded path (mesh=None)."""
    return int(mesh.devices.size) if mesh is not None else 1


def discover_mesh(n: Optional[int] = None):
    """Build the 1-axis lane Mesh of `n` devices (default: the
    `requested_mesh_size()` opt-in; < 2 returns None — no mesh).

    Devices are grouped by platform and real silicon is preferred over the
    host cpu platform. A CPU-pinned host (JAX_PLATFORMS=cpu) gets the
    `--xla_force_host_platform_device_count` virtual mesh — only here,
    under an explicit size request, and only if the pin lands before the
    first backend initialization. Raises RuntimeError when no platform
    group is wide enough."""
    size = requested_mesh_size() if n is None else max(0, int(n))
    if size < 2:
        return None
    plat = (os.environ.get("JAX_PLATFORMS") or "").split(",")[0]
    if plat.strip().lower() == "cpu":
        # the explicit size request on a CPU host IS the virtual-mesh
        # opt-in; a no-op if the backend already initialized with enough
        # virtual devices (tests/conftest.py pins 8 the same way)
        pin_virtual_cpu_mesh(size)
    import jax
    from jax.sharding import Mesh
    groups: dict = {}
    for d in jax.devices():
        groups.setdefault(d.platform, []).append(d)
    for _plat, devs in sorted(groups.items(), key=lambda kv: kv[0] == "cpu"):
        if len(devs) >= size:
            return Mesh(np.array(devs[:size]), axis_names=(AXIS,))
    have = {p: len(d) for p, d in groups.items()}
    raise RuntimeError(
        f"mesh of {size} devices requested but the attached platform "
        f"groups are {have}; on a 1-core host export JAX_PLATFORMS=cpu so "
        "the --xla_force_host_platform_device_count virtual mesh can be "
        "pinned (it must land before the first jax backend initialization)")


def shard_vmap(f, mesh, n_shard: int, n_rep: int = 0):
    """`shard_map(jax.vmap(f))` over the 1-axis lane mesh: the first
    `n_shard` args shard on their leading (mesh-sized) axis, the trailing
    `n_rep` args replicate into every shard — ONE definition of the spec
    boilerplate the multichip dryrun phases used to repeat inline."""
    import jax
    from jax.sharding import PartitionSpec as P
    from ..utils.jaxcompat import shard_map
    vf = jax.vmap(f, in_axes=(0,) * n_shard + (None,) * n_rep) \
        if n_rep else jax.vmap(f)
    return shard_map(vf, mesh=mesh,
                     in_specs=(P(AXIS),) * n_shard + (P(),) * n_rep,
                     out_specs=P(AXIS))


# --------------------------------------------------------------------------- #
# the sharded dispatch: shard_map(vmap(run_dp_chunk)) over the lane mesh      #
# --------------------------------------------------------------------------- #

# per-lane args (sharded, leading axes (mesh, K/mesh)) and replicated
# scoring args — the exact run_dp_chunk signature split
_N_LANE = 16     # len(_TABLE_KEYS) + len(_SCALAR_KEYS)
_N_SHARED = 9    # mat, inf_min, o1, e1, oe1, o2, e2, oe2, zdrop

_SHARDED_JIT = None


def _sharded_jit():
    """The ONE stable jitted sharded entry (built lazily so importing this
    module never pays a jax import). `jax.sharding.Mesh` is hashable, so
    the mesh rides as a static argname: every (mesh, statics) signature
    compiles once and `obs.compile_log.compile_watch` gets a real
    `_cache_size` handle for ground-truth miss detection."""
    global _SHARDED_JIT
    if _SHARDED_JIT is not None:
        return _SHARDED_JIT
    import jax

    @functools.partial(jax.jit, static_argnames=(
        "mesh", "gap_mode", "W", "max_ops", "plane16", "extend", "zdrop_on",
        "local", "gap_on_right", "put_gap_at_end"))
    def run_dp_chunk_sharded(*args, mesh, gap_mode, W, max_ops, plane16,
                             extend, zdrop_on, local, gap_on_right,
                             put_gap_at_end):
        from ..align.dp_chunk import run_dp_chunk

        def slot(*a):
            # one mesh slot: the unsharded vmapped chunk over its K/mesh
            # lane slice, scoring constants replicated by spec
            return run_dp_chunk(
                *a, gap_mode=gap_mode, W=W, max_ops=max_ops,
                plane16=plane16, extend=extend, zdrop_on=zdrop_on,
                local=local, gap_on_right=gap_on_right,
                put_gap_at_end=put_gap_at_end)

        return shard_vmap(slot, mesh, _N_LANE, _N_SHARED)(*args)

    _SHARDED_JIT = run_dp_chunk_sharded
    return _SHARDED_JIT


def shard_dp_round(abpt: Params, table_list: List[dict], Kb: int, R: int,
                   P: int, Qp: int, W: int, plane16: bool,
                   mesh) -> np.ndarray:
    """Sharded twin of `align.dp_chunk.dispatch_dp_chunk`: pad `table_list`
    to the shared (R, P) rungs and Kb lane slots exactly as the unsharded
    dispatch does, reshape the lane axis (Kb,) -> (mesh, Kb/mesh), and run
    ONE shard_map(vmap(run_dp_chunk)) round. Padding lanes are born
    finished (n_rows=2/qlen=0); contiguous packing means they land in the
    trailing shards, whose lanes no-op — shard-local repack is just the
    host repacking the lane list before the reshape, same as unsharded."""
    import jax.numpy as jnp
    from ..align.dp_chunk import (_SCALAR_KEYS, _TABLE_KEYS, _pad_tables,
                                  chunk_statics)
    from ..align.oracle import INT16_MIN, INT32_MIN, dp_inf_min
    from ..obs import metrics, trace

    S = mesh_size(mesh)
    if S < 2:
        raise ValueError("shard_dp_round needs a >=2-device mesh "
                         "(use dispatch_dp_chunk for the unsharded path)")
    if Kb % S:
        raise ValueError(
            f"sharded dispatch: K rung {Kb} is not divisible by the mesh "
            f"size {S} (k_rung(k, mesh_size) plans divisible rungs)")
    k_per = Kb // S
    max_ops = R + Qp + 8
    k_real = len(table_list)
    padded = [_pad_tables(t, R, P) for t in table_list]
    lane_args = []
    for key in _TABLE_KEYS:
        stacked = np.stack([t[key] for t in padded])
        if k_real < Kb:
            pad = np.zeros((Kb - k_real,) + stacked.shape[1:],
                           stacked.dtype)
            stacked = np.concatenate([stacked, pad])
        lane_args.append(jnp.asarray(
            stacked.reshape((S, k_per) + stacked.shape[1:])))
    for key in _SCALAR_KEYS:
        vec = np.asarray([t[key] for t in table_list], np.int32)
        if k_real < Kb:
            fill = 2 if key == "n_rows" else 0
            vec = np.concatenate([vec,
                                  np.full(Kb - k_real, fill, np.int32)])
        lane_args.append(jnp.asarray(vec.reshape(S, k_per)))
    inf_min = dp_inf_min(abpt, INT16_MIN if plane16 else INT32_MIN)
    mat = jnp.asarray(np.ascontiguousarray(abpt.mat.astype(np.int32)))
    shared = (mat, jnp.int32(inf_min),
              jnp.int32(abpt.gap_open1), jnp.int32(abpt.gap_ext1),
              jnp.int32(abpt.gap_oe1), jnp.int32(abpt.gap_open2),
              jnp.int32(abpt.gap_ext2), jnp.int32(abpt.gap_oe2),
              jnp.int32(max(abpt.zdrop, 0)))
    statics = chunk_statics(abpt, W, max_ops, plane16)
    # the bucket names the PER-SHARD shape (K = lanes each device runs)
    # plus the mesh axis — global lanes = K x mesh, the ladder's declared
    # sharded rung grammar
    bucket = dict(R=R, P=P, Qp=Qp, W=W, K=k_per, mesh=S, plane16=plane16,
                  gap_mode=abpt.gap_mode, align_mode=abpt.align_mode)
    metrics.publish_mesh(S, mesh.devices.flat[0].platform)
    shard_live = []
    for i in range(S):
        live = min(max(k_real - i * k_per, 0), k_per)
        shard_live.append(live)
        metrics.publish_shard_occupancy(i, live / k_per)
    import time as _time

    from ..obs import rounds
    t_dp = _time.perf_counter()
    with trace.span("dp_chunk", "dp", args=dict(bucket, sets=k_real)):
        with registry.watch("run_dp_chunk[sharded]", bucket):
            packed = _sharded_jit()(*lane_args, *shared, mesh=mesh,
                                    **statics)
            out = np.asarray(packed)  # sync inside the compile bracket
    # per-shard live split + dispatch wall feed the obs/rounds.py ring:
    # the fused shard_map bracket is the straggler's wall, the live split
    # is what skew/straggler attribution derives from
    rounds.note_dispatch(_time.perf_counter() - t_dp, shard_live=shard_live)
    return out.reshape((Kb,) + out.shape[2:])[:k_real]


# --------------------------------------------------------------------------- #
# compile-ladder integration: AOT warmer for the sharded rungs                #
# --------------------------------------------------------------------------- #

def _warm_dp_chunk_sharded(abpt: Params, anchor) -> list:
    """Precompile the sharded DP chunk for one anchor at the OPERATOR'S
    requested mesh width (the shapes runs will actually dispatch); with no
    mesh requested the anchor is skipped — sharding is opt-in, and warming
    mesh shapes a host can't build would fail the warm pass. Per-shard K
    halvings mirror `_warm_dp_chunk`'s repack chain: global K = mesh x
    per-shard pow2 rung, down to one lane per shard (the drain floor)."""
    from ..align.dp_chunk import P_FLOOR, plan_row_rung
    from ..align.oracle import int16_score_limit, max_score_bound
    from ..compile.ladder import k_rung, plan_chunk_buckets, qp_rung
    from ..obs import compile_log
    S = requested_mesh_size()
    if S < 2:
        return [{"entry": anchor.entry, "skipped": "no mesh requested"}]
    try:
        mesh = discover_mesh(S)
    except RuntimeError as e:
        return [{"entry": anchor.entry, "skipped": str(e)}]
    recs = []
    Qp = qp_rung(anchor.qmax)
    _qp, W, _local = plan_chunk_buckets(abpt, anchor.qmax)
    plane16 = (max_score_bound(abpt, anchor.qmax, 2)
               <= int16_score_limit(abpt))
    ks = []
    k = k_rung(anchor.k or 2)
    while k >= 1:
        ks.append(k)
        k //= 2
    rungs = []
    R = plan_row_rung(anchor.qmax + 2)
    stop = plan_row_rung(2 * (anchor.qmax + 2) + 64)
    for _g in range(anchor.growth + 1):
        rungs.append(R)
        if R >= stop:
            break
        R = plan_row_rung(R + 1)
    for R in rungs:
        for k_per in ks:
            Kb = S * k_per
            tables = [dict(
                base_r=np.zeros(R, np.int32),
                pre_idx=np.zeros((R, P_FLOOR), np.int32),
                pre_msk=np.zeros((R, P_FLOOR), bool),
                out_idx=np.zeros((R, P_FLOOR), np.int32),
                out_msk=np.zeros((R, P_FLOOR), bool),
                row_active=np.zeros(R, bool),
                remain_rows=np.zeros(R, np.int32),
                mpl0=np.zeros(R, np.int32), mpr0=np.zeros(R, np.int32),
                qp=np.zeros((abpt.m, Qp), np.int32),
                query=np.zeros(Qp, np.int32),
                n_rows=2, qlen=0, w=0, remain_end=0, dp_end0=0)] * Kb
            shard_dp_round(abpt, tables, Kb, R, P_FLOOR, Qp, W, plane16,
                           mesh)
            rr = compile_log.run_records()
            recs.append(
                rr[-1] if rr and rr[-1]["fn"] == "run_dp_chunk[sharded]"
                else {"fn": "run_dp_chunk[sharded]",
                      "bucket": dict(R=R, K=k_per, mesh=S, Qp=Qp, W=W)})
    return recs


registry.register_entry("run_dp_chunk[sharded]", handle=_sharded_jit,
                        warmer=_warm_dp_chunk_sharded)
